"""Compiled-artifact audit (analysis/deviceaudit.py): the registry
lowers on CPU, donation facts are verified at the HLO level, host
round-trips and f64 are detected, manifest drift fails, and the d2h
whitelist is validated against the code.

The expensive part — lowering every registered program — runs once per
module (session-scoped fixture); the failure-mode tests lower tiny
synthetic programs instead.
"""
import json
import textwrap
from pathlib import Path

import pytest

from bucketeer_tpu.analysis import deviceaudit, lint
from bucketeer_tpu.analysis.__main__ import main as cli_main

REPO = Path(__file__).resolve().parent.parent
MANIFEST = REPO / ".graftaudit-manifest.json"


def _lowered(repo_facts):
    return [f for f in repo_facts if not f.skipped]


# --- the registry on the real codec -----------------------------------

def test_registry_lowers_at_least_three_entry_points(repo_facts):
    lowered = _lowered(repo_facts)
    assert len(lowered) >= 3, [f.skipped for f in repo_facts]
    families = {f.name.split("/")[0] for f in lowered}
    # All three jitted codec layers are represented.
    assert {"frontend.rows", "cxd.scan", "decode.inverse"} <= families


def test_repo_programs_are_clean(repo_facts):
    findings = []
    for facts in repo_facts:
        findings += deviceaudit.check_program(facts)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_no_host_roundtrips_inside_device_programs(repo_facts):
    for facts in _lowered(repo_facts):
        assert facts.transfers == (), facts.name
        assert not facts.f64, facts.name


def test_donation_facts_match_declared_specs(repo_facts):
    """Every seam currently records donation as unusable (verified: the
    probe forces donation and XLA aliases nothing) — so the lowered
    alias set must equal the declared set for every program. A future
    program with a matching output aval flips this by declaring the
    donation, and the audit then enforces it stays effective."""
    for facts in _lowered(repo_facts):
        assert set(facts.aliased) == set(facts.declared_donate), facts.name


def test_checked_in_manifest_matches_lowered_programs(repo_facts):
    manifest = deviceaudit.manifest_from_facts(repo_facts)
    drift = deviceaudit.diff_manifest(
        deviceaudit.load_manifest(MANIFEST), manifest)
    assert drift == [], ("compiled programs drifted; regenerate with "
                         "`python -m bucketeer_tpu.analysis "
                         "--write-manifest` and commit the diff:\n"
                         + "\n".join(drift))


# --- failure modes, demonstrated on synthetic programs -----------------

def _synthetic(fn, declared, avals, probe=(0,), reason="unusable"):
    entry = deviceaudit.AuditProgram(
        "synthetic/test", lambda: (fn, declared, avals),
        probe_donate=probe, donate_reason=reason)
    facts = deviceaudit.lower_program(entry)
    facts.donate_reason = reason
    assert not facts.skipped, facts.skipped
    return facts


def test_effective_donation_is_verified():
    import jax
    import jax.numpy as jnp

    facts = _synthetic(lambda x: x * 2, (0,),
                       [jax.ShapeDtypeStruct((8, 8), jnp.float32)])
    assert facts.aliased == (0,)
    assert deviceaudit.check_program(facts) == []


def test_dropped_donation_is_detected():
    """The silent-drop case: the donated arg's aval matches no output
    (dtype changes), XLA keeps the donation request but aliases
    nothing — the audit must fail it."""
    import jax
    import jax.numpy as jnp

    facts = _synthetic(lambda x: x.astype(jnp.int32) + 1, (0,),
                       [jax.ShapeDtypeStruct((8, 8), jnp.float32)])
    assert facts.aliased == ()
    rules = [f.rule for f in deviceaudit.check_program(facts)]
    assert rules == [deviceaudit.DONATION_DROPPED]


def test_stale_unusable_claim_is_detected():
    """A program recorded donation-unusable whose probe *does* alias:
    the claim is stale and the HBM saving is being left on the table."""
    import jax
    import jax.numpy as jnp

    facts = _synthetic(lambda x: x * 2, (),
                       [jax.ShapeDtypeStruct((8, 8), jnp.float32)])
    assert facts.aliased == (0,)
    findings = deviceaudit.check_program(facts)
    assert [f.rule for f in findings] == [deviceaudit.STALE_DONATION]
    assert findings[0].severity == "warning"


def test_lifetime_buffers_are_never_stale():
    import jax
    import jax.numpy as jnp

    facts = _synthetic(lambda x: x * 2, (),
                       [jax.ShapeDtypeStruct((8, 8), jnp.float32)],
                       reason="lifetime")
    assert deviceaudit.check_program(facts) == []


def test_host_callback_is_detected():
    import jax
    import jax.numpy as jnp

    def leaky(x):
        jax.debug.callback(lambda v: None, x)
        return x + 1

    facts = _synthetic(leaky, (), [jax.ShapeDtypeStruct((4,), jnp.float32)],
                       probe=())
    assert facts.transfers, "callback custom_call not surfaced"
    rules = [f.rule for f in deviceaudit.check_program(facts)]
    assert deviceaudit.HOST_TRANSFER in rules


def test_f64_is_detected():
    import jax
    import jax.numpy as jnp

    def promoting(x):
        return x.astype(jnp.float64) * 2

    with jax.experimental.enable_x64():
        facts = _synthetic(promoting, (),
                           [jax.ShapeDtypeStruct((4,), jnp.float32)],
                           probe=())
    assert facts.f64
    rules = [f.rule for f in deviceaudit.check_program(facts)]
    assert deviceaudit.F64_IN_PROGRAM in rules


def test_f64_regex_ignores_hex_constant_payloads():
    facts = deviceaudit.ProgramFacts("x")
    assert deviceaudit._F64_RE.search("tensor<4x4xf64>")
    assert deviceaudit._F64_RE.search("tensor<f64>")
    assert not deviceaudit._F64_RE.search('dense<"0x3f64ab..."> : '
                                          "tensor<4xf32>")
    assert not facts.f64


# --- manifest drift ----------------------------------------------------

def test_manifest_drift_is_detected(repo_facts, tmp_path):
    manifest = deviceaudit.manifest_from_facts(repo_facts)
    tampered = json.loads(json.dumps(manifest))
    name = sorted(tampered["programs"])[0]
    tampered["programs"][name]["fingerprint"] = "0" * 64
    tampered["programs"][name]["op_counts"]["stablehlo.convert"] = 999
    drift = deviceaudit.diff_manifest(tampered, manifest)
    assert len(drift) == 1 and name in drift[0]
    assert "stablehlo.convert" in drift[0]

    tampered["programs"]["ghost/program"] = {"fingerprint": "x",
                                             "op_counts": {}}
    drift = deviceaudit.diff_manifest(tampered, manifest)
    assert any("ghost/program" in line for line in drift)

    assert deviceaudit.diff_manifest(None, manifest) != []


def test_env_skipped_programs_are_not_drift(repo_facts):
    """A program the manifest records but this environment cannot
    lower (facts.skipped) must not read as a removed registry entry —
    the skip mechanism exists to tolerate exactly that."""
    manifest = deviceaudit.manifest_from_facts(repo_facts)
    reduced = json.loads(json.dumps(manifest))
    name = sorted(reduced["programs"])[0]
    del reduced["programs"][name]
    assert any(name in line for line in
               deviceaudit.diff_manifest(manifest, reduced))
    assert deviceaudit.diff_manifest(manifest, reduced,
                                     skipped=(name,)) == []


def test_jax_version_change_is_one_actionable_line(repo_facts):
    """A jax upgrade shifts every fingerprint; the diff must say so in
    one line naming both versions instead of per-program noise."""
    manifest = deviceaudit.manifest_from_facts(repo_facts)
    stale = json.loads(json.dumps(manifest))
    stale["jax"] = "0.0.stale"
    for prog in stale["programs"].values():
        prog["fingerprint"] = "0" * 64
    drift = deviceaudit.diff_manifest(stale, manifest)
    assert len(drift) == 1
    assert "0.0.stale" in drift[0] and manifest["jax"] in drift[0]
    assert "--write-manifest" in drift[0]


# --- d2h whitelist validation ------------------------------------------

def test_repo_d2h_whitelist_is_live():
    project = lint.load_project(REPO / "bucketeer_tpu")
    findings = deviceaudit.validate_d2h_whitelist(project)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_stale_d2h_whitelist_entry_is_reported(tmp_path):
    """A sanctioned function that no longer transfers anything (and one
    that vanished entirely) must both be reported stale."""
    root = tmp_path / "pkg"
    (root / "codec").mkdir(parents=True)
    (root / "__init__.py").write_text('"""fixture"""\n')
    (root / "codec" / "__init__.py").write_text('"""fixture"""\n')
    (root / "codec" / "xfer.py").write_text(textwrap.dedent("""\
        import jax


        def gather_rows(rows):
            return rows * 2          # no device_get anymore


        def fetch_payload(rows):
            return jax.device_get(rows)
        """), encoding="utf-8")
    project = lint.load_project(root)
    findings = deviceaudit.validate_d2h_whitelist(project)
    stale = {f.message.split("'")[1] for f in findings}
    assert "gather_rows" in stale
    assert "fetch_payload" not in stale
    # Functions with no definition at all in the fixture are also stale.
    assert "run_cxd" in stale


# --- CLI ----------------------------------------------------------------

def test_cli_audit_passes_on_repo(capsys, cached_lowering):
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--audit", "--strict",
                   "--baseline", str(REPO / ".graftlint-baseline.json"),
                   "--manifest", str(MANIFEST)])
    assert rc == 0, capsys.readouterr().out


def test_cli_audit_fails_on_manifest_drift(tmp_path, capsys,
                                           cached_lowering):
    bad = tmp_path / "manifest.json"
    bad.write_text(json.dumps({"jax": "0", "programs": {
        "ghost/program": {"fingerprint": "x", "op_counts": {},
                          "n_ops": 0}}}) + "\n", encoding="utf-8")
    dump = tmp_path / "dump"
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--audit",
                   "--baseline", str(REPO / ".graftlint-baseline.json"),
                   "--manifest", str(bad), "--dump-dir", str(dump)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "audit-manifest-drift" in out
    # The lowered programs were dumped for the CI artifact upload.
    assert list(dump.glob("*.stablehlo.txt"))
