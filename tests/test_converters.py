"""Converter SPI tests (reference:
src/test/java/edu/ucla/library/bucketeer/converters/KakaduConverterTest.java,
ConverterFactoryTest.java). The reference could only assert on output
size; we decode the derivative and check pixels.
"""
import io
import os

import numpy as np
import pytest
from PIL import Image

from bucketeer_tpu.converters import (Conversion, ConverterError,
                                      KakaduConverter, TpuConverter,
                                      available_converters, get_converter,
                                      output_path)


@pytest.fixture
def tiff_file(tmp_path, rng):
    img = rng.integers(0, 256, size=(96, 128, 3)).astype(np.uint8)
    path = tmp_path / "test.tif"
    Image.fromarray(img).save(path)
    return str(path), img


@pytest.fixture
def gray16_tiff(tmp_path, rng):
    img = rng.integers(0, 65536, size=(64, 64)).astype(np.uint16)
    path = tmp_path / "scan16.tif"
    Image.fromarray(img).save(path)
    return str(path), img


def test_output_path_url_encodes_id(monkeypatch, tmp_path):
    # reference: KakaduConverter.java:57 URL-encodes ARK ids
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    path = output_path("ark:/21198/z10v8vhs")
    assert os.path.basename(path) == "ark%3A%2F21198%2Fz10v8vhs.jpx"


def test_tpu_converter_lossless(monkeypatch, tmp_path, tiff_file):
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    src, img = tiff_file
    out = TpuConverter().convert("ark:/1/abc", src, Conversion.LOSSLESS)
    assert os.path.exists(out)
    assert out.endswith(".jpx")
    # size oracle (reference: KakaduConverterTest.java:106-107) + decode
    assert os.path.getsize(out) > 1000
    dec = np.asarray(Image.open(out))
    np.testing.assert_array_equal(dec, img)


@pytest.fixture
def photo_tiff(tmp_path, rng):
    """Compressible photographic content (smooth shading + edges +
    correlated channels + light sensor noise) — the content class the
    lossy `-rate 3` recipe is for. An iid-noise image would need ~24 bpp
    for 30 dB, so no encoder can look good on one at 3 bpp."""
    y, x = np.mgrid[0:256, 0:384]
    lum = (110 + 70 * np.sin(x / 19.0) * np.cos(y / 13.0)
           + 25 * ((x // 32 + y // 32) % 2))
    img = np.clip(np.stack([lum + 10, lum * 0.92, lum * 0.85], -1)
                  + rng.normal(0, 3, (256, 384, 3)), 0, 255).astype(np.uint8)
    path = tmp_path / "photo.tif"
    Image.fromarray(img).save(path)
    return str(path), img


def test_tpu_converter_lossy(monkeypatch, tmp_path, photo_tiff):
    """The production lossy path (kakadu recipe, -rate 3) on
    photographic content: on-rate and high quality — and at least as
    good as OpenJPEG (via Pillow) gets at the same byte budget
    (matched-rate independent-encoder oracle, BASELINE.md)."""
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    src, img = photo_tiff
    out = TpuConverter().convert("ark:/1/xyz", src, Conversion.LOSSY)
    dec = np.asarray(Image.open(out))
    assert dec.shape == img.shape
    mse = np.mean((dec.astype(float) - img.astype(float)) ** 2)
    psnr = 10 * np.log10(255 ** 2 / max(mse, 1e-9))
    assert psnr > 34.0, f"lossy quality collapsed: {psnr:.2f} dB"

    bpp = 8.0 * os.path.getsize(out) / (img.shape[0] * img.shape[1])
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG2000", irreversible=True,
                              quality_mode="rates",
                              quality_layers=[24.0 / bpp])
    ref = np.asarray(Image.open(io.BytesIO(buf.getvalue())))
    ref_psnr = 10 * np.log10(
        255 ** 2 / max(np.mean((ref.astype(float) - img) ** 2), 1e-9))
    # 0.3 dB allowance: the production recipe carries 6 quality layers
    # plus SOP/EPH/PLT markers (progressive streaming the flat 1-layer
    # OpenJPEG file doesn't offer) inside the same byte budget.
    assert psnr >= ref_psnr - 0.3, (
        f"behind OpenJPEG at matched rate: {psnr:.2f} vs {ref_psnr:.2f} dB")


def test_tpu_converter_16bit_gray(monkeypatch, tmp_path, gray16_tiff):
    # BASELINE config 3: lossless 16-bit archival scans
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    src, img = gray16_tiff
    out = TpuConverter().convert("scan", src, Conversion.LOSSLESS)
    dec = np.asarray(Image.open(out))
    np.testing.assert_array_equal(dec, img)


def test_missing_source_raises(monkeypatch, tmp_path):
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    with pytest.raises(ConverterError):
        TpuConverter().convert("x", str(tmp_path / "absent.tif"))


def test_factory_default_is_tpu():
    conv = get_converter("tpu")
    assert isinstance(conv, TpuConverter)


def test_factory_falls_back_when_cli_missing(monkeypatch):
    # reference: ConverterFactory.java:37-47 falls back when Kakadu absent
    if KakaduConverter.is_available():
        pytest.skip("kakadu actually installed")
    conv = get_converter("kakadu")
    assert isinstance(conv, TpuConverter)


def test_available_report():
    avail = available_converters()
    assert avail["tpu"] is True
    assert set(avail) == {"tpu", "kakadu", "openjpeg"}
