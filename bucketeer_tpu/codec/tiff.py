"""Host-side TIFF reading: source images -> numpy arrays for the device
pipeline.

Replaces the reference's reliance on libtiff inside ``kdu_compress``
(reference: src/main/docker/Dockerfile:17-19,54-55 installs libtiff for the
Kakadu binary to consume). Supports 8/16-bit grayscale and RGB — the
archival-scan formats named in BASELINE.md configs 1 and 3.
"""
from __future__ import annotations

import numpy as np


def read_image(path: str) -> tuple[np.ndarray, int]:
    """Read an image file into ``(array, bitdepth)``.

    Returns (H, W) for grayscale or (H, W, 3) for color, dtype uint8 or
    uint16. Alpha channels are dropped; palette images are expanded.
    """
    from PIL import Image

    with Image.open(path) as img:
        if img.mode == "P":
            img = img.convert("RGB")
        elif img.mode == "1":   # bilevel -> 0/255 grayscale
            img = img.convert("L")
        elif img.mode in ("LA", "RGBA"):
            img = img.convert(img.mode[:-1])
        elif img.mode == "CMYK":
            img = img.convert("RGB")
        arr = np.asarray(img)

    if arr.ndim == 3 and arr.shape[2] == 4:
        arr = arr[:, :, :3]
    if arr.dtype == np.int32:  # PIL 'I' mode: 32-bit container for 16-bit data
        arr = np.clip(arr, 0, 65535).astype(np.uint16)
    if arr.dtype == np.uint16:
        return arr, 16
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    return arr, 8


def image_size(path: str) -> tuple[int, int]:
    """(width, height) without decoding pixel data."""
    from PIL import Image

    with Image.open(path) as img:
        return img.size
