"""Multi-chip sharding tests on the virtual 8-device CPU mesh — the
analog of the reference's container-based integration tier (SURVEY.md
§4): exercise the distributed seams without real hardware.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bucketeer_tpu.codec.dwt import dwt2d_forward
from bucketeer_tpu.codec.pipeline import make_plan, run_tiles
from bucketeer_tpu.parallel import (make_mesh, run_tiles_sharded,
                                    sharded_dwt2d_forward)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(tile_parallel=8)       # 1 x 8: all devices spatial


@pytest.fixture(scope="module")
def mesh42():
    return make_mesh(tile_parallel=2)       # 4 x 2: data x tile


def test_mesh_axes():
    m = make_mesh(tile_parallel=2)
    assert m.shape == {"data": 4, "tile": 2}


@pytest.mark.parametrize("reversible", [True, False])
def test_sharded_dwt_matches_single_device(rng, mesh8, reversible):
    h, w, levels = 256, 64, 2               # 256/(8*4)=8 rows at coarsest
    x = rng.integers(-1000, 1000, size=(h, w)).astype(np.int32)
    ref_ll, ref_bands = dwt2d_forward(
        jnp.asarray(x if reversible else x.astype(np.float32)),
        levels, reversible)
    ll, bands = sharded_dwt2d_forward(jnp.asarray(
        x if reversible else x.astype(np.float32)),
        levels, reversible, mesh8)
    if reversible:
        np.testing.assert_array_equal(np.asarray(ll), np.asarray(ref_ll))
        for got, ref in zip(bands, ref_bands):
            for k in ("HL", "LH", "HH"):
                np.testing.assert_array_equal(np.asarray(got[k]),
                                              np.asarray(ref[k]))
    else:
        np.testing.assert_allclose(np.asarray(ll), np.asarray(ref_ll),
                                   rtol=1e-5, atol=1e-3)
        for got, ref in zip(bands, ref_bands):
            for k in ("HL", "LH", "HH"):
                np.testing.assert_allclose(np.asarray(got[k]),
                                           np.asarray(ref[k]),
                                           rtol=1e-5, atol=1e-3)


def test_sharded_dwt_multicomponent(rng, mesh8):
    x = rng.integers(-500, 500, size=(3, 128, 32)).astype(np.int32)
    ref_ll, _ = dwt2d_forward(jnp.asarray(x), 1, True)
    ll, _ = sharded_dwt2d_forward(jnp.asarray(x), 1, True, mesh8)
    np.testing.assert_array_equal(np.asarray(ll), np.asarray(ref_ll))


def test_sharded_tile_batch_matches_local(rng, mesh42):
    plan = make_plan(64, 64, 3, 3, False, 8)
    tiles = rng.integers(0, 256, size=(10, 64, 64, 3)).astype(np.uint8)
    ref = run_tiles(plan, tiles)
    got = run_tiles_sharded(plan, tiles, mesh42)   # 10 pads to 12 over 4
    np.testing.assert_array_equal(got, ref)


def test_sharded_tile_batch_lossless(rng, mesh42):
    plan = make_plan(32, 32, 1, 2, True, 8)
    tiles = rng.integers(0, 256, size=(8, 32, 32)).astype(np.uint8)
    np.testing.assert_array_equal(
        run_tiles_sharded(plan, tiles, mesh42),
        run_tiles(plan, tiles))


# --- mesh-integrated encode (the product path, not just the kernels) ---

def _decode(data):
    import io

    from PIL import Image
    return np.asarray(Image.open(io.BytesIO(data)))


def test_can_row_shard():
    from bucketeer_tpu.parallel.sharded_dwt import can_row_shard

    assert can_row_shard(128, 2, 8)         # 16 rows/shard, 4/level-2
    assert not can_row_shard(128, 2, 1)     # no point with one shard
    assert not can_row_shard(100, 2, 8)     # not divisible
    assert not can_row_shard(64, 3, 8)      # 1 row at the coarsest level


def test_sharded_transform_tile_matches_run_tiles(rng, mesh8):
    from bucketeer_tpu.parallel.sharded_dwt import sharded_transform_tile

    plan = make_plan(128, 96, 3, 2, True, 8)
    tile = rng.integers(0, 256, (128, 96, 3)).astype(np.uint8)
    got = sharded_transform_tile(plan, tile, mesh8)
    np.testing.assert_array_equal(got, run_tiles(plan, tile[None])[0])


def test_sharded_transform_tile_lossy_matches_run_tiles(rng, mesh8):
    """The lossy prologue (ICT + 9/7 + fixed-point quantization) mirrors
    pipeline._transform_batch; if the two copies diverge, the mesh path
    silently corrupts derivatives. Float summation order across the
    shard boundary may move a coefficient by at most one quantizer
    index LSB."""
    from bucketeer_tpu.parallel.sharded_dwt import sharded_transform_tile

    plan = make_plan(128, 96, 3, 2, False, 8)
    tile = rng.integers(0, 256, (128, 96, 3)).astype(np.uint8)
    got = sharded_transform_tile(plan, tile, mesh8).astype(np.int64)
    ref = run_tiles(plan, tile[None])[0].astype(np.int64)
    assert np.abs(got - ref).max() <= 1
    assert (got != ref).mean() < 0.01


def test_mesh_encode_spatial_decodable(rng, mesh8):
    """A single giant tile encodes through sharded_dwt2d_forward (row
    shards + halo exchange) into a bit-exact, decodable JP2."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    img = rng.integers(0, 256, size=(128, 96), dtype=np.uint8)
    data = encoder.encode_jp2(img, 8, EncodeParams(lossless=True,
                                                   levels=2), mesh=mesh8)
    np.testing.assert_array_equal(_decode(data), img)


def test_mesh_encode_tiled_decodable(rng, mesh42):
    """A tiled image encodes through run_tiles_sharded (data axis) into
    a bit-exact, decodable JP2."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    img = rng.integers(0, 256, size=(160, 160, 3), dtype=np.uint8)
    data = encoder.encode_jp2(img, 8, EncodeParams(
        lossless=True, levels=2, tile_size=64), mesh=mesh42)
    np.testing.assert_array_equal(_decode(data), img)


def test_mesh_encode_spatial_bit_exact_vs_single_device(rng, mesh8):
    """Tier-1 contract for the sharded_transform_tile path: the mesh
    encode is not just decodable, it is byte-identical to the
    single-device encoder — the lossless pipeline is pure integer
    arithmetic, so any sharding seam that moves a bit shows up here."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    img = rng.integers(0, 256, size=(128, 96), dtype=np.uint8)
    params = EncodeParams(lossless=True, levels=2)
    assert (encoder.encode_jp2(img, 8, params, mesh=mesh8)
            == encoder.encode_jp2(img, 8, params))


def test_mesh_encode_tiled_bit_exact_vs_single_device(rng, mesh42):
    """Same contract for the run_tiles_sharded data-parallel path."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    img = rng.integers(0, 256, size=(160, 160, 3), dtype=np.uint8)
    params = EncodeParams(lossless=True, levels=2, tile_size=64)
    assert (encoder.encode_jp2(img, 8, params, mesh=mesh42)
            == encoder.encode_jp2(img, 8, params))


def test_shard_map_compat_is_single_sourced():
    """The version-compat shard_map import lives in parallel/compat.py
    only — sharded_dwt (and analysis/graftmesh) consume it from there."""
    from bucketeer_tpu.parallel import compat, sharded_dwt

    assert sharded_dwt.shard_map is compat.shard_map
    assert set(compat.SM_NO_CHECK) <= {"check_vma", "check_rep"}


def test_converter_routes_through_mesh(rng, monkeypatch, tmp_path):
    """The converter path: an over-threshold image on a multi-device
    host encodes its tile batches through run_tiles_sharded and the
    derivative decodes bit-exactly (BASELINE config 4's routing seam)."""
    from PIL import Image

    import bucketeer_tpu.parallel.batch as pbatch
    from bucketeer_tpu.converters import Conversion, TpuConverter

    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    img = rng.integers(0, 256, size=(640, 640), dtype=np.uint8)
    src = tmp_path / "map.tif"
    Image.fromarray(img).save(src)

    calls = []
    orig = pbatch.run_tiles_sharded

    def spy(plan, tiles, mesh):
        calls.append(dict(mesh.shape))
        return orig(plan, tiles, mesh)

    monkeypatch.setattr(pbatch, "run_tiles_sharded", spy)
    out = TpuConverter(mesh_min_pixels=1).convert(
        "map", str(src), Conversion.LOSSLESS)
    assert calls, "mesh routing did not reach run_tiles_sharded"
    np.testing.assert_array_equal(np.asarray(Image.open(out)), img)


def test_converter_mesh_threshold_respected(rng, monkeypatch, tmp_path):
    """Below the threshold the converter stays on the single-device
    pipeline (no mesh dispatch overhead for ordinary scans)."""
    from PIL import Image

    import bucketeer_tpu.parallel.batch as pbatch
    from bucketeer_tpu.converters import Conversion, TpuConverter

    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    img = rng.integers(0, 256, size=(96, 96), dtype=np.uint8)
    src = tmp_path / "small.tif"
    Image.fromarray(img).save(src)

    def boom(*a, **k):
        raise AssertionError("mesh path taken below threshold")

    monkeypatch.setattr(pbatch, "run_tiles_sharded", boom)
    out = TpuConverter(mesh_min_pixels=10_000_000).convert(
        "small", str(src), Conversion.LOSSLESS)
    np.testing.assert_array_equal(np.asarray(Image.open(out)), img)
