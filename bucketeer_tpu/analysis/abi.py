"""Native-ABI cross-checker: ctypes bindings vs t1.cpp exports.

``bucketeer_tpu/native/__init__.py`` binds a handful of ``extern "C"``
symbols by hand and guards against layout drift with a single integer
(``_ABI_VERSION`` vs ``t1_abi_version()``). Nothing enforced that the
two sides actually agree until the process crashed at runtime; this
checker parses both sides and turns drift into a lint failure:

- ``abi-version-mismatch``: the Python ``_ABI_VERSION`` constant differs
  from the value returned by ``t1_abi_version()`` in the C++ source.
- ``abi-missing-export``: Python configures ``lib.<symbol>`` but the
  C++ ``extern "C"`` block does not define it (a runtime
  ``AttributeError`` waiting to happen).
- ``abi-unbound-export``: the C++ side exports a symbol Python never
  binds (dead export, or a binding someone forgot) — warning severity.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .findings import ERROR, WARNING, Finding

VERSION_MISMATCH = "abi-version-mismatch"
MISSING_EXPORT = "abi-missing-export"
UNBOUND_EXPORT = "abi-unbound-export"

# A C function definition at column 0: return type tokens then the name.
_CPP_FN_RE = re.compile(r"(?m)^[A-Za-z_][\w]*\s*\*?\s+\*?(\w+)\s*\(")
_CPP_VERSION_RE = re.compile(
    r"t1_abi_version\s*\(\s*(?:void)?\s*\)\s*\{\s*return\s+(-?\d+)")
_CPP_KEYWORDS = {"if", "for", "while", "switch", "return", "sizeof"}


def parse_cpp_exports(cpp_text: str):
    """(exported function names, abi version int or None)."""
    start = cpp_text.find('extern "C"')
    block = cpp_text[start:] if start >= 0 else ""
    names = {m.group(1) for m in _CPP_FN_RE.finditer(block)}
    names -= _CPP_KEYWORDS
    m = _CPP_VERSION_RE.search(cpp_text)
    version = int(m.group(1)) if m else None
    return names, version


def parse_python_bindings(py_text: str, filename: str = "<native>"):
    """(_ABI_VERSION int or None, {symbols configured on ``lib``},
    line of the version assignment)."""
    tree = ast.parse(py_text, filename=filename)
    version = None
    version_line = 1
    symbols: dict = {}        # name -> first line used
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and \
                        target.id == "_ABI_VERSION" and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, int):
                    version = node.value.value
                    version_line = node.lineno
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "lib":
            symbols.setdefault(node.attr, node.lineno)
    return version, symbols, version_line


def check_native(native_dir: Path, rel_to: Path | None = None) -> list:
    """Cross-check one native package directory; returns findings."""
    native_dir = Path(native_dir)
    init = native_dir / "__init__.py"
    cpp = native_dir / "t1.cpp"
    if not init.exists() or not cpp.exists():
        return []

    def rel(p: Path) -> str:
        if rel_to is not None:
            try:
                return str(p.resolve().relative_to(Path(rel_to).resolve()))
            except ValueError:
                pass
        return str(p)

    try:
        py_version, symbols, version_line = parse_python_bindings(
            init.read_text(encoding="utf-8"), str(init))
        exports, cpp_version = parse_cpp_exports(
            cpp.read_text(encoding="utf-8"))
    except (OSError, SyntaxError) as exc:
        return [Finding("parse-error", rel(init), 1,
                        f"ABI cross-check could not parse: {exc}", ERROR)]

    findings = []
    if py_version is not None and cpp_version is not None and \
            py_version != cpp_version:
        findings.append(Finding(
            VERSION_MISMATCH, rel(init), version_line,
            f"_ABI_VERSION = {py_version} but t1.cpp's "
            f"t1_abi_version() returns {cpp_version}; bump them "
            "together whenever an exported signature changes", ERROR,
            f"_ABI_VERSION = {py_version}"))
    for sym, line in sorted(symbols.items()):
        if sym not in exports:
            findings.append(Finding(
                MISSING_EXPORT, rel(init), line,
                f"ctypes binds lib.{sym} but t1.cpp's extern \"C\" "
                "block does not define it", ERROR, f"lib.{sym}"))
    for sym in sorted(exports - set(symbols)):
        findings.append(Finding(
            UNBOUND_EXPORT, rel(cpp), 1,
            f"t1.cpp exports {sym}() but the ctypes loader never binds "
            "it", WARNING, sym))
    return findings
