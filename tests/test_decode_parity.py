"""Differential tests: our decoder vs OpenJPEG (via PIL) on the
encoder's own outputs.

The native decoder replaces the third-party oracle; these tests prove
the replacement agrees with it — bit-exact for lossless, identical
reconstruction for lossy (both sides implement the T.800 mid-point
rule), and matching ``-r``-style reduced decodes for r in {0, 1, 2}.
"""
import io

import numpy as np
import pytest
from PIL import Image

from bucketeer_tpu.codec import encoder
from bucketeer_tpu.codec.decode import decode
from bucketeer_tpu.codec.encoder import EncodeParams


def _pil_decode(data: bytes, reduce: int = 0) -> np.ndarray:
    im = Image.open(io.BytesIO(data))
    if reduce:
        im.reduce = reduce       # OpenJPEG's -r / opj_set_decoded_resolution_factor
    im.load()
    return np.asarray(im)


def _psnr(a, b, peak=255.0):
    mse = np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    return 10 * np.log10(peak * peak / max(mse, 1e-12))


def test_lossless_gray_matches_openjpeg(rng):
    img = rng.integers(0, 256, size=(67, 93)).astype(np.uint8)
    data = encoder.encode_jp2(img, 8, EncodeParams(lossless=True,
                                                   levels=3))
    np.testing.assert_array_equal(decode(data), _pil_decode(data))


def test_lossless_rgb_multitile_matches_openjpeg(rng):
    img = rng.integers(0, 256, size=(96, 80, 3)).astype(np.uint8)
    data = encoder.encode_jp2(img, 8, EncodeParams(
        lossless=True, levels=2, tile_size=64))
    np.testing.assert_array_equal(decode(data), _pil_decode(data))


def test_lossy_reconstruction_matches_openjpeg(rng):
    """Both decoders apply the same mid-point dequantization and the
    spec 9/7 synthesis; after the uint8 rounding the reconstructions
    must agree exactly (float noise between two conforming IDWTs sits
    orders of magnitude below half an intensity step)."""
    smooth = np.clip(
        np.cumsum(np.cumsum(rng.random((96, 96)), 0), 1) / 48
        + rng.random((96, 96)) * 20 + 90, 0, 255).astype(np.uint8)
    data = encoder.encode_jp2(smooth, 8, EncodeParams(
        lossless=False, levels=3, n_layers=5, rate=2.0,
        base_delta=0.5))
    ours, ref = decode(data), _pil_decode(data)
    assert int(np.abs(ours.astype(int) - ref.astype(int)).max()) <= 1
    assert _psnr(ours, ref) > 60.0
    assert abs(_psnr(ours, smooth) - _psnr(ref, smooth)) < 0.05


@pytest.mark.parametrize("r", [0, 1, 2])
def test_reduce_matches_openjpeg(rng, r):
    """decode(reduce=r) == OpenJPEG's reduced decode, bit for bit —
    including on the reference's full marker recipe (RPCL, SOP/EPH,
    tile-parts)."""
    img = rng.integers(0, 256, size=(150, 130, 3)).astype(np.uint8)
    params = EncodeParams.kakadu_recipe(lossless=True)
    params.levels = 3
    params.tile_size = 128
    data = encoder.encode_jp2(img, 8, params)
    ours = decode(data, reduce=r)
    ref = _pil_decode(data, reduce=r)
    assert ours.shape == ref.shape
    np.testing.assert_array_equal(ours, ref)


@pytest.mark.slow
def test_reduce_matches_openjpeg_lossy(rng):
    """Reduced decode of a lossy 9/7 stream: float synthesis on both
    sides, so allow one intensity step of rounding skew."""
    y, x = np.mgrid[0:128, 0:128]
    img = np.clip(128 + 80 * np.sin(x / 13.0) * np.cos(y / 9.0)
                  + rng.normal(0, 8, (128, 128)), 0, 255).astype(np.uint8)
    data = encoder.encode_jp2(img, 8, EncodeParams(
        lossless=False, levels=3, base_delta=1.0))
    for r in (1, 2):
        ours = decode(data, reduce=r)
        ref = _pil_decode(data, reduce=r)
        assert ours.shape == ref.shape
        assert int(np.abs(ours.astype(int) - ref.astype(int)).max()) <= 1
