"""Data-parallel tile batching over the ``data`` mesh axis.

The Lambda fan-out analog (reference: README.md:176 — up to 1000
concurrent converter functions; handlers/LoadCsvHandler.java:256-263
dispatches one item at a time): here a batch of same-shape tiles is laid
out with its leading dimension sharded across the mesh, and the fused
transform (codec/pipeline.py) runs SPMD — tiles are independent, so XLA
generates zero communication.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from ..analysis.contracts import contract
from ..codec.pipeline import TilePlan, compiled_transform
from .mesh import DATA_AXIS, batch_sharding


@contract(shapes={"tiles": [("B", "h", "w"), ("B", "h", "w", "C")]},
          dtypes={"tiles": "number"})
def run_tiles_sharded(plan: TilePlan, tiles: np.ndarray,
                      mesh: Mesh) -> np.ndarray:
    """Like :func:`bucketeer_tpu.codec.pipeline.run_tiles` but with the
    batch dimension sharded over the mesh's data axis. Pads the batch up
    to a multiple of the axis size (padding tiles are stripped on
    return)."""
    if tiles.ndim == 3:
        tiles = tiles[..., None]
    b = tiles.shape[0]
    n = mesh.shape[DATA_AXIS]
    pad = (-b) % n
    if pad:
        tiles = np.concatenate(
            [tiles, np.zeros((pad,) + tiles.shape[1:], tiles.dtype)])
    fn = compiled_transform(plan)
    arr = jax.device_put(tiles, batch_sharding(mesh))
    out = np.asarray(jax.device_get(fn(arr)))
    return out[:b]
