"""Message-field and shared-state names.

Port of the reference's constant namespace (reference:
src/main/java/edu/ucla/library/bucketeer/Constants.java:17-190). These are
the JSON field names used on the internal message bus, in HTTP payloads,
and as shared-state map keys, kept identical so external clients (the
Lambda-style converter callback, monitoring scripts like
src/test/scripts/fake-lambda.sh) work unchanged.
"""

MESSAGES = "bucketeer_messages"

# Message / payload field names
IMAGE_ID = "image-id"
FILE_PATH = "file-path"
JOB_NAME = "job-name"
CALLBACK_URL = "callback-url"
DERIVATIVE_IMAGE = "derivative-image"
CONVERSION_TYPE = "conversion-type"
SLACK_HANDLE = "slack-handle"
FAILURES = "failures"
STATUS = "status"
SUCCESS = "success"
COUNT = "count"
JOBS = "jobs"
REMAINING = "remaining"
NOTHING_PROCESSED = "nothing-processed"
# Admission backpressure: seconds-to-wait hint carried in a 503 reply
# body (engine/scheduler.py QueueFull -> HTTP Retry-After header).
RETRY_AFTER = "retry-after"
# graftscope trace context carried on bus messages: batch-item and S3
# messages are consumed in fresh asyncio tasks (no contextvar
# inheritance), so the request id rides the payload and the consumer
# re-enters it (bucketeer_tpu/obs).
REQUEST_ID = "request-id"
# Per-job dead-letter records in the GET /batch/jobs/{name} detail
# (engine/retry.py DeadLetterLog — items that exhausted their budget).
DEAD_LETTERS = "dead-letters"
BATCH_RESPONSE = "batch-response"
S3_BUCKET = "bucket"

# CSV form field (reference: src/main/webroot/upload/csv/index.html:40-59)
CSV_FILE_UPLOAD = "csvFileToUpload"

# Shared-state names (reference: Constants.java:130-149)
LAMBDA_JOBS = "lambda-jobs"
S3_UPLOADS = "s3-uploads"
S3_REQUEST_COUNT = "s3-request-count"
VERTICLE_MAP = "bucketeer-verticles"
JOB_LOCK = "job-lock"
JOB_LOCK_TIMEOUT = 10.0  # seconds (reference: Constants.java:44-49)
JOB_DELETE_TIMEOUT = 5.0  # seconds (reference: Constants.java:54)

# Misc
SLACK_ERROR_CHANNEL = "slack-error-channel"
WAIT_COUNT = "wait-count"
MAX_WAIT_COUNT = 10

# Content types
CONTENT_TYPE = "Content-Type"
JSON = "application/json"
HTML = "text/html"
CSV = "text/csv"
TEXT = "text/plain"

# Default TIFF file extensions accepted on the batch path
TIFF_EXTS = (".tif", ".tiff", ".TIF", ".TIFF")
JPX_EXT = ".jpx"
JP2_EXT = ".jp2"
