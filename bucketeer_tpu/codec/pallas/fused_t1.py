"""Pallas TPU kernel for the fused device Tier-1 (codec/cxd.py
``fused_program``): CX/D context modeling chained straight into the MQ
arithmetic coder inside one kernel.

One code-block per grid cell. The block's coefficients land in VMEM,
the kernel runs the shared stripe-parallel CX/D scan
(``cxd._cxd_single``, ``batch_emit=False``), and the resulting symbol
buffer — the (N, max_syms) intermediate that used to round-trip HBM
between the two-program chain (the ``perf-hbm-roundtrip`` finding) —
stays a kernel-local VMEM value consumed directly by the MQ back half
(``mq_scan._mq_block``'s chunk step). The MQ loop's trip count is the
block's *realized* symbol cursor (a scalar while, not a capacity-sized
fori): symbol capacity is a multiple of ``MQ_UNROLL``, so the last
chunk slice stays in bounds. Only finished byte segments, truncation
snapshots and distortion pairs leave the core.

VMEM working set per block at the largest plane bucket (L=32): the
symbol buffer (max_syms(32) ~ 196 KB), the byte buffer (~100 KB),
coefficients and scan state (~33 KB), tables ~1 KB — comfortably
resident; the common L=8/16 buckets use roughly a quarter/half of
that.

Semantics are locked to the jnp fused body by interpret-mode parity
tests (tests/test_mq_device.py) and the device audit lowers the
interpret-mode program on CPU per PR (``cxd.fused_program(...,
pallas=True, interpret=True)``, registry ``cxdmq.fused.pallas``). On
hardware the kernel sits behind the same ``BUCKETEER_CXD_PALLAS`` gate
and Mosaic capability probe as the other Tier-1 kernels.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:                                    # CPU-only jaxlibs lack the TPU ext
    from jax.experimental.pallas import tpu as pltpu
except ImportError:                     # pragma: no cover
    pltpu = None

from .. import cxd
from .cxd_scan import _table_specs, _tpu_params


def _kernel(L: int, cap: int,
            coeff_ref, meta_ref, zc_ref, scc_ref, scx_ref, qe_ref,
            rows_ref, snaps_ref, dlen_ref, dh_ref, dl_ref, cur_ref,
            curb_ref):
    coeffs = coeff_ref[0]
    nbp, floor = meta_ref[0, 0], meta_ref[0, 1]
    cls, h, w = meta_ref[0, 2], meta_ref[0, 3], meta_ref[0, 4]
    buf, counts, dh, dl, cur = cxd._cxd_single(
        L, meta_ref[0, 5], coeffs, nbp, floor, cls, h, w,
        tables=(zc_ref[:], scc_ref[:], scx_ref[:]), batch_emit=False)
    ops = cxd._mq_ops(batched=False)
    flag = (nbp > floor).astype(jnp.int32)
    carry = cxd._mq_drive_while(ops, qe_ref[:], cap, buf, counts, cur,
                                cur, cxd._mq_state(ops, (), L, cap))
    bytebuf, snaps, dlen, curb = cxd._mq_flush(ops, carry, flag != 0,
                                               cap)
    rows_ref[0] = bytebuf
    snaps_ref[0] = snaps
    dh_ref[0] = dh
    dl_ref[0] = dl
    dlen_ref[0, 0] = dlen
    cur_ref[0, 0] = cur
    curb_ref[0, 0] = curb


def fused_pallas(L: int, frac, blocks, nbps, floors, cls,
                 hs, ws, interpret: bool = False):
    """Drop-in replacement for the jnp fused body (``cxd._fused_body``):
    (N, 64, 64) int32 blocks + per-block meta -> (byte rows
    (N*cap/512, 512) uint8, snaps (N, L, 3) int32, dlen (N,) int32,
    dh/dl (N, L, 3) float32, symbol cursors (N,) int32, byte cursors
    (N,) int32). ``frac`` is the runtime fixed-point shift (scalar)."""
    from .cxd_scan import _meta_stack

    n = blocks.shape[0]
    cap = cxd.mq_capacity(cxd.max_syms(L))
    meta = _meta_stack(nbps, floors, cls, hs, ws, frac)
    tables, table_specs = _table_specs()
    qe = jnp.asarray(cxd._QE_ARR)
    vmem = dict(memory_space=pltpu.VMEM) if pltpu is not None else {}
    smem = dict(memory_space=pltpu.SMEM) if pltpu is not None else {}
    rows, snaps, dlen, dh, dl, cur, curb = pl.pallas_call(
        partial(_kernel, L, cap),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, cxd.CBLK, cxd.CBLK),
                         lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, 6), lambda b: (b, 0), **smem),
        ] + table_specs + [
            pl.BlockSpec(qe.shape, lambda b: (0, 0), **vmem),
        ],
        out_specs=(
            pl.BlockSpec((1, cap), lambda b: (b, 0), **vmem),
            pl.BlockSpec((1, L, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, 1), lambda b: (b, 0), **smem),
            pl.BlockSpec((1, L, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, L, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, 1), lambda b: (b, 0), **smem),
            pl.BlockSpec((1, 1), lambda b: (b, 0), **smem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, cap), jnp.uint8),
            jax.ShapeDtypeStruct((n, L, 3), jnp.int32),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
            jax.ShapeDtypeStruct((n, L, 3), jnp.float32),
            jax.ShapeDtypeStruct((n, L, 3), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
        ),
        interpret=interpret,
        **_tpu_params(interpret),
    )(blocks.astype(jnp.int32), meta, *tables, qe)
    return (rows.reshape(-1, cxd.MQ_ROW_BYTES), snaps, dlen[:, 0],
            dh, dl, cur[:, 0], curb[:, 0])
