"""Runtime shape/dtype contracts for codec entry points.

``@contract(shapes=..., dtypes=...)`` documents and enforces the array
interface of a function. Checks run under tests (or when
``BUCKETEER_CONTRACTS=1``); in production the decorator returns the
function *unchanged* at decoration time, so the hot path pays nothing —
not even an extra frame.

Shape specs
    ``shapes={"tiles": ("B", "h", "w")}`` — a tuple per parameter, one
    entry per dimension: an ``int`` must match exactly, a ``str`` is a
    symbolic dimension that must be consistent across every annotated
    argument of the same call, ``None`` matches anything. A ``list`` of
    tuples allows alternative ranks (e.g. grayscale vs RGB).

Dtype specs
    ``dtypes={"src": "integer"}`` — a numpy kind name ("integer",
    "floating", "unsignedinteger", "bool") or an exact dtype name
    ("uint8"); a tuple allows alternatives.

Violations raise :class:`ContractViolation` (a ``TypeError``) naming the
function, parameter, and the mismatch. Works on numpy arrays and on JAX
arrays/tracers alike — both carry ``.shape``/``.dtype``, so contracts
also validate shapes at trace time when applied inside jitted code.
"""
from __future__ import annotations

import functools
import inspect
import os
import sys

import numpy as np


class ContractViolation(TypeError):
    """An argument broke a @contract shape/dtype declaration."""


def contracts_enabled() -> bool:
    env = os.environ.get("BUCKETEER_CONTRACTS", "").strip().lower()
    if env in ("1", "true", "yes", "on"):
        return True
    if env in ("0", "false", "no", "off"):
        return False
    return "pytest" in sys.modules


def _check_shape(fname, pname, value, spec, symbols) -> None:
    shape = getattr(value, "shape", None)
    if shape is None:
        raise ContractViolation(
            f"{fname}: parameter '{pname}' must be array-like "
            f"(got {type(value).__name__})")
    alternatives = spec if isinstance(spec, list) else [spec]
    errors = []
    for alt in alternatives:
        if len(shape) != len(alt):
            errors.append(f"rank {len(shape)} != {len(alt)}")
            continue
        trial = dict(symbols)
        ok = True
        for dim, want in zip(shape, alt):
            if want is None:
                continue
            if isinstance(want, int):
                if dim != want:
                    ok = False
                    errors.append(f"dim {want} != {dim}")
                    break
            else:                      # symbolic
                bound = trial.setdefault(want, dim)
                if bound != dim:
                    ok = False
                    errors.append(f"{want}={bound} but got {dim}")
                    break
        if ok:
            symbols.update(trial)
            return
    raise ContractViolation(
        f"{fname}: parameter '{pname}' has shape {tuple(shape)}, "
        f"expected {spec} ({'; '.join(errors)})")


_KINDS = {"integer": np.integer, "floating": np.floating,
          "unsignedinteger": np.unsignedinteger,
          "signedinteger": np.signedinteger, "bool": np.bool_,
          "number": np.number}


def _check_dtype(fname, pname, value, spec) -> None:
    dtype = getattr(value, "dtype", None)
    if dtype is None:
        raise ContractViolation(
            f"{fname}: parameter '{pname}' must carry a dtype "
            f"(got {type(value).__name__})")
    alternatives = spec if isinstance(spec, (tuple, list)) else [spec]
    for alt in alternatives:
        kind = _KINDS.get(alt)
        if kind is not None:
            if np.issubdtype(np.dtype(dtype), kind):
                return
        elif np.dtype(dtype) == np.dtype(alt):
            return
    raise ContractViolation(
        f"{fname}: parameter '{pname}' has dtype {dtype}, "
        f"expected {spec}")


def contract(shapes: dict | None = None, dtypes: dict | None = None):
    """Declare (and under tests, enforce) array shapes/dtypes."""
    def decorate(fn):
        if not contracts_enabled():
            return fn
        sig = inspect.signature(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            bound = sig.bind(*args, **kwargs)
            symbols: dict = {}
            for pname, spec in (shapes or {}).items():
                if pname in bound.arguments:
                    _check_shape(fn.__qualname__, pname,
                                 bound.arguments[pname], spec, symbols)
            for pname, spec in (dtypes or {}).items():
                if pname in bound.arguments:
                    _check_dtype(fn.__qualname__, pname,
                                 bound.arguments[pname], spec)
            return fn(*args, **kwargs)

        wrapper.__contract__ = {"shapes": shapes, "dtypes": dtypes}
        return wrapper
    return decorate
