"""graftmesh: static SPMD/collective audit of the sharded programs.

deviceaudit lowers the single-device registry; this layer does the
same audit-before-build play for the *sharded* seams (parallel/) that
ROADMAP item 2's device-pool data plane will grow on. Every registered
mesh program — the row-sharded DWT behind ``sharded_transform_tile``,
the ``run_tiles_sharded`` data-parallel transform, and the sharded
variants of the fused Tier-1 program built through the existing
``*_program`` seams — is lowered under a forced 8-device host mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``; a subprocess
when the current interpreter was not started under that flag, exactly
like the PR 15 registry lowering in tests/conftest.py, because the XLA
device count is fixed at backend init) and the **partitioned** HLO is
audited:

- **collectives, with exact bytes** — every ``all-reduce`` /
  ``all-gather`` / ``reduce-scatter`` / ``collective-permute`` /
  ``all-to-all`` instruction is parsed with its per-device operand
  bytes (compiled shapes are already per-shard) and replica-group
  size, and priced by the ring model: the bytes each device moves over
  its ICI links per launch. The per-program collective histogram and
  total ICI bytes join ``.graftaudit-manifest.json`` under
  ``"mesh_programs"`` and are diffed in CI exactly like single-device
  drift — a change that doubles modeled ICI traffic fails the gate
  with no hardware run (tolerance: deviceaudit.COST_DRIFT_TOLERANCE).
- **per-device peak live bytes** — ``compiled.memory_analysis()``
  (argument + output + temp, all per-device) against the machine's
  VMEM budget, the number the single-device model cannot see.
- **roofline with a comms term** — the unpartitioned StableHLO runs
  through graftcost as usual and the parsed ICI bytes land in
  ``CostFacts.ici_bytes``, so modeled time is max(compute, HBM, ICI)
  (``MachineModel.ici_bandwidth`` / ``n_devices``).

Findings over these facts live in :mod:`rules_shard`
(``shard-implicit-allgather`` / ``shard-replicated-large`` /
``shard-axis-dead``), driven by ``python -m bucketeer_tpu.analysis
--mesh-audit`` with the same baseline + staleness hygiene as the AST
and perf rules.

Ring-model ICI bytes per device for group size g (the standard
bandwidth-optimal algorithms; collective-permute is point-to-point):

| collective | per-device link bytes |
|---|---|
| all-gather | in × (g−1) |
| reduce-scatter | in × (g−1)/g |
| all-reduce | 2 × in × (g−1)/g |
| all-to-all | in × (g−1)/g |
| collective-permute | in |
"""
from __future__ import annotations

import hashlib
import os
import re
import subprocess
import sys
from dataclasses import dataclass, field

from . import graftcost
from .deviceaudit import COST_DRIFT_TOLERANCE

MESH_DEVICES = 8
MESH_MANIFEST_KEY = "mesh_programs"
MESH_DRIFT = "shard-manifest-drift"

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

# One collective instruction in compiled HLO:
#   %ag = f32[8]{0} all-gather(f32[2]{0} %x), replica_groups=...
# Async pairs lower as -start/-done; the -start carries the operands,
# so -done lines (no "(" straight after the base name) never match.
_COLL_RE = re.compile(
    r"=\s*[^=]*?\b(all-reduce|all-gather|reduce-scatter|"
    r"collective-permute|all-to-all)(?:-start)?\(")
_HLO_SHAPE_RE = re.compile(
    r"\b(pred|bf16|f16|f32|f64|s4|s8|s16|s32|s64|u4|u8|u16|u32|u64|"
    r"c64|c128)\[([\d,]*)\]")
_HLO_DTYPE_BYTES = {"pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
                    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
                    "f32": 4, "s32": 4, "u32": 4,
                    "f64": 8, "s64": 8, "u64": 8, "c64": 8, "c128": 16}
# replica_groups comes literal ({{0,1},{2,3}}) or iota
# ([num_groups,group_size]<=[...]); group size is what the ring model
# needs from either.
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_REPL_PARAM_RE = re.compile(
    r"=\s*(\S+)\s+parameter\((\d+)\).*sharding=\{replicated\}")


def _shape_bytes(match: re.Match) -> int:
    dims = match.group(2)
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _HLO_DTYPE_BYTES.get(match.group(1), 4)


def _operand_section(line: str, start: int) -> str:
    """The text inside the op's operand parens, honoring nesting
    (tuple-typed operands of -start ops)."""
    depth = 0
    for i in range(start, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i]
    return line[start + 1:]


def ring_ici_bytes(kind: str, in_bytes: int, group: int) -> int:
    """Per-device bytes over the interconnect for one collective,
    under the bandwidth-optimal ring algorithms."""
    if kind == "collective-permute":
        return in_bytes
    if group <= 1:
        return 0
    if kind == "all-gather":
        return in_bytes * (group - 1)
    if kind == "all-reduce":
        return 2 * in_bytes * (group - 1) // group
    # reduce-scatter and all-to-all move the same ring volume.
    return in_bytes * (group - 1) // group


def parse_collectives(hlo_text: str, n_devices: int = MESH_DEVICES) -> dict:
    """Partitioned-HLO text -> {kind: {count, bytes_in, ici_bytes}}.

    ``bytes_in`` sums the per-device operand bytes of every instance
    (compiled shapes are per-shard already); ``ici_bytes`` applies the
    ring model with the instruction's replica-group size (iota or
    literal form; absent — collective-permute — the full mesh)."""
    out: dict = {}
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        kind = m.group(1)
        operands = _operand_section(line, m.end() - 1)
        in_bytes = sum(_shape_bytes(s)
                       for s in _HLO_SHAPE_RE.finditer(operands))
        attrs = line[m.end():]
        gm = _GROUPS_IOTA_RE.search(attrs)
        if gm:
            group = int(gm.group(2))
        else:
            gm = _GROUPS_LITERAL_RE.search(attrs)
            group = (len(gm.group(1).split(",")) if gm else n_devices)
        cell = out.setdefault(kind, {"count": 0, "bytes_in": 0,
                                     "ici_bytes": 0})
        cell["count"] += 1
        cell["bytes_in"] += in_bytes
        cell["ici_bytes"] += ring_ici_bytes(kind, in_bytes, group)
    return out


def parse_replicated_params(hlo_text: str) -> tuple:
    """Entry parameters the partitioner left fully replicated, as
    ((argnum, per_device_bytes), ...) — a replicated param costs its
    whole global size on every device."""
    found = []
    for line in hlo_text.splitlines():
        m = _REPL_PARAM_RE.search(line)
        if m is None:
            continue
        sm = _HLO_SHAPE_RE.search(m.group(1))
        nbytes = _shape_bytes(sm) if sm else 0
        found.append((int(m.group(2)), nbytes))
    return tuple(sorted(found))


# --- the registry ---------------------------------------------------------

@dataclass(frozen=True)
class MeshProgram:
    """One registered sharded program at one canonical mesh.

    ``build() -> (fn, in_shardings, example_args)`` — the callable
    comes from the owning module's ``*_program`` seam (pre-wrapped in
    shard_map for the manual-partitioning entries), ``in_shardings``
    is the tuple of NamedShardings the lowering pins (and the source
    of the mesh shape + declared-axes facts the rules read), and
    ``example_args`` are global-shape ShapeDtypeStructs.
    ``expected_collectives`` names the kinds the program *declares*
    (the DWT's halo ppermutes); anything else the partitioner inserts
    is fair game for ``shard-implicit-allgather``."""
    name: str
    build: object
    expected_collectives: tuple = ()


@dataclass
class MeshFacts:
    """Partitioned-artifact facts for one audited mesh program. Pure
    data — picklable across the subprocess lowering boundary."""
    name: str
    mesh_shape: dict = field(default_factory=dict)
    axes_used: tuple = ()
    expected_collectives: tuple = ()
    fingerprint: str = ""          # sha256 of the unpartitioned
                                   # StableHLO (stable, like deviceaudit)
    collectives: dict = field(default_factory=dict)
    ici_bytes: int = 0             # per-device ring-model total
    peak_live_bytes: int = 0       # per-device arg+out+temp
    replicated_args: tuple = ()    # ((argnum, bytes), ...)
    cost: object = None            # graftcost.CostFacts (+ ici_bytes)
    text: str = ""                 # partitioned HLO (for dumps)
    skipped: str = ""


def mesh_registry() -> list:
    """The canonical audited mesh programs — every sharded execution
    path the encoder ships, at the forced 8-device host mesh, sized to
    the smallest shapes that exercise the real program structure."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..codec import cxd
    from ..codec.pipeline import make_plan, transform_program
    from ..parallel.compat import SM_NO_CHECK, shard_map
    from ..parallel.mesh import DATA_AXIS, batch_sharding, make_mesh
    from ..parallel.sharded_dwt import sharded_dwt_program

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    entries = []

    # The device core of sharded_transform_tile / mesh-spatial encode:
    # rows over the tile axis, halo exchange via lax.ppermute.
    def dwt_entry(ndim, shape, reversible):
        def build():
            mesh = make_mesh(tile_parallel=MESH_DEVICES)
            fn, spec = sharded_dwt_program(2, reversible, mesh, ndim)
            return fn, (NamedSharding(mesh, spec),), [sds(shape,
                                                          jnp.int32)]
        return build

    entries.append(MeshProgram(
        "shard.dwt.tile/gray-rev-256x64-L2/T8",
        dwt_entry(2, (256, 64), True),
        expected_collectives=("collective-permute",)))
    entries.append(MeshProgram(
        "shard.dwt.tile/rgb-rev-256x64-L2/T8",
        dwt_entry(3, (3, 256, 64), True),
        expected_collectives=("collective-permute",)))

    # The run_tiles_sharded path: the fused transform under GSPMD with
    # the batch dimension on the data axis — tiles are independent, so
    # a clean lowering has zero collectives; anything the partitioner
    # inserts is a routing bug this audit exists to catch.
    def transform_entry():
        mesh = make_mesh(tile_parallel=1)
        plan = make_plan(64, 64, 1, 2, True, 8)
        fn, _donate = transform_program(plan)
        return fn, (batch_sharding(mesh),), [sds((8, 64, 64, 1),
                                                 jnp.int32)]
    entries.append(MeshProgram(
        "shard.transform.data/gray8-lossless-64x64-L2/B8",
        transform_entry))

    # The sharded variant of the fused Tier-1 program, through the
    # existing cxd.fused_program seam: one block per device under
    # manual data partitioning (shard_map via parallel.compat), the
    # shape the device-pool data plane will launch.
    def fused_entry():
        mesh = make_mesh(tile_parallel=1)
        fn, _donate = cxd.fused_program(2, pallas=False)
        specs = (P(DATA_AXIS),) * 6 + (P(),)
        sm = shard_map(fn, mesh=mesh, in_specs=specs,
                       out_specs=P(DATA_AXIS), **SM_NO_CHECK)
        ins = tuple(NamedSharding(mesh, s) for s in specs)
        args = ([sds((8, 64, 64), jnp.int32)]
                + [sds((8,), jnp.int32)] * 5 + [sds((), jnp.int32)])
        return sm, ins, args
    entries.append(MeshProgram("shard.cxdmq.fused.data/L2/N8",
                               fused_entry))

    # The batch data plane's assembled output (bucketeer_tpu/batches/):
    # the batched dequant with every band's leading batch axis on the
    # batch mesh — images are independent and the program is
    # elementwise per band, so a clean lowering has ZERO collectives;
    # any partitioner-inserted all-gather means the placement contract
    # (NamedSharding(mesh, P("batch")) end to end) broke somewhere.
    def batch_dequant_entry(reversible, deltas):
        def build():
            import numpy as np
            from jax.sharding import Mesh

            from ..batches import BATCH_AXIS, batch_mesh_program
            devices = np.asarray(jax.devices()[:MESH_DEVICES])
            mesh = Mesh(devices, (BATCH_AXIS,))
            fn, _donate = batch_mesh_program(reversible, deltas)
            shapes = ((8, 1, 16, 16),) * 4 + ((8, 1, 32, 32),) * 3
            ins = tuple(NamedSharding(mesh, P(BATCH_AXIS))
                        for _ in shapes)
            return fn, ins, [sds(s, jnp.int32) for s in shapes]
        return build
    entries.append(MeshProgram(
        "batch.assemble.dequant/gray-rev-L2/B8",
        batch_dequant_entry(True, (1.0,) * 7)))
    entries.append(MeshProgram(
        "batch.assemble.dequant/gray-irrev-L2/B8",
        batch_dequant_entry(False, (0.5,) * 7)))
    return entries


# --- lowering -------------------------------------------------------------

def _axes_facts(in_shardings) -> tuple:
    """(mesh_shape, axes_used) introspected from the NamedShardings the
    program declares — the facts shard-axis-dead compares against."""
    import jax

    mesh_shape: dict = {}
    axes: set = set()
    for s in jax.tree_util.tree_leaves(in_shardings):
        if not hasattr(s, "mesh"):
            continue
        mesh_shape = dict(s.mesh.shape)
        for part in s.spec:
            if part is None:
                continue
            if isinstance(part, (tuple, list)):
                axes.update(part)
            else:
                axes.add(part)
    return mesh_shape, tuple(sorted(axes))


def lower_mesh_program(entry: MeshProgram) -> MeshFacts:
    """Lower + partition one registered mesh program and extract its
    collective/memory facts. Needs the forced host mesh in-process —
    :func:`run_mesh_programs` owns the subprocess fallback."""
    import jax

    facts = MeshFacts(entry.name,
                      expected_collectives=tuple(
                          entry.expected_collectives))
    try:
        fn, in_shardings, args = entry.build()
        facts.mesh_shape, facts.axes_used = _axes_facts(in_shardings)
        lowered = jax.jit(fn, in_shardings=in_shardings).lower(*args)
        stablehlo = lowered.as_text()
        compiled = lowered.compile()
        hlo = compiled.as_text()
    except Exception as exc:  # pragma: no cover - env-dependent
        facts.skipped = f"{type(exc).__name__}: {exc}"
        return facts
    n = 1
    for size in facts.mesh_shape.values():
        n *= size
    facts.text = hlo
    facts.fingerprint = hashlib.sha256(
        stablehlo.encode("utf-8")).hexdigest()
    facts.collectives = parse_collectives(hlo, n_devices=n or
                                          MESH_DEVICES)
    facts.ici_bytes = sum(c["ici_bytes"]
                          for c in facts.collectives.values())
    facts.replicated_args = parse_replicated_params(hlo)
    try:
        mem = compiled.memory_analysis()
        facts.peak_live_bytes = int(mem.argument_size_in_bytes
                                    + mem.output_size_in_bytes
                                    + mem.temp_size_in_bytes)
    except (AttributeError, NotImplementedError,
            RuntimeError):  # pragma: no cover - backend-dependent
        facts.peak_live_bytes = 0
    facts.cost = graftcost.cost_program(stablehlo, entry.name)
    facts.cost.ici_bytes = facts.ici_bytes
    return facts


def _cpu_device_count() -> int:
    import jax

    try:
        return len([d for d in jax.devices() if d.platform == "cpu"])
    except Exception:  # pragma: no cover - backend init failure
        return 0


def _run_inline(entries=None) -> list:
    """Lower every registered mesh program in this process. Clears
    jax's global caches first, for the same fingerprint-reproducibility
    reason as deviceaudit.run_programs."""
    import jax

    jax.clear_caches()
    return [lower_mesh_program(e)
            for e in (mesh_registry() if entries is None else entries)]


_CHILD_SCRIPT = """\
import os, pickle, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()
import jax
jax.config.update('jax_platforms', 'cpu')
from bucketeer_tpu.analysis import graftmesh
pickle.dump(graftmesh._run_inline(), open(sys.argv[1], 'wb'))
"""


def _run_subprocess() -> list:
    """The PR 15 pattern: the XLA device count is fixed at backend
    init, so when this interpreter was not started under the forced
    flag the lowering runs in a child that is — and ships its
    MeshFacts back as a pickle (pure data)."""
    import pickle
    import tempfile

    with tempfile.TemporaryDirectory(prefix="graftmesh-") as tmp:
        out = os.path.join(tmp, "facts.pkl")
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SCRIPT, out],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                "graftmesh subprocess lowering failed:\n"
                + proc.stderr[-2000:])
        with open(out, "rb") as fh:
            return pickle.load(fh)


def run_mesh_programs(entries=None, *, in_process=None) -> list:
    """Lower every registered mesh program under the forced 8-device
    host mesh; returns [MeshFacts]. Runs inline when this interpreter
    already has the mesh (tests, the CI job with XLA_FLAGS exported),
    else in a subprocess started under the flag. ``in_process=False``
    forces the subprocess (the conftest fixture uses it so the inline
    path's cache clearing never hits the test process)."""
    if in_process is None:
        in_process = _cpu_device_count() >= MESH_DEVICES
    if in_process:
        return _run_inline(entries)
    if entries is not None:
        raise ValueError("custom entries cannot cross the subprocess "
                         "boundary; start this interpreter under "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=8 to lower them in-process")
    return _run_subprocess()


# --- manifest -------------------------------------------------------------

def mesh_manifest_from_facts(all_facts: list) -> dict:
    """The ``"mesh_programs"`` manifest section: per (program × mesh),
    the structural fingerprint, collective histogram, modeled ICI
    bytes and per-device peak live — the fingerprints CI diffs."""
    programs = {}
    for f in all_facts:
        if f.skipped:
            continue
        entry = {
            "fingerprint": f.fingerprint,
            "mesh": dict(sorted(f.mesh_shape.items())),
            "collectives": {k: dict(v) for k, v in
                            sorted(f.collectives.items())},
            "ici_bytes": f.ici_bytes,
            "peak_live_bytes": f.peak_live_bytes,
        }
        if f.cost is not None:
            entry["cost"] = f.cost.manifest_entry()
        programs[f.name] = entry
    return programs


def diff_mesh_manifest(old: dict | None, new_programs: dict,
                       skipped=()) -> list:
    """Drift lines between the checked-in manifest's mesh section and
    the freshly lowered one (empty = no drift). Same contract as
    deviceaudit.diff_manifest: programs named in ``skipped`` are
    tolerated missing; fingerprint changes, collective-histogram
    changes, and modeled ICI / peak-live movement beyond
    COST_DRIFT_TOLERANCE all fail — the doubled-ICI-traffic PR dies
    here with no hardware run, while layout jitter under the tolerance
    passes."""
    import jax

    if old is None or MESH_MANIFEST_KEY not in old:
        return [f"no checked-in mesh section: {len(new_programs)} "
                "sharded program(s) unaccounted — regenerate with "
                "--mesh-audit --write-manifest and commit it"]
    if old.get("jax") != jax.__version__:
        return [f"manifest was generated under jax {old.get('jax')} "
                f"but this environment runs jax {jax.__version__} — "
                "lowered programs are version-specific; regenerate "
                "with --write-manifest under the CI jax version"]
    lines = []
    olds = old[MESH_MANIFEST_KEY]
    for name in sorted(set(olds) - set(new_programs) - set(skipped)):
        lines.append(f"{name}: in the mesh manifest but no longer "
                     "lowered (registry entry removed?)")
    for name in sorted(set(new_programs) - set(olds)):
        lines.append(f"{name}: lowered but absent from the mesh "
                     "manifest (new sharded program — regenerate the "
                     "manifest)")
    for name in sorted(set(new_programs) & set(olds)):
        o, n = olds[name], new_programs[name]
        frags = []
        for key in ("ici_bytes", "peak_live_bytes"):
            a, b = o.get(key, 0), n.get(key, 0)
            if a == b:
                continue
            rel = (b - a) / max(abs(a), 1)
            if abs(rel) > COST_DRIFT_TOLERANCE:
                frags.append(f"{key} {a:g} -> {b:g} ({rel:+.0%})")
        if frags:
            lines.append(
                f"{name}: modeled mesh cost drifted beyond "
                f"{COST_DRIFT_TOLERANCE:.0%} ({'; '.join(frags)}) — "
                "a comms-relevant partitioned-program change; if "
                "intentional, regenerate with --mesh-audit "
                "--write-manifest and justify the new traffic in "
                "review")
            continue
        oc = {k: v.get("count", 0)
              for k, v in o.get("collectives", {}).items()}
        nc = {k: v.get("count", 0)
              for k, v in n.get("collectives", {}).items()}
        if oc != nc:
            deltas = [f"{k} {oc.get(k, 0)}->{nc.get(k, 0)}"
                      for k in sorted(set(oc) | set(nc))
                      if oc.get(k, 0) != nc.get(k, 0)]
            lines.append(f"{name}: collective histogram drifted "
                         f"({'; '.join(deltas)}) — the partitioner "
                         "now emits different communication for this "
                         "program")
            continue
        if o.get("fingerprint") != n["fingerprint"]:
            lines.append(f"{name}: sharded program drifted "
                         "(fingerprint changed; collective histogram "
                         "and modeled mesh cost within tolerance)")
    return lines


def render_mesh_line(facts: MeshFacts,
                     machine: graftcost.MachineModel) -> str:
    """One human line per audited mesh program for the CLI output."""
    n_coll = sum(c["count"] for c in facts.collectives.values())
    mesh = "x".join(str(v) for _, v in sorted(facts.mesh_shape.items()))
    head = (f"{facts.name} [mesh {mesh}]: {n_coll} collective(s), "
            f"{facts.ici_bytes / 1e6:.3g} MB ICI/device, peak-live "
            f"{facts.peak_live_bytes / 1e6:.3g} MB/device")
    if facts.cost is None:
        return head
    roof = facts.cost.roofline(machine)
    return (head + f", {roof['bound']}-bound ({machine.name}: "
            f"{roof['time_s'] * 1e6:.3g} us)")
