"""Converter selection (reference: converters/ConverterFactory.java:37-70
probes for Kakadu and falls back to OpenJPEG; here the TPU encoder is the
default and the CLI tools are opt-in/fallback).

Selection order:
1. ``BUCKETEER_CONVERTER`` env (``tpu`` | ``kakadu`` | ``openjpeg``);
2. the in-process TPU converter (always available);
"""
from __future__ import annotations

import os

from .base import Converter
from .cli import KakaduConverter, OpenJPEGConverter
from .tpu import TpuConverter

_BY_NAME = {
    "tpu": TpuConverter,
    "kakadu": KakaduConverter,
    "openjpeg": OpenJPEGConverter,
}

_instance: Converter | None = None


def available_converters() -> dict[str, bool]:
    return {
        "tpu": True,
        "kakadu": KakaduConverter.is_available(),
        "openjpeg": OpenJPEGConverter.is_available(),
    }


def get_converter(name: str | None = None) -> Converter:
    """Resolve (and cache) the process-wide converter instance."""
    global _instance
    if name is None and _instance is not None:
        return _instance
    choice = (name or os.environ.get("BUCKETEER_CONVERTER") or "tpu").lower()
    cls = _BY_NAME.get(choice)
    if cls is None:
        raise ValueError(f"unknown converter: {choice}")
    if cls is not TpuConverter and not cls.is_available():
        cls = TpuConverter
    converter = cls()
    if name is None:
        _instance = converter
    return converter
