"""Code-block-addressable Tier-2 stream index: random access for
region/zoom reads.

A deep-zoom viewer asks for a 512² window of a 100-MPix derivative; the
sequential parser would still walk every packet header in the file to
*find* the handful of packets that matter. The index removes that walk:
built once per stream (and cached by file identity in
``converters/reader.py``), it records for every packet its precinct key
``(comp, res, p_idx)``, quality layer, and ``(offset, length)`` into the
tile's concatenated tile-part bytes — so a region request seeks straight
to the packets of the precincts its window intersects and never parses
the rest of the stream (the reader still loads the file bytes whole —
the decode API is bytes-in — but all per-packet header and entropy
work is confined to the window).

Two build paths:

- **PLT markers** (``ORGgen_plt=yes`` in the reference recipe, and our
  encoder's ``gen_plt``): packet lengths are signaled in the tile-part
  headers, so the index is pure arithmetic — enumerate the packet
  sequence from the coded geometry, accumulate the signaled lengths, and
  never parse a single packet header.
- **Tag-tree walk** otherwise: one full header walk
  (``parser.parse(collect_index=True)``) records the offsets the hard
  way. Still once per stream, amortized across every later region read.

Random access is sound at precinct granularity: every piece of
packet-header state (inclusion/zero-bitplane tag trees, per-block
Lblock) is local to one precinct, chained only across that precinct's
own layers — which the index replays in layer order.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import codestream as cs
from ..encoder import _ceil_div, _packet_sequence
from . import parser as p
from .errors import DecodeError


@dataclass
class StreamIndex:
    """Per-stream random-access metadata. ``packets[tidx]`` lists
    ``(comp, res, p_idx, layer, offset, length)`` in codestream packet
    order, offsets relative to the tile's concatenated tile-part bytes;
    ``tile_spans[tidx]`` maps those bytes back into the codestream."""
    siz: tuple               # (width, height, n_comps, bitdepth, tw, th)
    cod: dict                # parser._parse_cod shape
    guard: int
    quants: dict             # (res, name) -> SubbandQuant
    tile_spans: dict         # tidx -> [(start, end)] codestream offsets
    packets: dict            # tidx -> [(comp, res, p_idx, layer, off, len)]
    source: str              # "plt" | "walk"
    n_packets: int

    @property
    def nbytes(self) -> int:
        """Rough in-memory footprint estimate — the index tier is
        count-bounded, but this is the size contract tests hold the
        index to (~6 small ints per packet entry plus fixed headers)."""
        return 120 * self.n_packets + 4096


def skeleton(idx: StreamIndex) -> p.ParsedStream:
    """A ParsedStream carrying the indexed stream's coded parameters
    with no tiles parsed — the starting point of an indexed region
    read (``parse_tiles`` fills in exactly the tiles a window needs)."""
    width, height, n_comps, bitdepth, tile_w, tile_h = idx.siz
    cod = idx.cod
    ps = p.ParsedStream(width, height, n_comps, bitdepth, tile_w, tile_h,
                        cod["levels"], cod["n_layers"],
                        cod["progression"], cod["mct"],
                        cod["reversible"], idx.guard,
                        cod["xcb"], cod["ycb"], idx.quants, [],
                        use_sop=cod["use_sop"], use_eph=cod["use_eph"])
    ps.precinct_exps = (cod["precinct_exps"]
                        or p._default_exps(cod["levels"]))
    return ps


def _plt_varints(payload: bytes, out: list) -> None:
    """Decode one PLT segment's packet lengths into ``out``: a Zplt
    byte, then 7-bit big-endian varints (A.7.3). A varint split across
    PLT segments is legal in T.800 but not worth the cross-segment
    state here — a None sentinel sends the caller to the walk path."""
    val = 0
    pending = False
    for b in payload[1:]:
        val = (val << 7) | (b & 0x7F)
        pending = True
        if not b & 0x80:
            out.append(val)
            val = 0
            pending = False
    if pending:
        out.append(None)


def build_index(data: bytes) -> StreamIndex:
    """Build the stream index: PLT arithmetic when the stream signals
    complete packet lengths, one tag-tree header walk otherwise."""
    code = p.unbox_jp2(data)
    r = p._Reader(code)
    if r.u16() != cs.SOC:
        raise DecodeError("missing SOC marker")
    siz, cod, guard, quants = p._parse_main_header(r)
    width, height, n_comps, bitdepth, tile_w, tile_h = siz
    n_tiles = _ceil_div(width, tile_w) * _ceil_div(height, tile_h)

    tile_spans: dict = {}
    plt_lens: dict = {}
    plt_next_z: dict = {}

    def on_segment(isot: int, marker: int, payload: bytes) -> None:
        if marker == cs.PLT:
            lens = plt_lens.setdefault(isot, [])
            # Zplt orders PLT segments logically; T.800 allows them to
            # be *stored* out of that order, in which case naive
            # concatenation would permute the offsets (and the
            # count/sum consistency checks could not tell). Demand
            # physical == logical order, else take the walk path.
            expected = plt_next_z.setdefault(isot, 0)
            if not payload or payload[0] != expected:
                lens.append(None)
                return
            plt_next_z[isot] = (expected + 1) & 0xFF
            _plt_varints(payload, lens)

    for isot, body_start, part_end in p._iter_tile_parts(
            r, code, n_tiles, on_segment):
        tile_spans.setdefault(isot, []).append((body_start, part_end))
    if len(tile_spans) != n_tiles:
        raise DecodeError(
            f"{n_tiles - len(tile_spans)} of {n_tiles} tiles have no "
            "tile-part")

    idx = _from_plt(siz, cod, guard, quants, tile_spans, plt_lens)
    if idx is not None:
        return idx
    # No (or inconsistent) PLT: pay the header walk once.
    ps = p.parse(bytes(data), collect_index=True)
    return StreamIndex(siz, cod, guard, quants, ps.tile_spans,
                       ps.packet_index, "walk", ps.n_packets)


def _from_plt(siz, cod, guard, quants, tile_spans: dict,
              plt_lens: dict) -> StreamIndex | None:
    """PLT fast path: offsets by accumulating signaled lengths along the
    enumerated packet sequence. None when the signaled lengths don't
    cover the packet count and tile bytes exactly."""
    ps = StreamIndex(siz, cod, guard, quants, tile_spans, {}, "plt", 0)
    sk = skeleton(ps)
    packets: dict = {}
    total = 0
    for tidx in sorted(tile_spans):
        lens = plt_lens.get(tidx, [])
        if not lens or any(ln is None for ln in lens):
            return None
        tile = p._build_tile(sk, tidx)
        records = p._build_precincts(sk, tile, sk.precinct_exps)
        seq = list(_packet_sequence(sk.progression, records,
                                    sk.levels + 1, sk.n_comps,
                                    sk.n_layers))
        nbytes = sum(e - s for s, e in tile_spans[tidx])
        if len(lens) != len(seq) or sum(lens) != nbytes:
            return None
        entries = []
        off = 0
        for (rec, layer), ln in zip(seq, lens):
            entries.append((rec.comp, rec.res, rec.p_idx, layer, off, ln))
            off += ln
        packets[tidx] = entries
        total += len(entries)
    ps.packets = packets
    ps.n_packets = total
    return ps


def _blocks_in_window(band, ps: p.ParsedStream, win: tuple):
    """Yield (blk, ly0, ly1, lx0, lx1) for the band's code-blocks whose
    tile-local band rectangle intersects ``win`` = (wy0, wy1, wx0, wx1)
    in the same coordinates."""
    wy0, wy1, wx0, wx1 = win
    for (cy, cx), blk in sorted(band.blocks.items()):
        gy0 = max(cy << ps.ycb, band.by0)
        gy1 = min((cy + 1) << ps.ycb, band.by1)
        gx0 = max(cx << ps.xcb, band.bx0)
        gx1 = min((cx + 1) << ps.xcb, band.bx1)
        ly0, ly1 = gy0 - band.by0, gy1 - band.by0
        lx0, lx1 = gx0 - band.bx0, gx1 - band.bx0
        if ly0 < wy1 and ly1 > wy0 and lx0 < wx1 and lx1 > wx0:
            yield blk, ly0, ly1, lx0, lx1


def _rec_wanted(rec, windows: dict, ps: p.ParsedStream) -> bool:
    """Whether a precinct record holds any code-block intersecting its
    band's window (windows keyed by (res, band name))."""
    for prec in rec.band_precincts:
        win = windows.get((prec.band.res, prec.band.name))
        if win is None:
            continue
        for _ in _blocks_in_window(prec.band, ps, win):
            return True
    return False


def parse_tiles(data: bytes, idx: StreamIndex, ps: p.ParsedStream,
                tile_windows: dict, max_res: int,
                max_layers: int) -> None:
    """Indexed Tier-2: build the requested tiles' geometry and parse
    *only* the packets of precincts whose windows need them, seeking by
    the index instead of walking the stream. ``tile_windows`` maps
    tidx -> {(res, name): (wy0, wy1, wx0, wx1)} band-local windows.
    Parsed tiles are appended to ``ps.tiles``."""
    code = p.unbox_jp2(data)
    parsed = 0
    for tidx in sorted(tile_windows):
        windows = tile_windows[tidx]
        spans = idx.tile_spans.get(tidx)
        entries = idx.packets.get(tidx)
        if spans is None or entries is None:
            raise DecodeError(f"stream index has no tile {tidx}")
        tile = p._build_tile(ps, tidx)
        records = p._build_precincts(ps, tile, ps.precinct_exps)
        rec_of = {(r.comp, r.res, r.p_idx): r for r in records}
        wanted_cache: dict = {}
        # Index offsets are relative to the tile's concatenated
        # tile-part bytes; map each wanted packet back to its file span
        # and parse it in place — no O(tile payload) copy per read.
        # Tile-parts split only at packet boundaries (T.800 A.4.2), so
        # a packet always lives inside one span.
        bounds = []                  # (cum_start, cum_end, file_start)
        cum = 0
        for s, e in spans:
            bounds.append((cum, cum + (e - s), s))
            cum += e - s
        for comp, res, p_idx, layer, off, ln in entries:
            if res > max_res or layer >= max_layers:
                continue
            key = (comp, res, p_idx)
            rec = rec_of.get(key)
            if rec is None:
                raise DecodeError(
                    f"stream index precinct {key} not in tile {tidx} "
                    "geometry")
            want = wanted_cache.get(key)
            if want is None:
                want = wanted_cache[key] = _rec_wanted(rec, windows, ps)
            if not want:
                continue
            end = off + ln
            span = next((b for b in bounds
                         if b[0] <= off and end <= b[1]), None)
            if span is None:
                raise DecodeError(
                    "indexed packet overruns tile bytes"
                    if end > cum else
                    f"indexed packet straddles tile-part boundary in "
                    f"tile {tidx}")
            fpos = span[2] + (off - span[0])
            fend = fpos + ln
            pos = p._parse_packet(ps, code, fpos, fend, rec, layer,
                                  store=True)
            if pos != fend:
                raise DecodeError(
                    f"indexed packet length mismatch in tile {tidx}: "
                    f"parsed to {pos - fpos}, index says {ln}")
            parsed += 1
            ps.bytes_parsed += ln
        ps.tiles.append(tile)
    ps.n_packets += parsed
    ps.n_packets_skipped += idx.n_packets - parsed
