"""Pallas TPU kernel for the MQ arithmetic coder (codec/cxd.py).

One code-block per grid cell: the block's CX/D symbol buffer lands in
VMEM and the kernel runs the same MQ_UNROLL-symbol trip the jnp path
scans with (``cxd._mq_chunk_step`` — shared verbatim through the
scalar ``ops`` seam, so the two implementations cannot drift),
carrying the A/C/CT registers, the outstanding ``pending`` byte, the
19 per-context Qe/MPS states, the byte buffer and the per-pass
truncation snapshots through a ``lax.fori_loop``, then flushing.
Renormalization is the arithmetic shift-count form (no per-shift
loop, at most three masked byteouts per symbol). Only the finished
byte segments leave the core — the MQ state machine never touches the
host.

The production device-MQ path runs this step *fused* behind the CX/D
scan (pallas/fused_t1.py, ``cxd.fused_program``) so the symbol buffer
never exists in HBM; this standalone kernel remains the per-block
parity/oracle surface (tests/test_mq_device.py) and the direct
counterpart of ``cxd._mq_run``.

Status: semantics are locked to the jnp path by interpret-mode parity
tests on every CI run. On hardware the kernel is selected by the same
``BUCKETEER_CXD_PALLAS`` gate as the CX/D kernel, behind the Mosaic
capability probe (support.py) that downgrades to the jnp scan — with a
logged reason and a metrics counter — on backends whose plugin cannot
compile Pallas programs.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:                                    # CPU-only jaxlibs lack the TPU ext
    from jax.experimental.pallas import tpu as pltpu
except ImportError:                     # pragma: no cover
    pltpu = None

from .. import cxd
from .cxd_scan import _tpu_params


def _mq_block(L: int, n_steps: int, cap: int, syms, counts, total,
              flag, qe_tab):
    """One block's MQ scan with the scalar ops — shared by this kernel
    and the fused kernel's back half."""
    ops = cxd._mq_ops(batched=False)
    carry = cxd._mq_state(ops, (), L, cap)
    carry = lax.fori_loop(
        0, n_steps // cxd.MQ_UNROLL,
        lambda t, cr: cxd._mq_chunk_step(ops, qe_tab, cap, syms, counts,
                                         total, t * cxd.MQ_UNROLL, cr),
        carry)
    return cxd._mq_flush(ops, carry, flag != 0, cap)


def _kernel(L: int, n_steps: int, cap: int,
            sym_ref, meta_ref, counts_ref, qe_ref,
            buf_ref, snaps_ref, dlen_ref, cur_ref):
    syms = sym_ref[0]
    counts = counts_ref[0]
    total, flag = meta_ref[0, 0], meta_ref[0, 1]
    buf, snaps, dlen, cur = _mq_block(L, n_steps, cap, syms, counts,
                                      total, flag, qe_ref[:])
    buf_ref[0] = buf
    snaps_ref[0] = snaps
    dlen_ref[0, 0] = dlen
    cur_ref[0, 0] = cur


def mq_pallas(L: int, n_steps: int, cap: int, buf, counts, totals, flags,
              interpret: bool = False):
    """Drop-in replacement for the batched jnp MQ scan
    (``cxd._mq_run``): (N, S) uint8 symbols + (N, L, 3) pass cursors +
    (N,) totals and flush flags -> (bytebuf (N, cap) uint8,
    snaps (N, L, 3) int32, dlen (N,) int32, cursors (N,) int32)."""
    n, msym = buf.shape
    meta = jnp.stack([totals, flags], axis=1).astype(jnp.int32)
    qe = jnp.asarray(cxd._QE_ARR)
    vmem = dict(memory_space=pltpu.VMEM) if pltpu is not None else {}
    smem = dict(memory_space=pltpu.SMEM) if pltpu is not None else {}
    bytebuf, snaps, dlen, cur = pl.pallas_call(
        partial(_kernel, L, n_steps, cap),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, msym), lambda b: (b, 0), **vmem),
            pl.BlockSpec((1, 2), lambda b: (b, 0), **smem),
            pl.BlockSpec((1, L, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec(qe.shape, lambda b: (0, 0), **vmem),
        ],
        out_specs=(
            pl.BlockSpec((1, cap), lambda b: (b, 0), **vmem),
            pl.BlockSpec((1, L, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, 1), lambda b: (b, 0), **smem),
            pl.BlockSpec((1, 1), lambda b: (b, 0), **smem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, cap), jnp.uint8),
            jax.ShapeDtypeStruct((n, L, 3), jnp.int32),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
        ),
        interpret=interpret,
        **_tpu_params(interpret),
    )(buf.astype(jnp.uint8), meta, counts.astype(jnp.int32), qe)
    return bytebuf, snaps, dlen[:, 0], cur[:, 0]
