"""JP2 / JPX file format boxing (T.800 Annex I; T.801 for JPX brand).

Wraps a raw codestream into the box structure decoders and IIIF viewers
expect. The reference emits ``.jpx`` files named after the URL-encoded
image id (reference: converters/KakaduConverter.java:34,57); we produce
the same, with .jp2 boxing available for maximum decoder compatibility.
"""
from __future__ import annotations

import struct


def _box(box_type: bytes, payload: bytes) -> bytes:
    return struct.pack(">I", 8 + len(payload)) + box_type + payload


SIGNATURE = struct.pack(">I", 12) + b"jP  " + b"\x0d\x0a\x87\x0a"


def ftyp(jpx: bool = False) -> bytes:
    if jpx:
        return _box(b"ftyp", b"jpx " + struct.pack(">I", 0) + b"jpx jp2 jpxb")
    return _box(b"ftyp", b"jp2 " + struct.pack(">I", 0) + b"jp2 ")


def jp2_header(width: int, height: int, n_comps: int, bitdepth: int,
               signed: bool = False) -> bytes:
    ihdr = _box(b"ihdr", struct.pack(
        ">IIHBBBB", height, width, n_comps,
        (bitdepth - 1) | (0x80 if signed else 0),
        7,   # compression type: JPEG 2000
        0,   # colorspace known
        0))  # no intellectual property
    enum_cs = 16 if n_comps >= 3 else 17  # sRGB / greyscale
    colr = _box(b"colr", bytes([1, 0, 0]) + struct.pack(">I", enum_cs))
    return _box(b"jp2h", ihdr + colr)


def wrap(codestream: bytes, width: int, height: int, n_comps: int,
         bitdepth: int, jpx: bool = False, signed: bool = False) -> bytes:
    return (SIGNATURE
            + ftyp(jpx)
            + jp2_header(width, height, n_comps, bitdepth, signed)
            + _box(b"jp2c", codestream))
