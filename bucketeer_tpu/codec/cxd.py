"""Device-side EBCOT context modeling: CX/D symbol streams on the TPU.

The host Tier-1 coder (native/t1.cpp) used to redo the full Annex D
context modeling — significance propagation / magnitude refinement /
cleanup, with live neighborhood state — for every bit-plane of every
code-block. Everything in that loop except the MQ state machine is
data-parallel across code-blocks, so this stage moves it onto the
device: a vmapped scan over each block's stripe columns emits, per
block, the exact ordered (context, decision) symbol sequence the MQ
coder consumes, packed 6 bits/symbol, plus per-pass symbol counts (the
pass boundaries PCRD truncation needs) and per-pass distortion sums.
The host side shrinks to ``t1_encode_cxd`` (native/t1.cpp): replay the
precomputed symbols through the MQ coder — no neighborhood state, no
bit-plane walks.

Two device implementations share one step function (`_make_step`):

- the jnp path (`lax.scan` over stripe-column steps, vmapped across
  blocks) — runs on every backend and is the CPU/test reference;
- the Pallas TPU kernel (codec/pallas/cxd_scan.py) — same step inside a
  ``pallas_call`` with one block per grid cell, gated by
  ``BUCKETEER_CXD_PALLAS`` (default: TPU backend only).

Byte parity is the contract: the symbol sequence equals the one
codec/t1.py's reference coder feeds its MQEncoder (tests/test_cxd.py
proves this with a recording coder), so replaying it yields
byte-identical block streams and identical truncation lengths.

Distortion exactness: PCRD byte-parity with the legacy packed path also
requires bit-identical per-pass distortion values. The native packed
coder accumulates integer-valued midpoint terms in float64; float64 is
unavailable on device, so the scan accumulates ``4 x dist`` (always an
integer) as an unevaluated double-float pair — Dekker two-product /
Knuth two-sum — which represents integer sums exactly to ~2^48. The
host reconstitutes ``(hi + lo) / 4`` in float64 and lands on the same
number the native coder would have produced.

Device MQ coding (``BUCKETEER_DEVICE_MQ``): the second half of Tier-1 —
the MQ arithmetic coder itself — also runs on device as a per-symbol
byte-emitting scan chained after the CX/D scan (`_make_mq_step`, with a
Pallas TPU kernel in codec/pallas/mq_scan.py sharing the same step).
The device then holds finished per-pass byte segments; the host's
``t1_encode_cxd`` MQ replay drops out of the hot path entirely and
:func:`run_device_mq` fetches bytes + per-pass truncation snapshots and
assembles ``t1.CodedBlock`` directly (:func:`assemble_mq_blocks`).
Byte identity with the host ``MQEncoder`` — including byte stuffing,
the 0xFF carry paths, flush, the trailing-0xFF drop and the per-pass
``truncation_length`` snapshots — is the contract
(tests/test_mq_device.py).
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis import graftcost, retrace
from ..config import truthy as cfg_truthy
from .mq import CTX_RL, CTX_UNIFORM, MQEncoder, QE_TABLE
from .pipeline import donate_argnums_if_supported
from .t1 import _SC, _ZC_HH, _ZC_LL_LH, BAND_CLS

CBLK = 64
STRIPES = CBLK // 4
COLS_PER_PLANE = STRIPES * CBLK          # stripe-column steps per pass
SYMS_PER_ROW = 512                       # fetch granularity (symbols)
PACKED_ROW_BYTES = SYMS_PER_ROW * 3 // 4  # 6 bits/symbol -> 384 bytes


def _zc_stack() -> np.ndarray:
    hl = np.transpose(_ZC_LL_LH, (1, 0, 2))
    return np.stack([_ZC_LL_LH, _ZC_HH, hl]).astype(np.int32)


def _sc_tables():
    ctx = np.zeros((3, 3), dtype=np.int32)
    xor = np.zeros((3, 3), dtype=np.int32)
    for (h, v), (c, x) in _SC.items():
        ctx[h + 1, v + 1] = c
        xor[h + 1, v + 1] = x
    return ctx, xor


def max_syms(P: int) -> int:
    """Static per-block symbol capacity: per plane, every sample emits at
    most one decision, a run-length shortcut adds at most 2 symbols per
    stripe column, and each sample emits its sign exactly once ever."""
    return P * (CBLK * CBLK + 2 * COLS_PER_PLANE) + CBLK * CBLK


def rows_per_block(P: int) -> int:
    return max_syms(P) // SYMS_PER_ROW


# --- exact double-float accumulation (see module docstring) -------------

def _two_sum(a, b):
    s = a + b
    bb = s - a
    return s, (a - (s - bb)) + (b - bb)


_SPLIT = np.float32(4097.0)      # 2^12 + 1 (Veltkamp)


def _two_prod(a, b):
    p = a * b
    aa = _SPLIT * a
    ahi = aa - (aa - a)
    alo = a - ahi
    bb = _SPLIT * b
    bhi = bb - (bb - b)
    blo = b - bhi
    err = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo
    return p, err


def _dd_accumulate(dh, dl, p, t, cond, fa, fb):
    """dh/dl[p, t] += fa * fb (exactly, masked by ``cond``)."""
    a = jnp.where(cond, fa, jnp.float32(0.0))
    b = jnp.where(cond, fb, jnp.float32(0.0))
    ph, pe = _two_prod(a, b)
    sh, se = _two_sum(dh[p, t], ph)
    te = dl[p, t] + pe + se
    nh, nl = _two_sum(sh, te)
    return dh.at[p, t].set(nh), dl.at[p, t].set(nl)


def _d4_sig(v, p):
    """4 x significance distortion (t1.sig_dist with tv = v) as two exact
    int-valued float32 factors: D4 = A * (4v - A), A = 2*(vb + 2^(p-1))."""
    a = ((v >> p) << (p + 1)) + (1 << p)
    return a.astype(jnp.float32), (4 * v - a).astype(jnp.float32)


def _d4_ref(v, p):
    """4 x refinement distortion (t1.ref_dist with tv = v):
    D4 = (C - B) * (4v - B - C) with B = 2*r1, C = 2*r0."""
    b = ((v >> (p + 1)) << (p + 2)) + (1 << (p + 1))
    c = ((v >> p) << (p + 1)) + (1 << p)
    return (c - b).astype(jnp.float32), (4 * v - b - c).astype(jnp.float32)


# --- the shared stripe-column step --------------------------------------

def _make_step(P: int, idx, neg, nbp, floor, cls, h, w, tables=None):
    """Build the scan step for one block.

    ``idx``/``neg``: (64, 64) int32 magnitude indices and sign bits;
    ``nbp``/``floor``/``cls``/``h``/``w``: scalars. The returned
    ``step(carry, xt)`` processes one stripe column of one pass
    (xt = [plane, pass, y0, x]) and is shared verbatim between the
    vmapped lax.scan path and the Pallas kernel (pallas/cxd_scan.py).
    ``tables``: optional (zc (3,3,3,5), sc_ctx (3,3), sc_xor (3,3))
    int32 arrays — the Pallas kernel passes them as kernel inputs
    (kernels cannot capture array constants); None embeds them.

    Carry: (chi (66,66) int32 zero-padded sign/significance state,
    pi (64,64) int32, refined (64,64) int32, cursor int32,
    buf (max_syms,) uint8, counts (P,3) int32 cursor-at-end-of-pass,
    dh/dl (P,3) float32 double-float 4x-distortion sums).
    """
    if tables is None:
        sc_c, sc_x = _sc_tables()
        tables = (jnp.asarray(_zc_stack()), jnp.asarray(sc_c),
                  jnp.asarray(sc_x))
    zc, sc_ctx, sc_xor = tables
    msym = max_syms(P)

    def emit(buf, cur, cond, ctx, d):
        sym = (ctx | (d << 5)).astype(jnp.uint8)
        buf = buf.at[jnp.where(cond, cur, msym)].set(sym, mode="drop")
        return buf, cur + cond.astype(jnp.int32)

    def step(carry, xt):
        chi, pi, ref, cur, buf, counts, dh, dl = carry
        p, t, y0, x = xt[0], xt[1], xt[2], xt[3]

        valid = (p < nbp) & (p >= floor)
        first = p == nbp - 1
        col_live = valid & ((t == 2) | jnp.logical_not(first)) \
            & (x < w) & (y0 < h)

        # One dynamic slice covers the whole stripe column plus its halo
        # in padded coordinates: sample (y, x) lives at patch[y-y0+1, 1].
        patch = lax.dynamic_slice(chi, (y0, x), (6, 3))
        pi_c = lax.dynamic_slice(pi, (y0, x), (4, 1))[:, 0]
        ref_c = lax.dynamic_slice(ref, (y0, x), (4, 1))[:, 0]
        v4 = lax.dynamic_slice(idx, (y0, x), (4, 1))[:, 0]
        n4 = lax.dynamic_slice(neg, (y0, x), (4, 1))[:, 0]
        bit4 = (v4 >> p) & 1

        def nbr_sums(sigm, i):
            sh = sigm[i + 1, 0] + sigm[i + 1, 2]
            sv = sigm[i, 1] + sigm[i + 2, 1]
            sd = (sigm[i, 0] + sigm[i, 2]
                  + sigm[i + 2, 0] + sigm[i + 2, 2])
            return sh, sv, sd

        def sign_emit(buf, cur, cond, patch, i, neg_i):
            hc = jnp.clip(patch[i + 1, 0] + patch[i + 1, 2], -1, 1)
            vc = jnp.clip(patch[i, 1] + patch[i + 2, 1], -1, 1)
            return emit(buf, cur, cond, sc_ctx[hc + 1, vc + 1],
                        neg_i ^ sc_xor[hc + 1, vc + 1])

        # Run-length shortcut (cleanup only): the whole stripe must be in
        # extent, uncoded, insignificant, with empty neighborhoods — all
        # judged on column-start state, exactly like the reference.
        sig0 = (patch != 0).astype(jnp.int32)
        empty = col_live & (t == 2) & ((y0 + 3) < h)
        for i in range(4):
            sh, sv, sd = nbr_sums(sig0, i)
            empty = empty & (sig0[i + 1, 1] == 0) & (pi_c[i] == 0) \
                & ((sh + sv + sd) == 0)
        rl_ok = empty
        any_run = bit4.max() > 0
        k = jnp.argmax(bit4).astype(jnp.int32)
        rl1 = rl_ok & any_run

        buf, cur = emit(buf, cur, rl_ok, jnp.int32(CTX_RL),
                        any_run.astype(jnp.int32))
        buf, cur = emit(buf, cur, rl1, jnp.int32(CTX_UNIFORM), (k >> 1) & 1)
        buf, cur = emit(buf, cur, rl1, jnp.int32(CTX_UNIFORM), k & 1)
        # Sample k becomes significant with no ZC decision: set state,
        # accumulate its distortion, code its sign.
        patch = patch.at[k + 1, 1].set(
            jnp.where(rl1, 1 - 2 * n4[k], patch[k + 1, 1]))
        fa, fb = _d4_sig(v4[k], p)
        dh, dl = _dd_accumulate(dh, dl, p, t, rl1, fa, fb)
        buf, cur = sign_emit(buf, cur, rl1, patch, k, n4[k])

        for i in range(4):
            samp_in = col_live & ((y0 + i) < h)
            sigm = (patch != 0).astype(jnp.int32)
            sig_i = sigm[i + 1, 1] != 0
            pi_i = pi_c[i] != 0
            sh, sv, sd = nbr_sums(sigm, i)
            nz = (sh + sv + sd) > 0
            sp = samp_in & (t == 0) & ~sig_i & nz
            mr = samp_in & (t == 1) & sig_i & ~pi_i
            rl_skip = rl_ok & (jnp.logical_not(any_run) | (i <= k))
            cl = samp_in & (t == 2) & ~sig_i & ~pi_i & ~rl_skip
            ctx = jnp.where(t == 1,
                            jnp.where(ref_c[i] != 0, 16,
                                      jnp.where(nz, 15, 14)),
                            zc[cls, sh, sv, sd])
            buf, cur = emit(buf, cur, sp | mr | cl, ctx, bit4[i])
            newsig = (sp | cl) & (bit4[i] == 1)
            pi_c = pi_c.at[i].set(jnp.where(sp, 1, pi_c[i]))
            ref_c = ref_c.at[i].set(jnp.where(mr, 1, ref_c[i]))
            patch = patch.at[i + 1, 1].set(
                jnp.where(newsig, 1 - 2 * n4[i], patch[i + 1, 1]))
            fa, fb = _d4_sig(v4[i], p)
            dh, dl = _dd_accumulate(dh, dl, p, t, newsig, fa, fb)
            fa, fb = _d4_ref(v4[i], p)
            dh, dl = _dd_accumulate(dh, dl, p, t, mr, fa, fb)
            buf, cur = sign_emit(buf, cur, newsig, patch, i, n4[i])

        chi = lax.dynamic_update_slice(chi, patch[1:5, 1:2],
                                       (y0 + 1, x + 1))
        pi = lax.dynamic_update_slice(pi, pi_c[:, None], (y0, x))
        ref = lax.dynamic_update_slice(ref, ref_c[:, None], (y0, x))
        counts = counts.at[p, t].set(cur)
        # The coded-this-plane flags reset after every cleanup pass.
        plane_done = (t == 2) & (y0 == CBLK - 4) & (x == CBLK - 1)
        pi = jnp.where(plane_done, jnp.zeros_like(pi), pi)
        return (chi, pi, ref, cur, buf, counts, dh, dl), None

    return step


def init_state(P: int):
    msym = max_syms(P)
    return (jnp.zeros((CBLK + 2, CBLK + 2), jnp.int32),
            jnp.zeros((CBLK, CBLK), jnp.int32),
            jnp.zeros((CBLK, CBLK), jnp.int32),
            jnp.int32(0),
            jnp.zeros((msym,), jnp.uint8),
            jnp.zeros((P, 3), jnp.int32),
            jnp.zeros((P, 3), jnp.float32),
            jnp.zeros((P, 3), jnp.float32))


def scan_xs(P: int) -> np.ndarray:
    """(T, 4) int32 [plane, pass, stripe_y0, column] in coding order:
    planes descending, passes sigprop/magref/cleanup, stripes then
    columns — first-plane and sub-floor steps are masked in the kernel,
    not skipped, so the shape stays static."""
    steps = []
    for p in range(P - 1, -1, -1):
        for t in range(3):
            for y0 in range(0, CBLK, 4):
                for x in range(CBLK):
                    steps.append((p, t, y0, x))
    return np.asarray(steps, dtype=np.int32)


def _cxd_single(P, frac_bits, xs, coeffs, nbp, floor, cls, h, w):
    idx = (jnp.abs(coeffs) >> frac_bits).astype(jnp.int32)
    # Bits below the floor are truncated away exactly as the packed
    # payload never ships them: the host coder's distortion estimates
    # are computed from the floored magnitudes, and byte-parity of the
    # PCRD decisions requires reproducing that — not the full-precision
    # values (t1.encode_block's "the caller must have zeroed the
    # corresponding magnitude bits" contract).
    idx = (idx >> floor) << floor
    neg = (coeffs < 0).astype(jnp.int32)
    step = _make_step(P, idx, neg, nbp, floor, cls, h, w)
    carry, _ = lax.scan(step, init_state(P), xs)
    _, _, _, cur, buf, counts, dh, dl = carry
    return buf, counts, dh, dl, cur


def pack6(buf: jnp.ndarray) -> jnp.ndarray:
    """(N, max_syms) uint8 symbols -> (N, max_syms*3/4) uint8, four 6-bit
    symbols per little-endian 24-bit group."""
    n, m = buf.shape
    q = buf.reshape(n, m // 4, 4).astype(jnp.int32)
    word = q[..., 0] | (q[..., 1] << 6) | (q[..., 2] << 12) | (q[..., 3] << 18)
    out = jnp.stack([word & 0xFF, (word >> 8) & 0xFF, (word >> 16) & 0xFF],
                    axis=-1)
    return out.astype(jnp.uint8).reshape(n, m * 3 // 4)


def unpack6(packed: np.ndarray, n_syms: int) -> np.ndarray:
    """Host-side inverse of :func:`pack6` for one block's byte region."""
    groups = np.frombuffer(packed.tobytes(), dtype=np.uint8)
    groups = groups[:-(len(groups) % 3) or None].reshape(-1, 3).astype(
        np.int32)
    word = groups[:, 0] | (groups[:, 1] << 8) | (groups[:, 2] << 16)
    syms = np.stack([(word >> (6 * r)) & 63 for r in range(4)],
                    axis=1).reshape(-1)
    return syms[:n_syms].astype(np.uint8)


def _use_pallas() -> bool:
    """Whether the Pallas kernels are the device implementation.
    ``BUCKETEER_CXD_PALLAS``: "auto" (default) = TPU backend only;
    truthy forces it, falsy disables. A positive choice is then gated
    on the Mosaic capability probe (codec/pallas/support.py): backends
    whose PJRT plugin cannot compile Pallas kernels (the ``axon``
    first-dispatch failures of BENCH_r02/r05) downgrade to the jnp scan
    with a logged reason and a metrics counter instead of crashing at
    first dispatch."""
    env = os.environ.get("BUCKETEER_CXD_PALLAS", "auto")
    if env == "auto":
        want = jax.default_backend() == "tpu"
    else:
        want = cfg_truthy(env)
    if not want:
        return False
    from .pallas import support

    ok, reason = support.mosaic_supported()
    if not ok:
        support.note_downgrade("BUCKETEER_CXD_PALLAS", reason)
        return False
    return True


def _cxd_body(impl, raw, blocks, nbps, floors, cls, hs, ws):
    buf, counts, dh, dl, cur = impl(blocks, nbps, floors, cls, hs, ws)
    if raw:
        # Device-MQ mode: the symbol buffer stays in HBM as the input
        # of the MQ scan (mq_program) — no 6-bit packing, no fetch.
        return buf, counts, dh, dl, cur
    packed = pack6(buf).reshape(-1, PACKED_ROW_BYTES)
    return packed, counts, dh, dl, cur


def cxd_program(P: int, frac_bits: int, pallas: bool | None = None,
                interpret: bool = False, raw: bool = False):
    """(traceable fn, device donate_argnums) for one CX/D program —
    the construction :func:`_compiled_cxd` jits, shared with the device
    audit (analysis/deviceaudit.py), which lowers both implementations
    on CPU (the Pallas kernel in interpret mode). ``pallas=None``
    defers to the runtime choice (:func:`_use_pallas`). ``raw`` returns
    the unpacked (N, max_syms) symbol buffer instead of packed 6-bit
    rows — the device-MQ chain's intermediate. The donate spec
    is empty by verified fact: no output aval matches the (N, 64, 64)
    int32 block input (symbol rows are uint8, tables are per-pass), so
    XLA would drop the alias silently."""
    if _use_pallas() if pallas is None else pallas:
        from .pallas.cxd_scan import cxd_pallas
        impl = partial(cxd_pallas, P, frac_bits, interpret=interpret)
    else:
        impl = jax.vmap(partial(_cxd_single, P, frac_bits,
                                jnp.asarray(scan_xs(P))))
    return retrace.instrument("cxd", partial(_cxd_body, impl, raw)), ()


@lru_cache(maxsize=64)
def _compiled_cxd(P: int, frac_bits: int, raw: bool = False):
    """One jitted CX/D program per (plane count, fixed-point shift,
    output form). The Pallas-vs-jnp choice is made here, outside the
    traced body (cached with the program — flip BUCKETEER_CXD_PALLAS
    before first use)."""
    fn, donate = cxd_program(P, frac_bits, raw=raw)
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


# --- host-side result assembly ------------------------------------------

@dataclass
class CxdStreams:
    """One chunk's CX/D payload, host-side: packed symbol rows plus the
    ordered pass tables the MQ replay walks."""
    payload: np.ndarray        # (R, 384) uint8 packed symbol rows
    row_offsets: np.ndarray    # (n,) int64 first payload row per block
    nbps: np.ndarray           # (n,) int32
    pass_offsets: np.ndarray   # (n+1,) int64 into the pass arrays
    pass_types: np.ndarray     # int32 0=sigprop 1=magref 2=cleanup
    pass_planes: np.ndarray    # int32
    pass_nsyms: np.ndarray     # int32 symbols in this pass
    pass_dists: np.ndarray     # float64 exact distortion reduction
    total_syms: int


def pass_tables(nbps: np.ndarray, floors: np.ndarray, counts: np.ndarray,
                dh: np.ndarray, dl: np.ndarray):
    """Per-block ordered pass lists from the device's cursor snapshots.

    ``counts[b, p, t]`` is the symbol cursor after pass (p, t); walking
    passes in coding order and differencing recovers per-pass symbol
    counts. Returns (pass_offsets (n+1,) int64, types, planes, nsyms
    int32 arrays, dists float64, totals (n,) int64).
    """
    n = len(nbps)
    types, planes, nsyms, dists = [], [], [], []
    offsets = np.zeros(n + 1, dtype=np.int64)
    totals = np.zeros(n, dtype=np.int64)
    dist = (dh.astype(np.float64) + dl.astype(np.float64)) / 4.0
    for b in range(n):
        prev = 0
        nbp, flo = int(nbps[b]), int(floors[b])
        for p in range(nbp - 1, flo - 1, -1):
            for t in ((2,) if p == nbp - 1 else (0, 1, 2)):
                c = int(counts[b, p, t])
                types.append(t)
                planes.append(p)
                nsyms.append(c - prev)
                dists.append(dist[b, p, t])
                prev = c
        totals[b] = prev
        offsets[b + 1] = len(types)
    return (offsets, np.asarray(types, np.int32),
            np.asarray(planes, np.int32), np.asarray(nsyms, np.int32),
            np.asarray(dists, np.float64), totals)


def replay_block(syms: np.ndarray, nbp: int, n_passes: int,
                 pass_types, pass_planes, pass_nsyms, pass_dists):
    """Pure-Python MQ replay of one block's symbol stream — the
    no-native fallback and the test reference. Returns t1.CodedBlock."""
    from . import t1

    mq = MQEncoder()
    passes = []
    pos = 0
    for j in range(n_passes):
        for s in syms[pos:pos + int(pass_nsyms[j])]:
            mq.encode(int(s) >> 5, int(s) & 31)
        pos += int(pass_nsyms[j])
        passes.append(t1.PassInfo(int(pass_types[j]), int(pass_planes[j]),
                                  mq.truncation_length(),
                                  float(pass_dists[j])))
    data = mq.flush() if n_passes else b""
    for info in passes:
        info.cum_length = min(info.cum_length, len(data))
    return t1.CodedBlock(data, nbp if n_passes else 0, passes)


class RecordingMQEncoder(MQEncoder):
    """MQEncoder that also records the (context, decision) sequence and
    the symbol count at every truncation point — the ground truth the
    device CX/D streams are tested against (tests/test_cxd.py)."""

    def __init__(self) -> None:
        super().__init__()
        self.symbols: list = []
        self.boundaries: list = []

    def encode(self, bit: int, ctx: int) -> None:
        self.symbols.append(ctx | (bit << 5))
        super().encode(bit, ctx)

    def truncation_length(self) -> int:
        self.boundaries.append(len(self.symbols))
        return super().truncation_length()


def reference_cxd(mags: np.ndarray, signs: np.ndarray, band: str,
                  floor: int = 0):
    """Reference CX/D stream via codec/t1.py with a recording coder.
    Returns (CodedBlock, symbols uint8 array, pass boundary list)."""
    from . import t1

    rec = RecordingMQEncoder()
    blk = t1.encode_block(mags, signs, band, floor=floor, mq=rec)
    return blk, np.asarray(rec.symbols, dtype=np.uint8), rec.boundaries


def _pad_chunk_meta(N: int, nbps: np.ndarray, floors: np.ndarray,
                    bandnames: list, hs: np.ndarray, ws: np.ndarray,
                    P: int):
    """Per-block metadata padded to the device batch size N: the
    padding tail gets floor >= nbp (dead blocks that emit nothing).
    The scan length and symbol capacity scale with the plane count;
    planes above every block's MSB emit nothing, so P is clamped to
    the chunk's realized maximum (bounded variants: one compile per
    distinct effective P, at most layout.P of them). Shared by the
    replay-mode (:func:`run_cxd`) and device-MQ
    (:func:`run_device_mq`) chunk entries — the padding invariant must
    not diverge between them."""
    n = len(nbps)
    P = max(1, min(P, int(nbps.max()) if n else 1))
    nbps_d = np.zeros(N, np.int32)
    nbps_d[:n] = nbps
    floors_d = np.full(N, P, np.int32)     # padding: floor >= nbp -> dead
    floors_d[:n] = floors
    cls = np.zeros(N, np.int32)
    cls[:n] = [BAND_CLS[b] for b in bandnames]
    hs_d = np.full(N, CBLK, np.int32)
    hs_d[:n] = hs
    ws_d = np.full(N, CBLK, np.int32)
    ws_d[:n] = ws
    return P, nbps_d, floors_d, cls, hs_d, ws_d


def run_cxd(blocks_dev, nbps: np.ndarray, floors: np.ndarray,
            bandnames: list, hs: np.ndarray, ws: np.ndarray,
            P: int, frac_bits: int) -> CxdStreams:
    """Run the device CX/D program for one chunk and fetch its streams.

    ``blocks_dev``: (N, 64, 64) int32 device array (N >= n real blocks;
    the tail is batch padding). Only the packed symbol rows each live
    block actually filled travel device->host (row-granular gather, like
    frontend.fetch_payload).
    """
    from . import frontend

    n = len(nbps)
    P, nbps_d, floors_d, cls, hs_d, ws_d = _pad_chunk_meta(
        int(blocks_dev.shape[0]), nbps, floors, bandnames, hs, ws, P)
    graftcost.record_bucket("cxd.blocks", n, int(blocks_dev.shape[0]))

    packed, counts, dh, dl, cur = _compiled_cxd(P, frac_bits)(
        blocks_dev, jnp.asarray(nbps_d), jnp.asarray(floors_d),
        jnp.asarray(cls), jnp.asarray(hs_d), jnp.asarray(ws_d))

    counts, dh, dl = (np.asarray(jax.device_get(a))[:n]
                      for a in (counts, dh, dl))
    offsets, types, planes, nsyms, dists, totals = pass_tables(
        nbps, floors, counts, dh, dl)
    if totals.size and int(totals.max()) > max_syms(P):
        raise ValueError(
            f"CX/D stream overflow: {int(totals.max())} symbols exceed "
            f"the static capacity {max_syms(P)} (P={P})")

    payload, row_offsets = _fetch_block_rows(
        packed, -(-totals // SYMS_PER_ROW), rows_per_block(P),
        PACKED_ROW_BYTES)
    return CxdStreams(payload, row_offsets[:-1], nbps.astype(np.int32),
                      offsets, types, planes, nsyms, dists,
                      int(totals.sum()))


def _fetch_block_rows(rows_dev, rows_needed: np.ndarray, rpb: int,
                      row_bytes: int):
    """Row-granular device->host fetch shared by the symbol-stream and
    byte-segment payloads: block b owns rows [b*rpb, (b+1)*rpb) of the
    device array and ships only its first ``rows_needed[b]``. Returns
    (payload (R, row_bytes) uint8, row_offsets (n+1,) int64)."""
    from . import frontend

    n = len(rows_needed)
    row_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(rows_needed, out=row_offsets[1:])
    src = np.empty(int(row_offsets[-1]), dtype=np.int64)
    for b in np.nonzero(rows_needed)[0]:
        o = row_offsets[b]
        src[o:row_offsets[b + 1]] = (b * rpb
                                     + np.arange(rows_needed[b]))
    return frontend.gather_rows(rows_dev, src, row_bytes), row_offsets


# --- the device MQ coder (BUCKETEER_DEVICE_MQ) --------------------------
#
# A per-symbol scan over the CX/D symbol buffer replicating the host
# MQEncoder register for register: A (16-bit interval), C (32-bit code,
# uint32 with the host's & 0xFFFFFFFF masks as native wraparound), CT
# (shift countdown), the 47-entry Qe state table, per-context
# index/MPS, the spec's byte-stuffing byteout (Annex C.2.5 incl. the
# carry that increments the previous byte) and the two-byteout flush
# with the software-convention trailing-0xFF drop. Per-pass truncation
# points are captured in-scan: whenever the symbol cursor crosses a
# pass boundary (the CX/D scan's ``counts`` snapshots), the byte count
# at that moment is recorded — exactly what ``MQEncoder.n_bytes()``
# returns when ``truncation_length`` is called at the end of a pass.

MQ_ROW_BYTES = 512       # byte-segment fetch granularity (gather_rows)

_QE_ARR = np.asarray(QE_TABLE, dtype=np.int32)     # (47, 4)


def mq_capacity(n_steps: int) -> int:
    """Static byte capacity for ``n_steps`` symbols, rounded to fetch
    rows. Each MQ decision is one binary symbol; the coder's sustained
    worst case is well under 2 bits/decision (a 15-shift emission needs
    an LPS at a tiny-Qe state, reachable only through long runs of
    sub-bit MPS coding), so 4 bits/symbol plus transient slack is a
    hard ceiling in practice — and :func:`run_device_mq` verifies the
    realized cursor against this capacity and fails loudly rather than
    ship a silently truncated stream."""
    cap = n_steps // 2 + 64
    return -(-cap // MQ_ROW_BYTES) * MQ_ROW_BYTES


def _mq_byteout(cond, c, ct, buf, cur, cap):
    """Annex C.2.5 BYTEOUT, masked by ``cond``: emit one byte of C into
    ``buf`` at ``cur`` (stuffing after 0xFF, carry into the previous
    byte), update C/CT. ``cap`` is the out-of-bounds drop index."""
    last = buf[cur - 1].astype(jnp.int32)
    is_ff = last == 0xFF
    carry = jnp.logical_not(is_ff) & (c >= jnp.uint32(0x8000000))
    newlast = jnp.where(carry, last + 1, last)
    stuff = is_ff | (carry & (newlast == 0xFF))
    c2 = jnp.where(carry & (newlast == 0xFF),
                   c & jnp.uint32(0x7FFFFFF), c)
    out_b = jnp.where(stuff, c2 >> jnp.uint32(20),
                      c2 >> jnp.uint32(19)) & jnp.uint32(0xFF)
    buf = buf.at[jnp.where(cond & carry, cur - 1, cap)].set(
        newlast.astype(jnp.uint8), mode="drop")
    buf = buf.at[jnp.where(cond, cur, cap)].set(
        out_b.astype(jnp.uint8), mode="drop")
    c = jnp.where(cond, jnp.where(stuff, c2 & jnp.uint32(0xFFFFF),
                                  c2 & jnp.uint32(0x7FFFF)), c)
    ct = jnp.where(cond, jnp.where(stuff, 7, 8), ct)
    return c, ct, buf, cur + cond.astype(jnp.int32)


def _mq_renorm(cond, a, c, ct, buf, cur, cap):
    """Annex C.2.4 RENORME as a masked fixed-trip loop: at most 15
    shifts bring A (>= 1 after the interval update) back above 0x8000;
    every CT expiry emits a byte."""
    active = cond
    for _ in range(15):
        a = jnp.where(active, (a << 1) & 0xFFFF, a)
        c = jnp.where(active, c << jnp.uint32(1), c)
        ct = ct - active.astype(jnp.int32)
        c, ct, buf, cur = _mq_byteout(active & (ct == 0), c, ct, buf,
                                      cur, cap)
        active = active & ((a & 0x8000) == 0)
    return a, c, ct, buf, cur


def _mq_init(P: int, cap: int):
    """Carry: (a, c, ct, cursor-into-buf, byte buffer, per-context Qe
    indices, per-context MPS, per-pass byte snapshots). buf[0] is the
    software convention's dummy pre-byte (MQEncoder.buf[0])."""
    # Initial context states (mq.initial_states) built by scalar
    # updates, not an embedded array — Pallas kernels cannot capture
    # array constants.
    idxs = (jnp.zeros((19,), jnp.int32).at[0].set(4)
            .at[CTX_RL].set(3).at[CTX_UNIFORM].set(46))
    return (jnp.int32(0x8000), jnp.uint32(0), jnp.int32(12),
            jnp.int32(1), jnp.zeros((cap,), jnp.uint8), idxs,
            jnp.zeros((19,), jnp.int32), jnp.zeros((P, 3), jnp.int32))


def _make_mq_step(cap: int, symbuf, total, counts, tables=None):
    """Build the per-symbol MQ encode step for one block — shared
    verbatim between the vmapped lax.scan path and the Pallas kernel
    (pallas/mq_scan.py), like :func:`_make_step` for the CX/D scan.

    ``symbuf``: (max_syms,) uint8 symbols (ctx | d << 5); ``total``:
    the block's realized symbol cursor; ``counts``: the (P, 3) pass
    cursor snapshots the CX/D scan produced (pass-boundary detection).
    ``tables``: optional (qe_tab (47, 4) int32,) — the Pallas kernel
    passes it as a kernel input; None embeds it."""
    if tables is None:
        tables = (jnp.asarray(_QE_ARR),)
    (qe_tab,) = tables

    def step(carry, s):
        a, c, ct, cur, buf, idxs, mpss, snaps = carry
        live = s < total
        sym = symbuf[s].astype(jnp.int32)
        d = sym >> 5
        ctx = sym & 31
        idx = idxs[ctx]
        qe = qe_tab[idx, 0]
        mps = mpss[ctx]
        is_mps = d == mps
        a1 = a - qe
        renorm_mps = (a1 & 0x8000) == 0
        lt = a1 < qe
        # Interval update (C.2.2/C.2.3 with conditional exchange): the
        # four (MPS/LPS x exchange) outcomes collapse to two selects.
        new_a = jnp.where(is_mps == lt, qe, a1)
        add_c = jnp.where(is_mps != lt, qe, 0)
        new_idx = jnp.where(is_mps,
                            jnp.where(renorm_mps, qe_tab[idx, 1], idx),
                            qe_tab[idx, 2])
        new_mps = jnp.where(jnp.logical_not(is_mps)
                            & (qe_tab[idx, 3] == 1), 1 - mps, mps)
        idxs = idxs.at[ctx].set(jnp.where(live, new_idx, idx))
        mpss = mpss.at[ctx].set(jnp.where(live, new_mps, mps))
        a = jnp.where(live, new_a, a)
        c = c + jnp.where(live, add_c, 0).astype(jnp.uint32)
        need_rn = live & jnp.where(is_mps, renorm_mps, True)
        a, c, ct, buf, cur = _mq_renorm(need_rn, a, c, ct, buf, cur,
                                        cap)
        # Pass boundary: bytes emitted so far == MQEncoder.n_bytes() at
        # the moment truncation_length() would have been called.
        snaps = jnp.where(live & (counts == s + 1), cur - 1, snaps)
        return (a, c, ct, cur, buf, idxs, mpss, snaps), None

    return step


def _mq_flush(carry, do_flush, cap: int):
    """Annex C.2.9 FLUSH (masked by ``do_flush`` — blocks with no
    coding passes ship no bytes, mirroring ``replay_block``'s
    ``mq.flush() if n_passes else b""``), plus the software
    convention's trailing-0xFF drop. Returns (buf, snaps, data_len,
    cursor)."""
    a, c, ct, cur, buf, idxs, mpss, snaps = carry
    tempc = c + a.astype(jnp.uint32)
    c = c | jnp.uint32(0xFFFF)
    c = jnp.where(c >= tempc, c - jnp.uint32(0x8000), c)
    c = c << ct.astype(jnp.uint32)
    c, ct, buf, cur = _mq_byteout(do_flush, c, ct, buf, cur, cap)
    c = c << ct.astype(jnp.uint32)
    c, ct, buf, cur = _mq_byteout(do_flush, c, ct, buf, cur, cap)
    nbytes = cur - 1
    last = buf[cur - 1].astype(jnp.int32)
    dlen = nbytes - (last == 0xFF).astype(jnp.int32)
    dlen = jnp.where(do_flush, dlen, 0)
    return buf, snaps, dlen, cur


def _mq_single(P, n_steps, cap, symbuf, counts, total, flush_flag):
    step = _make_mq_step(cap, symbuf, total, counts)
    carry, _ = lax.scan(step, _mq_init(P, cap),
                        jnp.arange(n_steps, dtype=jnp.int32))
    return _mq_flush(carry, flush_flag != 0, cap)


def _mq_body(impl, buf, counts, totals, flags):
    bytebuf, snaps, dlen, cur = impl(buf, counts, totals, flags)
    return bytebuf.reshape(-1, MQ_ROW_BYTES), snaps, dlen, cur


def mq_program(P: int, n_steps: int, pallas: bool | None = None,
               interpret: bool = False):
    """(traceable fn, device donate_argnums) for one MQ-coder program —
    the construction :func:`_compiled_mq` jits, shared with the device
    audit (analysis/deviceaudit.py). Inputs: the CX/D scan's raw
    (N, max_syms) uint8 symbol buffer, its (N, P, 3) pass cursors, the
    (N,) realized totals and (N,) flush flags; outputs byte-segment
    rows, per-pass byte snapshots, data lengths and cursors.
    ``n_steps`` is the pow-2-bucketed scan length (<= max_syms(P)).
    The donate spec is empty by verified fact: the uint8 symbol input
    reshapes to differently-shaped uint8 byte rows, so XLA would drop
    the alias silently (the audit's forced probe proves it)."""
    cap = mq_capacity(n_steps)
    if _use_pallas() if pallas is None else pallas:
        from .pallas.mq_scan import mq_pallas
        impl = partial(mq_pallas, P, n_steps, cap, interpret=interpret)
    else:
        impl = jax.vmap(partial(_mq_single, P, n_steps, cap))
    return retrace.instrument("mq", partial(_mq_body, impl)), ()


@lru_cache(maxsize=64)
def _compiled_mq(P: int, n_steps: int):
    fn, donate = mq_program(P, n_steps)
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


def _mq_steps_bucket(tmax: int, P: int) -> int:
    """Pow-2 scan-length bucket covering the chunk's realized maximum
    symbol cursor (compile variants stay O(log max_syms) per P, like
    the frontend's batch buckets), capped at the static capacity."""
    n = 256
    while n < tmax:
        n <<= 1
    return min(n, max_syms(P))


@dataclass
class MqDeviceResult:
    """One chunk's device-MQ outcome: finished code-blocks plus the
    segment timings/volumes the encoder's metrics report."""
    blocks: list               # [t1.CodedBlock]
    total_syms: int
    total_bytes: int
    cxd_s: float               # device context-modeling segment
    mq_s: float                # device MQ-coder segment (incl. fetch)
    host_s: float              # host assembly (the entire host share)


def assemble_mq_blocks(nbps: np.ndarray, floors: np.ndarray,
                       snaps: np.ndarray, dlens: np.ndarray,
                       dists: np.ndarray, payload: np.ndarray,
                       row_offsets: np.ndarray) -> list:
    """Host assembly of device-MQ outputs into ``t1.CodedBlock``s — the
    whole host share of Tier-1 in device-MQ mode (no MQ replay, no
    context modeling; bench.py re-times exactly this to measure the
    host-work reduction).

    ``snaps``: (n, P, 3) per-pass byte counts; ``dlens``: (n,) final
    data lengths; ``dists``: (n, P, 3) float64 exact distortions;
    ``payload``: (R, MQ_ROW_BYTES) fetched byte rows, each block's
    segment starting with the dummy pre-byte; ``row_offsets``: (n+1,)
    first payload row per block."""
    from . import t1
    from .rate import truncation_lengths

    out = []
    for b in range(len(nbps)):
        nbp, flo = int(nbps[b]), int(floors[b])
        dlen = int(dlens[b])
        if nbp <= flo:
            out.append(t1.CodedBlock(b"", 0))
            continue
        raw = payload[int(row_offsets[b]):int(row_offsets[b + 1])]
        data = raw.reshape(-1)[1:1 + dlen].tobytes()
        # One vectorized truncation-point map per block; the pass walk
        # below only indexes it (this loop is the host's entire Tier-1
        # share — keep numpy dispatch out of the per-pass path).
        cums = truncation_lengths(snaps[b], dlen)
        passes = []
        for p in range(nbp - 1, flo - 1, -1):
            for t in ((2,) if p == nbp - 1 else (0, 1, 2)):
                passes.append(t1.PassInfo(t, p, int(cums[p, t]),
                                          float(dists[b, p, t])))
        out.append(t1.CodedBlock(data, nbp, passes))
    return out


def run_device_mq(blocks_dev, nbps: np.ndarray, floors: np.ndarray,
                  bandnames: list, hs: np.ndarray, ws: np.ndarray,
                  P: int, frac_bits: int) -> MqDeviceResult:
    """Tier-1 for one chunk entirely on device: CX/D scan (symbols stay
    in HBM) chained into the MQ-coder scan, then a row-granular fetch
    of the finished byte segments + per-pass truncation snapshots.
    Output blocks are byte-identical to ``t1_batch.encode_cxd`` over
    ``run_cxd`` streams (and therefore to the legacy packed path)."""
    n = len(nbps)
    N = int(blocks_dev.shape[0])
    P, nbps_d, floors_d, cls, hs_d, ws_d = _pad_chunk_meta(
        N, nbps, floors, bandnames, hs, ws, P)
    graftcost.record_bucket("cxd.blocks", n, N)

    t0 = time.perf_counter()
    buf, counts, dh, dl, cur = _compiled_cxd(P, frac_bits, raw=True)(
        blocks_dev, jnp.asarray(nbps_d), jnp.asarray(floors_d),
        jnp.asarray(cls), jnp.asarray(hs_d), jnp.asarray(ws_d))
    # counts stays device-resident — it is the MQ program's boundary
    # input; only the small distortion/cursor arrays come host-side.
    dh_h, dl_h, cur_h = (np.asarray(jax.device_get(x))
                         for x in (dh, dl, cur))
    t_cxd = time.perf_counter() - t0

    if n and int(cur_h[:n].max()) > max_syms(P):
        raise ValueError(
            f"CX/D stream overflow: {int(cur_h[:n].max())} symbols "
            f"exceed the static capacity {max_syms(P)} (P={P})")
    dist = (dh_h.astype(np.float64) + dl_h.astype(np.float64)) / 4.0
    flags = (nbps_d > floors_d).astype(np.int32)

    t0 = time.perf_counter()
    n_steps = _mq_steps_bucket(int(cur_h.max()) if N else 1, P)
    # The MQ scan pads its *trip count* to a pow-2 bucket the same way
    # batches pad their leading dim: padding waste here is sequential
    # steps, the scarcest resource the cost model tracks.
    graftcost.record_bucket("mq.steps",
                            int(cur_h[:n].max()) if n else 0, n_steps)
    cap = mq_capacity(n_steps)
    rows, snaps, dlen, curb = _compiled_mq(P, n_steps)(
        buf, counts, cur, jnp.asarray(flags))
    snaps_h, dlen_h, curb_h = (np.asarray(jax.device_get(x))[:n]
                               for x in (snaps, dlen, curb))
    if n and int(curb_h.max()) > cap:
        raise ValueError(
            f"MQ byte-segment overflow: {int(curb_h.max())} bytes "
            f"exceed the static capacity {cap} ({n_steps} symbol "
            "steps) — the coded stream expanded past the 4-bit/symbol "
            "budget")
    # Row-granular byte fetch: only the rows each live block filled
    # (the block's segment includes the leading dummy pre-byte).
    payload, row_offsets = _fetch_block_rows(
        rows, -(-(dlen_h + 1) // MQ_ROW_BYTES) * (dlen_h > 0),
        cap // MQ_ROW_BYTES, MQ_ROW_BYTES)
    t_mq = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = assemble_mq_blocks(nbps, floors, snaps_h, dlen_h, dist,
                             payload, row_offsets)
    t_host = time.perf_counter() - t0
    return MqDeviceResult(out, int(cur_h[:n].sum()),
                          int(dlen_h.sum()), t_cxd, t_mq, t_host)
