"""File-path prefixes: map a CSV-relative ``File Name`` to an absolute
path under the image mount.

Port of the reference's prefix SPI (reference:
src/main/java/edu/ucla/library/bucketeer/utils/IFilePathPrefix.java:13,
GenericFilePathPrefix.java:12, UCLAFilePathPrefix.java:15,
FilePathPrefixFactory.java:22, PrefixDeserializer.java:21). Prefixes are
JSON-(de)serializable so a Job survives the job store round-trip.
"""
from __future__ import annotations

import os
from typing import Protocol


class FilePathPrefix(Protocol):
    """Resolves the directory prefix for a given relative file path."""

    def get_prefix(self, file_path: str) -> str: ...

    def to_json(self) -> dict: ...


class GenericFilePathPrefix:
    """Plain prefix: every file lives directly under the mount root
    (reference: utils/GenericFilePathPrefix.java:12)."""

    NAME = "GenericFilePathPrefix"

    def __init__(self, root: str = "") -> None:
        self.root = root

    def get_prefix(self, file_path: str) -> str:
        return self.root

    def to_json(self) -> dict:
        return {"prefix": self.NAME, "root": self.root}

    def __eq__(self, other) -> bool:
        return isinstance(other, GenericFilePathPrefix) and other.root == self.root


class UCLAFilePathPrefix:
    """UCLA mount layout: paths are stored under ``Masters/dlmasters/``
    unless the CSV path already starts with ``Masters/`` (reference:
    utils/UCLAFilePathPrefix.java:24-28,60-70)."""

    NAME = "UCLAFilePathPrefix"
    MASTERS = "Masters"
    DL_MASTERS = os.path.join("Masters", "dlmasters")

    def __init__(self, root: str = "") -> None:
        self.root = root

    def get_prefix(self, file_path: str) -> str:
        if file_path.startswith(self.MASTERS + os.sep) or \
                file_path.startswith(self.MASTERS + "/"):
            return self.root
        return os.path.join(self.root, self.DL_MASTERS)

    def to_json(self) -> dict:
        return {"prefix": self.NAME, "root": self.root}

    def __eq__(self, other) -> bool:
        return isinstance(other, UCLAFilePathPrefix) and other.root == self.root


def get_prefix(name: str | None, root: str = "") -> FilePathPrefix:
    """Factory by configured prefix name (reference:
    utils/FilePathPrefixFactory.java:22-40): 'UCLAFilePathPrefix' selects
    the UCLA layout, anything else the generic one."""
    if name and name.strip().lower() in ("ucla", UCLAFilePathPrefix.NAME.lower()):
        return UCLAFilePathPrefix(root)
    return GenericFilePathPrefix(root)


def from_json(data: dict | None) -> FilePathPrefix | None:
    """Deserialize a prefix written by ``to_json`` (reference:
    utils/PrefixDeserializer.java:45-60)."""
    if not data:
        return None
    name = data.get("prefix")
    root = data.get("root", "")
    if name == UCLAFilePathPrefix.NAME:
        return UCLAFilePathPrefix(root)
    return GenericFilePathPrefix(root)
