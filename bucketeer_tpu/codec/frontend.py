"""Device-side EBCOT Tier-1 front-end: bit-plane decomposition, coding
statistics and payload compaction on the TPU.

This stage exists because the encoder's measured ceiling was the
device-to-host transfer of raw int32 Mallat planes (4 bytes/sample —
~90% of wall clock over a constrained PCIe/tunnel link), while the MQ
coder itself only ever consumes *bit-planes*. So the device now:

1. runs the fused sample transform (pipeline._transform_batch: level
   shift + RCT/ICT + DWT + quantization),
2. carves the Mallat planes into 64x64 code-blocks (the reference
   recipe's ``Cblk={64,64}``, converters/KakaduConverter.java:38-44),
3. computes per-block/per-plane Tier-1 statistics — newly-significant
   counts and *exact* distortion sums (they replace the fractional-bit
   planes the host coder used for PCRD slopes), and
4. packs each bit-plane and the sign plane into 512-byte bitmaps held
   device-side; a gather then compacts exactly the planes the rate
   target needs (descending from each block's MSB to its floor) before
   the one device->host copy.

A block with b coded planes ships ``(b+1) * 512`` bytes instead of
``4096 * 4`` — typically 8-20x less, and blocks the rate allocator will
discard ship nothing at all. The host C++ coder (native/t1.cpp,
``t1_encode_packed``) consumes the bitmaps directly.

Everything here is plain jnp on static shapes, so the same program runs
on TPU and on the CPU backend (no-TPU dev mode / tests).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import graftcost, retrace
from ..analysis.contracts import contract
from .pipeline import (TilePlan, _bucket, _step_map, _transform_batch,
                       donate_argnums_if_supported)
from .quant import FRAC_BITS

CBLK = 64
ROW_BYTES = 512          # one packed 64x64 bitmap


@dataclass(frozen=True)
class BlockMeta:
    """One code-block's place inside a tile (canonical frontend order)."""
    comp: int
    slot_i: int          # index into plan.slots
    iy: int              # cell raster position within the tile-band
    ix: int
    h: int               # true coded extent (<= 64)
    w: int


@dataclass(frozen=True)
class FrontendLayout:
    """Host-side mirror of the device blockification for one plan."""
    plan: TilePlan
    metas: tuple          # tuple[BlockMeta], length n_per_tile
    P: int                # plane capacity (max Mb over subbands)
    mb_caps: tuple        # per-meta subband Mb (guard-bit ceiling)

    @property
    def n_per_tile(self) -> int:
        return len(self.metas)


@lru_cache(maxsize=256)
def layout_for(plan: TilePlan) -> FrontendLayout:
    """Block order: component-major, then plan.slots order (resolution
    then LL/HL/LH/HH), then raster cells — matching the band/cell walk
    of encoder._tile_bands so host metadata lines up index-for-index
    with the device's concatenated block axis."""
    metas = []
    caps = []
    for c in range(plan.n_comps):
        for si, s in enumerate(plan.slots):
            nby = -(-s.h // CBLK) if s.h else 0
            nbx = -(-s.w // CBLK) if s.w else 0
            for iy in range(nby):
                for ix in range(nbx):
                    metas.append(BlockMeta(
                        c, si, iy, ix,
                        min(CBLK, s.h - iy * CBLK),
                        min(CBLK, s.w - ix * CBLK)))
                    caps.append(s.quant.n_bitplanes)
    P = max((s.quant.n_bitplanes for s in plan.slots), default=1)
    return FrontendLayout(plan, tuple(metas), P, tuple(caps))


def _blockify(planes: jnp.ndarray, plan: TilePlan) -> jnp.ndarray:
    """(B, C, H, W) Mallat planes -> (B * n_per_tile, 64, 64) int32 in
    layout_for order. Partial edge blocks sit at the top-left of their
    64x64 container, zero-padded (padding never creates significance)."""
    b = planes.shape[0]
    parts = []
    for c in range(plan.n_comps):
        for s in plan.slots:
            if s.h == 0 or s.w == 0:
                continue
            band = planes[:, c, s.y0:s.y0 + s.h, s.x0:s.x0 + s.w]
            nby, nbx = -(-s.h // CBLK), -(-s.w // CBLK)
            band = jnp.pad(band, ((0, 0), (0, nby * CBLK - s.h),
                                  (0, nbx * CBLK - s.w)))
            band = band.reshape(b, nby, CBLK, nbx, CBLK)
            parts.append(band.transpose(0, 1, 3, 2, 4).reshape(
                b, nby * nbx, CBLK, CBLK))
    return jnp.concatenate(parts, axis=1).reshape(-1, CBLK, CBLK)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(N, 64, 64) {0,1} -> (N, 512) uint8, LSB-first within each byte
    (sample (y, x) -> byte y*8 + x//8, bit x%8)."""
    n = bits.shape[0]
    b = bits.reshape(n, CBLK, 8, 8).astype(jnp.int32)
    w = (1 << jnp.arange(8, dtype=jnp.int32))
    return (b * w).sum(axis=-1).astype(jnp.uint8).reshape(n, ROW_BYTES)


def _frontend_body(plan: TilePlan, P: int, frac_bits: int, mode: str,
                   step_map, batch: jnp.ndarray):
    """The full device program for one tile batch.

    ``mode``: "rows" packs per-plane bitmaps for the host coder's packed
    path; "cxd" skips the packing and returns the blockified int32
    coefficient planes instead — they stay in HBM as the input of the
    CX/D context-modeling stage (codec/cxd.py)."""
    planes = _transform_batch(plan, step_map, batch)
    blocks = _blockify(planes, plan)
    mag_fp = jnp.abs(blocks)
    idx = (mag_fp >> frac_bits).astype(jnp.uint32)
    maxidx = idx.max(axis=(1, 2)).astype(jnp.int32)

    if mode == "rows":
        rows = [_pack_bits(blocks < 0)]      # sign plane first
        for p in range(P):
            rows.append(_pack_bits((idx >> p) & 1))
        rows = jnp.stack(rows, axis=1)       # (N, P+1, 512)

    if frac_bits:
        tv = mag_fp.astype(jnp.float32) * (1.0 / (1 << frac_bits))
    else:
        tv = mag_fp.astype(jnp.float32)
    newsig, sigd, refd = [], [], []
    for p in range(P):
        hi = (idx >> p).astype(jnp.int32)
        is_new = (hi != 0) & ((idx >> (p + 1)) == 0)
        already = (idx >> (p + 1)) != 0
        newsig.append(is_new.sum(axis=(1, 2), dtype=jnp.int32))
        # Significance at plane p reconstructs to 1.5 * 2^p. Expanded,
        # cancellation-free form: tv² - (tv-r)² computed directly loses
        # float32 precision for high-Mb content (tv ~ 2^18 gives ~1%
        # per-sample error), and these sums replace the host's exact
        # distortions for PCRD slope ranking.
        r = jnp.float32(1.5 * (1 << p))
        sd = jnp.where(is_new, r * (2.0 * tv - r), 0.0)
        sigd.append(sd.sum(axis=(1, 2), dtype=jnp.float32))
        # Refinement halves the uncertainty interval (t1.ref_dist).
        # (tv-r1)² - (tv-r0)² in expanded form for the same reason.
        v1 = ((idx >> (p + 1)) << (p + 1)).astype(jnp.float32)
        v0 = ((idx >> p) << p).astype(jnp.float32)
        r1 = v1 + jnp.float32(1 << p)
        r0 = v0 + jnp.float32(0.5 * (1 << p))
        rd = jnp.where(already, (r0 - r1) * (2.0 * tv - r0 - r1), 0.0)
        refd.append(rd.sum(axis=(1, 2), dtype=jnp.float32))
    stats = (maxidx, jnp.stack(newsig, 1), jnp.stack(sigd, 1),
             jnp.stack(refd, 1))
    if mode == "rows":
        return rows.reshape(-1, ROW_BYTES), stats
    return blocks, stats


def frontend_program(plan: TilePlan, P: int, mode: str = "rows"):
    """(traceable fn, device donate_argnums) for one front-end variant —
    the exact construction :func:`_compiled_frontend` jits, shared with
    the device audit (analysis/deviceaudit.py) so the audited artifact
    is the shipped one.

    The donate spec is empty by *verified fact*, not oversight: the
    staged (B, h, w, C) int32 tile batch matches no output aval (rows
    are uint8 bitmaps, stats are per-block vectors), so XLA silently
    drops the alias — the audit lowers this program with donation
    forced and proves the ``tf.aliasing_output`` attribute never
    appears. Requesting it anyway would only emit a per-compile
    warning; ``rules_donation.WHITELIST`` records the same fact."""
    frac_bits = 0 if plan.lossless else FRAC_BITS
    step_map = jnp.asarray(_step_map(plan)) if not plan.lossless else None
    fn = retrace.instrument(
        "frontend", partial(_frontend_body, plan, P, frac_bits, mode,
                            step_map))
    return fn, ()


@lru_cache(maxsize=256)
def _compiled_frontend(plan: TilePlan, P: int, mode: str = "rows"):
    fn, donate = frontend_program(plan, P, mode)
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


@dataclass
class FrontendResult:
    """Per tile-batch device output. ``rows`` stays on device until
    fetch_payload pulls the compacted subset. In CX/D mode (``mode=
    "cxd"``) ``rows`` is None and ``blocks`` holds the blockified int32
    coefficient planes instead — the input of codec/cxd.py.

    ``block_base``: first block's index within the shared device
    ``rows`` array. Non-zero when this result is one request's window
    onto a merged cross-request launch (engine/scheduler.py) — the
    per-block host arrays are already sliced, only the row gather needs
    the offset (fetch_payload applies it)."""
    layout: FrontendLayout
    n_tiles: int          # real (unpadded) tiles in the batch
    rows: object          # jax array (B*n_per_tile*(P+1), 512) uint8
    nbps: np.ndarray      # (n_blocks,) int32 — real blocks only
    newsig: np.ndarray    # (n_blocks, P) int32
    sigd: np.ndarray      # (n_blocks, P) float32
    refd: np.ndarray      # (n_blocks, P) float32
    blocks: object = None  # jax array (B*n_per_tile, 64, 64) int32
    block_base: int = 0   # offset into the shared rows array (blocks)

    @property
    def n_blocks(self) -> int:
        return self.n_tiles * self.layout.n_per_tile


@dataclass
class PendingFrontend:
    """A dispatched, asynchronously executing frontend batch.

    ``dispatch_frontend`` returns immediately after queueing the device
    program (JAX dispatch is async); :meth:`resolve` blocks only for the
    small stats transfer. This is the seam the encoder's overlapped
    pipeline uses: chunk N+1's device program runs while chunk N's
    packed payload is Tier-1 coded on host threads."""
    layout: FrontendLayout
    n_tiles: int
    rows: object          # device array, stays in HBM (None in cxd mode)
    stats: object         # device array tuple (maxidx, newsig, sigd, refd)
    blocks: object = None  # device array (cxd mode only)
    # Host copy of ``stats``, fetched once: a merged cross-request
    # launch (engine/scheduler.py) is resolved by several request
    # threads, each slicing its own window.
    _stats_np: object = None
    _stats_lock: object = field(default_factory=threading.Lock,
                                repr=False)

    def _host_stats(self):
        with self._stats_lock:
            if self._stats_np is None:
                self._stats_np = jax.device_get(self.stats)
        return self._stats_np

    def resolve_stats(self, tile_off: int = 0,
                      n_tiles: int | None = None) -> FrontendResult:
        """Block for the per-block stats (a few KB) and build the
        FrontendResult. The bitmap rows stay on device.

        ``tile_off``/``n_tiles`` window the result onto a contiguous
        tile range of the batch — the seam the cross-request scheduler
        uses to hand each request its share of a merged launch. The
        defaults resolve the whole batch (solo launches)."""
        maxidx, newsig, sigd, refd = self._host_stats()
        if n_tiles is None:
            n_tiles = self.n_tiles
        npt = self.layout.n_per_tile
        off = tile_off * npt
        sl = slice(off, off + n_tiles * npt)
        m = maxidx[sl]
        nbps = np.zeros(n_tiles * npt, dtype=np.int32)
        nz = m > 0
        nbps[nz] = np.floor(np.log2(
            m[nz].astype(np.float64))).astype(np.int32) + 1
        # Guard-bit invariant: a magnitude above 2^Mb would make
        # payload_plan emit row indices into the next block's rows, and
        # the clamped device gather would corrupt the codestream
        # *silently*. Fail loudly like the legacy host path — a real
        # exception, not an assert, so `python -O` can't strip it.
        caps = np.tile(np.asarray(self.layout.mb_caps, dtype=np.int32),
                       n_tiles)
        bad = nbps > caps
        if bad.any():
            raise ValueError(
                f"guard-bit violation: block nbps {nbps[bad].max()} "
                f"exceeds its subband Mb "
                f"{caps[bad][int(np.argmax(nbps[bad]))]} (coefficient "
                "overflow in the device front-end)")
        return FrontendResult(self.layout, n_tiles, self.rows, nbps,
                              newsig[sl], sigd[sl], refd[sl],
                              blocks=self.blocks, block_base=off)


@contract(shapes={"tiles": [("B", "h", "w"), ("B", "h", "w", "C")]},
          dtypes={"tiles": "number"})
def dispatch_frontend(plan: TilePlan, tiles: np.ndarray,
                      mode: str = "rows",
                      device=None) -> PendingFrontend:
    """Queue transform + blockify + stats for a (B, h, w[, C]) tile
    batch on the device and return without waiting for the result.
    ``mode="cxd"`` keeps the raw blockified coefficients on device for
    the CX/D stage instead of packing bit-plane bitmaps; ``mode="mq"``
    is the full-device Tier-1 chain (CX/D scan + MQ coder,
    cxd.run_device_mq) — the front-end program is identical to "cxd"
    (one compiled variant serves both; the modes diverge downstream),
    the distinct name exists so the scheduler and metrics can tell the
    pipelines apart. ``device`` (a ``jax.Device``) stages the batch
    with a *committed* ``jax.device_put`` so the program — and every
    downstream stage consuming its output, even from another thread —
    runs on that core; None keeps default placement."""
    if tiles.ndim == 3:
        tiles = tiles[..., None]
    # Dtype audit at the host->device boundary: the device program's
    # first op widens to int32/float32 anyway (pipeline._transform_batch),
    # so an 8-byte host dtype would double or quadruple the transfer for
    # nothing. Narrow before staging.
    if tiles.dtype == np.int64:
        tiles = tiles.astype(np.int32)
    elif tiles.dtype == np.float64:
        tiles = tiles.astype(np.float32)
    b = tiles.shape[0]
    pad = _bucket(b) - b
    # Workload-shape seam: graftcost weighs per-bucket padding waste by
    # what the service actually launched (docs/analysis.md, graftcost).
    graftcost.record_bucket("frontend.batch", b, b + pad)
    if pad:
        tiles = np.concatenate(
            [tiles, np.zeros((pad,) + tiles.shape[1:], tiles.dtype)])
    layout = layout_for(plan)
    prog_mode = "cxd" if mode == "mq" else mode
    # Committed placement (device_put) vs jnp.asarray matters: an
    # uncommitted array snaps back to the default device the moment a
    # different thread consumes it; a committed one pins the whole
    # downstream chain (gather, fused Tier-1) to the pool worker's core.
    staged = (jax.device_put(tiles, device) if device is not None
              else jnp.asarray(tiles))
    out, stats = _compiled_frontend(plan, layout.P, prog_mode)(staged)
    if prog_mode == "rows":
        return PendingFrontend(layout, b, out, stats)
    return PendingFrontend(layout, b, None, stats, blocks=out)


@contract(shapes={"tiles": [("B", "h", "w"), ("B", "h", "w", "C")]},
          dtypes={"tiles": "number"})
def run_frontend(plan: TilePlan, tiles: np.ndarray) -> FrontendResult:
    """Transform + blockify + stats for a (B, h, w[, C]) tile batch,
    blocking until the stats are on host (the packed bitmap rows stay on
    device). Synchronous wrapper over dispatch_frontend/resolve_stats."""
    return dispatch_frontend(plan, tiles).resolve_stats()


def gather_program():
    """(traceable fn, donate spec) for the compaction gather — audit
    seam. ``rows`` is deliberately non-donated (whitelisted): one
    payload fetch re-reads the same device buffer across chunks."""
    def gather(rows, src):
        return rows[src]
    return retrace.instrument("gather", gather), ()


@lru_cache(maxsize=8)
def _compiled_gather(chunk_rows: int):
    fn, donate = gather_program()
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


GATHER_CHUNK = 4096      # rows per gather dispatch (= 2 MB of payload)


def payload_plan(nbps: np.ndarray, floors: np.ndarray, P: int):
    """Row indices to fetch: for each live block (nbp > floor), its sign
    row then plane rows nbp-1 .. floor (coding order). Returns
    (src int64 (R,), offsets int64 (n+1,)) — offsets in rows, so block
    b's payload is rows [offsets[b], offsets[b+1])."""
    n = len(nbps)
    # nbps beyond the packed plane capacity would index into the *next*
    # block's rows; the device gather clamps out-of-bounds indices, so
    # the corruption would be silent. Fail loudly (ADVICE round 5 #1) —
    # a real exception, not an assert, so `python -O` can't strip it.
    if n and int(nbps.max()) > P:
        raise ValueError(
            f"block nbps {int(nbps.max())} exceeds packed plane "
            f"capacity {P}: guard-bit invariant violated upstream")
    counts = np.where(nbps > floors, nbps - floors + 1, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    src = np.empty(int(offsets[-1]), dtype=np.int64)
    base = np.arange(n, dtype=np.int64) * (P + 1)
    live = np.nonzero(counts)[0]
    for b in live:
        o = offsets[b]
        src[o] = base[b]                       # sign row
        nplanes = counts[b] - 1
        src[o + 1:o + 1 + nplanes] = (
            base[b] + 1 + np.arange(nbps[b] - 1, floors[b] - 1, -1))
    return src, offsets


def gather_rows(rows, src: np.ndarray, row_bytes: int) -> np.ndarray:
    """Compact selected rows of a device (R_total, row_bytes) uint8
    array and copy them host-side in fixed-size gather chunks (one
    compiled program per row width, bounded padding). Shared by the
    packed-bitmap payload fetch and the CX/D symbol-stream fetch."""
    r = len(src)
    if r == 0:
        return np.empty((0, row_bytes), dtype=np.uint8)
    padded = -(-r // GATHER_CHUNK) * GATHER_CHUNK
    src_pad = np.zeros(padded, dtype=np.int64)
    src_pad[:r] = src
    gather = _compiled_gather(GATHER_CHUNK)
    outs = []
    for i in range(0, padded, GATHER_CHUNK):
        outs.append(gather(rows, jnp.asarray(src_pad[i:i + GATHER_CHUNK])))
    out = np.concatenate([np.asarray(jax.device_get(o)) for o in outs])
    return out[:r]


@contract(shapes={"src": ("R",)}, dtypes={"src": "integer"})
def fetch_payload(result: FrontendResult, src: np.ndarray) -> np.ndarray:
    """Compact the selected bitmap rows on device and copy them host-side.
    Returns (R, 512) uint8. ``src`` is relative to the result's own
    first block (payload_plan output); for a window onto a merged
    cross-request launch the shared-array offset is applied here."""
    if result.block_base:
        src = src + np.int64(result.block_base) * (result.layout.P + 1)
    return gather_rows(result.rows, src, ROW_BYTES)


def unpack_block(payload: np.ndarray, offset: int, nbp: int, floor: int,
                 h: int, w: int):
    """Numpy reference unpack (also the no-native fallback): payload rows
    for one block -> (mags uint32 (h,w), negs bool (h,w)). Bits below
    ``floor`` are zero — the coder never visits those planes."""
    def bits(row):
        return np.unpackbits(row.reshape(CBLK, 8), axis=1,
                             bitorder="little")[:h, :w]
    negs = bits(payload[offset]).astype(bool)
    mags = np.zeros((h, w), dtype=np.uint32)
    for j, p in enumerate(range(nbp - 1, floor - 1, -1)):
        mags |= bits(payload[offset + 1 + j]).astype(np.uint32) << p
    return mags, negs
