"""Mosaic/Pallas capability probe + graceful downgrade bookkeeping.

BENCH_r02/r05 died at the *first compiled dispatch* on the experimental
``axon`` platform — after ``jax.devices()`` had succeeded — and a
Pallas kernel is the most backend-demanding program this codebase
ships: a PJRT plugin can run plain XLA yet reject Mosaic lowering
outright. So the Pallas gates (``BUCKETEER_CXD_PALLAS``, and through it
the device-MQ kernel behind ``BUCKETEER_DEVICE_MQ``) no longer take the
flag's word for it: a positive choice is verified by compiling and
dispatching a trivial ``pallas_call`` once per process, and a failing
probe *downgrades* to the jnp scan — same semantics, byte-identical
output — with a logged reason and an ``encode.pallas_downgrades``
metrics counter instead of crashing the encode.

The probe result is cached for the process lifetime (backend identity
cannot change under JAX once initialized); tests reset it via
:func:`reset_probe`.
"""
from __future__ import annotations

import logging
import threading

LOG = logging.getLogger(__name__)

_LOCK = threading.Lock()
_PROBE: tuple | None = None       # (ok, reason)
_NOTED: set = set()               # flags already logged
_SINK = None                      # server.metrics.Metrics-like


def set_metrics_sink(sink) -> None:
    """Install a metrics sink with ``count(name, n=1)`` (the server
    wires server.metrics.GLOBAL at boot); None disables."""
    global _SINK
    _SINK = sink


def reset_probe() -> None:
    """Forget the cached probe result and logged flags (tests only)."""
    global _PROBE
    with _LOCK:
        _PROBE = None
        _NOTED.clear()


def _run_probe() -> tuple:
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _probe_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1

        out = pl.pallas_call(
            _probe_kernel,
            out_shape=jax.ShapeDtypeStruct((8,), jnp.int32),
        )(jnp.arange(8, dtype=jnp.int32))
        jax.block_until_ready(out)
        return True, ""
    except Exception as exc:        # any compile/dispatch failure
        return False, (f"{type(exc).__name__}: "
                       + str(exc).splitlines()[0][:160])


def mosaic_supported() -> tuple:
    """(ok, reason): can this backend compile and run a Pallas kernel?
    Probed once per process with a real compiled dispatch — the same
    code path a production kernel's first launch takes."""
    global _PROBE
    with _LOCK:
        if _PROBE is None:
            _PROBE = _run_probe()
        return _PROBE


def note_downgrade(flag: str, reason: str) -> None:
    """Record one Pallas->jnp downgrade: log the reason once per flag,
    bump the ``encode.pallas_downgrades`` counter every time so the
    /metrics surface shows the fleet is not running the kernels it was
    asked to."""
    if flag not in _NOTED:
        _NOTED.add(flag)
        LOG.warning(
            "%s requested but this backend cannot run Pallas/Mosaic "
            "kernels (%s); downgrading to the jnp scan (byte-identical, "
            "slower)", flag, reason or "probe failed")
    if _SINK is not None:
        _SINK.count("encode.pallas_downgrades")
