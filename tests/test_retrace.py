"""Recompile sentinel (analysis/retrace.py): stable shapes must not
retrace, and unexpected retraces must fail loudly."""
import numpy as np
import pytest

from bucketeer_tpu.analysis import retrace
from bucketeer_tpu.codec import frontend
from bucketeer_tpu.codec.pipeline import make_plan, run_tiles


def _plan(lossless=True):
    return make_plan(16, 16, 1, 2, lossless, 8)


def test_instrument_counts_traces_not_calls():
    import jax

    calls = retrace.snapshot().get("unit-test-stage", 0)
    fn = jax.jit(retrace.instrument(
        "unit-test-stage", lambda x: x * 2))
    fn(np.float32(1.0))
    fn(np.float32(2.0))       # same shape/dtype: cached, no retrace
    assert retrace.snapshot()["unit-test-stage"] - calls == 1


def test_transform_stage_stable_across_repeat_batches(rng):
    plan = _plan()
    tiles = rng.integers(0, 255, (3, 16, 16), dtype=np.uint8)
    run_tiles(plan, tiles)                    # warm (bucketed to 4)
    four = np.concatenate([tiles, tiles[:1]])
    with retrace.expect_max_retraces(0, stages=("transform",)):
        run_tiles(plan, tiles)
        run_tiles(plan, four)                 # same bucket: still 4


def test_new_bucket_is_a_detected_retrace(rng):
    plan = _plan()
    tiles = rng.integers(0, 255, (3, 16, 16), dtype=np.uint8)
    run_tiles(plan, tiles)
    with pytest.raises(retrace.RetraceError) as exc:
        with retrace.expect_max_retraces(0, stages=("transform",)):
            big = rng.integers(0, 255, (5, 16, 16), dtype=np.uint8)
            run_tiles(plan, big)              # bucket 8: new program
    assert "transform" in str(exc.value)


def test_frontend_stage_stable(rng):
    plan = _plan()
    tiles = rng.integers(0, 255, (2, 16, 16), dtype=np.uint8)

    def round_trip():
        res = frontend.run_frontend(plan, tiles)
        src, _ = frontend.payload_plan(
            res.nbps, np.zeros_like(res.nbps), res.layout.P)
        frontend.fetch_payload(res, src)

    round_trip()                              # warm frontend + gather
    with retrace.expect_max_retraces(0, stages=("frontend", "gather")):
        round_trip()
