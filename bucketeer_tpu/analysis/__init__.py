"""graftlint: JAX/TPU-aware static analysis + runtime contracts.

Three legs, one goal — keep the fused device pipeline's invariants
enforced instead of implied:

- **Lint engine** (``python -m bucketeer_tpu.analysis``): AST rules for
  host syncs and Python branches on tracers inside jit-compiled code,
  float64 leakage, unsanctioned device-to-host copies, swallowed
  exceptions in the engine/server handlers, empty packages, and a
  ctypes <-> C++ ABI cross-check for the native Tier-1 coder.
  See docs/analysis.md for every rule and the suppression syntax
  (``# graftlint: disable=<rule>``).
- **Cost audit** (:mod:`graftcost`, ``--cost``): a static roofline &
  memory-traffic model over the same lowered artifacts the device
  audit produces — FLOPs, HBM bytes under a fusion-region model,
  arithmetic intensity, sequential-scan depth and peak live buffers,
  with ``perf-*`` rules (:mod:`rules_perf`) and tolerance-gated cost
  fingerprints in the program manifest.
- **Contracts** (:func:`contract`): shape/dtype declarations on codec
  entry points, enforced under tests, zero-cost in production.
- **Retrace sentinel** (:mod:`retrace`): per-stage XLA compilation
  counters so unexpected recompiles fail tests instead of silently
  stalling the service.
"""
from .contracts import ContractViolation, contract, contracts_enabled
from .findings import ERROR, WARNING, Finding
from .lint import load_baseline, run_lint, write_baseline

__all__ = [
    "ContractViolation", "contract", "contracts_enabled",
    "ERROR", "WARNING", "Finding",
    "load_baseline", "run_lint", "write_baseline",
]
