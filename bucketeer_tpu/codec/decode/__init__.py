"""Native JP2 decode subsystem — the inference-path mirror of the
encoder, and the self-contained round-trip oracle (no OpenJPEG in the
loop):

- ``parser``   Tier-2: JP2 boxes, markers, packet headers (host)
- ``index``    code-block-addressable stream index (random access)
- ``t1_dec``   MQ + EBCOT context-modeling pass decode (host)
- ``device``   dequantize + inverse DWT + inverse RCT/ICT (jitted)
- ``decoder``  orchestration, partial decode (``reduce`` / ``layers``),
               windowed region decode (``region`` / ``index``)

Public API: :func:`decode`, :func:`build_index`, :class:`StreamIndex`,
:class:`DecodeError`, :func:`set_metrics_sink`.
"""
from .decoder import decode, set_metrics_sink
from .errors import DecodeError, InvalidParam
from .index import StreamIndex, build_index
from .parser import probe

__all__ = ["decode", "probe", "build_index", "StreamIndex",
           "DecodeError", "InvalidParam", "set_metrics_sink"]
