"""HTTP API: the 8 OpenAPI operations + static web UI.

Port of the reference's contract-driven router and handlers (reference:
verticles/MainVerticle.java:110-163 builds an OpenAPI3 router from
bucketeer.yaml and binds handlers by operationId; handlers/ implements
them). Same paths, operationIds, status codes, and payload shapes — the
contract lives in ``bucketeer_tpu/server/openapi.yaml`` and is served at
``/docs/openapi.yaml``.

Router quirks kept for parity:
- ``/upload`` redirects to the CSV upload form
  (reference: MainVerticle.java:143-158);
- non-PATCH methods on the batch status-update path return 405, not 404
  (reference: handlers/MatchingOpNotFoundHandler.java:28-47);
- validation failures render the HTML error template with 400, unexpected
  errors 500 (reference: handlers/FailureHandler.java:57-95).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import re
import time
import urllib.parse
import uuid

from aiohttp import web

from .. import config as cfg
from .. import constants as c
from .. import job_factory
from .. import models as m
from .. import obs
from ..codec.decode import DecodeError, InvalidParam
from ..converters import TpuReader, available_converters, derivative_path
from ..engine import Engine, start_job, update_item_status
from ..engine.journal import JournalUnavailable
from ..engine.s3 import S3_UPLOADER
from ..engine.scheduler import DeadlineExceeded, QueueFull
from ..engine.store import LockTimeout
from ..engine.workers import IMAGE_WORKER
from ..utils import path_prefix as pp
from . import metrics as metrics_mod

LOG = logging.getLogger(__name__)

WEBROOT = os.path.join(os.path.dirname(__file__), "webroot")
# reference: MatchingOpNotFoundHandler.java:28 — the status-update URL
STATUS_UPDATE_RE = re.compile(r"^/batch/jobs/[^/]+/[^/]+/(?:true|false)$")


def _html(template: str, **kw) -> str:
    path = os.path.join(WEBROOT, template)
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    for key, value in kw.items():
        text = text.replace("{{" + key + "}}", str(value))
    return text


def _error_page(status: int, message: str,
                headers: dict | None = None) -> web.Response:
    # reference: FailureHandler.java:57-95 renders error.html
    return web.Response(status=status, content_type="text/html",
                        headers=headers,
                        text=_html("error.html", status=status,
                                   message=message))


def _unavailable(message: str, retry_after: float) -> web.Response:
    """503 + Retry-After — the one shape every degradation state maps
    to (QueueFull, open circuit, journal unavailable): the client
    should back off and come back, nothing is broken."""
    return _error_page(
        503, message,
        headers={"Retry-After":
                 str(max(1, int(round(float(retry_after)))))})


class Api:
    """The handler set, bound to an :class:`Engine`."""

    def __init__(self, engine: Engine) -> None:
        self.engine = engine
        # The process-wide registry: the encoder reports its
        # device-dispatch vs host-coding segments (overlapped pipeline)
        # and PCRD/Tier-2 retry counters into it, and /metrics serves
        # it. One shared object, so app re-creation can't strand a
        # stale sink.
        self.metrics = metrics_mod.GLOBAL
        from ..codec import decode as codec_decode
        from ..codec import encoder as codec_encoder
        from ..engine.scheduler import get_scheduler
        codec_encoder.set_metrics_sink(self.metrics)
        codec_decode.set_metrics_sink(self.metrics)
        # The cross-request encode scheduler reports queue-wait,
        # per-launch batch occupancy and admission rejects into the
        # same registry, so /metrics shows the serving picture whole.
        get_scheduler().set_metrics_sink(self.metrics)
        # XLA retrace sentinel: every compile of a jitted stage bumps a
        # retrace.<stage> counter here. In production a retrace is a
        # multi-second device stall (usually an unstable shape leaking
        # past the pow-2 buckets) — this makes it alertable, not just a
        # test-time assertion.
        from ..analysis import retrace
        retrace.set_metrics_sink(self.metrics)
        # Pallas/Mosaic capability downgrades (codec/pallas/support.py):
        # a backend that cannot compile the Tier-1 kernels falls back to
        # the jnp scans and bumps encode.pallas_downgrades here, so a
        # fleet silently running without its kernels is visible.
        from ..codec.pallas import support as pallas_support
        pallas_support.set_metrics_sink(self.metrics)
        # Compressed-domain tensor delivery: the tensor codec reports
        # its encode/decode stages and byte counters into the same
        # registry (tensor.encode / tensor.encode_device /
        # tensor.decode segments, tensor.* counters).
        from .. import tensor as tensor_mod
        tensor_mod.set_metrics_sink(self.metrics)
        # Batch data plane (graftfeed): assembly seconds, per-item
        # failure counts — the scheduler side (merged dequant launches,
        # batchread.* occupancy) already reports via its own sink.
        from .. import batches as batches_mod
        batches_mod.set_metrics_sink(self.metrics)
        # Ingest-robustness counters: retry attempts, dead letters,
        # breaker transitions (engine/retry.py) and journal records /
        # truncated-tail recoveries (engine/journal.py) all land in the
        # same /metrics registry.
        from ..engine import retry as engine_retry
        engine_retry.set_metrics_sink(self.metrics)
        # graftscope (bucketeer_tpu/obs): the process recorder —
        # request-scoped span trees, the always-on flight recorder
        # behind GET /debug/flight, Chrome-trace export behind
        # GET /debug/trace/{id}, request-id log stamping. Gated by
        # BUCKETEER_TRACE (default on); its own counters (flight
        # dumps/suppressions) land in this registry too.
        recorder = obs.maybe_install()
        if recorder is not None:
            recorder.set_metrics_sink(self.metrics)
        # Per-endpoint latency SLOs: the trace middleware reports every
        # request here; breaches bump slo.breach.* counters and freeze
        # the flight recorder with the request id attached.
        self.slo = obs.SloWatchdog.parse(
            engine.config.get_str(cfg.SLO)
            or os.environ.get("BUCKETEER_SLO"),
            sink=self.metrics,
            flight=recorder.flight if recorder is not None else None)
        if self.slo.active:
            self.metrics.add_reporter("slo", self.slo.report)
            # Keys are handler names (get_image, load_image, ...) —
            # log the parsed spec so a typo'd/operationId-style key
            # that will never match is visible at boot, not after an
            # incident with no breach ever recorded.
            LOG.info("SLO watchdog active: %s", self.slo.report())
        # Live breaker state (open/half_open/closed + consecutive
        # failures) rendered as a /metrics section beside the
        # transition counters.
        self.metrics.add_reporter("breakers",
                                  engine.bus.breakers.report)
        # Decode work is admitted through the same scheduler as encodes
        # (typed read-priority jobs): tile reads share the bounded
        # queue's 503 backpressure but outrank queued encodes, and the
        # reader's cache hits bypass admission entirely.
        self.reader = TpuReader(
            cache_mb=engine.config.get_int(cfg.DECODE_CACHE_MB, -1),
            metrics=self.metrics, scheduler=get_scheduler())
        self._background: set[asyncio.Task] = set()
        # Image-mount path prefix (reference: MainVerticle.java:92-102
        # installs it on the JobFactory at boot).
        self.prefix = pp.get_prefix(
            engine.config.get_str(cfg.FILESYSTEM_PREFIX),
            engine.config.get_str(cfg.FILESYSTEM_IMAGE_MOUNT) or "")

    # --- getStatus (reference: handlers/GetStatusHandler.java:30-46) ---
    async def get_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "ok",
            "features": self.engine.flags.report(),
        })

    # --- getConfig (reference: handlers/GetConfigHandler.java:33-77) ---
    async def get_config(self, request: web.Request) -> web.Response:
        config = self.engine.config
        return web.json_response({
            cfg.IIIF_URL: config.get_str(cfg.IIIF_URL),
            cfg.FILESYSTEM_IMAGE_MOUNT:
                config.get_str(cfg.FILESYSTEM_IMAGE_MOUNT),
            cfg.FILESYSTEM_CSV_MOUNT:
                config.get_str(cfg.FILESYSTEM_CSV_MOUNT),
            cfg.S3_BUCKET: config.get_str(cfg.S3_BUCKET),
            cfg.LAMBDA_S3_BUCKET: config.get_str(cfg.LAMBDA_S3_BUCKET),
            cfg.S3_REGION: config.get_str(cfg.S3_REGION),
            cfg.THUMBNAIL_SIZE: config.get_str(cfg.THUMBNAIL_SIZE),
            cfg.MAX_SOURCE_SIZE: config.get_int(cfg.MAX_SOURCE_SIZE),
            "converters": available_converters(),
        })

    # --- loadImage (reference: handlers/LoadImageHandler.java:35-96) ---
    async def load_image(self, request: web.Request) -> web.Response:
        image_id = urllib.parse.unquote(request.match_info["image_id"])
        file_path = urllib.parse.unquote(request.match_info["file_path"])
        callback_url = request.query.get(c.CALLBACK_URL)
        if not image_id or not file_path:
            return _error_page(400, "image-id and file-path are required")
        if not file_path.startswith("/"):
            file_path = "/" + file_path
        exists = await asyncio.to_thread(os.path.exists, file_path)
        if not exists:
            return _error_page(404, f"source not found: {file_path}")
        message = {c.IMAGE_ID: image_id, c.FILE_PATH: file_path}
        if callback_url:
            message[c.CALLBACK_URL] = callback_url
        # Trace context rides the message: the worker's consumer task
        # re-enters it, so the convert/upload spans and log lines
        # carry this request's id.
        request_id = obs.current_request_id()
        if request_id:
            message[c.REQUEST_ID] = request_id
        with self.metrics.time("single_image"):
            reply = await self.engine.bus.request_with_retry(
                IMAGE_WORKER, message)
        if not reply.is_success:
            if reply.code == 503:
                # Encode-scheduler backpressure (bounded admission
                # queue full, or the request's deadline expired): tell
                # the client when to come back instead of pretending
                # the service broke.
                retry_after = reply.body.get(c.RETRY_AFTER, 1)
                return _unavailable(
                    reply.message or "encode queue full", retry_after)
            return _error_page(500, reply.message or "conversion failed")
        # 201 + JSON echo (reference: LoadImageHandler.java:73-75)
        return web.json_response(
            {c.IMAGE_ID: image_id, c.FILE_PATH: file_path}, status=201)

    # --- getImage (new: the IIIF-facing read path; no reference analog,
    # the reference only writes derivatives) ---
    async def get_image(self, request: web.Request) -> web.Response:
        """Decode the stored JP2/JPX derivative for an image id.

        Query: ``region=x,y,w,h`` (or the IIIF aliases ``full`` /
        ``square``) decodes only that full-resolution window — Tier-1
        runs solely for the intersecting code-blocks; ``reduce`` drops
        the finest resolution levels (a IIIF zoom-out), ``layers``
        truncates at a quality layer, ``format`` is ``png`` (default)
        or ``raw`` (npy bytes for pipelines). Region decodes are
        admitted through the scheduler at read priority: past the
        bounded queue the answer is 503 + Retry-After.
        """
        image_id = urllib.parse.unquote(request.match_info["image_id"])
        try:
            reduce = int(request.query.get("reduce", "0"))
            layers = (int(request.query["layers"])
                      if "layers" in request.query else None)
        except ValueError:
            return _error_page(400, "reduce/layers must be integers")
        if reduce < 0 or (layers is not None and layers < 1):
            return _error_page(400, "reduce must be >= 0, layers >= 1")
        fmt = request.query.get("format", "png")
        if fmt not in ("png", "raw"):
            return _error_page(400, f"unknown format: {fmt}")
        path = derivative_path(image_id)
        if path is None:
            return _error_page(404, f"no derivative for: {image_id}")
        region_q = request.query.get("region")
        region = None
        if region_q and region_q != "full":
            if region_q == "square":
                # IIIF `square`: the centered largest square. dims()
                # hits the reader's file-identity cache after the
                # first probe, so repeats don't re-read the file.
                try:
                    width, height = await asyncio.to_thread(
                        self.reader.dims, path)
                except DecodeError as exc:
                    LOG.warning("decode failed for %s: %s",
                                image_id, exc)
                    self.metrics.count("decode.failures")
                    return _error_page(500, f"decode failed: {exc}")
                side = min(width, height)
                region = ((width - side) // 2,
                          (height - side) // 2, side, side)
            else:
                parts = region_q.split(",")
                if len(parts) != 4:
                    return _error_page(
                        400, "region must be x,y,w,h or full or square")
                try:
                    region = tuple(int(v) for v in parts)
                except ValueError:
                    return _error_page(
                        400, "region coordinates must be integers")
        self.metrics.count("decode.requests")
        if region is not None:
            self.metrics.count("decode.region_requests")
        if reduce or layers is not None:
            self.metrics.count("decode.partial_requests")
        try:
            with self.metrics.time("image_read"):
                img = await asyncio.to_thread(
                    self.reader.read, path, reduce, layers, region)
        except InvalidParam as exc:
            # The derivative is fine; the request asked for something
            # no stream could satisfy (e.g. reduce beyond the coded
            # decomposition levels, or a region outside the image).
            return _error_page(400, str(exc))
        except (QueueFull, DeadlineExceeded) as exc:
            return _unavailable(str(exc),
                                getattr(exc, "retry_after", 1))
        except DecodeError as exc:
            LOG.warning("decode failed for %s: %s", image_id, exc)
            self.metrics.count("decode.failures")
            return _error_page(500, f"decode failed: {exc}")
        bitdepth = 8
        if img.itemsize > 1 and fmt == "png" and img.ndim == 3:
            # PNG RGB48 is outside PIL's encoder; the downshift needs
            # the stream's true bit depth (9..16), not a fixed >> 8.
            bitdepth = (await asyncio.to_thread(
                self.reader.probe, path))["bitdepth"]
        return _image_response(img, fmt, bitdepth)

    # --- getCoefficients (new: compressed-domain delivery — the
    # "RGB no more" read path; serves the subband coefficient tensors
    # a training job consumes instead of pixels) ---
    async def get_coefficients(self, request: web.Request) -> web.Response:
        """Decode the stored derivative to per-subband coefficient
        tensors (Tier-1 + dequantization only; no inverse DWT / color
        transform). Query: ``region=x,y,w,h``, ``reduce``, ``layers``
        as on the pixel read. Response: an ``.npz`` stream with one
        ``r{res}_{name}`` array per subband plus an ``X-Coeff-Meta``
        JSON header (geometry, quantizer steps, region windows).
        Admitted at read priority: past the bounded queue the answer
        is 503 + Retry-After."""
        image_id = urllib.parse.unquote(request.match_info["image_id"])
        try:
            reduce = int(request.query.get("reduce", "0"))
            layers = (int(request.query["layers"])
                      if "layers" in request.query else None)
        except ValueError:
            return _error_page(400, "reduce/layers must be integers")
        if reduce < 0 or (layers is not None and layers < 1):
            return _error_page(400, "reduce must be >= 0, layers >= 1")
        path = derivative_path(image_id)
        if path is None:
            return _error_page(404, f"no derivative for: {image_id}")
        region_q = request.query.get("region")
        region = None
        if region_q and region_q != "full":
            parts = region_q.split(",")
            if len(parts) != 4:
                return _error_page(400, "region must be x,y,w,h or full")
            try:
                region = tuple(int(v) for v in parts)
            except ValueError:
                return _error_page(
                    400, "region coordinates must be integers")
        self.metrics.count("decode.requests")
        try:
            with self.metrics.time("coefficients_read"):
                cs = await asyncio.to_thread(
                    self.reader.read_coefficients, path, reduce,
                    layers, region)
        except InvalidParam as exc:
            return _error_page(400, str(exc))
        except (QueueFull, DeadlineExceeded) as exc:
            return _unavailable(str(exc),
                                getattr(exc, "retry_after", 1))
        except DecodeError as exc:
            LOG.warning("coefficient decode failed for %s: %s",
                        image_id, exc)
            self.metrics.count("decode.failures")
            return _error_page(500, f"decode failed: {exc}")
        # The d2h materialization + npz serialization are hundreds of
        # ms for a large image — off the event loop like the decode.
        return await asyncio.to_thread(_coefficients_response, cs)

    # --- putTensor / getTensor (new: the general bit-plane tensor
    # codec as a service — checkpoint/activation compression through
    # the device Tier-1 kernels) ---
    async def put_tensor(self, request: web.Request) -> web.Response:
        """Encode the request body (an ``.npy`` tensor) through the
        bit-plane codec and store the container beside the image
        derivatives. Query: ``planes=k`` keeps only the top k payload
        planes (encode-time floors); ``rate=b`` truncates the lossless
        encode to a byte budget. 201 + stats on success; 400 for bodies
        the codec cannot serve; 503 + Retry-After under admission
        backpressure (tensor jobs are batch-class — interactive reads
        outrank them in the shared scheduler queue)."""
        import io

        import numpy as np

        from .. import tensor as tensor_mod
        from ..converters.base import output_path
        from ..engine.scheduler import get_scheduler

        tensor_id = urllib.parse.unquote(request.match_info["tensor_id"])
        try:
            planes = (int(request.query["planes"])
                      if "planes" in request.query else None)
            rate = (int(request.query["rate"])
                    if "rate" in request.query else None)
        except ValueError:
            return _error_page(400, "planes/rate must be integers")
        body = await request.read()
        if not body:
            return _error_page(400, "missing .npy request body")
        try:
            arr = np.load(io.BytesIO(body), allow_pickle=False)
        except Exception:
            return _error_page(400, "request body is not a valid .npy")
        self.metrics.count("tensor.encode_requests")
        try:
            with self.metrics.time("tensor_encode"):
                blob = await asyncio.to_thread(
                    get_scheduler().submit_tensor,
                    tensor_mod.encode_tensor, arr, planes=planes,
                    rate=rate)
        except TypeError as exc:
            return _error_page(400, str(exc))
        except ValueError as exc:
            return _error_page(400, str(exc))
        except (QueueFull, DeadlineExceeded) as exc:
            return _unavailable(str(exc),
                                getattr(exc, "retry_after", 1))
        path = output_path(tensor_id, ".btt")
        # Unique temp name: concurrent PUTs of the same id must not
        # interleave writes before the atomic replace (the converter's
        # derivative writes follow the same rule).
        tmp = f"{path}.{os.getpid()}.{id(blob):x}.part"
        def _write():
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        await asyncio.to_thread(_write)
        stats = tensor_mod.tensor_stats(blob)
        stats["tensor-id"] = tensor_id
        return web.json_response(stats, status=201)

    async def get_tensor(self, request: web.Request) -> web.Response:
        """Decode a stored tensor container back to an ``.npy`` stream
        (``format=blob`` returns the raw progressive container;
        ``planes=k`` truncates on the fly at a plane boundary before
        decoding). 503 + Retry-After under admission backpressure."""
        import io

        import numpy as np

        from .. import tensor as tensor_mod
        from ..converters.base import output_path
        from ..engine.scheduler import get_scheduler

        tensor_id = urllib.parse.unquote(request.match_info["tensor_id"])
        fmt = request.query.get("format", "npy")
        if fmt not in ("npy", "blob"):
            return _error_page(400, f"unknown format: {fmt}")
        try:
            planes = (int(request.query["planes"])
                      if "planes" in request.query else None)
        except ValueError:
            return _error_page(400, "planes must be an integer")
        path = output_path(tensor_id, ".btt")
        exists = await asyncio.to_thread(os.path.exists, path)
        if not exists:
            return _error_page(404, f"no tensor for: {tensor_id}")
        def _read():
            with open(path, "rb") as fh:
                return fh.read()
        blob = await asyncio.to_thread(_read)
        self.metrics.count("tensor.decode_requests")
        try:
            if fmt == "blob":
                if planes is not None:
                    blob = await asyncio.to_thread(
                        tensor_mod.truncate_tensor, blob, planes=planes)
                return web.Response(
                    body=blob, content_type="application/octet-stream",
                    headers={"X-Tensor-Format": "btt1"})
            with self.metrics.time("tensor_decode"):
                arr = await asyncio.to_thread(
                    get_scheduler().submit_tensor,
                    tensor_mod.decode_tensor, blob, planes=planes)
        except ValueError as exc:
            return _error_page(400, str(exc))
        except (QueueFull, DeadlineExceeded) as exc:
            return _unavailable(str(exc),
                                getattr(exc, "retry_after", 1))
        except DecodeError as exc:
            LOG.warning("tensor decode failed for %s: %s",
                        tensor_id, exc)
            self.metrics.count("tensor.decode_failures")
            return _error_page(500, f"tensor decode failed: {exc}")
        def _serialize():
            buf = io.BytesIO()
            np.save(buf, arr)
            return buf.getvalue()
        body = await asyncio.to_thread(_serialize)
        return web.Response(
            body=body,
            content_type="application/octet-stream",
            headers={"X-Tensor-Shape": "x".join(map(str, arr.shape)),
                     "X-Tensor-Dtype": str(arr.dtype)})

    # --- batch data plane (graftfeed: bucketeer_tpu/batches) -----------
    async def post_batches(self, request: web.Request) -> web.Response:
        """Assemble a sharded coefficient batch from a JSON recipe.
        One admitted ``batchread`` request covers the whole batch
        (admission 503 + Retry-After, per-batch deadline, priority
        between interactive reads and bulk encodes); per-item decode
        failures land as typed entries in the returned manifest, not
        an all-or-nothing error. ``store=true`` writes a progressive
        ``BTB1`` container beside the derivatives and returns its
        handle; otherwise the batched bands stream back as one npz."""
        from .. import batches as batches_mod
        from ..converters.base import output_path
        from ..engine.scheduler import get_scheduler

        try:
            doc = await request.json()
        except Exception:
            return _error_page(400, "request body must be a JSON object")
        try:
            recipe = batches_mod.parse_recipe(doc)
        except InvalidParam as exc:
            return _error_page(400, str(exc))
        self.metrics.count("batchread.requests")
        try:
            with self.metrics.time("batch_assemble"):
                result = await asyncio.to_thread(
                    get_scheduler().submit_batchread,
                    batches_mod.assemble_batch, recipe,
                    deadline_s=recipe.deadline_s)
        except InvalidParam as exc:
            # Request-shaped problems found past parsing (unknown ids,
            # mixed geometry, reduce beyond the coded levels).
            return _error_page(400, str(exc))
        except (QueueFull, DeadlineExceeded) as exc:
            return _unavailable(str(exc),
                                getattr(exc, "retry_after", 1))
        except DecodeError as exc:
            self.metrics.count("batchread.failures")
            return _error_page(500, f"batch assembly failed: {exc}")
        if not recipe.store:
            return await asyncio.to_thread(_batch_response, result)
        batch_id = uuid.uuid4().hex
        blob = await asyncio.to_thread(
            batches_mod.encode_batch, result, planes=recipe.planes)
        path = output_path(batch_id, ".btb")
        tmp = f"{path}.{os.getpid()}.{id(blob):x}.part"
        def _write():
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        await asyncio.to_thread(_write)
        stats = await asyncio.to_thread(batches_mod.batch_stats, blob)
        stats["batch-id"] = batch_id
        return web.json_response(stats, status=201)

    async def get_batch(self, request: web.Request) -> web.Response:
        """Read a stored batch container back: ``planes=k`` serves the
        progressive low-plane-first cut (BTT1 truncation per band),
        ``format=blob`` returns the raw (possibly truncated) BTB1
        container, ``format=npz`` (default) decodes to the per-band
        host arrays. Decode work is admitted at batchread priority."""
        import io

        import numpy as np

        from .. import batches as batches_mod
        from ..converters.base import output_path
        from ..engine.scheduler import get_scheduler

        batch_id = urllib.parse.unquote(request.match_info["batch_id"])
        fmt = request.query.get("format", "npz")
        if fmt not in ("npz", "blob"):
            return _error_page(400, f"unknown format: {fmt}")
        try:
            planes = (int(request.query["planes"])
                      if "planes" in request.query else None)
        except ValueError:
            return _error_page(400, "planes must be an integer")
        if planes is not None and planes < 1:
            return _error_page(400, "planes must be >= 1")
        path = output_path(batch_id, ".btb")
        exists = await asyncio.to_thread(os.path.exists, path)
        if not exists:
            return _error_page(404, f"no stored batch: {batch_id}")
        def _read():
            with open(path, "rb") as fh:
                return fh.read()
        blob = await asyncio.to_thread(_read)
        try:
            if fmt == "blob":
                if planes is not None:
                    blob = await asyncio.to_thread(
                        batches_mod.truncate_batch, blob, planes)
                return web.Response(
                    body=blob,
                    content_type="application/octet-stream",
                    headers={"X-Batch-Format": "btb1"})
            with self.metrics.time("batch_decode"):
                header, bands = await asyncio.to_thread(
                    get_scheduler().submit_batchread,
                    batches_mod.decode_batch, blob, planes=planes)
        except InvalidParam as exc:
            return _error_page(400, str(exc))
        except (QueueFull, DeadlineExceeded) as exc:
            return _unavailable(str(exc),
                                getattr(exc, "retry_after", 1))
        except DecodeError as exc:
            LOG.warning("batch decode failed for %s: %s",
                        batch_id, exc)
            self.metrics.count("batchread.decode_failures")
            return _error_page(500, f"batch decode failed: {exc}")
        def _serialize():
            buf = io.BytesIO()
            np.savez(buf, **{f"r{res}_{name}": arr
                             for (res, name), arr in bands.items()})
            return buf.getvalue()
        body = await asyncio.to_thread(_serialize)
        meta = {k: header.get(k) for k in
                ("ids", "layout", "meta", "manifest")}
        return web.Response(
            body=body, content_type="application/octet-stream",
            headers={"X-Batch-Meta": json.dumps(meta)})

    # --- loadImagesFromCSV (reference: handlers/LoadCsvHandler.java:100-230) ---
    async def load_csv(self, request: web.Request) -> web.Response:
        reader = await request.multipart() if request.content_type.startswith(
            "multipart/") else None
        slack_handle = None
        csv_bytes = None
        csv_name = "job"
        subsequent = False
        if reader is None:
            return _error_page(400, "multipart form upload required")
        async for part in reader:
            if part.name == c.SLACK_HANDLE:
                slack_handle = (await part.text()).strip()
            elif part.name == c.CSV_FILE_UPLOAD:
                csv_name = os.path.splitext(
                    os.path.basename(part.filename or "job"))[0]
                csv_bytes = await part.read(decode=True)
            elif part.name == c.FAILURES:
                subsequent = (await part.text()).strip().lower() in (
                    "true", "on", "yes", "1")
        # Validation (reference: LoadCsvHandler.java:105-124)
        if not slack_handle:
            return _error_page(400, "missing required slack-handle")
        if not csv_bytes:
            return _error_page(400, "missing required CSV upload")

        # Graceful degradation (same ladder as QueueFull): a new job is
        # not accepted while the S3 target's circuit is open — the
        # batch would only pile work onto a dead target.
        breaker = self.engine.bus.breakers.lookup(S3_UPLOADER)
        if breaker is not None and breaker.is_open:
            return _unavailable(
                "upload target unavailable (circuit open)",
                breaker.time_until_ready())

        job_name = csv_name
        # Duplicate running job -> 429 (reference: :190-202)
        try:
            async with self.engine.store.locked():
                if job_name in self.engine.store:
                    return _error_page(
                        429, f"batch job '{job_name}' is already running")
                try:
                    job = job_factory.create_job(
                        job_name,
                        csv_bytes.decode("utf-8", errors="replace"),
                        subsequent_run=subsequent, prefix=self.prefix)
                    warnings: list[str] = []
                except job_factory.JobCreationWarnings as warn:
                    job = warn.job
                    warnings = warn.errors.messages
                except m.ProcessingException as exc:
                    return _error_page(400, "; ".join(exc.messages))
                job.slack_handle = slack_handle
                # Off-loop: durable acceptance fsyncs the WAL record.
                await asyncio.to_thread(self.engine.store.put, job)
                # A fresh run of a job name must not inherit the
                # dead letters of a finished same-named run.
                self.engine.bus.dead_letters.clear_job(job_name)
        except JournalUnavailable as exc:
            # Durable acceptance is the contract: a job the journal
            # can't record is not accepted (it would silently lose its
            # crash-safety), so the client backs off and retries.
            return _unavailable(str(exc), exc.retry_after)
        except LockTimeout as exc:
            return _unavailable(str(exc), 1.0)

        # Respond first, then start the work (reference: :226-230 sends
        # the success page before dispatching items).
        task = asyncio.create_task(self._start_job(job))
        self._background.add(task)
        task.add_done_callback(self._background.discard)
        return web.Response(
            content_type="text/html",
            text=_html("success.html", job=job_name,
                       count=len(job.items),
                       warnings="<br>".join(warnings)))

    async def _start_job(self, job: m.Job) -> None:
        try:
            with self.metrics.time("batch_dispatch"):
                await start_job(job, self.engine.bus, self.engine.config,
                                self.engine.flags,
                                store=self.engine.store)
        except Exception:
            # The client already got its 200 (the success page is sent
            # before dispatch), so this log line is the only trace of a
            # dispatch failure — carry the full request context.
            LOG.exception(
                "start_job failed for job %r (%d items, %d remaining, "
                "slack handle %r)", job.name, len(job.items),
                job.remaining(), job.slack_handle)

    # --- updateBatchJob (reference: handlers/BatchJobStatusHandler.java:56-197) ---
    async def update_batch_job(self, request: web.Request) -> web.Response:
        job_name = urllib.parse.unquote(request.match_info["job_name"])
        image_id = urllib.parse.unquote(request.match_info["image_id"])
        success = request.match_info["success"] == "true"
        try:
            await update_item_status(
                self.engine.store, self.engine.bus, job_name, image_id,
                success, self.engine.config.get_str(cfg.IIIF_URL))
        except m.JobNotFoundError:
            return _error_page(404, f"job not found: {job_name}")
        except KeyError:
            return _error_page(404, f"item not found: {image_id}")
        except JournalUnavailable as exc:
            return _unavailable(str(exc), exc.retry_after)
        except LockTimeout as exc:
            return _unavailable(str(exc), 1.0)
        return web.Response(status=204)

    # --- getJobs (reference: handlers/GetJobsHandler.java:31-60) ---
    async def get_jobs(self, request: web.Request) -> web.Response:
        names = self.engine.store.names()
        return web.json_response({c.COUNT: len(names), c.JOBS: names})

    # --- getJobStatuses (reference: handlers/GetJobStatusesHandler.java:32-100) ---
    async def get_job_statuses(self, request: web.Request) -> web.Response:
        job_name = urllib.parse.unquote(request.match_info["job_name"])
        job = self.engine.store.maybe_get(job_name)
        if job is None:
            return _error_page(404, f"job not found: {job_name}")
        return web.json_response({
            c.COUNT: len(job.items),
            c.SLACK_HANDLE: job.slack_handle,
            c.REMAINING: job.remaining(),
            c.JOBS: [{
                c.IMAGE_ID: item.id,
                c.STATUS: str(item.workflow_state),
                c.FILE_PATH: item.file_path,
            } for item in job.items],
            # Items that exhausted their retry budget (engine/retry.py)
            # instead of spinning forever — the operator-facing record.
            c.DEAD_LETTERS:
                self.engine.bus.dead_letters.for_job(job_name),
        })

    # --- deleteJob (reference: handlers/DeleteJobHandler.java:32-120) ---
    async def delete_job(self, request: web.Request) -> web.Response:
        job_name = urllib.parse.unquote(request.match_info["job_name"])
        job = self.engine.store.maybe_get(job_name)
        if job is None:
            return _error_page(404, f"job not found: {job_name}")
        before = job.remaining()
        # Liveness probe: only delete if no progress during the wait
        # (reference: DeleteJobHandler.java:90-120, 5 s).
        await asyncio.sleep(float(request.app.get(
            "job-delete-timeout", c.JOB_DELETE_TIMEOUT)))
        job = self.engine.store.maybe_get(job_name)
        if job is None:
            return _error_page(404, f"job not found: {job_name}")
        if job.remaining() != before:
            return _error_page(
                400, f"job '{job_name}' is still processing")
        try:
            async with self.engine.store.locked():
                await asyncio.to_thread(self.engine.store.remove,
                                        job_name)
        except KeyError:
            # Finalized (or deleted) between the probe and the remove.
            return _error_page(404, f"job not found: {job_name}")
        except JournalUnavailable as exc:
            return _unavailable(str(exc), exc.retry_after)
        except LockTimeout:
            # Match updateBatchJob's contention behavior: 503, not 500.
            return _error_page(503, "job lock timed out; try again")
        return web.Response(status=204)

    # --- metrics (new: SURVEY.md §5 says the reference has none) ---
    async def get_metrics(self, request: web.Request) -> web.Response:
        fmt = request.query.get("format", "json")
        if fmt == "prometheus":
            return web.Response(
                text=self.metrics.prometheus(),
                content_type="text/plain", charset="utf-8")
        if fmt != "json":
            return _error_page(400, f"unknown format: {fmt}")
        return web.json_response(self.metrics.report())

    # --- graftscope debug surface (new: bucketeer_tpu/obs) ---
    async def get_flight(self, request: web.Request) -> web.Response:
        """The always-on flight recorder: recent spans across all
        threads plus stored dumps (auto-frozen on 5xx / SLO breach).
        ``?dump=<seq>`` fetches one stored dump in full; ``?freeze=1``
        forces a dump right now (operator poke)."""
        rec = obs.get_recorder()
        if rec is None:
            return web.json_response({"enabled": False})
        if "dump" in request.query:
            try:
                seq = int(request.query["dump"])
            except ValueError:
                return _error_page(400, "dump must be an integer seq")
            entry = rec.flight.get(seq)
            if entry is None:
                return _error_page(404, f"no flight dump with seq {seq}")
            return web.json_response(entry)
        if cfg.truthy(request.query.get("freeze")):
            rec.flight.dump("operator-freeze", force=True)
        return web.json_response(rec.flight.report())

    async def get_trace(self, request: web.Request) -> web.Response:
        """Per-request Chrome-trace/Perfetto JSON: every span of one
        request id, plus linked merged-launch spans. Loads directly in
        chrome://tracing / ui.perfetto.dev."""
        rec = obs.get_recorder()
        if rec is None:
            return _error_page(503, "tracing disabled (BUCKETEER_TRACE)")
        request_id = urllib.parse.unquote(
            request.match_info["request_id"])
        doc = obs.export.chrome_trace(rec, request_id)
        if not doc["traceEvents"]:
            return _error_page(
                404, f"no buffered spans for request {request_id}")
        return web.json_response(doc)


def _coefficients_response(cs) -> web.Response:
    """Serialize a CoefficientSet: one npz stream (band key
    ``r{res}_{name}``) + an X-Coeff-Meta JSON header with the geometry
    a consumer needs to interpret the planes."""
    import io

    import numpy as np

    host = cs.to_host()
    buf = io.BytesIO()
    np.savez(buf, **{f"r{res}_{name}": arr
                     for (res, name), arr in host.items()})
    meta = {
        "width": cs.width, "height": cs.height,
        "components": cs.n_comps, "bitdepth": cs.bitdepth,
        "levels": cs.levels, "reduce": cs.reduce,
        "reversible": cs.reversible, "mct": cs.used_mct,
        "deltas": {f"r{res}_{name}": delta
                   for (res, name), delta in cs.deltas.items()},
    }
    if cs.region is not None:
        meta["region"] = list(cs.region)
        meta["windows"] = {f"r{res}_{name}": list(win)
                           for (res, name), win in cs.windows.items()}
    return web.Response(
        body=buf.getvalue(), content_type="application/octet-stream",
        headers={"X-Coeff-Meta": json.dumps(meta)})


def _batch_response(result) -> web.Response:
    """Serialize a BatchResult: one npz stream of the (N, C, Hb, Wb)
    batched bands (key ``r{res}_{name}``) + an X-Batch-Meta JSON
    header carrying the geometry, the achieved layout, and the
    per-item manifest (typed failures included)."""
    import io

    import numpy as np

    host = result.to_host()
    buf = io.BytesIO()
    np.savez(buf, **{f"r{res}_{name}": arr
                     for (res, name), arr in host.items()})
    meta = {
        "ids": list(result.ids),
        "layout": result.layout,
        "meta": result.meta,
        "manifest": result.manifest,
        "deltas": {f"r{res}_{name}": delta
                   for (res, name), delta in result.deltas.items()},
    }
    return web.Response(
        body=buf.getvalue(), content_type="application/octet-stream",
        headers={"X-Batch-Meta": json.dumps(meta)})


def _image_response(img, fmt: str, bitdepth: int = 8) -> web.Response:
    """Serialize a decoded array: PNG for viewers (deep RGB is
    downshifted to 8 bits using the stream's true bit depth — PNG RGB48
    is outside PIL's encoder), npy bytes for pipelines (exact dtype,
    shape in headers)."""
    import io

    import numpy as np

    if fmt == "raw":
        buf = io.BytesIO()
        np.save(buf, img)
        return web.Response(
            body=buf.getvalue(),
            content_type="application/octet-stream",
            headers={"X-Image-Shape": "x".join(map(str, img.shape)),
                     "X-Image-Dtype": str(img.dtype)})
    from PIL import Image

    if img.dtype == np.uint16 and img.ndim == 3:
        img = (img >> max(0, bitdepth - 8)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    return web.Response(body=buf.getvalue(), content_type="image/png")


@web.middleware
async def trace_middleware(request: web.Request, handler):
    """graftscope's HTTP root: every request gets a trace context
    (inbound ``X-Request-Id`` honored, else generated), a root span
    named after the handler, an ``http.<endpoint>`` latency sample
    (the per-endpoint p50/p95/p99 behind /metrics), an SLO check, and
    — for 5xx outcomes — an automatic flight-recorder dump. Outermost
    middleware, so the error middleware's 500 mapping is visible
    here as a status, not an exception."""
    api = request.app.get("api")
    request_id = request.headers.get("X-Request-Id") or uuid.uuid4().hex
    endpoint = getattr(handler, "__name__", "handler")
    t0 = time.perf_counter()
    status = 500
    with obs.request_context(request_id):
        with obs.span(f"http.{endpoint}", method=request.method,
                      path=request.path):
            try:
                response = await handler(request)
                status = response.status
                response.headers.setdefault("X-Request-Id", request_id)
                return response
            except web.HTTPException as exc:
                # Raise-style responses (redirects, the 404->405
                # rewrite) are outcomes, not errors.
                status = exc.status
                exc.headers.setdefault("X-Request-Id", request_id)
                raise
            finally:
                if api is not None:
                    dt = time.perf_counter() - t0
                    api.metrics.record(f"http.{endpoint}", dt)
                    breached = api.slo.observe(endpoint, dt,
                                               request_id=request_id)
                    if status >= 500 and not breached:
                        rec = obs.get_recorder()
                        if rec is not None:
                            rec.flight.dump(f"error:{endpoint}",
                                            request_id=request_id)


@web.middleware
async def error_middleware(request: web.Request, handler):
    try:
        return await handler(request)
    except web.HTTPNotFound:
        # 404 -> 405 rewrite for wrong-method hits on the status-update
        # URL (reference: MatchingOpNotFoundHandler.java:31-47).
        if (STATUS_UPDATE_RE.match(request.path)
                and request.method != "PATCH"):
            return _error_page(405, "use PATCH for batch status updates")
        return _error_page(404, f"not found: {request.path}")
    except web.HTTPException:
        raise
    except Exception as exc:
        LOG.exception("unhandled error on %s", request.path)
        return _error_page(500, f"internal error: {exc}")


def build_app(engine: Engine,
              job_delete_timeout: float | None = None) -> web.Application:
    """Assemble the aiohttp application (reference:
    MainVerticle.java:110-163)."""
    api = Api(engine)
    app = web.Application(middlewares=[trace_middleware,
                                       error_middleware],
                          client_max_size=512 * 1024 * 1024)
    app["api"] = api
    app["engine"] = engine
    if job_delete_timeout is not None:
        app["job-delete-timeout"] = job_delete_timeout

    app.router.add_get("/status", api.get_status)
    app.router.add_get("/config", api.get_config)
    app.router.add_get("/images/{image_id}", api.get_image)
    # Registered before the loadImage catch-all so the literal
    # "coefficients" segment routes here (a source file named exactly
    # "coefficients" would have to be loaded by absolute path).
    app.router.add_get("/images/{image_id}/coefficients",
                       api.get_coefficients)
    app.router.add_get("/images/{image_id}/{file_path:.+}", api.load_image)
    app.router.add_post("/tensors/{tensor_id}", api.put_tensor)
    app.router.add_get("/tensors/{tensor_id}", api.get_tensor)
    app.router.add_post("/batches", api.post_batches)
    app.router.add_get("/batches/{batch_id}", api.get_batch)
    app.router.add_post("/batch/input/csv", api.load_csv)
    app.router.add_patch(
        "/batch/jobs/{job_name}/{image_id:.+}/{success:(?:true|false)}",
        api.update_batch_job)
    app.router.add_get("/batch/jobs", api.get_jobs)
    app.router.add_get("/batch/jobs/{job_name}", api.get_job_statuses)
    app.router.add_delete("/batch/jobs/{job_name}", api.delete_job)
    app.router.add_get("/metrics", api.get_metrics)
    app.router.add_get("/debug/flight", api.get_flight)
    app.router.add_get("/debug/trace/{request_id}", api.get_trace)

    # Static web UI (reference: src/main/webroot; MainVerticle.java:143-158)
    async def upload_redirect(request):
        raise web.HTTPFound("/upload/csv/index.html")

    async def index(request):
        return web.Response(content_type="text/html",
                            text=_html("index.html"))

    async def upload_form(request):
        return web.Response(content_type="text/html",
                            text=_html("upload/csv/index.html"))

    async def docs(request):
        return web.Response(content_type="text/html",
                            text=_html("docs/index.html"))

    async def openapi_spec(request):
        spec = os.path.join(os.path.dirname(__file__), "openapi.yaml")
        with open(spec, "r", encoding="utf-8") as fh:
            return web.Response(content_type="application/yaml",
                                text=fh.read())

    app.router.add_get("/", index)
    app.router.add_get("/index.html", index)
    app.router.add_get("/upload", upload_redirect)
    app.router.add_get("/upload/", upload_redirect)
    app.router.add_get("/upload/csv/", upload_form)
    app.router.add_get("/upload/csv/index.html", upload_form)
    app.router.add_get("/docs", docs)
    app.router.add_get("/docs/", docs)
    app.router.add_get("/docs/openapi.yaml", openapi_spec)

    async def on_startup(app):
        await engine.start()

    async def on_cleanup(app):
        await engine.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app
