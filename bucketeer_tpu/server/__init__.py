"""HTTP API layer + web UI (reference: SURVEY.md §1 L3/L4 — the
OpenAPI-contract router, handlers, and static webroot)."""
from .app import build_app
from .metrics import Metrics

__all__ = ["build_app", "Metrics"]
