"""Closed-loop kill-and-restart ingest driver (the chaos smoke).

``python -m bucketeer_tpu.engine.chaos --workdir D --items 4 --seed 7
--kill-after 1`` runs a real batch ingest (CSV -> dispatch -> stub
convert -> fake S3 -> status -> finalize) over a journal-backed
:class:`~.store.JobStore` and, via a graftgremlin plan, hard-kills the
process (``os._exit(137)``) in the at-least-once window — after the
``kill-after``-th item resolved, while later items sit
dispatched-but-unresolved. A second invocation with ``--resume`` on the
same workdir replays the journal, re-queues the surviving items,
finalizes the job, and prints a JSON summary with the output CSV's
sha256 — byte-identical across two replays of the same seed, which is
exactly what the CI ``chaos`` job asserts.

Everything that could wiggle is pinned: deterministic source bytes and
derivative bytes (sha256 of the item id), one batch-converter instance,
seeded retry jitter, and a fault trace (``--trace``) recording every
injection decision for the artifact upload.
"""
from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import sys

from .. import config as cfg
from .. import constants as c
from .. import features, job_factory
from ..utils import path_prefix as pp
from . import faults
from .batch import BatchConverterWorker, start_job
from .bus import MessageBus
from .retry import RetryPolicy
from .s3 import FakeS3Client, S3UploadWorker, S3UploaderConfig
from .slack import RecordingSlackClient, SlackWorker
from .store import Counters, JobStore, UploadsMap
from .workers import (FINALIZE_JOB, FinalizeJobWorker, ItemFailureWorker)

JOB_NAME = "chaos-job"
KILL_EXIT = 137


class _StubConverter:
    """Deterministic instant 'conversion': derivative bytes are a pure
    function of the item id, so replays are byte-identical."""

    def __init__(self, outdir: str) -> None:
        self.outdir = outdir

    def convert(self, image_id: str, source_path: str,
                conversion=None) -> str:
        out = os.path.join(self.outdir,
                           image_id.replace("/", "_") + ".jpx")
        with open(out, "wb") as fh:
            fh.write(b"JPX" + hashlib.sha256(
                image_id.encode()).hexdigest().encode())
        return out


def _build_world(workdir: str, items: int):
    src = os.path.join(workdir, "src")
    out = os.path.join(workdir, "out")
    deriv = os.path.join(workdir, "deriv")
    for d in (src, out, deriv):
        os.makedirs(d, exist_ok=True)
    names = []
    for i in range(items):
        name = f"img{i}.tif"
        with open(os.path.join(src, name), "wb") as fh:
            fh.write(b"II*\x00" + hashlib.sha256(
                name.encode()).digest())
        names.append(name)
    csv_text = "Item ARK,File Name\n" + "\n".join(
        f"ark:/chaos/{i},{n}" for i, n in enumerate(names)) + "\n"
    config = cfg.Config.load(overrides={
        cfg.FILESYSTEM_CSV_MOUNT: out,
        cfg.IIIF_URL: "http://iiif.chaos/iiif",
        cfg.SLACK_CHANNEL_ID: "chaos",
        cfg.S3_REQUEUE_DELAY: 0.02,
    })
    flags = features.FeatureFlagChecker(
        static={features.FS_WRITE_CSV: True})
    return src, out, deriv, csv_text, config, flags


async def _run(args) -> dict:
    workdir = args.workdir
    journal_dir = os.path.join(workdir, "journal")
    src, out, deriv, csv_text, config, flags = _build_world(
        workdir, args.items)

    store = JobStore(journal_dir=journal_dir)
    recovery: dict = dict(store.recovery)
    bus = MessageBus(retry_delay=0.02,
                     retry_policy=RetryPolicy(max_attempts=8,
                                              base_delay=0.02,
                                              max_delay=0.2),
                     seed=args.seed)
    counters, uploads = Counters(), UploadsMap()
    s3 = FakeS3Client(os.path.join(workdir, "s3"))
    S3UploadWorker(s3, S3UploaderConfig(bucket="chaos", max_retries=4),
                   counters, uploads).register(bus)
    conv = _StubConverter(deriv)
    # One converter instance: the resolve order (and so the kill point)
    # is deterministic.
    BatchConverterWorker(conv, store, bus, config,
                         counters=counters).register(bus, instances=1)
    ItemFailureWorker(store, bus).register(bus)
    FinalizeJobWorker(store, bus, config, flags).register(bus)
    SlackWorker(RecordingSlackClient()).register(bus)

    pre = {"jobs": store.names()}
    if args.resume:
        # Journal recovery already repopulated the store; account for
        # what survived the kill *before* re-driving it.
        job = store.maybe_get(JOB_NAME)
        if job is None:
            raise SystemExit(f"--resume but no recovered job in "
                             f"{journal_dir}")
        pre["resolved_at_recovery"] = \
            len(job.items) - job.remaining()
        pre["dispatched_unresolved_at_recovery"] = \
            len(store.dispatched(JOB_NAME))
        if job.remaining() == 0:
            await bus.send(FINALIZE_JOB, {c.JOB_NAME: JOB_NAME})
        else:
            await start_job(job, bus, config, flags, store=store)
    else:
        job = job_factory.create_job(
            JOB_NAME, csv_text, prefix=pp.GenericFilePathPrefix(src))
        job.slack_handle = "gremlin"
        async with store.locked():
            store.put(job)
        await start_job(job, bus, config, flags, store=store)

    for _ in range(int(args.timeout / 0.02)):
        if JOB_NAME not in store:
            break
        await asyncio.sleep(0.02)
    else:
        raise SystemExit(
            f"job did not finalize within {args.timeout}s "
            f"(remaining={store.get(JOB_NAME).remaining()})")
    await bus.close()
    store.close()

    csv_path = os.path.join(out, f"{JOB_NAME}.csv")
    with open(csv_path, "rb") as fh:
        csv_bytes = fh.read()
    states = [row.rsplit(",", 2)[-2] for row in
              csv_bytes.decode().strip().splitlines()[1:]]
    return {
        "phase": "resume" if args.resume else "fresh",
        "recovery": recovery,
        **pre,
        "items": args.items,
        "states": {s: states.count(s) for s in sorted(set(states))},
        "uploads": len(s3.metadata),
        "dead_letters": len(bus.dead_letters),
        "csv_path": csv_path,
        "csv_sha256": hashlib.sha256(csv_bytes).hexdigest(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="kill-and-restart ingest chaos smoke")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--items", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill-after", type=int, default=None,
                    help="hard-kill (exit 137) at the status write of "
                         "item N+1 — N items durably resolved, the "
                         "rest dispatched-unresolved")
    ap.add_argument("--resume", action="store_true",
                    help="recover the journal in --workdir and finish "
                         "the job")
    ap.add_argument("--scenario", default=None,
                    help="also install a named seeded fault scenario "
                         f"({', '.join(sorted(faults.SCENARIOS))})")
    ap.add_argument("--trace", default=None,
                    help="write the fault-decision trace JSON here")
    ap.add_argument("--timeout", type=float, default=60.0)
    args = ap.parse_args(argv)

    plan = None
    if args.kill_after is not None:
        plan = faults.FaultPlan(args.seed).at(
            "batch.status", after=args.kill_after, hard_exit=KILL_EXIT)
    elif args.scenario:
        plan = faults.make_plan(args.scenario, args.seed)
    if plan is not None:
        plan.trace_path = args.trace
        faults.install(plan)
    try:
        report = asyncio.run(_run(args))
    finally:
        if plan is not None:
            plan.flush_trace()
            faults.install(None)
    json.dump(report, sys.stdout, indent=1, sort_keys=True)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
