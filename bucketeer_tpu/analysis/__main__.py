"""graftlint CLI: ``python -m bucketeer_tpu.analysis [--strict] [paths]``.

Exit codes: 0 clean (in non-strict mode, warnings alone stay clean),
1 findings, 2 bad invocation.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .findings import ERROR
from .lint import load_baseline, run_lint, write_baseline

DEFAULT_BASELINE = ".graftlint-baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bucketeer_tpu.analysis",
        description="JAX/TPU-aware lint for the bucketeer codebase")
    parser.add_argument("paths", nargs="*",
                        help="package directories to lint (default: the "
                             "installed bucketeer_tpu package)")
    parser.add_argument("--strict", action="store_true",
                        help="warnings also fail the run")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE} "
                             "next to the linted package, if present)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record current findings as the baseline "
                             "and exit 0")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    args = parser.parse_args(argv)

    if args.paths:
        roots = [Path(p) for p in args.paths]
    else:
        roots = [Path(__file__).resolve().parent.parent]
    for root in roots:
        if not root.is_dir():
            print(f"not a directory: {root}", file=sys.stderr)
            return 2

    # One baseline file for the whole invocation (explicit --baseline,
    # else next to the first root) so a --write-baseline round trip
    # covers every linted root.
    baseline_path = (Path(args.baseline) if args.baseline
                     else roots[0].parent / DEFAULT_BASELINE)
    baseline = (set() if args.write_baseline
                else load_baseline(baseline_path)
                if baseline_path.exists() else set())
    findings = []
    for root in roots:
        findings += run_lint(root, baseline=baseline)

    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    if args.as_json:
        print(json.dumps([{
            "rule": f.rule, "path": f.path, "line": f.line,
            "severity": f.severity, "message": f.message,
            "fingerprint": f.fingerprint(),
        } for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())

    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = len(findings) - errors
    if findings and not args.as_json:
        print(f"graftlint: {errors} error(s), {warnings} warning(s)")
    if errors or (args.strict and warnings):
        return 1
    if not findings and not args.as_json:
        print("graftlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
