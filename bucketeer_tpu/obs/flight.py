"""Always-on flight recorder: bounded dump history over the span rings.

The rings (:mod:`.trace`) already hold the recent past at all times;
a *dump* freezes that picture with a reason attached — an unhandled
5xx, an SLO breach (:mod:`.slo`), or an operator poke at
``GET /debug/flight``. Dumps are rate-limited (``min_interval_s``) so
an error storm yields one picture per window instead of 10k copies of
the same rings, and the suppression count says how many triggers the
window absorbed.
"""
from __future__ import annotations

import itertools
from collections import deque

from ..analysis.graftrace import seam


class FlightRecorder:
    def __init__(self, recorder, max_dumps: int = 8,
                 min_interval_s: float = 1.0):
        self.recorder = recorder
        self.max_dumps = max_dumps
        self.min_interval_s = min_interval_s
        self._lock = seam.make_lock("obs.FlightRecorder._lock")
        self._dumps: deque = deque(maxlen=max_dumps)
        self._seq = itertools.count(1)
        self._last = None
        self.suppressed = 0

    def dump(self, reason: str, request_id=None, force: bool = False):
        """Freeze the current rings under ``reason``. Returns the dump
        entry, or None when the rate limit absorbed the trigger."""
        now = seam.monotonic()
        with self._lock:
            seam.read(self, "_last")
            if (not force and self._last is not None
                    and now - self._last < self.min_interval_s):
                seam.write(self, "suppressed")
                self.suppressed += 1
                suppressed = True
            else:
                seam.write(self, "_last")
                self._last = now
                suppressed = False
                seq = next(self._seq)
        if suppressed:
            self.recorder._count("obs.flight_dumps_suppressed")
            return None
        # Snapshot outside our lock: it takes the recorder's and each
        # ring's lock, and nothing may nest under _lock (lock-order
        # hygiene — rules_lockorder watches the static shape).
        spans = self.recorder.snapshot()
        entry = {
            "seq": seq,
            "at": now,
            "reason": reason,
            "request_id": request_id,
            "n_spans": len(spans),
            "spans": spans,
        }
        with self._lock:
            seam.write(self, "_dumps")
            self._dumps.append(entry)
        self.recorder._count("obs.flight_dumps")
        return entry

    def get(self, seq: int):
        with self._lock:
            seam.read(self, "_dumps")
            for entry in self._dumps:
                if entry["seq"] == seq:
                    return entry
        return None

    def report(self, live_limit: int = 512) -> dict:
        """The ``GET /debug/flight`` body: recent live spans plus dump
        summaries (full dumps are fetched by ``?dump=<seq>``)."""
        with self._lock:
            seam.read(self, "_dumps")
            dumps = [{k: e[k] for k in
                      ("seq", "at", "reason", "request_id", "n_spans")}
                     for e in self._dumps]
            seam.read(self, "suppressed")
            suppressed = self.suppressed
        return {
            "enabled": True,
            "recorder": self.recorder.stats(),
            "live": self.recorder.snapshot(limit=live_limit),
            "dumps": dumps,
            "suppressed": suppressed,
            "min_interval_s": self.min_interval_s,
        }
