"""@contract runtime shape/dtype checks (analysis/contracts.py)."""
import numpy as np
import pytest

from bucketeer_tpu.analysis.contracts import (ContractViolation, contract,
                                              contracts_enabled)


def test_enabled_under_pytest():
    # pytest is in sys.modules here, so contracts default to on.
    assert contracts_enabled()


def test_shape_and_symbol_consistency():
    @contract(shapes={"a": ("n", "m"), "b": ("m",)})
    def f(a, b):
        return a @ b

    f(np.zeros((3, 4)), np.zeros(4))
    with pytest.raises(ContractViolation, match="'b'"):
        f(np.zeros((3, 4)), np.zeros(5))      # m mismatch across args


def test_rank_alternatives_and_exact_dims():
    @contract(shapes={"x": [("B", "h", "w"), ("B", "h", "w", 3)]})
    def f(x):
        return x

    f(np.zeros((2, 8, 8)))
    f(np.zeros((2, 8, 8, 3)))
    with pytest.raises(ContractViolation):
        f(np.zeros((2, 8, 8, 4)))             # C must be exactly 3
    with pytest.raises(ContractViolation):
        f(np.zeros(8))                        # no rank-1 alternative


def test_wildcard_and_non_array():
    @contract(shapes={"x": (None, 512)})
    def f(x):
        return x

    f(np.zeros((7, 512), dtype=np.uint8))
    with pytest.raises(ContractViolation, match="array-like"):
        f([1, 2, 3])


def test_dtype_kinds_and_exact():
    @contract(dtypes={"x": "integer", "y": ("float32", "float64"),
                      "z": "uint8"})
    def f(x, y, z):
        return x, y, z

    f(np.zeros(3, np.int64), np.zeros(3, np.float32),
      np.zeros(3, np.uint8))
    with pytest.raises(ContractViolation, match="'x'"):
        f(np.zeros(3, np.float32), np.zeros(3, np.float32),
          np.zeros(3, np.uint8))
    with pytest.raises(ContractViolation, match="'z'"):
        f(np.zeros(3, np.int64), np.zeros(3, np.float64),
          np.zeros(3, np.int8))


def test_checks_jax_arrays_too():
    import jax.numpy as jnp

    @contract(shapes={"x": ("n",)}, dtypes={"x": "floating"})
    def f(x):
        return x

    f(jnp.zeros(4, jnp.float32))
    with pytest.raises(ContractViolation):
        f(jnp.zeros((4, 4), jnp.float32))


def test_env_var_disables(monkeypatch):
    monkeypatch.setenv("BUCKETEER_CONTRACTS", "0")

    def g(x):
        return x

    decorated = contract(shapes={"x": ("n",)})(g)
    assert decorated is g          # no-op at decoration time
    monkeypatch.setenv("BUCKETEER_CONTRACTS", "1")
    decorated = contract(shapes={"x": ("n",)})(g)
    assert decorated is not g


def test_codec_entry_points_are_contracted():
    from bucketeer_tpu.codec import encoder, frontend, pipeline, t1_batch
    from bucketeer_tpu.parallel import batch, sharded_dwt

    for fn in (pipeline.run_tiles, frontend.run_frontend,
               frontend.fetch_payload, encoder.encode_array,
               encoder.encode_jp2, t1_batch.encode_packed,
               batch.run_tiles_sharded,
               sharded_dwt.sharded_dwt2d_forward):
        assert hasattr(fn, "__contract__"), fn

    with pytest.raises(ContractViolation):
        pipeline.run_tiles(None, np.zeros(16))        # rank 1: rejected
    with pytest.raises(ContractViolation):
        encoder.encode_array(np.zeros((4, 4), dtype=object))
