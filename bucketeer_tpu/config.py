"""Configuration keys and layered config loading.

Key names are kept identical to the reference's property names
(reference: src/main/java/edu/ucla/library/bucketeer/Config.java:10-77) so
deployment configs carry over. Loading replaces the reference's three-layer
scheme (Vert.x ConfigRetriever properties file + env->python2 template +
moirai HOCON flags; reference: verticles/MainVerticle.java:84,
docker-entrypoint.sh:12-36) with a plain properties-file + environment
overlay — no template renderer needed.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any

# --- Config key names (reference: Config.java:10-77) ---
HTTP_PORT = "http.port"
OPENAPI_SPEC_PATH = "openapi.spec.path"
S3_ACCESS_KEY = "bucketeer.s3.access_key"
S3_SECRET_KEY = "bucketeer.s3.secret_key"
S3_REGION = "bucketeer.s3.region"
S3_BUCKET = "bucketeer.s3.bucket"
S3_ENDPOINT = "bucketeer.s3.endpoint"
LAMBDA_S3_BUCKET = "lambda.s3.bucket"
IIIF_URL = "bucketeer.iiif.url"
LARGE_IMAGE_URL = "bucketeer.large.image.url"
BATCH_CALLBACK_URL = "batch.callback.url"
FESTER_URL = "bucketeer.fester.url"
THUMBNAIL_SIZE = "bucketeer.thumbnail.size"
MAX_SOURCE_SIZE = "bucketeer.max.source.file.size"
S3_MAX_REQUESTS = "s3.max.requests"
S3_MAX_RETRIES = "s3.max.retries"
S3_REQUEUE_DELAY = "s3.requeue.delay"
S3_UPLOADER_INSTANCES = "s3.uploader.instances"
S3_UPLOADER_THREADS = "s3.uploader.threads"
FILESYSTEM_IMAGE_MOUNT = "bucketeer.fs.image.mount"
FILESYSTEM_CSV_MOUNT = "bucketeer.fs.csv.mount"
FILESYSTEM_PREFIX = "bucketeer.fs.image.prefix"
SLACK_OAUTH_TOKEN = "bucketeer.slack.oauth.token"
SLACK_CHANNEL_ID = "bucketeer.slack.channel.id"
SLACK_ERROR_CHANNEL_ID = "bucketeer.slack.error.channel.id"
SLACK_WEBHOOK_URL = "bucketeer.slack.webhook.url"
FEATURE_FLAGS = "feature.flags"

# TPU-specific additions (no reference analog — the encode runs in-process)
TPU_LOSSY_RATE = "bucketeer.tpu.lossy.rate"          # bpp, kdu '-rate 3' analog
TPU_BATCH_SIZE = "bucketeer.tpu.batch.size"          # vmap batch for CSV path
TPU_MESH_SHAPE = "bucketeer.tpu.mesh.shape"          # e.g. "2x4" for v5e-8
# Images at/above this pixel count route through the device mesh when
# >1 device is visible (converters/tpu.py); 0/absent keeps the
# converter's built-in threshold, negative disables mesh routing.
MESH_MIN_PIXELS = "bucketeer.mesh.min.pixels"
# Default conversion type when a request doesn't say: "lossless" (the
# reference hardwires LOSSLESS at ImageWorkerVerticle.java:58-64; here it
# is a default, not a constant) or "lossy".
CONVERSION_TYPE = "bucketeer.conversion.type"
# Tier-1 split: run EBCOT context modeling on the device and replay the
# CX/D streams through the host MQ coder (codec/cxd.py). Truthy enables,
# "0"/empty disables, absent defers to the BUCKETEER_DEVICE_CXD env.
DEVICE_CXD = "bucketeer.tpu.device.cxd"
# Full Tier-1 on device: the fused CX/D + MQ program, so the host only
# assembles finished byte segments (codec/cxd.py run_device_mq). Truthy
# enables, "0"/empty disables, absent defers to the BUCKETEER_DEVICE_MQ
# env — whose default is "auto": on for the TPU backend only, off
# everywhere else (on CPU the measured tier1_split shows the native
# host replay beating the emulated device; other accelerators must
# opt in explicitly until measured — docs/pipeline.md flag table).
DEVICE_MQ = "bucketeer.tpu.device.mq"
# JAX persistent compilation cache directory: repeated bench/server runs
# reuse compiled XLA programs instead of recompiling at boot. Env analog:
# BUCKETEER_COMPILE_CACHE (converters/tpu.py wires both).
COMPILE_CACHE = "bucketeer.tpu.compile.cache"
# Cross-request encode scheduler (engine/scheduler.py): admission bound
# (queued + running requests before 503), encode slots, shared host
# Tier-1 pool size, device-batching aggregation window, and the default
# per-request deadline (0 = none). Each also has a BUCKETEER_SCHED_*
# env analog read by the scheduler itself.
SCHED_QUEUE_DEPTH = "bucketeer.sched.queue.depth"
SCHED_MAX_CONCURRENT = "bucketeer.sched.max.concurrent"
SCHED_POOL_SIZE = "bucketeer.sched.pool.size"
SCHED_WINDOW_MS = "bucketeer.sched.window.ms"
SCHED_DEADLINE_S = "bucketeer.sched.deadline.s"
# Device-pool data plane: worker-per-device cap (0 = every
# jax.devices() entry), pipeline-stage mapping mode (auto | off), and
# a fixed front-end/Tier-1 split overriding the bi-criteria mapper
# (0 = let the mapper choose). Env analogs: BUCKETEER_SCHED_DEVICES,
# BUCKETEER_SCHED_PIPELINE, BUCKETEER_SCHED_PIPELINE_SPLIT.
SCHED_DEVICES = "bucketeer.sched.devices"
SCHED_PIPELINE = "bucketeer.sched.pipeline"
SCHED_PIPELINE_SPLIT = "bucketeer.sched.pipeline.split"
# Decoded-image LRU cache budget for the GET /images read path, in MB
# (converters/reader.py; 0 disables). Env analog by the standard
# overlay: BUCKETEER_DECODE_CACHE_MB.
DECODE_CACHE_MB = "bucketeer.decode.cache.mb"
# graftscope (bucketeer_tpu/obs): per-endpoint latency SLO spec, e.g.
# "default=500,get_image=250" in milliseconds per endpoint (the
# handler name labelling /metrics' http.* stages); a breach
# bumps slo.breach.* counters and freezes the flight recorder. Empty
# disables the watchdog. Env analog: BUCKETEER_SLO. (Tracing itself is
# gated by BUCKETEER_TRACE, default on; ring size by
# BUCKETEER_TRACE_RING.)
SLO = "bucketeer.slo"
# Durable job store (engine/journal.py): when set, the JobStore keeps a
# write-ahead journal + snapshot in this directory so killed processes
# resume their batch jobs on restart. Absent/empty keeps the in-memory
# store (tests, dev). Env analog: BUCKETEER_JOB_JOURNAL_DIR.
JOB_JOURNAL_DIR = "bucketeer.job.journal.dir"
# Unified retry policy (engine/retry.py): every engine retry loop (bus
# requeue, S3 upload, status writes) draws bounded exponential-backoff
# + full-jitter delays from one policy, and per-address circuit
# breakers trip open after this many consecutive target failures,
# half-opening after the reset window. Env analogs by the standard
# overlay (BUCKETEER_RETRY_MAX_ATTEMPTS, ...).
RETRY_MAX_ATTEMPTS = "bucketeer.retry.max.attempts"
RETRY_BASE_DELAY_S = "bucketeer.retry.base.delay.s"
RETRY_MAX_DELAY_S = "bucketeer.retry.max.delay.s"
BREAKER_THRESHOLD = "bucketeer.breaker.failure.threshold"
BREAKER_RESET_S = "bucketeer.breaker.reset.s"

# Every known key (env overlay applies to these even without defaults).
ALL_KEYS = (
    HTTP_PORT, OPENAPI_SPEC_PATH, S3_ACCESS_KEY, S3_SECRET_KEY, S3_REGION,
    S3_BUCKET, S3_ENDPOINT, LAMBDA_S3_BUCKET, IIIF_URL, LARGE_IMAGE_URL,
    BATCH_CALLBACK_URL, FESTER_URL, THUMBNAIL_SIZE, MAX_SOURCE_SIZE,
    S3_MAX_REQUESTS, S3_MAX_RETRIES, S3_REQUEUE_DELAY,
    S3_UPLOADER_INSTANCES, S3_UPLOADER_THREADS, FILESYSTEM_IMAGE_MOUNT,
    FILESYSTEM_CSV_MOUNT, FILESYSTEM_PREFIX, SLACK_OAUTH_TOKEN,
    SLACK_CHANNEL_ID, SLACK_ERROR_CHANNEL_ID, SLACK_WEBHOOK_URL,
    FEATURE_FLAGS, TPU_LOSSY_RATE, TPU_BATCH_SIZE, TPU_MESH_SHAPE,
    MESH_MIN_PIXELS, CONVERSION_TYPE, DEVICE_CXD, DEVICE_MQ,
    COMPILE_CACHE,
    SCHED_QUEUE_DEPTH, SCHED_MAX_CONCURRENT, SCHED_POOL_SIZE,
    SCHED_WINDOW_MS, SCHED_DEADLINE_S, SCHED_DEVICES, SCHED_PIPELINE,
    SCHED_PIPELINE_SPLIT, DECODE_CACHE_MB,
    JOB_JOURNAL_DIR, RETRY_MAX_ATTEMPTS, RETRY_BASE_DELAY_S,
    RETRY_MAX_DELAY_S, BREAKER_THRESHOLD, BREAKER_RESET_S,
)

_DEFAULTS: dict[str, Any] = {
    HTTP_PORT: 8888,                    # reference: MainVerticle.java:54
    MAX_SOURCE_SIZE: 300_000_000,       # reference: pom.xml:192-193
    S3_MAX_REQUESTS: 20,                # reference: S3BucketVerticle.java:44
    S3_MAX_RETRIES: 30,                 # reference: pom.xml:163-166
    S3_REQUEUE_DELAY: 1,                # seconds
    S3_UPLOADER_INSTANCES: 1,
    S3_UPLOADER_THREADS: 0,             # <=0 => cores-1 (MainVerticle.java:64-77)
    THUMBNAIL_SIZE: "!200,200",
    TPU_LOSSY_RATE: 3.0,
    TPU_BATCH_SIZE: 8,
    TPU_MESH_SHAPE: "",
    RETRY_MAX_ATTEMPTS: 32,
    RETRY_MAX_DELAY_S: 30.0,
    BREAKER_THRESHOLD: 5,
    BREAKER_RESET_S: 30.0,
}


def truthy(value) -> bool:
    """Shared boolean parsing for env vars and config values: None,
    "", "0", "false", "no" and "off" (case-insensitive) are falsy,
    anything else is truthy. Every flag-style switch goes through here
    so "FLAG=false" means the same thing on every surface."""
    if value is None:
        return False
    return str(value).strip().lower() not in ("", "0", "false", "no",
                                              "off")


@dataclass
class Config:
    """Immutable-ish runtime config: properties file < environment < overrides."""

    values: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def load(cls, properties_path: str | None = None,
             overrides: dict[str, Any] | None = None) -> "Config":
        values: dict[str, Any] = dict(_DEFAULTS)
        path = properties_path or os.environ.get("BUCKETEER_CONFIG")
        if path and os.path.exists(path):
            values.update(_parse_properties(path))
        # Environment overlay: either the exact key, or KEY with dots->underscores,
        # upper-cased (container style: BUCKETEER_S3_BUCKET).
        for key in set(values) | set(ALL_KEYS):
            env_key = key.replace(".", "_").upper()
            if env_key in os.environ:
                values[key] = os.environ[env_key]
        for k, v in os.environ.items():
            if k in values or k in ALL_KEYS:  # exact-name env entries
                values[k] = v
        if overrides:
            values.update(overrides)
        return cls(values)

    def get(self, key: str, default: Any = None) -> Any:
        return self.values.get(key, default if default is not None else _DEFAULTS.get(key))

    def get_int(self, key: str, default: int | None = None) -> int:
        v = self.get(key, default)
        return int(v) if v is not None else 0

    def get_float(self, key: str, default: float | None = None) -> float:
        v = self.get(key, default)
        return float(v) if v is not None else 0.0

    def get_str(self, key: str, default: str | None = None) -> str | None:
        v = self.get(key, default)
        return str(v) if v is not None else None

    def set(self, key: str, value: Any) -> None:
        self.values[key] = value


def _parse_properties(path: str) -> dict[str, str]:
    """Parse a java-style .properties file (the reference's config format)."""
    out: dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith(("#", "!")):
                continue
            # Java Properties semantics: split on whichever of '='/':'
            # appears first in the line.
            positions = [(line.index(s), s) for s in ("=", ":") if s in line]
            if positions:
                _, sep = min(positions)
                k, _, v = line.partition(sep)
                out[k.strip()] = v.strip()
    return out
