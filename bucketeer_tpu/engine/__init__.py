"""Async job engine: message bus, shared state, and workers.

Replaces the reference's Vert.x verticle runtime + event bus (reference:
src/main/java/edu/ucla/library/bucketeer/verticles/ — see SURVEY.md §1
L2). Same request/reply + ``retry`` backpressure protocol, same shared
state semantics, asyncio instead of an event-bus process."""
from .batch import BATCH_CONVERTER, BatchConverterWorker, start_job
from .bus import BusClosed, BusError, MessageBus, Reply
from .core import Engine
from .journal import JobJournal, JournalUnavailable
from .retry import (BreakerRegistry, CircuitBreaker, DeadLetterLog,
                    RetryPolicy)
from .s3 import (FakeS3Client, HttpS3Client, S3_UPLOADER, S3Error,
                 S3UploadWorker, S3UploaderConfig)
from .scheduler import (PRIORITY_BATCH, PRIORITY_SINGLE, DeadlineExceeded,
                        EncodeScheduler, QueueFull, get_scheduler)
from .slack import HttpSlackClient, RecordingSlackClient, SlackWorker
from .store import Counters, JobStore, LockTimeout, UploadsMap
from .workers import (FESTER, FINALIZE_JOB, IMAGE_WORKER, ITEM_FAILURE,
                      LARGE_IMAGE, FesterWorker, FinalizeJobWorker,
                      ImageWorker, ItemFailureWorker, LargeImageWorker,
                      update_item_status)

__all__ = [
    "Engine", "MessageBus", "Reply", "BusError", "BusClosed",
    "JobStore", "Counters", "UploadsMap", "LockTimeout",
    "JobJournal", "JournalUnavailable",
    "RetryPolicy", "CircuitBreaker", "BreakerRegistry", "DeadLetterLog",
    "FakeS3Client", "HttpS3Client", "S3Error", "S3UploadWorker",
    "S3UploaderConfig", "S3_UPLOADER",
    "SlackWorker", "HttpSlackClient", "RecordingSlackClient",
    "ImageWorker", "ItemFailureWorker", "FinalizeJobWorker",
    "LargeImageWorker", "FesterWorker", "update_item_status",
    "IMAGE_WORKER", "ITEM_FAILURE", "FINALIZE_JOB", "LARGE_IMAGE", "FESTER",
    "BatchConverterWorker", "BATCH_CONVERTER", "start_job",
    "EncodeScheduler", "get_scheduler", "QueueFull", "DeadlineExceeded",
    "PRIORITY_SINGLE", "PRIORITY_BATCH",
]
