"""Performance lint rules over graftcost's modeled program facts.

These rules fire on *anti-patterns in the compiled artifacts*, not on
source: the model sees what the Python cannot — realized trip counts,
materialized intermediates, modeled intensity. Today's known offenders
are carried in ``.graftlint-baseline.json`` (the same baseline the AST
rules use, with the same staleness hygiene), so the build stays green
while the debt stays visible: a *new* program joining the offender list
fails ``--strict``, and a *fixed* offender leaves a stale baseline
entry that itself fails ``--strict`` until pruned.

| rule | fires when |
|---|---|
| ``perf-scan-per-element`` | a ``stablehlo.while`` trip count >= one
  step per stripe column of a single pass (1024 for 64x64 blocks) —
  the scan serializes per coefficient/symbol rather than per
  vectorizable stripe column. The pre-stripe-parallel CX/D and MQ
  scans were the offenders; the restructured scans (COLS_PER_TRIP
  columns per trip, MQ_UNROLL symbols per trip, Mb-clamped plane
  loops) sit well under the threshold and the manifest drift gate
  pins that. |
| ``perf-hbm-roundtrip`` | a declared program chain ships a large
  intermediate through HBM — produced by one program, reconsumed by
  the next. The one historical chain (the (N, max_syms) symbol buffer
  between the raw CX/D scan and the MQ coder) was fused away
  (``cxd.fused_program``); CHAINS is empty until a new hand-off
  appears. |
| ``perf-low-intensity-kernel`` | a Pallas program models below the
  intensity threshold (flop/byte) — memory-bound by construction, so
  kernel-side compute tuning is wasted until its traffic shrinks. |

All three are warnings: they are debt, not bugs — but the ``cost-audit``
CI job runs ``--strict``, so unbaselined debt fails the build.
"""
from __future__ import annotations

from .findings import WARNING, Finding
from .graftcost import CostFacts, MachineModel

SCAN_PER_ELEMENT = "perf-scan-per-element"
HBM_ROUNDTRIP = "perf-hbm-roundtrip"
LOW_INTENSITY = "perf-low-intensity-kernel"

# One step per stripe column of one pass over a 64x64 block
# (16 stripes x 64 columns) is the coarsest acceptable sequential
# granularity; trips at or beyond it scale with coefficients/symbols.
SCAN_TRIP_THRESHOLD = 1024

# An inter-program intermediate below this never matters.
ROUNDTRIP_MIN_BYTES = 8192

# Below this modeled flop/byte a Pallas kernel is memory-bound on
# every machine model shipped (both ridges sit above it).
LOW_INTENSITY_THRESHOLD = 1.0

# Declared program chains (source family -> dest family, what travels):
# the audit models each program alone; these name the HBM hand-offs
# between them. Keyed by registry-name family (text before the first
# "/"), so bucket suffixes don't matter. Empty today: the one declared
# chain — the (N, max_syms) uint8 symbol buffer between the raw CX/D
# scan and the MQ coder — was fused away (cxd.fused_program keeps the
# buffer a program-internal value; registry entries cxdmq.fused*), so
# its perf-hbm-roundtrip findings are resolved, not baselined.
CHAINS = ()


def _loc(name: str) -> str:
    return f"<graftcost:{name}>"


def run(costs: list, machine: MachineModel) -> list:
    """Findings over a list of :class:`CostFacts` (one per lowered
    registry program). Pure — no lowering, no device."""
    findings = []
    by_family: dict = {}
    for c in costs:
        if not isinstance(c, CostFacts):
            continue
        by_family.setdefault(c.name.split("/")[0], c)

        if c.max_trip >= SCAN_TRIP_THRESHOLD:
            findings.append(Finding(
                SCAN_PER_ELEMENT, _loc(c.name), 0,
                f"sequential scan with {c.max_trip} trips (total scan "
                f"depth {c.scan_depth}) — at or beyond one step per "
                f"stripe column per pass ({SCAN_TRIP_THRESHOLD}), the "
                "trip count scales with coefficients/symbols rather "
                "than stripe columns; vectorize the step (process a "
                "stripe column per trip) to cut the modeled "
                "sequential floor", WARNING))

        if ".pallas" in c.name \
                and c.intensity < LOW_INTENSITY_THRESHOLD:
            findings.append(Finding(
                LOW_INTENSITY, _loc(c.name), 0,
                f"Pallas program models {c.intensity:.3f} flop/byte "
                f"(< {LOW_INTENSITY_THRESHOLD}, {machine.name} ridge "
                f"{machine.ridge():.1f}) — memory-bound by "
                "construction; shrink its traffic (fuse the chain, "
                "keep state VMEM-resident) before tuning compute",
                WARNING))

    for src, dst, what in CHAINS:
        s, d = by_family.get(src), by_family.get(dst)
        if s is None or d is None:
            continue
        # The hand-off buffer is the chain's dominant output — use its
        # own size, not the sum over every auxiliary result.
        hand_off = max(s.output_sizes, default=s.output_bytes)
        if hand_off >= ROUNDTRIP_MIN_BYTES:
            findings.append(Finding(
                HBM_ROUNDTRIP, _loc(f"{s.name} -> {d.name}"), 0,
                f"{what} ({hand_off} bytes at the audit bucket) "
                f"round-trips HBM between '{src}' and '{dst}' — "
                "produced by one program and reconsumed by the next; "
                "fusing the chain keeps it on-chip and removes "
                f"{hand_off} bytes of traffic per launch each way",
                WARNING))
    return findings
