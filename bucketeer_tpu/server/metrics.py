"""Per-stage timing metrics.

New relative to the reference — it has no metrics endpoint (SURVEY.md §5:
"No Prometheus/metrics endpoint"); the TPU build reports MPixels/s per
stage because throughput is the product metric."""
from __future__ import annotations

import contextlib
import logging
import math
import re
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

from .. import obs
from ..analysis.graftrace import seam

LOG = logging.getLogger(__name__)


class LatencyHist:
    """Fixed log2-bucketed histogram with quarter-octave resolution.

    Buckets are geometric: bucket *i* covers
    ``[2^((LO+i)/SUB), 2^((LO+i+1)/SUB))`` seconds with ``SUB=4``
    sub-buckets per octave, spanning ~1 µs to 256 s, plus an underflow
    and an overflow bucket. Fixed bounds mean zero allocation after
    construction, O(1) observe, lossless merging across processes, and
    a worst-case quantile error of one bucket width (2^(1/4) ≈ 19%) —
    the server-side p50/p95/p99 the mean/min/max ``ValueStats`` could
    never answer. The same shape backs the Prometheus
    ``_bucket``/``_sum``/``_count`` exposition."""

    SUB = 4                       # sub-buckets per octave
    LO_EXP = -20                  # 2^-20 s ≈ 0.95 µs
    HI_EXP = 8                    # 2^8 s = 256 s
    N = (HI_EXP - LO_EXP) * SUB   # finite buckets

    __slots__ = ("counts", "total", "sum")

    def __init__(self) -> None:
        self.counts = [0] * (self.N + 2)   # [under] + finite + [over]
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.total += 1
        self.sum += v
        if v < 2.0 ** self.LO_EXP:
            self.counts[0] += 1
            return
        i = int(math.floor(math.log2(v) * self.SUB)) \
            - self.LO_EXP * self.SUB
        if i >= self.N:
            self.counts[self.N + 1] += 1
        else:
            self.counts[i + 1] += 1

    @classmethod
    def upper_bound(cls, i: int) -> float:
        """Inclusive upper bound of counts[i] (Prometheus ``le``)."""
        if i >= cls.N + 1:
            return math.inf
        return 2.0 ** ((cls.LO_EXP * cls.SUB + i) / cls.SUB)

    def _bucket_value(self, i: int) -> float:
        """Representative value of bucket i: geometric midpoint for
        finite buckets, the adjacent edge for under/overflow."""
        if i == 0:
            return 2.0 ** self.LO_EXP
        if i >= self.N + 1:
            return 2.0 ** self.HI_EXP
        lo = (self.LO_EXP * self.SUB + i - 1) / self.SUB
        return 2.0 ** (lo + 0.5 / self.SUB)

    def percentile(self, q: float) -> float:
        """Approximate q-quantile (0..1) from the buckets."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        cum = 0
        last = 0
        for i, n in enumerate(self.counts):
            if n == 0:
                continue
            cum += n
            last = i
            if cum + 1e-9 >= target:
                return self._bucket_value(i)
        return self._bucket_value(last)

    def percentiles_ms(self) -> dict:
        return {f"p{int(q * 100)}_ms":
                round(self.percentile(q) * 1e3, 3)
                for q in (0.5, 0.95, 0.99)}


@dataclass
class StageStats:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    pixels: int = 0
    items: int = 0        # stage-specific unit (e.g. CX/D symbols)
    hist: LatencyHist = field(default_factory=LatencyHist)

    def record(self, seconds: float, pixels: int = 0,
               items: int = 0) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)
        self.pixels += pixels
        self.items += items
        self.hist.observe(seconds)


@dataclass
class OverlapStats:
    """Paired device/host segments of a pipelined stage. ``saved_s`` is
    wall time hidden by running the two sides concurrently: with no
    overlap wall == device + host, so anything above wall was saved."""
    count: int = 0
    device_s: float = 0.0
    host_s: float = 0.0
    wall_s: float = 0.0
    pixels: int = 0

    def record(self, device_s: float, host_s: float, wall_s: float,
               pixels: int = 0) -> None:
        self.count += 1
        self.device_s += device_s
        self.host_s += host_s
        self.wall_s += wall_s
        self.pixels += pixels

    @property
    def saved_s(self) -> float:
        return max(0.0, self.device_s + self.host_s - self.wall_s)

    @property
    def overlap_ratio(self) -> float:
        """Fraction of the shorter side's work hidden behind the longer
        side (1.0 = the cheaper stage is entirely free)."""
        shorter = min(self.device_s, self.host_s)
        return self.saved_s / shorter if shorter > 0 else 0.0


@dataclass
class ValueStats:
    """Distribution of an observed value (no timing attached): batch
    occupancy, queue lengths, ... Mean/min/max are kept for cheap
    reading, but the product metric is the log2-bucket histogram —
    p50/p95/p99 server-side, where the old aggregates hid the tail."""
    count: int = 0
    total: float = 0.0
    vmin: float = 0.0
    vmax: float = 0.0
    hist: LatencyHist = field(default_factory=LatencyHist)

    def observe(self, value: float) -> None:
        if self.count == 0:
            self.vmin = self.vmax = value
        else:
            self.vmin = min(self.vmin, value)
            self.vmax = max(self.vmax, value)
        self.count += 1
        self.total += value
        self.hist.observe(value)


@dataclass
class Metrics:
    stages: dict = field(default_factory=lambda: defaultdict(StageStats))
    overlaps: dict = field(
        default_factory=lambda: defaultdict(OverlapStats))
    counters: dict = field(default_factory=lambda: defaultdict(int))
    values: dict = field(default_factory=lambda: defaultdict(ValueStats))
    started_at: float = field(default_factory=time.time)
    # Encodes run on real threads (the scheduler's shared Tier-1 pool,
    # BatchConverterWorker's asyncio.to_thread converts, instances=2),
    # and += on the stat fields is a read-modify-write — serialize every
    # update or rare-event counters silently lose increments. The
    # single _lock covers stages, overlaps, counters and values; the
    # hammer test (tests/test_metrics.py) races all four, and the
    # graftrace seam lets the race explorer serialize + check them.
    _lock: threading.Lock = field(
        default_factory=lambda: seam.make_lock("Metrics._lock"),
        repr=False)
    # Live-state reporters: name -> zero-arg callable returning a JSON
    # section merged into report() (e.g. the engine's circuit-breaker
    # registry — current state belongs in /metrics next to the
    # transition counters). Called *outside* _lock: a reporter may take
    # its own locks and must not nest under ours.
    _reporters: dict = field(default_factory=dict, repr=False)

    @contextlib.contextmanager
    def time(self, stage: str, pixels: int = 0):
        # Every timed stage is also a graftscope span (no-op without a
        # recorder): the existing stage instrumentation across the
        # codec/engine IS the span tree's interior, one seam for both.
        t0 = time.perf_counter()
        with obs.span(stage):
            try:
                yield
            finally:
                self.record(stage, time.perf_counter() - t0, pixels)

    def record(self, stage: str, seconds: float, pixels: int = 0,
               items: int = 0) -> None:
        with self._lock:
            seam.write(self, "stages")
            self.stages[stage].record(seconds, pixels, items)

    def record_overlap(self, stage: str, device_s: float, host_s: float,
                       wall_s: float, pixels: int = 0) -> None:
        """Record one pipelined run's device-dispatch vs host-coding
        segments (codec/encoder.py overlapped pipeline)."""
        with self._lock:
            seam.write(self, "overlaps")
            self.overlaps[stage].record(device_s, host_s, wall_s, pixels)

    def count(self, name: str, n: int = 1) -> None:
        """Bump an event counter (PCRD floor re-runs, Tier-2 rebuild
        iterations, mesh routings, admission rejects, ...)."""
        with self._lock:
            seam.write(self, "counters")
            self.counters[name] += n

    def observe(self, name: str, value: float) -> None:
        """Record one sample of a value distribution (e.g. the encode
        scheduler's per-launch batch occupancy)."""
        with self._lock:
            seam.write(self, "values")
            self.values[name].observe(float(value))

    def add_reporter(self, name: str, fn) -> None:
        """Attach (or replace) a live-state section of the report."""
        with self._lock:
            seam.write(self, "_reporters")
            self._reporters[name] = fn

    def report(self) -> dict:
        with self._lock:
            seam.read(self, "stages")
            seam.read(self, "overlaps")
            seam.read(self, "counters")
            seam.read(self, "values")
            out = self._report_locked()
            seam.read(self, "_reporters")
            reporters = dict(self._reporters)
        for name, fn in sorted(reporters.items()):
            try:
                out[name] = fn()
            except Exception as exc:
                # A broken reporter must not take /metrics down with it.
                LOG.warning("metrics reporter %r failed: %s", name, exc)
        return out

    def _report_locked(self) -> dict:
        out = {"uptime_s": round(time.time() - self.started_at, 1),
               "stages": {}}
        for name, st in sorted(self.stages.items()):
            entry = {
                "count": st.count,
                "total_s": round(st.total_s, 3),
                "mean_s": round(st.total_s / st.count, 4) if st.count else 0,
                "max_s": round(st.max_s, 3),
            }
            if st.pixels:
                entry["mpixels"] = round(st.pixels / 1e6, 2)
                if st.total_s > 0:
                    entry["mpixels_per_s"] = round(
                        st.pixels / 1e6 / st.total_s, 2)
            if st.items:
                entry["items"] = st.items
                if st.total_s > 0:
                    entry["items_per_s"] = round(st.items / st.total_s, 1)
            if st.count:
                entry.update(st.hist.percentiles_ms())
            out["stages"][name] = entry
        if self.overlaps:
            out["overlap"] = {}
            for name, ov in sorted(self.overlaps.items()):
                out["overlap"][name] = {
                    "count": ov.count,
                    "device_s": round(ov.device_s, 3),
                    "host_s": round(ov.host_s, 3),
                    "wall_s": round(ov.wall_s, 3),
                    "saved_s": round(ov.saved_s, 3),
                    "overlap_ratio": round(ov.overlap_ratio, 4),
                }
        if self.values:
            out["values"] = {}
            for name, vs in sorted(self.values.items()):
                entry = {
                    "count": vs.count,
                    "mean": round(vs.total / vs.count, 4) if vs.count
                    else 0,
                    "min": round(vs.vmin, 4),
                    "max": round(vs.vmax, 4),
                }
                if vs.count:
                    entry.update({
                        f"p{int(q * 100)}":
                        round(vs.hist.percentile(q), 4)
                        for q in (0.5, 0.95, 0.99)})
                out["values"][name] = entry
        if self.counters:
            out["counters"] = dict(sorted(self.counters.items()))
        return out

    # -- Prometheus text exposition ------------------------------------

    def prometheus(self) -> str:
        """Render the registry in Prometheus text exposition format
        (``GET /metrics?format=prometheus``): counters as one labelled
        counter family, stages and values as labelled histogram
        families with ``_bucket``/``_sum``/``_count`` series (sparse —
        only buckets whose cumulative count changed, plus ``+Inf``),
        overlap segments as gauges. tests/test_obs.py round-trips the
        output through a minimal line-format checker."""
        with self._lock:
            seam.read(self, "stages")
            seam.read(self, "counters")
            seam.read(self, "values")
            seam.read(self, "overlaps")
            uptime = time.time() - self.started_at
            counters = dict(self.counters)
            stages = {name: (list(st.hist.counts), st.hist.sum,
                             st.count)
                      for name, st in self.stages.items()}
            values = {name: (list(vs.hist.counts), vs.hist.sum,
                             vs.count)
                      for name, vs in self.values.items()}
            overlaps = {name: (ov.count, ov.device_s, ov.host_s,
                               ov.wall_s, ov.saved_s)
                        for name, ov in self.overlaps.items()}
        lines = [
            "# HELP bucketeer_uptime_seconds Process uptime.",
            "# TYPE bucketeer_uptime_seconds gauge",
            f"bucketeer_uptime_seconds {uptime:.3f}",
        ]
        if counters:
            lines += [
                "# HELP bucketeer_counter_total Event counters.",
                "# TYPE bucketeer_counter_total counter",
            ]
            for name, n in sorted(counters.items()):
                lines.append(
                    f'bucketeer_counter_total{{name="{_label(name)}"}}'
                    f" {n}")
        for family, label, series, help_text in (
                ("bucketeer_stage_seconds", "stage", stages,
                 "Per-stage latency (log2-bucketed)."),
                ("bucketeer_value", "name", values,
                 "Observed value distributions (log2-bucketed).")):
            if not series:
                continue
            lines += [
                f"# HELP {family} {help_text}",
                f"# TYPE {family} histogram",
            ]
            for name, (counts, hsum, count) in sorted(series.items()):
                sel = f'{label}="{_label(name)}"'
                cum = 0
                for i, n in enumerate(counts):
                    if n == 0:
                        continue
                    cum += n
                    le = _fmt_float(LatencyHist.upper_bound(i))
                    lines.append(
                        f'{family}_bucket{{{sel},le="{le}"}} {cum}')
                lines.append(
                    f'{family}_bucket{{{sel},le="+Inf"}} {cum}')
                lines.append(
                    f'{family}_sum{{{sel}}} {_fmt_float(hsum)}')
                lines.append(f'{family}_count{{{sel}}} {count}')
        if overlaps:
            lines += [
                "# HELP bucketeer_overlap_seconds Pipelined "
                "device/host segment seconds.",
                "# TYPE bucketeer_overlap_seconds gauge",
            ]
            for name, (count, dev, host, wall, saved) in sorted(
                    overlaps.items()):
                base = f'stage="{_label(name)}"'
                for seg, val in (("device", dev), ("host", host),
                                 ("wall", wall), ("saved", saved)):
                    lines.append(
                        f'bucketeer_overlap_seconds{{{base},'
                        f'segment="{seg}"}} {_fmt_float(val)}')
        return "\n".join(lines) + "\n"


_LABEL_BAD = re.compile(r'[\\"\n]')


def _label(value: str) -> str:
    """Escape a Prometheus label value (names here are dotted metric
    names, but the renderer must never emit a broken line)."""
    return _LABEL_BAD.sub("_", str(value))


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    return f"{v:.9g}"


# Process-wide registry: the encoder reports into one well-known object
# (codec.encoder.set_metrics_sink) and every Api instance serves the
# same one, so re-creating the app never strands a stale sink and
# concurrent Apis don't fight over last-writer-wins.
GLOBAL = Metrics()
