"""HTTP-level ingest robustness: the degradation ladder surfaces as
503 + Retry-After (journal unavailable, open circuit), the per-job
dead-letter detail field, idempotent PATCH replay, /metrics counter
visibility, and Engine journal resume after a simulated crash."""
import asyncio
import os

import pytest
from aiohttp import FormData

from bucketeer_tpu import config as cfg
from bucketeer_tpu import features
from bucketeer_tpu import job_factory
from bucketeer_tpu.engine import (Engine, FakeS3Client, JobStore,
                                  RecordingSlackClient)
from bucketeer_tpu.engine import faults
from bucketeer_tpu.models import WorkflowState
from bucketeer_tpu.server.app import build_app
from bucketeer_tpu.utils import path_prefix as pp


class StubConverter:
    def __init__(self, tmpdir):
        self.tmpdir = str(tmpdir)

    def convert(self, image_id, source_path, conversion=None):
        out = os.path.join(self.tmpdir,
                           image_id.replace("/", "_") + ".jpx")
        with open(out, "wb") as fh:
            fh.write(b"JPX!")
        return out


CSV_TEXT = "Item ARK,File Name\nark:/1/a,imgA.tif\nark:/1/b,imgB.tif\n"


def _write_images(tmp_path):
    for name in ("imgA.tif", "imgB.tif"):
        (tmp_path / name).write_bytes(b"II*\x00")


def _csv_form(csv_text=CSV_TEXT):
    form = FormData()
    form.add_field("csvFileToUpload", csv_text.encode(),
                   filename="test-job.csv", content_type="text/csv")
    form.add_field("slack-handle", "tester")
    return form


def make_env(tmp_path, overrides=None):
    config = cfg.Config.load(overrides={
        cfg.IIIF_URL: "http://iiif.test/iiif",
        cfg.SLACK_CHANNEL_ID: "chan",
        cfg.FILESYSTEM_CSV_MOUNT: str(tmp_path / "csv-mount"),
        cfg.FILESYSTEM_IMAGE_MOUNT: str(tmp_path),
        cfg.S3_REQUEUE_DELAY: 0.01,
        **(overrides or {})})
    engine = Engine(
        config,
        flags=features.FeatureFlagChecker(
            static={features.FS_WRITE_CSV: True}),
        converter=StubConverter(tmp_path),
        s3_client=FakeS3Client(str(tmp_path / "s3")),
        slack_client=RecordingSlackClient())
    return build_app(engine, job_delete_timeout=0.1), engine


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.install(None)


async def _wait(cond, timeout=15.0):
    for _ in range(int(timeout / 0.02)):
        if cond():
            return True
        await asyncio.sleep(0.02)
    return cond()


async def test_journal_unavailable_csv_upload_503(tmp_path,
                                                  aiohttp_client):
    _write_images(tmp_path)
    app, engine = make_env(tmp_path, overrides={
        cfg.JOB_JOURNAL_DIR: str(tmp_path / "journal")})
    client = await aiohttp_client(app)
    faults.install(faults.FaultPlan().at(
        "journal.write", lambda: OSError("disk gone"), times=1))
    resp = await client.post("/batch/input/csv", data=_csv_form())
    assert resp.status == 503
    assert int(resp.headers["Retry-After"]) >= 1
    assert "test-job" not in engine.store     # not half-accepted
    # The fault budget is spent: the retried upload goes through and
    # the job runs to completion from its durable record.
    resp = await client.post("/batch/input/csv", data=_csv_form())
    assert resp.status == 200
    assert await _wait(lambda: "test-job" not in engine.store)
    out = (tmp_path / "csv-mount" / "test-job.csv").read_text()
    assert out.count("succeeded") == 2


async def test_circuit_open_csv_upload_503(tmp_path, aiohttp_client):
    _write_images(tmp_path)
    app, engine = make_env(tmp_path)
    client = await aiohttp_client(app)
    for _ in range(engine.s3_breaker.threshold):
        engine.s3_breaker.record_failure()
    assert engine.s3_breaker.is_open
    resp = await client.post("/batch/input/csv", data=_csv_form())
    assert resp.status == 503
    assert int(resp.headers["Retry-After"]) >= 1
    assert "test-job" not in engine.store
    engine.s3_breaker.record_success()        # weather clears
    resp = await client.post("/batch/input/csv", data=_csv_form())
    assert resp.status == 200
    assert await _wait(lambda: "test-job" not in engine.store)


async def test_dead_letters_in_job_detail_and_metrics(tmp_path,
                                                      aiohttp_client):
    _write_images(tmp_path)
    app, engine = make_env(tmp_path)
    client = await aiohttp_client(app)
    job = job_factory.create_job(
        "test-job", CSV_TEXT,
        prefix=pp.GenericFilePathPrefix(str(tmp_path)))
    job.slack_handle = "tester"
    async with engine.store.locked():
        engine.store.put(job)
    engine.bus.dead_letters.record(
        "s3-uploader", 6, "S3 503: outage", image_id="a.jpx",
        job_name="test-job")
    body = await (await client.get("/batch/jobs/test-job")).json()
    assert body["dead-letters"] == [{
        "address": "s3-uploader", "image-id": "a.jpx",
        "job-name": "test-job", "attempts": 6,
        "error": "S3 503: outage",
        "at": body["dead-letters"][0]["at"]}]
    metrics = await (await client.get("/metrics")).json()
    assert metrics["counters"]["retry.dead_letters"] >= 1
    # Live breaker state is a /metrics section, not just counters.
    assert metrics["breakers"]["s3-uploader"]["state"] == "closed"


async def test_new_run_does_not_inherit_stale_dead_letters(
        tmp_path, aiohttp_client):
    """Yesterday's dead letters for 'test-job' must not show up in a
    fresh upload of the same job name."""
    _write_images(tmp_path)
    app, engine = make_env(tmp_path)
    client = await aiohttp_client(app)
    engine.bus.dead_letters.record(
        "s3-uploader", 6, "stale", image_id="old.jpx",
        job_name="test-job")
    resp = await client.post("/batch/input/csv", data=_csv_form())
    assert resp.status == 200
    assert engine.bus.dead_letters.for_job("test-job") == []
    assert await _wait(lambda: "test-job" not in engine.store)


async def test_patch_replay_is_idempotent(tmp_path, aiohttp_client):
    """A double PATCH (the Lambda retrying its callback) must not flip
    a resolved item or re-finalize the job."""
    _write_images(tmp_path)
    app, engine = make_env(tmp_path)
    client = await aiohttp_client(app)
    job = job_factory.create_job(
        "test-job", CSV_TEXT,
        prefix=pp.GenericFilePathPrefix(str(tmp_path)))
    job.slack_handle = "tester"
    async with engine.store.locked():
        engine.store.put(job)
    resp = await client.patch("/batch/jobs/test-job/ark%3A%2F1%2Fa/true")
    assert resp.status == 204
    resp = await client.patch(
        "/batch/jobs/test-job/ark%3A%2F1%2Fa/false")   # replayed, flips?
    assert resp.status == 204
    item = engine.store.get("test-job").find_item("ark:/1/a")
    assert item.workflow_state is WorkflowState.SUCCEEDED


async def test_engine_resumes_journaled_job_on_startup(tmp_path,
                                                       aiohttp_client):
    """The crash story end to end at the Engine level: a journal left
    behind by a killed process (1 of 2 items resolved, 1 dispatched)
    finalizes after restart with every item accounted exactly once."""
    _write_images(tmp_path)
    jdir = str(tmp_path / "journal")
    # The "previous process": journal a half-done job, then vanish.
    store = JobStore(journal_dir=jdir)
    job = job_factory.create_job(
        "test-job", CSV_TEXT,
        prefix=pp.GenericFilePathPrefix(str(tmp_path)))
    job.slack_handle = "tester"
    store.put(job)
    store.mark_dispatched("test-job", "ark:/1/a")
    store.mark_dispatched("test-job", "ark:/1/b")
    store.resolve_item("test-job", "ark:/1/a", True,
                       "http://iiif.test/iiif/a")
    store.close()

    app, engine = make_env(tmp_path, overrides={
        cfg.JOB_JOURNAL_DIR: jdir})
    recovered = engine.store.get("test-job")
    assert recovered.remaining() == 1
    assert engine.store.dispatched("test-job") == {"ark:/1/b"}
    client = await aiohttp_client(app)   # startup fires the resume task
    assert await _wait(lambda: "test-job" not in engine.store)
    out = (tmp_path / "csv-mount" / "test-job.csv").read_text()
    # Exactly once: the pre-crash success kept its state (and URL from
    # the journal), the dispatched-unresolved item was re-driven.
    assert out.count("succeeded") == 2
    assert "http://iiif.test/iiif/a" in out
    # A fresh store over the same dir shows the finalize was journaled.
    store2 = JobStore(journal_dir=jdir)
    assert "test-job" not in store2
    store2.close()


async def test_resume_finalizes_fully_resolved_job(tmp_path,
                                                   aiohttp_client):
    """Crash in the gap between the last status write and the finalize
    message: on restart the job has remaining()==0 and must finalize
    without re-dispatching anything."""
    _write_images(tmp_path)
    jdir = str(tmp_path / "journal")
    store = JobStore(journal_dir=jdir)
    job = job_factory.create_job(
        "test-job", CSV_TEXT,
        prefix=pp.GenericFilePathPrefix(str(tmp_path)))
    job.slack_handle = "tester"
    store.put(job)
    store.resolve_item("test-job", "ark:/1/a", True)
    store.resolve_item("test-job", "ark:/1/b", False)
    store.close()

    app, engine = make_env(tmp_path, overrides={
        cfg.JOB_JOURNAL_DIR: jdir})
    client = await aiohttp_client(app)
    assert await _wait(lambda: "test-job" not in engine.store)
    out = (tmp_path / "csv-mount" / "test-job.csv").read_text()
    assert "succeeded" in out and "failed" in out
