"""Production-side seam for graftrace.

The serving core (``engine/scheduler.py``, ``converters/reader.py``,
``server/metrics.py``) creates its synchronization primitives and marks
its shared-field accesses through this module instead of calling
``threading`` directly. In production nothing is installed and every
function is a no-op wrapper around the real primitive — one module
global load plus a ``None`` check, no allocation, no extra frames kept
alive. Under ``python -m bucketeer_tpu.analysis --race`` (or the
graftrace tests) a :class:`~.runtime.TraceRuntime` is installed and the
same calls return *controlled* primitives that serialize threads at
yield points so interleavings can be explored and replayed
deterministically.

Annotation policy (mirrors the static ``unguarded-field-write`` rule):
every *write* to lock-guarded shared state is marked with
:func:`write`, cross-thread-sensitive reads with :func:`read`.
Documented lock-free fast reads (cache-hit paths, stat snapshots whose
worst case is staleness) are deliberately *not* annotated — the
dynamic detector, like the lint, flags corruption, not staleness.
"""
from __future__ import annotations

import threading
import time

_RT = None   # the installed TraceRuntime; None in production


def install(rt) -> None:
    """Install (or, with None, remove) the active graftrace runtime.
    Only the explorer and tests call this."""
    global _RT
    _RT = rt


def active() -> bool:
    return _RT is not None


def runtime():
    return _RT


# -- primitive factories ------------------------------------------------

def make_lock(name: str = "lock"):
    rt = _RT
    if rt is None:
        return threading.Lock()
    return rt.make_lock(name)


def make_rlock(name: str = "rlock"):
    rt = _RT
    if rt is None:
        return threading.RLock()
    return rt.make_rlock(name)


def make_condition(name: str = "cond", lock=None):
    rt = _RT
    if rt is None:
        return threading.Condition(lock)
    return rt.make_condition(name, lock)


def make_event(name: str = "event"):
    rt = _RT
    if rt is None:
        return threading.Event()
    return rt.make_event(name)


def start_thread(target, *, name: str, args: tuple = (),
                 daemon: bool = True):
    """Create *and start* a thread. Returns the started thread object
    (a real ``threading.Thread`` in production, a controlled handle
    with the same ``is_alive``/``join`` surface under graftrace)."""
    rt = _RT
    if rt is None:
        t = threading.Thread(target=target, name=name, args=args,
                             daemon=daemon)
        t.start()
        return t
    return rt.start_thread(target, name=name, args=args)


# -- yield points -------------------------------------------------------

def read(owner, field: str) -> None:
    """Mark a cross-thread-sensitive read of ``owner.field``."""
    rt = _RT
    if rt is not None:
        rt.access(owner, field, False)


def write(owner, field: str) -> None:
    """Mark a mutation of shared state ``owner.field`` (assignment,
    augmented assignment, or an in-place container mutation)."""
    rt = _RT
    if rt is not None:
        rt.access(owner, field, True)


def yield_point(tag: str = "") -> None:
    """A pure scheduling point with no access semantics (e.g. inside a
    stubbed device launch, so close() can interleave mid-launch)."""
    rt = _RT
    if rt is not None:
        rt.yield_point(tag)


# -- virtual time -------------------------------------------------------

def monotonic() -> float:
    """``time.monotonic`` in production; the runtime's deterministic
    virtual clock under graftrace, so deadline/window timeouts are
    schedule decisions instead of wall-clock races."""
    rt = _RT
    if rt is None:
        return time.monotonic()
    return rt.monotonic()
