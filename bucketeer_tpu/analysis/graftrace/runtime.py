"""The controlled scheduler: serialize instrumented threads, explore
interleavings, replay them bit-for-bit.

Model
-----
Exactly one *controlled* thread runs at a time; every other controlled
thread is parked on a private semaphore. At each **yield point** — lock
acquire/release, condition wait/notify, event set/wait, thread
start/join, and every :func:`seam.read`/:func:`seam.write` field
access — the running thread asks the runtime's *strategy* which thread
runs next and hands off if the answer is not itself. Because the
program under test is deterministic apart from scheduling (virtual
clock below), the sequence of chosen thread names fully determines the
run: recording it gives replay, forcing a prefix gives systematic
(CHESS-style) exploration, and seeding the random strategy gives a
reproducible random walk.

Blocking is modeled, never real: a thread that would block (contended
lock, un-set event, condition wait, join on a live thread) is marked
blocked and another runnable thread is scheduled. When *no* thread is
runnable the runtime first advances the **virtual clock** to the
earliest timed-wait deadline (``seam.monotonic`` serves this clock, so
per-request deadlines and the scheduler's batching window become
deterministic schedule decisions); if no timed waiter exists either,
that is a real deadlock — reported with every blocked thread's stack,
held locks and wait target, then the run is aborted instead of hanging.

Threads spawned through :func:`seam.start_thread` are controlled from
birth; a foreign thread that touches the seam mid-run is adopted and
serialized from its first instrumented operation. Teardown aborts any
thread still alive when the scenario body returns (they unwind via the
:class:`_Abort` BaseException at their next yield point), so 500+
schedules never leak OS threads.
"""
from __future__ import annotations

import os
import random
import sys
import threading

_CLOCK_EPS = 1e-4        # virtual seconds added per scheduling decision
_CLOCK_START = 1000.0


class _Abort(BaseException):
    """Teardown/deadlock unwinder. A BaseException so scenario-level
    ``except Exception`` handlers (and the scheduler's own device-loop
    catch-all) never swallow it."""


class ThreadState:
    __slots__ = ("tid", "name", "sem", "vc", "held", "blocked_on",
                 "wake_deadline", "timed_out", "finished", "aborted",
                 "error", "real_ident", "real_thread")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = f"{tid}:{name}"
        self.sem = threading.Semaphore(0)
        self.vc: dict = {}
        self.held: list = []          # traced locks, acquisition order
        self.blocked_on = None        # (kind, obj) | None
        self.wake_deadline = None     # virtual-clock absolute deadline
        self.timed_out = False
        self.finished = False
        self.aborted = False
        self.error = None
        self.real_ident = None
        self.real_thread = None


_HARNESS_FILES = ("graftrace/runtime.py", "graftrace/seam.py",
                  "graftrace/detector.py", "graftrace/explore.py")


def _frame_name(filename: str):
    """Repo-relative name of an app frame, or None for harness /
    interpreter-internal frames that would bury the signal."""
    fn = filename.replace("\\", "/")
    if fn.endswith(_HARNESS_FILES) or fn.endswith("/threading.py"):
        return None
    if "bucketeer_tpu" in fn:
        return "bucketeer_tpu" + fn.split("bucketeer_tpu", 1)[1]
    return os.path.basename(fn)


def _walk_app_frames(f, limit: int = 8) -> tuple:
    out = []
    while f is not None and len(out) < limit:
        name = _frame_name(f.f_code.co_filename)
        if name is not None:
            out.append((name, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return tuple(out)


def app_stack(skip: int = 2, limit: int = 8) -> tuple:
    """A trimmed (file, line, function) stack of the caller, excluding
    harness frames, repo-relative where possible. Cheap frame walk —
    called on every instrumented access, so no traceback objects."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    return _walk_app_frames(f, limit)


# -- strategies ---------------------------------------------------------

class RandomStrategy:
    """Seeded-random walk: uniform over the runnable set. Deterministic
    given the seed because the runnable set and its order are."""

    def __init__(self, seed: int):
        self.seed = seed
        self._rng = random.Random(seed)
        self.diverged_at = None

    def choose(self, step, runnable, current):
        return runnable[self._rng.randrange(len(runnable))]


class GuidedStrategy:
    """Force a recorded prefix of thread-name choices, then fall back
    to the default rule (continue the current thread if runnable, else
    the lowest-id runnable). The systematic explorer and trace replay
    both run on this."""

    def __init__(self, prefix=()):
        self.prefix = list(prefix)
        self.diverged_at = None

    def choose(self, step, runnable, current):
        if step < len(self.prefix):
            want = self.prefix[step]
            for t in runnable:
                if t.name == want:
                    return t
            if self.diverged_at is None:
                self.diverged_at = step
        for t in runnable:
            if t is current:
                return t
        return runnable[0]


class TraceRuntime:
    """One controlled execution. Install via ``seam.install(rt)``, run
    the scenario body with :meth:`run`, read results off the runtime
    (``decision_log``, ``deadlocks``, ``errors``, and the detector)."""

    def __init__(self, strategy, detector, max_steps: int = 50000):
        self._mu = threading.Lock()
        self._states: dict = {}       # real ident -> ThreadState
        self._order: list = []        # ThreadState, tid order
        self._strategy = strategy
        self.detector = detector
        self.clock = _CLOCK_START
        self.decision_log: list = []  # {runnable, current, chosen, preempt}
        self.preemptions = 0
        self.deadlocks: list = []
        self.errors: list = []        # (thread name, exception)
        self.step_overflow = False
        self._max_steps = max_steps
        self._steps = 0
        self._tearing_down = False
        self._main = None

    # -- public seam surface -------------------------------------------

    def make_lock(self, name):
        return TracedLock(self, name)

    def make_rlock(self, name):
        return TracedRLock(self, name)

    def make_condition(self, name, lock=None):
        return TracedCondition(self, name, lock)

    def make_event(self, name):
        return TracedEvent(self, name)

    def start_thread(self, target, *, name, args=()):
        t = TracedThread(self, target, name, args)
        t.start()
        return t

    def access(self, owner, field, is_write):
        st = self._current()
        self._decision(st)
        if not self._tearing_down:
            self.detector.on_access(st, owner, field, is_write,
                                    app_stack(skip=3))

    def yield_point(self, tag=""):
        self._decision(self._current())

    def monotonic(self):
        return self.clock

    @property
    def divergence(self):
        return getattr(self._strategy, "diverged_at", None)

    # -- running a scenario --------------------------------------------

    def run(self, fn) -> "TraceRuntime":
        st = self._register("main", parent=None)
        st.real_ident = threading.get_ident()
        with self._mu:
            self._states[st.real_ident] = st
            self._main = st
        try:
            fn()
        except _Abort:
            pass
        except BaseException as exc:  # graftlint: disable=swallowed-exception
            # Scenario-invariant failures become findings, not crashes:
            # the explorer reports them with the schedule that broke
            # the invariant.
            self.errors.append((st.name, exc))
        finally:
            st.finished = True
            self._teardown()
        return self

    def _teardown(self):
        with self._mu:
            self._tearing_down = True
            leftovers = [t for t in self._order
                         if not t.finished and t is not self._main]
            for t in leftovers:
                t.aborted = True
                t.blocked_on = None
                t.sem.release()
        for t in leftovers:
            real = t.real_thread
            if real is not None:
                real.join(timeout=5)
                if real.is_alive():
                    self.errors.append((t.name, RuntimeError(
                        "graftrace teardown: thread did not unwind")))

    # -- registration ---------------------------------------------------

    def _register(self, name, parent):
        with self._mu:
            st = ThreadState(len(self._order), name)
            self._order.append(st)
        if parent is None:
            self.detector.init_thread(st)
        else:
            self.detector.fork(parent, st)
        return st

    def _current(self) -> ThreadState:
        ident = threading.get_ident()
        st = self._states.get(ident)
        if st is None:
            # A thread the harness did not spawn touched the seam:
            # adopt and serialize it from here on.
            st = self._register(threading.current_thread().name,
                                parent=None)
            st.real_ident = ident
            with self._mu:
                self._states[ident] = st
            st.sem.acquire()          # wait for a turn
            if st.aborted:
                raise _Abort()
        return st

    def _bind(self, st: ThreadState):
        st.real_ident = threading.get_ident()
        with self._mu:
            self._states[st.real_ident] = st

    # -- scheduling core ------------------------------------------------

    def _runnable_locked(self):
        return [t for t in self._order
                if not t.finished and not t.aborted
                and t.blocked_on is None]

    def _choose_locked(self, runnable, current):
        chosen = self._strategy.choose(len(self.decision_log), runnable,
                                       current)
        preempt = (chosen is not current
                   and any(t is current for t in runnable))
        if preempt:
            self.preemptions += 1
        self.decision_log.append({
            "runnable": [t.name for t in runnable],
            "current": current.name,
            "chosen": chosen.name,
            "preempt": preempt,
        })
        return chosen

    def _decision(self, st: ThreadState):
        """A scheduling point for a *running* thread."""
        if st.aborted:
            raise _Abort()
        if self._tearing_down:
            return
        self._steps += 1
        if self._steps > self._max_steps:
            self.step_overflow = True
            raise _Abort()
        with self._mu:
            self.clock += _CLOCK_EPS
            runnable = self._runnable_locked()
            chosen = self._choose_locked(runnable, st)
        if chosen is not st:
            chosen.sem.release()
            st.sem.acquire()
            if st.aborted:
                raise _Abort()

    def _block(self, st: ThreadState, kind, obj, timeout=None) -> bool:
        """Block ``st`` on (kind, obj); returns True when the wake was
        a virtual-clock timeout rather than a real wake."""
        if st.aborted:
            raise _Abort()
        if self._tearing_down:
            return False
        with self._mu:
            st.blocked_on = (kind, obj)
            st.wake_deadline = (None if timeout is None
                                else self.clock + max(0.0, timeout))
            st.timed_out = False
            chosen = self._next_locked(st)
        if chosen is not None:
            chosen.sem.release()
        st.sem.acquire()
        if st.aborted:
            raise _Abort()
        return st.timed_out

    def _next_locked(self, current):
        """Pick the next thread when ``current`` just blocked or
        finished. Advances the virtual clock over timed waits; when
        everyone is blocked with no deadline, records a deadlock and
        aborts the blocked set (caller's sem is released via the abort
        path, so nothing hangs)."""
        runnable = self._runnable_locked()
        if runnable:
            return self._choose_locked(runnable, current)
        timed = [t for t in self._order
                 if not t.finished and not t.aborted
                 and t.blocked_on is not None
                 and t.wake_deadline is not None]
        if timed:
            self.clock = max(self.clock,
                             min(t.wake_deadline for t in timed))
            self.clock += _CLOCK_EPS
            for t in timed:
                if t.wake_deadline <= self.clock:
                    t.timed_out = True
                    t.blocked_on = None
                    t.wake_deadline = None
            runnable = self._runnable_locked()
            if runnable:
                return self._choose_locked(runnable, current)
        blocked = [t for t in self._order
                   if not t.finished and not t.aborted
                   and t.blocked_on is not None]
        if blocked:
            self._record_deadlock_locked(blocked)
            for t in blocked:
                t.aborted = True
                t.blocked_on = None
                t.sem.release()
        return None

    def _record_deadlock_locked(self, blocked):
        frames = sys._current_frames()
        report = []
        for t in blocked:
            kind, obj = t.blocked_on
            stack = _walk_app_frames(frames.get(t.real_ident))
            report.append({
                "thread": t.name,
                "waiting_for": f"{kind}:{getattr(obj, 'name', type(obj).__name__)}",
                "holding": [lk.name for lk in t.held],
                "stack": stack,
            })
        self.deadlocks.append(tuple(
            sorted((r["thread"], r["waiting_for"], tuple(r["holding"]),
                    r["stack"]) for r in report)))

    def _wake(self, pred):
        """Mark matching blocked threads runnable (they stay parked
        until the strategy picks them)."""
        with self._mu:
            for t in self._order:
                if not t.finished and not t.aborted and \
                        t.blocked_on is not None and pred(t):
                    t.blocked_on = None
                    t.wake_deadline = None
                    t.timed_out = False

    def _thread_finished(self, st: ThreadState):
        self.detector.finish(st)
        with self._mu:
            st.finished = True
            for t in self._order:
                if t.blocked_on == ("join", st):
                    t.blocked_on = None
                    t.wake_deadline = None
                    t.timed_out = False
            chosen = None
            if not self._tearing_down:
                chosen = self._next_locked(st)
        if chosen is not None:
            chosen.sem.release()


# -- controlled primitives ---------------------------------------------

class TracedLock:
    """Controlled non-reentrant lock. A thread re-acquiring it blocks
    on itself — which the deadlock detector then reports, exactly like
    production would hang."""

    def __init__(self, rt: TraceRuntime, name: str):
        self.rt = rt
        self.name = name
        self.owner = None
        self.vc: dict = {}

    def acquire(self, blocking=True, timeout=-1):
        rt = self.rt
        st = rt._current()
        rt._decision(st)
        rt.detector.on_acquire_attempt(st, self)
        while self.owner is not None:
            if not blocking:
                return False
            to = None if timeout is None or timeout < 0 else timeout
            if rt._block(st, "lock", self, to):
                return False
        self.owner = st
        rt.detector.on_acquire(st, self)
        st.held.append(self)
        return True

    def release(self):
        rt = self.rt
        st = rt._current()
        if self.owner is not st:
            if st.aborted or rt._tearing_down:
                return
            raise RuntimeError(f"release of unheld traced lock {self.name}")
        rt.detector.on_release(st, self)
        self.owner = None
        if self in st.held:
            st.held.remove(self)
        rt._wake(lambda t: t.blocked_on == ("lock", self))
        rt._decision(st)

    def locked(self):
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class TracedRLock:
    def __init__(self, rt: TraceRuntime, name: str):
        self.rt = rt
        self.name = name
        self.owner = None
        self.count = 0
        self.vc: dict = {}

    def acquire(self, blocking=True, timeout=-1):
        rt = self.rt
        st = rt._current()
        rt._decision(st)
        if self.owner is st:
            self.count += 1
            return True
        rt.detector.on_acquire_attempt(st, self)
        while self.owner is not None:
            if not blocking:
                return False
            to = None if timeout is None or timeout < 0 else timeout
            if rt._block(st, "lock", self, to):
                return False
        self.owner = st
        self.count = 1
        rt.detector.on_acquire(st, self)
        st.held.append(self)
        return True

    def release(self):
        rt = self.rt
        st = rt._current()
        if self.owner is not st:
            if st.aborted or rt._tearing_down:
                return
            raise RuntimeError(f"release of unheld traced rlock {self.name}")
        self.count -= 1
        if self.count > 0:
            return
        rt.detector.on_release(st, self)
        self.owner = None
        if self in st.held:
            st.held.remove(self)
        rt._wake(lambda t: t.blocked_on == ("lock", self))
        rt._decision(st)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class TracedCondition:
    """Controlled condition variable. Happens-before flows through the
    underlying lock (the notifier holds it while notifying, the waiter
    reacquires it before returning), matching CPython semantics."""

    def __init__(self, rt: TraceRuntime, name: str, lock=None):
        self.rt = rt
        self.name = name
        self._lock = lock if lock is not None else TracedRLock(rt, name)

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()

    def wait(self, timeout=None):
        rt = self.rt
        st = rt._current()
        lock = self._lock
        if lock.owner is not st:
            if st.aborted:
                raise _Abort()
            raise RuntimeError(f"wait on un-acquired condition {self.name}")
        saved = lock.count if isinstance(lock, TracedRLock) else 1
        rt.detector.on_release(st, lock)
        if isinstance(lock, TracedRLock):
            lock.count = 0
        lock.owner = None
        if lock in st.held:
            st.held.remove(lock)
        rt._wake(lambda t: t.blocked_on == ("lock", lock))
        timed_out = rt._block(st, "cond", self, timeout)
        rt.detector.on_acquire_attempt(st, lock)
        while lock.owner is not None:
            rt._block(st, "lock", lock)
        lock.owner = st
        if isinstance(lock, TracedRLock):
            lock.count = saved
        rt.detector.on_acquire(st, lock)
        st.held.append(lock)
        return not timed_out

    def notify(self, n=1):
        rt = self.rt
        st = rt._current()
        if self._lock.owner is not st:
            # Mirror CPython: notifying without holding the lock is
            # itself the bug class this checker exists to catch.
            if st.aborted:
                raise _Abort()
            if not rt._tearing_down:
                raise RuntimeError(
                    f"cannot notify on un-acquired condition {self.name}")
        with rt._mu:
            woken = 0
            for t in rt._order:
                if woken >= n:
                    break
                if not t.finished and not t.aborted and \
                        t.blocked_on == ("cond", self):
                    t.blocked_on = None
                    t.wake_deadline = None
                    t.timed_out = False
                    woken += 1
        rt._decision(st)

    def notify_all(self):
        self.notify(n=len(self.rt._order))


class TracedEvent:
    def __init__(self, rt: TraceRuntime, name: str):
        self.rt = rt
        self.name = name
        self._flag = False
        self.vc: dict = {}

    def is_set(self):
        # Observing the flag True is an acquire: `while not
        # ev.is_set(): ev.wait()` idioms may never call wait() at all,
        # yet the set()->is_set() edge is exactly the ordering the
        # caller is relying on. No scheduling decision — is_set() in a
        # spin loop must not explode the schedule tree.
        if self._flag:
            st = self.rt._states.get(threading.get_ident())
            if st is not None:
                self.rt.detector.on_event_wait(st, self)
        return self._flag

    def set(self):
        rt = self.rt
        st = rt._current()
        rt.detector.on_event_set(st, self)
        self._flag = True
        rt._wake(lambda t: t.blocked_on == ("event", self))
        rt._decision(st)

    def clear(self):
        self._flag = False

    def wait(self, timeout=None):
        rt = self.rt
        st = rt._current()
        rt._decision(st)
        if self._flag:
            rt.detector.on_event_wait(st, self)
            return True
        timed_out = rt._block(st, "event", self, timeout)
        if timed_out and not self._flag:
            return False
        rt.detector.on_event_wait(st, self)
        return self._flag


class TracedThread:
    """Controlled thread handle with the ``threading.Thread`` surface
    the scheduler uses (start/is_alive/join). Registered with the
    runtime from the *parent's* context at start(), so the runnable set
    is deterministic regardless of OS thread-start latency."""

    def __init__(self, rt: TraceRuntime, target, name: str, args=()):
        self.rt = rt
        self.name = name
        self._target = target
        self._args = args
        self.st = None

    def start(self):
        rt = self.rt
        parent = rt._current()
        st = rt._register(self.name, parent=parent)
        self.st = st
        real = threading.Thread(target=self._run,
                                name=f"graftrace-{st.name}", daemon=True)
        st.real_thread = real
        real.start()
        rt._decision(parent)
        return self

    def _run(self):
        rt = self.rt
        st = self.st
        rt._bind(st)
        st.sem.acquire()              # wait for the first turn
        try:
            if not st.aborted:
                self._target(*self._args)
        except _Abort:
            pass
        except BaseException as exc:  # graftlint: disable=swallowed-exception
            # Delivered to the explorer as a scenario-invariant finding
            # together with the schedule that produced it.
            st.error = exc
            rt.errors.append((st.name, exc))
        finally:
            rt._thread_finished(st)

    def is_alive(self):
        return self.st is not None and not self.st.finished

    def join(self, timeout=None):
        rt = self.rt
        st = rt._current()
        rt._decision(st)
        target = self.st
        if target is None or target.finished:
            if target is not None:
                rt.detector.on_join(st, target)
            return
        timed_out = rt._block(st, "join", target, timeout)
        if not timed_out:
            rt.detector.on_join(st, target)
