"""The tensor-delivery HTTP surface (ISSUE 13): GET
/images/{id}/coefficients (npz of subband planes + X-Coeff-Meta),
POST/GET /tensors/{id} (npy in, container stored, npy/blob out,
progressive planes=), typed 400s, and the 503 + Retry-After admission
ladder shared with every other endpoint.
"""
import io
import json

import numpy as np
import pytest

from bucketeer_tpu import config as cfg
from bucketeer_tpu import features
from bucketeer_tpu.codec import encoder as codec_encoder
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.converters import output_path
from bucketeer_tpu.engine import Engine, FakeS3Client, RecordingSlackClient
from bucketeer_tpu.server.app import build_app


@pytest.fixture
def env_client(tmp_path, aiohttp_client):
    async def factory():
        config = cfg.Config.load(overrides={
            cfg.IIIF_URL: "http://iiif.test/iiif",
            cfg.SLACK_CHANNEL_ID: "chan",
            cfg.FILESYSTEM_CSV_MOUNT: str(tmp_path / "csv-mount"),
        })
        engine = Engine(
            config,
            flags=features.FeatureFlagChecker(static={}),
            converter=None,
            s3_client=FakeS3Client(str(tmp_path / "s3")),
            slack_client=RecordingSlackClient())
        app = build_app(engine, job_delete_timeout=0.1)
        client = await aiohttp_client(app)
        return client, engine

    return factory


def _write_derivative(tmp_path, monkeypatch, image_id="coeff-img",
                      size=64):
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    rng = np.random.default_rng(23)
    img = rng.integers(0, 256, size=(size, size, 3)).astype(np.uint8)
    data = codec_encoder.encode_jp2(
        img, 8, EncodeParams(lossless=True, levels=2, tile_size=size,
                             gen_plt=True), jpx=True)
    with open(output_path(image_id, ".jpx"), "wb") as fh:
        fh.write(data)
    return img, data


async def test_get_coefficients(tmp_path, env_client, monkeypatch):
    from bucketeer_tpu.tensor import decode_to_coefficients

    _, data = _write_derivative(tmp_path, monkeypatch)
    client, _ = await env_client()
    resp = await client.get("/images/coeff-img/coefficients")
    assert resp.status == 200
    meta = json.loads(resp.headers["X-Coeff-Meta"])
    assert meta["levels"] == 2 and meta["reversible"] is True
    with np.load(io.BytesIO(await resp.read())) as npz:
        got = dict(npz)
    expected = decode_to_coefficients(data).to_host()
    assert set(got) == {f"r{r}_{n}" for r, n in expected}
    for (r, n), arr in expected.items():
        np.testing.assert_array_equal(got[f"r{r}_{n}"], arr)

    # Region read: windows in the meta, windowed arrays in the npz.
    resp = await client.get(
        "/images/coeff-img/coefficients?region=8,8,32,32")
    assert resp.status == 200
    meta = json.loads(resp.headers["X-Coeff-Meta"])
    assert "windows" in meta
    with np.load(io.BytesIO(await resp.read())) as npz:
        for key, win in meta["windows"].items():
            np.testing.assert_array_equal(
                npz[key],
                expected[_unkey(key)][:, win[0]:win[1], win[2]:win[3]])


def _unkey(key: str):
    res, name = key.split("_")
    return (int(res[1:]), name)


async def test_get_coefficients_errors(tmp_path, env_client,
                                       monkeypatch):
    _write_derivative(tmp_path, monkeypatch)
    client, _ = await env_client()
    assert (await client.get(
        "/images/no-such/coefficients")).status == 404
    assert (await client.get(
        "/images/coeff-img/coefficients?reduce=-1")).status == 400
    assert (await client.get(
        "/images/coeff-img/coefficients?reduce=9")).status == 400
    assert (await client.get(
        "/images/coeff-img/coefficients?region=1,2,3")).status == 400
    assert (await client.get(
        "/images/coeff-img/coefficients?region=0,0,0,5")).status == 400


async def test_tensor_post_get_roundtrip(tmp_path, env_client,
                                         monkeypatch):
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    # Host backend over HTTP: the endpoint's job is plumbing, the
    # backend equivalence is the codec suite's job.
    monkeypatch.setenv("BUCKETEER_TENSOR_BACKEND", "host")
    client, _ = await env_client()
    rng = np.random.default_rng(29)
    arr = rng.standard_normal((40, 30)).astype(np.float32)
    buf = io.BytesIO()
    np.save(buf, arr)

    resp = await client.post("/tensors/ckpt%2Flayer0",
                             data=buf.getvalue())
    assert resp.status == 201
    stats = await resp.json()
    assert stats["tensor-id"] == "ckpt/layer0"
    assert stats["dtype"] == "float32"
    assert stats["shape"] == [40, 30]
    assert stats["coded_bytes"] > 0

    resp = await client.get("/tensors/ckpt%2Flayer0")
    assert resp.status == 200
    assert resp.headers["X-Tensor-Dtype"] == "float32"
    got = np.load(io.BytesIO(await resp.read()))
    np.testing.assert_array_equal(got.view(np.uint32),
                                  arr.view(np.uint32))

    # Progressive: planes= truncation over HTTP, and the raw blob.
    resp = await client.get("/tensors/ckpt%2Flayer0?planes=8")
    assert resp.status == 200
    approx = np.load(io.BytesIO(await resp.read()))
    assert approx.shape == arr.shape
    resp = await client.get("/tensors/ckpt%2Flayer0?format=blob")
    assert resp.status == 200
    blob = await resp.read()
    from bucketeer_tpu.tensor import decode_tensor
    np.testing.assert_array_equal(
        decode_tensor(blob).view(np.uint32), arr.view(np.uint32))

    metrics = await (await client.get("/metrics")).json()
    counters = metrics["counters"]
    assert counters["tensor.encode_requests"] == 1
    assert counters["tensor.decode_requests"] >= 2
    assert "tensor.encode" in metrics["stages"]


async def test_tensor_errors(tmp_path, env_client, monkeypatch):
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    client, _ = await env_client()
    assert (await client.get("/tensors/none")).status == 404
    assert (await client.post("/tensors/x", data=b"")).status == 400
    assert (await client.post("/tensors/x",
                              data=b"not an npy")).status == 400
    # Unsupported dtype inside a valid npy -> 400, not 500.
    buf = io.BytesIO()
    np.save(buf, np.zeros(4, dtype=np.complex64))
    assert (await client.post("/tensors/x",
                              data=buf.getvalue())).status == 400
    buf = io.BytesIO()
    np.save(buf, np.zeros(4, dtype=np.int8))
    assert (await client.post("/tensors/x?planes=zzz",
                              data=buf.getvalue())).status == 400


async def test_tensor_admission_503(tmp_path, env_client, monkeypatch):
    """QueueFull from the shared scheduler surfaces as 503 +
    Retry-After on the tensor endpoints, the same ladder as every
    other admitted kind (forced via the graftgremlin injection point,
    like the ingest suite does)."""
    from bucketeer_tpu.engine import faults
    from bucketeer_tpu.engine.scheduler import QueueFull

    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    client, _ = await env_client()
    buf = io.BytesIO()
    np.save(buf, np.zeros(8, dtype=np.int8))

    faults.install(faults.FaultPlan().at(
        "sched.submit", lambda: QueueFull(1, 2.5, "tensor"), times=1))
    try:
        resp = await client.post("/tensors/busy", data=buf.getvalue())
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
    finally:
        faults.install(None)