"""deviceaudit: compiled-artifact audit of the jitted codec programs.

graftlint's AST rules reason about *source*; this layer reasons about
what XLA actually compiles. Every registered jitted entry point (the
encode front-end's rows/cxd variants, the standalone sample transform,
the CX/D scan in both its jnp and Pallas-interpret forms, the decode
inverse and windowed region inverse, the compaction gather) is lowered
via ``jax.jit(...).lower(...)`` for a canonical power-of-two bucket
shape — on CPU, no device needed — and the StableHLO text is inspected
for facts the AST cannot see:

- **donation effectiveness** — an arg is donated only if the lowered
  entry carries ``tf.aliasing_output`` on it. JAX/XLA silently drop a
  requested donation whose aval matches no output (dtype or axis-order
  mismatch); the audit fails when a program's *declared* donate spec
  (the ``*_program`` seam each codec module exports) does not lower to
  a real alias, and, symmetrically, flags a donation recorded as
  "unusable" that would in fact alias (stale claim). This is how the
  repo knows its donation story is factual: the front-end and inverse
  donations PR 6 requested were verified dropped here and removed.
- **no host round-trips** — host callbacks (``xla_python_cpu_callback``
  and friends), infeed/outfeed and send/recv inside a device program
  are hard failures. Together with the d2h whitelist this pins the
  fact that device↔host traffic happens only at the sanctioned seams.
- **dtype hygiene** — any ``f64`` tensor type in a lowered program
  fails; ``stablehlo.convert`` churn is recorded in the manifest so
  drift (a new promotion sneaking into a hot program) fails CI.
- **program manifest** — ``.graftaudit-manifest.json`` records, per
  program × bucket, a stable fingerprint (sha256 of the lowered text)
  plus an op histogram. ``--audit`` diffs against the checked-in file
  exactly like ``bench_gate.py`` gates throughput — but statically, on
  every PR, with no device. Regenerate after an intentional change
  with ``python -m bucketeer_tpu.analysis --write-manifest``.

The d2h whitelist validation closes the loop from the other side:
since no audited program transfers mid-flight, every sanctioned name in
``rules_jax.D2H_SANCTIONED`` must still perform an explicit transfer
(``jax.device_get`` / ``np.asarray`` of a device value, or delegate to
another sanctioned function). A whitelisted function that no longer
transfers is reported stale (``stale-d2h-whitelist``, warning).
"""
from __future__ import annotations

import ast
import hashlib
import json
import re
import warnings
from dataclasses import dataclass, field
from pathlib import Path

from . import graftcost
from .findings import ERROR, WARNING, Finding

MANIFEST_NAME = ".graftaudit-manifest.json"

# Relative drift in a modeled cost field (flops / hbm_bytes /
# scan_depth / peak_live_bytes) beyond which the manifest gate fails —
# a kernel-tuning PR that silently doubles modeled HBM traffic fails CI
# here, with no bench run. Small churn (layout jitter, a constant
# folded differently) stays under it.
COST_DRIFT_TOLERANCE = 0.10

DONATION_DROPPED = "audit-donation-dropped"
STALE_DONATION = "audit-stale-donation-claim"
HOST_TRANSFER = "audit-host-transfer"
F64_IN_PROGRAM = "audit-f64"
MANIFEST_DRIFT = "audit-manifest-drift"
STALE_D2H = "stale-d2h-whitelist"

# custom_call targets that round-trip through the host mid-program.
_TRANSFER_CALL_RE = re.compile(
    r"custom_call\s+@([\w.\-]*(?:callback|infeed|outfeed|host_|"
    r"send|recv)[\w.\-]*)", re.IGNORECASE)
_TRANSFER_OP_RE = re.compile(r"\bstablehlo\.(infeed|outfeed|send|recv)\b")
# f64 in a *type* position (tensor<f64> / tensor<4x4xf64>) — a bare
# substring check would false-positive on hex constant payloads.
_F64_RE = re.compile(r"[<x]f64[>]")
_OP_RE = re.compile(r"=\s+\"?([a-z_]+\.[\w]+)")
_ALIAS_RE = re.compile(
    r"%arg(\d+):[^{)%]*\{[^}]*tf\.aliasing_output[^}]*\}")


@dataclass(frozen=True)
class AuditProgram:
    """One registered jitted entry point at one canonical bucket.

    ``build() -> (fn, declared_donate, example_args)`` — the traceable
    callable and donate spec come from the owning module's ``*_program``
    seam, so the lowered artifact is the shipped construction.
    ``probe_donate`` names the argnums the audit *forces* donation on
    to learn whether XLA could alias them; ``donate_reason`` explains
    why probe-only args are not declared: ``"unusable"`` (no matching
    output aval — verified here, and a *stale claim* if the probe ever
    shows an alias) or ``"lifetime"`` (the buffer outlives the launch —
    aliasing legality is irrelevant, never flagged).
    """
    name: str
    build: object
    probe_donate: tuple = (0,)
    donate_reason: str = "unusable"


@dataclass
class ProgramFacts:
    """Lowered-artifact facts for one audited program."""
    name: str
    fingerprint: str = ""
    n_ops: int = 0
    op_counts: dict = field(default_factory=dict)
    declared_donate: tuple = ()
    probe_donate: tuple = ()
    aliased: tuple = ()            # argnums XLA will actually alias
    transfers: tuple = ()          # host round-trip ops found
    f64: bool = False
    text: str = ""                 # lowered StableHLO (for dumps)
    skipped: str = ""              # non-empty: not lowerable here
    donate_reason: str = "unusable"
    cost: object = None            # graftcost.CostFacts (set by
                                   # run_programs; pure fn of ``text``)

    def stale_donation_claim(self) -> bool:
        """True when the probe shows XLA would alias an arg the seam
        records as donation-unusable; "lifetime" buffers are never
        donated on purpose, so aliasing legality is irrelevant."""
        if self.donate_reason != "unusable":
            return False
        return bool(set(self.aliased) - set(self.declared_donate))


def registry() -> list:
    """The canonical audited programs. One entry per (jitted entry
    point, representative bucket); shapes are the smallest power-of-two
    buckets of the shipping tile geometry so CPU lowering stays cheap
    while exercising the same program structure as production."""
    import jax
    import jax.numpy as jnp

    from ..codec import cxd, frontend
    from ..codec.decode import device as ddevice
    from ..codec.pipeline import make_plan, transform_program

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    entries = []

    plan_g = make_plan(64, 64, 1, 2, True, 8)
    p_g = frontend.layout_for(plan_g).P
    plan_c = make_plan(64, 64, 3, 2, False, 8)
    p_c = frontend.layout_for(plan_c).P

    entries.append(AuditProgram(
        "frontend.rows/gray8-lossless-64x64-L2/B1",
        lambda: frontend.frontend_program(plan_g, p_g, "rows")
        + ([sds((1, 64, 64, 1), jnp.int32)],)))
    entries.append(AuditProgram(
        "frontend.rows/rgb8-lossy-64x64-L2/B2",
        lambda: frontend.frontend_program(plan_c, p_c, "rows")
        + ([sds((2, 64, 64, 3), jnp.int32)],)))
    entries.append(AuditProgram(
        "frontend.cxd/gray8-lossless-64x64-L2/B1",
        lambda: frontend.frontend_program(plan_g, p_g, "cxd")
        + ([sds((1, 64, 64, 1), jnp.int32)],)))
    entries.append(AuditProgram(
        "pipeline.transform/gray8-lossless-64x64-L2/B1",
        lambda: transform_program(plan_g)
        + ([sds((1, 64, 64, 1), jnp.int32)],)))

    def cxd_args(n):
        # Block batch + per-block meta + the runtime fixed-point shift
        # (dynamic on purpose: lossless and lossy share one compile).
        return ([sds((n, 64, 64), jnp.int32)]
                + [sds((n,), jnp.int32)] * 5 + [sds((), jnp.int32)])

    entries.append(AuditProgram(
        "cxd.scan/L2/N1",
        lambda: cxd.cxd_program(2, pallas=False) + (cxd_args(1),)))
    entries.append(AuditProgram(
        "cxd.scan.pallas/L2/N1",
        lambda: cxd.cxd_program(2, pallas=True, interpret=True)
        + (cxd_args(1),)))
    # Fused device Tier-1 (BUCKETEER_DEVICE_MQ): CX/D context modeling
    # chained straight into the MQ coder inside one program, so the
    # (N, max_syms) symbol buffer never exists in HBM (the
    # perf-hbm-roundtrip the old two-program chain carried). The MQ
    # half's trip count is the realized symbol cursor — a dynamic
    # while the static cost model reports as unknown_trips rather
    # than a readable depth.
    entries.append(AuditProgram(
        "cxdmq.fused/L2/N1",
        lambda: cxd.fused_program(2, pallas=False) + (cxd_args(1),)))
    entries.append(AuditProgram(
        "cxdmq.fused.pallas/L2/N1",
        lambda: cxd.fused_program(2, pallas=True, interpret=True)
        + (cxd_args(1),)))

    iplan_g = ddevice.make_inverse_plan(64, 64, 1, 2, True, 8, False,
                                        lambda lvl, name: 1.0)
    iplan_c = ddevice.make_inverse_plan(64, 64, 3, 2, False, 8, True,
                                        lambda lvl, name: 0.5)
    entries.append(AuditProgram(
        "decode.inverse/gray8-reversible-64x64-L2/B1",
        lambda: ddevice.inverse_program(iplan_g)
        + ([sds((1, 1, 64, 64), jnp.int32)],)))
    entries.append(AuditProgram(
        "decode.inverse/rgb8-irreversible-64x64-L2/B2",
        lambda: ddevice.inverse_program(iplan_c)
        + ([sds((2, 3, 64, 64), jnp.int32)],)))

    rplan = ddevice.make_region_plan(64, 64, 1, 2, True, 8, False,
                                     lambda lvl, name: 1.0,
                                     16, 48, 16, 48)

    def region_entry():
        fn, donate = ddevice.region_program(
            rplan.levels, rplan.steps, rplan.used_mct, rplan.bitdepth)
        hvs = tuple(sds((1, by1 - by0, bx1 - bx0), jnp.int32)
                    for _, _, by0, by1, bx0, bx1, _ in rplan.slots)
        return fn, donate, [hvs]

    entries.append(AuditProgram(
        "decode.region_inverse/gray8-reversible-64x64-L2/win32",
        region_entry))

    entries.append(AuditProgram(
        "frontend.gather/rows512/chunk4096",
        lambda: frontend.gather_program()
        + ([sds((84, 512), jnp.uint8), sds((4096,), jnp.int64)],),
        probe_donate=(), donate_reason="lifetime"))

    # Compressed-domain tensor delivery (bucketeer_tpu/tensor/): the
    # tensor codec's block packer (the staged limb buffer becomes the
    # HBM-resident CX/D input; donation verified unusable — reshape
    # changes the aval) and the coefficient dequantizer (Tier-1
    # half-magnitudes -> device-resident subband coefficients; input
    # donated on the reversible int32->int32 path, verified dropped on
    # the float32 path). The Tier-1 program the tensor codec chains
    # after the packer is the cxdmq.fused entry above — one program,
    # two workloads.
    from ..tensor import codec as tcodec
    from ..tensor import coeffs as tcoeffs

    entries.append(AuditProgram(
        "tensor.pack/B4",
        lambda: tcodec.pack_program()
        + ([sds((4 * 4096,), jnp.int32)],)))

    def dq_entry(reversible, deltas, shapes):
        def build():
            fn, donate = tcoeffs.dequant_program(reversible, deltas)
            return fn, donate, [sds(s, jnp.int32) for s in shapes]
        return build

    dq_shapes = ((1, 16, 16), (1, 16, 16), (1, 16, 16), (1, 16, 16),
                 (1, 32, 32), (1, 32, 32), (1, 32, 32))
    entries.append(AuditProgram(
        "decode.coeffs.dequant/gray-reversible-L2",
        dq_entry(True, (1.0,) * 7, dq_shapes),
        donate_reason="declared"))
    entries.append(AuditProgram(
        "decode.coeffs.dequant/gray-irreversible-L2",
        dq_entry(False, (0.5,) * 7, dq_shapes)))

    # Batch data plane (bucketeer_tpu/batches/): the merged dequant as
    # the scheduler's _launch_dequant actually runs it — the same
    # program with the group's images stacked along a leading batch
    # axis. Donation carries over verbatim: reversible stays
    # int32->int32 (declared, aliased per band), irreversible drops the
    # alias (float32 outputs match no input aval).
    from ..batches import batch_mesh_program

    def bdq_entry(reversible, deltas, shapes):
        def build():
            fn, donate = batch_mesh_program(reversible, deltas)
            return fn, donate, [sds(s, jnp.int32) for s in shapes]
        return build

    bdq_shapes = tuple((4,) + s for s in dq_shapes)
    entries.append(AuditProgram(
        "batch.assemble.dequant/gray-reversible-L2/B4",
        bdq_entry(True, (1.0,) * 7, bdq_shapes),
        donate_reason="declared"))
    entries.append(AuditProgram(
        "batch.assemble.dequant/gray-irreversible-L2/B4",
        bdq_entry(False, (0.5,) * 7, bdq_shapes)))
    return entries


def lower_program(entry: AuditProgram) -> ProgramFacts:
    """Lower one registered program and extract its artifact facts.
    Donation is forced for ``probe_donate`` args (union with the
    declared spec) so the lowering itself answers "could XLA alias
    this?"; the unusable-donation warning JAX emits for a failed probe
    is expected and silenced."""
    import jax

    facts = ProgramFacts(entry.name)
    try:
        fn, declared, args = entry.build()
        probe = tuple(sorted(set(declared) | set(entry.probe_donate)))
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            lowered = jax.jit(fn, donate_argnums=probe).lower(*args)
            text = lowered.as_text()
    except Exception as exc:  # pragma: no cover - env-dependent
        facts.skipped = f"{type(exc).__name__}: {exc}"
        return facts
    facts.text = text
    facts.fingerprint = hashlib.sha256(text.encode("utf-8")).hexdigest()
    ops: dict = {}
    for m in _OP_RE.finditer(text):
        ops[m.group(1)] = ops.get(m.group(1), 0) + 1
    facts.op_counts = dict(sorted(ops.items()))
    facts.n_ops = sum(ops.values())
    facts.declared_donate = tuple(declared)
    facts.probe_donate = probe
    facts.aliased = tuple(sorted(
        int(m.group(1)) for m in _ALIAS_RE.finditer(text)))
    transfers = [m.group(1) for m in _TRANSFER_CALL_RE.finditer(text)]
    transfers += [m.group(1) for m in _TRANSFER_OP_RE.finditer(text)]
    facts.transfers = tuple(sorted(set(transfers)))
    facts.f64 = bool(_F64_RE.search(text))
    return facts


def check_program(facts: ProgramFacts) -> list:
    """Findings for one program's lowered facts (empty = clean)."""
    loc = f"<deviceaudit:{facts.name}>"
    out = []
    if facts.skipped:
        return out
    for argnum in facts.declared_donate:
        if argnum not in facts.aliased:
            out.append(Finding(
                DONATION_DROPPED, loc, 0,
                f"arg {argnum} is declared donated but the lowered "
                "program carries no tf.aliasing_output for it — XLA "
                "silently dropped the donation (no output matches the "
                "input aval). Fix the program or record the donation "
                "as unusable in its *_program seam", ERROR))
    if facts.stale_donation_claim():
        stale = sorted(set(facts.aliased) - set(facts.declared_donate))
        out.append(Finding(
            STALE_DONATION, loc, 0,
            f"arg(s) {stale} are recorded as donation-unusable but the "
            "lowered program shows XLA would alias them — the claim is "
            "stale; declare the donation and reap the HBM saving",
            WARNING))
    if facts.transfers:
        out.append(Finding(
            HOST_TRANSFER, loc, 0,
            f"host round-trip op(s) inside the device program: "
            f"{list(facts.transfers)} — device programs must ship "
            "results through the sanctioned d2h seams only", ERROR))
    if facts.f64:
        out.append(Finding(
            F64_IN_PROGRAM, loc, 0,
            "f64 tensor type in the lowered program (TPUs emulate f64 "
            "at heavy cost; a silent promotion leaked past the AST "
            "float64-leak rule)", ERROR))
    return out


def run_programs(entries=None) -> list:
    """Lower every registered program; returns [ProgramFacts].

    Clears JAX's global trace/lowering caches first: StableHLO emission
    dedupes private helpers (``@_where`` and friends) by *cached jaxpr
    object identity*, so a warm cache from earlier work in the process
    (e.g. the test suite) can split one shared helper into two
    identical copies and shift every symbol after it — a different
    fingerprint for the same program. Cold caches make the lowering a
    pure function of the registry, matching the fresh-process CLI run
    that generated the checked-in manifest."""
    import jax

    jax.clear_caches()
    out = []
    for entry in (registry() if entries is None else entries):
        facts = lower_program(entry)
        facts.donate_reason = entry.donate_reason
        if not facts.skipped:
            # The static cost model (graftcost) is a pure function of
            # the lowered text; computing it here keeps the manifest's
            # cost fingerprints in lockstep with the structural ones.
            facts.cost = graftcost.cost_program(facts.text, facts.name)
        out.append(facts)
    return out


# --- manifest ------------------------------------------------------------

def manifest_from_facts(all_facts: list) -> dict:
    import jax
    programs = {}
    for f in all_facts:
        if f.skipped:
            continue
        programs[f.name] = {
            "fingerprint": f.fingerprint,
            "n_ops": f.n_ops,
            "convert_ops": f.op_counts.get("stablehlo.convert", 0),
            "donated": list(f.declared_donate),
            "aliased": list(f.aliased),
            "transfers": list(f.transfers),
            "op_counts": f.op_counts,
        }
        if f.cost is not None:
            programs[f.name]["cost"] = f.cost.manifest_entry()
    return {"jax": jax.__version__, "programs": programs}


def load_manifest(path) -> dict | None:
    try:
        return json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError):
        return None


def write_manifest(path, manifest: dict) -> None:
    Path(path).write_text(json.dumps(manifest, indent=2) + "\n",
                          encoding="utf-8")


def _cost_drift(old_cost: dict, new_cost: dict) -> list:
    """Per-field relative drifts beyond COST_DRIFT_TOLERANCE, as
    rendered fragments ("hbm_bytes 1.2e6 -> 2.6e6 (+117%)")."""
    frags = []
    for key in ("flops", "hbm_bytes", "scan_depth", "peak_live_bytes",
                "ici_bytes"):
        a, b = old_cost.get(key), new_cost.get(key)
        if a is None or b is None or a == b:
            continue
        base = max(abs(a), 1)
        rel = (b - a) / base
        if abs(rel) > COST_DRIFT_TOLERANCE:
            frags.append(f"{key} {a:g} -> {b:g} ({rel:+.0%})")
    return frags


def diff_manifest(old: dict | None, new: dict, skipped=()) -> list:
    """Human-readable drift lines between the checked-in manifest and
    the freshly lowered one (empty = no drift). Programs named in
    ``skipped`` (not lowerable in this environment) are ignored;
    everything else — fingerprint changes, op-count deltas,
    added/removed programs — is drift. A JAX version change is reported
    as one actionable line instead of a wall of per-program fingerprint
    noise: the lowered text is version-specific by construction.

    Modeled-cost drift gets the same one-actionable-line treatment: a
    program whose cost fingerprint (flops / HBM bytes / scan depth /
    peak live bytes) moved beyond COST_DRIFT_TOLERANCE is reported as
    *what got more expensive and by how much* — the perf-regression
    gate that works without a bench run — instead of (or ahead of) the
    raw op-count delta."""
    if old is None:
        return [f"no checked-in manifest: {len(new['programs'])} "
                "program(s) unaccounted — regenerate with "
                "--write-manifest and commit it"]
    if old.get("jax") != new.get("jax"):
        return [f"manifest was generated under jax {old.get('jax')} but "
                f"this environment runs jax {new.get('jax')} — lowered "
                "programs are version-specific; regenerate with "
                "--write-manifest under the CI jax version and review "
                "the op-count deltas in the diff"]
    lines = []
    olds, news = old.get("programs", {}), new["programs"]
    for name in sorted(set(olds) - set(news) - set(skipped)):
        lines.append(f"{name}: in the manifest but no longer lowered "
                     "(registry entry removed?)")
    for name in sorted(set(news) - set(olds)):
        lines.append(f"{name}: lowered but absent from the manifest "
                     "(new program — regenerate the manifest)")
    for name in sorted(set(news) & set(olds)):
        o, n = olds[name], news[name]
        cost_frags = _cost_drift(o.get("cost", {}), n.get("cost", {}))
        if cost_frags:
            # The actionable line: what got more expensive, by how
            # much, against the tolerance — one line per program.
            lines.append(
                f"{name}: modeled cost drifted beyond "
                f"{COST_DRIFT_TOLERANCE:.0%} ({'; '.join(cost_frags)})"
                " — a perf-relevant compiled-program change; if "
                "intentional, regenerate with --write-manifest and "
                "justify the new cost in review")
            continue
        if o.get("fingerprint") == n["fingerprint"]:
            continue
        deltas = []
        oc, nc = o.get("op_counts", {}), n["op_counts"]
        for op in sorted(set(oc) | set(nc)):
            a, b = oc.get(op, 0), nc.get(op, 0)
            if a != b:
                deltas.append(f"{op} {a}->{b}")
        detail = ("; ".join(deltas[:8]) if deltas
                  else "same op counts, different structure")
        lines.append(f"{name}: compiled program drifted "
                     f"({o.get('n_ops')} -> {n['n_ops']} ops: {detail}"
                     "; modeled cost within tolerance)")
    return lines


# --- d2h whitelist validation --------------------------------------------

_TRANSFER_FUNCS = {"device_get", "asarray", "array", "copy_to_host"}


def _calls_in(fnode: ast.AST):
    for node in ast.walk(fnode):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                yield f.attr
            elif isinstance(f, ast.Name):
                yield f.id


def validate_d2h_whitelist(project) -> list:
    """Cross-check rules_jax.D2H_SANCTIONED against the code: every
    sanctioned name must still *perform* a device->host transfer
    (jax.device_get / np.asarray of a device value) or delegate to
    another sanctioned name. The audited programs contain no in-flight
    transfers (see check_program), so these seams are, verifiably, the
    only places bytes cross — an entry that stopped transferring is a
    stale hole in the d2h fence."""
    from .rules_jax import D2H_SANCTIONED, D2H_SCOPES

    defs: dict = {}
    for mod in project.modules:
        parts = mod.relpath.split("/")
        if not any(p in parts for p in D2H_SCOPES):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in D2H_SANCTIONED:
                defs.setdefault(node.name, []).append((mod, node))

    findings = []
    for name in sorted(D2H_SANCTIONED):
        sites = defs.get(name)
        if not sites:
            findings.append(Finding(
                STALE_D2H, "bucketeer_tpu/analysis/rules_jax.py", 1,
                f"d2h whitelist entry '{name}' matches no function in "
                "the codec/parallel layers — remove it from "
                "D2H_SANCTIONED", WARNING))
            continue
        for mod, node in sites:
            called = set(_calls_in(node))
            if called & _TRANSFER_FUNCS or called & (D2H_SANCTIONED
                                                    - {name}):
                continue
            findings.append(Finding(
                STALE_D2H, mod.relpath, node.lineno,
                f"d2h whitelist entry '{name}' no longer performs a "
                "device->host transfer (no jax.device_get / np.asarray "
                "and no call into another sanctioned seam) — stale "
                "whitelist entries widen the fence for free",
                WARNING, mod.source_line(node.lineno)))
    return findings


# --- the full audit ------------------------------------------------------

def run_audit(manifest_path, package_root=None, dump_dir=None,
              facts=None):
    """Lower + verify every registered program, validate the d2h
    whitelist, and diff the manifest. Returns (findings, manifest,
    facts). ``facts`` accepts a precomputed ``run_programs()`` result
    so a CLI run combining ``--audit`` with ``--cost`` lowers the
    registry once. On any program-level failure with ``dump_dir`` set,
    the lowered text of every program is written there for the CI
    artifact upload."""
    from .lint import load_project

    all_facts = run_programs() if facts is None else facts
    findings = []
    for facts in all_facts:
        findings += check_program(facts)
    lowered = [f for f in all_facts if not f.skipped]
    if len(lowered) < 3:
        findings.append(Finding(
            MANIFEST_DRIFT, "<deviceaudit>", 0,
            f"only {len(lowered)} program(s) lowered — the audit "
            "needs the registry to cover the jitted entry points "
            f"(skipped: {[f.name for f in all_facts if f.skipped]})",
            ERROR))
    manifest = manifest_from_facts(all_facts)
    for line in diff_manifest(
            load_manifest(manifest_path), manifest,
            skipped=tuple(f.name for f in all_facts if f.skipped)):
        findings.append(Finding(MANIFEST_DRIFT, str(manifest_path), 0,
                                line, ERROR))
    if package_root is not None:
        findings += validate_d2h_whitelist(load_project(Path(package_root)))
    if findings and dump_dir:
        dump = Path(dump_dir)
        dump.mkdir(parents=True, exist_ok=True)
        for facts in all_facts:
            if facts.text:
                safe = re.sub(r"[^\w.\-]", "_", facts.name)
                (dump / f"{safe}.stablehlo.txt").write_text(
                    facts.text, encoding="utf-8")
    return findings, manifest, all_facts
