"""Batched Tier-1 dispatch: native C++ thread pool when available, pure
Python fallback otherwise (reference analog: ConverterFactory probing for
Kakadu and falling back, converters/ConverterFactory.java:37-47).

The whole image's code-blocks go through one call so the native thread
pool sees the full parallelism (blocks are independent — SURVEY.md §7).
"""
from __future__ import annotations

import os

import numpy as np

from .. import native
from ..analysis.contracts import contract
from . import t1

_BAND_CLS = t1.BAND_CLS        # single source of the band-class table

# Concurrency bookkeeping for the native dispatch. ctypes releases the
# GIL for the duration of every CDLL call (only PyDLL keeps it), so the
# encoder's host-coding worker genuinely overlaps the main thread's
# device dispatch — tests/test_native_t1.py proves it by running Python
# work concurrently with a native call. Each native entry records the
# thread-pool size it fanned out to so sizing regressions (e.g. an env
# override silently pinning the pool to 1) are observable.
last_native_call: dict = {}


def _note_call(fn: str, n_blocks: int, threads: int) -> None:
    last_native_call.update(fn=fn, n_blocks=n_blocks, threads=threads)


def default_threads() -> int:
    env = os.environ.get("BUCKETEER_T1_THREADS")
    if env:
        return max(1, int(env))
    return max(1, (os.cpu_count() or 2) - 1)


def _collect(lib, handle, n: int) -> list:
    """Pull a native T1Result handle into [t1.CodedBlock]."""
    try:
        nbps = np.zeros(n, dtype=np.int32)
        npasses = np.zeros(n, dtype=np.int32)
        nbytes = np.zeros(n, dtype=np.int64)
        lib.t1_block_sizes(handle, nbps.ctypes.data, npasses.ctypes.data,
                           nbytes.ctypes.data)
        out = []
        for i in range(n):
            np_i, nb_i = int(npasses[i]), int(nbytes[i])
            data = np.empty(max(nb_i, 1), dtype=np.uint8)
            ptype = np.zeros(max(np_i, 1), dtype=np.int32)
            pplane = np.zeros(max(np_i, 1), dtype=np.int32)
            plen = np.zeros(max(np_i, 1), dtype=np.int64)
            pdist = np.zeros(max(np_i, 1), dtype=np.float64)
            lib.t1_block_get(handle, i, data.ctypes.data, ptype.ctypes.data,
                             pplane.ctypes.data, plen.ctypes.data,
                             pdist.ctypes.data)
            passes = [t1.PassInfo(int(ptype[k]), int(pplane[k]),
                                  int(plen[k]), float(pdist[k]))
                      for k in range(np_i)]
            out.append(t1.CodedBlock(bytes(data[:nb_i].tobytes()),
                                     int(nbps[i]), passes))
        return out
    finally:
        lib.t1_result_free(handle)


@contract(shapes={"payload": ("R", 512), "offsets": ("n1",),
                  "nbps": ("n",), "floors": ("n",), "hs": ("n",),
                  "ws": ("n",)},
          dtypes={"payload": "uint8", "offsets": "integer",
                  "nbps": "integer", "floors": "integer",
                  "hs": "integer", "ws": "integer"})
def encode_packed(payload: np.ndarray, offsets: np.ndarray,
                  nbps: np.ndarray, floors: np.ndarray,
                  hs: np.ndarray, ws: np.ndarray,
                  bands: list) -> list:
    """Tier-1 over the device front-end's packed bitmap payload
    (codec/frontend.py): payload (R, 512) uint8 rows, offsets (n+1,)
    row offsets per block, per-block nbps/floors/dims and band names.
    Returns [t1.CodedBlock] in block order."""
    n = len(nbps)
    lib = native.load()
    cls = np.array([_BAND_CLS[b] for b in bands], dtype=np.int32)
    if lib is not None and n:
        # Bind every converted array to a local: .ctypes.data of an
        # unnamed temporary is a dangling pointer by call time.
        payload = np.ascontiguousarray(payload, dtype=np.uint8)
        offs = np.ascontiguousarray(offsets[:n], dtype=np.int64)
        nbps_c = np.ascontiguousarray(nbps, dtype=np.int32)
        floors_c = np.ascontiguousarray(floors, dtype=np.int32)
        hs_c = np.ascontiguousarray(hs, dtype=np.int32)
        ws_c = np.ascontiguousarray(ws, dtype=np.int32)
        threads = default_threads()
        _note_call("t1_encode_packed", n, threads)
        handle = lib.t1_encode_packed(
            n, payload.ctypes.data, offs.ctypes.data, nbps_c.ctypes.data,
            floors_c.ctypes.data, hs_c.ctypes.data, ws_c.ctypes.data,
            cls.ctypes.data, threads)
        return _collect(lib, handle, n)
    out = []
    for i in range(n):
        if nbps[i] <= floors[i]:
            out.append(t1.CodedBlock(b"", 0))
            continue
        from . import frontend
        mags, negs = frontend.unpack_block(payload, int(offsets[i]),
                                           int(nbps[i]), int(floors[i]),
                                           int(hs[i]), int(ws[i]))
        out.append(t1.encode_block(mags, negs, bands[i],
                                   floor=int(floors[i])))
    return out


def encode_blocks(specs: list) -> list:
    """specs: [(mags uint32 (h,w), signs bool (h,w), band_name,
    fracs uint8 (h,w) | None)] -> [t1.CodedBlock] in order."""
    lib = native.load()
    if lib is None or not specs:
        return [t1.encode_block(m, s, b, f) for m, s, b, f in specs]

    n = len(specs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    hs = np.zeros(n, dtype=np.int32)
    ws = np.zeros(n, dtype=np.int32)
    cls = np.zeros(n, dtype=np.int32)
    any_fracs = any(f is not None for _, _, _, f in specs)
    for i, (m, _, band, _) in enumerate(specs):
        hs[i], ws[i] = m.shape
        cls[i] = _BAND_CLS[band]
        offsets[i + 1] = offsets[i] + m.size
    total = int(offsets[-1])
    mags = np.empty(total, dtype=np.uint32)
    negs = np.empty(total, dtype=np.uint8)
    fracs = np.zeros(total, dtype=np.uint8) if any_fracs else None
    for i, (m, s, _, f) in enumerate(specs):
        mags[offsets[i]:offsets[i + 1]] = np.ascontiguousarray(
            m, dtype=np.uint32).ravel()
        negs[offsets[i]:offsets[i + 1]] = np.ascontiguousarray(
            s, dtype=np.uint8).ravel()
        if f is not None:
            fracs[offsets[i]:offsets[i + 1]] = np.ascontiguousarray(
                f, dtype=np.uint8).ravel()

    threads = default_threads()
    _note_call("t1_encode_blocks", n, threads)
    handle = lib.t1_encode_blocks(
        n, mags.ctypes.data, negs.ctypes.data,
        fracs.ctypes.data if fracs is not None else None,
        offsets.ctypes.data,
        hs.ctypes.data, ws.ctypes.data, cls.ctypes.data, threads)
    return _collect(lib, handle, n)


def encode_cxd(streams) -> list:
    """MQ replay of precomputed device CX/D streams (codec/cxd.py) —
    the host half of the BUCKETEER_DEVICE_CXD Tier-1 split. Native
    thread pool when available, pure-Python MQEncoder replay otherwise.
    Returns [t1.CodedBlock] in block order, byte-identical to what
    encode_packed would have produced from the same coefficients."""
    from . import cxd

    n = len(streams.nbps)
    lib = native.load()
    if lib is not None and n:
        payload = np.ascontiguousarray(streams.payload, dtype=np.uint8)
        row_offs = np.ascontiguousarray(streams.row_offsets,
                                        dtype=np.int64)
        nbps_c = np.ascontiguousarray(streams.nbps, dtype=np.int32)
        p_offs = np.ascontiguousarray(streams.pass_offsets,
                                      dtype=np.int64)
        p_types = np.ascontiguousarray(streams.pass_types, dtype=np.int32)
        p_planes = np.ascontiguousarray(streams.pass_planes,
                                        dtype=np.int32)
        p_nsyms = np.ascontiguousarray(streams.pass_nsyms, dtype=np.int32)
        p_dists = np.ascontiguousarray(streams.pass_dists,
                                       dtype=np.float64)
        threads = default_threads()
        _note_call("t1_encode_cxd", n, threads)
        handle = lib.t1_encode_cxd(
            n, payload.ctypes.data, row_offs.ctypes.data,
            nbps_c.ctypes.data, p_offs.ctypes.data, p_types.ctypes.data,
            p_planes.ctypes.data, p_nsyms.ctypes.data,
            p_dists.ctypes.data, threads)
        return _collect(lib, handle, n)

    out = []
    for b in range(n):
        p0, p1 = int(streams.pass_offsets[b]), int(
            streams.pass_offsets[b + 1])
        n_syms = int(streams.pass_nsyms[p0:p1].sum())
        start = int(streams.row_offsets[b])
        n_rows = -(-n_syms // cxd.SYMS_PER_ROW)
        syms = cxd.unpack6(streams.payload[start:start + n_rows], n_syms)
        out.append(cxd.replay_block(
            syms, int(streams.nbps[b]), p1 - p0, streams.pass_types[p0:p1],
            streams.pass_planes[p0:p1], streams.pass_nsyms[p0:p1],
            streams.pass_dists[p0:p1]))
    return out
