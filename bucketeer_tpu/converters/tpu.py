"""The in-process TPU converter — the component the reference outsources
to the Kakadu binary (reference: converters/KakaduConverter.java:55-77).

Emits the reference's full Kakadu recipe (reference:
KakaduConverter.java:38-44): ``Clevels=6 Clayers=6
Cprecincts={256,256},{256,256},{128,128} Stiles={512,512} Corder=RPCL
ORGgen_plt=yes ORGtparts=R Cblk={64,64} Cuse_sop=yes Cuse_eph=yes``;
lossless = reversible 5/3 + RCT (``Creversible=yes -rate -``), lossy =
irreversible 9/7 + ICT with PCRD-opt truncation to 3 bpp (``-rate 3``).
"""
from __future__ import annotations

import os

from ..codec import tiff
from ..codec.encoder import EncodeParams, encode_jp2
from .base import Conversion, ConverterError, output_path

LOSSY_RATE = 3.0    # reference: -rate 3 (KakaduConverter.java:43)


class TpuConverter:
    """JPEG 2000 encoding on the local TPU/accelerator via the JAX codec."""

    name = "TPU"

    def __init__(self, lossy_rate: float = LOSSY_RATE,
                 jpx: bool = True) -> None:
        self.lossy_rate = lossy_rate
        self.jpx = jpx

    def convert(self, image_id: str, source_path: str,
                conversion: Conversion = Conversion.LOSSLESS) -> str:
        if not os.path.exists(source_path):
            raise ConverterError(f"source not found: {source_path}")
        try:
            img, bitdepth = tiff.read_image(source_path)
        except Exception as exc:
            raise ConverterError(
                f"cannot read {source_path}: {exc}") from exc

        h, w = img.shape[:2]
        params = EncodeParams.kakadu_recipe(
            lossless=conversion == Conversion.LOSSLESS,
            rate=self.lossy_rate)
        # Tiny images can't sustain 6 levels; clamp like encoders do.
        while params.levels > 1 and (min(h, w) >> params.levels) < 4:
            params.levels -= 1
        if max(h, w) <= params.tile_size:
            params.tile_size = None         # single tile, like kdu untiled
        # The base step is calibrated for 8-bit signals; scale it with
        # the signal range so deeper scans quantize proportionally.
        params.base_delta *= (1 << (bitdepth - 8))
        try:
            data = encode_jp2(img, bitdepth, params, jpx=self.jpx)
        except Exception as exc:
            raise ConverterError(
                f"encode failed for {image_id}: {exc}") from exc

        dest = output_path(image_id, ".jpx" if self.jpx else ".jp2")
        # Unique temp name: concurrent converts of the same id must not
        # interleave writes before the atomic replace.
        tmp = f"{dest}.{os.getpid()}.{id(data):x}.part"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, dest)
        return dest
