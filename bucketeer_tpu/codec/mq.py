"""MQ arithmetic coder (JPEG 2000 Part 1 / ITU-T T.800, Annex C).

The binary adaptive arithmetic coder at the heart of EBCOT Tier-1 — the
innermost loop of the encode the reference delegates to Kakadu
(reference: converters/AbstractConverter.java:29-39 shells out; SURVEY.md
§7 ranks this the #1 hard part). This module is the *reference
implementation* used for unit tests and as the ground truth the native
C++ coder (bucketeer_tpu/native) must match bit-exactly; production
encoding runs the C++ path over many code-blocks in parallel.

Includes both encoder and decoder: the decoder exists so tests can prove
round-trips without external tools (PIL/OpenJPEG validates full
codestreams separately).
"""
from __future__ import annotations

# State-transition table, T.800 Table C.2: (Qe, NMPS, NLPS, SWITCH).
QE_TABLE = (
    (0x5601, 1, 1, 1), (0x3401, 2, 6, 0), (0x1801, 3, 9, 0),
    (0x0AC1, 4, 12, 0), (0x0521, 5, 29, 0), (0x0221, 38, 33, 0),
    (0x5601, 7, 6, 1), (0x5401, 8, 14, 0), (0x4801, 9, 14, 0),
    (0x3801, 10, 14, 0), (0x3001, 11, 17, 0), (0x2401, 12, 18, 0),
    (0x1C01, 13, 20, 0), (0x1601, 29, 21, 0), (0x5601, 15, 14, 1),
    (0x5401, 16, 14, 0), (0x5101, 17, 15, 0), (0x4801, 18, 16, 0),
    (0x3801, 19, 17, 0), (0x3401, 20, 18, 0), (0x3001, 21, 19, 0),
    (0x2801, 22, 19, 0), (0x2401, 23, 20, 0), (0x2201, 24, 21, 0),
    (0x1C01, 25, 22, 0), (0x1801, 26, 23, 0), (0x1601, 27, 24, 0),
    (0x1401, 28, 25, 0), (0x1201, 29, 26, 0), (0x1101, 30, 27, 0),
    (0x0AC1, 31, 28, 0), (0x09C1, 32, 29, 0), (0x08A1, 33, 30, 0),
    (0x0521, 34, 31, 0), (0x0441, 35, 32, 0), (0x02A1, 36, 33, 0),
    (0x0221, 37, 34, 0), (0x0141, 38, 35, 0), (0x0111, 39, 36, 0),
    (0x0085, 40, 37, 0), (0x0049, 41, 38, 0), (0x0025, 42, 39, 0),
    (0x0015, 43, 40, 0), (0x0009, 44, 41, 0), (0x0005, 45, 42, 0),
    (0x0001, 45, 43, 0), (0x5601, 46, 46, 0),
)

N_CONTEXTS = 19
# Initial context states (T.800 Table D.7): UNIFORM=46, RL=3, ZC ctx0=4.
CTX_UNIFORM = 18
CTX_RL = 17


def initial_states():
    idx = [0] * N_CONTEXTS
    idx[0] = 4          # the all-zero-neighborhood ZC context
    idx[CTX_RL] = 3
    idx[CTX_UNIFORM] = 46
    return idx


class MQEncoder:
    """Spec Annex C.2 encoder (software conventions: leading dummy byte)."""

    def __init__(self) -> None:
        self.a = 0x8000
        self.c = 0
        self.ct = 12
        self.buf = bytearray([0])  # buf[0] is the dummy pre-byte
        self.ctx_idx = initial_states()
        self.ctx_mps = [0] * N_CONTEXTS

    def encode(self, bit: int, ctx: int) -> None:
        idx = self.ctx_idx[ctx]
        qe, nmps, nlps, switch = QE_TABLE[idx]
        if bit == self.ctx_mps[ctx]:
            self.a -= qe
            if (self.a & 0x8000) == 0:
                if self.a < qe:
                    self.a = qe
                else:
                    self.c += qe
                self.ctx_idx[ctx] = nmps
                self._renorm()
            else:
                self.c += qe
        else:
            self.a -= qe
            if self.a < qe:
                self.c += qe
            else:
                self.a = qe
            if switch:
                self.ctx_mps[ctx] ^= 1
            self.ctx_idx[ctx] = nlps
            self._renorm()

    def _renorm(self) -> None:
        while True:
            self.a = (self.a << 1) & 0xFFFF
            self.c = (self.c << 1) & 0xFFFFFFFF
            self.ct -= 1
            if self.ct == 0:
                self._byteout()
            if self.a & 0x8000:
                break

    def _byteout(self) -> None:
        if self.buf[-1] == 0xFF:
            self.buf.append((self.c >> 20) & 0xFF)
            self.c &= 0xFFFFF
            self.ct = 7
        elif self.c < 0x8000000:
            self.buf.append((self.c >> 19) & 0xFF)
            self.c &= 0x7FFFF
            self.ct = 8
        else:
            self.buf[-1] += 1
            if self.buf[-1] == 0xFF:
                self.c &= 0x7FFFFFF
                self.buf.append((self.c >> 20) & 0xFF)
                self.c &= 0xFFFFF
                self.ct = 7
            else:
                self.buf.append((self.c >> 19) & 0xFF)
                self.c &= 0x7FFFF
                self.ct = 8

    def n_bytes(self) -> int:
        """Bytes emitted so far (without flush)."""
        return len(self.buf) - 1

    def truncation_length(self) -> int:
        """Conservative prefix length sufficient to decode everything
        encoded so far (used for layer truncation points between
        non-terminated passes)."""
        return len(self.buf) - 1 + 4

    def flush(self) -> bytes:
        tempc = self.c + self.a
        self.c |= 0xFFFF
        if self.c >= tempc:
            self.c -= 0x8000
        self.c = (self.c << self.ct) & 0xFFFFFFFF
        self._byteout()
        self.c = (self.c << self.ct) & 0xFFFFFFFF
        self._byteout()
        out = self.buf[1:]
        if out and out[-1] == 0xFF:
            out = out[:-1]
        return bytes(out)


class MQDecoder:
    """Spec Annex C.3 decoder (for round-trip tests)."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.bp = 0
        self.ctx_idx = initial_states()
        self.ctx_mps = [0] * N_CONTEXTS
        b = self._byte(0)
        self.c = b << 16
        self._bytein()
        self.c = (self.c << 7) & 0xFFFFFFFF
        self.ct -= 7
        self.a = 0x8000

    def _byte(self, i: int) -> int:
        return self.data[i] if i < len(self.data) else 0xFF

    def _bytein(self) -> None:
        if self._byte(self.bp) == 0xFF:
            if self._byte(self.bp + 1) > 0x8F:
                self.c += 0xFF00
                self.ct = 8
            else:
                self.bp += 1
                self.c += self._byte(self.bp) << 9
                self.ct = 7
        else:
            self.bp += 1
            self.c += self._byte(self.bp) << 8
            self.ct = 8

    def decode(self, ctx: int) -> int:
        idx = self.ctx_idx[ctx]
        qe, nmps, nlps, switch = QE_TABLE[idx]
        self.a -= qe
        if ((self.c >> 16) & 0xFFFF) < qe:
            # LPS exchange path
            if self.a < qe:
                d = self.ctx_mps[ctx]
                self.ctx_idx[ctx] = nmps
            else:
                d = 1 - self.ctx_mps[ctx]
                if switch:
                    self.ctx_mps[ctx] ^= 1
                self.ctx_idx[ctx] = nlps
            self.a = qe
            self._renorm()
        else:
            self.c -= qe << 16
            if (self.a & 0x8000) == 0:
                # MPS exchange path
                if self.a < qe:
                    d = 1 - self.ctx_mps[ctx]
                    if switch:
                        self.ctx_mps[ctx] ^= 1
                    self.ctx_idx[ctx] = nlps
                else:
                    d = self.ctx_mps[ctx]
                    self.ctx_idx[ctx] = nmps
                self._renorm()
            else:
                d = self.ctx_mps[ctx]
        return d

    def _renorm(self) -> None:
        while True:
            if self.ct == 0:
                self._bytein()
            self.a = (self.a << 1) & 0xFFFF
            self.c = (self.c << 1) & 0xFFFFFFFF
            self.ct -= 1
            if self.a & 0x8000:
                break
