"""Static SPMD/collective audit (analysis/graftmesh.py): the mesh
registry lowers and partitions under the forced 8-device host mesh,
the collective parser prices exact bytes with the ring model, the
shard-* rules fire on seeded violations exactly once, and the mesh
manifest gate fails on doubled modeled ICI traffic while layout
jitter under the tolerance passes.

The expensive part — partitioning every registered mesh program —
runs once per session (the mesh_facts subprocess fixture) and only in
the tests marked ``slow``: tier-1 keeps the parsers, the drift-gate
semantics (synthetic section) and the seeded violations, while the
``shard-audit`` CI job runs this file unfiltered. Seeded violations
lower tiny synthetic programs in-process, which works because
conftest.py starts this interpreter under
``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""
import json
import shutil
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from bucketeer_tpu.analysis import deviceaudit, graftmesh, rules_shard
from bucketeer_tpu.analysis.__main__ import main as cli_main

REPO = Path(__file__).resolve().parent.parent
MANIFEST = REPO / ".graftaudit-manifest.json"
BASELINE = REPO / ".graftlint-baseline.json"


def _lowered(mesh_facts):
    return [f for f in mesh_facts if not f.skipped]


# --- the ring model and HLO parsers ------------------------------------

def test_ring_model_bytes():
    assert graftmesh.ring_ici_bytes("collective-permute", 100, 8) == 100
    assert graftmesh.ring_ici_bytes("all-gather", 100, 8) == 700
    assert graftmesh.ring_ici_bytes("all-reduce", 100, 8) == 175
    assert graftmesh.ring_ici_bytes("reduce-scatter", 100, 8) == 87
    assert graftmesh.ring_ici_bytes("all-to-all", 100, 8) == 87
    # A group of one moves nothing (permute is point-to-point: it
    # still pays its operand).
    assert graftmesh.ring_ici_bytes("all-gather", 100, 1) == 0


def test_parse_collectives_iota_literal_and_async_forms():
    hlo = "\n".join([
        # Iota replica_groups [num_groups, group_size].
        "  %ag = f32[8,16]{1,0} all-gather(f32[1,16]{1,0} %p), "
        "channel_id=1, replica_groups=[1,8]<=[8], dimensions={0}",
        # Async pair with literal groups of two: the -start carries the
        # operand, the -done must NOT double-count.
        "  %ar-s = f32[4]{0} all-reduce-start(f32[4]{0} %x), "
        "replica_groups={{0,1},{2,3}}, to_apply=%add",
        "  %ar-d = f32[4]{0} all-reduce-done(f32[4]{0} %ar-s)",
        # No replica_groups attribute: the full mesh.
        "  %cp = s32[64]{0} collective-permute(s32[64]{0} %y), "
        "source_target_pairs={{0,1},{1,2}}",
    ])
    got = graftmesh.parse_collectives(hlo, n_devices=8)
    assert got["all-gather"] == {"count": 1, "bytes_in": 64,
                                 "ici_bytes": 64 * 7}
    assert got["all-reduce"] == {"count": 1, "bytes_in": 16,
                                 "ici_bytes": 2 * 16 * 1 // 2}
    assert got["collective-permute"] == {"count": 1, "bytes_in": 256,
                                         "ici_bytes": 256}


def test_parse_replicated_params_ignores_sharded_ones():
    hlo = "\n".join([
        "  %p0 = f32[8,64]{1,0} parameter(0), "
        "sharding={devices=[8,1]<=[8]}",
        "  %p1 = f32[1024]{0} parameter(1), sharding={replicated}",
        "  %p2 = s32[] parameter(2), sharding={replicated}",
    ])
    assert graftmesh.parse_replicated_params(hlo) == ((1, 4096), (2, 4))


# --- the registry on the real sharded programs -------------------------

@pytest.mark.slow
def test_registry_lowers_at_least_three_mesh_programs(mesh_facts):
    lowered = _lowered(mesh_facts)
    assert len(lowered) >= 3, [f.skipped for f in mesh_facts]
    families = {f.name.split("/")[0] for f in lowered}
    # Every sharded execution path the encoder ships is represented.
    assert {"shard.dwt.tile", "shard.transform.data",
            "shard.cxdmq.fused.data"} <= families


@pytest.mark.slow
def test_dwt_halo_exchange_is_the_only_collective(mesh_facts):
    """The row-sharded DWT declares exactly its halo exchange: two
    ppermutes per level x two levels, and nothing else."""
    dwt = [f for f in _lowered(mesh_facts)
           if f.name.startswith("shard.dwt.tile/")]
    assert dwt
    for f in dwt:
        assert set(f.collectives) == {"collective-permute"}, f.name
        assert f.collectives["collective-permute"]["count"] == 4, f.name
        assert f.ici_bytes > 0


@pytest.mark.slow
def test_data_parallel_programs_are_collective_free(mesh_facts):
    """Tiles/blocks on the data axis are independent — a clean
    partition has zero collectives; anything else is the routing bug
    this audit exists to catch."""
    data = [f for f in _lowered(mesh_facts)
            if f.name.split("/")[0].endswith(".data")]
    assert data
    for f in data:
        assert f.collectives == {}, (f.name, f.collectives)
        assert f.ici_bytes == 0


@pytest.mark.slow
def test_mesh_facts_are_fully_populated(mesh_facts):
    for f in _lowered(mesh_facts):
        assert f.peak_live_bytes > 0, f.name
        assert len(f.fingerprint) == 64, f.name
        n = 1
        for size in f.mesh_shape.values():
            n *= size
        assert n == graftmesh.MESH_DEVICES, (f.name, f.mesh_shape)
        assert f.axes_used, f.name
        # The comms term reached the roofline input.
        assert f.cost is not None and f.cost.ici_bytes == f.ici_bytes


@pytest.mark.slow
def test_repo_mesh_programs_are_rule_clean(mesh_facts):
    findings = rules_shard.run(mesh_facts)
    assert findings == [], "\n".join(f.render() for f in findings)


@pytest.mark.slow
def test_checked_in_manifest_matches_mesh_programs(mesh_facts):
    drift = graftmesh.diff_mesh_manifest(
        deviceaudit.load_manifest(MANIFEST),
        graftmesh.mesh_manifest_from_facts(mesh_facts))
    assert drift == [], ("sharded programs drifted; regenerate with "
                         "`python -m bucketeer_tpu.analysis "
                         "--mesh-audit --write-manifest` and commit "
                         "the diff:\n" + "\n".join(drift))


# --- seeded violations, lowered in-process -----------------------------

def test_seeded_implicit_allgather_fires_exactly_once():
    """A sharding-constraint mismatch — input sharded over data, body
    pinned replicated — makes GSPMD reshard 8 MB over the
    interconnect; shard-implicit-allgather must fire, once."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bucketeer_tpu.parallel.mesh import batch_sharding, make_mesh

    mesh = make_mesh(tile_parallel=1)

    def build():
        def forced(x):
            y = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P()))
            return y * 2
        return (forced, (batch_sharding(mesh),),
                [jax.ShapeDtypeStruct((8, 512, 512), jnp.float32)])

    facts = graftmesh.lower_mesh_program(
        graftmesh.MeshProgram("synthetic/allgather", build))
    assert not facts.skipped, facts.skipped
    cell = facts.collectives.get("all-gather")
    assert cell and cell["ici_bytes"] >= rules_shard.ALLGATHER_MIN_BYTES
    findings = rules_shard.run([facts])
    assert [f.rule for f in findings] == [
        rules_shard.SHARD_IMPLICIT_ALLGATHER]
    assert "all-gather" in findings[0].message


def test_seeded_replicated_large_operand_fires_exactly_once():
    """A 100 MB operand left fully replicated costs every device the
    global array; shard-replicated-large must fire, once — while the
    registry's 4-byte replicated scalars stay under the threshold."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bucketeer_tpu.parallel.mesh import batch_sharding, make_mesh

    mesh = make_mesh(tile_parallel=1)

    def build():
        def apply(x, table):
            return x + table[0]
        ins = (batch_sharding(mesh), NamedSharding(mesh, P()))
        return apply, ins, [
            jax.ShapeDtypeStruct((8, 64), jnp.float32),
            jax.ShapeDtypeStruct((25_000_000,), jnp.float32)]

    facts = graftmesh.lower_mesh_program(
        graftmesh.MeshProgram("synthetic/replicated", build))
    assert not facts.skipped, facts.skipped
    assert (1, 100_000_000) in facts.replicated_args
    findings = rules_shard.run([facts])
    assert [f.rule for f in findings] == [
        rules_shard.SHARD_REPLICATED_LARGE]
    assert "operand 1" in findings[0].message


def test_seeded_dead_mesh_axis_fires_exactly_once():
    """A 4x2 mesh whose program shards only over 'data' leaves the
    2-device 'tile' axis idle; shard-axis-dead must fire, once."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bucketeer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tile_parallel=2)

    def build():
        return (lambda x: x * 2,
                (NamedSharding(mesh, P("data")),),
                [jax.ShapeDtypeStruct((8, 64), jnp.float32)])

    facts = graftmesh.lower_mesh_program(
        graftmesh.MeshProgram("synthetic/deadaxis", build))
    assert not facts.skipped, facts.skipped
    assert facts.mesh_shape == {"data": 4, "tile": 2}
    assert facts.axes_used == ("data",)
    findings = rules_shard.run([facts])
    assert [f.rule for f in findings] == [rules_shard.SHARD_AXIS_DEAD]
    assert "'tile'" in findings[0].message


# --- the mesh manifest drift gate --------------------------------------
# Pure-function tests on a hand-built mesh section shaped exactly like
# mesh_manifest_from_facts output — no lowering, so tier-1 keeps the
# gate semantics without paying the session fixture.

def _synth_section():
    return {
        "shard.a.tile/T8": {
            "fingerprint": "a" * 64,
            "mesh": {"data": 1, "tile": 8},
            "collectives": {"collective-permute": {
                "count": 4, "bytes_in": 3072, "ici_bytes": 3072}},
            "ici_bytes": 3072, "peak_live_bytes": 112696},
        "shard.b.data/B8": {
            "fingerprint": "b" * 64,
            "mesh": {"data": 8, "tile": 1},
            "collectives": {},
            "ici_bytes": 0, "peak_live_bytes": 228352},
    }


def _synth_manifest():
    return {"jax": jax.__version__,
            graftmesh.MESH_MANIFEST_KEY: _synth_section()}


def test_doubled_ici_traffic_fails_drift_gate():
    """The acceptance scenario: a change that doubles a program's
    modeled ICI traffic dies at the gate with one actionable line —
    no hardware run needed."""
    new = _synth_section()
    new["shard.a.tile/T8"]["ici_bytes"] *= 2
    drift = graftmesh.diff_mesh_manifest(_synth_manifest(), new)
    assert len(drift) == 1 and "shard.a.tile/T8" in drift[0]
    assert "ici_bytes" in drift[0] and "+100%" in drift[0]


def test_cost_jitter_under_tolerance_passes_drift_gate():
    new = _synth_section()
    for entry in new.values():
        entry["ici_bytes"] = int(entry["ici_bytes"] * 1.05)
        entry["peak_live_bytes"] = int(entry["peak_live_bytes"] * 1.05)
    assert graftmesh.diff_mesh_manifest(_synth_manifest(), new) == []


def test_collective_histogram_change_is_drift():
    new = _synth_section()
    new["shard.a.tile/T8"]["collectives"]["collective-permute"][
        "count"] += 2
    drift = graftmesh.diff_mesh_manifest(_synth_manifest(), new)
    assert len(drift) == 1 and "shard.a.tile/T8" in drift[0]
    assert "collective histogram" in drift[0]
    assert "collective-permute" in drift[0]


def test_fingerprint_ghost_and_missing_section_drift():
    old = _synth_manifest()
    new = _synth_section()
    new["shard.a.tile/T8"]["fingerprint"] = "0" * 64
    drift = graftmesh.diff_mesh_manifest(old, new)
    assert len(drift) == 1 and "fingerprint changed" in drift[0]

    old[graftmesh.MESH_MANIFEST_KEY]["ghost/prog"] = {
        "fingerprint": "x", "collectives": {}, "ici_bytes": 0,
        "peak_live_bytes": 0}
    drift = graftmesh.diff_mesh_manifest(old, new)
    assert any("ghost/prog" in line for line in drift)
    # A program this environment could not lower is tolerated missing.
    assert not any("ghost/prog" in line for line in
                   graftmesh.diff_mesh_manifest(
                       old, new, skipped=("ghost/prog",)))

    # No checked-in mesh section at all: one regenerate-and-commit line.
    for missing in (None, {"jax": jax.__version__}):
        lines = graftmesh.diff_mesh_manifest(missing, new)
        assert len(lines) == 1 and "--mesh-audit" in lines[0]


def test_jax_version_change_is_one_actionable_line():
    old = _synth_manifest()
    old["jax"] = "0.0.stale"
    drift = graftmesh.diff_mesh_manifest(old, _synth_section())
    assert len(drift) == 1
    assert "0.0.stale" in drift[0] and jax.__version__ in drift[0]


# --- CLI ----------------------------------------------------------------

@pytest.mark.slow
def test_cli_mesh_audit_passes_on_repo(capsys, cached_mesh_lowering):
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--mesh-audit",
                   "--strict", "--baseline", str(BASELINE),
                   "--manifest", str(MANIFEST)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "shard.dwt.tile/" in out and "MB ICI/device" in out


@pytest.mark.slow
def test_cli_mesh_audit_fails_on_doubled_ici(tmp_path, capsys,
                                             cached_mesh_lowering):
    manifest = json.loads(MANIFEST.read_text(encoding="utf-8"))
    assert any(e["ici_bytes"]
               for e in manifest[graftmesh.MESH_MANIFEST_KEY].values())
    for entry in manifest[graftmesh.MESH_MANIFEST_KEY].values():
        entry["ici_bytes"] *= 2
    bad = tmp_path / "manifest.json"
    bad.write_text(json.dumps(manifest) + "\n", encoding="utf-8")
    dump = tmp_path / "dump"
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--mesh-audit",
                   "--baseline", str(BASELINE), "--manifest", str(bad),
                   "--dump-dir", str(dump)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "shard-manifest-drift" in out and "ici_bytes" in out
    # The partitioned HLO was dumped for the CI artifact upload.
    assert list(dump.glob("*.partitioned.hlo.txt"))


def test_cli_write_manifest_without_mesh_audit_preserves_section(
        tmp_path, capsys, cached_lowering):
    """A single-device --write-manifest refresh must carry the mesh
    section over, not silently drop it (that would turn the next
    --mesh-audit run red)."""
    working = tmp_path / "manifest.json"
    shutil.copy(MANIFEST, working)
    before = json.loads(working.read_text(encoding="utf-8"))[
        graftmesh.MESH_MANIFEST_KEY]
    assert before, "expected a checked-in mesh section"
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--write-manifest",
                   "--manifest", str(working)])
    assert rc == 0, capsys.readouterr().out
    after = json.loads(working.read_text(encoding="utf-8"))
    assert after[graftmesh.MESH_MANIFEST_KEY] == before


@pytest.mark.slow
def test_stale_shard_baseline_entry_fails_strict(tmp_path, capsys,
                                                 cached_mesh_lowering):
    """A fixed shard offender leaves a stale baseline line: --mesh-audit
    --strict must fail on it, while a lint-only run must leave shard
    entries alone (the family did not run)."""
    data = json.loads(BASELINE.read_text(encoding="utf-8"))
    data["findings"].append({
        "fingerprint": "feedfacefeedface",
        "rule": "shard-axis-dead",
        "path": "<graftmesh:ghost.mesh/T8>", "line": 0})
    tampered = tmp_path / "baseline.json"
    tampered.write_text(json.dumps(data) + "\n", encoding="utf-8")

    rc = cli_main([str(REPO / "bucketeer_tpu"), "--mesh-audit",
                   "--strict", "--baseline", str(tampered),
                   "--manifest", str(MANIFEST)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale-baseline-entry" in out and "feedfacefeedface" in out

    rc = cli_main([str(REPO / "bucketeer_tpu"), "--strict",
                   "--baseline", str(tampered)])
    assert rc == 0, capsys.readouterr().out


@pytest.mark.slow
def test_skipped_mesh_program_shard_entries_are_not_stale(
        tmp_path, capsys, mesh_facts, monkeypatch):
    """An environment that cannot partition a mesh program must not
    judge that program's shard baseline entries stale — mirrors
    diff_mesh_manifest's skipped= tolerance."""
    import copy

    hobbled = copy.deepcopy(mesh_facts)
    hobbled[0].skipped = "synthetic: not lowerable here"
    name = hobbled[0].name
    monkeypatch.setattr(
        graftmesh, "run_mesh_programs",
        lambda entries=None, *, in_process=None: copy.deepcopy(hobbled))
    data = json.loads(BASELINE.read_text(encoding="utf-8"))
    data["findings"].append({
        "fingerprint": "cafebabecafebabe",
        "rule": "shard-implicit-allgather",
        "path": f"<graftmesh:{name}>", "line": 0})
    tampered = tmp_path / "baseline.json"
    tampered.write_text(json.dumps(data) + "\n", encoding="utf-8")
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--mesh-audit",
                   "--strict", "--baseline", str(tampered),
                   "--manifest", str(MANIFEST)])
    out = capsys.readouterr().out
    assert "not lowerable here" in out
    assert rc == 0, out


def test_lint_only_write_baseline_preserves_shard_entries(tmp_path,
                                                          capsys):
    """A plain --write-baseline must not drop shard-* entries it did
    not re-derive — same keep rule the perf family has."""
    data = json.loads(BASELINE.read_text(encoding="utf-8"))
    data["findings"].append({
        "fingerprint": "0123456789abcdef",
        "rule": "shard-replicated-large",
        "path": "<graftmesh:ghost>", "line": 0})
    working = tmp_path / "baseline.json"
    working.write_text(json.dumps(data) + "\n", encoding="utf-8")
    rc = cli_main([str(REPO / "bucketeer_tpu"), "--write-baseline",
                   "--baseline", str(working)])
    assert rc == 0, capsys.readouterr().out
    after = json.loads(working.read_text(encoding="utf-8"))["findings"]
    assert any(e["fingerprint"] == "0123456789abcdef" for e in after)
