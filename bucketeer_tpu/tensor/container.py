"""The self-describing tensor container (``BTT1``).

Layout (little-endian throughout)::

    magic    4s   b"BTT1"
    version  u8   1
    dtype    u8   planes.DtypeSpec.code
    ndim     u8
    limbs    u8   K (16-bit limb planes per element)
    shape    u64 * ndim
    n_negz   u32  negative-zero escape count (floats; else 0)
    negz     u64 * n_negz   flat positions
    n_blocks u32  total coded blocks = K * ceil(n_elements / 4096)
    pcap     u8   max nbp over all blocks (plane capacity, informational)
    per block, limb-major then block-raster order:
        nbp   u8   coded magnitude bit-planes (0 = all-zero block)
        kept  u8   planes kept after truncation (== nbp when whole)
        dlen  u32  stored data bytes
        cums  u32 * kept   cumulative truncation length at the end of
                           each plane's **cleanup** pass, MSB plane
                           first (rate.truncation_lengths semantics:
                           bytes-at-boundary + 4, capped at the flushed
                           stream length) — the plane-boundary cut
                           points progressive truncation slices at
    block data segments, concatenated in the same order (dlen each)

Every multi-byte read is bounds-checked; malformed input raises the
decode subsystem's typed :class:`DecodeError`, never a raw
struct.error/IndexError — the container crosses the same trust boundary
as a JP2 file (it arrives over HTTP).
"""
from __future__ import annotations

import struct

import numpy as np

from ..codec.decode.errors import DecodeError
from . import planes

MAGIC = b"BTT1"
VERSION = 1
BLOCK_SAMPLES = 64 * 64

# A conforming encoder caps limbs at 16 magnitude planes (planes.py);
# anything above is malformed input, not a bigger tensor.
MAX_NBP = planes.LIMB_BITS


class _Reader:
    """Bounds-checked cursor over the container bytes."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def need(self, n: int) -> None:
        if self.pos + n > len(self.data):
            raise DecodeError(
                f"truncated tensor container: need {n} bytes at offset "
                f"{self.pos}, have {len(self.data) - self.pos}")

    def take(self, fmt: str):
        n = struct.calcsize(fmt)
        self.need(n)
        out = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += n
        return out

    def raw(self, n: int) -> bytes:
        self.need(n)
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out


class TensorBlock:
    """One coded 64x64 block of one limb plane."""

    __slots__ = ("nbp", "kept", "data", "cums")

    def __init__(self, nbp: int, kept: int, data: bytes,
                 cums: np.ndarray) -> None:
        self.nbp = nbp
        self.kept = kept
        self.data = data
        self.cums = cums          # (kept,) int64 plane-boundary lengths


class EncodedTensor:
    """A parsed container: header fields + per-block streams."""

    def __init__(self, spec: planes.DtypeSpec, shape: tuple,
                 neg_zeros: np.ndarray, blocks: list) -> None:
        self.spec = spec
        self.shape = tuple(int(s) for s in shape)
        self.neg_zeros = neg_zeros
        self.blocks = blocks      # [TensorBlock], limb-major

    @property
    def n_elements(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def blocks_per_limb(self) -> int:
        return -(-self.n_elements // BLOCK_SAMPLES) if self.n_elements \
            else 0

    @property
    def pcap(self) -> int:
        return max((b.nbp for b in self.blocks), default=0)


def dump(enc: EncodedTensor) -> bytes:
    """Serialize an EncodedTensor to container bytes."""
    out = [MAGIC, struct.pack("<BBBB", VERSION, enc.spec.code,
                              len(enc.shape), enc.spec.n_limbs)]
    out.append(struct.pack(f"<{len(enc.shape)}Q", *enc.shape))
    out.append(struct.pack("<I", len(enc.neg_zeros)))
    if len(enc.neg_zeros):
        out.append(np.asarray(enc.neg_zeros,
                              dtype="<u8").tobytes())
    out.append(struct.pack("<IB", len(enc.blocks), enc.pcap))
    for b in enc.blocks:
        out.append(struct.pack("<BBI", b.nbp, b.kept, len(b.data)))
        if b.kept:
            out.append(np.asarray(b.cums, dtype="<u4").tobytes())
    for b in enc.blocks:
        out.append(bytes(b.data))
    return b"".join(out)


def parse(data: bytes) -> EncodedTensor:
    """Parse container bytes; every structural violation is a typed
    :class:`DecodeError`."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise TypeError("tensor container must be bytes")
    r = _Reader(bytes(data))
    if r.raw(4) != MAGIC:
        raise DecodeError("not a tensor container (bad magic)")
    version, code, ndim, k = r.take("<BBBB")
    if version != VERSION:
        raise DecodeError(f"unsupported container version {version}")
    try:
        spec = planes.spec_by_code(code)
    except ValueError as exc:
        raise DecodeError(str(exc)) from None
    if k != spec.n_limbs:
        raise DecodeError(
            f"container claims {k} limbs for {spec.name} "
            f"(expects {spec.n_limbs})")
    if ndim > 16:
        raise DecodeError(f"{ndim} dimensions exceeds the 16-dim cap")
    shape = r.take(f"<{ndim}Q")
    n = 1
    for s in shape:
        if s > (1 << 40):
            raise DecodeError(f"dimension {s} exceeds the size cap")
        n *= int(s)
    if n > (1 << 40):
        raise DecodeError(f"{n} elements exceeds the size cap")
    (n_negz,) = r.take("<I")
    if n_negz > n:
        raise DecodeError(
            f"{n_negz} negative-zero escapes exceed the element count")
    neg_zeros = np.frombuffer(r.raw(8 * n_negz), dtype="<u8").astype(
        np.int64)
    if neg_zeros.size and int(neg_zeros.max()) >= max(n, 1):
        raise DecodeError("negative-zero escape position out of range")
    n_blocks, _pcap = r.take("<IB")
    expect = k * (-(-n // BLOCK_SAMPLES) if n else 0)
    if n_blocks != expect:
        raise DecodeError(
            f"container claims {n_blocks} blocks; the shape implies "
            f"{expect}")
    blocks = []
    dlens = []
    for _ in range(n_blocks):
        nbp, kept, dlen = r.take("<BBI")
        if nbp > MAX_NBP:
            raise DecodeError(
                f"{nbp} bit-planes exceeds the {MAX_NBP}-plane limb cap")
        if kept > nbp:
            raise DecodeError(
                f"block keeps {kept} planes of {nbp} coded")
        if dlen > len(r.data):
            raise DecodeError("block data length exceeds the container")
        cums = np.frombuffer(r.raw(4 * kept), dtype="<u4").astype(
            np.int64)
        if kept:
            if np.any(np.diff(cums) < 0):
                raise DecodeError(
                    "plane-boundary lengths must be non-decreasing")
            if int(cums[-1]) > dlen:
                raise DecodeError(
                    "plane boundary beyond the stored block data")
        blocks.append(TensorBlock(int(nbp), int(kept), b"", cums))
        dlens.append(dlen)
    for b, dlen in zip(blocks, dlens):
        b.data = r.raw(dlen)
    if r.pos != len(r.data):
        raise DecodeError(
            f"{len(r.data) - r.pos} trailing bytes after the last "
            "block segment")
    return EncodedTensor(spec, shape, neg_zeros, blocks)
