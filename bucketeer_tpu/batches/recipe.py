"""Batch recipe: the validated request half of the batch data plane.

``parse_recipe`` is the single gate between an untrusted ``POST
/batches`` JSON body and the assembler: every malformed field raises
the typed :class:`InvalidParam` (HTTP 400), never an unhandled
``TypeError``/``KeyError`` (HTTP 500) — the same fuzz contract the
image decode parameters carry (tests/test_batches.py drives it with
generated garbage)."""
from __future__ import annotations

import os
import re
from dataclasses import dataclass

from ..codec.decode.errors import InvalidParam

# Hard per-recipe item bound: the assembler stages every item's band
# planes concurrently, so N is an HBM/host-memory knob, not taste.
MAX_ITEMS = int(os.environ.get("BUCKETEER_BATCH_MAX_ITEMS", "64"))

_LAYOUTS = ("auto", "sharded", "replicated")
_DTYPES = (None, "int32", "float32")
_KNOWN_KEYS = frozenset((
    "ids", "region", "reduce", "layers", "dtype", "layout", "store",
    "planes", "deadline_s"))
_ID_RE = re.compile(r"^[A-Za-z0-9._~%-]{1,256}$")


@dataclass(frozen=True)
class BatchRecipe:
    """One validated batch read request.

    ``ids`` are the images, in batch order; ``region``/``reduce``/
    ``layers`` apply uniformly to every item (exactly the
    :func:`decode_to_coefficients` parameters); ``dtype`` pins the
    expected coefficient dtype (``int32`` reversible / ``float32``
    irreversible) or None for whatever the codestreams carry;
    ``layout`` is the placement contract (``sharded`` demands
    ``P("batch")`` and fails closed, ``auto`` falls back to replicated
    when the surviving batch doesn't divide the mesh); ``planes``
    floors the stored container when ``store`` is set."""
    ids: tuple
    region: tuple | None = None
    reduce: int = 0
    layers: int | None = None
    dtype: str | None = None
    layout: str = "auto"
    store: bool = False
    planes: int | None = None
    deadline_s: float | None = None


def _want_int(doc: dict, key: str, lo: int, hi: int = 1 << 30):
    v = doc[key]
    if isinstance(v, bool) or not isinstance(v, int):
        raise InvalidParam(f"{key} must be an integer")
    if not lo <= v <= hi:
        raise InvalidParam(f"{key}={v} out of range [{lo}, {hi}]")
    return v


def parse_recipe(doc) -> BatchRecipe:
    """Validate an untrusted JSON document into a :class:`BatchRecipe`.
    Raises :class:`InvalidParam` for every malformed shape — unknown
    keys, non-list ids, zero-size regions, negative reduce — so the
    HTTP layer's 400 branch is the only failure path."""
    if not isinstance(doc, dict):
        raise InvalidParam("batch recipe must be a JSON object")
    unknown = sorted(set(doc) - _KNOWN_KEYS)
    if unknown:
        raise InvalidParam(f"unknown recipe keys: {', '.join(unknown)}")

    ids = doc.get("ids")
    if not isinstance(ids, list) or not ids:
        raise InvalidParam("ids must be a non-empty list of image ids")
    if len(ids) > MAX_ITEMS:
        raise InvalidParam(
            f"batch of {len(ids)} items exceeds the {MAX_ITEMS}-item "
            f"cap (BUCKETEER_BATCH_MAX_ITEMS)")
    for i in ids:
        if not isinstance(i, str) or not _ID_RE.match(i):
            raise InvalidParam(f"bad image id: {i!r}")

    region = None
    if doc.get("region") is not None:
        r = doc["region"]
        if (not isinstance(r, (list, tuple)) or len(r) != 4
                or any(isinstance(v, bool) or not isinstance(v, int)
                       for v in r)):
            raise InvalidParam("region must be [x, y, w, h] integers")
        x, y, w, h = r
        if x < 0 or y < 0:
            raise InvalidParam("region origin must be non-negative")
        if w <= 0 or h <= 0:
            raise InvalidParam(f"zero-size region {w}x{h}")
        region = (x, y, w, h)

    reduce = _want_int(doc, "reduce", 0, 32) if "reduce" in doc else 0
    layers = None
    if doc.get("layers") is not None:
        layers = _want_int(doc, "layers", 1)

    dtype = doc.get("dtype")
    if dtype not in _DTYPES:
        raise InvalidParam(f"dtype must be int32 or float32, "
                           f"not {dtype!r}")
    layout = doc.get("layout", "auto")
    if layout not in _LAYOUTS:
        raise InvalidParam(f"layout must be one of {_LAYOUTS}, "
                           f"not {layout!r}")

    store = doc.get("store", False)
    if not isinstance(store, bool):
        raise InvalidParam("store must be a boolean")
    planes = None
    if doc.get("planes") is not None:
        planes = _want_int(doc, "planes", 1, 64)
        if not store:
            raise InvalidParam("planes only applies to stored batches "
                               "(set store=true, or truncate on GET)")

    deadline_s = None
    if doc.get("deadline_s") is not None:
        v = doc["deadline_s"]
        if isinstance(v, bool) or not isinstance(v, (int, float)) \
                or not 0 < float(v) <= 3600:
            raise InvalidParam("deadline_s must be in (0, 3600]")
        deadline_s = float(v)

    return BatchRecipe(ids=tuple(ids), region=region, reduce=reduce,
                       layers=layers, dtype=dtype, layout=layout,
                       store=store, planes=planes,
                       deadline_s=deadline_s)
