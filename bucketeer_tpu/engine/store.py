"""Shared state: job store, upload results, counters, locks.

Port of the reference's Vert.x shared data (reference: SURVEY.md §1 state
table): async map ``lambda-jobs`` (job-name -> Job) as the job queue
(reference: Constants.java:145, handlers/LoadCsvHandler.java:185), local
map ``s3-uploads`` of completed uploads (S3BucketVerticle.java:171),
shared counters (``s3-request-count``, per-image retry counters,
S3BucketVerticle.java:89,251), and a ``job-lock`` with a 10 s acquisition
timeout guarding job mutation (Constants.java:44-49,
handlers/BatchJobStatusHandler.java:115-127).

Single-process asyncio: plain dicts + one asyncio.Lock give the same
guarantees the single-node Vert.x shared data gave the reference — plus,
when a journal directory is configured (``bucketeer.job.journal.dir`` /
``BUCKETEER_JOB_JOURNAL_DIR``), a write-ahead journal + snapshot
(:mod:`.journal`) so jobs survive a process kill: recovery re-loads
queued jobs and re-queues items stuck dispatched-but-unresolved, with
idempotent item resolution so a replayed status update can't
double-count toward finalization. In-memory stays the default (tests,
dev mode).
"""
from __future__ import annotations

import asyncio
import contextlib
import logging
from collections import defaultdict

from .. import constants
from ..models import Job, JobNotFoundError, WorkflowState
from . import faults
from .journal import JobJournal, JournalUnavailable  # noqa: F401 (re-export)

LOG = logging.getLogger(__name__)


class LockTimeout(TimeoutError):
    """Could not acquire the job lock within the timeout (reference:
    BatchJobStatusHandler.java:115-127 fails the request on lock
    timeout)."""


class JobStore:
    """The ``lambda-jobs`` map + job lock (+ optional WAL)."""

    # Journal records between snapshot compactions: a long-lived server
    # ingesting for weeks must not grow journal.jsonl without bound
    # (replay stays state-sized, not history-sized).
    COMPACT_EVERY = 1000

    def __init__(self,
                 lock_timeout: float = constants.JOB_LOCK_TIMEOUT,
                 journal_dir: str | None = None) -> None:
        self._jobs: dict[str, Job] = {}
        self._dispatched: dict[str, set] = {}
        self._lock = asyncio.Lock()
        self.lock_timeout = lock_timeout
        self._journal: JobJournal | None = None
        self._appends_since_compact = 0
        self.recovery: dict = {}
        if journal_dir:
            self._journal = JobJournal(journal_dir)
            self._recover()

    def _recover(self) -> None:
        """Load snapshot + journal, then compact so the next crash
        replays from here."""
        jobs, dispatched, stats = self._journal.load()
        self._jobs = jobs
        self._dispatched = dispatched
        self.recovery = stats
        if jobs or stats["records"] or stats["truncated"]:
            LOG.info(
                "job journal recovered: %d job(s), %d record(s) applied,"
                " %d ignored%s", len(jobs), stats["records"],
                stats["ignored"],
                " (truncated tail dropped)" if stats["truncated"] else "")
        self._journal.compact(self._jobs, self._dispatched)

    @property
    def durable(self) -> bool:
        return self._journal is not None

    @contextlib.asynccontextmanager
    async def locked(self, timeout: float | None = None):
        """The job mutation lock (reference: Constants.java:44-49)."""
        faults.point("store.lock")
        try:
            await asyncio.wait_for(self._lock.acquire(),
                                   timeout or self.lock_timeout)
        except asyncio.TimeoutError:
            raise LockTimeout(
                f"job-lock not acquired in {timeout or self.lock_timeout}s")
        try:
            yield self
        finally:
            self._lock.release()

    def _append(self, record: dict) -> None:
        if self._journal is not None:
            self._journal.append(record)   # may raise JournalUnavailable
            self._appends_since_compact += 1

    def _maybe_compact(self) -> None:
        """Re-snapshot once the journal has grown past the threshold.
        Called from :meth:`remove` (finalization), whose callers hold
        the store lock — put/resolve appends (also lock-holders) can't
        interleave. A dispatch mark racing in from the fan-out loop can
        at worst make this pass fail (caught below) or miss its record
        until the next compaction — a lost *mark* only re-dispatches
        one item after a crash, never loses state."""
        if (self._journal is None
                or self._appends_since_compact < self.COMPACT_EVERY):
            return
        try:
            self._journal.compact(self._jobs, self._dispatched)
            self._appends_since_compact = 0
        except (JournalUnavailable, RuntimeError) as exc:
            # Compaction is an optimization; the WAL is still the
            # durable record. Try again at the next threshold cross.
            LOG.warning("journal compaction skipped: %s", exc)

    def put(self, job: Job) -> None:
        # WAL discipline: journal first — a job the disk doesn't have
        # must not be accepted into memory.
        self._append({"op": "put", "job": job.to_json()})
        self._jobs[job.name] = job
        self._dispatched.setdefault(job.name, set())

    def get(self, name: str) -> Job:
        try:
            return self._jobs[name]
        except KeyError:
            raise JobNotFoundError(name)

    def maybe_get(self, name: str) -> Job | None:
        return self._jobs.get(name)

    def remove(self, name: str) -> Job:
        if name not in self._jobs:
            raise JobNotFoundError(name)
        self._append({"op": "remove", "job": name})
        self._dispatched.pop(name, None)
        job = self._jobs.pop(name)
        self._maybe_compact()
        return job

    def names(self) -> list[str]:
        return sorted(self._jobs)

    def __contains__(self, name: str) -> bool:
        return name in self._jobs

    def __len__(self) -> int:
        return len(self._jobs)

    # -- durable ingest bookkeeping (ISSUE 11 tentpole piece 1) ----------

    def mark_dispatched(self, job_name: str, image_id: str) -> None:
        """Record that an item was handed to a worker. After a crash,
        items dispatched-but-unresolved are still EMPTY in the replayed
        job and get re-queued by the resume pass."""
        if job_name not in self._jobs:
            return
        self._append({"op": "dispatch", "job": job_name, "id": image_id})
        self._dispatched.setdefault(job_name, set()).add(image_id)

    def dispatched(self, job_name: str) -> set:
        return set(self._dispatched.get(job_name, ()))

    def resolve_item(self, job_name: str, image_id: str, success: bool,
                     access_url: str | None = None) -> tuple[bool, bool]:
        """Idempotently set one item's terminal state (call under
        :meth:`locked`). Returns ``(job_finished, applied)`` — a replayed
        update on an already-terminal item is a no-op with
        ``applied=False``, so it can never double-count toward
        finalization (at-least-once delivery, exactly-once accounting).
        """
        job = self.get(job_name)               # raises JobNotFoundError
        item = job.find_item(image_id)
        if item is None:
            raise KeyError(f"item {image_id} not in job {job_name}")
        if item.workflow_state != WorkflowState.EMPTY:
            return job.remaining() == 0, False
        state = (WorkflowState.SUCCEEDED if success
                 else WorkflowState.FAILED)
        self._append({"op": "resolve", "job": job_name, "id": image_id,
                      "state": state.name,
                      "url": access_url if success else None})
        item.set_state(state)
        if success and access_url:
            item.access_url = access_url
        self._dispatched.get(job_name, set()).discard(image_id)
        return job.remaining() == 0, True

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()


class Counters:
    """Shared counters: global in-flight S3 requests + per-image retry
    counts (reference: S3BucketVerticle.java:89-99,219-277). Per-image
    entries are reset when the upload settles or the item resolves —
    a long ingest run must not grow the map without bound."""

    def __init__(self) -> None:
        self._values: dict[str, int] = defaultdict(int)

    def increment(self, name: str) -> int:
        self._values[name] += 1
        return self._values[name]

    def decrement(self, name: str) -> int:
        self._values[name] -= 1
        return self._values[name]

    def get(self, name: str) -> int:
        return self._values[name]

    def reset(self, name: str) -> None:
        self._values.pop(name, None)

    def names(self, prefix: str = "") -> list[str]:
        """Counter names with a live entry (for leak tests/pruning)."""
        return sorted(n for n in self._values if n.startswith(prefix))


class UploadsMap:
    """Completed-upload records (reference: S3BucketVerticle.java:168-175
    stores per-image success entries in the ``s3-uploads`` local map)."""

    def __init__(self) -> None:
        self._records: dict[str, dict] = {}

    def record(self, image_id: str, details: dict) -> None:
        self._records[image_id] = details

    def get(self, image_id: str) -> dict | None:
        return self._records.get(image_id)

    def __len__(self) -> int:
        return len(self._records)
