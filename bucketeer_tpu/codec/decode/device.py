"""Device-side decode back half: dequantization, multi-level inverse DWT
and inverse RCT/ICT as one jitted XLA program per reconstructed tile
shape — the inference-path mirror of ``pipeline._transform_batch``.

The host Tier-1 decoder hands over signed half-magnitude integers
(``t1_dec``: ``|hval| = 2*(m + 0.5) * 2^p``) assembled into the Mallat
layout of the *reduced* tile (partial decode drops the finest
resolutions before anything reaches the device). Dequantization is then
uniform over the layout:

- reversible (5/3): exact coefficient = ``sign * (|hval| >> 1)`` — the
  midpoint half-bit floors away, so full lossless decodes are bit-exact
  and truncated ones match OpenJPEG's integer reconstruction;
- irreversible (9/7): coefficient = ``hval * (delta_b / 2)`` against a
  static per-pixel half-step map, the decode twin of the encoder's
  ``_step_map``.

Like the encode pipeline, everything is static-shaped elementwise/concat
work XLA fuses into a few kernels; batches of same-shape tiles share one
program, padded to power-of-two bucket sizes to bound retraces.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis import graftcost, retrace
from ...analysis.contracts import contract
from ..dwt import _along_rows, _inv53_last, dwt2d_inverse
from ..pipeline import (_band_geometry, _bucket,
                        donate_argnums_if_supported)
from ..transforms import ict_inverse, level_shift_inverse, rct_inverse


@dataclass(frozen=True)
class InversePlan:
    """Static decode plan for one reconstructed tile shape. ``slots``
    carries (name, level, y0, x0, h, w, delta) rectangles of the reduced
    Mallat layout — deltas are the *signaled* steps from QCD, so the
    decoder dequantizes with exactly what the encoder quantized with."""
    tile_h: int              # reduced tile height (after ``reduce``)
    tile_w: int
    n_comps: int
    levels: int              # levels remaining after ``reduce``
    reversible: bool
    bitdepth: int
    used_mct: bool
    slots: tuple             # ((name, level, y0, x0, h, w, delta), ...)


def make_inverse_plan(rh: int, rw: int, n_comps: int, levels: int,
                      reversible: bool, bitdepth: int, used_mct: bool,
                      delta_of) -> InversePlan:
    """``delta_of(level, name) -> float`` maps a reduced-layout band to
    its signaled quantizer step (level as in ``_band_geometry``: 1 =
    finest of the reduced tile; the LL entry uses its own level)."""
    slots = tuple(
        (name, lvl, y0, x0, bh, bw, float(delta_of(lvl, name)))
        for name, lvl, y0, x0, bh, bw in _band_geometry(rh, rw, levels))
    return InversePlan(rh, rw, n_comps, levels, reversible, bitdepth,
                       used_mct, slots)


def _half_step_map(plan: InversePlan) -> np.ndarray:
    """(h, w) float32 map of delta_b / 2 over the reduced Mallat layout
    (hvals are in doubled units, so the half step lands on delta)."""
    m = np.ones((plan.tile_h, plan.tile_w), dtype=np.float32)
    for _, _, y0, x0, bh, bw, delta in plan.slots:
        m[y0:y0 + bh, x0:x0 + bw] = delta * 0.5
    return m


def _inverse_body(plan: InversePlan, half_map, hv: jnp.ndarray):
    """(B, C, h, w) int32 half-magnitudes -> (B, h, w, C) int32 samples."""
    if plan.reversible:
        mag = jnp.abs(hv) >> 1
        vals = jnp.where(hv < 0, -mag, mag)
    else:
        vals = hv.astype(jnp.float32) * half_map

    bands = [dict() for _ in range(plan.levels)]
    ll = None
    for name, lvl, y0, x0, bh, bw, _ in plan.slots:
        rect = vals[..., y0:y0 + bh, x0:x0 + bw]
        if name == "LL":
            ll = rect
        else:
            bands[lvl - 1][name] = rect
    img = dwt2d_inverse(ll, bands, plan.reversible)

    x = jnp.moveaxis(img, 1, -1)                  # (B, h, w, C)
    if plan.used_mct:
        x = rct_inverse(x) if plan.reversible else ict_inverse(x)
    x = level_shift_inverse(x, plan.bitdepth)
    if not plan.reversible:
        x = jnp.round(x)
    x = jnp.clip(x, 0, (1 << plan.bitdepth) - 1)
    return x.astype(jnp.int32)


def inverse_program(plan: InversePlan):
    """(traceable fn, device donate_argnums) — the construction
    :func:`_compiled_inverse` jits, shared with the device audit
    (analysis/deviceaudit.py). The donate spec is empty by verified
    fact: the (B, C, h, w) int32 half-magnitude input never matches the
    (B, h, w, C) sample output aval (the color axis moves), so XLA
    silently drops any requested alias — the audit's forced lowering
    proves ``tf.aliasing_output`` never appears. The whitelist entry in
    ``rules_donation`` records the same reason."""
    half_map = (None if plan.reversible
                else jnp.asarray(_half_step_map(plan)))
    return retrace.instrument(
        "inverse", partial(_inverse_body, plan, half_map)), ()


@lru_cache(maxsize=256)
def _compiled_inverse(plan: InversePlan):
    fn, donate = inverse_program(plan)
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


# --- windowed (region) inverse -------------------------------------------
#
# A region read must not pay for the whole tile: the synthesis needs
# only a halo-expanded window of each subband. The halo rule that keeps
# the window self-sufficient: boundary effects penetrate at most one
# sample per lifting step inward from a window edge, so a halo of 2
# coefficients per side per level suffices for the 2-step 5/3 and 4 for
# the 4-step 9/7 — except at true tile boundaries, where the window
# clamps and the reflect extension is exactly the full decode's. Window
# starts are rounded down to even so the lo/hi interleave parity matches
# the full transform. The halo governs *which code-blocks Tier-1 must
# decode* for both wavelets.
#
# How the device half runs the window differs by wavelet:
#
# - reversible (5/3): a dedicated windowed program — integer lifting is
#   immune to compiler rewrites, so the windowed result is bit-identical
#   to the full decode's crop by arithmetic, at any shape.
# - irreversible (9/7): float codegen is shape-dependent (XLA fuses /
#   contracts differently per array width — measured 1-ulp differences
#   that flip a x.5 rounding), so a differently-shaped windowed program
#   cannot promise the bit-exact-crop contract. Instead the windowed
#   coefficients scatter into a zeroed full-tile Mallat plane and run
#   the *same compiled program* as the full decode (shared cache entry,
#   zero extra compiles); samples inside the window only depend on the
#   halo-covered coefficients, so the crop is bit-exact by construction.
#   Device FLOPs are the cheap part of a read — Tier-2 and host Tier-1,
#   where the windowing earns its 10-100x, stay windowed either way.


def halo(reversible: bool) -> int:
    """Per-side, per-level coefficient halo for a bit-exact windowed
    inverse DWT (lifting-step count of the synthesis filter)."""
    return 2 if reversible else 4


@dataclass(frozen=True)
class RegionPlan:
    """Static decode plan for one (tile shape, window) pair.

    ``slots`` carries ``(name, level, by0, by1, bx0, bx1, delta)`` —
    the *window rectangle in band coordinates* (tile-local) of every
    subband the synthesis needs, level 1 = finest, LL carrying
    ``level == levels``. ``steps`` is one entry per synthesis level,
    coarsest first: the crop applied after that level's interleave,
    relative to the level's interleaved window."""
    tile_h: int              # reduced tile height (context for caching)
    tile_w: int
    n_comps: int
    levels: int              # levels remaining after ``reduce``
    reversible: bool
    bitdepth: int
    used_mct: bool
    out_h: int               # final window extent (== y1 - y0)
    out_w: int
    win: tuple               # (y0, y1, x0, x1) tile-local sample window
    slots: tuple             # ((name, lvl, by0, by1, bx0, bx1, delta), ...)
    steps: tuple             # ((ry0, ry1, rx0, rx1), ...) coarse -> fine


def _window_chain(a: int, b: int, n: int, levels: int, r: int) -> tuple:
    """Per-dimension window recursion: for each decomposition level
    (finest first) the halo-expanded, even-aligned interleaved window
    plus its lo/hi halves; the needed span of the next-coarser LL is the
    lo half. Returns ([(u0, u1, lo, hi, s_prev)], final LL span)."""
    out = []
    s0, s1 = a, b
    for _ in range(levels):
        u0 = max(0, s0 - r) & ~1
        u1 = min(n, s1 + r)
        lo = (u0 >> 1, (u1 + 1) >> 1)
        hi = (u0 >> 1, u1 >> 1)
        out.append((u0, u1, lo, hi, (s0, s1)))
        s0, s1 = lo
        n = (n + 1) >> 1
    return out, (s0, s1)


def make_region_plan(rh: int, rw: int, n_comps: int, levels: int,
                     reversible: bool, bitdepth: int, used_mct: bool,
                     delta_of, y0: int, y1: int, x0: int,
                     x1: int) -> RegionPlan:
    """Plan a windowed inverse reconstructing tile-local samples
    ``[y0, y1) x [x0, x1)`` of an (rh, rw) reduced tile. ``delta_of``
    as in :func:`make_inverse_plan`."""
    r = halo(reversible)
    rows, ll_r = _window_chain(y0, y1, rh, levels, r)
    cols, ll_c = _window_chain(x0, x1, rw, levels, r)
    slots = []
    for lvl in range(1, levels + 1):
        _, _, lo_r, hi_r, _ = rows[lvl - 1]
        _, _, lo_c, hi_c, _ = cols[lvl - 1]
        slots.append(("HL", lvl, lo_r[0], lo_r[1], hi_c[0], hi_c[1],
                      float(delta_of(lvl, "HL"))))
        slots.append(("LH", lvl, hi_r[0], hi_r[1], lo_c[0], lo_c[1],
                      float(delta_of(lvl, "LH"))))
        slots.append(("HH", lvl, hi_r[0], hi_r[1], hi_c[0], hi_c[1],
                      float(delta_of(lvl, "HH"))))
    slots.append(("LL", levels, ll_r[0], ll_r[1], ll_c[0], ll_c[1],
                  float(delta_of(levels, "LL"))))
    steps = []
    for lvl in range(levels, 0, -1):
        u0r, _, _, _, (sa_r, sb_r) = rows[lvl - 1]
        u0c, _, _, _, (sa_c, sb_c) = cols[lvl - 1]
        steps.append((sa_r - u0r, sb_r - u0r, sa_c - u0c, sb_c - u0c))
    return RegionPlan(rh, rw, n_comps, levels, reversible, bitdepth,
                      used_mct, y1 - y0, x1 - x0, (y0, y1, x0, x1),
                      tuple(slots), tuple(steps))


def _region_body(levels: int, steps: tuple, used_mct: bool,
                 bitdepth: int, hvs):
    """Windowed reversible synthesis: per-slot (C, bh, bw) int32
    half-magnitudes -> (h, w, C) int32 samples for the planned window.
    Integer lifting end to end, so the result is rewrite-immune and
    bit-identical to the full decode's crop at any window shape. Slot
    order is the RegionPlan convention: (HL, LH, HH) per level, LL
    last."""
    vals = {}
    names = [(name, lvl) for lvl in range(1, levels + 1)
             for name in ("HL", "LH", "HH")] + [("LL", levels)]
    for (name, lvl), hv in zip(names, hvs):
        mag = jnp.abs(hv) >> 1
        vals[(name, lvl)] = jnp.where(hv < 0, -mag, mag)
    ll = vals[("LL", levels)]
    for lvl in range(levels, 0, -1):
        v_lo = _inv53_last(ll, vals[("HL", lvl)])
        v_hi = _inv53_last(vals[("LH", lvl)], vals[("HH", lvl)])
        ll = _along_rows(_inv53_last, v_lo, v_hi)
        ry0, ry1, rx0, rx1 = steps[levels - lvl]
        ll = ll[..., ry0:ry1, rx0:rx1]
    x = jnp.moveaxis(ll, 0, -1)                   # (h, w, C)
    if used_mct:
        x = rct_inverse(x)
    x = level_shift_inverse(x, bitdepth)
    x = jnp.clip(x, 0, (1 << bitdepth) - 1)
    return x.astype(jnp.int32)


def _compiled_region_inverse(plan: RegionPlan):
    # Key on what actually enters the trace — levels, relative crop
    # steps, MCT, bitdepth (plus the slot shapes, which jit buckets
    # itself) — so same-size same-parity windows at different (x, y)
    # share one compiled program instead of one per tile position.
    return _compiled_region_inverse_cached(
        plan.levels, plan.steps, plan.used_mct, plan.bitdepth)


def region_program(levels: int, steps: tuple, used_mct: bool,
                   bitdepth: int):
    """(traceable fn, device donate_argnums) for the windowed reversible
    synthesis — audit seam. Donation of the per-slot window tuple is
    unusable (no slot aval matches the (h, w, C) sample output); the
    audit verifies the drop, ``rules_donation`` records it."""
    return retrace.instrument(
        "region_inverse",
        partial(_region_body, levels, steps, used_mct, bitdepth)), ()


@lru_cache(maxsize=256)
def _compiled_region_inverse_cached(levels: int, steps: tuple,
                                    used_mct: bool, bitdepth: int):
    fn, donate = region_program(levels, steps, used_mct, bitdepth)
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


def _full_plan_from_region(plan: RegionPlan) -> InversePlan:
    """The full-tile InversePlan a region plan's stream would use — the
    irreversible region path runs this (cache-shared with full decodes)
    so its float codegen is the full decode's, bit for bit."""
    deltas = {(name, lvl): delta
              for name, lvl, _, _, _, _, delta in plan.slots}
    return make_inverse_plan(
        plan.tile_h, plan.tile_w, plan.n_comps, plan.levels,
        plan.reversible, plan.bitdepth, plan.used_mct,
        lambda lvl, name: deltas[(name, lvl)])


def run_region_inverse(plan: RegionPlan, hv_slots: list) -> np.ndarray:
    """Device back half of a region read: per-slot (C, bh, bw) int32
    half-magnitude window arrays (RegionPlan slot order) ->
    (out_h, out_w, C) int32 samples. Reversible streams run the
    dedicated windowed program; irreversible streams scatter the window
    into a zeroed full-tile plane and run the full decode's own program
    (see the module comment on why that is what keeps the float path
    bit-exact)."""
    if plan.reversible:
        fn = _compiled_region_inverse(plan)
        out = fn(tuple(jnp.asarray(a) for a in hv_slots))
        return np.asarray(jax.device_get(out))
    planes = np.zeros((plan.n_comps, plan.tile_h, plan.tile_w),
                      dtype=np.int32)
    origins = {(name, lvl): (y0, x0)
               for name, lvl, y0, x0, _, _ in _band_geometry(
                   plan.tile_h, plan.tile_w, plan.levels)}
    for (name, lvl, by0, by1, bx0, bx1, _), hv in zip(plan.slots,
                                                      hv_slots):
        y0, x0 = origins[(name, lvl)]
        planes[:, y0 + by0:y0 + by1, x0 + bx0:x0 + bx1] = hv
    samples = run_inverse(_full_plan_from_region(plan), planes[None])[0]
    wy0, wy1, wx0, wx1 = plan.win
    return samples[wy0:wy1, wx0:wx1]


@contract(shapes={"hvals": ("B", "C", "h", "w")},
          dtypes={"hvals": "integer"})
def run_inverse(plan: InversePlan, hvals: np.ndarray) -> np.ndarray:
    """Run the jitted inverse for a (B, C, h, w) int32 batch of decoded
    tile coefficient planes; returns (B, h, w, C) int32 samples on host.
    The batch is padded to a power-of-two bucket so a long-running read
    service compiles O(log max-batch) programs per tile shape."""
    b = hvals.shape[0]
    pad = _bucket(b) - b
    graftcost.record_bucket("decode.batch", b, b + pad)
    if pad:
        hvals = np.concatenate(
            [hvals, np.zeros((pad,) + hvals.shape[1:], hvals.dtype)])
    fn = _compiled_inverse(plan)
    out = fn(jnp.asarray(hvals))
    return np.asarray(jax.device_get(out))[:b]
