"""HttpS3Client SigV4 + streaming tests against a local aiohttp stub.

The reference's uploader is exercised against real S3 in its ITs
(reference: verticles/S3BucketVerticleTest.java:85-168); here a local
stub server independently recomputes the SigV4 signature from the
request it received, so a canonical-URI/path mismatch (the classic
double-encoding bug) fails the test. Keys with ':' — every ARK-derived
key — are the regression case.
"""
from __future__ import annotations

import asyncio
import hashlib
import hmac
import re

import pytest

from bucketeer_tpu.engine.s3 import HttpS3Client

ACCESS, SECRET, REGION = "AKIDEXAMPLE", "testsecretkey", "us-west-2"


def _expected_signature(method: str, raw_path: str, query: str,
                        headers: dict, payload_hash: str) -> str:
    """Independent SigV4 computation from the *received* request."""
    amz_date = headers["x-amz-date"]
    datestamp = amz_date[:8]
    auth = headers["authorization"]
    signed_list = re.search(r"SignedHeaders=([^,]+)", auth).group(1)
    canonical_headers = "".join(
        f"{h}:{headers[h].strip()}\n" for h in signed_list.split(";"))
    canonical = "\n".join([method, raw_path, query, canonical_headers,
                           signed_list, payload_hash])
    scope = f"{datestamp}/{REGION}/s3/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])

    def hs(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = hs(f"AWS4{SECRET}".encode(), datestamp)
    k = hs(k, REGION)
    k = hs(k, "s3")
    k = hs(k, "aws4_request")
    return hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()


async def _run_put(tmp_path, key: str, body: bytes, metadata: dict):
    from aiohttp import web

    src = tmp_path / "src.bin"
    src.write_bytes(body)
    seen = {}

    async def handler(request: web.Request) -> web.Response:
        seen["raw_path"] = request.raw_path.split("?")[0]
        seen["query"] = request.query_string
        seen["headers"] = {k.lower(): v
                           for k, v in request.headers.items()}
        seen["body"] = await request.read()
        seen["host"] = request.headers.get("Host")
        return web.Response(status=200)

    app = web.Application(client_max_size=64 << 20)
    app.router.add_route("PUT", "/{tail:.*}", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]

    client = HttpS3Client(ACCESS, SECRET, REGION,
                          endpoint=f"http://127.0.0.1:{port}")
    try:
        await client.put("bkt", key, str(src), metadata)
    finally:
        await client.close()
        await runner.cleanup()
    return seen


class TestHttpS3Client:
    def test_signature_valid_for_ark_key(self, tmp_path):
        """A ':'-bearing ARK key must sign the path actually sent."""
        key = "ark:/21198/z10005 v%2Fxyz.jpx"
        body = b"jp2-bytes" * 100
        seen = asyncio.run(_run_put(tmp_path, key, body,
                                    {"image-id": key, "job-name": "j1"}))
        # Path on the wire is single-encoded.
        assert seen["raw_path"] == \
            "/bkt/ark%3A/21198/z10005%20v%252Fxyz.jpx"
        auth = seen["headers"]["authorization"]
        got_sig = re.search(r"Signature=([0-9a-f]+)", auth).group(1)
        payload_hash = seen["headers"]["x-amz-content-sha256"]
        assert payload_hash == hashlib.sha256(body).hexdigest()
        expect = _expected_signature("PUT", seen["raw_path"], seen["query"],
                                     seen["headers"], payload_hash)
        assert got_sig == expect, "signed path != request path"

    def test_streams_body_and_metadata(self, tmp_path):
        body = b"\x00\x01" * (3 << 20)  # 6 MB, > one CHUNK
        seen = asyncio.run(_run_put(tmp_path, "plain.jpx", body,
                                    {"image-id": "plain.jpx"}))
        assert seen["body"] == body
        assert seen["headers"]["x-amz-meta-image-id"] == "plain.jpx"
        # Chunked streaming still declares the exact length up front.
        assert int(seen["headers"]["content-length"]) == len(body)

    def test_non_200_raises(self, tmp_path):
        from aiohttp import web

        from bucketeer_tpu.engine.s3 import S3Error

        async def go():
            async def handler(request):
                return web.Response(status=403, text="SignatureDoesNotMatch")

            app = web.Application()
            app.router.add_route("PUT", "/{tail:.*}", handler)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            src = tmp_path / "s.bin"
            src.write_bytes(b"x")
            client = HttpS3Client(ACCESS, SECRET, REGION,
                                  endpoint=f"http://127.0.0.1:{port}")
            try:
                with pytest.raises(S3Error) as err:
                    await client.put("b", "k", str(src), {})
                assert err.value.status == 403
            finally:
                await client.close()
                await runner.cleanup()

        asyncio.run(go())
