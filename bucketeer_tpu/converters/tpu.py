"""The in-process TPU converter — the component the reference outsources
to the Kakadu binary (reference: converters/KakaduConverter.java:55-77).

Mirrors the Kakadu encode recipe structurally (reference:
KakaduConverter.java:38-44): 6 decomposition levels, 64x64 code-blocks,
1024-tiled large images; lossless = reversible 5/3 + RCT, lossy =
irreversible 9/7 + ICT at the configured rate.
"""
from __future__ import annotations

import os

from ..codec import tiff
from ..codec.encoder import EncodeParams, encode_jp2
from .base import Conversion, ConverterError, output_path

# Tile images larger than this many pixels (kdu runs untiled but the
# reference recipe declares Stiles={512,512}; we tile big inputs so the
# device program stays one of a few static shapes).
TILE_THRESHOLD = 2048 * 2048
TILE_SIZE = 1024
LEVELS = 6          # reference: Clevels=6
LOSSY_BASE_DELTA = 2.0


class TpuConverter:
    """JPEG 2000 encoding on the local TPU/accelerator via the JAX codec."""

    name = "TPU"

    def __init__(self, levels: int = LEVELS, lossy_base_delta: float =
                 LOSSY_BASE_DELTA, jpx: bool = True) -> None:
        self.levels = levels
        self.lossy_base_delta = lossy_base_delta
        self.jpx = jpx

    def convert(self, image_id: str, source_path: str,
                conversion: Conversion = Conversion.LOSSLESS) -> str:
        if not os.path.exists(source_path):
            raise ConverterError(f"source not found: {source_path}")
        try:
            img, bitdepth = tiff.read_image(source_path)
        except Exception as exc:
            raise ConverterError(
                f"cannot read {source_path}: {exc}") from exc

        h, w = img.shape[:2]
        levels = self.levels
        # Tiny images can't sustain 6 levels; clamp like encoders do.
        while levels > 1 and (min(h, w) >> levels) < 4:
            levels -= 1
        params = EncodeParams(
            lossless=conversion == Conversion.LOSSLESS,
            levels=levels,
            tile_size=TILE_SIZE if h * w > TILE_THRESHOLD else None,
            # The base step is calibrated for 8-bit signals; scale it with
            # the signal range so 16-bit scans lose proportionally.
            base_delta=self.lossy_base_delta * (1 << (bitdepth - 8)),
        )
        try:
            data = encode_jp2(img, bitdepth, params, jpx=self.jpx)
        except Exception as exc:
            raise ConverterError(
                f"encode failed for {image_id}: {exc}") from exc

        dest = output_path(image_id, ".jpx" if self.jpx else ".jp2")
        # Unique temp name: concurrent converts of the same id must not
        # interleave writes before the atomic replace.
        tmp = f"{dest}.{os.getpid()}.{id(data):x}.part"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, dest)
        return dest
