"""graftscope through the product surface: the traced HTTP stack
(request ids, span trees for region reads, flight/trace debug
endpoints, Prometheus format, SLO breach handling) and the
merged-device-launch span links through the real scheduler."""
import json
import logging
import threading

import numpy as np
import pytest

from bucketeer_tpu import config as cfg
from bucketeer_tpu import features
from bucketeer_tpu import obs
from bucketeer_tpu.codec import encoder as codec_encoder
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.converters import output_path
from bucketeer_tpu.engine import Engine, FakeS3Client, RecordingSlackClient
from bucketeer_tpu.obs import logctx
from bucketeer_tpu.server.app import build_app


@pytest.fixture
def fresh_obs():
    """A fresh recorder for the app to adopt (Api's maybe_install keeps
    an existing one), torn down afterwards so later tests see the
    disabled fast path."""
    obs.install(None)
    logctx.uninstall()
    try:
        yield
    finally:
        obs.install(None)
        logctx.uninstall()


@pytest.fixture
def env_client(tmp_path, aiohttp_client, fresh_obs):
    """(http client, engine) factory — the test_api harness, local to
    this module (fixtures don't import across test files)."""

    async def factory(extra_config=None):
        overrides = {
            cfg.IIIF_URL: "http://iiif.test/iiif",
            cfg.SLACK_CHANNEL_ID: "chan",
            cfg.FILESYSTEM_CSV_MOUNT: str(tmp_path / "csv-mount"),
        }
        overrides.update(extra_config or {})
        config = cfg.Config.load(overrides=overrides)
        engine = Engine(
            config,
            flags=features.FeatureFlagChecker(static={}),
            converter=None,
            s3_client=FakeS3Client(str(tmp_path / "s3")),
            slack_client=RecordingSlackClient())
        app = build_app(engine, job_delete_timeout=0.1)
        client = await aiohttp_client(app)
        return client, engine

    return factory


def _write_derivative(tmp_path, monkeypatch, image_id="ark:/9/obs",
                      size=64):
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    rng = np.random.default_rng(11)
    img = rng.integers(0, 256, size=(size, size, 3)).astype(np.uint8)
    data = codec_encoder.encode_jp2(
        img, 8, EncodeParams(lossless=True, levels=2, tile_size=size,
                             gen_plt=True), jpx=True)
    with open(output_path(image_id, ".jpx"), "wb") as fh:
        fh.write(data)
    return img


async def test_region_read_yields_complete_span_tree(
        env_client, tmp_path, monkeypatch):
    """Acceptance (ISSUE 14): one GET /images/{id}?region=... request
    produces a complete exported span tree — HTTP root -> admitted
    read (queue wait) -> decode — with the same request id on every
    span, honored from the inbound X-Request-Id header and echoed in
    the response; the export is valid Chrome-trace JSON."""
    _write_derivative(tmp_path, monkeypatch)
    client, _ = await env_client()

    resp = await client.get(
        "/images/ark:%2F9%2Fobs?region=0,0,32,32&format=raw",
        headers={"X-Request-Id": "acc-1"})
    assert resp.status == 200
    assert resp.headers["X-Request-Id"] == "acc-1"

    trace = await client.get("/debug/trace/acc-1")
    assert trace.status == 200
    doc = json.loads(await trace.text())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    # HTTP root -> handler stage -> scheduler queue wait -> decode job.
    assert {"http.get_image", "image_read", "decode.queue_wait",
            "decode.read"} <= names, names
    for e in xs:
        assert e["args"]["request_id"] == "acc-1", e
    # Parent links resolve within the tree: everything hangs off the
    # HTTP root.
    ids = {e["args"]["span_id"] for e in xs}
    roots = [e for e in xs if "parent_id" not in e["args"]]
    assert [e["name"] for e in roots] == ["http.get_image"]
    for e in xs:
        if "parent_id" in e["args"]:
            assert e["args"]["parent_id"] in ids, e
    # Structural Chrome-trace contract.
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M")
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0


async def test_error_path_stamps_logs_and_dumps_flight(
        env_client, tmp_path, monkeypatch, caplog):
    """A 5xx outcome auto-freezes the flight recorder with the request
    id, and the log lines the request emitted carry the same id."""
    monkeypatch.setenv("BUCKETEER_TMPDIR", str(tmp_path))
    with open(output_path("ark:/9/bad", ".jpx"), "wb") as fh:
        fh.write(b"not a jp2 at all")
    client, _ = await env_client()

    with caplog.at_level(logging.WARNING):
        resp = await client.get("/images/ark:%2F9%2Fbad",
                                headers={"X-Request-Id": "err-7"})
    assert resp.status == 500
    assert resp.headers["X-Request-Id"] == "err-7"
    decode_logs = [r for r in caplog.records
                   if "decode failed" in r.message]
    assert decode_logs, "expected the handler's decode-failure log"
    for record in decode_logs:
        assert record.request_id == "err-7"

    flight = await client.get("/debug/flight")
    report = json.loads(await flight.text())
    assert report["enabled"] is True
    reasons = {(d["reason"], d["request_id"]) for d in report["dumps"]}
    assert ("error:get_image", "err-7") in reasons, reasons


async def test_slo_breach_triggers_flight_dump(env_client):
    """Test-pinned acceptance: an SLO breach bumps the breach counters
    and freezes the flight recorder."""
    client, _ = await env_client(
        extra_config={cfg.SLO: "default=0.000001"})
    resp = await client.get("/status")
    assert resp.status == 200
    rid = resp.headers["X-Request-Id"]
    assert rid                       # generated when not supplied

    metrics = json.loads(await (await client.get("/metrics")).text())
    counters = metrics["counters"]
    assert counters["slo.breaches"] >= 1
    assert counters["slo.breach.get_status"] >= 1
    assert metrics["slo"]["default_ms"] == pytest.approx(1e-6)

    report = json.loads(await (await client.get("/debug/flight")).text())
    assert any(d["reason"] == "slo-breach:get_status"
               for d in report["dumps"]), report["dumps"]


async def test_metrics_formats_and_endpoint_percentiles(env_client):
    client, _ = await env_client()
    await client.get("/status")
    await client.get("/status")

    rep = json.loads(await (await client.get("/metrics")).text())
    status_stage = rep["stages"]["http.get_status"]
    assert status_stage["count"] >= 2
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        assert key in status_stage

    prom = await client.get("/metrics?format=prometheus")
    assert prom.status == 200
    assert prom.content_type == "text/plain"
    text = await prom.text()
    assert "# TYPE bucketeer_stage_seconds histogram" in text
    assert 'bucketeer_stage_seconds_bucket{stage="http.get_status"' \
        in text
    assert 'le="+Inf"' in text
    assert 'bucketeer_stage_seconds_count{stage="http.get_status"}' \
        in text

    assert (await client.get("/metrics?format=bogus")).status == 400


async def test_flight_endpoint_freeze_and_fetch(env_client):
    client, _ = await env_client()
    await client.get("/status")
    report = json.loads(
        await (await client.get("/debug/flight?freeze=1")).text())
    assert report["enabled"] is True
    assert report["dumps"], report
    seq = report["dumps"][-1]["seq"]
    entry = json.loads(
        await (await client.get(f"/debug/flight?dump={seq}")).text())
    assert entry["seq"] == seq
    assert isinstance(entry["spans"], list)
    assert (await client.get("/debug/flight?dump=xyz")).status == 400
    assert (await client.get("/debug/flight?dump=99999")).status == 404
    assert (await client.get("/debug/trace/nope-absent")).status == 404


def test_merged_launch_span_links_both_requests():
    """Acceptance (ISSUE 14): a device launch that merges chunks from
    two requests yields ONE launch span, linked to both request
    contexts, carrying occupancy and the graftcost-modeled cost; each
    request's Chrome export includes the shared launch span."""
    from bucketeer_tpu.engine.scheduler import (EncodeScheduler,
                                                _SlicedPending)
    from bucketeer_tpu.obs.trace import Recorder

    class FakePending:
        def __init__(self, n):
            self.n = n

        def resolve_stats(self, tile_off=0, n_tiles=None):
            return ("stats", tile_off, n_tiles)

    def stub_launch(plan, tiles, mode="rows"):
        return FakePending(len(tiles))

    prev = obs.get_recorder()
    for attempt in range(5):
        rec = Recorder()
        obs.install(rec)
        try:
            sched = EncodeScheduler(window_s=0.5, max_concurrent=4)
            sched.launch_fn = stub_launch
            plan = ("plan", 4, 4)
            tiles = np.zeros((1, 4, 4, 3), dtype=np.uint8)
            results = {}
            barrier = threading.Barrier(2)

            def client(i):
                with obs.request_context(f"req-{i}"):
                    barrier.wait()
                    results[i] = sched.submit(
                        lambda: sched.dispatch_frontend(plan, tiles))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            sched.close()

            launches = [s for s in rec.snapshot()
                        if s["name"] == "device.launch"]
            assert launches, "no launch span recorded"
            merged = [s for s in launches
                      if s["attrs"]["occupancy"] == 2]
            if not merged:
                continue      # unlucky schedule: retry the merge
            (launch,) = merged
            linked = {link[0] for link in launch["links"]}
            assert linked == {"req-0", "req-1"}, launch["links"]
            assert launch["attrs"]["tiles"] == 2
            assert launch["attrs"]["mode"] == "rows"
            assert launch["attrs"]["device_id"] == 0
            # graftcost-modeled cost beside the measured duration —
            # the per-launch measured-vs-modeled drift sample.
            assert launch["attrs"]["modeled_s"] > 0
            assert launch["attrs"]["modeled_from"].startswith(
                "frontend.rows/")
            assert launch["dur"] >= 0
            # Both requests got sliced views of the one merged launch.
            assert {type(r) for r in results.values()} == {
                _SlicedPending}
            for i in range(2):
                doc = obs.chrome_trace(f"req-{i}")
                names = {e["name"] for e in doc["traceEvents"]
                         if e["ph"] == "X"}
                assert {"encode.queue_wait", "device.launch"} <= names
            return
        finally:
            obs.install(prev)
    raise AssertionError("no merged (occupancy=2) launch in 5 attempts")


def test_real_encode_span_tree_through_scheduler():
    """A real (tiny) encode through the scheduler with tracing on:
    dispatch, host Tier-1 pool item, reassembly and Tier-2 spans all
    appear under the request's trace — the encode-side span coverage
    the flight recorder shows in production."""
    from bucketeer_tpu.engine.scheduler import EncodeScheduler
    from bucketeer_tpu.obs.trace import Recorder

    prev = obs.get_recorder()
    rec = Recorder()
    obs.install(rec)
    try:
        sched = EncodeScheduler(window_s=0.0)
        img = np.linspace(0, 255, 64 * 64 * 3).reshape(
            64, 64, 3).astype(np.uint8)
        with obs.request_context("enc-1"):
            out = sched.encode_jp2(img, 8, EncodeParams(
                lossless=True, levels=2))
        sched.close()
        assert out[:4] == b"\x00\x00\x00\x0c"      # JP2 signature box
        mine = {s["name"] for s in rec.spans_for("enc-1")}
        assert {"encode.queue_wait", "encode.dispatch",
                "encode.resolve_stats", "encode.host_t1",
                "encode.reassemble", "encode.tier2"} <= mine, mine
        # The pool item ran on a sched-t1 thread yet joined the trace.
        host = [s for s in rec.spans_for("enc-1")
                if s["name"] == "encode.host_t1"]
        assert any(s["thread"].startswith("sched-t1") for s in host)
    finally:
        obs.install(prev)
