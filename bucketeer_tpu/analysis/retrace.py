"""Recompile sentinel: count XLA traces per pipeline stage.

Every retrace of a jitted stage is a multi-second compile stall on TPU
and usually a bug (an unstable shape or dtype leaking into a supposedly
bucketed call path — exactly the regression class the front-end's
power-of-two batch bucketing exists to prevent). The codec wraps the
Python callable of each jitted program with :func:`instrument`; the
wrapper body only executes when JAX traces it, so ``TRACE_COUNTS``
counts compilations, not calls, with zero steady-state overhead.

Tests assert stability with :func:`expect_max_retraces`::

    with retrace.expect_max_retraces(0, stages=("transform",)):
        encode_array(img)          # second encode of the same geometry

Works on every JAX version (it relies on nothing but trace-time
execution of the wrapped Python body).
"""
from __future__ import annotations

import contextlib
from collections import Counter

TRACE_COUNTS: Counter = Counter()


def instrument(stage: str, fn):
    """Wrap ``fn`` so each JAX trace of it bumps ``TRACE_COUNTS[stage]``.

    The returned wrapper is what gets jitted; its Python body runs once
    per (re)compilation and never again, so the counter is exactly the
    number of traced program variants.
    """
    def traced(*args, **kwargs):
        TRACE_COUNTS[stage] += 1
        return fn(*args, **kwargs)
    traced.__name__ = getattr(fn, "__name__", stage)
    return traced


def snapshot() -> dict:
    return dict(TRACE_COUNTS)


def delta(before: dict, stages=None) -> dict:
    """New traces per stage since ``before`` (only nonzero entries)."""
    out = {}
    for stage, count in TRACE_COUNTS.items():
        if stages is not None and stage not in stages:
            continue
        d = count - before.get(stage, 0)
        if d:
            out[stage] = d
    return out


class RetraceError(AssertionError):
    """More XLA recompilations than the test allowed."""


@contextlib.contextmanager
def expect_max_retraces(n: int, stages=None):
    """Fail if the enclosed block triggers more than ``n`` new traces
    (across ``stages``, or all instrumented stages when None)."""
    before = snapshot()
    yield
    new = delta(before, stages)
    total = sum(new.values())
    if total > n:
        raise RetraceError(
            f"expected at most {n} XLA retrace(s), got {total}: {new} "
            "— a shape or dtype is unstable on the jit path")
