"""Batched Tier-1 dispatch: native C++ thread pool when available, pure
Python fallback otherwise (reference analog: ConverterFactory probing for
Kakadu and falling back, converters/ConverterFactory.java:37-47).

The whole image's code-blocks go through one call so the native thread
pool sees the full parallelism (blocks are independent — SURVEY.md §7).
"""
from __future__ import annotations

import os

import numpy as np

from .. import native
from ..analysis.contracts import contract
from . import t1

_BAND_CLS = {"LL": 0, "LH": 0, "HH": 1, "HL": 2}


def default_threads() -> int:
    env = os.environ.get("BUCKETEER_T1_THREADS")
    if env:
        return max(1, int(env))
    return max(1, (os.cpu_count() or 2) - 1)


def _collect(lib, handle, n: int) -> list:
    """Pull a native T1Result handle into [t1.CodedBlock]."""
    try:
        nbps = np.zeros(n, dtype=np.int32)
        npasses = np.zeros(n, dtype=np.int32)
        nbytes = np.zeros(n, dtype=np.int64)
        lib.t1_block_sizes(handle, nbps.ctypes.data, npasses.ctypes.data,
                           nbytes.ctypes.data)
        out = []
        for i in range(n):
            np_i, nb_i = int(npasses[i]), int(nbytes[i])
            data = np.empty(max(nb_i, 1), dtype=np.uint8)
            ptype = np.zeros(max(np_i, 1), dtype=np.int32)
            pplane = np.zeros(max(np_i, 1), dtype=np.int32)
            plen = np.zeros(max(np_i, 1), dtype=np.int64)
            pdist = np.zeros(max(np_i, 1), dtype=np.float64)
            lib.t1_block_get(handle, i, data.ctypes.data, ptype.ctypes.data,
                             pplane.ctypes.data, plen.ctypes.data,
                             pdist.ctypes.data)
            passes = [t1.PassInfo(int(ptype[k]), int(pplane[k]),
                                  int(plen[k]), float(pdist[k]))
                      for k in range(np_i)]
            out.append(t1.CodedBlock(bytes(data[:nb_i].tobytes()),
                                     int(nbps[i]), passes))
        return out
    finally:
        lib.t1_result_free(handle)


@contract(shapes={"payload": ("R", 512), "offsets": ("n1",),
                  "nbps": ("n",), "floors": ("n",), "hs": ("n",),
                  "ws": ("n",)},
          dtypes={"payload": "uint8", "offsets": "integer",
                  "nbps": "integer", "floors": "integer",
                  "hs": "integer", "ws": "integer"})
def encode_packed(payload: np.ndarray, offsets: np.ndarray,
                  nbps: np.ndarray, floors: np.ndarray,
                  hs: np.ndarray, ws: np.ndarray,
                  bands: list) -> list:
    """Tier-1 over the device front-end's packed bitmap payload
    (codec/frontend.py): payload (R, 512) uint8 rows, offsets (n+1,)
    row offsets per block, per-block nbps/floors/dims and band names.
    Returns [t1.CodedBlock] in block order."""
    n = len(nbps)
    lib = native.load()
    cls = np.array([_BAND_CLS[b] for b in bands], dtype=np.int32)
    if lib is not None and n:
        # Bind every converted array to a local: .ctypes.data of an
        # unnamed temporary is a dangling pointer by call time.
        payload = np.ascontiguousarray(payload, dtype=np.uint8)
        offs = np.ascontiguousarray(offsets[:n], dtype=np.int64)
        nbps_c = np.ascontiguousarray(nbps, dtype=np.int32)
        floors_c = np.ascontiguousarray(floors, dtype=np.int32)
        hs_c = np.ascontiguousarray(hs, dtype=np.int32)
        ws_c = np.ascontiguousarray(ws, dtype=np.int32)
        handle = lib.t1_encode_packed(
            n, payload.ctypes.data, offs.ctypes.data, nbps_c.ctypes.data,
            floors_c.ctypes.data, hs_c.ctypes.data, ws_c.ctypes.data,
            cls.ctypes.data, default_threads())
        return _collect(lib, handle, n)
    out = []
    for i in range(n):
        if nbps[i] <= floors[i]:
            out.append(t1.CodedBlock(b"", 0))
            continue
        from . import frontend
        mags, negs = frontend.unpack_block(payload, int(offsets[i]),
                                           int(nbps[i]), int(floors[i]),
                                           int(hs[i]), int(ws[i]))
        out.append(t1.encode_block(mags, negs, bands[i],
                                   floor=int(floors[i])))
    return out


def encode_blocks(specs: list) -> list:
    """specs: [(mags uint32 (h,w), signs bool (h,w), band_name,
    fracs uint8 (h,w) | None)] -> [t1.CodedBlock] in order."""
    lib = native.load()
    if lib is None or not specs:
        return [t1.encode_block(m, s, b, f) for m, s, b, f in specs]

    n = len(specs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    hs = np.zeros(n, dtype=np.int32)
    ws = np.zeros(n, dtype=np.int32)
    cls = np.zeros(n, dtype=np.int32)
    any_fracs = any(f is not None for _, _, _, f in specs)
    for i, (m, _, band, _) in enumerate(specs):
        hs[i], ws[i] = m.shape
        cls[i] = _BAND_CLS[band]
        offsets[i + 1] = offsets[i] + m.size
    total = int(offsets[-1])
    mags = np.empty(total, dtype=np.uint32)
    negs = np.empty(total, dtype=np.uint8)
    fracs = np.zeros(total, dtype=np.uint8) if any_fracs else None
    for i, (m, s, _, f) in enumerate(specs):
        mags[offsets[i]:offsets[i + 1]] = np.ascontiguousarray(
            m, dtype=np.uint32).ravel()
        negs[offsets[i]:offsets[i + 1]] = np.ascontiguousarray(
            s, dtype=np.uint8).ravel()
        if f is not None:
            fracs[offsets[i]:offsets[i + 1]] = np.ascontiguousarray(
                f, dtype=np.uint8).ravel()

    handle = lib.t1_encode_blocks(
        n, mags.ctypes.data, negs.ctypes.data,
        fracs.ctypes.data if fracs is not None else None,
        offsets.ctypes.data,
        hs.ctypes.data, ws.ctypes.data, cls.ctypes.data, default_threads())
    return _collect(lib, handle, n)
