"""graftrace: deterministic interleaving explorer + happens-before race
detector for the serving core.

Two cooperating halves:

- **Controlled scheduler** (:mod:`runtime`): instrumented drop-ins for
  ``threading.Lock``/``RLock``/``Condition``/``Event`` plus explicit
  field-access yield points, installed through the production seams in
  :mod:`seam` (zero-overhead no-ops until a runtime is installed). When
  active, every instrumented thread is serialized and the scheduler
  decides, at each yield point, which thread runs next — CHESS-style
  systematic exploration with bounded preemptions, or a seeded-random
  walk. Any schedule replays bit-for-bit from its decision trace, and a
  run where every thread blocks is reported as a deadlock with all
  stacks instead of hanging.
- **Race detector** (:mod:`detector`): a vector-clock happens-before
  checker over the instrumented shared-field accesses, reporting data
  races (both stack traces, locks held on each side) and
  lock-inversion cycles from the dynamic lock-acquisition-order graph.
  :mod:`crosscheck` validates the dynamic verdicts against the static
  ``rules_locks`` field inference — each analysis audits the other.

Entry points: ``python -m bucketeer_tpu.analysis --race`` (see
:mod:`explore` for budgets and trace replay) and the scenario suite in
:mod:`scenarios` covering merged-batch encode, read-vs-batch priority,
QueueFull/deadline expiry, cache eviction and scheduler shutdown/drain.
"""
from .seam import active, install

__all__ = ["active", "install"]
