"""Converter SPI: pluggable TIFF -> JPEG 2000 conversion.

Port of the reference's converter layer (reference:
src/main/java/edu/ucla/library/bucketeer/converters/Converter.java:22,
ConverterFactory.java:37-103, KakaduConverter.java:34-77,
OpenJPEGConverter.java:12-25, AbstractConverter.java:29-39) with the
roles inverted: the in-process TPU encoder is the primary converter (the
reference shells out to the Kakadu binary for this), and the CLI
converters wrap ``kdu_compress`` / ``opj_compress`` when installed — as a
correctness oracle and a no-TPU dev mode.
"""
from .base import Conversion, Converter, ConverterError, output_path
from .cli import CliConverter, KakaduConverter, OpenJPEGConverter
from .factory import available_converters, get_converter
from .reader import TpuReader, derivative_path
from .tpu import TpuConverter

__all__ = [
    "Conversion", "Converter", "ConverterError", "output_path",
    "CliConverter", "KakaduConverter", "OpenJPEGConverter",
    "TpuConverter", "TpuReader", "derivative_path", "get_converter",
    "available_converters",
]
