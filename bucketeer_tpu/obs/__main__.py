"""Sample-trace generator: ``python -m bucketeer_tpu.obs out.json``.

Runs one real (tiny) encode through the cross-request scheduler with
tracing on and writes the request's Chrome-trace JSON — the artifact
the CI ``obs`` job uploads so a reviewer can drop a real span tree
into chrome://tracing or ui.perfetto.dev without booting the server.
``--synthetic`` skips JAX entirely (a hand-built span tree), for
environments without a working backend.
"""
from __future__ import annotations

import json
import sys
import time


def _synthetic_spans():
    from . import request_context, span

    with request_context("sample-request"):
        with span("http.getImage", method="GET", path="/images/sample"):
            with span("decode.queue_wait"):
                time.sleep(0.002)
            with span("decode.read"):
                with span("decode.t2_parse"):
                    time.sleep(0.001)
                with span("decode.t1"):
                    time.sleep(0.003)
                with span("decode.device_inverse"):
                    time.sleep(0.001)


def _real_encode():
    import numpy as np

    from ..codec.encoder import EncodeParams
    from ..engine.scheduler import EncodeScheduler
    from . import request_context, span

    sched = EncodeScheduler(window_s=0.005)
    try:
        img = np.linspace(0, 255, 96 * 96 * 3).reshape(
            96, 96, 3).astype(np.uint8)
        with request_context("sample-request"):
            with span("http.loadImage", method="GET",
                      path="/images/sample/sample.tif"):
                sched.encode_jp2(img, 8, EncodeParams(
                    lossless=True, levels=2))
    finally:
        sched.close()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    synthetic = "--synthetic" in argv
    paths = [a for a in argv if not a.startswith("-")]
    if len(paths) != 1:
        print("usage: python -m bucketeer_tpu.obs [--synthetic] "
              "OUT.json", file=sys.stderr)
        return 2

    from . import Recorder, chrome_trace, install

    install(Recorder())
    try:
        if synthetic:
            _synthetic_spans()
        else:
            try:
                _real_encode()
            # Reported on stderr, then degraded — the artifact must
            # exist even where no backend does.
            except Exception as exc:  # graftlint: disable=swallowed-exception
                print(f"real encode unavailable ({exc}); "
                      "falling back to --synthetic", file=sys.stderr)
                _synthetic_spans()
        doc = chrome_trace("sample-request")
    finally:
        install(None)
    with open(paths[0], "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
    print(f"wrote {len(doc['traceEvents'])} trace event(s) to "
          f"{paths[0]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
