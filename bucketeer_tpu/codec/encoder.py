"""Top-level JPEG 2000 encoder: the TPU-native replacement for the
``kdu_compress`` invocation at the core of the reference service
(reference: converters/KakaduConverter.java:55-77,
converters/AbstractConverter.java:29-39).

Pipeline (SURVEY.md §7 minimum slice):
  host image array -> [device] level shift + RCT/ICT + tiled multi-level
  DWT + quantization (one jitted XLA program per tile shape,
  bucketeer_tpu.codec.pipeline; tiles batched per shape group so an
  image is at most four device calls) -> EBCOT Tier-1 per code-block ->
  PCRD-opt layer allocation (codec/rate.py) -> Tier-2 packets with real
  precincts, any of the five progressions, SOP/EPH/PLT markers and
  per-resolution tile-parts -> codestream -> JP2/JPX boxes.

The full structural recipe of the reference's Kakadu invocation
(``Clevels=6 Clayers=6 Cprecincts={256,256},{256,256},{128,128}
Stiles={512,512} Corder=RPCL ORGgen_plt=yes ORGtparts=R Cblk={64,64}
Cuse_sop=yes Cuse_eph=yes``, lossy ``-rate 3``; reference:
converters/KakaduConverter.java:38-44) is available via
:meth:`EncodeParams.kakadu_recipe`.

This module is the orchestration; it works standalone on CPU (the same
jitted program runs on the host backend) so the service runs in a no-TPU
dev mode, mirroring how the reference degrades to OpenJPEG when Kakadu is
absent (reference: converters/ConverterFactory.java:37-47).
"""
from __future__ import annotations

import contextlib
import math
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from .. import obs
from ..analysis.contracts import contract
from ..config import truthy as cfg_truthy
from . import codestream as cs
from . import cxd as cxd_mod
from . import frontend
from . import jp2 as jp2box
from . import rate as rate_mod
from . import t1, t1_batch, t2
from .dwt import synthesis_gains
from .pipeline import TilePlan, make_plan
from .quant import FRAC_BITS, GUARD_BITS, SubbandQuant

CBLK_EXP = 6  # 64x64 code-blocks (reference recipe Cblk={64,64})

# --- overlapped pipeline knobs -------------------------------------------
# The encoder is a two-stage pipeline: the jitted device front-end
# (transform + blockify + bit-plane pack) and host Tier-1 entropy coding
# (native thread pool). Tile groups are split into chunks of
# BUCKETEER_OVERLAP_TILES tiles; while chunk N's packed payload is coded
# on the host worker, chunk N+1's device program is already dispatched
# (JAX async dispatch), so host entropy coding hides behind device
# compute (SURVEY.md §7 hard part 6).
OVERLAP_DEPTH = 2       # dispatched-but-unfetched chunks (staging buffers)
HOST_QUEUE_DEPTH = 2    # unfinished host-coding jobs before back-pressure


def _overlap_tiles() -> int:
    """Tiles per pipeline chunk. Power-of-two keeps the batch bucketing
    (pipeline._bucket) from compiling extra program variants."""
    return max(1, int(os.environ.get("BUCKETEER_OVERLAP_TILES", "8")))


def _device_cxd(params: EncodeParams) -> bool:
    """Whether this encode runs the device-CX/D Tier-1 split: the
    explicit EncodeParams.device_cxd wins, else BUCKETEER_DEVICE_CXD
    (config.truthy spellings)."""
    if params.device_cxd is not None:
        return bool(params.device_cxd)
    return cfg_truthy(os.environ.get("BUCKETEER_DEVICE_CXD"))


def _device_mq(params: EncodeParams) -> bool:
    """Whether this encode runs Tier-1 entirely on device (the fused
    CX/D + MQ program, codec/cxd.py run_device_mq): the explicit
    EncodeParams.device_mq wins, else BUCKETEER_DEVICE_MQ. The env
    default is "auto": device MQ on the TPU backend only — on the CPU
    backend the jnp scans emulate the device and the measured
    ``tier1_split`` (BENCH_r08) shows the native host replay beating
    the emulated device by orders of magnitude, and other accelerator
    backends stay opt-in until their own split is measured; flip with
    BUCKETEER_DEVICE_MQ=1/0 (docs/pipeline.md flag table)."""
    if params.device_mq is not None:
        return bool(params.device_mq)
    env = os.environ.get("BUCKETEER_DEVICE_MQ", "auto")
    if env == "auto":
        import jax
        return jax.default_backend() == "tpu"
    return cfg_truthy(env)


class _ImmediateResult:
    """Future-quack for Tier-1 work finished inline. Device-MQ mode
    bypasses the host Tier-1 pool entirely — the blocks come back
    assembled from the device fetch — but the pipeline's ordered
    reassembly (``futs`` submission order) stays uniform."""

    __slots__ = ("_value",)

    def __init__(self, value) -> None:
        self._value = value

    def done(self) -> bool:
        return True

    def result(self):
        return self._value


# Optional per-stage timing/counter sink (server.metrics.Metrics). The
# server installs its instance at boot so /metrics shows the encoder's
# device-dispatch vs host-coding segments and the measured overlap.
_metrics_sink = None


def set_metrics_sink(sink) -> None:
    """Install a metrics sink with ``record(stage, seconds, pixels=0)``,
    ``record_overlap(stage, device_s, host_s, wall_s, pixels=0)`` and
    ``count(name, n=1)`` (server.metrics.Metrics). None disables."""
    global _metrics_sink
    _metrics_sink = sink


# --- scheduler seam -------------------------------------------------------
# The cross-request encode scheduler (engine/scheduler.py) routes an
# encode's device dispatch and host Tier-1 through process-wide shared
# resources. It installs them thread-locally around the encode call so
# nothing about encode_array's signature or its per-request pipeline
# logic changes: with no services installed the encoder behaves exactly
# as before (private one-worker host executor, direct device dispatch).

_SERVICES = threading.local()


@dataclass
class _PipelineServices:
    dispatch: object          # callable(plan, tiles, mode=...) -> pending
    pool: object              # shared executor; NOT shut down per encode
    check: object = None      # callable raising on deadline/cancel
    t1_launch: object = None  # callable(stage_fn, payload) -> stage
                              # result; the scheduler's pipeline-stage
                              # hook for the fused CX/D+MQ program
                              # (None = run inline on this thread)


def current_services() -> _PipelineServices | None:
    return getattr(_SERVICES, "svc", None)


@contextlib.contextmanager
def pipeline_services(dispatch=None, pool=None, check=None,
                      t1_launch=None):
    """Install scheduler-owned pipeline services for encodes running on
    this thread (the scheduler wraps each admitted request in this)."""
    prev = getattr(_SERVICES, "svc", None)
    _SERVICES.svc = _PipelineServices(dispatch, pool, check, t1_launch)
    try:
        yield
    finally:
        _SERVICES.svc = prev


@dataclass
class EncodeParams:
    lossless: bool = True
    levels: int = 5
    tile_size: int | None = None       # None = single tile (whole image)
    base_delta: float = 0.5            # irreversible base step (image domain)
    n_layers: int = 1
    progression: int = cs.PROG_LRCP
    rate: float | None = None          # target bpp for the whole file (lossy)
    precincts: tuple | None = None     # ((w,h),...) highest-resolution first
    use_sop: bool = False
    use_eph: bool = False
    gen_plt: bool = False
    tparts_r: bool = False             # tile-part per resolution (ORGtparts=R)
    mct: str = "auto"                  # multi-component transform: auto|on|off
    comment: str = "bucketeer-tpu jp2 encoder"
    # Tier-1 split: run EBCOT context modeling on the device and replay
    # the CX/D streams through the host MQ coder (codec/cxd.py +
    # native t1_encode_cxd). None = the BUCKETEER_DEVICE_CXD env flag
    # decides; the converter wires the bucketeer.tpu.device.cxd config
    # key through here. Byte-identical output either way.
    device_cxd: bool | None = None
    # Full Tier-1 on device: chain the MQ arithmetic coder after the
    # CX/D scan (codec/cxd.py run_device_mq) so the device emits
    # finished per-pass byte segments and the host does Tier-2 assembly
    # only — no MQ replay, no host Tier-1 pool. None = the
    # BUCKETEER_DEVICE_MQ env flag decides; the converter wires the
    # bucketeer.tpu.device.mq config key through here. Implies the
    # CX/D split. Byte-identical output in every mode.
    device_mq: bool | None = None

    @classmethod
    def kakadu_recipe(cls, lossless: bool,
                      rate: float | None = 3.0) -> "EncodeParams":
        """The reference's exact Kakadu option set
        (converters/KakaduConverter.java:38-44): 6 levels, 6 layers,
        512x512 tiles, RPCL, precincts 256/256/128, SOP+EPH, PLT,
        R tile-parts; lossless = reversible unbounded rate, lossy 3 bpp.
        """
        return cls(lossless=lossless, levels=6, tile_size=512,
                   base_delta=1.0 if lossless else 2.0,
                   n_layers=6, progression=cs.PROG_RPCL,
                   rate=None if lossless else rate,
                   precincts=((256, 256), (256, 256), (128, 128)),
                   use_sop=True, use_eph=True, gen_plt=True, tparts_r=True)


def _ceil_div(a: int, b: int) -> int:
    return -((-a) // b)


def _band_rect(tcx0: int, tcx1: int, tcy0: int, tcy1: int,
               res: int, name: str, levels: int) -> tuple:
    """Global band-coordinate rectangle of a tile-component's subband
    (T.800 eq. B-15, image/tile offsets 0)."""
    if name == "LL":
        k, xob, yob = levels, 0, 0
    else:
        k = levels - res + 1
        xob = 1 if name in ("HL", "HH") else 0
        yob = 1 if name in ("LH", "HH") else 0
    step = 1 << k
    half = 1 << (k - 1)
    bx0 = _ceil_div(tcx0 - half * xob, step)
    bx1 = _ceil_div(tcx1 - half * xob, step)
    by0 = _ceil_div(tcy0 - half * yob, step)
    by1 = _ceil_div(tcy1 - half * yob, step)
    return bx0, bx1, by0, by1


def _precinct_exps(params: EncodeParams, levels: int) -> list:
    """Per-resolution (PPx, PPy) exponents on the resolution grid,
    r=0 (coarsest) first. Kakadu's Cprecincts lists highest resolution
    first with the last entry repeating downward
    (KakaduConverter.java:39)."""
    if not params.precincts:
        return [(15, 15)] * (levels + 1)
    spec = [(int(math.log2(w)), int(math.log2(h)))
            for w, h in params.precincts]
    out = []
    for r in range(levels + 1):
        i = levels - r
        ppx, ppy = spec[i] if i < len(spec) else spec[-1]
        eff = ppx - (1 if r > 0 else 0)
        assert eff >= CBLK_EXP, (
            f"precinct 2^{ppx} at res {r} smaller than the 64x64 "
            "code-block; shrink Cblk or grow the precinct")
        out.append((ppx, ppy))
    return out


# L2 norms of the inverse multi-component transform's columns: a unit
# error in Y/Cb/Cr maps to this much RGB error, so PCRD must scale
# component distortions by norm² or chroma is starved (the classic
# "grayscale matches, RGB lags" failure).
_ICT_NORMS = (1.7321, 1.8051, 1.5734)
_RCT_NORMS = (1.7321, 0.8292, 0.8292)


def _rd_at_rate(x2w: np.ndarray, r_target: float,
                lam_fixed: float | None) -> float:
    """Water-filling over per-sample 'coefficient' energies.

    x2w: RGB-domain weighted energies (w_c · x²). At slope λ every
    coded coefficient sits at RGB-domain distortion λ (component
    distortion λ/w_c), coding rate ½log2(x2w/λ). With a rate target,
    bisect λ to hit it and return the total distortion Σ min(x2w, λ);
    with λ fixed (no rate target), return the rate at that slope
    (smaller = cheaper at matched distortion)."""
    l2 = 0.5 * np.log2(x2w)
    if lam_fixed is not None:
        return float(np.maximum(0.0, l2 - 0.5 * math.log2(
            lam_fixed)).sum())
    lo, hi = 1e-9, float(x2w.max()) + 1.0
    for _ in range(50):
        lam = (lo * hi) ** 0.5
        r = float(np.maximum(0.0, l2 - 0.5 * math.log2(lam)).sum())
        if r > r_target:
            lo = lam
        else:
            hi = lam
    lam = (lo * hi) ** 0.5
    return float(np.minimum(x2w, lam).sum())


def _mct_helps(img: np.ndarray, lossless: bool,
               rate: float | None = None,
               base_delta: float = 0.5) -> bool:
    """Per-image, per-rate choice of the multi-component transform.

    The ICT/RCT only pays when the channels correlate *at the operating
    point*: correlated structure favors it, but channel-independent
    fine detail (sensor noise, false color) makes per-channel coding
    cheaper — and which effect wins depends on the target rate (at high
    rates the independent residue dominates the marginal bit). So model
    both bases with water-filling R-D over high-frequency (gradient)
    samples — weighted by the squared inverse-transform column norms
    that map component error to RGB error — and pick the basis with
    less distortion at the target rate (or less rate at the quantizer
    floor when uncapped). kdu_compress applies the ICT unconditionally
    (reference: converters/KakaduConverter.java:38-44, no Cycc=no), so
    this choice matches it on photographs and beats it on
    channel-independent content.
    """
    h, w = img.shape[:2]
    step = max(1, max(h, w) // 256)
    a = img[::step, ::step].astype(np.float32)
    g = np.concatenate([np.diff(a, axis=1).reshape(-1, 3),
                        np.diff(a, axis=0).reshape(-1, 3)])
    if g.shape[0] > 65536:        # bound the host cost of the decision
        g = g[:: g.shape[0] // 65536 + 1]
    n = g.shape[0]
    if n < 16:
        return True
    r, gg, b = g[:, 0], g[:, 1], g[:, 2]
    if lossless:
        comps = ((r + 2 * gg + b) / 4.0, b - gg, r - gg)
        norms2 = [m * m for m in _RCT_NORMS]
    else:
        comps = (0.299 * r + 0.587 * gg + 0.114 * b,
                 -0.16875 * r - 0.33126 * gg + 0.5 * b,
                 0.5 * r - 0.41869 * gg - 0.08131 * b)
        norms2 = [m * m for m in _ICT_NORMS]

    eps = 1e-4
    x2w_rgb = (g * g).reshape(-1) + eps
    x2w_ycc = np.concatenate([w * (c * c) + eps
                              for c, w in zip(comps, norms2)])

    if rate is not None:
        # Total bit budget for the sampled pixels (rate is bpp over all
        # components); lower distortion at that budget wins.
        r_target = rate * n
        return _rd_at_rate(x2w_ycc, r_target, None) < _rd_at_rate(
            x2w_rgb, r_target, None)
    # No rate cap: compare rate at the quantizer floor.
    lam = max((1.0 if lossless else base_delta) ** 2 / 12.0, 1e-6)
    return _rd_at_rate(x2w_ycc, 0.0, lam) < _rd_at_rate(
        x2w_rgb, 0.0, lam)


@dataclass
class _Band:
    name: str
    res: int
    comp: int
    q: SubbandQuant
    bx0: int
    bx1: int
    by0: int
    by1: int
    mags: np.ndarray | None
    signs: np.ndarray | None
    fracs: np.ndarray | None
    blocks: dict = field(default_factory=dict)  # (cy, cx) -> t1.CodedBlock

    @property
    def cell_range(self):
        """Global 64-grid cell index ranges [cx0, cx1) x [cy0, cy1)."""
        if self.bx1 <= self.bx0 or self.by1 <= self.by0:
            return 0, 0, 0, 0
        return (self.bx0 >> CBLK_EXP, ((self.bx1 - 1) >> CBLK_EXP) + 1,
                self.by0 >> CBLK_EXP, ((self.by1 - 1) >> CBLK_EXP) + 1)


def _grid_aligned(plan: TilePlan, origin: tuple) -> str:
    """Classify a tile at ``origin`` for the Tier-1 path choice:

    - ``"ok"``: every sub-band block lands on the global 64-grid exactly
      where the device front-end's band-local blockification puts it (no
      global cell boundary cuts a band's interior) — the packed device
      path applies. Holds for power-of-two tile grids.
    - ``"straddle"``: band geometry matches the local Mallat layout but a
      global 64-grid cell boundary cuts a band's interior (e.g. tile 96
      at 2 levels) — the host Tier-1 path (_legacy_tier1) slices blocks
      against the global cell grid instead.
    - ``"mismatch"``: the tile's *global* band rectangle disagrees with
      the local Mallat geometry (tile size not divisible by 2^levels,
      e.g. tile 50 at 2 levels: global LL height 12 vs local 13). No
      path can code such a tile — the device produces band arrays of the
      wrong shape — so encode_array raises NotImplementedError instead
      of letting _legacy_tier1 die on an alignment assert downstream.
    """
    y0, x0 = origin
    tcx1, tcy1 = x0 + plan.tile_w, y0 + plan.tile_h
    cb = 1 << CBLK_EXP
    state = "ok"
    for slot in plan.slots:
        bx0, bx1, by0, by1 = _band_rect(x0, tcx1, y0, tcy1,
                                        slot.resolution, slot.name,
                                        plan.levels)
        if (by1 - by0, bx1 - bx0) != (slot.h, slot.w):
            return "mismatch"
        if by0 % cb and (by0 % cb) + slot.h > cb:
            state = "straddle"
        if bx0 % cb and (bx0 % cb) + slot.w > cb:
            state = "straddle"
    return state


def _collect_blocks(band: _Band, specs: list, dests: list) -> None:
    """Queue a band's code-blocks (global 64-grid cells intersecting the
    tile-band rect, T.800 B.7) into the host Tier-1 batch — the legacy
    path for tile grids the device front-end cannot blockify."""
    cx0, cx1, cy0, cy1 = band.cell_range
    for cy in range(cy0, cy1):
        for cx in range(cx0, cx1):
            gy0 = max(cy << CBLK_EXP, band.by0)
            gy1 = min((cy + 1) << CBLK_EXP, band.by1)
            gx0 = max(cx << CBLK_EXP, band.bx0)
            gx1 = min((cx + 1) << CBLK_EXP, band.bx1)
            ly0, lx0 = gy0 - band.by0, gx0 - band.bx0
            sl = (slice(ly0, ly0 + gy1 - gy0), slice(lx0, lx0 + gx1 - gx0))
            specs.append((band.mags[sl], band.signs[sl], band.name,
                          None if band.fracs is None else band.fracs[sl]))
            dests.append((band, cy, cx))


def _tile_bands(plan: TilePlan, origin: tuple):
    """Band geometry for one tile in global coordinates.

    Returns (comp_res, band_of_slot): comp_res is the
    [component][resolution] band-list structure Tier-2 walks;
    band_of_slot maps (comp, slot_index) to its _Band so the device
    front-end's canonical block order (frontend.layout_for) can be
    joined to Tier-2's cells. Also asserts that the tile origin puts
    every code-block on the global 64-grid exactly where the device's
    local-grid blockification put it."""
    y0, x0 = origin
    tcx1, tcy1 = x0 + plan.tile_w, y0 + plan.tile_h
    comp_res = []
    band_of_slot = {}
    for c in range(plan.n_comps):
        resolutions = [[] for _ in range(plan.levels + 1)]
        for si, slot in enumerate(plan.slots):
            bx0, bx1, by0, by1 = _band_rect(
                x0, tcx1, y0, tcy1, slot.resolution, slot.name,
                plan.levels)
            assert (by1 - by0, bx1 - bx0) == (slot.h, slot.w), (
                f"band {slot.name}@r{slot.resolution}: global rect "
                f"{(by1 - by0, bx1 - bx0)} != local {(slot.h, slot.w)}"
                " — tile origin not aligned for this level count")
            # The device blockifies on the band-local 64-grid; Tier-2
            # cells live on the *global* 64-grid. They coincide exactly
            # when no global cell boundary cuts the band interior —
            # guaranteed for power-of-two tile grids (origin offsets are
            # multiples of the band size or of 64), asserted here.
            assert (by0 % (1 << CBLK_EXP) == 0
                    or (by0 % (1 << CBLK_EXP)) + slot.h <= (1 << CBLK_EXP)
                    ), "tile origin splits code-blocks vertically"
            assert (bx0 % (1 << CBLK_EXP) == 0
                    or (bx0 % (1 << CBLK_EXP)) + slot.w <= (1 << CBLK_EXP)
                    ), "tile origin splits code-blocks horizontally"
            band = _Band(slot.name, slot.resolution, c, slot.quant,
                         bx0, bx1, by0, by1, None, None, None)
            resolutions[slot.resolution].append(band)
            band_of_slot[(c, si)] = band
        comp_res.append(resolutions)
    return comp_res, band_of_slot


def _block_layers(blk: t1.CodedBlock,
                  assign: rate_mod.LayerAssignment | None) -> dict:
    """LayerAssignment boundaries -> per-layer BlockLayer slices."""
    if not blk.passes:
        return {}
    layers = {}
    prev_p, prev_b = 0, 0
    for layer, (cp, cb) in enumerate(assign.boundaries):
        if cp > prev_p:
            layers[layer] = t2.BlockLayer(cp - prev_p, blk.data[prev_b:cb])
            prev_p, prev_b = cp, cb
    return layers


@dataclass
class _PrecinctRec:
    comp: int
    res: int
    p_idx: int          # raster index within (comp, res)
    ref_y: int          # reference-grid position (progression ordering)
    ref_x: int
    band_precincts: list


def _build_precincts(comp_res: list, origin: tuple, plan: TilePlan,
                     exps: list, assigns_of) -> list:
    """Partition a tile's bands into precincts (anchored at 0 on each
    *global* resolution grid, T.800 B.6) and fill Tier-2 block state."""
    y0, x0 = origin
    tcx1, tcy1 = x0 + plan.tile_w, y0 + plan.tile_h
    levels = plan.levels
    records = []
    for c, resolutions in enumerate(comp_res):
        for r, bands in enumerate(resolutions):
            e = levels - r
            trx0, trx1 = _ceil_div(x0, 1 << e), _ceil_div(tcx1, 1 << e)
            try0, try1 = _ceil_div(y0, 1 << e), _ceil_div(tcy1, 1 << e)
            if trx1 <= trx0 or try1 <= try0:
                continue
            ppx, ppy = exps[r]
            px_lo, px_hi = trx0 >> ppx, ((trx1 - 1) >> ppx) + 1
            py_lo, py_hi = try0 >> ppy, ((try1 - 1) >> ppy) + 1
            shift = 0 if r == 0 else 1
            p_idx = 0
            for py in range(py_lo, py_hi):
                for px in range(px_lo, px_hi):
                    bps = []
                    for band in bands:
                        pbx0 = (px << ppx) >> shift
                        pbx1 = ((px + 1) << ppx) >> shift
                        pby0 = (py << ppy) >> shift
                        pby1 = ((py + 1) << ppy) >> shift
                        cx0, cx1, cy0, cy1 = band.cell_range
                        kx0 = max(cx0, pbx0 >> CBLK_EXP)
                        kx1 = min(cx1, _ceil_div(pbx1, 1 << CBLK_EXP))
                        ky0 = max(cy0, pby0 >> CBLK_EXP)
                        ky1 = min(cy1, _ceil_div(pby1, 1 << CBLK_EXP))
                        nbw, nbh = max(0, kx1 - kx0), max(0, ky1 - ky0)
                        prec = t2.Precinct(nbw, nbh)
                        for i, (cy, cx) in enumerate(
                                (cy, cx) for cy in range(ky0, ky1)
                                for cx in range(kx0, kx1)):
                            blk = band.blocks[(cy, cx)]
                            pb = t2.PrecinctBlock(
                                missing_bitplanes=band.q.n_bitplanes
                                - blk.n_bitplanes)
                            pb.layers = _block_layers(blk, assigns_of(blk))
                            prec.blocks[i] = pb
                        bps.append(prec)
                    ref_y = max(try0, py << ppy) << e
                    ref_x = max(trx0, px << ppx) << e
                    records.append(_PrecinctRec(c, r, p_idx, ref_y, ref_x,
                                                bps))
                    p_idx += 1
    return records


def _packet_sequence(progression: int, records: list, n_res: int,
                     n_comps: int, n_layers: int):
    """Yield (record, layer) in codestream packet order (T.800 B.12).

    Position-based progressions order precincts by their reference-grid
    position; components here always have unit subsampling, so sorting
    by the precinct's (y, x) anchor is exactly the standard's positional
    scan."""
    if progression == cs.PROG_LRCP:
        recs = sorted(records, key=lambda p: (p.res, p.comp, p.p_idx))
        for l in range(n_layers):
            for rec in recs:
                yield rec, l
    elif progression == cs.PROG_RLCP:
        recs = sorted(records, key=lambda p: (p.res, p.comp, p.p_idx))
        for r in range(n_res):
            for l in range(n_layers):
                for rec in recs:
                    if rec.res == r:
                        yield rec, l
    elif progression == cs.PROG_RPCL:
        recs = sorted(records,
                      key=lambda p: (p.res, p.ref_y, p.ref_x, p.comp))
        for rec in recs:
            for l in range(n_layers):
                yield rec, l
    elif progression == cs.PROG_PCRL:
        recs = sorted(records,
                      key=lambda p: (p.ref_y, p.ref_x, p.comp, p.res))
        for rec in recs:
            for l in range(n_layers):
                yield rec, l
    elif progression == cs.PROG_CPRL:
        recs = sorted(records,
                      key=lambda p: (p.comp, p.ref_y, p.ref_x, p.res))
        for rec in recs:
            for l in range(n_layers):
                yield rec, l
    else:
        raise ValueError(f"unknown progression {progression}")


def _tile_parts(params: EncodeParams, tidx: int, records: list,
                n_res: int, n_comps: int) -> list:
    """Encode a tile's packets and split them into tile-parts.

    Returns [(tile_idx, tpsot, tnsot, aux_segments, body)]. With
    ``tparts_r`` and a resolution-major progression this is one
    tile-part per resolution (``ORGtparts=R``), each carrying its own
    PLT when ``gen_plt`` (KakaduConverter.java:40)."""
    split_r = params.tparts_r and params.progression in (cs.PROG_RPCL,
                                                         cs.PROG_RLCP)
    groups: list = []        # [(packets bytes list, lengths list)]
    group_of_res: dict = {}
    sop_counter = 0
    for rec, layer in _packet_sequence(params.progression, records, n_res,
                                       n_comps, params.n_layers):
        pkt = t2.encode_packet(
            rec.band_precincts, layer, params.n_layers,
            sop_index=sop_counter if params.use_sop else None,
            use_eph=params.use_eph)
        sop_counter += 1
        key = rec.res if split_r else 0
        if key not in group_of_res:
            group_of_res[key] = len(groups)
            groups.append(([], []))
        pkts, lens = groups[group_of_res[key]]
        pkts.append(pkt)
        lens.append(len(pkt))

    parts = []
    tnsot = len(groups)
    for tpsot, (pkts, lens) in enumerate(groups):
        aux = [cs.plt(lens, zplt=tpsot)] if params.gen_plt else []
        parts.append((tidx, tpsot, tnsot, aux, b"".join(pkts)))
    return parts


def _band_weight(slot, gains) -> float:
    """PCRD distortion weight: (step x 2-D synthesis L2 norm)²."""
    ll_gain, band_gains = gains
    if slot.name == "LL":
        g = ll_gain
    else:
        lvl = len(band_gains) - slot.resolution + 1
        g = band_gains[lvl - 1][slot.name]
    return (slot.quant.delta * g) ** 2


def _legacy_tier1(groups: dict, plans: dict, img: np.ndarray,
                  params: EncodeParams, bitdepth: int, n_comps: int,
                  used_mct: bool, gains, weight_of_slot: dict,
                  mesh=None):
    """Host-side Tier-1 over raw coefficient planes. Two callers:

    - tile grids whose sub-bands *straddle* global 64-grid cells (tile
      size divisible by 2^levels but not a multiple of 64, e.g. 96): the
      device front-end cannot blockify these, so code-blocks are sliced
      on the host, clipped to the global cell grid. Tile sizes whose
      global band rects disagree with the local Mallat geometry never
      reach here — encode_array raises NotImplementedError for those.
    - mesh-sharded encodes (``mesh`` not None): the transform runs
      data-parallel over the mesh (parallel.batch.run_tiles_sharded), or
      row-sharded with DWT halo exchange for a single giant tile
      (parallel.sharded_dwt.sharded_transform_tile), and the planes come
      back for host block slicing.

    Returns (tile_records, coded blocks, weights, qcd_values)."""
    from .pipeline import extract_bands, run_tiles

    if mesh is not None:
        from ..parallel.batch import run_tiles_sharded
        from ..parallel.mesh import TILE_AXIS
        from ..parallel.sharded_dwt import (can_row_shard,
                                            sharded_transform_tile)

    def transform(plan: TilePlan, batch: np.ndarray) -> np.ndarray:
        if mesh is None:
            return run_tiles(plan, batch)
        n_rows = mesh.shape[TILE_AXIS]
        if (batch.shape[0] == 1 and n_rows > 1
                and can_row_shard(plan.tile_h, plan.levels, n_rows)):
            return sharded_transform_tile(plan, batch[0], mesh)[None]
        return run_tiles_sharded(plan, batch, mesh)

    specs: list = []
    dests: list = []
    tile_records = []
    qcd_values = None
    norms = _RCT_NORMS if params.lossless else _ICT_NORMS
    for (th, tw), members in groups.items():
        plan = plans[(th, tw)]
        batch = np.stack([img[y0:y0 + th, x0:x0 + tw]
                          for _, y0, x0 in members])
        planes = transform(plan, batch)
        if qcd_values is None:
            qcd_values = _qcd_values(plan)
        for s in plan.slots:
            weight_of_slot.setdefault((s.resolution, s.name),
                                      _band_weight(s, gains))
        for (tidx, y0, x0), tile_planes in zip(members, planes):
            tcx1, tcy1 = x0 + plan.tile_w, y0 + plan.tile_h
            comp_res = []
            for c in range(plan.n_comps):
                resolutions = []
                for res_bands in extract_bands(tile_planes[c], plan):
                    bands = []
                    for slot, mags, signs, fracs in res_bands:
                        bx0, bx1, by0, by1 = _band_rect(
                            x0, tcx1, y0, tcy1, slot.resolution,
                            slot.name, plan.levels)
                        assert (by1 - by0, bx1 - bx0) == (slot.h,
                                                          slot.w), (
                            "tile origin not aligned for this level "
                            "count")
                        band = _Band(slot.name, slot.resolution, c,
                                     slot.quant, bx0, bx1, by0, by1,
                                     mags, signs, fracs)
                        _collect_blocks(band, specs, dests)
                        bands.append(band)
                    resolutions.append(bands)
                comp_res.append(resolutions)
            tile_records.append((tidx, (y0, x0), plan, comp_res))

    blocks = []
    weights = []
    for (band, cy, cx), blk in zip(dests, t1_batch.encode_blocks(specs)):
        band.blocks[(cy, cx)] = blk
        blocks.append(blk)
        cw = norms[band.comp] ** 2 if used_mct else 1.0
        weights.append(weight_of_slot[(band.res, band.name)] * cw)
    for _, _, _, comp_res in tile_records:
        for resolutions in comp_res:
            for bands in resolutions:
                for band in bands:
                    band.mags = band.signs = band.fracs = None
    return tile_records, blocks, weights, qcd_values


@dataclass
class _Chunk:
    """One unit of the overlapped pipeline: up to BUCKETEER_OVERLAP_TILES
    same-shape tiles plus the host-side metadata joining the device's
    canonical block order to Tier-2's cells."""
    plan: TilePlan
    members: list            # [(tidx, y0, x0)]
    dests: list              # [(band, cy, cx)] in frontend block order
    hs: np.ndarray
    ws: np.ndarray
    bandnames: list
    wts: np.ndarray          # PCRD distortion weight per block
    ns: np.ndarray           # true samples per block
    pending: object = None   # frontend.PendingFrontend while dispatched
    fres: object = None      # frontend.FrontendResult once resolved


def _build_chunks(groups: dict, plans: dict, used_mct: bool, gains,
                  weight_of_slot: dict, norms) -> tuple:
    """Split shape groups into pipeline chunks (order is deterministic:
    group dict order, then member order — byte-identical output to the
    unchunked encoder). Returns (chunks, tile_records, qcd_values)."""
    chunk_tiles = _overlap_tiles()
    tile_records: list = []
    chunks: list = []
    qcd_values = None
    for (th, tw), members in groups.items():
        plan = plans[(th, tw)]
        if qcd_values is None:
            qcd_values = _qcd_values(plan)
        for s in plan.slots:
            weight_of_slot.setdefault((s.resolution, s.name),
                                      _band_weight(s, gains))
        layout = frontend.layout_for(plan)
        for i in range(0, len(members), chunk_tiles):
            part = members[i:i + chunk_tiles]
            dests, hs, ws, bandnames, wts, ns = [], [], [], [], [], []
            for (tidx, y0, x0) in part:
                comp_res, band_of_slot = _tile_bands(plan, (y0, x0))
                tile_records.append((tidx, (y0, x0), plan, comp_res))
                for m in layout.metas:
                    band = band_of_slot[(m.comp, m.slot_i)]
                    cx0, _, cy0, _ = band.cell_range
                    dests.append((band, cy0 + m.iy, cx0 + m.ix))
                    hs.append(m.h)
                    ws.append(m.w)
                    bandnames.append(band.name)
                    cw = norms[m.comp] ** 2 if used_mct else 1.0
                    wts.append(weight_of_slot[(band.res, band.name)] * cw)
                    ns.append(m.h * m.w)
            chunks.append(_Chunk(plan, part, dests,
                                 np.asarray(hs, np.int32),
                                 np.asarray(ws, np.int32), bandnames,
                                 np.asarray(wts), np.asarray(ns)))
    return chunks, tile_records, qcd_values


@contract(shapes={"img": [("H", "W"), ("H", "W", "C")]},
          dtypes={"img": "number"})
def encode_array(img: np.ndarray, bitdepth: int = 8,
                 params: EncodeParams | None = None, mesh=None) -> bytes:
    """Encode a (H, W) or (H, W, 3) array into a raw JPEG 2000 codestream.

    ``mesh``: optional jax Mesh (parallel.mesh.make_mesh). When given,
    the sample transform runs sharded across the mesh — data-parallel
    over tile batches, or row-sharded with DWT halo exchange for a
    single giant tile — and Tier-1 runs on host planes. None (default)
    uses the single-device overlapped packed-frontend pipeline.
    """
    params = params or EncodeParams()
    h, w = img.shape[:2]
    n_comps = 1 if img.ndim == 2 else img.shape[2]
    assert n_comps in (1, 3), "components must be 1 or 3"
    tile = params.tile_size or max(h, w)
    levels = params.levels

    if img.ndim == 2:
        img = img[..., None]
    if n_comps != 3:
        used_mct = False
    elif params.mct == "on":
        used_mct = True
    elif params.mct == "off":
        used_mct = False
    else:
        used_mct = _mct_helps(img, params.lossless,
                              None if params.lossless else params.rate,
                              params.base_delta)

    # Group tiles by shape: interior tiles batch into one device call;
    # ragged right/bottom tiles form up to three more groups.
    n_tiles_x = _ceil_div(w, tile)
    n_tiles_y = _ceil_div(h, tile)
    groups: dict = {}
    for ty in range(n_tiles_y):
        for tx in range(n_tiles_x):
            y0, x0 = ty * tile, tx * tile
            th, tw = min(tile, h - y0), min(tile, w - x0)
            groups.setdefault((th, tw), []).append(
                (ty * n_tiles_x + tx, y0, x0))

    gains = synthesis_gains(levels, params.lossless)
    weight_of_slot: dict = {}
    target = None
    if params.rate is not None and not params.lossless:
        target = params.rate * w * h / 8.0
    norms = _RCT_NORMS if params.lossless else _ICT_NORMS
    plans = {shape: make_plan(shape[0], shape[1], n_comps, levels,
                              params.lossless, bitdepth, params.base_delta,
                              use_mct=used_mct) for shape in groups}

    states = {_grid_aligned(plans[shape], (y0, x0))
              for shape, members in groups.items()
              for _, y0, x0 in members}
    if "mismatch" in states:
        raise NotImplementedError(
            f"tile size {tile} with {levels} decomposition levels: the "
            "global band rectangle of a tile disagrees with its local "
            "Mallat geometry, so neither the device front-end nor the "
            "host fallback can code it. Use a tile size divisible by "
            f"2^levels ({1 << levels}), or fewer levels.")
    if mesh is not None or "straddle" in states:
        # Host-side block slicing: sharded transforms (mesh) or tile
        # grids whose sub-bands straddle global 64-grid cells.
        tile_records, all_blocks, block_weights, qcd_values = \
            _legacy_tier1(groups, plans, img, params, bitdepth, n_comps,
                          used_mct, gains, weight_of_slot, mesh=mesh)
        assign_index = {id(b): i for i, b in enumerate(all_blocks)}
        return _finish(img, params, tile_records, all_blocks,
                       block_weights, assign_index, qcd_values, used_mct,
                       bitdepth, n_comps, levels, tile, target)

    # Overlapped device/host pipeline. Device front-end per chunk —
    # fused transform, blockification, per-plane stats, bit-plane
    # bitmaps packed on device (codec/frontend.py); host Tier-1 over
    # the compacted payload on a bounded worker. Only the small stats
    # come back eagerly; bitmaps stay in HBM until floors are known.
    chunks, tile_records, qcd_values = _build_chunks(
        groups, plans, used_mct, gains, weight_of_slot, norms)

    use_mq = _device_mq(params)
    use_cxd = use_mq or _device_cxd(params)
    frac_bits = 0 if params.lossless else FRAC_BITS
    tm = {"device": 0.0, "host": 0.0, "cxd": 0.0, "mq": 0.0,
          "mq_dev": 0.0}
    # The shared scheduler pool may run two of this encode's chunks
    # concurrently (the private executor never did); serialize the
    # timing accumulator so segments stay exact.
    tm_lock = threading.Lock()
    n_syms = [0]
    n_mq_bytes = [0]
    floor_lam = [0.0]
    t_wall0 = time.perf_counter()

    # Scheduler services (engine/scheduler.py): device dispatch routed
    # through the process-wide batching thread and host Tier-1 on the
    # shared pool. Absent services keep the historical private pipeline.
    svc = current_services()
    dispatch_fn = (svc.dispatch if svc is not None
                   and svc.dispatch is not None
                   else frontend.dispatch_frontend)

    def _tm_add(key: str, dt: float) -> None:
        with tm_lock:
            tm[key] += dt

    def check_deadline() -> None:
        if svc is not None and svc.check is not None:
            svc.check()

    def dispatch(chunk: _Chunk) -> None:
        check_deadline()
        t0 = time.perf_counter()
        with obs.span("encode.dispatch", tiles=len(chunk.members)):
            batch = np.stack([img[y0:y0 + chunk.plan.tile_h,
                                  x0:x0 + chunk.plan.tile_w]
                              for _, y0, x0 in chunk.members])
            mode = "mq" if use_mq else ("cxd" if use_cxd else "rows")
            chunk.pending = dispatch_fn(chunk.plan, batch, mode=mode)
        _tm_add("device", time.perf_counter() - t0)

    def resolve(chunk: _Chunk) -> None:
        t0 = time.perf_counter()
        with obs.span("encode.resolve_stats"):
            chunk.fres = chunk.pending.resolve_stats()
        chunk.pending = None
        _tm_add("device", time.perf_counter() - t0)

    def host_code(chunk: _Chunk, floors: np.ndarray, payload: np.ndarray,
                  offsets: np.ndarray) -> list:
        """Runs on the bounded worker; native Tier-1 releases the GIL,
        so this overlaps the caller's device dispatch/waits. Submitted
        through obs.bind so the pool thread re-enters the request's
        trace context (host Tier-1 items show in the span tree)."""
        t0 = time.perf_counter()
        with obs.span("encode.host_t1", blocks=len(chunk.dests)):
            blocks = t1_batch.encode_packed(payload, offsets,
                                            chunk.fres.nbps,
                                            floors, chunk.hs, chunk.ws,
                                            chunk.bandnames)
            if not params.lossless:
                _correct_distortions(blocks, chunk.fres)
        _tm_add("host", time.perf_counter() - t0)
        return blocks

    def host_replay(chunk: _Chunk, streams) -> list:
        """The CX/D-mode host half: pure MQ replay of the device's
        symbol streams — no context modeling left on the host."""
        t0 = time.perf_counter()
        with obs.span("encode.mq_replay", blocks=len(chunk.dests)):
            blocks = t1_batch.encode_cxd(streams)
            if not params.lossless:
                _correct_distortions(blocks, chunk.fres)
        dt = time.perf_counter() - t0
        _tm_add("host", dt)
        _tm_add("mq", dt)
        return blocks

    def fetch_and_submit(pool, chunk: _Chunk, floors: np.ndarray,
                         futs: list, release_rows: bool) -> None:
        t0 = time.perf_counter()
        if use_mq:
            # Tier-1 never touches the host: the device runs CX/D and
            # the MQ coder back to back (symbols stay in HBM between
            # the two programs) and ships finished byte segments; the
            # shared host Tier-1 pool is bypassed entirely.
            def t1_stage(blocks_dev):
                return cxd_mod.run_device_mq(
                    blocks_dev, chunk.fres.nbps, floors,
                    chunk.bandnames, chunk.hs, chunk.ws,
                    chunk.fres.layout.P, frac_bits)

            with obs.span("encode.t1_device", blocks=len(chunk.dests)):
                if svc is not None and svc.t1_launch is not None:
                    # Pipeline-stage mapping: the scheduler stages the
                    # fused program onto its Tier-1 device subset (the
                    # payload is re-committed to the worker's core);
                    # the span here covers staging wait + execution.
                    res = svc.t1_launch(t1_stage, chunk.fres.blocks)
                else:
                    res = t1_stage(chunk.fres.blocks)
            _tm_add("device", res.cxd_s + res.mq_s)
            _tm_add("cxd", res.cxd_s)
            _tm_add("mq_dev", res.mq_s)
            n_syms[0] += res.total_syms
            n_mq_bytes[0] += res.total_bytes
            if release_rows:
                chunk.fres.blocks = None    # free the HBM staging buffer
            blocks = res.blocks
            th0 = time.perf_counter()
            if not params.lossless:
                _correct_distortions(blocks, chunk.fres)
            # The whole host share: assembly + distortion correction.
            _tm_add("host", res.host_s + time.perf_counter() - th0)
            # No back-pressure check: nothing is in flight — every
            # entry this branch appends is already resolved.
            futs.append(_ImmediateResult(blocks))
            return
        if use_cxd:
            with obs.span("encode.cxd_device",
                          blocks=len(chunk.dests)):
                streams = cxd_mod.run_cxd(
                    chunk.fres.blocks, chunk.fres.nbps, floors,
                    chunk.bandnames, chunk.hs, chunk.ws,
                    chunk.fres.layout.P, frac_bits)
            dt = time.perf_counter() - t0
            _tm_add("device", dt)
            _tm_add("cxd", dt)
            n_syms[0] += streams.total_syms
            if release_rows:
                chunk.fres.blocks = None    # free the HBM staging buffer
        else:
            src, offsets = frontend.payload_plan(chunk.fres.nbps, floors,
                                                 chunk.fres.layout.P)
            payload = frontend.fetch_payload(chunk.fres, src)
            _tm_add("device", time.perf_counter() - t0)
            if release_rows:
                chunk.fres.rows = None  # free the staging buffer in HBM
        # Back-pressure: at most HOST_QUEUE_DEPTH unfinished host jobs
        # so payload staging stays bounded.
        live = [f for f in futs if not f.done()]
        if len(live) > HOST_QUEUE_DEPTH:
            live[0].result()
        # obs.bind: the shared pool's threads don't inherit contextvars;
        # rebind the request's trace context around the host-coding item.
        if use_cxd:
            futs.append(pool.submit(obs.bind(host_replay), chunk,
                                    streams))
        else:
            futs.append(pool.submit(obs.bind(host_code), chunk, floors,
                                    payload, offsets))

    def chunk_floors(margin: float) -> list:
        if target is None:
            return [np.zeros(c.fres.n_blocks, np.int32) for c in chunks]
        # Plane capacity could in principle differ between shape
        # groups; pad the per-plane stats to the widest.
        pmax = max(c.fres.layout.P for c in chunks)

        def padp(a):
            return np.pad(a, ((0, 0), (0, pmax - a.shape[1])))

        nbps = np.concatenate([c.fres.nbps for c in chunks])
        newsig = np.concatenate([padp(c.fres.newsig) for c in chunks])
        sigd = np.concatenate([padp(c.fres.sigd) for c in chunks])
        refd = np.concatenate([padp(c.fres.refd) for c in chunks])
        wts = np.concatenate([c.wts for c in chunks])
        ns = np.concatenate([c.ns for c in chunks])
        floors, floor_lam[0] = rate_mod.estimate_floors(
            nbps, newsig, sigd, refd, wts, ns, target, margin)
        out, ofs = [], 0
        for c in chunks:
            out.append(floors[ofs:ofs + c.fres.n_blocks])
            ofs += c.fres.n_blocks
        return out

    # Host Tier-1 executor: the scheduler's shared many-worker pool when
    # one is installed (never shut down here), else the historical
    # private one-worker executor. Reassembly stays ordered either way —
    # results are collected in futs submission order — so output is
    # byte-identical to the serial path.
    if svc is not None and svc.pool is not None:
        pool_cm = contextlib.nullcontext(svc.pool)
    else:
        pool_cm = ThreadPoolExecutor(max_workers=1)
    with pool_cm as pool:
        if target is None:
            # Streaming: floors are all zero, so each chunk flows
            # dispatch -> resolve -> fetch -> host-code independently;
            # at most OVERLAP_DEPTH chunks staged in HBM (the rows
            # buffer is released as soon as its payload is fetched).
            futs: list = []
            staged: deque = deque()
            for chunk in chunks:
                dispatch(chunk)
                staged.append(chunk)
                if len(staged) >= OVERLAP_DEPTH:
                    c = staged.popleft()
                    resolve(c)
                    fetch_and_submit(pool, c, np.zeros(
                        c.fres.n_blocks, np.int32), futs,
                        release_rows=True)
            while staged:
                c = staged.popleft()
                resolve(c)
                fetch_and_submit(pool, c, np.zeros(
                    c.fres.n_blocks, np.int32), futs, release_rows=True)
            blocks_by_chunk = [f.result() for f in futs]
        else:
            # Rate-targeted: floors need global stats, so phase A
            # queues every chunk's device program (rows stay resident —
            # a later margin attempt may re-fetch deeper planes), then
            # phase B overlaps per-chunk payload fetch with host coding.
            for chunk in chunks:
                dispatch(chunk)
            for chunk in chunks:
                resolve(chunk)
            margin = 3.0
            for attempt in range(3):
                if attempt and _metrics_sink is not None:
                    _metrics_sink.count("encode.floor_reruns")
                floors_by_chunk = chunk_floors(margin)
                futs = []
                for chunk, floors in zip(chunks, floors_by_chunk):
                    fetch_and_submit(pool, chunk, floors, futs,
                                     release_rows=False)
                blocks_by_chunk = [f.result() for f in futs]
                avail = sum(len(b.data) for blocks in blocks_by_chunk
                            for b in blocks)
                if avail >= 1.05 * target:
                    if attempt == 2 or avail >= 2.0 * target:
                        # Out of retries, or supply is so abundant that
                        # PCRD's cut sits far above the floor tail —
                        # skip the per-pass slope walk on the common
                        # path (it costs Python time per pass).
                        break
                    # Supply is snug: the floors may have clipped
                    # *cheap* passes PCRD wanted. Compare the realized
                    # PCRD cut slope against the floor threshold (the
                    # granted safety plane covers modest gaps; a 4x
                    # violation means real quality loss).
                    flat = [b for blocks in blocks_by_chunk
                            for b in blocks]
                    wts_all = np.concatenate([c.wts for c in chunks])
                    realized = rate_mod.cut_slope(flat, wts_all,
                                                  target * 0.96)
                    if realized >= floor_lam[0] / 4.0:
                        break
                    if _metrics_sink is not None:
                        _metrics_sink.count("encode.floor_slope_retries")
                # Estimator undershoot: lower the floors and redo —
                # PCRD needs enough passes to spend the budget.
                margin *= 4.0

    wall_s = time.perf_counter() - t_wall0
    if _metrics_sink is not None:
        _metrics_sink.record("encode.device_dispatch", tm["device"],
                             pixels=h * w)
        _metrics_sink.record("encode.host_code", tm["host"], pixels=h * w)
        if use_mq:
            # Full-device Tier-1 segments: context modeling, the MQ
            # coder (items=bytes -> bytes/s), and their sum (items=
            # symbols -> symbols/s). encode.host_code above is the
            # whole host share (block assembly only).
            _metrics_sink.record("encode.cxd_device", tm["cxd"],
                                 pixels=h * w)
            _metrics_sink.record("encode.mq_device", tm["mq_dev"],
                                 pixels=h * w, items=n_mq_bytes[0])
            _metrics_sink.record("encode.t1_device_total",
                                 tm["cxd"] + tm["mq_dev"],
                                 pixels=h * w, items=n_syms[0])
            _metrics_sink.count("encode.cxd_symbols", n_syms[0])
            _metrics_sink.count("encode.mq_device_bytes", n_mq_bytes[0])
        elif use_cxd:
            # The Tier-1 split's own segments: device context modeling
            # vs host MQ replay, plus symbol throughput (/metrics shows
            # items_per_s on the replay stage).
            _metrics_sink.record("encode.cxd_device", tm["cxd"],
                                 pixels=h * w)
            _metrics_sink.record("encode.mq_replay", tm["mq"],
                                 pixels=h * w, items=n_syms[0])
            _metrics_sink.count("encode.cxd_symbols", n_syms[0])
        _metrics_sink.record_overlap("encode", tm["device"], tm["host"],
                                     wall_s, pixels=h * w)

    all_coded: list = []
    block_weights: list = []
    assign_index: dict = {}     # id(CodedBlock) -> index
    with obs.span("encode.reassemble", chunks=len(chunks)):
        for chunk, blocks in zip(chunks, blocks_by_chunk):
            for (band, cy, cx), blk, bw in zip(chunk.dests, blocks,
                                               chunk.wts):
                assert blk.n_bitplanes <= band.q.n_bitplanes, (
                    f"block bitplanes {blk.n_bitplanes} exceed Mb "
                    f"{band.q.n_bitplanes} in {band.name}")
                band.blocks[(cy, cx)] = blk
                assign_index[id(blk)] = len(all_coded)
                all_coded.append(blk)
                block_weights.append(bw)
            chunk.fres = None     # release stats + any remaining rows
    return _finish(img, params, tile_records, all_coded, block_weights,
                   assign_index, qcd_values, used_mct, bitdepth, n_comps,
                   levels, tile, target)


def _finish(img: np.ndarray, params: EncodeParams, tile_records: list,
            all_blocks: list, block_weights: list, assign_index: dict,
            qcd_values: list, used_mct: bool, bitdepth: int, n_comps: int,
            levels: int, tile: int, target: float | None) -> bytes:
    """PCRD layer allocation + Tier-2 + codestream assembly, iterated a
    few times so the assembled file size (headers included) lands on the
    byte target."""
    with obs.span("encode.tier2"):
        return _finish_spanned(img, params, tile_records, all_blocks,
                               block_weights, assign_index, qcd_values,
                               used_mct, bitdepth, n_comps, levels,
                               tile, target)


def _finish_spanned(img: np.ndarray, params: EncodeParams,
                    tile_records: list, all_blocks: list,
                    block_weights: list, assign_index: dict,
                    qcd_values: list, used_mct: bool, bitdepth: int,
                    n_comps: int, levels: int, tile: int,
                    target: float | None) -> bytes:
    h, w = img.shape[:2]
    exps = _precinct_exps(params, levels)
    segs = [
        cs.siz(w, h, n_comps, bitdepth, tile, tile),
        cs.cod(params.progression, params.n_layers,
               use_mct=used_mct, levels=levels,
               cblk_w_exp=CBLK_EXP, cblk_h_exp=CBLK_EXP,
               reversible=params.lossless,
               precinct_exps=exps if params.precincts else None,
               use_sop=params.use_sop, use_eph=params.use_eph),
        cs.qcd(0 if params.lossless else 2, GUARD_BITS, qcd_values),
    ]
    if params.comment:
        segs.append(cs.com(params.comment))

    def build(budget: float | None) -> bytes:
        assigns = rate_mod.allocate(all_blocks, block_weights,
                                    params.n_layers, budget)

        def assigns_of(blk):
            return assigns[assign_index[id(blk)]]

        parts = []
        for tidx, origin, plan, comp_res in sorted(tile_records,
                                                   key=lambda t: t[0]):
            records = _build_precincts(comp_res, origin, plan, exps,
                                       assigns_of)
            parts.extend(_tile_parts(params, tidx, records, levels + 1,
                                     n_comps))
        return cs.assemble_parts(segs, parts)

    if target is None:
        return build(None)

    # Budget the block bytes, then correct for actual header overhead.
    budget = max(1024.0, target * 0.96)
    out = build(budget)
    for _ in range(3):
        err = len(out) - target
        if abs(err) <= 0.02 * target:
            break
        budget = max(1024.0, budget - err)
        # Each extra Tier-2 rebuild multiplies worst-case encode cost;
        # count them so adversarial-content blowups are observable.
        if _metrics_sink is not None:
            _metrics_sink.count("encode.t2_rebuilds")
        out = build(budget)
    return out


def _correct_distortions(blocks: list, fres) -> None:
    """Replace the host coder's fractionless per-pass distortion
    estimates with the device front-end's exact per-plane sums.

    The packed payload ships no fractional-magnitude bits (and, under a
    bit-plane floor, no low integer bits), so native Tier-1's midpoint
    estimates are biased; the device computed the exact per-plane
    significance/refinement distortion totals from the full fixed-point
    coefficients (frontend._frontend_body). Pass-level granularity is
    recovered by scaling each pass in plane p by the exact/estimated
    plane-total ratio for its kind (sig = SPP+CP, ref = MRP)."""
    P = fres.layout.P
    for bi, blk in enumerate(blocks):
        if not blk.passes:
            continue
        est_sig = [0.0] * P
        est_ref = [0.0] * P
        for info in blk.passes:
            if info.pass_type == 1:
                est_ref[info.bitplane] += info.dist_reduction
            else:
                est_sig[info.bitplane] += info.dist_reduction
        for info in blk.passes:
            p = info.bitplane
            est = est_ref[p] if info.pass_type == 1 else est_sig[p]
            exact = (fres.refd[bi, p] if info.pass_type == 1
                     else fres.sigd[bi, p])
            if est > 0.0 and exact >= 0.0:
                info.dist_reduction *= exact / est
            # A zero estimate with nonzero exact distortion cannot be
            # apportioned; keep the estimate (it is zero) — the hull
            # treats the pass as free distortion-wise either way.


def _qcd_values(plan: TilePlan) -> list:
    vals = []
    for slot in plan.slots:
        if plan.lossless:
            vals.append(slot.quant.exponent)
        else:
            vals.append((slot.quant.exponent, slot.quant.mantissa))
    return vals


@contract(shapes={"img": [("H", "W"), ("H", "W", "C")]},
          dtypes={"img": "number"})
def encode_jp2(img: np.ndarray, bitdepth: int = 8,
               params: EncodeParams | None = None, jpx: bool = False,
               mesh=None) -> bytes:
    """Encode to a boxed .jp2 / .jpx file image."""
    code = encode_array(img, bitdepth, params, mesh=mesh)
    h, w = img.shape[:2]
    n_comps = 1 if img.ndim == 2 else img.shape[2]
    return jp2box.wrap(code, w, h, n_comps, bitdepth, jpx=jpx)
