"""EBCOT Tier-1 code-block coder (JPEG 2000 Part 1, Annex D).

Bit-plane context modeling (significance propagation / magnitude
refinement / cleanup passes) + MQ coding per 64x64 code-block — the
compute-dominant stage of the encode the reference outsources to Kakadu
(reference: converters/KakaduConverter.java:38-44, ``Cblk={64,64}``;
SURVEY.md §7 "hard parts" #1).

This module is the pure-Python reference implementation: ground truth for
tests and for the native C++ coder (bucketeer_tpu/native/t1.cpp) that the
production path uses, with code-blocks fanned out across host threads
while the TPU computes the next tile's transforms. The sequential MQ
state machine stays on host (it is inherently serial per block — a
property of the codestream format, not of the implementation).

Code-blocks are embarrassingly parallel: nothing here shares state across
blocks, which is exactly what both the C++ thread pool and the device
batching exploit.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .mq import MQEncoder, CTX_RL, CTX_UNIFORM
from .quant import FRAC_BITS

# --- Context tables (T.800 Tables D.1-D.4) ---

# Zero-coding context from (sum_h, sum_v, sum_d), per band class.
def _build_zc_tables():
    ll_lh = np.zeros((3, 3, 5), dtype=np.uint8)
    hh = np.zeros((3, 3, 5), dtype=np.uint8)
    for sh in range(3):
        for sv in range(3):
            for sd in range(5):
                # LL & LH band table (T.800 Table D.1, first column group)
                if sh == 2:
                    c = 8
                elif sh == 1:
                    c = 7 if sv >= 1 else (6 if sd >= 1 else 5)
                else:
                    if sv == 2:
                        c = 4
                    elif sv == 1:
                        c = 3
                    else:
                        c = 2 if sd >= 2 else (1 if sd == 1 else 0)
                ll_lh[sh, sv, sd] = c
                # HH table (diagonal-dominant)
                if sd >= 3:
                    c = 8
                elif sd == 2:
                    c = 7 if (sh + sv) >= 1 else 6
                elif sd == 1:
                    hv = sh + sv
                    c = 5 if hv >= 2 else (4 if hv == 1 else 3)
                else:
                    hv = sh + sv
                    c = 2 if hv >= 2 else (1 if hv == 1 else 0)
                hh[sh, sv, sd] = c
    return ll_lh, hh


_ZC_LL_LH, _ZC_HH = _build_zc_tables()

# Band name -> context-table class, shared by every consumer (the native
# batch entry, the device CX/D stage, and this reference coder's HL
# transposition convention): 0 = LL/LH table, 1 = HH table, 2 = HL
# (LL/LH with the H and V roles swapped). One table — a drifted copy
# would silently break device-vs-host byte parity.
BAND_CLS = {"LL": 0, "LH": 0, "HH": 1, "HL": 2}

# Sign-coding context + XOR bit from (h, v) in {-1,0,1} (Table D.3).
_SC = {}
for _h in (-1, 0, 1):
    for _v in (-1, 0, 1):
        if _h == 1:
            _ctx, _xor = (13, 0) if _v == 1 else ((12, 0) if _v == 0 else (11, 0))
        elif _h == 0:
            _ctx, _xor = (10, 0) if _v == 1 else ((9, 0) if _v == 0 else (10, 1))
        else:
            _ctx, _xor = (11, 1) if _v == 1 else ((12, 1) if _v == 0 else (13, 1))
        _SC[(_h, _v)] = (_ctx, _xor)


@dataclass
class PassInfo:
    pass_type: int        # 0=sigprop, 1=magref, 2=cleanup
    bitplane: int
    cum_length: int       # conservative truncation length after this pass
    dist_reduction: float  # in quantizer-unit^2 (caller scales)


@dataclass
class CodedBlock:
    data: bytes
    n_bitplanes: int      # actual coded bit-planes (after skipping zeros)
    passes: list = field(default_factory=list)  # list[PassInfo]


def encode_block(mags: np.ndarray, signs: np.ndarray, band: str,
                 fracs: np.ndarray | None = None,
                 floor: int = 0, mq: MQEncoder | None = None) -> CodedBlock:
    """Encode one code-block.

    mags: (h, w) uint32 magnitudes (quantizer indices); signs: (h, w)
    bool/int, nonzero = negative; band: LL/HL/LH/HH (context-table class);
    fracs: optional (h, w) uint8 fractional magnitude bits (FRAC_BITS of
    |c|/delta below the index) for exact distortion estimation — None
    means the indices are exact (reversible path); floor: lowest coded
    bit-plane (planes below it are omitted from the pass list — a
    truncation the rate allocator would have made; the caller must have
    zeroed the corresponding magnitude bits); mq: optional MQEncoder
    stand-in (codec/cxd.py injects a recording coder to extract the
    reference CX/D symbol stream).
    """
    h, w = mags.shape
    maxv = int(mags.max()) if mags.size else 0
    nbps = int(maxv).bit_length()
    blk = CodedBlock(b"", nbps)
    if nbps == 0:
        return blk

    # HL uses the LL/LH table with H and V swapped (transpose the roles).
    swap_hv = band == "HL"
    zc_table = _ZC_HH if band == "HH" else _ZC_LL_LH

    mq = mq or MQEncoder()
    sigma = np.zeros((h, w), dtype=np.uint8)
    pi = np.zeros((h, w), dtype=np.uint8)      # coded-in-current-plane flag
    refined = np.zeros((h, w), dtype=np.uint8)
    m = mags.astype(np.int64)
    neg = signs.astype(bool)

    def neighbor_sums(y: int, x: int):
        sh = sv = sd = 0
        if x > 0 and sigma[y, x - 1]:
            sh += 1
        if x < w - 1 and sigma[y, x + 1]:
            sh += 1
        if y > 0 and sigma[y - 1, x]:
            sv += 1
        if y < h - 1 and sigma[y + 1, x]:
            sv += 1
        if y > 0 and x > 0 and sigma[y - 1, x - 1]:
            sd += 1
        if y > 0 and x < w - 1 and sigma[y - 1, x + 1]:
            sd += 1
        if y < h - 1 and x > 0 and sigma[y + 1, x - 1]:
            sd += 1
        if y < h - 1 and x < w - 1 and sigma[y + 1, x + 1]:
            sd += 1
        return sh, sv, sd

    def zc_context(y: int, x: int) -> int:
        sh, sv, sd = neighbor_sums(y, x)
        if swap_hv:
            sh, sv = sv, sh
        return int(zc_table[sh, sv, sd])

    def sign_contrib(y: int, x: int) -> int:
        if not (0 <= y < h and 0 <= x < w) or not sigma[y, x]:
            return 0
        return -1 if neg[y, x] else 1

    def code_sign(y: int, x: int) -> None:
        hc = sign_contrib(y, x - 1) + sign_contrib(y, x + 1)
        vc = sign_contrib(y - 1, x) + sign_contrib(y + 1, x)
        hc = max(-1, min(1, hc))
        vc = max(-1, min(1, vc))
        ctx, xor = _SC[(hc, vc)]
        mq.encode(int(neg[y, x]) ^ xor, ctx)

    # True magnitude in index units: the coded index plus the retained
    # fractional bits (quantize_fp). With no fracs the indices are exact
    # (reversible path). Accurate tv matters because PCRD ranks passes by
    # slope; a fixed +0.5 midpoint mis-ranks blocks whose slopes cluster
    # (e.g. chroma noise), splitting rate badly across components.
    fr = (fracs.astype(np.float64) / float(1 << FRAC_BITS)
          if fracs is not None else np.zeros((h, w)))

    def sig_dist(y: int, x: int, p: int) -> float:
        v = m[y, x]
        vb = (v >> p) << p
        tv = v + fr[y, x]
        r = vb + (1 << p) * 0.5
        return float(tv * tv - (tv - r) * (tv - r))

    def ref_dist(y: int, x: int, p: int) -> float:
        v = m[y, x]
        v1 = (v >> (p + 1)) << (p + 1)
        r1 = v1 + (1 << (p + 1)) * 0.5
        v0 = (v >> p) << p
        r0 = v0 + (1 << p) * 0.5
        tv = v + fr[y, x]
        return float((tv - r1) * (tv - r1) - (tv - r0) * (tv - r0))

    def stripes():
        for y0 in range(0, h, 4):
            for x in range(w):
                yield y0, x

    passes: list[PassInfo] = []
    dist = 0.0

    for p in range(nbps - 1, floor - 1, -1):
        bit = 1 << p
        first_plane = p == nbps - 1

        if not first_plane:
            # Pass 1: significance propagation
            dist = 0.0
            for y0, x in stripes():
                for y in range(y0, min(y0 + 4, h)):
                    if sigma[y, x]:
                        continue
                    sh, sv, sd = neighbor_sums(y, x)
                    if sh + sv + sd == 0:
                        continue
                    shh, svv = (sv, sh) if swap_hv else (sh, sv)
                    ctx = int(zc_table[shh, svv, sd])
                    b = 1 if (m[y, x] & bit) else 0
                    mq.encode(b, ctx)
                    pi[y, x] = 1
                    if b:
                        sigma[y, x] = 1
                        dist += sig_dist(y, x, p)
                        code_sign(y, x)
            passes.append(PassInfo(0, p, mq.truncation_length(), dist))

            # Pass 2: magnitude refinement
            dist = 0.0
            for y0, x in stripes():
                for y in range(y0, min(y0 + 4, h)):
                    if not sigma[y, x] or pi[y, x]:
                        continue
                    if refined[y, x]:
                        ctx = 16
                    else:
                        sh, sv, sd = neighbor_sums(y, x)
                        ctx = 15 if (sh + sv + sd) else 14
                    mq.encode(1 if (m[y, x] & bit) else 0, ctx)
                    dist += ref_dist(y, x, p)
                    refined[y, x] = 1
            passes.append(PassInfo(1, p, mq.truncation_length(), dist))

        # Pass 3: cleanup
        dist = 0.0
        for y0, x in stripes():
            y = y0
            # Run-length shortcut: full stripe, nothing coded/significant,
            # empty neighborhoods for all four rows.
            if (y0 + 3 < h
                    and not sigma[y0:y0 + 4, x].any()
                    and not pi[y0:y0 + 4, x].any()
                    and all(sum(neighbor_sums(yy, x)) == 0
                            for yy in range(y0, y0 + 4))):
                run_bits = [1 if (m[yy, x] & bit) else 0
                            for yy in range(y0, y0 + 4)]
                if not any(run_bits):
                    mq.encode(0, CTX_RL)
                    continue
                mq.encode(1, CTX_RL)
                k = run_bits.index(1)
                mq.encode((k >> 1) & 1, CTX_UNIFORM)
                mq.encode(k & 1, CTX_UNIFORM)
                yk = y0 + k
                sigma[yk, x] = 1
                dist += sig_dist(yk, x, p)
                code_sign(yk, x)
                y = yk + 1
            for yy in range(y, min(y0 + 4, h)):
                if sigma[yy, x] or pi[yy, x]:
                    continue
                ctx = zc_context(yy, x)
                b = 1 if (m[yy, x] & bit) else 0
                mq.encode(b, ctx)
                if b:
                    sigma[yy, x] = 1
                    dist += sig_dist(yy, x, p)
                    code_sign(yy, x)
        passes.append(PassInfo(2, p, mq.truncation_length(), dist))
        pi[:] = 0

    data = mq.flush()
    # Truncation lengths are capped by the final stream length.
    for info in passes:
        info.cum_length = min(info.cum_length, len(data))
    blk.data = data
    blk.passes = passes
    return blk
