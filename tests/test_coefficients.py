"""Compressed-domain coefficient delivery (ISSUE 13):
decode_to_coefficients is bit-exact against slicing the subband state
out of a full decode — full reads, region+reduce+layers windows (with
and without the stream index), across 5/3 and 9/7, gray/RGB, 16-bit,
multi-tile — with the results device-resident; plus the reader's
tiered-cache integration and typed parameter errors.
"""
import numpy as np
import pytest

from bucketeer_tpu.codec import encoder
from bucketeer_tpu.codec.decode import (DecodeError, InvalidParam,
                                        build_index)
from bucketeer_tpu.codec.decode import decoder as decoder_mod
from bucketeer_tpu.codec.decode import parser
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.codec.pipeline import _band_geometry
from bucketeer_tpu.tensor import decode_to_coefficients
from bucketeer_tpu.tensor.coeffs import (band_downsample, band_keys,
                                         band_window)


def _expected_bands(data: bytes, reduce: int = 0, layers=None) -> dict:
    """Oracle: the subband state of a full decode — Tier-1
    half-magnitudes of every tile, dequantized with the decoder's own
    rule, assembled per band across the tile grid (prefix-sum
    origins), independently of the implementation under test."""
    ps = parser.parse(data, reduce=reduce, layers=layers)
    levels = ps.levels - reduce
    n_tx = -(-ps.width // ps.tile_w)
    tiles = {}
    for tile in ps.tiles:
        hv, *_ = decoder_mod._tile_hvals(ps, tile, reduce)
        tiles[divmod(tile.idx, n_tx)] = hv
    out = {}
    for key in band_keys(levels):
        rows = []
        for ty in sorted({t[0] for t in tiles}):
            cols = []
            for tx in sorted({t[1] for t in tiles}):
                hv = tiles[(ty, tx)]
                for name, lvl, y0, x0, bh, bw in _band_geometry(
                        hv.shape[1], hv.shape[2], levels):
                    res = 0 if name == "LL" else levels - lvl + 1
                    if (res, name) == key:
                        cols.append(hv[:, y0:y0 + bh, x0:x0 + bw])
                        break
            rows.append(np.concatenate(cols, axis=2))
        band = np.concatenate(rows, axis=1)
        if ps.reversible:
            mag = np.abs(band) >> 1
            out[key] = np.where(band < 0, -mag, mag)
        else:
            delta = float(ps.quants[key].delta)
            out[key] = (band.astype(np.float32)
                        * np.float32(delta * 0.5))
    return out


def _encode(rng, shape, lossless=True, levels=2, bitdepth=8,
            tile_size=None, **kw):
    img = rng.integers(0, 1 << bitdepth, size=shape).astype(
        np.uint8 if bitdepth <= 8 else np.uint16)
    params = EncodeParams(lossless=lossless, levels=levels,
                          **({"tile_size": tile_size} if tile_size
                             else {}), **kw)
    return img, encoder.encode_jp2(img, bitdepth, params)


@pytest.mark.parametrize("shape,lossless,bitdepth", [
    ((96, 120), True, 8),            # gray 5/3
    ((96, 96, 3), False, 8),         # RGB 9/7 + ICT
    ((80, 64), True, 16),            # 16-bit archival
])
def test_full_read_matches_subband_slicing(rng, shape, lossless,
                                           bitdepth):
    img, data = _encode(rng, shape, lossless=lossless, bitdepth=bitdepth)
    cs = decode_to_coefficients(data)
    expected = _expected_bands(data)
    assert set(cs.bands) == set(expected)
    host = cs.to_host()
    for key, exp in expected.items():
        assert host[key].dtype == exp.dtype
        np.testing.assert_array_equal(host[key], exp, err_msg=str(key))
    assert cs.reversible is lossless
    assert cs.nbytes == sum(a.nbytes for a in host.values())


def test_bands_are_device_resident(rng):
    import jax

    _, data = _encode(rng, (64, 64))
    cs = decode_to_coefficients(data)
    for arr in cs.bands.values():
        assert isinstance(arr, jax.Array)


@pytest.mark.parametrize("lossless,shape,reduce", [
    (True, (96, 120), 0),
    (True, (96, 120), 1),
    (False, (96, 96, 3), 0),
    (False, (96, 96, 3), 1),
    (True, (80, 64), 0),             # 16-bit below
])
def test_region_read_matches_full_slicing(rng, lossless, shape, reduce):
    bitdepth = 16 if shape == (80, 64) else 8
    img, data = _encode(rng, shape, lossless=lossless,
                        bitdepth=bitdepth)
    full = decode_to_coefficients(data, reduce=reduce).to_host()
    h, w = shape[:2]
    region = (w // 4 + 1, h // 3, w // 2, h // 2 + 3)
    idx = build_index(data)
    for use_idx in (None, idx):
        cs = decode_to_coefficients(data, region=region, reduce=reduce,
                                    index=use_idx)
        x, y, rw, rh = region
        s = 1 << reduce
        for key in band_keys(cs.levels):
            d = band_downsample(key[0], cs.levels)
            fb = full[key]
            # The documented mapping: region -> reduced sample window
            # -> dyadic band window, clamped.
            w0, w1 = band_window(y // s, -(-min(y + rh, h) // s), d,
                                 fb.shape[1])
            c0, c1 = band_window(x // s, -(-min(x + rw, w) // s), d,
                                 fb.shape[2])
            assert cs.windows[key] == (w0, w1, c0, c1), key
            np.testing.assert_array_equal(
                np.asarray(cs.bands[key]), fb[:, w0:w1, c0:c1],
                err_msg=f"{key} idx={use_idx is not None}")


def test_multi_tile_full_and_region(rng):
    img, data = _encode(rng, (96, 144), levels=2, tile_size=64,
                        gen_plt=True)
    expected = _expected_bands(data)
    cs = decode_to_coefficients(data)
    host = cs.to_host()
    for key, exp in expected.items():
        np.testing.assert_array_equal(host[key], exp, err_msg=str(key))
    # A window straddling all tile boundaries.
    cs2 = decode_to_coefficients(data, region=(30, 20, 80, 60),
                                 index=build_index(data))
    for key, win in cs2.windows.items():
        np.testing.assert_array_equal(
            np.asarray(cs2.bands[key]),
            host[key][:, win[0]:win[1], win[2]:win[3]],
            err_msg=str(key))


def test_layers_truncation_matches_full(rng):
    img, data = _encode(rng, (96, 96), lossless=False, levels=2,
                        base_delta=2.0, rate=1.0)
    full = _expected_bands(data, layers=1)
    host = decode_to_coefficients(data, layers=1).to_host()
    for key, exp in full.items():
        np.testing.assert_array_equal(host[key], exp, err_msg=str(key))


def test_region_tier1_work_is_windowed(rng):
    """Region coefficient reads must not pay full-image Tier-1: the
    block counter shows a small fraction for a small window (the PR 6
    property, inherited through the shared windowed fill)."""
    from bucketeer_tpu.server.metrics import Metrics

    from bucketeer_tpu.codec import decode as codec_decode

    _, data = _encode(rng, (384, 384), levels=2, gen_plt=True)
    idx = build_index(data)
    sink = Metrics()
    codec_decode.set_metrics_sink(sink)
    try:
        decode_to_coefficients(data)
        full_blocks = sink.report()["counters"]["decode.blocks"]
        sink2 = Metrics()
        codec_decode.set_metrics_sink(sink2)
        decode_to_coefficients(data, region=(0, 0, 32, 32), index=idx)
        win_counters = sink2.report()["counters"]
    finally:
        codec_decode.set_metrics_sink(None)
    assert win_counters["decode.region_blocks"] < full_blocks / 2
    assert win_counters["decode.coeff_requests"] == 1


def test_invalid_params_typed(rng):
    _, data = _encode(rng, (64, 64), levels=2)
    with pytest.raises(InvalidParam):
        decode_to_coefficients(data, reduce=7)
    with pytest.raises(InvalidParam):
        decode_to_coefficients(data, reduce=-1)
    with pytest.raises(InvalidParam):
        decode_to_coefficients(data, layers=0)
    for bad in ((0, 0, 0, 5), (-1, 0, 5, 5), (999, 0, 5, 5),
                ("a", 0, 5, 5), (1.5, 0, 5, 5)):
        with pytest.raises(InvalidParam):
            decode_to_coefficients(data, region=bad)


# --- reader integration: the tiered cache gains a coefficients key -------

class _CountingScheduler:
    def __init__(self):
        self.reads = 0

    def read(self, fn, *a, **kw):
        self.reads += 1
        return fn(*a, **kw)


def test_reader_coefficient_cache(rng, tmp_path):
    from bucketeer_tpu.converters.reader import TpuReader
    from bucketeer_tpu.server.metrics import Metrics

    img, data = _encode(rng, (96, 96), gen_plt=True)
    path = tmp_path / "c.jp2"
    path.write_bytes(data)
    sink = Metrics()
    sched = _CountingScheduler()
    reader = TpuReader(cache_mb=8, metrics=sink, scheduler=sched)

    cs1 = reader.read_coefficients(str(path))
    cs2 = reader.read_coefficients(str(path))
    assert cs2 is cs1                       # decoded-tile tier hit
    assert sched.reads == 1                 # miss was admitted once
    counters = sink.report()["counters"]
    assert counters["decode.cache_hits"] == 1
    assert counters["decode.cache_misses"] == 1

    # The coefficients=True key dimension: a pixel read of the same
    # (path, reduce, layers, region) is a distinct entry, not a hit.
    reader.read(str(path))
    counters = sink.report()["counters"]
    assert counters["decode.cache_misses"] == 2

    # Region reads share the stream-index tier with pixel reads.
    r1 = reader.read_coefficients(str(path), region=(8, 8, 32, 32))
    r2 = reader.read_coefficients(str(path), region=(8, 8, 32, 32))
    assert r2 is r1
    counters = sink.report()["counters"]
    assert counters["decode.index_cache_misses"] == 1
    np.testing.assert_array_equal(
        np.asarray(r1.bands[(0, "LL")]),
        np.asarray(cs1.bands[(0, "LL")])[
            :, r1.windows[(0, "LL")][0]:r1.windows[(0, "LL")][1],
            r1.windows[(0, "LL")][2]:r1.windows[(0, "LL")][3]])


def test_decode_cache_holds_coefficient_sets(rng):
    """CoefficientSets participate in the byte-budgeted LRU exactly
    like arrays: sized by nbytes, evicted in LRU order (their bands
    are immutable jax arrays, so no write lock applies)."""
    from bucketeer_tpu.converters.reader import _DecodeCache

    _, data = _encode(rng, (64, 64))
    cs = decode_to_coefficients(data)
    cache = _DecodeCache(max_bytes=3 * cs.nbytes + 16)
    for k in range(4):
        cache.put(("coeffs", k), cs)
    assert cache.evictions == 1
    assert cache.get(("coeffs", 0)) is None
    assert cache.get(("coeffs", 3)) is cs
    assert cache.nbytes <= cache.max_bytes
