"""Batch job dispatch + the in-process TPU batch converter.

Port of the reference's batch orchestration (reference:
handlers/LoadCsvHandler.java:237-314 ``startJob``) with the Lambda
fan-out replaced by the local device mesh: instead of uploading source
TIFFs to a "lambda" S3 bucket for an external converter fleet
(reference: :256-263), items are queued to the in-process batch
converter, which encodes on the TPU, uploads the derivative, and pushes
the result through the *same* status-update seam the external Lambda
would use (PATCH semantics; reference: BatchJobStatusHandler.java,
SURVEY.md §7 layer 4). Setting ``bucketeer.batch.mode=lambda`` restores
the reference's external flow: sources are uploaded to the lambda bucket
and a real Lambda PATCHes statuses back.
"""
from __future__ import annotations

import asyncio
import inspect
import logging
import os

from .. import config as cfg
from .. import constants as c
from .. import features
from ..converters import Conversion, ConverterError
from ..models import Job, WorkflowState
from .bus import MessageBus, Reply
from .s3 import S3_UPLOADER
from .scheduler import PRIORITY_BATCH, DeadlineExceeded, QueueFull
from .store import JobStore, LockTimeout
from .workers import (FINALIZE_JOB, ITEM_FAILURE, LARGE_IMAGE,
                      update_item_status)

LOG = logging.getLogger(__name__)

BATCH_CONVERTER = "batch-converter"
BATCH_MODE = "bucketeer.batch.mode"          # "tpu" (default) | "lambda"


class BatchConverterWorker:
    """The TPU stand-in for the kakadu-lambda-converter fleet: convert,
    upload the derivative, report status through the shared seam."""

    def __init__(self, converter, store: JobStore, bus: MessageBus,
                 config) -> None:
        self.converter = converter
        self.store = store
        self.bus = bus
        self.config = config
        # Mesh routing threshold: batch items at/above this pixel count
        # encode across the device mesh (converters/tpu.py routes a
        # giant single tile row-sharded, tiled batches data-sharded)
        # whenever >1 device is visible — the in-process analog of the
        # reference's large-image peer routing. The config key overrides
        # the converter's built-in/env default so the fleet is tunable
        # per deployment.
        mesh_px = config.get_int(cfg.MESH_MIN_PIXELS, 0)
        if mesh_px and hasattr(converter, "mesh_min_pixels"):
            converter.mesh_min_pixels = mesh_px
            LOG.info("mesh routing threshold set to %d pixels", mesh_px)
        # Tier-1 split and compile cache (converters/tpu.py): the config
        # keys override the converter's env-driven defaults.
        cxd_flag = config.get_str(cfg.DEVICE_CXD)
        if cxd_flag is not None and hasattr(converter, "device_cxd"):
            converter.device_cxd = cfg.truthy(cxd_flag)
            LOG.info("device CX/D Tier-1 split %s by config",
                     "enabled" if converter.device_cxd else "disabled")
        mq_flag = config.get_str(cfg.DEVICE_MQ)
        if mq_flag is not None and hasattr(converter, "device_mq"):
            converter.device_mq = cfg.truthy(mq_flag)
            LOG.info("full-device Tier-1 (MQ coder on device) %s by "
                     "config",
                     "enabled" if converter.device_mq else "disabled")
        cache_dir = config.get_str(cfg.COMPILE_CACHE)
        if cache_dir:
            from ..converters.tpu import maybe_enable_compile_cache
            maybe_enable_compile_cache(cache_dir)

    def register(self, bus: MessageBus, instances: int = 2) -> None:
        bus.consumer(BATCH_CONVERTER, self.handle, instances=instances)

    async def handle(self, message: dict) -> Reply:
        job_name = message[c.JOB_NAME]
        image_id = message[c.IMAGE_ID]
        file_path = message[c.FILE_PATH]
        ok = False
        conversion = Conversion(
            message.get(c.CONVERSION_TYPE)
            or self.config.get_str(cfg.CONVERSION_TYPE) or "lossless")
        # Batch items yield to interactive single-image traffic in the
        # encode scheduler's slot queue; only converters that know the
        # scheduler take the kwarg (the stub/CLI ones don't).
        kwargs = {}
        if "priority" in inspect.signature(
                self.converter.convert).parameters:
            kwargs["priority"] = PRIORITY_BATCH
        try:
            derivative = await asyncio.to_thread(
                self.converter.convert, image_id, file_path, conversion,
                **kwargs)
            reply = await self.bus.request_with_retry(S3_UPLOADER, {
                c.IMAGE_ID: os.path.basename(derivative),
                c.FILE_PATH: derivative,
                c.JOB_NAME: job_name,
                c.DERIVATIVE_IMAGE: True,
            })
            ok = reply.is_success
        except QueueFull as exc:
            # Encode-queue backpressure is transient by definition: the
            # bus's retry protocol requeues the item after a delay
            # instead of failing it (the reference's S3 semantics).
            LOG.warning("encode queue full for %s: %s", image_id, exc)
            return Reply.retry()
        except DeadlineExceeded as exc:
            LOG.error("batch item %s missed its encode deadline: %s",
                      image_id, exc)
        except ConverterError as exc:
            LOG.error("batch convert failed for %s: %s", image_id, exc)
        except Exception as exc:
            LOG.exception("batch item %s errored: %s", image_id, exc)
        for attempt in range(3):
            try:
                await update_item_status(
                    self.store, self.bus, job_name, image_id, ok,
                    self.config.get_str(cfg.IIIF_URL))
                break
            except KeyError:
                LOG.warning("job %s vanished before item %s resolved",
                            job_name, image_id)
                break
            except LockTimeout:
                # A transient lock timeout must not strand the item as
                # EMPTY forever (the job would never finalize); retry.
                LOG.warning("job lock timeout updating %s/%s (attempt %d)",
                            job_name, image_id, attempt + 1)
                await asyncio.sleep(0.1 * (attempt + 1))
        else:
            # Status never written: requeue the whole message rather than
            # ack it, or the item stays EMPTY and the job never finalizes.
            return Reply.retry()
        return Reply.success() if ok else Reply.failure(
            500, f"conversion failed for {image_id}")


async def start_job(job: Job, bus: MessageBus, config,
                    flags: features.FeatureFlagChecker,
                    conversion: str | None = None) -> None:
    """Dispatch every pending item of a queued job (reference:
    LoadCsvHandler.java:237-314):

    - within the size cap -> batch converter (or lambda-bucket upload in
      ``lambda`` mode);
    - oversized + large-images flag -> peer routing;
    - oversized without the flag -> item FAILED;
    - nothing runnable at all -> finalize immediately with
      ``nothing-processed`` (reference: :309-313).
    """
    max_size = config.get_int(cfg.MAX_SOURCE_SIZE)
    lambda_mode = (config.get_str(BATCH_MODE) or "tpu").lower() == "lambda"
    large_ok = flags.is_enabled(features.LARGE_IMAGES)
    dispatched = 0

    for item in job.items:
        if item.workflow_state != WorkflowState.EMPTY or not item.has_file():
            continue
        path = item.get_file()
        try:
            size = os.path.getsize(path)
        except OSError:
            await bus.send(ITEM_FAILURE,
                           {c.JOB_NAME: job.name, c.IMAGE_ID: item.id})
            dispatched += 1
            continue

        if size <= max_size:
            if lambda_mode:
                # Reference flow: push the source TIFF to the lambda
                # bucket; the external converter PATCHes back
                # (reference: LoadCsvHandler.java:256-263).
                ext = os.path.splitext(path)[1]
                reply = await bus.request_with_retry(S3_UPLOADER, {
                    c.IMAGE_ID: item.id + ext,
                    c.FILE_PATH: path,
                    c.JOB_NAME: job.name,
                    c.S3_BUCKET: config.get_str(cfg.LAMBDA_S3_BUCKET),
                })
                if not reply.is_success:
                    await bus.send(ITEM_FAILURE, {c.JOB_NAME: job.name,
                                                  c.IMAGE_ID: item.id})
            else:
                msg = {c.JOB_NAME: job.name, c.IMAGE_ID: item.id,
                       c.FILE_PATH: path}
                if conversion:
                    msg[c.CONVERSION_TYPE] = conversion
                await bus.send(BATCH_CONVERTER, msg)
            dispatched += 1
        elif large_ok:
            # reference: LoadCsvHandler.java:270-281
            # Send the absolute prefixed path — the same one the size check
            # used — matching the reference's source.getAbsolutePath()
            # (reference: LoadCsvHandler.java:256).
            reply = await bus.request_with_retry(LARGE_IMAGE, {
                c.JOB_NAME: job.name, c.IMAGE_ID: item.id,
                c.FILE_PATH: path,
            })
            if not reply.is_success:
                await bus.send(ITEM_FAILURE, {c.JOB_NAME: job.name,
                                              c.IMAGE_ID: item.id})
            dispatched += 1
        else:
            # reference: LoadCsvHandler.java:284-288 — too big, no route
            await bus.send(ITEM_FAILURE,
                           {c.JOB_NAME: job.name, c.IMAGE_ID: item.id})
            dispatched += 1

    if dispatched == 0:
        # reference: LoadCsvHandler.java:309-313
        await bus.send(FINALIZE_JOB, {c.JOB_NAME: job.name,
                                      c.NOTHING_PROCESSED: True})
