"""Per-endpoint latency SLO watchdog.

The HTTP trace middleware reports every request's (endpoint, seconds)
here. A breach bumps ``slo.breaches`` + ``slo.breach.<endpoint>``
counters in /metrics and triggers a flight-recorder dump carrying the
request id — so the spans of the slow request (and everything that ran
beside it) are frozen at the moment the budget blew, not re-requested
after the evidence scrolled out of the rings.

Configuration (first match wins):

- ``bucketeer.slo`` config key / ``BUCKETEER_SLO`` env: a spec like
  ``"default=500,get_image=250,load_image=2000"`` (milliseconds per
  endpoint — the handler name that labels the ``http.*`` stages in
  ``/metrics``; a bare number sets the default). Empty/unset disables
  the watchdog.
"""
from __future__ import annotations

import logging
import re

LOG = logging.getLogger(__name__)

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")


def _normalize_key(key: str) -> str:
    """Handler names label the ``http.*`` stages, but operators keep
    writing OpenAPI operationIds (``postBatches=800``) — normalize
    camelCase keys to the snake_case handler name (``post_batches``)
    instead of silently never matching."""
    if any(ch.isupper() for ch in key):
        return _CAMEL_RE.sub("_", key).lower()
    return key


class SloWatchdog:
    def __init__(self, default_ms: float | None = None,
                 per_endpoint: dict | None = None, sink=None,
                 flight=None):
        self.default_ms = default_ms
        self.per_endpoint = dict(per_endpoint or {})
        self._sink = sink
        self._flight = flight

    @classmethod
    def parse(cls, spec: str | None, sink=None, flight=None
              ) -> "SloWatchdog":
        """Parse a ``default=500,get_image=250`` spec (ms; keys are
        handler names — the ``http.*`` stage labels in ``/metrics``.
        camelCase OpenAPI operationIds like ``postBatches`` are
        normalized to the handler name). Malformed entries are skipped
        with
        a warning — a bad SLO string must not take the server down.
        Keys are not validated against the route table here (the
        watchdog has no registry); the server logs the parsed spec at
        boot so a never-matching key is visible next to the
        ``http.*`` stages it should have matched."""
        default = None
        per: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                if "=" in part:
                    key, val = part.split("=", 1)
                    key = _normalize_key(key.strip())
                    if key == "default":
                        default = float(val)
                    else:
                        per[key] = float(val)
                else:
                    default = float(part)
            except ValueError:
                LOG.warning("ignoring malformed SLO spec entry %r", part)
        return cls(default, per, sink=sink, flight=flight)

    @property
    def active(self) -> bool:
        return self.default_ms is not None or bool(self.per_endpoint)

    def threshold_ms(self, endpoint: str) -> float | None:
        # Lookups normalize like parse() does, so a camelCase label
        # finds the budget whichever spelling configured it.
        return self.per_endpoint.get(_normalize_key(endpoint),
                                     self.default_ms)

    def observe(self, endpoint: str, seconds: float,
                request_id=None) -> bool:
        """Record one served request; returns True on breach."""
        threshold = self.threshold_ms(endpoint)
        if threshold is None or seconds * 1e3 <= threshold:
            return False
        if self._sink is not None:
            self._sink.count("slo.breaches")
            self._sink.count(f"slo.breach.{endpoint}")
        LOG.warning("SLO breach on %s: %.1f ms > %.1f ms budget",
                    endpoint, seconds * 1e3, threshold)
        if self._flight is not None:
            self._flight.dump(f"slo-breach:{endpoint}",
                              request_id=request_id)
        return True

    def report(self) -> dict:
        out = {}
        if self.default_ms is not None:
            out["default_ms"] = self.default_ms
        out.update({f"{k}_ms": v for k, v in
                    sorted(self.per_endpoint.items())})
        return out
