"""Benchmark harness: the BASELINE configs, end to end, on whatever
backend is available.

Measures the product encode path (device transform + Tier-1 entropy
coding + Tier-2/boxing) against the 500 MPix/s north star
(BASELINE.json) and prints exactly one JSON line:

- config 1: single 4096x4096 RGB -> lossy JP2 with the *real* reference
  recipe (``-rate 3``, 512x512 tiles, 6 levels, RPCL, 6 layers —
  KakaduConverter.java:38-44), not the easier untargeted config earlier
  rounds measured.
- config 2: batch of 2Kx2K RGB images, lossy 9/7, 5 levels.
- config 3: lossless RCT-free 5/3 on a 16-bit grayscale archival scan.
- config 4: sharded-DWT dryrun — the row-sharded multi-level transform
  (parallel/sharded_dwt.py) over the device mesh; reported as a dryrun
  number because Tier-1/Tier-2 are excluded.
- config 5: mixed-size batch with upload overlapped with encode (the
  S3BucketVerticle-overlap analog: a background writer drains finished
  encodes while the next image encodes).

Backend init is retried with exponential backoff — the recurring
``axon ... UNAVAILABLE`` TPU setup error killed BENCH_r02 and r05
outright — and falls back to CPU after the retries so the harness
always reports *some* platform-labelled number instead of rc=1.
Init-time probing is not enough, though: BENCH_r05 showed the same
error raised at the *first dispatch* (``jax.devices()`` succeeds, the
first compiled program dies), after the init retry has already passed.
When a config fails with a backend-unavailable error, the harness
re-execs itself once under ``JAX_PLATFORMS=cpu`` (a half-initialized
PJRT plugin cannot be torn down in-process) and the JSON line reports
``platform_fallback: true`` — bench exits 0 on TPU-less hosts.

Env knobs: BENCH_SMOKE=1 shrinks every config to CI-smoke size;
BENCH_SIZE / BENCH_REPEATS / BENCH_BATCH_N / BENCH_BATCH_SIZE /
BENCH_SCAN_SIZE / BENCH_SHARD_SIZE / BENCH_CONFIGS (comma list, e.g.
"1,4") override individual configs; BENCH_BACKEND_RETRIES /
BENCH_BACKEND_BACKOFF tune the retry ladder.
"""
from __future__ import annotations

import json
import os
import platform as platform_mod
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

BASELINE_MPIX_S = 500.0
SMOKE = os.environ.get("BENCH_SMOKE", "") not in ("", "0")

# Set (to "1") when the harness re-exec'd itself onto CPU after a
# backend-unavailable error at run time; the guard also stops a second
# re-exec if even the CPU run somehow trips the detector.
_REEXEC_ENV = "BUCKETEER_BENCH_CPU_REEXEC"


def _backend_unavailable(exc: BaseException) -> bool:
    """Recognize the PJRT backend-setup failure that surfaces at first
    dispatch (BENCH_r05: ``RuntimeError: Unable to initialize backend
    'axon': UNAVAILABLE ...``), including when a config wrapped it."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        msg = str(exc)
        if ("Unable to initialize backend" in msg
                or "TPU backend setup/compile error" in msg
                or ("UNAVAILABLE" in msg and "backend" in msg)):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def _reexec_on_cpu() -> None:
    """Replace the process with a CPU-pinned copy of itself. In-process
    recovery is not possible once a PJRT plugin half-initialized: jitted
    programs cache backend handles and the failing plugin stays
    registered, so a clean interpreter is the only reliable path."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env[_REEXEC_ENV] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _env_int(name: str, default: int, smoke: int | None = None) -> int:
    if name in os.environ:
        return int(os.environ[name])
    return smoke if (SMOKE and smoke is not None) else default


# --- backend bring-up ----------------------------------------------------

def _clear_backends() -> None:
    import jax

    for fn in (getattr(jax, "clear_backends", None),
               getattr(getattr(getattr(jax, "extend", None), "backend",
                               None), "clear_backends", None)):
        if fn is not None:
            try:
                fn()
                return
            except Exception:
                continue


def _probe_dispatch() -> None:
    """Force a real compiled-program dispatch. ``jax.devices()``
    succeeding is not enough: BENCH_r02/r05 died with ``Unable to
    initialize backend 'axon': UNAVAILABLE`` at the *first dispatch*
    after the init probe had passed, so the init retry ladder has to
    exercise the same code path a config's first jit will."""
    import jax
    import jax.numpy as jnp

    jax.jit(lambda x: x * 2 + 1)(
        jnp.arange(16, dtype=jnp.int32)).block_until_ready()


def init_backend() -> dict:
    """Bring up a JAX backend, retrying transient TPU setup failures
    (exponential backoff), then falling back to CPU. Returns platform
    metadata for the report; raises only if even CPU init fails."""
    retries = _env_int("BENCH_BACKEND_RETRIES", 3)
    backoff = float(os.environ.get("BENCH_BACKEND_BACKOFF", "2.0"))
    errors: list = []
    import jax

    for attempt in range(retries + 1):
        try:
            devices = jax.devices()
            _probe_dispatch()
            return {"platform": devices[0].platform,
                    "n_devices": len(devices),
                    "attempts": attempt + 1, "fallback": False,
                    "dispatch_probe": True,
                    "errors": errors}
        # RuntimeError is the documented 'Unable to initialize backend'
        # path; a failed init can also leave xla_bridge half-built so
        # the *next* call dies on an AssertionError — treat any
        # exception as a retriable init failure.
        except Exception as exc:
            errors.append(f"{type(exc).__name__}: "
                          + str(exc).split("\n")[0][:200])
            _clear_backends()
            if attempt < retries:
                time.sleep(backoff * (2 ** attempt))
    # Out of retries: CPU keeps the scoreboard alive (rc=0, labelled).
    _clear_backends()
    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    # The fallback backend gets the same real-dispatch probe as the
    # primary: a broken CPU fallback must surface here as a labelled
    # init failure, not as a mid-config crash behind an asserted probe.
    probe_ok = True
    try:
        _probe_dispatch()
    except Exception as exc:
        probe_ok = False
        errors.append(f"cpu fallback probe: {type(exc).__name__}: "
                      + str(exc).split("\n")[0][:200])
    return {"platform": devices[0].platform, "n_devices": len(devices),
            "attempts": retries + 1, "fallback": True,
            "dispatch_probe": probe_ok, "errors": errors}


# --- synthetic content ---------------------------------------------------

def synthetic_photo(h: int, w: int | None = None,
                    seed: int = 7) -> np.ndarray:
    """Photograph-like content: smooth gradients + texture + edges, so the
    entropy coder sees realistic significance statistics."""
    w = w or h
    rng = np.random.default_rng(seed)
    y, x = np.mgrid[0:h, 0:w]
    base = (128 + 96 * np.sin(2 * np.pi * x / w * 3)
            * np.cos(2 * np.pi * y / h * 2))
    texture = rng.normal(0, 12, size=(h, w))
    edges = ((x // 256 + y // 256) % 2) * 20
    img = np.stack([
        np.clip(base + texture + edges, 0, 255),
        np.clip(base * 0.8 + texture + 30, 0, 255),
        np.clip(base * 0.6 + texture + edges + 60, 0, 255),
    ], axis=-1)
    return img.astype(np.uint8)


def synthetic_scan16(size: int, seed: int = 11) -> np.ndarray:
    """16-bit grayscale archival-scan-like content (BASELINE config 3)."""
    rng = np.random.default_rng(seed)
    y, x = np.mgrid[0:size, 0:size]
    base = 32768 + 18000 * np.sin(x / 37.0) * np.cos(y / 29.0)
    grain = rng.normal(0, 600, size=(size, size))
    return np.clip(base + grain, 0, 65535).astype(np.uint16)


def _timed(fn, repeats: int) -> tuple:
    """(best seconds, last result) over ``repeats`` runs after the
    caller's warmup."""
    best, result = float("inf"), None
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - t0)
    return best, result


def _stage_profile(sink, prefixes=("encode.", "decode.")) -> dict:
    """Per-stage split from a Metrics sink, for the bench JSON: the
    same stage registry /metrics serves (front-end dispatch vs CX/D vs
    MQ replay vs Tier-2, decode segments), no parallel timer set to
    drift out of sync."""
    out = {}
    for name, st in sink.report()["stages"].items():
        if not name.startswith(tuple(prefixes)):
            continue
        entry = {"total_s": st["total_s"], "count": st["count"]}
        for k in ("mpixels_per_s", "items_per_s", "items"):
            if k in st:
                entry[k] = st[k]
        out[name] = entry
    return out


def _stage_percentiles(sink, prefixes=("encode.", "decode.")) -> dict:
    """Server-side p50/p95/p99 per stage from the sink's log2-bucket
    histograms (ISSUE 14) — the bench JSON twin of ``stage_profile``,
    so tail behavior ships next to the throughput split."""
    out = {}
    for name, st in sink.report()["stages"].items():
        if not name.startswith(tuple(prefixes)):
            continue
        if "p95_ms" not in st:
            continue
        out[name] = {k: st[k] for k in ("p50_ms", "p95_ms", "p99_ms")}
    return out


def _assert_p95_agreement(server_ms, client_ms, context: str) -> None:
    """Server-side histograms must agree with client-observed
    percentiles: quarter-octave buckets bound quantization at ~19%,
    the rest of the window covers sampling noise on smoke-sized runs
    plus the client's extra thread-scheduling overhead."""
    assert server_ms is not None, f"{context}: no server-side histogram"
    assert abs(server_ms - client_ms) <= 0.5 * client_ms + 10.0, (
        f"{context}: server-side p95 {server_ms:.1f} ms disagrees with "
        f"client-side {client_ms:.1f} ms beyond tolerance")


# --- configs -------------------------------------------------------------

# The three Tier-1 modes the split compares: legacy host Tier-1 over
# packed bitmaps, device CX/D + host MQ replay, and full-device Tier-1
# (CX/D + MQ coder on device, host = block assembly only).
_SPLIT_MODES = (("legacy", dict(device_cxd=False, device_mq=False)),
                ("cxd", dict(device_cxd=True, device_mq=False)),
                ("device_mq", dict(device_mq=True)))


def _tier1_split_report(img, params) -> dict:
    """Host-coding segment across the three Tier-1 modes (legacy /
    MQ-replay / device-MQ): one instrumented encode per mode, reporting
    the host seconds, the device Tier-1 segments, symbol and byte
    throughput and the measured overlap ratio — plus re-timed
    host-Tier-1-only numbers, whose ratios are the acceptance gates
    (ISSUE 3: replay vs legacy; ISSUE 9: device-MQ host work <= 1/5 of
    replay's — with device MQ the host's whole Tier-1 share is
    assemble_mq_blocks)."""
    import dataclasses

    from bucketeer_tpu.codec import cxd as cxd_mod
    from bucketeer_tpu.codec import encoder, t1_batch
    from bucketeer_tpu.server.metrics import Metrics

    # Two probes. Serial (the config's own tiling, usually one chunk):
    # the host segment runs uncontended, so the per-mode host seconds
    # compare cleanly. Overlap (many single-tile chunks): the ratio the
    # pipeline actually achieves when host coding hides behind device
    # compute — on CPU the two sides share cores, which would skew the
    # serial timing if merged into one probe.
    out: dict = {}
    calls: dict = {}
    for mode, flags in _SPLIT_MODES:
        calls[mode] = []
        out[mode] = _tier1_split_one(
            encoder, Metrics, img,
            dataclasses.replace(params, **flags), mode,
            capture=calls[mode])
    # The sink segments above include scheduling noise at smoke sizes;
    # the speedup numbers re-time the captured host Tier-1 calls alone
    # (same inputs the measured encode used), min of 3 — this is "host
    # Tier-1 time per chunk" with nothing else on the cores.
    for mode, fn in (("legacy", t1_batch.encode_packed),
                     ("cxd", t1_batch.encode_cxd),
                     ("device_mq", cxd_mod.assemble_mq_blocks)):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for args in calls[mode]:
                fn(*args)
            best = min(best, time.perf_counter() - t0)
        out[mode]["host_tier1_retimed_s"] = round(best, 4)
    legacy_s = out["legacy"]["host_tier1_retimed_s"]
    cxd_s = out["cxd"]["host_tier1_retimed_s"]
    mq_s = out["device_mq"]["host_tier1_retimed_s"]
    out["host_tier1_speedup"] = (round(legacy_s / cxd_s, 2)
                                 if cxd_s > 0 else None)
    # The ISSUE 9 acceptance number: host Tier-1 work with the MQ coder
    # on device vs the MQ-replay mode's host share.
    out["host_reduction_device_mq_vs_replay"] = (
        round(cxd_s / mq_s, 2) if mq_s > 0 else None)

    side = min(128, img.shape[0], img.shape[1])
    ov_img = img[:side, :side]
    ov_params = dataclasses.replace(params, tile_size=min(64, side))
    prev_tiles = os.environ.get("BUCKETEER_OVERLAP_TILES")
    os.environ["BUCKETEER_OVERLAP_TILES"] = "1"
    try:
        # Overlap is a device-vs-host race; in device-MQ mode the host
        # side is assembly-only (nothing to hide), so the probe covers
        # the two modes with a real host segment.
        out["overlap_probe"] = {
            mode: _tier1_split_one(
                encoder, Metrics, ov_img,
                dataclasses.replace(ov_params, **flags),
                mode)["overlap_ratio"]
            for mode, flags in _SPLIT_MODES[:2]}
    finally:
        if prev_tiles is None:
            os.environ.pop("BUCKETEER_OVERLAP_TILES", None)
        else:
            os.environ["BUCKETEER_OVERLAP_TILES"] = prev_tiles
    out["graftcost_prediction"] = _graftcost_prediction(out)
    return out


def _graftcost_prediction(split: dict) -> dict:
    """The static cost model's device-Tier-1 symbol throughput per
    machine model (graftcost.tier1_prediction) beside the measured
    device-MQ number, with the prediction error on the
    backend-matching model — every bench run calibrates the model, so
    its machine numbers are tracked against reality instead of
    trusted."""
    import jax

    from bucketeer_tpu.analysis import graftcost

    modeled = graftcost.tier1_prediction()
    if not modeled:
        return {}
    entry: dict = {"modeled": modeled}
    measured = (split.get("device_mq") or {}).get("symbols_per_s") or 0
    entry["measured_symbols_per_s"] = measured
    machine = "cpu" if jax.default_backend() == "cpu" else "tpu_v4"
    entry["machine_for_error"] = machine
    mp = modeled.get(machine, {}).get("symbols_per_s")
    if measured and mp:
        # Signed relative error: +1.0 means the model promised double
        # what the hardware delivered.
        entry["prediction_error"] = round(mp / measured - 1.0, 3)
    return entry


def _tier1_split_one(encoder, Metrics, img, p, mode,
                     capture: list | None = None) -> dict:
    from bucketeer_tpu.codec import cxd as cxd_mod
    from bucketeer_tpu.codec import t1_batch

    encoder.encode_jp2(img, 8, p)               # warm: exclude compiles
    sink = Metrics()
    encoder.set_metrics_sink(sink)
    orig = (t1_batch.encode_packed, t1_batch.encode_cxd,
            cxd_mod.assemble_mq_blocks)
    if capture is not None:
        # Record the host Tier-1 inputs so the caller can re-time the
        # host calls in isolation after the encode. In device-MQ mode
        # the host's whole Tier-1 share is the block assembly.
        def cap_packed(*args):
            capture.append(args)
            return orig[0](*args)

        def cap_cxd(streams):
            capture.append((streams,))
            return orig[1](streams)

        def cap_mq(*args):
            capture.append(args)
            return orig[2](*args)

        t1_batch.encode_packed = cap_packed
        t1_batch.encode_cxd = cap_cxd
        cxd_mod.assemble_mq_blocks = cap_mq
    try:
        encoder.encode_jp2(img, 8, p)
    finally:
        encoder.set_metrics_sink(None)
        (t1_batch.encode_packed, t1_batch.encode_cxd,
         cxd_mod.assemble_mq_blocks) = orig
    rep = sink.report()
    st = rep["stages"]
    ov = rep.get("overlap", {}).get("encode", {})
    entry = {
        "host_tier1_s": st["encode.host_code"]["total_s"],
        "device_s": st["encode.device_dispatch"]["total_s"],
        "overlap_ratio": ov.get("overlap_ratio", 0.0),
    }
    if mode == "cxd":
        entry["mq_replay_s"] = st["encode.mq_replay"]["total_s"]
        entry["cxd_device_s"] = st["encode.cxd_device"]["total_s"]
        entry["symbols"] = st["encode.mq_replay"].get("items", 0)
        entry["symbols_per_s"] = st["encode.mq_replay"].get(
            "items_per_s", 0)
    elif mode == "device_mq":
        entry["cxd_device_s"] = st["encode.cxd_device"]["total_s"]
        entry["mq_device_s"] = st["encode.mq_device"]["total_s"]
        entry["t1_device_total_s"] = st[
            "encode.t1_device_total"]["total_s"]
        entry["symbols"] = st["encode.t1_device_total"].get("items", 0)
        entry["symbols_per_s"] = st["encode.t1_device_total"].get(
            "items_per_s", 0)
        entry["bytes"] = st["encode.mq_device"].get("items", 0)
        entry["bytes_per_s"] = st["encode.mq_device"].get(
            "items_per_s", 0)
    return entry


def _want_tier1_split() -> bool:
    """The CX/D comparison runs the jnp scan as the 'device' on CPU —
    fine at smoke sizes, prohibitive at the full 4096². Auto: smoke or
    a real accelerator; BENCH_CXD=1/0 forces."""
    import jax

    from bucketeer_tpu.config import truthy

    env = os.environ.get("BENCH_CXD", "auto")
    if env != "auto":
        return truthy(env)
    return SMOKE or jax.default_backend() != "cpu"


def config1_single_4k(repeats: int) -> dict:
    """BASELINE config 1, real recipe: 4096x4096 RGB -> lossy `-rate 3`,
    512 tiles, 6 levels, RPCL, 6 layers, SOP/EPH/PLT."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    from bucketeer_tpu.server.metrics import Metrics

    size = _env_int("BENCH_SIZE", 4096, smoke=512)
    img = synthetic_photo(size)
    params = EncodeParams.kakadu_recipe(lossless=False, rate=3.0)
    # Warm with the real geometry: a smaller slab would dispatch
    # different chunk/batch-bucket program variants and leave XLA
    # compiles inside the first timed repeat.
    encoder.encode_jp2(img, 8, params)
    # Per-stage split of the timed repeats via the /metrics stage
    # registry (ROADMAP item 5: where does the wall clock actually go).
    sink = Metrics()
    encoder.set_metrics_sink(sink)
    try:
        best, data = _timed(lambda: encoder.encode_jp2(img, 8, params),
                            repeats)
    finally:
        encoder.set_metrics_sink(None)
    mpix = size * size / 1e6
    result = {"value": round(mpix / best, 3), "unit": "MPix/s",
              "seconds": round(best, 3),
              "image": f"{size}x{size}x3 uint8",
              "recipe": "kakadu rate=3 tiles=512 levels=6",
              "output_bytes": len(data),
              "bpp": round(8.0 * len(data) / (size * size), 3),
              "stage_profile": _stage_profile(sink),
              "repeats": repeats}
    if _want_tier1_split():
        # On CPU, bound the jnp-scan 'device' cost: the host-segment
        # comparison is per-chunk anyway, so a 192² slab is
        # representative and keeps smoke CI fast (the three-mode split
        # runs the CX/D and MQ scans several times each).
        import jax

        split_img = (img if jax.default_backend() != "cpu"
                     else img[:min(size, 192), :min(size, 192)])
        result["tier1_split"] = _tier1_split_report(split_img, params)
    # Pow-2 bucket occupancy of everything this config launched,
    # weighted by the recorded workload-shape histogram (the graftcost
    # seams in frontend/cxd/decode record each launch).
    from bucketeer_tpu.analysis import graftcost

    hist = graftcost.bucket_histogram()
    if hist:
        result["padding_waste"] = graftcost.padding_waste(hist)
    return result


def config2_batch_2k(repeats: int) -> dict:
    """BASELINE config 2 (scaled by env): N 2Kx2K RGB images, lossy
    CDF 9/7, 5 DWT levels, aggregate throughput."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    n = _env_int("BENCH_BATCH_N", 8, smoke=2)
    size = _env_int("BENCH_BATCH_SIZE", 2048, smoke=256)
    imgs = [synthetic_photo(size, seed=100 + i) for i in range(n)]
    params = EncodeParams(lossless=False, levels=5, tile_size=1024,
                          base_delta=2.0, rate=3.0)
    encoder.encode_jp2(imgs[0], 8, params)                 # compile

    def run():
        return sum(len(encoder.encode_jp2(im, 8, params)) for im in imgs)

    best, total_bytes = _timed(run, repeats)
    mpix = n * size * size / 1e6
    return {"value": round(mpix / best, 3), "unit": "MPix/s",
            "seconds": round(best, 3), "images": n,
            "image": f"{size}x{size}x3 uint8",
            "output_bytes": total_bytes, "repeats": repeats}


def config3_lossless16(repeats: int) -> dict:
    """BASELINE config 3: lossless 5/3 on a 16-bit grayscale scan."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    size = _env_int("BENCH_SCAN_SIZE", 2048, smoke=256)
    img = synthetic_scan16(size)
    params = EncodeParams(lossless=True, levels=5,
                          tile_size=min(1024, size))
    encoder.encode_jp2(img, 16, params)    # warm the real geometry
    best, data = _timed(lambda: encoder.encode_jp2(img, 16, params),
                        repeats)
    mpix = size * size / 1e6
    return {"value": round(mpix / best, 3), "unit": "MPix/s",
            "seconds": round(best, 3),
            "image": f"{size}x{size} uint16",
            "output_bytes": len(data),
            "bpp": round(8.0 * len(data) / (size * size), 3),
            "repeats": repeats}


def config4_sharded_dryrun(repeats: int) -> dict:
    """BASELINE config 4 dryrun: the row-sharded multi-level DWT over
    the full device mesh (the 20000x20000 map-scan transform), Tier-1/2
    excluded — hence 'dryrun', not a full-encode number."""
    import jax
    import jax.numpy as jnp

    from bucketeer_tpu.parallel import make_mesh, sharded_dwt2d_forward
    from bucketeer_tpu.parallel.sharded_dwt import can_row_shard

    size = _env_int("BENCH_SHARD_SIZE", 8192, smoke=512)
    n_dev = len(jax.devices())
    levels = 5
    while levels > 1 and not can_row_shard(size, levels, max(n_dev, 2)):
        levels -= 1
    shards = n_dev if n_dev > 1 and can_row_shard(size, levels,
                                                  n_dev) else 1
    mesh = make_mesh(tile_parallel=shards)
    img = synthetic_scan16(size).astype(np.int32)

    def run():
        if shards > 1:
            ll, bands = sharded_dwt2d_forward(jnp.asarray(img), levels,
                                              True, mesh)
        else:
            from bucketeer_tpu.codec.dwt import dwt2d_forward
            ll, bands = dwt2d_forward(jnp.asarray(img), levels, True)
        jax.block_until_ready(ll)
        return ll

    run()                                                  # compile
    best, _ = _timed(run, repeats)
    mpix = size * size / 1e6
    return {"value": round(mpix / best, 3), "unit": "MPix/s",
            "seconds": round(best, 4), "dryrun": True,
            "stage": "sharded multi-level 5/3 DWT only",
            "image": f"{size}x{size} int32", "levels": levels,
            "shards": shards, "repeats": repeats}


def config5_mixed_overlap(repeats: int) -> dict:
    """BASELINE config 5 analog: mixed-size batch, 'upload' (durable
    local write, the FakeS3 stand-in) overlapped with the next encode."""
    import tempfile

    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    if SMOKE and "BENCH_MIXED_SIZES" not in os.environ:
        sizes = [256, 128, 192]
    else:
        sizes = [int(s) for s in os.environ.get(
            "BENCH_MIXED_SIZES", "2048,1024,1536,768").split(",")]
    imgs = [synthetic_photo(s, seed=200 + i)
            for i, s in enumerate(sizes)]
    params = EncodeParams(lossless=False, levels=5, tile_size=1024,
                          base_delta=2.0, rate=3.0)
    for im in imgs:
        encoder.encode_jp2(im, 8, params)                  # compile all

    def upload(data: bytes, path: str) -> None:
        with open(path, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())

    def run():
        total = 0
        with tempfile.TemporaryDirectory() as tmp, \
                ThreadPoolExecutor(max_workers=2) as pool:
            futs = []
            for i, im in enumerate(imgs):
                data = encoder.encode_jp2(im, 8, params)
                total += len(data)
                futs.append(pool.submit(
                    upload, data, os.path.join(tmp, f"{i}.jp2")))
            for f in futs:
                f.result()
        return total

    best, total_bytes = _timed(run, repeats)
    mpix = sum(s * s for s in sizes) / 1e6
    return {"value": round(mpix / best, 3), "unit": "MPix/s",
            "seconds": round(best, 3), "sizes": sizes,
            "output_bytes": total_bytes, "repeats": repeats,
            "overlap": "upload behind encode"}


def config6_decode(repeats: int) -> dict:
    """Decode path (the GET /images read endpoint's engine): full decode
    and a reduce=2 thumbnail read of a lossless JP2, with the
    per-segment split (decode.t2_parse / mq / t1 / device_inverse).
    Host Tier-1 decode is pure Python for now, so the default size is
    modest; the segment report is what tracks where the time goes."""
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.decode import decode, set_metrics_sink
    from bucketeer_tpu.codec.encoder import EncodeParams
    from bucketeer_tpu.server.metrics import Metrics

    size = _env_int("BENCH_DECODE_SIZE", 256, smoke=96)
    img = synthetic_photo(size)
    params = EncodeParams(lossless=True, levels=4,
                          tile_size=min(128, size))
    data = encoder.encode_jp2(img, 8, params)
    decode(data)                               # warm the inverse compiles
    decode(data, reduce=2)
    sink = Metrics()
    set_metrics_sink(sink)
    try:
        best_full, full = _timed(lambda: decode(data), repeats)
        best_thumb, thumb = _timed(lambda: decode(data, reduce=2),
                                   repeats)
    finally:
        set_metrics_sink(None)
    segments = {}
    for name, st in sink.report()["stages"].items():
        if name.startswith("decode."):
            entry = {"total_s": st["total_s"]}
            for k in ("mpixels_per_s", "items_per_s", "items"):
                if k in st:
                    entry[k] = st[k]
            segments[name] = entry
    mpix = size * size / 1e6
    t_mpix = thumb.shape[0] * thumb.shape[1] / 1e6
    return {"value": round(mpix / best_full, 3), "unit": "MPix/s",
            "seconds": round(best_full, 3),
            "image": f"{size}x{size}x3 uint8 lossless",
            "input_bytes": len(data),
            "full_shape": list(full.shape),
            "thumbnail": {"reduce": 2, "shape": list(thumb.shape),
                          "seconds": round(best_thumb, 3),
                          "value": round(t_mpix / best_thumb, 3),
                          "speedup_vs_full": round(
                              best_full / best_thumb, 2)},
            "segments": segments, "repeats": repeats}


def config7_concurrent_serving(repeats: int) -> dict:
    """Concurrent serving through the cross-request encode scheduler
    (engine/scheduler.py): N closed-loop clients, each encoding R
    distinct same-shape images back to back, all through one shared
    scheduler. Reports aggregate MPix/s, per-request p50/p95 latency,
    measured device-batch occupancy (requests per merged launch), the
    serialized 1-client x N*R baseline, and byte-identity vs the serial
    encoder — the continuous-batching numbers the serving story stands
    on. With more than one visible device the scheduler's pool spreads
    launches (ISSUE 17): the report carries per-device launch counts
    and a serialized single-device-pool comparison round. Env:
    BENCH_CLIENTS, BENCH_REQS_PER_CLIENT, BENCH_SERVE_SIZE,
    BENCH_SCHED_SLOTS, BENCH_SCHED_WINDOW_MS, BENCH_SCHED_DEVICES
    (0 = every visible device)."""
    import threading

    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams
    from bucketeer_tpu.engine.scheduler import EncodeScheduler
    from bucketeer_tpu.server.metrics import Metrics

    n_clients = _env_int("BENCH_CLIENTS", 8, smoke=4)
    per_client = _env_int("BENCH_REQS_PER_CLIENT", 3, smoke=3)
    size = _env_int("BENCH_SERVE_SIZE", 1024, smoke=192)
    window_s = float(os.environ.get("BENCH_SCHED_WINDOW_MS", "10")) / 1e3
    # Encode slots: cap concurrency at roughly the host's cores — more
    # admitted encodes than cores just thrash the GIL-bound Tier-2
    # share; the queue (not the OS scheduler) should hold the excess.
    slots = _env_int("BENCH_SCHED_SLOTS",
                     max(2, min(n_clients, (os.cpu_count() or 2) - 1)))
    devices = _env_int("BENCH_SCHED_DEVICES", 0)
    imgs = [[synthetic_photo(size, seed=300 + 16 * c + k)
             for k in range(per_client)] for c in range(n_clients)]
    flat = [im for client_imgs in imgs for im in client_imgs]
    params = EncodeParams(lossless=False, levels=4, base_delta=2.0,
                          rate=3.0)

    # Serialized baseline (and the byte-identity reference): one client
    # encoding every image back to back on the plain encoder. The first
    # encode warms the solo-batch compile; best of two passes so a
    # noisy neighbor can't sandbag the comparison either way.
    encoder.encode_jp2(flat[0], 8, params)
    serial_s = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        serial = [encoder.encode_jp2(im, 8, params) for im in flat]
        serial_s = min(serial_s, time.perf_counter() - t0)

    sched = EncodeScheduler(max_concurrent=slots,
                            queue_depth=2 * n_clients,
                            window_s=window_s,
                            devices=devices or None)
    sink = Metrics()

    def round_trip(s=None) -> tuple:
        s = s if s is not None else sched
        outs = [[None] * per_client for _ in range(n_clients)]
        lats: list = []
        errs: list = []
        barrier = threading.Barrier(n_clients)

        def client(c: int) -> None:
            barrier.wait()
            for k in range(per_client):
                c0 = time.perf_counter()
                try:
                    outs[c][k] = s.encode_jp2(imgs[c][k], 8, params)
                except BaseException as exc:
                    errs.append(exc)
                    return
                lats.append(time.perf_counter() - c0)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        w0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            # A silently dead client would turn a regression into a
            # bogus-but-green data point; fail the config instead.
            raise errs[0]
        return time.perf_counter() - w0, outs, lats

    round_trip()                 # warm the merged-bucket compiles
    # Sink attached after warmup: the server-side request histogram
    # then covers exactly the measured rounds, so its p95 is
    # comparable 1:1 with the client-side all-round percentile.
    sched.set_metrics_sink(sink)
    best, outs, lats = None, None, None
    all_lats: list = []
    for _ in range(max(repeats, 3)):
        wall, o, l = round_trip()
        all_lats.extend(l)
        if best is None or wall < best:
            best, outs, lats = wall, o, l
    # Single-device-pool comparison round (ISSUE 17): same clients and
    # images with the pool pinned to one device — the floor the
    # multi-device aggregate throughput must not fall below.
    sched1 = EncodeScheduler(max_concurrent=slots,
                             queue_depth=2 * n_clients,
                             window_s=window_s, devices=1)
    try:
        round_trip(sched1)       # warm this pool's merge window shape
        single_best = min(round_trip(sched1)[0] for _ in range(2))
    finally:
        sched1.close()
    try:
        lats_ms = sorted(x * 1e3 for x in lats)
        rep = sink.report()
        occ = rep.get("values", {}).get("encode.batch_occupancy",
                                        {"count": 0, "mean": 0, "max": 0})
        counters = rep.get("counters", {})
        qw = rep["stages"].get("encode.queue_wait", {})
        flat_out = [o for client_outs in outs for o in client_outs]
        mpix = len(flat) * size * size / 1e6
        # ISSUE 14 gate: the new server-side request-latency histogram
        # (encode.request, /metrics p95) must agree with what clients
        # actually measured across the same rounds.
        all_ms = sorted(x * 1e3 for x in all_lats)
        client_p95_ms = all_ms[min(len(all_ms) - 1,
                                   int(len(all_ms) * 0.95))]
        server_p95_ms = rep["stages"].get("encode.request",
                                          {}).get("p95_ms")
        _assert_p95_agreement(server_p95_ms, client_p95_ms,
                              "7_concurrent_serving")
        return {
            "value": round(mpix / best, 3), "unit": "MPix/s",
            "seconds": round(best, 3), "clients": n_clients,
            "requests_per_client": per_client, "slots": slots,
            "image": f"{size}x{size}x3 uint8 rate=3",
            "p50_ms": round(lats_ms[len(lats_ms) // 2], 1),
            "p95_ms": round(lats_ms[min(len(lats_ms) - 1,
                                        int(len(lats_ms) * 0.95))], 1),
            "serialized_seconds": round(serial_s, 3),
            "speedup_vs_serialized": round(serial_s / best, 2),
            "occupancy": {"mean": occ["mean"], "max": occ["max"],
                          "launches": occ["count"]},
            "devices": sched.pool_report().get("devices"),
            "device_launches": {
                k.rsplit(".", 1)[-1]: v for k, v in counters.items()
                if k.startswith("encode.device_launches.d")},
            "distinct_devices": sum(
                1 for k in counters
                if k.startswith("encode.device_launches.d")),
            "single_device_pool_seconds": round(single_best, 3),
            "speedup_vs_single_device_pool": round(single_best / best,
                                                   2),
            "queue_wait_ms": round(
                1e3 * qw.get("total_s", 0.0) / max(1, qw.get("count", 1)),
                2),
            "admission_rejects": counters.get("encode.admission_rejects",
                                              0),
            "byte_identical": all(a == b
                                  for a, b in zip(serial, flat_out)),
            "server_p95_ms": round(server_p95_ms, 1),
            "client_p95_all_rounds_ms": round(client_p95_ms, 1),
            "stage_percentiles": _stage_percentiles(sink, ("encode.",)),
            "repeats": repeats,
        }
    finally:
        sched.close()


def config8_tile_storm(repeats: int) -> dict:
    """Closed-loop tile-request storm against the random-access read
    path (the GET /images?region= engine): N clients pull tile regions
    of a stored derivative through the shared scheduler at read
    priority. Two phases — cache-cold (every tile distinct: index
    build + indexed Tier-2 + windowed Tier-1/inverse) and cache-warm
    (same tiles again: decoded-tile LRU hits) — reporting aggregate
    tiles/s and p50/p95 latency per phase against the whole-image-decode
    baseline (what serving a tile cost before random access). Env:
    BENCH_STORM_SIZE, BENCH_STORM_TILE, BENCH_STORM_CLIENTS."""
    import dataclasses
    import queue as queue_mod
    import tempfile
    import threading

    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.decode import decode, set_metrics_sink
    from bucketeer_tpu.codec.encoder import EncodeParams
    from bucketeer_tpu.converters.reader import TpuReader
    from bucketeer_tpu.engine.scheduler import Scheduler
    from bucketeer_tpu.server.metrics import Metrics

    size = _env_int("BENCH_STORM_SIZE", 1024, smoke=256)
    tile = _env_int("BENCH_STORM_TILE", max(64, size // 8), smoke=64)
    clients = _env_int("BENCH_STORM_CLIENTS", 4, smoke=4)
    img = synthetic_photo(size)
    # The reference recipe (RPCL + PLT + R tile-parts): the index build
    # takes the PLT arithmetic path, no header walk.
    params = dataclasses.replace(
        EncodeParams.kakadu_recipe(lossless=False, rate=3.0),
        tile_size=min(512, size))
    data = encoder.encode_jp2(img, 8, params)

    # Whole-image-decode baseline: what one tile request costs when the
    # server can only decode everything and crop.
    decode(data)                                   # warm the compiles
    base_s, full = _timed(lambda: decode(data), max(1, repeats))

    tiles = [(x, y, tile, tile)
             for y in range(0, size, tile) for x in range(0, size, tile)]

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "storm.jp2")
        with open(path, "wb") as fh:
            fh.write(data)
        sink = Metrics()
        sched = Scheduler(max_concurrent=max(2, clients),
                          queue_depth=4 * clients)
        sched.set_metrics_sink(sink)
        set_metrics_sink(sink)
        reader = TpuReader(cache_mb=256, metrics=sink, scheduler=sched)

        def run_phase(check_against=None) -> dict:
            work: queue_mod.Queue = queue_mod.Queue()
            for t in tiles:
                work.put(t)
            lats: list = []
            errs: list = []
            lock = threading.Lock()

            def client() -> None:
                while True:
                    try:
                        region = work.get_nowait()
                    except queue_mod.Empty:
                        return
                    t0 = time.perf_counter()
                    try:
                        out = reader.read(path, region=region)
                    except BaseException as exc:
                        errs.append(exc)
                        return
                    dt = time.perf_counter() - t0
                    with lock:
                        lats.append(dt)
                    if check_against is not None:
                        x, y, w, h = region
                        if not np.array_equal(
                                out, check_against[y:y + h, x:x + w]):
                            errs.append(AssertionError(
                                f"tile {region} not bit-exact"))

            threads = [threading.Thread(target=client)
                       for _ in range(clients)]
            w0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - w0
            if errs:
                raise errs[0]
            lats.sort()
            return {"tiles": len(lats),
                    "seconds": round(wall, 3),
                    "tiles_per_s": round(len(lats) / wall, 2),
                    "p50_ms": round(1e3 * lats[len(lats) // 2], 1),
                    "p95_ms": round(
                        1e3 * lats[min(len(lats) - 1,
                                       int(len(lats) * 0.95))], 1),
                    "mean_ms": round(1e3 * sum(lats) / len(lats), 2)}

        try:
            # Warm the region-inverse compiles (one pass), then drop
            # both cache tiers so the cold phase measures the whole
            # random-access path (index build included), not XLA
            # compilation.
            run_phase()
            reader.reset_caches(tiles=True, index=True)
            sink2 = Metrics()
            sched.set_metrics_sink(sink2)
            reader.metrics = sink2
            set_metrics_sink(sink2)
            cold = run_phase(check_against=full)
            warm = run_phase()
            rep = sink2.report()
            counters = rep.get("counters", {})
            # ISSUE 14 gate: server-side decode.request p95 (histogram;
            # only cache misses reach the scheduler, so it covers
            # exactly the cold phase) vs the cold clients' own p95.
            server_p95_ms = rep["stages"].get("decode.request",
                                              {}).get("p95_ms")
            _assert_p95_agreement(server_p95_ms, cold["p95_ms"],
                                  "8_tile_storm")
        finally:
            set_metrics_sink(None)
            sched.close()

    # Aggregate serving throughput vs a whole-image-decode server on
    # the same hardware (which, like us, is GIL-bound across clients):
    # it serves at most 1/full_s tiles/s however many clients connect.
    speedup = cold["tiles_per_s"] * base_s
    return {
        "value": cold["tiles_per_s"], "unit": "tiles/s",
        "seconds": cold["seconds"],
        "image": f"{size}x{size}x3 uint8 rate=3",
        "tile": f"{tile}x{tile}",
        "tile_area_fraction": round(tile * tile / (size * size), 5),
        "clients": clients,
        "cold": cold, "warm": warm,
        "full_decode_baseline_s": round(base_s, 3),
        "speedup_vs_full_decode": round(speedup, 2),
        "region_blocks": counters.get("decode.region_blocks", 0),
        "cache": {
            "tile_hits": counters.get("decode.cache_hits", 0),
            "tile_misses": counters.get("decode.cache_misses", 0),
            "index_hits": counters.get("decode.index_cache_hits", 0),
            "index_misses": counters.get("decode.index_cache_misses", 0),
        },
        "admission_rejects": counters.get("decode.admission_rejects", 0),
        # Least-loaded pool placement of decode request threads
        # (ISSUE 17): which devices served the cold-phase reads.
        "device_assigned": {
            k.rsplit(".", 1)[-1]: v for k, v in counters.items()
            if k.startswith("decode.device_assigned.d")},
        "server_p95_ms": round(server_p95_ms, 1),
        "stage_profile": _stage_profile(sink2, ("decode.",)),
        "stage_percentiles": _stage_percentiles(sink2, ("decode.",)),
        "repeats": repeats,
    }


def config9_batch_dataplane(repeats: int) -> dict:
    """The batch data plane (ISSUE 19): one submit_batchread through a
    1-device scheduler pool (per-item fan-out + merged dequant
    launches + sharded placement) vs the decode-then-stack baseline a
    client would write against the same server: N admitted reads
    (sched.read -> decode_to_coefficients), host materialize,
    np.stack, re-upload.  Both paths pay the identical Tier-1 entropy
    decode, so the margin is the serving overhead the batch plane
    amortizes — one admission instead of N, one merged dequant launch
    instead of N dispatches, one device-side stack instead of a
    host round-trip.  Reports batches/s, bytes/s and the speedup at
    2-3 batch sizes, the merged-launch occupancy and per-device
    launch spread from the scheduler's own ledger, plus a
    byte-identity check of the batched bands against the baseline
    stack. Env: BENCH_BATCHPLANE_SIZE (image edge),
    BENCH_BATCHPLANE_NS (comma list of batch sizes)."""
    import jax

    from bucketeer_tpu import batches as batches_mod
    from bucketeer_tpu import tensor as tensor_mod
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams
    from bucketeer_tpu.engine.scheduler import EncodeScheduler
    from bucketeer_tpu.server.metrics import Metrics

    # Training-crop-sized tiles: the batch plane amortizes per-request
    # serving overhead (admission, spans, dequant dispatch, host
    # round-trip), so the margin over decode-then-stack is largest
    # where per-item decode work is small — which is exactly the
    # data-loader regime (small coefficient crops, big N).
    size = _env_int("BENCH_BATCHPLANE_SIZE", 64, smoke=32)
    ns_spec = os.environ.get("BENCH_BATCHPLANE_NS",
                             "4,8" if SMOKE else "2,4,8")
    sizes = [int(s) for s in ns_spec.split(",") if s.strip()]
    n_max = max(sizes)

    params = EncodeParams(lossless=True, levels=2,
                          tile_size=min(128, size))
    blobs = {}
    for i in range(n_max):
        blobs[f"img{i}"] = encoder.encode_jp2(
            synthetic_photo(size, seed=1901 + i), 8, params)

    # A generous merge window costs full groups nothing (the worker
    # breaks out the moment the advertised fan-out width arrives) but
    # keeps one GIL-straggler item from splitting the merged launch.
    sched = EncodeScheduler(queue_depth=32, max_concurrent=16,
                            devices=1, window_s=0.3)

    def serve_one(blob):
        """One per-image coefficient read as the serving tier delivers
        it: admitted interactive read, bands materialized into the npz
        payload a GET response carries."""
        import io

        cs = sched.read(tensor_mod.decode_to_coefficients, blob)
        buf = io.BytesIO()
        np.savez(buf, **{f"r{res}_{name}": arr
                         for (res, name), arr in cs.to_host().items()})
        return buf.getvalue()

    def baseline(ids):
        """Decode-then-stack: what a training loader does without the
        batch plane — N per-image tensor reads across the serving
        boundary (each an admitted read returning its npz payload),
        parsed client-side, stacked on host, re-uploaded as the batch
        tensor. The batch path's consumer keeps the sharded device
        arrays instead, so it pays none of this per image."""
        import io

        def parse(payload):
            out = {}
            for name, arr in np.load(io.BytesIO(payload)).items():
                res, band = name[1:].split("_", 1)
                out[(int(res), band)] = arr
            return out

        hosts = [parse(serve_one(blobs[i])) for i in ids]
        return {key: jax.device_put(
                    np.stack([h[key] for h in hosts]))
                for key in hosts[0]}

    sink = Metrics()
    sched.set_metrics_sink(sink)
    # The margin is a few percent of a decode-bound total: min-of-1
    # is inside the noise floor, so impose a local repeats floor.
    repeats = max(repeats, 7)
    per_size = {}
    try:
        for n in sizes:
            ids = [f"img{i}" for i in range(n)]
            recipe = batches_mod.parse_recipe({"ids": ids})
            # Warm compiles on both paths before timing.
            result = sched.submit_batchread(
                batches_mod.assemble_batch, recipe,
                data_for=blobs.get)
            base = baseline(ids)
            # Byte identity: the sharded batch must equal the stacked
            # per-image reads bit for bit.
            host = result.to_host()
            identical = all(
                np.array_equal(host[key], np.asarray(base[key]))
                for key in host)
            if not identical:
                raise AssertionError(
                    f"batch path diverged from decode-then-stack "
                    f"at N={n}")
            # Interleave the reps: this box's wall-clock drifts by
            # tens of percent between runs, so alternating paths puts
            # both mins under the same weather instead of timing one
            # path entirely inside a bad stretch.
            best_batch = best_base = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                result = sched.submit_batchread(
                    batches_mod.assemble_batch, recipe,
                    data_for=blobs.get)
                best_batch = min(best_batch,
                                 time.perf_counter() - t0)
                t0 = time.perf_counter()
                baseline(ids)
                best_base = min(best_base,
                                time.perf_counter() - t0)
            nbytes = result.nbytes
            per_size[str(n)] = {
                "batch_seconds": round(best_batch, 4),
                "baseline_seconds": round(best_base, 4),
                "ratio": round(best_base / best_batch, 3),
                "batches_per_s": round(1.0 / best_batch, 3),
                "mb_per_s": round(nbytes / 1e6 / best_batch, 3),
                "baseline_mb_per_s": round(
                    nbytes / 1e6 / best_base, 3),
                "batch_bytes": int(nbytes),
                "layout": result.layout,
            }
    finally:
        sched.close()

    report = sink.report()
    occ = report.get("values", {}).get("batchread.batch_occupancy", {})
    counters = report.get("counters", {})
    spread = {k.rsplit(".", 1)[1]: v for k, v in counters.items()
              if k.startswith("batchread.device_launches.d")}
    head = per_size[str(n_max)]
    return {
        "value": head["ratio"], "unit": "x vs decode-then-stack",
        "seconds": head["batch_seconds"],
        "image": f"{size}x{size}x3 uint8 lossless L2",
        "byte_identity": True,
        "batch_sizes": per_size,
        "merged_launch_occupancy_max": occ.get("max", 0),
        "merged_launch_occupancy_mean": occ.get("mean", 0),
        "device_launches": counters.get(
            "batchread.device_launches", 0),
        "device_spread": spread,
        "merged_images": counters.get("batchread.merged_images", 0),
        "repeats": repeats,
    }


def config10_tensor_codec(repeats: int) -> dict:
    """Compressed-domain tensor delivery (ISSUE 13), both products.

    (a) coefficient reads: decode_to_coefficients (Tier-1 + dequant,
    device-resident subbands) vs a full pixel decode of the same
    stream — coefficient MB/s and the read speedup from skipping the
    inverse DWT / color transform.
    (b) the tensor codec: encode_tensor/decode_tensor MB/s and the
    compression ratio vs np.savez_compressed on the same array. The
    device-MQ chain is sequential-scan-bound on CPU (the graftcost
    elephant), so the CPU sweep codes a low-plane int8 workload;
    BENCH_TENSOR_FLOAT=1 (or a real accelerator) adds the float32
    checkpoint-style workload. Env: BENCH_COEFF_SIZE,
    BENCH_TENSOR_ELEMS, BENCH_TENSOR_BACKEND (device|replay|host)."""
    import io

    from bucketeer_tpu import tensor as tensor_mod
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.decode import decode
    from bucketeer_tpu.codec.encoder import EncodeParams

    # --- (a) coefficient reads vs full decode --------------------------
    size = _env_int("BENCH_COEFF_SIZE", 256, smoke=96)
    img = synthetic_photo(size)
    params = EncodeParams(lossless=True, levels=4,
                          tile_size=min(128, size))
    data = encoder.encode_jp2(img, 8, params)
    cs = tensor_mod.decode_to_coefficients(data)      # warm compiles
    decode(data)
    best_coeff, cs = _timed(
        lambda: tensor_mod.decode_to_coefficients(data), repeats)
    best_full, _ = _timed(lambda: decode(data), repeats)
    coeff_mb = cs.nbytes / 1e6
    coefficients = {
        "image": f"{size}x{size}x3 uint8 lossless",
        "coefficient_bytes": cs.nbytes,
        "seconds": round(best_coeff, 3),
        "mb_per_s": round(coeff_mb / best_coeff, 3),
        "full_decode_seconds": round(best_full, 3),
        "speedup_vs_full_decode": round(best_full / best_coeff, 3),
        "bands": len(cs.bands),
    }

    # --- (b) the tensor codec ------------------------------------------
    backend = os.environ.get("BENCH_TENSOR_BACKEND", "device")
    n = _env_int("BENCH_TENSOR_ELEMS", 16384, smoke=8192)
    rng = np.random.default_rng(1013)
    workloads = {
        # Quantized-checkpoint-like: low-entropy small-range int8 —
        # few magnitude planes, so the sequential device scans stay
        # affordable on the CPU backend too.
        "int8_quantized": (rng.normal(0.0, 2.0, size=n)
                           .clip(-7, 7).round().astype(np.int8)),
    }
    if os.environ.get("BENCH_TENSOR_FLOAT", "") not in ("", "0"):
        workloads["float32_weights"] = (
            rng.standard_normal(n).astype(np.float32) * 0.02)
    tensors = {}
    for name, arr in workloads.items():
        blob = tensor_mod.encode_tensor(arr, device=backend)  # warm
        best_enc, blob = _timed(
            lambda a=arr: tensor_mod.encode_tensor(a, device=backend),
            repeats)
        best_dec, out = _timed(
            lambda b=blob: tensor_mod.decode_tensor(b), repeats)
        if not np.array_equal(
                out.view((np.uint8, out.dtype.itemsize)),
                arr.view((np.uint8, arr.dtype.itemsize))):
            raise AssertionError(f"{name}: lossy roundtrip")
        buf = io.BytesIO()
        np.savez_compressed(buf, arr=arr)
        mb = arr.nbytes / 1e6
        tensors[name] = {
            "elements": int(arr.size),
            "raw_bytes": int(arr.nbytes),
            "coded_bytes": len(blob),
            "ratio": round(arr.nbytes / len(blob), 3),
            "savez_bytes": buf.getbuffer().nbytes,
            "ratio_vs_savez": round(
                buf.getbuffer().nbytes / len(blob), 3),
            "encode_mb_per_s": round(mb / best_enc, 4),
            "decode_mb_per_s": round(mb / best_dec, 4),
            "backend": backend,
        }

    head = tensors["int8_quantized"]
    return {
        "value": head["encode_mb_per_s"], "unit": "MB/s",
        "seconds": round(head["raw_bytes"] / 1e6
                         / max(head["encode_mb_per_s"], 1e-9), 3),
        "coefficients": coefficients,
        "tensors": tensors,
        "repeats": repeats,
    }


CONFIGS = {
    "1_single_4k_rate3": config1_single_4k,
    "2_batch_2k_lossy": config2_batch_2k,
    "3_lossless_16bit": config3_lossless16,
    "4_sharded_dwt_dryrun": config4_sharded_dryrun,
    "5_mixed_upload_overlap": config5_mixed_overlap,
    "6_decode_roundtrip": config6_decode,
    "7_concurrent_serving": config7_concurrent_serving,
    "8_tile_storm": config8_tile_storm,
    "9_batch_dataplane": config9_batch_dataplane,
    "10_tensor_codec": config10_tensor_codec,
}


def _last_valid_headline() -> dict | None:
    """The most recent recorded headline with a real value, for the
    carry-forward when a run doesn't execute config 1 (BENCH_r06
    recorded only decode configs and emitted headline 0.0, which the
    gate then had nothing to protect). Scans the checked-in BENCH_r*
    records newest-first, then BENCH_REF.json."""
    import glob

    def doc_of(path):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            return None
        try:
            # Whole-file JSON: either a bare bench line or the run
            # driver's wrapper with the line under "parsed" (r01-r05).
            whole = json.loads(text)
            if isinstance(whole, dict):
                if "metric" in whole and "value" in whole:
                    return whole
                parsed = whole.get("parsed")
                if isinstance(parsed, dict) and "value" in parsed:
                    return parsed
        except ValueError:
            pass
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict) and "value" in cand:
                    doc = cand
        return doc

    root = os.path.dirname(os.path.abspath(__file__))
    candidates = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                        reverse=True)
    candidates.append(os.path.join(root, "BENCH_REF.json"))
    for path in candidates:
        doc = doc_of(path)
        if doc and float(doc.get("value") or 0.0) > 0:
            return {"value": float(doc["value"]),
                    "source": os.path.basename(path)}
    return None


def main() -> int:
    from bucketeer_tpu.converters.tpu import (compile_cache_entries,
                                              maybe_enable_compile_cache)

    cache = maybe_enable_compile_cache()     # BUCKETEER_COMPILE_CACHE
    entries_before = cache.get("entries", 0)
    backend = init_backend()
    # CPU (dev mode / fallback) is ~500x off the accelerator: keep the
    # default sweep under ~5 minutes there. Explicit env always wins,
    # and BENCH_SMOKE's own (smaller) scaling takes precedence.
    if backend["platform"] == "cpu" and not SMOKE:
        os.environ.setdefault("BENCH_BATCH_N", "4")
        # Config 7 at accelerator defaults is minutes of CPU encode;
        # shrink the serving load the same way.
        os.environ.setdefault("BENCH_CLIENTS", "4")
        os.environ.setdefault("BENCH_REQS_PER_CLIENT", "2")
        os.environ.setdefault("BENCH_SERVE_SIZE", "512")
    repeats = _env_int(
        "BENCH_REPEATS", 3 if backend["platform"] != "cpu" else 1,
        smoke=1)
    wanted = os.environ.get("BENCH_CONFIGS", "")
    selected = ({k: f for k, f in CONFIGS.items()
                 if k.split("_")[0] in wanted.split(",")} if wanted
                else CONFIGS)

    results: dict = {}
    for name, fn in selected.items():
        try:
            results[name] = fn(repeats)
        except Exception as exc:                    # keep the scoreboard
            if (_backend_unavailable(exc)
                    and _REEXEC_ENV not in os.environ):
                # Backend died at first dispatch (init-time probing
                # passed): restart the whole sweep on CPU rather than
                # reporting rc=1 with zero numbers (BENCH_r05).
                print(f"# backend unavailable during {name}; "
                      "re-exec under JAX_PLATFORMS=cpu",
                      file=sys.stderr)
                _reexec_on_cpu()
            results[name] = {"error": f"{type(exc).__name__}: {exc}"}

    entries_after = compile_cache_entries()
    headline = results.get("1_single_4k_rate3", {})
    value = headline.get("value", 0.0)
    # Headline hygiene: a run that didn't execute (or couldn't finish)
    # config 1 must not publish 0.0 as the number of record — carry the
    # last valid headline forward, flagged stale so the gate skips it.
    headline_stale = False
    headline_from = None
    if not value:
        prev = _last_valid_headline()
        if prev:
            value = prev["value"]
            headline_stale = True
            headline_from = prev["source"]
    print(json.dumps({
        "metric": "lossy_jp2_encode_throughput",
        "value": value,
        "headline_stale": headline_stale,
        "headline_from": headline_from,
        "unit": "MPix/s",
        "vs_baseline": round(value / BASELINE_MPIX_S, 4),
        "platform": backend["platform"],
        "n_devices": backend["n_devices"],
        # True when this run is not on the requested accelerator: either
        # init-time retries fell back, or a dispatch-time backend error
        # re-exec'd the sweep onto CPU.
        "platform_fallback": bool(backend["fallback"]
                                  or os.environ.get(_REEXEC_ENV)),
        # A fallback run is NOT a device measurement: consumers (the CI
        # regression gate, the scoreboard) must treat these numbers as
        # CPU plumbing checks, never as accelerator throughput.
        "device_run_valid": not bool(backend["fallback"]
                                     or os.environ.get(_REEXEC_ENV)),
        "backend": backend,
        # Coarse machine class for the regression gate: wall-clock
        # throughput is only comparable between runs of the same class
        # (hosted-runner vs dev-box variance alone exceeds the gate's
        # loss threshold).
        "machine": {"arch": platform_mod.machine(),
                    "cpu_count": os.cpu_count()},
        "smoke": SMOKE,
        "compile_cache": {
            "enabled": cache["enabled"], "dir": cache["dir"],
            "entries_before": entries_before,
            "entries_after": entries_after,
            # 0 new entries on an enabled cache = every program was a
            # cache hit; anything else counts the misses persisted.
            "misses_persisted": max(0, entries_after - entries_before),
        },
        "configs": results,
    }))
    ok = any("value" in r for r in results.values())
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
