"""Unified retry policy: bounded exponential backoff + full jitter,
per-address circuit breakers, and the dead-letter record.

Replaces the three ad-hoc loops the engine grew from the reference:
the bus's infinite fixed-delay requeue (reference:
AbstractBucketeerVerticle.java:76-96), the S3 uploader's infinite 5xx
retry (reference: S3BucketVerticle.java:185-194), and the batch
converter's hand-rolled ``range(3)`` status-update loop. Every retry
path now draws its delays from one :class:`RetryPolicy` (so a forced
permanent outage ends in a bounded number of attempts, never a retry
storm) and records items that exhaust their budget in a
:class:`DeadLetterLog` visible via ``/metrics`` counters and the
``GET /batch/jobs/{name}`` detail field.

Determinism: jitter comes from a caller-owned ``random.Random`` (the
bus seeds one per instance), so a seeded graftgremlin fault scenario
replays its retry schedule bit-for-bit.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace

_METRICS = None   # optional server.metrics.Metrics sink


def set_metrics_sink(sink) -> None:
    """Install the /metrics registry (server/app.py wires the GLOBAL
    one). One sink serves the whole ingest-robustness layer:
    retry/breaker/dead-letter events here, plus the journal's counters
    (engine/journal.py) and the bus's retry accounting — they import
    :func:`count_metric` instead of growing sinks of their own."""
    global _METRICS
    _METRICS = sink


def count_metric(name: str, n: int = 1) -> None:
    sink = _METRICS
    if sink is not None:
        sink.count(name, n)


_count = count_metric       # internal alias


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded attempts with exponential backoff + full jitter
    (AWS-architecture-blog style: delay = U(0, min(cap, base*mult^k)),
    which decorrelates a thundering herd better than equal jitter)."""

    max_attempts: int = 32
    base_delay: float = 1.0
    max_delay: float = 30.0
    multiplier: float = 2.0

    def delay(self, attempt: int, rng) -> float:
        """Delay before retry number ``attempt`` (0-based). ``rng`` is a
        ``random.Random`` owned by the caller so schedules replay."""
        cap = min(self.max_delay,
                  self.base_delay * self.multiplier ** attempt)
        return rng.uniform(0.0, cap)

    def with_base(self, base_delay: float) -> "RetryPolicy":
        return replace(self, base_delay=base_delay)

    def exhausted(self, attempts: int) -> bool:
        return attempts >= self.max_attempts


# Breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-target circuit breaker: ``threshold`` *consecutive* failures
    trip it open; while open every :meth:`allow` fast-fails (no call is
    attempted against the dead target); after ``reset_s`` it half-opens
    and admits exactly one probe — probe success closes it, probe
    failure re-opens the full ``reset_s`` window.

    Thread-safe (the S3 worker runs on the event loop but records can
    arrive from ``asyncio.to_thread`` helpers); the clock is injectable
    so tests and seeded fault scenarios control time.
    """

    def __init__(self, name: str, threshold: int = 5,
                 reset_s: float = 30.0, clock=time.monotonic) -> None:
        self.name = name
        self.threshold = max(1, threshold)
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.open_count = 0          # lifetime trips, for stats/tests

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state_locked()

    @property
    def is_open(self) -> bool:
        """True while calls would fast-fail (open and not yet due for a
        half-open probe)."""
        with self._lock:
            return (self._effective_state_locked() == OPEN)

    def _effective_state_locked(self) -> str:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.reset_s:
            return HALF_OPEN
        return self._state

    def allow(self) -> bool:
        """May a call proceed now? OPEN -> False (fast-fail); due for
        half-open -> True exactly once (the probe) until it resolves."""
        with self._lock:
            state = self._effective_state_locked()
            if state == CLOSED:
                return True
            if state == OPEN:
                return False
            # HALF_OPEN: one probe at a time
            if self._state == OPEN:           # first arrival past reset_s
                self._state = HALF_OPEN
                self._probe_in_flight = False
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            _count(f"breaker.{self.name}.probes")
            return True

    def release_probe(self) -> None:
        """The admitted half-open probe never reached the target
        (local error, backpressure shed): hand the slot back so the
        next call can probe, recording no outcome. Without this the
        breaker would wedge HALF_OPEN with a phantom probe in flight
        and fast-fail forever."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_in_flight = False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != CLOSED:
                self._state = CLOSED
                self._probe_in_flight = False
                _count(f"breaker.{self.name}.closed")

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # Failed probe: re-open the full window.
                self._state = OPEN
                self._opened_at = self._clock()
                self._probe_in_flight = False
                self.open_count += 1
                _count(f"breaker.{self.name}.reopened")
            elif (self._state == CLOSED
                    and self._consecutive_failures >= self.threshold):
                self._state = OPEN
                self._opened_at = self._clock()
                self.open_count += 1
                _count(f"breaker.{self.name}.opened")

    def time_until_ready(self) -> float:
        """Seconds until the next call may be attempted (0 when closed
        or already due for its half-open probe) — the Retry-After hint."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self.reset_s
                       - (self._clock() - self._opened_at))

    def report(self) -> dict:
        with self._lock:
            return {"state": self._effective_state_locked(),
                    "consecutive_failures": self._consecutive_failures,
                    "open_count": self.open_count}


class BreakerRegistry:
    """Per-address breakers (ISSUE 11 tentpole piece 2). Addresses get a
    breaker only when some component asks for one (``get``); senders use
    ``lookup`` so an address without a wired breaker costs nothing."""

    def __init__(self, threshold: int = 5, reset_s: float = 30.0,
                 clock=time.monotonic) -> None:
        self.threshold = threshold
        self.reset_s = reset_s
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def get(self, address: str, threshold: int | None = None,
            reset_s: float | None = None) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(address)
            if br is None:
                br = CircuitBreaker(
                    address,
                    threshold if threshold is not None else self.threshold,
                    reset_s if reset_s is not None else self.reset_s,
                    self._clock)
                self._breakers[address] = br
            return br

    def lookup(self, address: str) -> CircuitBreaker | None:
        with self._lock:
            return self._breakers.get(address)

    def report(self) -> dict:
        with self._lock:
            return {name: br.report()
                    for name, br in sorted(self._breakers.items())}


@dataclass
class DeadLetterRecord:
    address: str
    image_id: str | None
    job_name: str | None
    attempts: int
    error: str
    at: float = field(default_factory=time.time)

    def to_json(self) -> dict:
        return {"address": self.address, "image-id": self.image_id,
                "job-name": self.job_name, "attempts": self.attempts,
                "error": self.error, "at": round(self.at, 3)}


class DeadLetterLog:
    """Items that exhausted their retry budget, instead of spinning
    forever. Bounded (oldest dropped); surfaced at ``/metrics``
    (``retry.dead_letters`` counter) and in the per-job detail field."""

    def __init__(self, max_records: int = 1000) -> None:
        self.max_records = max_records
        self._records: list[DeadLetterRecord] = []
        self._lock = threading.Lock()

    def record(self, address: str, attempts: int, error: str,
               image_id: str | None = None,
               job_name: str | None = None) -> DeadLetterRecord:
        rec = DeadLetterRecord(address, image_id, job_name, attempts,
                               error)
        with self._lock:
            self._records.append(rec)
            if len(self._records) > self.max_records:
                del self._records[:len(self._records) - self.max_records]
        _count("retry.dead_letters")
        return rec

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def records(self) -> list[DeadLetterRecord]:
        with self._lock:
            return list(self._records)

    def for_job(self, job_name: str) -> list[dict]:
        with self._lock:
            return [r.to_json() for r in self._records
                    if r.job_name == job_name]

    def clear_job(self, job_name: str) -> None:
        """Drop a job's records — called when a *new* run of the same
        job name is accepted, so yesterday's dead letters don't leak
        into today's detail view."""
        with self._lock:
            self._records = [r for r in self._records
                             if r.job_name != job_name]
