"""ctypes bindings for the native Tier-1 coder (t1.cpp).

The production entropy-coding path: batches of code-blocks are encoded in
C++ across a thread pool (cores-1 threads by default, mirroring the
reference's uploader-pool sizing, reference:
verticles/MainVerticle.java:64-77). Falls back transparently to the pure
Python coder when the shared library is missing and cannot be built
(e.g. no compiler in the deployment image) — the analog of the
reference's Kakadu-to-OpenJPEG degradation
(reference: converters/ConverterFactory.java:37-47).

Set ``BUCKETEER_NO_NATIVE=1`` to force the Python path.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path

LOG = logging.getLogger(__name__)

_DIR = Path(__file__).resolve().parent
_LIB_PATH = _DIR / "libbucketeer_t1.so"
_ABI_VERSION = 4     # must match t1_abi_version() in t1.cpp
_lib = None
_tried = False


class NativeABIError(RuntimeError):
    """The loaded libbucketeer_t1.so speaks a different ABI than these
    bindings expect. Calling into it anyway would misread the argument
    layout, so the loader refuses it."""

    def __init__(self, found: int, expected: int, lib_path: Path):
        self.found = found
        self.expected = expected
        self.lib_path = Path(lib_path)
        super().__init__(
            f"{self.lib_path.name}: t1_abi_version() returned {found}, "
            f"these bindings expect {expected} "
            "(the symbol is absent entirely when -1). Remediation: "
            f"delete {self.lib_path} so it is rebuilt from t1.cpp, or "
            "set BUCKETEER_NO_NATIVE=1 to force the pure-Python coder.")


def _check_abi(lib: ctypes.CDLL) -> None:
    """Raise :class:`NativeABIError` unless ``lib`` matches
    ``_ABI_VERSION`` (the single ABI guard; every load path funnels
    through here)."""
    try:
        lib.t1_abi_version.restype = ctypes.c_int32
        found = int(lib.t1_abi_version())
    except AttributeError:
        found = -1
    if found != _ABI_VERSION:
        raise NativeABIError(found, _ABI_VERSION, _LIB_PATH)


def _build(out: Path | None = None) -> bool:
    src = _DIR / "t1.cpp"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
             "-o", str(out or _LIB_PATH), str(src)],
            check=True, capture_output=True, timeout=300)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("BUCKETEER_NO_NATIVE"):
        return None
    src = _DIR / "t1.cpp"
    try:
        stale = (not _LIB_PATH.exists()
                 or _LIB_PATH.stat().st_mtime < src.stat().st_mtime)
    except OSError:
        stale = False        # source pruned from deployment; use the .so
    if stale and not _build():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    # ABI guard: a prebuilt .so from an older tree (deployment images
    # prune t1.cpp, defeating the mtime staleness check) must not be
    # called with a newer argument layout. Rebuild if possible, else
    # fall back to the pure-Python coder.
    try:
        _check_abi(lib)
    except NativeABIError as exc:
        # dlopen dedupes by pathname, so rebuilding in place and
        # re-CDLL'ing _LIB_PATH would hand back the stale mapping (and
        # g++ truncating a currently-mapped .so risks SIGBUS). Build to
        # a distinct path, load that, then rename it over _LIB_PATH
        # (atomic, new inode) so future processes load it directly.
        rebuilt = _LIB_PATH.with_suffix(f".v{_ABI_VERSION}.so")
        if not (src.exists() and _build(rebuilt)):
            LOG.warning("%s; no source to rebuild from — falling back "
                        "to the pure-Python Tier-1 coder", exc)
            return None
        try:
            lib = ctypes.CDLL(str(rebuilt))
            _check_abi(lib)
        except (OSError, NativeABIError) as exc2:
            LOG.warning("%s after rebuild — falling back to the "
                        "pure-Python Tier-1 coder", exc2)
            return None
        try:
            os.replace(rebuilt, _LIB_PATH)
        except OSError:
            LOG.warning("could not move rebuilt %s over %s; the stale "
                        "library remains on disk", rebuilt, _LIB_PATH)
    lib.t1_encode_blocks.restype = ctypes.c_void_p
    lib.t1_encode_blocks.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int]
    lib.t1_encode_packed.restype = ctypes.c_void_p
    lib.t1_encode_packed.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int]
    lib.t1_encode_cxd.restype = ctypes.c_void_p
    lib.t1_encode_cxd.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int]
    lib.t1_block_sizes.restype = None
    lib.t1_block_sizes.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 3
    lib.t1_block_get.restype = None
    lib.t1_block_get.argtypes = [ctypes.c_void_p, ctypes.c_int] + \
        [ctypes.c_void_p] * 5
    lib.t1_result_free.restype = None
    lib.t1_result_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None
