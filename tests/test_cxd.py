"""Device CX/D context modeling (codec/cxd.py) vs the reference coder.

The contract under test: the device stripe scan emits *exactly* the
(context, decision) sequence codec/t1.py feeds its MQEncoder — across
band classes, all three passes, the run-length shortcut, sign coding,
partial blocks and bit-plane floors — so replaying the stream through
the host MQ coder (native t1_encode_cxd or the Python fallback) yields
byte-identical block data, identical truncation points, and
bit-identical distortion values. On top of that, end-to-end encodes
with BUCKETEER_DEVICE_CXD must be byte-identical to the legacy packed
path.
"""
import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bucketeer_tpu import native
from bucketeer_tpu.codec import cxd, encoder, rate as rate_mod, t1_batch
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.server.metrics import Metrics

P_TEST = 5          # one compiled scan shared by every unit trial


@pytest.fixture(scope="module")
def cxd_single():
    return jax.jit(partial(cxd._cxd_single, P_TEST, 0))


def _random_block(rng, h, w, max_bits=P_TEST, density=0.3):
    mags = ((rng.random((h, w)) < density)
            * rng.integers(0, 1 << max_bits, size=(h, w))).astype(
        np.uint32)
    negs = rng.random((h, w)) < 0.5
    return mags, negs


def _run_device(cxd_single, mags, negs, band, floor):
    h, w = mags.shape
    coeffs = np.zeros((64, 64), np.int32)
    coeffs[:h, :w] = mags.astype(np.int64) * np.where(negs, -1, 1)
    nbp = int(mags.max()).bit_length()
    buf, counts, dh, dl, cur = cxd_single(
        jnp.asarray(coeffs), jnp.int32(nbp), jnp.int32(floor),
        jnp.int32(cxd.BAND_CLS[band]), jnp.int32(h), jnp.int32(w))
    return (np.asarray(buf), np.asarray(counts), np.asarray(dh),
            np.asarray(dl), int(cur), nbp)


def test_streams_match_reference_across_bands_and_floors(rng, cxd_single):
    """Property test: device symbol streams, pass boundaries and
    distortion values equal the recording reference for random blocks in
    every band class, with and without floors, including partial blocks
    and blocks with fewer planes than the scan capacity."""
    cases = [(cxd_single, rng, band, floor, hw)
             for band in ("LL", "HL", "LH", "HH")
             for floor, hw in ((0, (64, 64)), (2, (37, 11)))]
    cases.append((cxd_single, rng, "LL", 0, (5, 64)))
    for args in cases:
        _check_one(*args)
    # Fewer coded planes than capacity: plane masking above the MSB.
    mags, negs = _random_block(rng, 16, 16, max_bits=2)
    _check_block(cxd_single, mags, negs, "HH", 0)


def _check_one(cxd_single, rng, band, floor, hw):
    mags, negs = _random_block(rng, *hw)
    mags.flat[0] = (1 << P_TEST) - 1       # pin nbp == P_TEST
    _check_block(cxd_single, mags, negs, band, floor)


def _check_block(cxd_single, mags, negs, band, floor):
    # The packed path truncates magnitude bits below the floor before
    # estimating distortions; mirror that for the reference.
    mags_f = (mags >> floor) << floor
    ref_blk, ref_syms, ref_bounds = cxd.reference_cxd(
        mags_f, negs, band, floor)
    buf, counts, dh, dl, cur, nbp = _run_device(
        cxd_single, mags, negs, band, floor)
    assert cur == len(ref_syms), (band, floor)
    np.testing.assert_array_equal(buf[:cur], ref_syms)
    assert cur <= cxd.max_syms(P_TEST)

    offs, types, planes, nsyms, dists, totals = cxd.pass_tables(
        np.array([nbp], np.int32), np.array([floor], np.int32),
        counts[None], dh[None], dl[None])
    np.testing.assert_array_equal(np.cumsum(nsyms), ref_bounds)
    ref_d = np.array([p.dist_reduction for p in ref_blk.passes])
    np.testing.assert_array_equal(dists, ref_d)   # bit-identical f64

    replayed = cxd.replay_block(buf[:cur], nbp, len(types), types,
                                planes, nsyms, dists)
    assert replayed.data == ref_blk.data
    for got, want in zip(replayed.passes, ref_blk.passes):
        assert got.cum_length == want.cum_length
        assert got.pass_type == want.pass_type
        assert got.bitplane == want.bitplane


def test_eff_group_partitioner():
    """The Mb clamp's launch planner: dead blocks (all-zero, or floored
    away) join no group, live blocks bucket by pow-2 of their realized
    plane depth, and tiny groups merge into the next larger bucket."""
    nbps = np.array([0, 5, 5, 1, 3, 3, 3, 3, 8], np.int32)
    floors = np.array([0, 5, 1, 0, 0, 0, 0, 0, 0], np.int32)
    groups, eff = cxd._eff_groups(nbps, floors)
    np.testing.assert_array_equal(eff, [0, 0, 4, 1, 3, 3, 3, 3, 8])
    by_l = {l: list(i) for l, i in groups}
    covered = sorted(i for idxs in by_l.values() for i in idxs)
    assert covered == [2, 3, 4, 5, 6, 7, 8]     # 0 and 1 are dead
    # eff 1..8 all land in the smallest launch bucket.
    assert by_l == {8: [2, 3, 4, 5, 6, 7, 8]}
    for l_val, idxs in groups:
        assert l_val in cxd.LAUNCH_PLANE_BUCKETS
        assert len(idxs) >= cxd.GROUP_MIN_BLOCKS or l_val == max(by_l)
        assert all(eff[i] <= l_val for i in idxs)
    # A deeper block splits off its own bucket once populated.
    nbps2 = np.array([3, 3, 3, 3, 12, 12, 12, 12], np.int32)
    groups2, eff2 = cxd._eff_groups(nbps2, np.zeros(8, np.int32))
    assert {l: list(i) for l, i in groups2} == \
        {8: [0, 1, 2, 3], 16: [4, 5, 6, 7]}
    # The bucket mapper itself.
    assert [cxd._launch_bucket(e) for e in (1, 4, 5, 9, 17)] == \
        [8, 8, 8, 16, 32]
    with pytest.raises(ValueError):
        cxd._launch_bucket(33)


def test_sparse_mb_clamped_chunk_byte_identical(rng, cxd_single):
    """Mb-clamped sparse cases through the full grouped chunk path:
    all-zero blocks and floored-dead blocks launch nothing, a
    single-significant-coefficient block rides the smallest bucket,
    and every live block replays byte-identical to the reference."""
    n = 6
    blocks = np.zeros((n, 64, 64), np.int32)
    metas = []
    bands = ["LL", "HH", "HL", "LH", "LL", "HH"]
    for i, maxb in enumerate((P_TEST, 1, 2, P_TEST, P_TEST, 3)):
        h = int(rng.integers(1, 65))
        w = int(rng.integers(1, 65))
        mags, negs = _random_block(rng, h, w, max_bits=maxb)
        if i == 1:
            mags[:] = 0
            mags[h // 2, w // 2] = 1        # single significant sample
        if i == 4:
            mags[:] = 0                     # all-zero block
        blocks[i, :h, :w] = mags.astype(np.int64) * np.where(negs, -1, 1)
        metas.append((mags, negs, bands[i], h, w))
    nbps = np.array([int(m.max()).bit_length() for m, *_ in metas],
                    np.int32)
    floors = np.array([0, 0, 0, P_TEST, 0, 1], np.int32)  # 3: dead
    hs = np.array([m[3] for m in metas], np.int32)
    ws = np.array([m[4] for m in metas], np.int32)
    groups, eff = cxd._eff_groups(nbps, floors)
    grouped = {i for _, idxs in groups for i in idxs}
    assert 3 not in grouped and 4 not in grouped    # zero trips
    streams = cxd.run_cxd(jnp.asarray(blocks), nbps, floors, bands,
                          hs, ws, P_TEST, 0)
    got = t1_batch.encode_cxd(streams)
    for i, (mags, negs, band, h, w) in enumerate(metas):
        floor = int(floors[i])
        if nbps[i] <= floor:
            assert got[i].data == b"" and not got[i].passes
            continue
        mags_f = (mags >> floor) << floor
        ref_blk, _, _ = cxd.reference_cxd(mags_f, negs, band, floor)
        assert got[i].data == ref_blk.data, f"block {i}"
        for gp, rp in zip(got[i].passes, ref_blk.passes):
            assert gp.cum_length == rp.cum_length
            assert gp.dist_reduction == rp.dist_reduction


def test_pack6_roundtrip(rng):
    syms = rng.integers(0, 64, size=512).astype(np.uint8)
    packed = np.asarray(cxd.pack6(jnp.asarray(syms[None])))[0]
    assert packed.nbytes == 384                  # ~6 bits/symbol
    np.testing.assert_array_equal(cxd.unpack6(packed, 500), syms[:500])


def test_run_cxd_and_native_replay_match_reference(rng):
    """The full chunk path: run_cxd (device program + pass tables +
    row-granular symbol fetch) then t1_batch.encode_cxd — native thread
    pool when available — equals the reference coder block for block."""
    n = 5
    blocks = np.zeros((n, 64, 64), np.int32)
    metas = []
    for i in range(n):
        h = int(rng.integers(1, 65))
        w = int(rng.integers(1, 65))
        mags, negs = _random_block(rng, h, w)
        if i == 3:
            mags[:] = 0                         # all-zero block
        blocks[i, :h, :w] = mags.astype(np.int64) * np.where(negs, -1, 1)
        metas.append((mags, negs, ["LL", "HL", "LH", "HH", "LL"][i], h, w))
    nbps = np.array([int(m.max()).bit_length() for m, *_ in metas],
                    np.int32)
    floors = np.array([0, 1, 0, 0, 5], np.int32)  # block 4: floor >= nbp
    streams = cxd.run_cxd(jnp.asarray(blocks), nbps, floors,
                          [b for *_, b, _, _ in metas],
                          np.array([m[3] for m in metas], np.int32),
                          np.array([m[4] for m in metas], np.int32),
                          P_TEST, 0)
    got = t1_batch.encode_cxd(streams)
    for i, (mags, negs, band, h, w) in enumerate(metas):
        floor = int(floors[i])
        if nbps[i] <= floor:
            assert got[i].data == b"" and got[i].n_bitplanes == 0
            continue
        mags_f = (mags >> floor) << floor
        ref_blk, _, _ = cxd.reference_cxd(mags_f, negs, band, floor)
        assert got[i].data == ref_blk.data, f"block {i}"
        assert got[i].n_bitplanes == ref_blk.n_bitplanes
        assert len(got[i].passes) == len(ref_blk.passes)
        for gp, rp in zip(got[i].passes, ref_blk.passes):
            assert gp.cum_length == rp.cum_length
            assert gp.dist_reduction == rp.dist_reduction


def test_python_fallback_replay_matches(rng, monkeypatch):
    mags, negs = _random_block(rng, 33, 29)
    blocks = np.zeros((1, 64, 64), np.int32)
    blocks[0, :33, :29] = mags.astype(np.int64) * np.where(negs, -1, 1)
    nbps = np.array([int(mags.max()).bit_length()], np.int32)
    streams = cxd.run_cxd(jnp.asarray(blocks), nbps,
                          np.zeros(1, np.int32), ["HH"],
                          np.array([33], np.int32),
                          np.array([29], np.int32), P_TEST, 0)
    monkeypatch.setattr(native, "load", lambda: None)
    got = t1_batch.encode_cxd(streams)
    ref_blk, _, _ = cxd.reference_cxd(mags, negs, "HH", 0)
    assert got[0].data == ref_blk.data


def test_pallas_kernel_matches_jnp_scan(rng):
    """The Pallas kernel (interpret mode on CPU) and the vmapped scan
    share one scan body; prove their outputs are bit-identical anyway —
    buffer, counts, cursors, distortions. Kept at L=2: interpret mode
    executes every trip through the Python interpreter, so trip count
    is this test's wall clock."""
    from bucketeer_tpu.codec.pallas.cxd_scan import cxd_pallas

    L = 2
    n = 2
    blocks = np.zeros((n, 64, 64), np.int32)
    for i in range(n):
        mags, negs = _random_block(rng, 64, 64, max_bits=L, density=0.2)
        blocks[i] = mags.astype(np.int64) * np.where(negs, -1, 1)
    nbps = np.array([int(np.abs(blocks[i]).max()).bit_length()
                     for i in range(n)], np.int32)
    floors = np.array([0, 1], np.int32)
    cls = np.array([0, 2], np.int32)
    hw = np.full(n, 64, np.int32)
    args = (jnp.int32(0), jnp.asarray(blocks), jnp.asarray(nbps),
            jnp.asarray(floors), jnp.asarray(cls), jnp.asarray(hw),
            jnp.asarray(hw))
    ref = [np.asarray(a)
           for a in jax.jit(cxd._scan_impl(L, False, False))(*args)]
    got = [np.asarray(a) for a in cxd_pallas(L, *args, interpret=True)]
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)


def test_e2e_lossless_byte_identical(rng):
    img = _photo(rng, 64, 64)
    params = EncodeParams(lossless=True, levels=2)
    legacy = encoder.encode_jp2(
        img, 8, dataclasses.replace(params, device_cxd=False))
    split = encoder.encode_jp2(
        img, 8, dataclasses.replace(params, device_cxd=True))
    assert legacy == split


def test_e2e_rate_target_byte_identical_env_flag(rng, monkeypatch):
    """Rate-targeted lossy (floors, PCRD, margin retries) through the
    env flag: distortion parity must hold or layers shift."""
    img = _photo(rng, 64, 64, comps=3)
    params = EncodeParams(lossless=False, levels=2, rate=1.5,
                          n_layers=3, base_delta=0.5)
    monkeypatch.delenv("BUCKETEER_DEVICE_CXD", raising=False)
    legacy = encoder.encode_jp2(img, 8, params)
    monkeypatch.setenv("BUCKETEER_DEVICE_CXD", "1")
    sink = Metrics()
    encoder.set_metrics_sink(sink)
    try:
        split = encoder.encode_jp2(img, 8, params)
    finally:
        encoder.set_metrics_sink(None)
    assert legacy == split
    report = sink.report()
    assert "encode.cxd_device" in report["stages"]
    mq = report["stages"]["encode.mq_replay"]
    assert mq["items"] > 0                      # symbols/s observable
    assert report["counters"]["encode.cxd_symbols"] == mq["items"]


def _photo(rng, h, w, comps=1):
    y, x = np.mgrid[0:h, 0:w]
    base = 120 + 80 * np.sin(x / 17.0) * np.cos(y / 13.0)
    img = base[..., None] + rng.normal(0, 8, (h, w, comps))
    img = np.clip(img, 0, 255).astype(np.uint8)
    return img[..., 0] if comps == 1 else img


# --- floor estimator regression (ADVICE r5 #4) --------------------------

def test_estimate_floors_never_zeroes_live_block():
    """A block whose top plane clears the loose slope threshold must
    keep at least its MSB plane instead of being dropped outright."""
    n, P = 3, 4
    nbps = np.array([4, 4, 4], np.int32)
    newsig = np.zeros((n, P), np.int64)
    sigd = np.zeros((n, P), np.float64)
    newsig[:, 3] = 8
    # Block 0 dominates (sets the threshold); block 1's top plane is
    # ~8x cheaper (within the 16x slack); block 2 is noise, far below.
    sigd[0, :] = [1.0, 10.0, 100.0, 1e6]
    sigd[1, 3] = 1e6 / 8.0
    sigd[2, 3] = 1e-3
    refd = np.zeros((n, P), np.float64)
    weights = np.ones(n)
    n_samples = np.full(n, 4096)
    floors, lam = rate_mod.estimate_floors(
        nbps, newsig, sigd, refd, weights, n_samples,
        target_bytes=20.0, margin=1.0)
    assert lam > 0
    assert floors[0] < nbps[0]
    assert floors[1] == nbps[1] - 1, (
        f"live block fully zeroed: floors={floors} lam={lam}")
    assert floors[2] == nbps[2]


def test_cut_slope_detects_floor_violation():
    """cut_slope returns the realized PCRD cut; a cut far below the
    floor threshold is the retry trigger."""
    from bucketeer_tpu.codec import t1

    blocks = []
    for lens, dists in (((10, 20), (100.0, 110.0)),
                        ((8, 30), (80.0, 84.0))):
        blk = t1.CodedBlock(b"x" * lens[-1], 5)
        blk.passes = [t1.PassInfo(2, 4, lens[0], dists[0]),
                      t1.PassInfo(2, 3, lens[1], dists[1] - dists[0])]
        blocks.append(blk)
    tight = rate_mod.cut_slope(blocks, [1.0, 1.0], 12.0)
    loose = rate_mod.cut_slope(blocks, [1.0, 1.0], 1000.0)
    assert tight > loose >= 0.0
