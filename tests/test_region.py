"""Random-access region decode: bit-exact-crop parity across the
coding-option matrix, the only-intersecting-blocks invariant (metrics
backed), the stream index (PLT and walk builds, indexed == sequential),
and the typed rejection of malformed region parameters.
"""
import dataclasses

import numpy as np
import pytest

from bucketeer_tpu.codec import encoder
from bucketeer_tpu.codec.decode import (InvalidParam, build_index, decode,
                                        set_metrics_sink)
from bucketeer_tpu.codec.decode import index as sindex
from bucketeer_tpu.codec.decode import parser
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.server.metrics import Metrics


def _img(rng, h, w, comps=3, depth=8):
    hi = (1 << depth) - 1
    dtype = np.uint8 if depth <= 8 else np.uint16
    shape = (h, w) if comps == 1 else (h, w, comps)
    return rng.integers(0, hi + 1, shape, dtype=dtype)


REGIONS = [(0, 0, 33, 33), (17, 9, 40, 23), (31, 37, 9, 50),
           (60, 60, 500, 500)]


@pytest.mark.parametrize("comps,depth,lossless,tile,levels", [
    (3, 8, True, 64, 3),          # RGB lossless, multi-tile
    (3, 8, False, 64, 3),         # RGB lossy 9/7, multi-tile
    (1, 8, True, None, 3),        # grayscale single tile
    (1, 16, True, 96, 2),         # 16-bit, straddle-96 banding
    (3, 8, False, None, 4),       # lossy single tile, deeper pyramid
])
def test_region_bit_exact_vs_full_crop(rng, comps, depth, lossless,
                                       tile, levels):
    img = _img(rng, 80, 96, comps, depth)
    params = EncodeParams(lossless=lossless, levels=levels,
                          tile_size=tile, base_delta=2.0)
    data = encoder.encode_jp2(img, depth, params)
    full = decode(data)
    for region in REGIONS:
        got = decode(data, region=region)
        x, y, w, h = region
        want = full[y:min(y + h, 80), x:min(x + w, 96)]
        assert got.shape == want.shape
        assert np.array_equal(got, want), (region, lossless, tile)


@pytest.mark.parametrize("reduce", [0, 1, 2])
def test_region_with_reduce_matches_reduced_crop(rng, reduce):
    img = _img(rng, 80, 96)
    params = EncodeParams(lossless=True, levels=3, tile_size=64)
    data = encoder.encode_jp2(img, 8, params)
    full = decode(data, reduce=reduce)
    s = 1 << reduce
    for region in [(17, 9, 40, 23), (64, 64, 48, 32)]:
        x, y, w, h = region
        got = decode(data, region=region, reduce=reduce)
        want = full[y // s:-(-min(y + h, 80) // s),
                    x // s:-(-min(x + w, 96) // s)]
        assert np.array_equal(got, want), (region, reduce)


def test_region_with_layers_matches_layered_crop(rng):
    img = _img(rng, 96, 96)
    params = EncodeParams(lossless=False, levels=3, tile_size=96,
                          n_layers=4, base_delta=2.0, rate=2.0)
    data = encoder.encode_jp2(img, 8, params)
    for layers in (1, 2, None):
        full = decode(data, layers=layers)
        got = decode(data, region=(10, 20, 50, 40), layers=layers)
        assert np.array_equal(got, full[20:60, 10:60])


def test_region_kakadu_recipe_all_tiles(rng):
    """The reference recipe end to end (RPCL, SOP/EPH/PLT, R
    tile-parts, 6 layers): every aligned tile of a multi-tile lossy
    stream reconstructs bit-exactly through the region path."""
    img = _img(rng, 128, 128)
    params = dataclasses.replace(
        EncodeParams.kakadu_recipe(lossless=False, rate=3.0),
        tile_size=64, levels=3)
    data = encoder.encode_jp2(img, 8, params)
    full = decode(data)
    for y in range(0, 128, 64):
        for x in range(0, 128, 64):
            got = decode(data, region=(x, y, 64, 64))
            assert np.array_equal(got, full[y:y + 64, x:x + 64]), (x, y)


# --- only intersecting code-blocks run Tier-1 -------------------------

def test_small_region_decodes_under_10pct_of_blocks(rng):
    """The acceptance invariant at scale: a 96² window of a 2048² image
    MQ-decodes <10% of the stream's code-blocks (metrics-backed via the
    decode.blocks counter; the full count comes from the Tier-2 parse,
    no full decode needed)."""
    img = _img(rng, 2048, 2048, comps=1)
    params = EncodeParams(lossless=False, levels=6, tile_size=None,
                          base_delta=2.0, rate=1.0)
    data = encoder.encode_jp2(img, 8, params)
    ps = parser.parse(data)
    total_blocks = sum(
        len(band.blocks)
        for tile in ps.tiles
        for resolutions in tile.comp_res
        for bands in resolutions
        for band in bands)
    sink = Metrics()
    set_metrics_sink(sink)
    try:
        decode(data, region=(0, 0, 96, 96))
    finally:
        set_metrics_sink(None)
    counters = sink.report()["counters"]
    region_blocks = counters["decode.region_blocks"]
    assert counters["decode.blocks"] == region_blocks
    assert region_blocks < 0.10 * total_blocks, (
        region_blocks, total_blocks)


def test_region_block_counter_scales_with_window(rng):
    """Fast-size version of the invariant: the 64²-of-512² region
    touches a small fraction of the blocks and strictly fewer than the
    full-window region (the counter is the one the acceptance test and
    dashboards read)."""
    img = _img(rng, 512, 512, comps=1)
    params = EncodeParams(lossless=False, levels=4, tile_size=None,
                          base_delta=2.0, rate=1.0)
    data = encoder.encode_jp2(img, 8, params)
    ps = parser.parse(data)
    total_blocks = sum(
        len(band.blocks)
        for tile in ps.tiles
        for resolutions in tile.comp_res
        for bands in resolutions
        for band in bands)

    def blocks_for(region):
        sink = Metrics()
        set_metrics_sink(sink)
        try:
            decode(data, region=region)
        finally:
            set_metrics_sink(None)
        return sink.report()["counters"]["decode.region_blocks"]

    small = blocks_for((0, 0, 64, 64))
    big = blocks_for((0, 0, 512, 512))
    assert big == total_blocks        # full window == every block
    assert small < 0.45 * total_blocks
    assert small < big


def test_indexed_region_skips_nonintersecting_packets(rng):
    img = _img(rng, 128, 128)
    params = dataclasses.replace(
        EncodeParams.kakadu_recipe(lossless=True), tile_size=64,
        levels=3)
    data = encoder.encode_jp2(img, 8, params)
    idx = build_index(data)
    sink = Metrics()
    set_metrics_sink(sink)
    try:
        decode(data, region=(0, 0, 32, 32), index=idx)
    finally:
        set_metrics_sink(None)
    rep = sink.report()
    counters = rep["counters"]
    # Three of four tiles contribute nothing; their packets are never
    # header-parsed, let alone body-read.
    assert counters["decode.packets_skipped"] > idx.n_packets / 2
    parsed = rep["stages"]["decode.t2_parse"]["items"]
    assert parsed + counters["decode.packets_skipped"] == idx.n_packets


# --- the stream index -------------------------------------------------

def test_plt_and_walk_index_agree(rng):
    """The PLT arithmetic and the tag-tree walk must land on identical
    packet offsets — same stream, two build paths."""
    img = _img(rng, 96, 96)
    params = dataclasses.replace(
        EncodeParams.kakadu_recipe(lossless=True), tile_size=64,
        levels=3)
    data = encoder.encode_jp2(img, 8, params)
    idx_plt = build_index(data)
    assert idx_plt.source == "plt"
    ps = parser.parse(bytes(data), collect_index=True)
    assert idx_plt.packets == ps.packet_index
    assert idx_plt.tile_spans == ps.tile_spans


def test_walk_index_used_without_plt(rng):
    img = _img(rng, 80, 80)
    params = EncodeParams(lossless=True, levels=3, tile_size=80)
    data = encoder.encode_jp2(img, 8, params)
    idx = build_index(data)
    assert idx.source == "walk"
    full = decode(data)
    got = decode(data, region=(5, 5, 40, 40), index=idx)
    assert np.array_equal(got, full[5:45, 5:45])


def test_out_of_order_zplt_falls_back_to_walk(rng):
    """T.800 lets PLT segments be stored out of Zplt order; naive
    concatenation would permute the offsets without tripping the
    count/sum consistency checks. A non-sequential Zplt must send the
    build to the walk path, not produce a wrong index."""
    img = _img(rng, 96, 96)
    params = dataclasses.replace(
        EncodeParams.kakadu_recipe(lossless=True), tile_size=64,
        levels=3)
    data = bytearray(encoder.encode_jp2(img, 8, params))
    pos = bytes(data).find(b"\xff\x58")      # first PLT marker
    assert pos > 0
    assert data[pos + 4] == 0                # Zplt of the first segment
    data[pos + 4] = 7                        # claim it is segment 7
    idx = build_index(bytes(data))
    assert idx.source == "walk"
    full = decode(bytes(data))
    got = decode(bytes(data), region=(5, 5, 40, 40), index=idx)
    assert np.array_equal(got, full[5:45, 5:45])


@pytest.mark.parametrize("progression", [0, 1, 2, 3, 4])
def test_indexed_decode_matches_sequential_all_progressions(
        rng, progression):
    img = _img(rng, 80, 80)
    params = EncodeParams(lossless=True, levels=2, tile_size=80,
                          n_layers=2, progression=progression,
                          gen_plt=True)
    data = encoder.encode_jp2(img, 8, params)
    idx = build_index(data)
    full = decode(data)
    for region in [(0, 0, 30, 30), (41, 33, 39, 47)]:
        x, y, w, h = region
        a = decode(data, region=region)
        b = decode(data, region=region, index=idx)
        assert np.array_equal(a, full[y:y + h, x:x + w])
        assert np.array_equal(a, b)


def test_index_nbytes_is_small(rng):
    img = _img(rng, 96, 96)
    params = dataclasses.replace(
        EncodeParams.kakadu_recipe(lossless=True), tile_size=64,
        levels=3)
    data = encoder.encode_jp2(img, 8, params)
    idx = build_index(data)
    assert idx.nbytes < max(4 * len(data), 1 << 20)
    assert idx.n_packets == sum(len(v) for v in idx.packets.values())


def test_skeleton_carries_stream_parameters(rng):
    img = _img(rng, 80, 80)
    params = EncodeParams(lossless=True, levels=2, tile_size=80)
    data = encoder.encode_jp2(img, 8, params)
    idx = build_index(data)
    sk = sindex.skeleton(idx)
    assert (sk.width, sk.height) == (80, 80)
    assert sk.levels == 2 and sk.reversible
    assert sk.tiles == []


# --- malformed region parameters --------------------------------------

@pytest.mark.parametrize("region", [
    (-1, 0, 10, 10),              # negative origin
    (0, -3, 10, 10),
    (200, 0, 10, 10),             # origin beyond width
    (0, 200, 10, 10),             # origin beyond height
    (0, 0, 0, 10),                # zero extent
    (0, 0, 10, 0),
    (0, 0, -5, 10),               # negative extent
    ("a", 0, 10, 10),             # non-integer
    (1.5, 0, 10, 10),             # non-integral float
    (0, 0, 10),                   # wrong arity
    (None, None, None, None),
])
def test_bad_region_raises_invalid_param(rng, region):
    img = _img(rng, 96, 96, comps=1)
    data = encoder.encode_jp2(
        img, 8, EncodeParams(lossless=True, levels=2, tile_size=96))
    with pytest.raises(InvalidParam):
        decode(data, region=region)


def test_region_beyond_levels_reduce_raises(rng):
    img = _img(rng, 64, 64, comps=1)
    data = encoder.encode_jp2(
        img, 8, EncodeParams(lossless=True, levels=2, tile_size=64))
    with pytest.raises(InvalidParam):
        decode(data, region=(0, 0, 8, 8), reduce=5)
    idx = build_index(data)
    with pytest.raises(InvalidParam):
        decode(data, region=(0, 0, 8, 8), reduce=5, index=idx)
