"""Per-request trace export in Chrome-trace (Perfetto) JSON.

``chrome_trace(recorder, request_id)`` renders every span of one
request — plus any *linked* span, i.e. the merged device launch that
batched this request's chunks with others — as complete events
(``ph: "X"``) on one process, one track per thread. The output loads
directly in ``chrome://tracing`` / https://ui.perfetto.dev; tests pin
the structural contract (tests/test_obs.py) so the endpoint can't
drift into something the viewers reject.
"""
from __future__ import annotations


def spans_for(recorder, request_id) -> list:
    return recorder.spans_for(request_id)


def chrome_trace(recorder, request_id) -> dict:
    """Chrome-trace document for one request id. Empty ``traceEvents``
    means the rings hold nothing for that id (expired or unknown)."""
    rid = str(request_id)
    spans = recorder.spans_for(rid)
    events: list = []
    tids: dict = {}
    base = min((s["t0"] for s in spans), default=0.0)
    for s in spans:
        tids.setdefault(s["thread"], len(tids) + 1)
    for thread, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": thread},
        })
    for s in spans:
        args = dict(s["attrs"])
        args["span_id"] = s["span_id"]
        if s["parent_id"] is not None:
            args["parent_id"] = s["parent_id"]
        if s["trace_id"] is not None:
            args["request_id"] = s["trace_id"]
        if s["links"]:
            args["links"] = [list(link) for link in s["links"]]
        if s["status"] != "ok":
            args["status"] = s["status"]
        events.append({
            "name": s["name"],
            "cat": "graftscope",
            "ph": "X",
            "pid": 1,
            "tid": tids[s["thread"]],
            "ts": round((s["t0"] - base) * 1e6, 3),
            "dur": round((s["dur"] or 0.0) * 1e6, 3),
            "args": args,
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"request_id": rid, "spans": len(spans)},
    }
