"""Compressed-domain tensor delivery (ROADMAP item 4): the second
workload the Tier-1 kernels serve.

Two products:

- :func:`decode_to_coefficients` — stop the image decode after Tier-1
  + dequantization and return device-resident per-subband coefficient
  tensors (tensor/coeffs.py), composable with the PR 6 StreamIndex for
  sharded random-access region reads;
- the general bit-plane tensor codec — :func:`encode_tensor` /
  :func:`decode_tensor` / :func:`truncate_tensor` route arbitrary
  int/float tensors through the block partitioner, CX/D scan and
  device MQ coder into a self-describing progressive container
  (tensor/codec.py, tensor/container.py, tensor/planes.py).
"""
from .codec import (decode_tensor, encode_tensor, set_metrics_sink,
                    tensor_services, tensor_stats, truncate_tensor)
from .coeffs import (CoefficientSet, coeff_services,
                     decode_to_coefficients)

__all__ = ["encode_tensor", "decode_tensor", "truncate_tensor",
           "tensor_stats", "tensor_services", "coeff_services",
           "set_metrics_sink", "decode_to_coefficients",
           "CoefficientSet"]
