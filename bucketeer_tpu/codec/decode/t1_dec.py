"""EBCOT Tier-1 decode (T.800 Annex D, decode direction).

The exact inverse of ``t1.encode_block``: the MQ decoder (codec/mq.py,
Annex C.3) regenerates the CX/D decision stream while the same
significance-propagation / magnitude-refinement / cleanup context
modeling that produced it replays in lockstep — context modeling *is*
the decoder's address generator, so the two halves cannot be separated
the way the encode side's device-CX/D split separates them.

Decoded samples are returned as signed "half-magnitude" integers
``hval``: for a sample whose lowest decoded bit-plane is ``p`` with
decoded magnitude bits ``m`` (in units of ``2^p``),

    |hval| = (2*m + 1) << p        (i.e. 2 * (m + 0.5) * 2^p)

— the standard mid-point reconstruction carried in doubled units so it
stays integer-exact. A fully decoded lossless sample ends at p=0 with
``|hval| = 2*mag + 1``, so the device inverse recovers the exact
coefficient as ``|hval| >> 1``; a truncated (quality-layer) decode keeps
the same half-step midpoint OpenJPEG reconstructs, which is what makes
the lossy differential tests line up.

Hot-loop engineering: flat Python lists (cheaper scalar indexing than
numpy), incremental neighbor-significance counters updated only on the
rare became-significant events, and context tables flattened to 1-D.
Code-blocks are independent; ``decode_blocks`` is the batch entry.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

from ..mq import CTX_RL, CTX_UNIFORM, MQDecoder
from ..t1 import _SC, _ZC_HH, _ZC_LL_LH
from .errors import DecodeError

_services = threading.local()


@contextlib.contextmanager
def decode_services(check=None):
    """Install a per-thread hook polled between code-blocks in
    :func:`decode_blocks` — the decode-side mirror of the encoder's
    ``pipeline_services`` seam. The scheduler uses it to enforce read
    deadlines mid-decode instead of only while queued."""
    prev = getattr(_services, "check", None)
    _services.check = check
    try:
        yield
    finally:
        _services.check = prev


def poll() -> None:
    """Run this thread's installed check (deadline enforcement) — a
    no-op when none is installed. For code on the admitted read path
    that waits outside :func:`decode_blocks` (e.g. single-flight index
    waiters) and must still honor the request deadline."""
    check = getattr(_services, "check", None)
    if check is not None:
        check()


def _flat_zc(table, swap_hv: bool) -> list:
    """(3,3,5) context table -> flat [sh*15 + sv*5 + sd] list, with the
    H/V role swap applied for HL bands at build time."""
    out = [0] * 45
    for sh in range(3):
        for sv in range(3):
            for sd in range(5):
                shh, svv = (sv, sh) if swap_hv else (sh, sv)
                out[sh * 15 + sv * 5 + sd] = int(table[shh, svv, sd])
    return out


_ZC_FLAT = {
    "LL": _flat_zc(_ZC_LL_LH, False),
    "LH": _flat_zc(_ZC_LL_LH, False),
    "HL": _flat_zc(_ZC_LL_LH, True),
    "HH": _flat_zc(_ZC_HH, False),
}

# Sign-coding (ctx, xor) flattened to [(h+1)*3 + (v+1)].
_SC_FLAT = [_SC[(h, v)] for h in (-1, 0, 1) for v in (-1, 0, 1)]


def max_passes(nbps: int) -> int:
    """Pass-count ceiling for a block with ``nbps`` coded bit-planes:
    one cleanup for the MSB plane, three passes per lower plane."""
    return max(0, 3 * nbps - 2)


def decode_block(data: bytes, nbps: int, npasses: int, band: str,
                 h: int, w: int) -> tuple:
    """Decode one code-block's pass stream.

    Returns (hvals int32 (h, w) signed half-magnitudes, n_decisions).
    Raises DecodeError for pass/plane counts no conforming encoder can
    emit (the packet header is attacker-controlled input).
    """
    if nbps <= 0 or npasses <= 0:
        return np.zeros((h, w), dtype=np.int32), 0
    if nbps > 30:
        raise DecodeError(f"{nbps} bit-planes exceeds the 30-plane cap")
    if npasses > max_passes(nbps):
        raise DecodeError(
            f"{npasses} passes exceeds the {max_passes(nbps)} possible "
            f"for {nbps} bit-planes")

    mq = MQDecoder(bytes(data))
    decode = mq.decode
    zc = _ZC_FLAT[band]
    size = h * w
    sigma = [0] * size
    pi = [0] * size
    refined = [0] * size
    nb_h = [0] * size        # significant horizontal neighbors
    nb_v = [0] * size
    nb_d = [0] * size
    habs = [0] * size        # |hval| in doubled units
    neg = [0] * size
    n_dec = 0

    def set_sig(i: int, y: int, x: int) -> None:
        """Mark (y, x) significant and bump its neighbors' counters."""
        sigma[i] = 1
        if x > 0:
            nb_h[i - 1] += 1
            if y > 0:
                nb_d[i - 1 - w] += 1
            if y < h - 1:
                nb_d[i - 1 + w] += 1
        if x < w - 1:
            nb_h[i + 1] += 1
            if y > 0:
                nb_d[i + 1 - w] += 1
            if y < h - 1:
                nb_d[i + 1 + w] += 1
        if y > 0:
            nb_v[i - w] += 1
        if y < h - 1:
            nb_v[i + w] += 1

    def decode_sign(i: int, y: int, x: int) -> int:
        hc = vc = 0
        if x > 0 and sigma[i - 1]:
            hc += -1 if neg[i - 1] else 1
        if x < w - 1 and sigma[i + 1]:
            hc += -1 if neg[i + 1] else 1
        if y > 0 and sigma[i - w]:
            vc += -1 if neg[i - w] else 1
        if y < h - 1 and sigma[i + w]:
            vc += -1 if neg[i + w] else 1
        hc = -1 if hc < -1 else (1 if hc > 1 else hc)
        vc = -1 if vc < -1 else (1 if vc > 1 else vc)
        ctx, xor = _SC_FLAT[(hc + 1) * 3 + (vc + 1)]
        return decode(ctx) ^ xor

    done = [npasses]

    def tick() -> bool:
        done[0] -= 1
        return done[0] == 0

    p = nbps - 1
    first_plane = True
    while p >= 0:
        bit3 = 3 << p
        bit1 = 1 << p

        if not first_plane:
            # Pass 1: significance propagation
            for y0 in range(0, h, 4):
                ymax = y0 + 4 if y0 + 4 < h else h
                for x in range(w):
                    i = y0 * w + x
                    for y in range(y0, ymax):
                        if not sigma[i] and (nb_h[i] or nb_v[i]
                                             or nb_d[i]):
                            ctx = zc[nb_h[i] * 15 + nb_v[i] * 5
                                     + nb_d[i]]
                            n_dec += 1
                            pi[i] = 1
                            if decode(ctx):
                                n_dec += 1
                                neg[i] = decode_sign(i, y, x)
                                set_sig(i, y, x)
                                habs[i] = bit3
                        i += w
            if tick():
                break

            # Pass 2: magnitude refinement
            for y0 in range(0, h, 4):
                ymax = y0 + 4 if y0 + 4 < h else h
                for x in range(w):
                    i = y0 * w + x
                    for y in range(y0, ymax):
                        if sigma[i] and not pi[i]:
                            if refined[i]:
                                ctx = 16
                            elif nb_h[i] or nb_v[i] or nb_d[i]:
                                ctx = 15
                            else:
                                ctx = 14
                            n_dec += 1
                            if decode(ctx):
                                habs[i] += bit1
                            else:
                                habs[i] -= bit1
                            refined[i] = 1
                        i += w
            if tick():
                break

        # Pass 3: cleanup (with the run-length shortcut)
        for y0 in range(0, h, 4):
            ymax = y0 + 4 if y0 + 4 < h else h
            for x in range(w):
                i0 = y0 * w + x
                y = y0
                if y0 + 3 < h:
                    rl = True
                    i = i0
                    for _ in range(4):
                        if (sigma[i] or pi[i] or nb_h[i] or nb_v[i]
                                or nb_d[i]):
                            rl = False
                            break
                        i += w
                    if rl:
                        n_dec += 1
                        if not decode(CTX_RL):
                            continue
                        n_dec += 2
                        k = (decode(CTX_UNIFORM) << 1) | decode(
                            CTX_UNIFORM)
                        yk = y0 + k
                        ik = i0 + k * w
                        n_dec += 1
                        neg[ik] = decode_sign(ik, yk, x)
                        set_sig(ik, yk, x)
                        habs[ik] = bit3
                        y = yk + 1
                i = i0 + (y - y0) * w
                for yy in range(y, ymax):
                    if not sigma[i] and not pi[i]:
                        ctx = zc[nb_h[i] * 15 + nb_v[i] * 5 + nb_d[i]]
                        n_dec += 1
                        if decode(ctx):
                            n_dec += 1
                            neg[i] = decode_sign(i, yy, x)
                            set_sig(i, yy, x)
                            habs[i] = bit3
                    i += w
        if tick():
            break
        for i in range(size):
            pi[i] = 0
        first_plane = False
        p -= 1

    hv = np.array(habs, dtype=np.int64).reshape(h, w)
    if hv.size and int(hv.max()) >= (1 << 31):
        raise DecodeError("decoded magnitude overflows int32")
    hv = hv.astype(np.int32)
    hv[np.array(neg, dtype=bool).reshape(h, w)] *= -1
    return hv, n_dec


def decode_blocks(specs: list) -> tuple:
    """Batch entry: specs [(data, nbps, npasses, band, h, w)] ->
    ([hvals arrays], total decisions). Blocks are independent (the same
    property the encode side's thread pool exploits); kept sequential
    here — the pure-Python MQ loop is GIL-bound either way."""
    out = []
    total = 0
    check = getattr(_services, "check", None)
    for data, nbps, npasses, band, h, w in specs:
        if check is not None:
            check()
        hv, n = decode_block(data, nbps, npasses, band, h, w)
        out.append(hv)
        total += n
    return out, total
