"""The general bit-plane tensor codec: arbitrary int/float tensors
through the image pipeline's own Tier-1 kernels.

``encode_tensor`` maps a tensor to 16-bit signed limb planes
(tensor/planes.py), carves them into the same 64x64 code-blocks the
image front-end uses, and routes them through the device CX/D
context-modeling scan chained into the device MQ arithmetic coder
(codec/cxd.py, the ``BUCKETEER_DEVICE_MQ`` machinery of PR 9) — the
host never touches a symbol; it assembles finished byte segments into
the self-describing ``BTT1`` container (tensor/container.py). This is
the "RD-optimized trit-plane latent coding" shape from PAPERS.md
applied to our binary planes: checkpoint/activation tensors become
progressive bit-plane streams truncatable at any plane boundary.

Three backends share one output, byte for byte:

- ``device`` (default): CX/D scan -> MQ scan, both on device
  (cxd.run_device_mq);
- ``replay``: device CX/D scan, host MQ replay (cxd.run_cxd +
  t1_batch.encode_cxd) — the mode the byte-identity contract names;
- ``host``: the pure-host reference coder (t1.encode_block), no device
  at all — the oracle small tests compare the other two against
  (transitively byte-identical by the PR 3/PR 9 parity suites).

Decoding is host Tier-1 (codec/decode/t1_dec.py — the MQ state machine
is inherently serial), then the inverse plane mapping. Lossless for
every supported dtype, including IEEE NaN payloads and negative zeros
(an explicit escape list; see tensor/planes.py).

Rate control: every block's plane-boundary truncation points (the
``rate.truncation_lengths`` rule, bytes-at-boundary + 4 capped at the
stream) are recorded in the container, so :func:`truncate_tensor` cuts
an existing blob to ``planes=`` (keep the top-k absolute payload
planes) or ``rate=`` (byte budget, deepest global plane cut that fits)
by pure byte slicing — no recode, the trit-plane paper's progressive
property. ``encode_tensor(planes=k)`` instead floors the planes at
encode time, so the skipped planes cost no coding work at all.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from functools import lru_cache

import numpy as np

from ..analysis import graftcost, retrace
from ..codec import t1, t1_batch
from ..codec import cxd as cxd_mod
from ..codec.decode import t1_dec
from ..codec.decode.errors import DecodeError
from ..codec.pipeline import _bucket, donate_argnums_if_supported
from . import container
from . import planes as _planes

BLOCK = 64
BLOCK_SAMPLES = container.BLOCK_SAMPLES

# Blocks per device chunk: bounds the HBM symbol buffer
# (N x max_syms(16) ~ 100 KB/block) while keeping the vmapped scan wide.
DEFAULT_CHUNK_BLOCKS = 64

# Every tensor block codes with the LL context tables: there is no
# subband orientation to exploit in a generic tensor, and one fixed
# class keeps device and host paths trivially in agreement.
BAND = "LL"

_metrics_sink = None


def set_metrics_sink(sink) -> None:
    """Install a metrics sink with ``record``/``count``; None disables
    (the server wires server.metrics.GLOBAL here, same seam as the
    encoder's)."""
    global _metrics_sink
    _metrics_sink = sink


_services = threading.local()


@contextlib.contextmanager
def tensor_services(check=None, launch=None):
    """Per-thread scheduler services — the tensor-codec mirror of the
    encoder's ``pipeline_services`` and the decoder's
    ``decode_services``. ``check`` is the deadline hook polled between
    chunks/blocks; ``launch`` (``callable(rows, floors, backend) ->
    (blocks, n_syms, device_seconds)``) routes device-backend chunks
    through the scheduler's pool so compatible chunks from concurrent
    tensor jobs merge into one launch. The scheduler installs both for
    ``kind="tensor"`` jobs."""
    prev = (getattr(_services, "check", None),
            getattr(_services, "launch", None))
    _services.check = check
    _services.launch = launch
    try:
        yield
    finally:
        _services.check, _services.launch = prev


def _poll() -> None:
    check = getattr(_services, "check", None)
    if check is not None:
        check()


# --- the device block packer ---------------------------------------------

def pack_program():
    """(traceable fn, device donate_argnums) for the tensor block
    packer — audit seam (analysis/deviceaudit.py). One flat int32 limb
    buffer becomes the (N, 64, 64) block batch the CX/D scan consumes
    (it stays in HBM) plus per-block magnitude maxima (the only small
    host fetch: nbps drive the pass tables). The donate spec is empty
    by verified fact: although the block output is a pure reshape of
    the input, no output matches the flat (N*4096,) aval, so XLA drops
    the alias — the audit's forced probe proves ``tf.aliasing_output``
    never appears."""
    import jax.numpy as jnp

    def body(flat):
        blocks = flat.reshape(-1, BLOCK, BLOCK)
        return blocks, jnp.abs(blocks).max(axis=(1, 2))

    return retrace.instrument("tensor_pack", body), ()


@lru_cache(maxsize=1)
def _compiled_pack():
    import jax

    fn, donate = pack_program()
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


def fetch_block_meta(maxmag_dev) -> np.ndarray:
    """The pack stage's one device->host transfer: the (N,) per-block
    magnitude maxima (4 bytes/block — the blocks themselves stay in HBM
    for the CX/D scan). Sanctioned in rules_jax.D2H_SANCTIONED."""
    import jax

    return np.asarray(jax.device_get(maxmag_dev))


# --- encode ---------------------------------------------------------------

def _resolve_backend(device) -> str:
    if device is None:
        device = os.environ.get("BUCKETEER_TENSOR_BACKEND", "device")
    if device not in ("device", "replay", "host"):
        raise ValueError(
            f"unknown tensor backend {device!r}: expected device | "
            "replay | host")
    return device


def _chunk_blocks(chunk_blocks) -> int:
    if chunk_blocks is not None:
        return max(1, int(chunk_blocks))
    try:
        return max(1, int(os.environ.get(
            "BUCKETEER_TENSOR_CHUNK_BLOCKS", str(DEFAULT_CHUNK_BLOCKS))))
    except ValueError:
        return DEFAULT_CHUNK_BLOCKS


def _block_rows(limbs: np.ndarray) -> np.ndarray:
    """(K, n) limb planes -> (K * nb, 4096) int32 block rows,
    limb-major, tails zero-padded (zeros never become significant, so
    padding costs no symbols)."""
    k, n = limbs.shape
    nb = -(-n // BLOCK_SAMPLES) if n else 0
    rows = np.zeros((k, nb * BLOCK_SAMPLES), dtype=np.int32)
    rows[:, :n] = limbs
    return rows.reshape(k * nb, BLOCK_SAMPLES)


def _limb_bases(k: int, nb: int) -> np.ndarray:
    """Absolute payload-plane base of every block (limb-major order):
    limb j covers planes [(K-1-j)*16, (K-j)*16)."""
    return np.repeat(
        np.array([(k - 1 - j) * _planes.LIMB_BITS for j in range(k)],
                 dtype=np.int32), nb)


def _encode_host(rows: np.ndarray, floors: np.ndarray) -> list:
    out = []
    for row, floor in zip(rows, floors):
        _poll()
        block = row.reshape(BLOCK, BLOCK)
        mags = (np.abs(block).astype(np.uint32) >> floor) << floor
        out.append(t1.encode_block(mags, block < 0, BAND,
                                   floor=int(floor)))
    return out


def encode_chunk_device(rows: np.ndarray, floors: np.ndarray,
                        backend: str, device=None):
    """One chunk through the device: pack -> CX/D (-> MQ). Returns
    ([t1.CodedBlock], symbols, device_seconds). ``device`` (a
    ``jax.Device``) stages the limb buffer with a *committed*
    ``jax.device_put`` so the pack and every downstream device stage
    run on that core — the scheduler's pool workers use it; None keeps
    default placement."""
    import jax.numpy as jnp

    n = len(rows)
    nbuck = _bucket(n)
    flat = np.zeros(nbuck * BLOCK_SAMPLES, dtype=np.int32)
    flat[:n * BLOCK_SAMPLES] = rows.ravel()
    graftcost.record_bucket("tensor.blocks", n, nbuck)
    t0 = time.perf_counter()
    if device is not None:
        import jax
        staged = jax.device_put(flat, device)
    else:
        staged = jnp.asarray(flat)
    blocks_dev, maxmag_dev = _compiled_pack()(staged)
    maxmag = fetch_block_meta(maxmag_dev)[:n]
    nbps = np.zeros(n, dtype=np.int32)
    nz = maxmag > 0
    nbps[nz] = np.floor(np.log2(maxmag[nz].astype(np.float64))).astype(
        np.int32) + 1
    hs = np.full(n, BLOCK, dtype=np.int32)
    bandnames = [BAND] * n
    if backend == "device":
        res = cxd_mod.run_device_mq(blocks_dev, nbps, floors, bandnames,
                                    hs, hs, _planes.LIMB_BITS, 0)
        return res.blocks, res.total_syms, time.perf_counter() - t0
    streams = cxd_mod.run_cxd(blocks_dev, nbps, floors, bandnames, hs,
                              hs, _planes.LIMB_BITS, 0)
    dev_s = time.perf_counter() - t0
    return t1_batch.encode_cxd(streams), streams.total_syms, dev_s


def _to_tensor_block(blk: t1.CodedBlock) -> container.TensorBlock:
    cums = np.asarray([p.cum_length for p in blk.passes
                       if p.pass_type == 2], dtype=np.int64)
    return container.TensorBlock(blk.n_bitplanes, len(cums), blk.data,
                                 cums)


def encode_tensor(arr, planes: int | None = None,
                  rate: int | None = None, device: str | None = None,
                  chunk_blocks: int | None = None) -> bytes:
    """Encode a tensor to ``BTT1`` container bytes.

    ``planes=k`` keeps only the top ``k`` absolute payload planes
    (encode-time floors: the dropped planes cost no coding work);
    ``rate=b`` encodes losslessly and then truncates the blob to the
    deepest global plane cut fitting ``b`` bytes. ``device`` picks the
    backend (``device`` | ``replay`` | ``host``; env default
    ``BUCKETEER_TENSOR_BACKEND``) — all three are byte-identical.
    """
    arr = np.asarray(arr)
    spec = _planes.spec_for(arr.dtype)
    t_wall = time.perf_counter()
    backend = _resolve_backend(device)
    limbs = _planes.to_limbs(arr)
    negz = _planes.negative_zero_positions(arr, spec)
    rows = _block_rows(limbs)
    k = spec.n_limbs
    nb = len(rows) // k if k else 0
    total_bits = k * _planes.LIMB_BITS
    bases = _limb_bases(k, nb)
    if planes is not None:
        if planes < 0:
            raise ValueError(f"planes must be >= 0, got {planes}")
        cut = max(0, total_bits - int(planes))
    else:
        cut = 0
    floors = np.clip(cut - bases, 0, _planes.LIMB_BITS).astype(np.int32)

    coded: list = []
    n_syms = 0
    dev_s = 0.0
    chunk = _chunk_blocks(chunk_blocks)
    launch = getattr(_services, "launch", None)
    for off in range(0, len(rows), chunk):
        _poll()
        sub = rows[off:off + chunk]
        fsub = floors[off:off + chunk]
        if backend == "host":
            coded += _encode_host(sub, fsub)
        else:
            if backend == "device" and launch is not None:
                # Scheduler seam: the pool runs (and possibly merges)
                # the chunk on a free device; byte-identical because
                # per-block coding is independent of its batch-mates.
                blks, syms, ds = launch(sub, fsub, backend)
            else:
                blks, syms, ds = encode_chunk_device(sub, fsub, backend)
            coded += blks
            n_syms += syms
            dev_s += ds

    enc = container.EncodedTensor(
        spec, arr.shape, negz, [_to_tensor_block(b) for b in coded])
    blob = container.dump(enc)
    if _metrics_sink is not None:
        _metrics_sink.record("tensor.encode",
                             time.perf_counter() - t_wall,
                             items=arr.nbytes)
        if dev_s:
            _metrics_sink.record("tensor.encode_device", dev_s,
                                 items=n_syms)
        _metrics_sink.count("tensor.encode_blocks", len(coded))
        _metrics_sink.count("tensor.raw_bytes", arr.nbytes)
        _metrics_sink.count("tensor.coded_bytes", len(blob))
    if rate is not None:
        return truncate_tensor(blob, rate=rate)
    return blob


# --- truncation -----------------------------------------------------------

def _cut_kept(b: container.TensorBlock, base: int, cut: int) -> int:
    """Planes block ``b`` keeps under the absolute payload-plane
    ``cut`` (never more than it already has)."""
    floor_new = max(b.nbp - b.kept, min(cut - base, _planes.LIMB_BITS))
    return max(0, b.nbp - floor_new)


def _container_size(enc: container.EncodedTensor, cut: int,
                    bases: np.ndarray) -> int:
    """Serialized size of ``_apply_cut(enc, cut)`` from the parsed
    headers alone — no byte copies (rate= probes every cut, so this
    must be arithmetic, not a dump)."""
    size = 17 + 8 * len(enc.shape) + 8 * len(enc.neg_zeros)
    for b, base in zip(enc.blocks, bases):
        kept = _cut_kept(b, int(base), cut)
        size += 6 + 4 * kept
        if kept == b.kept:
            size += len(b.data)
        elif kept:
            size += int(b.cums[kept - 1])
    return size


def _apply_cut(enc: container.EncodedTensor,
               cut: int) -> container.EncodedTensor:
    """Truncate every block at the absolute payload-plane ``cut``
    (drop planes below it) by slicing at the recorded plane-boundary
    lengths — no recode."""
    k = enc.spec.n_limbs
    nb = enc.blocks_per_limb
    bases = _limb_bases(k, nb)
    blocks = []
    for b, base in zip(enc.blocks, bases):
        kept = _cut_kept(b, int(base), cut)
        if kept == b.kept:
            blocks.append(b)
        elif kept == 0:
            blocks.append(container.TensorBlock(
                b.nbp, 0, b"", np.zeros(0, dtype=np.int64)))
        else:
            end = int(b.cums[kept - 1])
            blocks.append(container.TensorBlock(
                b.nbp, kept, b.data[:end], b.cums[:kept]))
    return container.EncodedTensor(enc.spec, enc.shape, enc.neg_zeros,
                                   blocks)


def truncate_tensor(blob: bytes, planes: int | None = None,
                    rate: int | None = None) -> bytes:
    """Progressively truncate an encoded tensor at plane boundaries.

    ``planes=k``: keep the top ``k`` absolute payload planes.
    ``rate=b``: the deepest (least destructive) global plane cut whose
    container fits ``b`` bytes; the header itself is the floor — a
    budget below it returns the fully-cut container.
    """
    enc = container.parse(blob)
    total_bits = enc.spec.n_limbs * _planes.LIMB_BITS
    if (planes is None) == (rate is None):
        raise ValueError("pass exactly one of planes= / rate=")
    if planes is not None:
        if planes < 0:
            raise ValueError(f"planes must be >= 0, got {planes}")
        return container.dump(_apply_cut(enc, total_bits - min(
            int(planes), total_bits)))
    if rate < 0:
        raise ValueError(f"rate must be >= 0, got {rate}")
    # Candidate sizes are pure header arithmetic (_container_size);
    # only the winning cut is serialized.
    bases = _limb_bases(enc.spec.n_limbs, enc.blocks_per_limb)
    for cut in range(0, total_bits + 1):
        if _container_size(enc, cut, bases) <= rate:
            break
    else:
        cut = total_bits
    return container.dump(_apply_cut(enc, cut))


# --- decode ---------------------------------------------------------------

def decode_tensor(blob: bytes, planes: int | None = None) -> np.ndarray:
    """Decode ``BTT1`` container bytes back to a tensor. A losslessly
    coded blob round-trips bit-exact (NaN payloads and negative zeros
    included); a truncated blob (or ``planes=k``, an on-the-fly cut)
    reconstructs missing planes at the EBCOT midpoint, floored — the
    same deterministic rule the image decoder's quality layers use.
    Malformed input raises the typed :class:`DecodeError`."""
    if planes is not None and planes < 0:
        raise ValueError(f"planes must be >= 0, got {planes}")
    t_wall = time.perf_counter()
    try:
        enc = container.parse(blob)
        total_bits = enc.spec.n_limbs * _planes.LIMB_BITS
        if planes is not None:
            enc = _apply_cut(enc, total_bits - min(int(planes),
                                                   total_bits))
        k = enc.spec.n_limbs
        nb = enc.blocks_per_limb
        n = enc.n_elements
        limbs = np.zeros((k, nb * BLOCK_SAMPLES), dtype=np.int32)
        n_dec = 0
        for i, b in enumerate(enc.blocks):
            _poll()
            if not (b.kept and b.nbp):
                continue
            hv, nd = t1_dec.decode_block(
                b.data, b.nbp, 3 * b.kept - 2, BAND, BLOCK, BLOCK)
            n_dec += nd
            mag = np.abs(hv) >> 1
            j, bi = divmod(i, nb)
            limbs[j, bi * BLOCK_SAMPLES:(bi + 1) * BLOCK_SAMPLES] = \
                np.where(hv < 0, -mag, mag).ravel()
        out = _planes.from_limbs(limbs[:, :n], enc.spec, enc.shape,
                                 enc.neg_zeros)
    except DecodeError:
        raise
    except (IndexError, KeyError, ValueError, OverflowError) as exc:
        raise DecodeError(f"malformed tensor container: {exc}") from exc
    if _metrics_sink is not None:
        _metrics_sink.record("tensor.decode",
                             time.perf_counter() - t_wall,
                             items=n_dec)
        _metrics_sink.count("tensor.decode_blocks", len(enc.blocks))
    return out


def tensor_stats(blob: bytes) -> dict:
    """Cheap container metadata for the HTTP layer (no Tier-1 work)."""
    enc = container.parse(blob)
    raw = enc.n_elements * enc.spec.itemsize
    coded = len(blob)
    return {
        "dtype": enc.spec.name,
        "shape": list(enc.shape),
        "limbs": enc.spec.n_limbs,
        "blocks": len(enc.blocks),
        "planes": enc.pcap,
        "raw_bytes": raw,
        "coded_bytes": coded,
        "ratio": round(raw / coded, 4) if coded else 0.0,
    }
