"""lock-order-cycle: static lock-acquisition-order graph + cycle check.

The dynamic half of this analysis (graftrace's detector) records the
lock orders that *executed*; this rule computes the orders that are
*written*, so a cross-lock inversion is flagged on every PR even when
no test drives both paths. Locks are identified with the same
inference as ``rules_locks`` (class lock fields incl. the graftrace
seam factories, plus module-level ``NAME = threading.Lock()``
globals); an edge ``A -> B`` is recorded when code acquires B while
(statically) holding A:

- directly nested ``with`` blocks, and
- one hop through a same-class method call: ``with self._a:
  self.foo()`` where ``foo`` acquires ``self._b`` adds ``A -> B``
  (the device thread's cv-held snapshot of scheduler state is exactly
  this shape).

A cycle in the resulting digraph is deadlock *potential*: two threads
walking different edges of the cycle can block each other forever. A
length-1 cycle (re-acquiring a non-reentrant ``Lock`` you already
hold) is certain deadlock and is flagged too; reentrant kinds
(``RLock``/``Condition``) are exempt from self-edges.

Out of scope (documented): cross-class object graphs (two *different*
classes' locks nested — the dynamic graph covers those, with real
stacks), manual ``acquire()``/``release()`` pairing, and deeper than
one call hop. Closures and nested defs are skipped — they escape the
static context, same policy as ``rules_locks``.
"""
from __future__ import annotations

import ast

from .findings import ERROR, Finding
from .graftrace.detector import find_lock_cycles
from .rules_locks import LOCK_FACTORIES, _leaf_name, _method_self

LOCK_ORDER_CYCLE = "lock-order-cycle"

_REENTRANT = {"RLock", "Condition", "make_rlock", "make_condition"}


def _factory_kind(node):
    """The factory leaf name when ``node`` is a lock-factory call (or a
    zero-arg lambda around one, the dataclass default_factory idiom)."""
    if isinstance(node, ast.Lambda):
        node = node.body
    if isinstance(node, ast.Call):
        name = _leaf_name(node.func)
        if name in LOCK_FACTORIES:
            return name
    return None


def _class_lock_kinds(cls: ast.ClassDef) -> dict:
    """attr -> factory kind for a class's lock fields."""
    kinds: dict = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign):
            kind = _factory_kind(stmt.value)
            if kind:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        kinds[t.id] = kind
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and \
                isinstance(stmt.value, ast.Call):
            kind = _factory_kind(stmt.value)
            if kind:
                kinds[stmt.target.id] = kind
            for kw in stmt.value.keywords:
                if kw.arg == "default_factory":
                    kind = _factory_kind(kw.value) or \
                        (_leaf_name(kw.value)
                         if _leaf_name(kw.value) in LOCK_FACTORIES
                         else None)
                    if kind:
                        kinds[stmt.target.id] = kind
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        self_name = _method_self(meth)
        if self_name is None:
            continue
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign):
                kind = _factory_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == self_name:
                            kinds[t.attr] = kind
    return kinds


def _module_lock_kinds(mod) -> dict:
    kinds: dict = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            kind = _factory_kind(stmt.value)
            if kind:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        kinds[t.id] = kind
    return kinds


class _FuncScan:
    """Edge collection over one function body with a held-lock stack."""

    def __init__(self, resolve, one_hop, edges, ctx):
        self.resolve = resolve      # expr -> (qual, kind) | None
        self.one_hop = one_hop      # method name -> set of (qual, kind, line)
        self.edges = edges          # (a, b) -> info dict
        self.ctx = ctx              # "Class.method" for messages

    def _add_edge(self, held, acq, line):
        (a, akind), (b, bkind) = held, acq
        if a == b and bkind in _REENTRANT:
            return
        self.edges.setdefault((a, b), {
            "held": a, "acquired": b, "line": line, "context": self.ctx})

    def scan(self, stmts, held):
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # closures escape the context
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in stmt.items:
                got = self.resolve(item.context_expr)
                if got is not None:
                    for h in inner:
                        self._add_edge(h, got, item.context_expr.lineno)
                    inner.append(got)
            self.scan(stmt.body, inner)
            return
        if held:
            self._calls(stmt, held)
        for name in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, name, ()):
                self._stmt(s, held)
        for h in getattr(stmt, "handlers", ()):
            for s in h.body:
                self._stmt(s, held)

    def _calls(self, stmt, held):
        """One-hop: self-method calls in this statement's expressions
        add edges to every lock that method acquires."""
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                continue                # child statements recurse above
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == self.one_hop.get("__self__"):
                    for qual, kind, _line in \
                            self.one_hop.get(f.attr, ()):
                        for h in held:
                            self._add_edge(h, (qual, kind), sub.lineno)


def _acquired_in(meth, resolve) -> set:
    """Every lock a method acquires anywhere in its body (the one-hop
    summary). Nested defs excluded."""
    out = set()

    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    got = resolve(item.context_expr)
                    if got is not None:
                        out.add((got[0], got[1], item.context_expr.lineno))
                walk(stmt.body)
                continue
            for name in ("body", "orelse", "finalbody"):
                walk(getattr(stmt, name, ()))
            for h in getattr(stmt, "handlers", ()):
                walk(h.body)

    walk(meth.body)
    return out


def _collect_edges(mod, edges: dict) -> None:
    mod_locks = _module_lock_kinds(mod)

    def module_resolve(expr):
        if isinstance(expr, ast.Name) and expr.id in mod_locks:
            return (f"{mod.relpath}:{expr.id}", mod_locks[expr.id])
        return None

    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan = _FuncScan(module_resolve, {}, edges,
                             f"{mod.relpath}:{node.name}")
            scan.scan(node.body, [])
        if not isinstance(node, ast.ClassDef):
            continue
        cls = node
        lock_kinds = _class_lock_kinds(cls)
        methods = [m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]

        def make_resolve(self_name):
            def resolve(expr):
                if isinstance(expr, ast.Attribute) and \
                        isinstance(expr.value, ast.Name) and \
                        expr.value.id == self_name and \
                        expr.attr in lock_kinds:
                    return (f"{cls.name}.{expr.attr}",
                            lock_kinds[expr.attr])
                return module_resolve(expr)
            return resolve

        summaries: dict = {}
        for meth in methods:
            self_name = _method_self(meth)
            if self_name is None:
                continue
            summaries[meth.name] = _acquired_in(meth,
                                                make_resolve(self_name))
        for meth in methods:
            self_name = _method_self(meth)
            if self_name is None:
                continue
            one_hop = dict(summaries)
            one_hop["__self__"] = self_name
            scan = _FuncScan(make_resolve(self_name), one_hop, edges,
                             f"{cls.name}.{meth.name}")
            scan.scan(meth.body, [])


def run(project) -> list:
    edges_by_mod: dict = {}
    all_edges: dict = {}
    for mod in project.modules:
        before = set(all_edges)
        _collect_edges(mod, all_edges)
        for key in set(all_edges) - before:
            edges_by_mod[key] = mod

    findings = []
    for cyc in find_lock_cycles(all_edges):
        first = cyc["edges"][0] if cyc["edges"] else None
        mod = edges_by_mod.get((first["held"], first["acquired"])) \
            if first else None
        path = mod.relpath if mod is not None else "<unknown>"
        line = first["line"] if first else 1
        chain = " -> ".join(cyc["nodes"] + (cyc["nodes"][0],))
        if len(cyc["nodes"]) == 1:
            msg = (f"non-reentrant lock {cyc['nodes'][0]} is re-acquired "
                   f"while already held (in {first['context']}) — "
                   "certain self-deadlock")
        else:
            detail = "; ".join(
                f"{e['context']} takes {e['acquired']} while holding "
                f"{e['held']} (line {e['line']})" for e in cyc["edges"])
            msg = (f"lock-acquisition-order cycle {chain}: {detail} — "
                   "two threads walking different edges deadlock; pick "
                   "one global order or drop the nesting")
        findings.append(Finding(
            LOCK_ORDER_CYCLE, path, line, msg, ERROR,
            mod.source_line(line) if mod is not None else ""))
    return findings
