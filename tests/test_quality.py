"""Matched-rate quality gates vs an independent encoder (VERDICT r2 #1).

Every case encodes the same image with this codec and with OpenJPEG (via
Pillow) at the same byte budget and compares PSNR — the honest analog of
the BASELINE north star (≤0.1 dB vs kdu_compress at `-rate 3`,
reference: converters/KakaduConverter.java:43). kdu itself is not
installable here; OpenJPEG is the stand-in oracle.

Two content regimes matter:
- correlated channels (photographs — the service's actual workload,
  UCLA Library digitized collections): our adaptive MCT applies the ICT
  and beats OpenJPEG's per-channel coding by >1.5 dB;
- independent channel noise: adaptive MCT turns the ICT off and matches
  OpenJPEG at parity.
"""
import io

import numpy as np
import pytest
from PIL import Image

from bucketeer_tpu.codec import encoder
from bucketeer_tpu.codec.encoder import EncodeParams


def _psnr(a, b):
    mse = np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    return 10 * np.log10(255.0 ** 2 / max(mse, 1e-12))


def _opj_at(img: np.ndarray, bpp: float) -> float:
    """OpenJPEG's PSNR on img at the given total bpp."""
    src_bpp = 8.0 * (img.shape[2] if img.ndim == 3 else 1)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG2000", irreversible=True,
                              quality_mode="rates",
                              quality_layers=[src_bpp / bpp])
    return _psnr(np.asarray(Image.open(io.BytesIO(buf.getvalue()))), img)


def _ours_at(img: np.ndarray, bpp: float) -> tuple:
    params = EncodeParams(lossless=False, levels=5, n_layers=1, rate=bpp,
                          base_delta=0.5)
    data = encoder.encode_jp2(img, 8, params)
    got_bpp = 8.0 * len(data) / (img.shape[0] * img.shape[1])
    dec = np.asarray(Image.open(io.BytesIO(data)))
    return _psnr(dec, img), got_bpp


@pytest.fixture(scope="module")
def photo():
    """Photograph-like: shared luminance structure across channels,
    edges, mild sensor noise."""
    rng = np.random.default_rng(5)
    y, x = np.mgrid[0:512, 0:512]
    lum = (110 + 70 * np.sin(x / 37.0) * np.cos(y / 23.0)
           + 25 * ((x // 128 + y // 128) % 2)
           + rng.normal(0, 6, (512, 512)))
    img = np.stack([lum + 10, lum * 0.92, lum * 0.85], -1)
    img = img + rng.normal(0, 3, (512, 512, 3))
    return np.clip(img, 0, 255).astype(np.uint8)


@pytest.mark.parametrize("bpp", [1.0, 2.0, 3.0])
def test_beats_openjpeg_on_photo_content(photo, bpp):
    ours, got_bpp = _ours_at(photo, bpp)
    assert abs(got_bpp - bpp) <= 0.05 * bpp + 0.02
    theirs = _opj_at(photo, got_bpp)
    assert ours >= theirs - 0.1, (
        f"{bpp} bpp: ours {ours:.2f} dB vs OpenJPEG {theirs:.2f} dB")


def test_parity_on_uncorrelated_noise():
    """Adaptive MCT must not pay the ICT tax on channel-independent
    content: parity with OpenJPEG's (always per-channel) coding."""
    rng = np.random.default_rng(42)
    y, x = np.mgrid[0:256, 0:256]
    base = 128 + 80 * np.sin(x / 21.0) * np.cos(y / 17.0)
    img = np.clip(base[..., None] + rng.normal(0, 14, (256, 256, 3)),
                  0, 255).astype(np.uint8)
    ours, got_bpp = _ours_at(img, 3.0)
    theirs = _opj_at(img, got_bpp)
    assert ours >= theirs - 0.25, (
        f"ours {ours:.2f} dB vs OpenJPEG {theirs:.2f} dB")


def test_mct_choice_is_content_adaptive(photo):
    from bucketeer_tpu.codec.encoder import _mct_helps
    rng = np.random.default_rng(0)
    noise = rng.integers(0, 256, (128, 128, 3)).astype(np.uint8)
    for rate in (None, 1.0, 3.0):
        assert _mct_helps(photo, False, rate) is True
        assert _mct_helps(noise, False, rate) is False
