"""The batch data plane (ISSUE 19, graftfeed): recipe validation (fuzz
-> typed InvalidParam, never a 500-shaped crash), batch assembly
bit-exactness against stacking per-image decode_to_coefficients (int32
reversible and float32 irreversible, with region/reduce/layers), the
sharded-vs-replicated placement contract on the conftest-forced
8-device mesh, per-item partial-failure manifests, the merged dequant
launch, and the BTB1 stored-container round trip with progressive
plane truncation and corruption fuzzing."""
import struct
import threading

import numpy as np
import pytest

from bucketeer_tpu import batches as batches_mod
from bucketeer_tpu.batches import (BatchRecipe, assemble_batch,
                                   decode_batch, encode_batch,
                                   parse_recipe, truncate_batch)
from bucketeer_tpu.batches.store import MAGIC, batch_stats
from bucketeer_tpu.codec import encoder as codec_encoder
from bucketeer_tpu.codec.decode.errors import DecodeError, InvalidParam
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.engine.scheduler import EncodeScheduler
from bucketeer_tpu.server.metrics import Metrics
from bucketeer_tpu.tensor import decode_to_coefficients


def _encode(size=32, lossless=True, levels=2, seed=7):
    rng = np.random.default_rng(seed)
    img = rng.integers(0, 256, size=(size, size, 3)).astype(np.uint8)
    return codec_encoder.encode_jp2(
        img, 8, EncodeParams(lossless=lossless, levels=levels,
                             tile_size=size, gen_plt=True), jpx=True)


@pytest.fixture(scope="module")
def blobs8():
    """Eight compatible reversible 32px codestreams, keyed img0..img7."""
    return {f"img{i}": _encode(seed=100 + i) for i in range(8)}


@pytest.fixture(scope="module")
def lossy4():
    """Four compatible irreversible (9/7, float32) codestreams."""
    return {f"lossy{i}": _encode(lossless=False, seed=200 + i)
            for i in range(4)}


def _oracle(blobs, ids, **kwargs):
    """Stacked per-image decode_to_coefficients — the ground truth the
    batch plane must match bit-for-bit."""
    hosts = [decode_to_coefficients(blobs[i], **kwargs).to_host()
             for i in ids]
    return {key: np.stack([h[key] for h in hosts])
            for key in hosts[0]}


def _assert_bitexact(result, expected):
    got = result.to_host()
    assert set(got) == set(expected)
    for key in expected:
        assert got[key].dtype == expected[key].dtype, key
        np.testing.assert_array_equal(got[key], expected[key])


# --- recipe validation -------------------------------------------------

def test_recipe_parse_roundtrip():
    r = parse_recipe({"ids": ["a", "b"], "region": [8, 8, 16, 16],
                      "reduce": 1, "layers": 2, "dtype": "int32",
                      "layout": "sharded", "store": True, "planes": 4,
                      "deadline_s": 30})
    assert r == BatchRecipe(ids=("a", "b"), region=(8, 8, 16, 16),
                            reduce=1, layers=2, dtype="int32",
                            layout="sharded", store=True, planes=4,
                            deadline_s=30.0)
    assert parse_recipe({"ids": ["x"]}).layout == "auto"


@pytest.mark.parametrize("doc", [
    None, [], "ids", 42,
    {},                                        # no ids
    {"ids": []},                               # empty ids
    {"ids": "img0"},                           # not a list
    {"ids": [1, 2]},                           # non-string ids
    {"ids": ["ok", "bad id"]},                 # id fails the charset
    {"ids": ["a" * 300]},                      # id too long
    {"ids": [f"i{k}" for k in range(200)]},    # over MAX_ITEMS
    {"ids": ["a"], "bogus": 1},                # unknown key
    {"ids": ["a"], "region": [1, 2, 3]},       # 3-tuple region
    {"ids": ["a"], "region": [0, 0, 0, 5]},    # zero-size region
    {"ids": ["a"], "region": [-1, 0, 4, 4]},   # negative origin
    {"ids": ["a"], "region": [0, 0, True, 4]},  # bool is not an int
    {"ids": ["a"], "region": "0,0,4,4"},       # string region
    {"ids": ["a"], "reduce": -1},
    {"ids": ["a"], "reduce": 99},
    {"ids": ["a"], "reduce": 1.5},
    {"ids": ["a"], "layers": 0},
    {"ids": ["a"], "dtype": "int8"},
    {"ids": ["a"], "layout": "mesh"},
    {"ids": ["a"], "store": "yes"},
    {"ids": ["a"], "planes": 4},               # planes without store
    {"ids": ["a"], "store": True, "planes": 0},
    {"ids": ["a"], "deadline_s": 0},
    {"ids": ["a"], "deadline_s": -5},
    {"ids": ["a"], "deadline_s": 1e9},
    {"ids": ["a"], "deadline_s": "soon"},
])
def test_recipe_fuzz_typed_invalid(doc):
    with pytest.raises(InvalidParam):
        parse_recipe(doc)


def test_recipe_fuzz_random_mutations():
    """Seeded garbage over the recipe keyspace: every outcome is a
    parsed recipe or a typed InvalidParam — never a TypeError/KeyError
    escaping toward a 500."""
    rng = np.random.default_rng(17)
    pool = [None, True, False, -1, 0, 1, 3.7, "x", "", [], {}, ["a"],
            [0], {"k": 1}, float("nan"), "int32", "sharded", [1, 2, 3, 4]]
    keys = ["ids", "region", "reduce", "layers", "dtype", "layout",
            "store", "planes", "deadline_s", "junk"]
    for _ in range(300):
        doc = {keys[k]: pool[v] for k, v in zip(
            rng.integers(0, len(keys), size=rng.integers(0, 6)),
            rng.integers(0, len(pool), size=6))}
        try:
            parse_recipe(doc)
        except InvalidParam:
            pass


# --- assembly bit-exactness and placement -----------------------------

def test_assemble_reversible_sharded_bitexact(blobs8):
    """Eight reversible images through an admitted batchread on a real
    scheduler: int32 bands, bit-exact against per-image decode+stack,
    placed P("batch") over the conftest-forced 8-device mesh, and the
    per-image dequants merge into combined device launches."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ids = sorted(blobs8)
    recipe = BatchRecipe(ids=tuple(ids))
    sched = EncodeScheduler(queue_depth=16, max_concurrent=8,
                            devices=1, window_s=0.3)
    sink = Metrics()
    sched.set_metrics_sink(sink)
    try:
        result = sched.submit_batchread(assemble_batch, recipe,
                                        data_for=blobs8.get)
    finally:
        sched.close()

    assert result.layout == "sharded"
    assert result.ids == tuple(ids)
    assert all(e["ok"] for e in result.manifest)
    for arr in result.bands.values():
        assert arr.shape[0] == 8
        sharding = arr.sharding
        assert isinstance(sharding, NamedSharding)
        assert sharding.spec == P("batch")
    assert result.meta["reversible"] is True
    _assert_bitexact(result, _oracle(blobs8, ids))
    for key in result.to_host():
        assert result.to_host()[key].dtype == np.int32

    counters = sink.report()["counters"]
    # Merging happened: fewer device launches than images rode them.
    assert counters["batchread.merged_images"] == 8
    assert counters["batchread.device_launches"] < 8


def test_assemble_irreversible_float32_replicated(lossy4):
    """Four irreversible images: float32 bands, bit-exact (the 9/7
    dequant is the same elementwise program either path), and under
    layout=auto a 4-item batch does not divide the 8-device mesh, so
    placement falls back to replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ids = sorted(lossy4)
    recipe = BatchRecipe(ids=tuple(ids), dtype="float32")
    sched = EncodeScheduler(queue_depth=16, max_concurrent=8,
                            devices=1, window_s=0.3)
    try:
        result = sched.submit_batchread(assemble_batch, recipe,
                                        data_for=lossy4.get)
    finally:
        sched.close()

    assert result.layout == "replicated"
    assert result.meta["reversible"] is False
    for arr in result.bands.values():
        assert isinstance(arr.sharding, NamedSharding)
        assert arr.sharding.spec == P()
    _assert_bitexact(result, _oracle(lossy4, ids))
    for key, arr in result.to_host().items():
        assert arr.dtype == np.float32


def test_assemble_region_reduce_layers_standalone(blobs8):
    """region/reduce/layers apply uniformly to every item, and a
    standalone call (no scheduler hooks -> inline dequant) is the same
    bit-exact result as the admitted path."""
    ids = ["img0", "img3", "img5"]
    kwargs = dict(region=(8, 8, 16, 16), reduce=1, layers=1)
    result = assemble_batch(
        BatchRecipe(ids=tuple(ids), **kwargs), data_for=blobs8.get)
    assert result.layout == "replicated"     # 3 items on 8 devices
    assert result.meta["reduce"] == 1
    _assert_bitexact(result, _oracle(blobs8, ids, **kwargs))


def test_assemble_request_shaped_errors(blobs8, lossy4):
    both = dict(blobs8)
    both.update(lossy4)
    both["tiny"] = _encode(size=16, seed=5)

    def run(recipe):
        return assemble_batch(recipe, data_for=both.get)

    with pytest.raises(InvalidParam, match="unknown image ids"):
        run(BatchRecipe(ids=("img0", "nope", "gone")))
    with pytest.raises(InvalidParam, match="mixed geometry"):
        run(BatchRecipe(ids=("img0", "tiny")))
    with pytest.raises(InvalidParam, match="mixed geometry"):
        run(BatchRecipe(ids=("img0", "lossy0")))   # reversibility split
    with pytest.raises(InvalidParam, match="beyond the"):
        run(BatchRecipe(ids=("img0", "img1"), reduce=5))
    with pytest.raises(InvalidParam, match="dtype=float32"):
        run(BatchRecipe(ids=("img0",), dtype="float32"))
    with pytest.raises(InvalidParam, match="dtype=int32"):
        run(BatchRecipe(ids=("lossy0",), dtype="int32"))
    with pytest.raises(InvalidParam, match="outside the"):
        run(BatchRecipe(ids=("img0",), region=(64, 0, 8, 8)))
    with pytest.raises(InvalidParam, match="does not divide"):
        run(BatchRecipe(ids=("img0", "img1", "img2"),
                        layout="sharded"))


def test_assemble_partial_failure_manifest(blobs8):
    """A corrupt item fails alone: its manifest row carries the typed
    error, the surviving rows stay bit-exact and in recipe order."""
    ids = ["img0", "img1", "img2", "img3"]
    blobs = {i: blobs8[i] for i in ids}
    # Past the main header (so the probe passes), then truncated so
    # Tier-1 hits the cliff mid-codestream.
    blobs["img2"] = blobs["img2"][:len(blobs["img2"]) // 2]

    result = assemble_batch(BatchRecipe(ids=tuple(ids)),
                            data_for=blobs.get)
    assert [e["id"] for e in result.manifest] == ids
    flags = {e["id"]: e["ok"] for e in result.manifest}
    assert flags == {"img0": True, "img1": True,
                     "img2": False, "img3": True}
    bad = next(e for e in result.manifest if not e["ok"])
    assert bad["error"] and bad["message"]
    assert result.ids == ("img0", "img1", "img3")
    _assert_bitexact(result, _oracle(blobs8, ["img0", "img1", "img3"]))


def test_assemble_all_items_failed(blobs8):
    blobs = {"a": blobs8["img0"][:40], "b": blobs8["img1"][:40]}
    with pytest.raises(DecodeError):
        assemble_batch(BatchRecipe(ids=("a", "b")), data_for=blobs.get)


# --- the merged dequant launch ----------------------------------------

def test_dequant_launches_merge_to_expected_width():
    """Three concurrent compatible dequant dispatches with
    _expected=3 merge into ONE pool launch; each caller still gets its
    own slice back (stub pool: no JAX, launch identity observable).
    One device: an idle peer worker cuts the merge window by design
    (it could take the compatible job instead)."""
    launches = []

    def stub(plan, arrays, mode="rows"):
        assert mode == "dequant"
        launches.append(len(arrays))
        return "launch-%d" % len(launches)

    sched = EncodeScheduler(queue_depth=8, max_concurrent=4,
                            devices=1, window_s=2.0)
    sched.launch_fn = stub
    try:
        arrays = [np.arange(6, dtype=np.int32).reshape(2, 3)]
        fns = [lambda: sched.dispatch_dequant(
            True, (0.5,), arrays, _expected=3) for _ in range(3)]
        outs = [None] * 3
        barrier = threading.Barrier(3)

        def client(i):
            barrier.wait()
            outs[i] = fns[i]()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "dequant dispatch hung"
    finally:
        sched.close()
    assert launches == [3]
    assert [o for o in outs] == [("launch-1", 3)] * 3


def test_band_slice_views():
    """BandSlice is a transparent lazy row view of the merged batched
    output: shape/dtype describe the row, materialize and __array__
    produce it."""
    from bucketeer_tpu.tensor.coeffs import BandSlice

    parent = np.arange(24, dtype=np.int32).reshape(4, 2, 3)
    v = BandSlice(parent, 2)
    assert v.shape == (2, 3)
    assert v.dtype == np.int32
    np.testing.assert_array_equal(v.materialize(), parent[2])
    np.testing.assert_array_equal(np.asarray(v), parent[2])
    assert np.asarray(v, dtype=np.float64).dtype == np.float64


# --- BTB1 stored container --------------------------------------------

@pytest.fixture(scope="module")
def stored(blobs8):
    ids = ["img0", "img1", "img2", "img4"]
    result = assemble_batch(BatchRecipe(ids=tuple(ids)),
                            data_for=blobs8.get)
    return result, encode_batch(result)


def test_btb1_roundtrip_exact(stored):
    result, blob = stored
    assert blob[:4] == MAGIC
    header, bands = decode_batch(blob)
    assert header["ids"] == list(result.ids)
    assert header["layout"] == result.layout
    assert [e["ok"] for e in header["manifest"]] == [True] * 4
    host = result.to_host()
    assert set(bands) == set(host)
    for key in host:
        np.testing.assert_array_equal(bands[key], host[key])


def test_btb1_progressive_truncation(stored):
    result, blob = stored
    cut = truncate_batch(blob, planes=2)
    assert len(cut) < len(blob)
    header, bands = decode_batch(cut)
    # Same geometry, coarser values; a deeper decode-side cut of the
    # full blob equals decoding the truncated container.
    _, direct = decode_batch(blob, planes=2)
    host = result.to_host()
    for key in host:
        assert bands[key].shape == host[key].shape
        np.testing.assert_array_equal(bands[key], direct[key])
    stats = batch_stats(cut)
    assert stats["ids"] == list(result.ids)
    assert stats["n_bands"] == len(host)
    assert stats["coded_bytes"] == len(cut)


@pytest.mark.parametrize("mangle", [
    lambda b: b[:3],                                   # shorter than magic
    lambda b: b"XXXX" + b[4:],                         # flipped magic
    lambda b: b[:4] + struct.pack(">BI", 9, 1) + b[9:],  # bad version
    lambda b: b[:5] + struct.pack(">I", 1 << 30) + b[9:],  # header overrun
    lambda b: b[:12] + b"\x00" + b[13:],               # mangled JSON
    lambda b: b[:len(b) // 2],                         # tail-truncated
    lambda b: b[:9],                                   # header missing
])
def test_btb1_corruption_typed(stored, mangle):
    _, blob = stored
    with pytest.raises(DecodeError):
        decode_batch(mangle(blob))
    with pytest.raises(DecodeError):
        truncate_batch(mangle(blob), planes=1)
