"""graftscope core (bucketeer_tpu/obs): the span tracer's no-op fast
path and overhead budget, ring accounting, context propagation
(threads, bind), the flight recorder's dumps and rate limiting,
Chrome-trace export validity, log-record correlation, the SLO
watchdog, the modeled launch cost, the histogram math behind the new
server-side percentiles, and the Prometheus exposition round-trip."""
import json
import logging
import math
import re
import threading
import time

import numpy as np
import pytest

from bucketeer_tpu import obs
from bucketeer_tpu.obs import cost as obs_cost
from bucketeer_tpu.obs import logctx
from bucketeer_tpu.obs.trace import _NOOP, Recorder
from bucketeer_tpu.server.metrics import LatencyHist, Metrics


@pytest.fixture
def recorder():
    prev = obs.get_recorder()
    rec = Recorder(ring_spans=64)
    obs.install(rec)
    try:
        yield rec
    finally:
        obs.install(prev)


@pytest.fixture
def no_recorder():
    """Force the disabled fast path: an earlier test in the session
    may have booted an Api, which installs the process recorder."""
    prev = obs.get_recorder()
    obs.install(None)
    try:
        yield
    finally:
        obs.install(prev)


# --- disabled fast path + overhead budget --------------------------------

def test_noop_fast_path_is_pinned(no_recorder):
    """With no recorder, span() returns the one shared no-op object —
    no allocation, no context traffic, nothing recorded."""
    assert obs.get_recorder() is None
    handle = obs.span("anything", attr=1)
    assert handle is _NOOP
    with handle as s:
        assert s is None
    assert obs.current_context() is None
    # bind() must be the identity when disabled.
    fn = lambda: 7  # noqa: E731
    assert obs.bind(fn) is fn


def test_overhead_budget_vs_tier1_split_probe(no_recorder):
    """ISSUE 14 budget: with tracing disabled, the whole graftscope
    surface must cost <2% of the tier1_split probe. A small encode has
    well under 500 span-surface calls (a handful per chunk plus the
    scheduler/metrics seams); 500x the measured per-call no-op cost
    must fit the 2% budget of the same encode measured here."""
    from bucketeer_tpu.codec import encoder

    assert obs.get_recorder() is None
    img = np.linspace(0, 255, 128 * 128 * 3).reshape(
        128, 128, 3).astype(np.uint8)
    params = encoder.EncodeParams(lossless=True, levels=2)
    encoder.encode_array(img, 8, params)          # warm the compiles
    t0 = time.perf_counter()
    encoder.encode_array(img, 8, params)
    encode_s = time.perf_counter() - t0

    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        with obs.span("probe", x=1):
            pass
    per_call = (time.perf_counter() - t0) / n

    budget = 0.02 * encode_s
    assert 500 * per_call < budget, (
        f"disabled-span cost {per_call * 1e9:.0f} ns/call; 500 calls "
        f"= {500 * per_call * 1e3:.3f} ms > 2% probe budget "
        f"{budget * 1e3:.3f} ms")


# --- enabled tracing ------------------------------------------------------

def test_span_tree_parents_and_request_id(recorder):
    with obs.request_context("req-1"):
        with obs.span("outer") as outer:
            with obs.span("inner", k=3) as inner:
                pass
    spans = {s["name"]: s for s in recorder.snapshot()}
    assert spans["outer"]["trace_id"] == "req-1"
    assert spans["outer"]["parent_id"] is None
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["inner"]["attrs"] == {"k": 3}
    assert spans["inner"]["dur"] >= 0.0
    assert outer.span_id != inner.span_id


def test_error_status_and_attr(recorder):
    with pytest.raises(ValueError):
        with obs.request_context("req-e"):
            with obs.span("boom"):
                raise ValueError("nope")
    (s,) = recorder.snapshot()
    assert s["status"] == "error"
    assert "ValueError" in s["attrs"]["error"]


def test_bind_carries_context_to_foreign_thread(recorder):
    captured = {}

    def work():
        with obs.span("pool-item"):
            captured["rid"] = obs.current_request_id()

    with obs.request_context("req-t"):
        with obs.span("parent") as parent:
            bound = obs.bind(work)
    t = threading.Thread(target=bound)
    t.start()
    t.join()
    assert captured["rid"] == "req-t"
    spans = {s["name"]: s for s in recorder.snapshot()}
    assert spans["pool-item"]["trace_id"] == "req-t"
    assert spans["pool-item"]["parent_id"] == parent.span_id
    # Per-thread rings: the foreign thread got its own.
    assert recorder.stats()["rings"] == 2


def test_ring_overwrite_accounting():
    prev = obs.get_recorder()
    rec = Recorder(ring_spans=8)
    obs.install(rec)
    try:
        with obs.request_context("req-r"):
            for k in range(20):
                with obs.span(f"s{k}"):
                    pass
        (ring,) = rec._all_rings()
        assert ring.total == 20
        assert len(ring.snapshot()) == 8
        assert ring.dropped == 12
        # The ring keeps the newest spans in order.
        names = [s.name for s in ring.snapshot()]
        assert names == [f"s{k}" for k in range(12, 20)]
    finally:
        obs.install(prev)


def test_spans_for_includes_linked_launches(recorder):
    with obs.request_context("req-a"):
        with obs.span("work") as work:
            pass
    with obs.span("device.launch", ctx=None,
                  links=[("req-a", work.span_id)], occupancy=2):
        pass
    mine = recorder.spans_for("req-a")
    assert {s["name"] for s in mine} == {"work", "device.launch"}
    assert recorder.spans_for("req-zzz") == []


# --- flight recorder ------------------------------------------------------

def test_flight_dump_and_rate_limit(recorder):
    with obs.request_context("req-f"):
        with obs.span("a"):
            pass
    entry = recorder.flight.dump("test-reason", request_id="req-f")
    assert entry is not None
    assert entry["reason"] == "test-reason"
    assert entry["n_spans"] == len(entry["spans"]) == 1
    # Within the rate window, a non-forced dump is suppressed...
    assert recorder.flight.dump("again") is None
    assert recorder.flight.suppressed == 1
    # ...but force always dumps.
    assert recorder.flight.dump("forced", force=True) is not None
    report = recorder.flight.report()
    assert report["enabled"] is True
    assert [d["reason"] for d in report["dumps"]] == ["test-reason",
                                                      "forced"]
    assert recorder.flight.get(entry["seq"])["spans"] == entry["spans"]
    assert recorder.flight.get(999) is None
    json.dumps(report)          # JSON-safe end to end


def test_flight_dump_counters_reach_metrics_sink(recorder):
    sink = Metrics()
    recorder.set_metrics_sink(sink)
    recorder.flight.dump("r1", force=True)
    recorder.flight.dump("r2")
    counters = sink.report()["counters"]
    assert counters["obs.flight_dumps"] == 1
    assert counters["obs.flight_dumps_suppressed"] == 1


# --- Chrome-trace export --------------------------------------------------

def _check_chrome_trace(doc):
    """Structural contract chrome://tracing / Perfetto accept."""
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["name"], str)
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert isinstance(ev["args"], dict)
    json.loads(json.dumps(doc))


def test_chrome_trace_export(recorder):
    with obs.request_context("req-x"):
        with obs.span("http.get_image", method="GET"):
            with obs.span("decode.read"):
                pass
    doc = obs.chrome_trace("req-x")
    _check_chrome_trace(doc)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"http.get_image", "decode.read"}
    for e in xs:
        assert e["args"]["request_id"] == "req-x"
    # Unknown request: valid doc, no events.
    assert obs.chrome_trace("nope")["traceEvents"] == []


def test_sample_trace_cli(tmp_path, no_recorder):
    from bucketeer_tpu.obs.__main__ import main

    out = tmp_path / "trace.json"
    assert main(["--synthetic", str(out)]) == 0
    doc = json.loads(out.read_text())
    _check_chrome_trace(doc)
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "http.getImage" in names
    assert obs.get_recorder() is None      # CLI restored the global


# --- log correlation ------------------------------------------------------

def test_log_records_carry_request_id(recorder, caplog):
    logctx.install()
    try:
        log = logging.getLogger("obs-test")
        with caplog.at_level(logging.INFO, logger="obs-test"):
            with obs.request_context("req-log"):
                log.info("inside")
            log.info("outside")
        by_msg = {r.message: r for r in caplog.records}
        assert by_msg["inside"].request_id == "req-log"
        assert by_msg["outside"].request_id == "-"
    finally:
        logctx.uninstall()


# --- SLO watchdog ---------------------------------------------------------

def test_slo_parse_and_thresholds():
    w = obs.SloWatchdog.parse("default=500,getImage=250,bogus=x")
    assert w.threshold_ms("getImage") == 250
    assert w.threshold_ms("loadImage") == 500
    assert w.active
    assert obs.SloWatchdog.parse("") .active is False
    assert obs.SloWatchdog.parse("750").threshold_ms("any") == 750


def test_slo_camelcase_operation_ids():
    """Operators write OpenAPI operationIds (``postBatches=800``); the
    ``http.*`` stages are labeled with snake_case handler names. Both
    spellings must find the same budget, whichever configured it."""
    w = obs.SloWatchdog.parse("postBatches=800,get_batch=250")
    assert w.threshold_ms("post_batches") == 800
    assert w.threshold_ms("postBatches") == 800
    assert w.threshold_ms("get_batch") == 250
    assert w.threshold_ms("getBatch") == 250
    assert w.report() == {"get_batch_ms": 250.0, "post_batches_ms": 800.0}


def test_slo_breach_counts_and_dumps_flight(recorder):
    sink = Metrics()
    watchdog = obs.SloWatchdog.parse("getImage=10", sink=sink,
                                     flight=recorder.flight)
    assert watchdog.observe("getImage", 0.005, "fast") is False
    assert watchdog.observe("getImage", 0.5, "slow-req") is True
    counters = sink.report()["counters"]
    assert counters["slo.breaches"] == 1
    assert counters["slo.breach.getImage"] == 1
    dumps = recorder.flight.report()["dumps"]
    assert dumps and dumps[-1]["reason"] == "slo-breach:getImage"
    assert dumps[-1]["request_id"] == "slow-req"
    # Unknown endpoint with no default: never a breach.
    assert watchdog.observe("other", 99.0) is False


# --- modeled launch cost --------------------------------------------------

def test_modeled_launch_seconds_from_manifest():
    obs_cost.reset_cache()
    modeled = obs_cost.modeled_launch_seconds(2)
    assert modeled is not None, "repo manifest should provide a model"
    seconds, source = modeled
    assert seconds > 0
    assert source.startswith("frontend.rows/")
    # Linear bucket scaling: 4x the tiles ~ 2x the 2-tile estimate
    # when the nearest bucket stays the same family.
    more, _ = obs_cost.modeled_launch_seconds(8)
    assert more > seconds
    assert obs_cost.modeled_launch_seconds(0) is None


# --- histogram math -------------------------------------------------------

def test_latency_hist_percentiles_track_exact():
    import random

    h = LatencyHist()
    rng = random.Random(7)
    vals = [rng.lognormvariate(-3.0, 1.0) for _ in range(4000)]
    for v in vals:
        h.observe(v)
    vals.sort()
    for q in (0.5, 0.95, 0.99):
        exact = vals[min(len(vals) - 1, int(q * len(vals)))]
        approx = h.percentile(q)
        # One quarter-octave bucket of quantization error, both ways.
        assert exact / 1.25 <= approx <= exact * 1.25, (q, exact, approx)
    assert h.total == 4000
    assert h.sum == pytest.approx(sum(vals))


def test_latency_hist_edges():
    h = LatencyHist()
    h.observe(0.0)                      # underflow
    h.observe(1e9)                      # overflow
    assert h.counts[0] == 1
    assert h.counts[-1] == 1
    assert h.percentile(0.0) > 0
    assert math.isfinite(h.percentile(1.0))
    assert LatencyHist.upper_bound(LatencyHist.N + 1) == math.inf


# --- Prometheus exposition ------------------------------------------------

_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_LABEL = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"$')


def parse_prometheus(text):
    """Minimal Prometheus text-format checker: every non-comment line
    is ``name{labels} value``; HELP/TYPE comments well-formed; returns
    [(name, {labels}, value)]."""
    samples = []
    typed = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            assert parts[0] == "#" and parts[1] in ("HELP", "TYPE"), line
            if parts[1] == "TYPE":
                assert parts[3].split()[0] in (
                    "counter", "gauge", "histogram", "summary"), line
                typed.add(parts[2])
            continue
        m = _LINE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        name, _, labels_raw, value = m.groups()
        labels = {}
        if labels_raw:
            for pair in labels_raw.split(","):
                lm = _LABEL.match(pair)
                assert lm, f"malformed label in: {line!r}"
                labels[lm.group(1)] = lm.group(2)
        if value != "+Inf":
            float(value)
        samples.append((name, labels, value))
    return samples, typed


def test_prometheus_round_trip():
    m = Metrics()
    m.record("encode.queue_wait", 0.004)
    m.record("encode.queue_wait", 0.012)
    m.record("http.get_image", 0.120, pixels=1000)
    m.count("encode.device_launches", 3)
    m.observe("encode.batch_occupancy", 2)
    m.record_overlap("encode", 0.1, 0.2, 0.25)
    text = m.prometheus()
    samples, typed = parse_prometheus(text)
    assert "bucketeer_stage_seconds" in typed
    assert "bucketeer_counter_total" in typed

    def series(metric, **labels):
        return [(la, v) for (n, la, v) in samples if n == metric
                and all(la.get(k) == val for k, val in labels.items())]

    # Histogram contract per series: cumulative buckets are
    # monotonically nondecreasing in le, +Inf equals _count, _sum is
    # present.
    for stage, count in (("encode.queue_wait", 2),
                         ("http.get_image", 1)):
        buckets = series("bucketeer_stage_seconds_bucket", stage=stage)
        assert buckets, text
        les = []
        counts = []
        for la, v in buckets:
            les.append(math.inf if la["le"] == "+Inf"
                       else float(la["le"]))
            counts.append(int(v))
        assert les == sorted(les)
        assert counts == sorted(counts)
        assert les[-1] == math.inf and counts[-1] == count
        (_, total) = series("bucketeer_stage_seconds_count",
                            stage=stage)[0]
        assert int(total) == count
        assert series("bucketeer_stage_seconds_sum", stage=stage)
    assert series("bucketeer_counter_total",
                  name="encode.device_launches") == [
        ({"name": "encode.device_launches"}, "3")]
    assert series("bucketeer_value_bucket",
                  name="encode.batch_occupancy")
    assert series("bucketeer_overlap_seconds", stage="encode",
                  segment="saved")


def test_metrics_report_has_percentile_keys():
    m = Metrics()
    for v in (0.01, 0.02, 0.04):
        m.record("stage", v)
        m.observe("val", v * 100)
    rep = m.report()
    st = rep["stages"]["stage"]
    assert st["p50_ms"] <= st["p95_ms"] <= st["p99_ms"]
    assert 15 <= st["p50_ms"] <= 30
    vals = rep["values"]["val"]
    assert vals["p50"] <= vals["p95"] <= vals["p99"]
