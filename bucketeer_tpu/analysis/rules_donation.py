"""missing-donation: jitted entry points with no donation decision.

The codec's jitted programs consume large freshly-staged host arrays —
a tile batch, a half-magnitude coefficient batch — that no caller reads
after the launch. Without ``donate_argnums`` XLA must keep the input
buffer alive alongside the output, doubling (or worse) the HBM
high-water mark of every launch; with it the input aliases into the
output. A jit call in the hot modules with *no* donation spec is either
an oversight or needs an explicit whitelist entry explaining why
aliasing would be wrong.

This AST rule enforces that a *decision* is on record: every jit call
in scope must either pass ``donate_argnums``/``donate_argnames`` (the
``*_program`` seams do, with an explicit — possibly empty — spec and
the reason in their docstring) or be whitelisted here. Whether a
recorded donation actually *takes effect* is the compiled-artifact
audit's job (analysis/deviceaudit.py): it lowers each program with
donation forced and checks the ``tf.aliasing_output`` attribute, which
is how the front-end and decode-inverse donations PR 6 requested were
discovered to be silently dropped — no output aval matches the donated
input (the color axis moves between input and output), so XLA cannot
alias. Those specs are now explicitly empty at the seams, with the
audit guarding both directions (a declared donation that stops
aliasing, and an "unusable" claim that becomes aliasable).

Scope: the device entry points of the encode front-end
(``codec/frontend.py``) and the decode back half
(``codec/decode/device.py``) — the two modules whose array operands are
tile-sized. Whitelisted: ``gather`` (the chunked payload gather re-reads
the same device ``rows`` buffer across successive dispatches; donating
it would free a buffer later chunks still read).
"""
from __future__ import annotations

import ast

from .findings import ERROR, Finding
from .rules_jax import _attr_root, _unwrap_jit_target, enclosing_functions

MISSING_DONATION = "missing-donation"

# Module suffixes whose jit roots stage tile-sized arrays per launch.
SCOPES = ("codec/frontend.py", "codec/decode/device.py")

# Jitted functions where donation is *unsafe*, with the reason on
# record: the buffer outlives the launch.
WHITELIST = {
    "gather",        # frontend._compiled_gather: `rows` is shared by
                     # every chunk of one payload fetch
}

DONATE_KWARGS = ("donate_argnums", "donate_argnames")


def run(project) -> list:
    findings: list = []
    for mod in project.modules:
        if not mod.relpath.endswith(SCOPES):
            continue
        scopes = enclosing_functions(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            root, chain = _attr_root(node.func)
            leaf = chain[-1] if chain else root
            is_jit = ((root in mod.jax_aliases
                       and leaf in ("jit", "pmap"))
                      or root in mod.jit_names)
            if not is_jit:
                continue
            name, _ = _unwrap_jit_target(mod, node.args[0], project,
                                         scopes.get(id(node)))
            if name in WHITELIST:
                continue
            if any(kw.arg in DONATE_KWARGS for kw in node.keywords):
                continue
            findings.append(Finding(
                MISSING_DONATION, mod.relpath, node.lineno,
                f"jit of {name or '<anonymous>'} records no donation "
                "decision: the staged input buffer stays live beside "
                "the output for the whole launch. Pass donate_argnums "
                "(pipeline.donate_argnums_if_supported gates CPU; an "
                "explicit empty spec with the reason documented also "
                "counts), or whitelist the function in rules_donation "
                "with the reason aliasing is unsafe",
                ERROR, mod.source_line(node.lineno)))
    return findings
