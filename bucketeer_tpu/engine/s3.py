"""S3 upload layer: clients + the uploader worker.

Port of the reference's S3BucketVerticle and its vertx-super-s3 client
(reference: verticles/S3BucketVerticle.java:44-336):

- global in-flight cap — increments the shared ``s3-request-count``
  counter and replies ``retry`` when over ``s3.max.requests`` (:88-108);
- streams the file with ``image-id`` / ``job-name`` user metadata
  (:141-155);
- success: records the upload, deletes derivative source files, replies
  ``success`` (:168-175,286-303);
- errors: bounded per-image retry counter (``s3.max.retries``) then a
  failure reply (:185-194,219-277). The reference retried 5xx forever;
  here 5xx/timeouts draw from the *same* bounded budget and trip the
  per-target circuit breaker (engine/retry.py) — while it is open the
  worker fast-fails with ``retry`` without touching the dead target,
  and the half-open window admits one probe;
- always decrements the in-flight counter (:312-336).

Clients: :class:`FakeS3Client` stores objects in a local directory (the
reference's test seam is a fake uploader verticle, reference:
verticles/FakeS3BucketVerticle.java:17-28 — ours still exercises the
real worker logic); :class:`HttpS3Client` speaks real SigV4 REST over
aiohttp (replacement for vertx-super-s3).
"""
from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import logging
import os
import shutil
import urllib.parse
from dataclasses import dataclass

from .. import constants as c
from .. import obs
from .. import op
from . import faults
from .bus import MessageBus, Reply
from .retry import CircuitBreaker
from .store import Counters, UploadsMap

LOG = logging.getLogger(__name__)

S3_UPLOADER = "s3-uploader"         # bus address (reference: verticle name)


class S3Error(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        self.status = status
        super().__init__(f"S3 {status}: {message}")


class FakeS3Client:
    """Local-directory object store for tests and no-cloud dev mode."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.metadata: dict[str, dict] = {}
        self.fail_next: list[int] = []   # fault injection: status codes

    async def put(self, bucket: str, key: str, file_path: str,
                  metadata: dict | None = None) -> None:
        if self.fail_next:
            raise S3Error(self.fail_next.pop(0), "injected failure")
        dest = os.path.join(self.root, bucket, key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        await asyncio.to_thread(shutil.copyfile, file_path, dest)
        self.metadata[f"{bucket}/{key}"] = dict(metadata or {})

    async def close(self) -> None:
        pass

    # test helpers
    def exists(self, bucket: str, key: str) -> bool:
        return os.path.exists(os.path.join(self.root, bucket, key))

    def size(self, bucket: str, key: str) -> int:
        return os.path.getsize(os.path.join(self.root, bucket, key))


class HttpS3Client:
    """Minimal async S3 REST client with AWS SigV4 signing (PUT object).

    Replaces the reference's vertx-super-s3 dependency; endpoint override
    supports S3-compatible stores (MinIO, LocalStack).
    """

    def __init__(self, access_key: str, secret_key: str, region: str,
                 endpoint: str | None = None) -> None:
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region or "us-east-1"
        self.endpoint = endpoint
        self._session = None

    def _url(self, bucket: str, key: str) -> str:
        quoted = urllib.parse.quote(key, safe="/")
        if self.endpoint:
            return f"{self.endpoint.rstrip('/')}/{bucket}/{quoted}"
        return f"https://{bucket}.s3.{self.region}.amazonaws.com/{quoted}"

    def _sign(self, method: str, url: str, headers: dict,
              payload_hash: str) -> dict:
        """SigV4 header signing (AWS General Reference, Signature V4)."""
        parts = urllib.parse.urlsplit(url)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = dict(headers)
        headers["host"] = parts.netloc
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash

        signed = sorted(h.lower() for h in headers)
        canonical_headers = "".join(
            f"{h}:{str(headers[next(k for k in headers if k.lower() == h)]).strip()}\n"
            for h in signed)
        signed_list = ";".join(signed)
        # parts.path is already single-percent-encoded by _url (quote with
        # safe="/"), which is exactly the canonical-URI form SigV4 wants;
        # re-quoting would double-encode ('%3A' -> '%253A') and break the
        # signature for every ARK-derived key.
        canonical = "\n".join([
            method, parts.path,
            parts.query, canonical_headers, signed_list, payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def hmac_sha(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hmac_sha(f"AWS4{self.secret_key}".encode(), datestamp)
        k = hmac_sha(k, self.region)
        k = hmac_sha(k, "s3")
        k = hmac_sha(k, "aws4_request")
        signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_list}, Signature={signature}")
        del headers["host"]   # aiohttp sets it
        return headers

    CHUNK = 1 << 20

    async def put(self, bucket: str, key: str, file_path: str,
                  metadata: dict | None = None) -> None:
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        # Stream the object: one chunked pass to hash, one to send, so a
        # 300 MB source never lives in RAM (reference streams too,
        # S3BucketVerticle.java:141-155).
        size, payload_hash = await asyncio.to_thread(
            self._hash_file, file_path)
        url = self._url(bucket, key)
        headers = {f"x-amz-meta-{k}": str(v)
                   for k, v in (metadata or {}).items()}
        headers["content-length"] = str(size)
        headers = self._sign("PUT", url, headers, payload_hash)

        async def body():
            with open(file_path, "rb") as fh:
                # Reads go through a thread so a slow disk/NFS never
                # stalls the event loop mid-upload.
                while chunk := await asyncio.to_thread(fh.read, self.CHUNK):
                    yield chunk

        # encoded=True keeps yarl from re-quoting the path (it would turn
        # %3A back into ':'), so the wire path is byte-identical to the
        # canonical URI we signed.
        import yarl
        async with self._session.put(yarl.URL(url, encoded=True),
                                     data=body(), headers=headers) as resp:
            if resp.status != 200:
                raise S3Error(resp.status, (await resp.text())[:500])

    @classmethod
    def _hash_file(cls, path: str) -> tuple[int, str]:
        digest = hashlib.sha256()
        size = 0
        with open(path, "rb") as fh:
            while chunk := fh.read(cls.CHUNK):
                digest.update(chunk)
                size += len(chunk)
        return size, digest.hexdigest()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


@dataclass
class S3UploaderConfig:
    bucket: str
    max_requests: int = 20          # reference: s3.max.requests
    max_retries: int = 30           # reference: s3.max.retries
    requeue_delay: float = 1.0      # reference: s3.requeue.delay (seconds)


class S3UploadWorker:
    """The uploader consumer; register on the bus with N instances
    (reference: MainVerticle.java:233-242 deploys instances x threads)."""

    def __init__(self, client, config: S3UploaderConfig,
                 counters: Counters, uploads: UploadsMap,
                 breaker: CircuitBreaker | None = None) -> None:
        self.client = client
        self.config = config
        self.counters = counters
        self.uploads = uploads
        self.breaker = breaker

    def register(self, bus: MessageBus, instances: int = 1) -> None:
        bus.consumer(S3_UPLOADER, self.handle, instances=instances)

    @staticmethod
    def _retryable_status(exc: Exception) -> int | None:
        """5xx-class status when the failure is the *target's* fault
        (server trouble or a timeout) — these trip the breaker; client
        errors (4xx, local OSError) don't."""
        if isinstance(exc, S3Error):
            return exc.status if 500 <= exc.status < 600 else None
        if isinstance(exc, (asyncio.TimeoutError, TimeoutError)):
            return 504
        return None

    async def handle(self, message: dict) -> Reply:
        # Trace context rides the message (consumers run in fresh
        # tasks); the store op shows in the originating request's tree.
        with obs.request_context(message.get(c.REQUEST_ID)):
            return await self._handle_put(message)

    async def _handle_put(self, message: dict) -> Reply:
        image_id = message[c.IMAGE_ID]
        file_path = message[c.FILE_PATH]
        job_name = message.get(c.JOB_NAME)
        bucket = message.get(c.S3_BUCKET) or self.config.bucket
        derivative = bool(message.get(c.DERIVATIVE_IMAGE))

        # Backpressure first: cap concurrent in-flight puts (reference:
        # S3BucketVerticle.java:88-108). Checked *before* the breaker
        # so a shed message can never consume the half-open probe slot.
        in_flight = self.counters.increment(c.S3_REQUEST_COUNT)
        if in_flight > self.config.max_requests:
            self.counters.decrement(c.S3_REQUEST_COUNT)
            return Reply.retry()

        # Circuit open: fast-fail without touching the dead target —
        # allow() grants exactly one probe once the half-open window is
        # due (engine/retry.py).
        if self.breaker is not None and not self.breaker.allow():
            self.counters.decrement(c.S3_REQUEST_COUNT)
            return Reply.retry()

        metadata = {c.IMAGE_ID: image_id}
        if job_name:
            metadata[c.JOB_NAME] = job_name
        try:
            faults.point("s3.put", image_id=image_id, bucket=bucket)
            with obs.span("s3.put", image_id=image_id, bucket=bucket):
                await self.client.put(bucket, image_id, file_path,
                                      metadata)
        except Exception as exc:
            status = self._retryable_status(exc)
            if self.breaker is not None:
                if status is not None:
                    self.breaker.record_failure()
                elif isinstance(exc, S3Error):
                    # A 4xx is the request's fault, not the target's —
                    # the target *answered*, so the circuit stays
                    # healthy.
                    self.breaker.record_success()
                else:
                    # Local errors (OSError on the source file, ...)
                    # never contacted the target: no outcome for the
                    # breaker — but if this call held the half-open
                    # probe slot, hand it back or the breaker wedges
                    # with a phantom probe forever.
                    self.breaker.release_probe()
            if status is None and isinstance(exc, S3Error):
                status = exc.status
            return self._failure_reply(image_id, status or 0, str(exc))
        finally:
            # Always release the in-flight slot (reference: :312-336).
            self.counters.decrement(c.S3_REQUEST_COUNT)

        if self.breaker is not None:
            self.breaker.record_success()
        self.uploads.record(image_id, {
            c.FILE_PATH: file_path, c.JOB_NAME: job_name, "bucket": bucket})
        self.counters.reset(f"retries-{image_id}")
        if derivative:
            # The local derivative was an intermediate; clean it up
            # (reference: S3BucketVerticle.java:286-303).
            try:
                os.remove(file_path)
            except OSError:
                LOG.warning("could not delete derivative %s", file_path)
        return Reply.success({c.IMAGE_ID: image_id})

    def _failure_reply(self, image_id: str, status: int,
                       message: str) -> Reply:
        # One bounded budget for every failure class. The reference
        # retried 5xx forever (:185-194); a permanent outage now ends
        # in a failure reply (dead-lettered by the sender) after
        # ``s3.max.retries`` attempts instead of spinning.
        key = f"retries-{image_id}"
        attempts = self.counters.increment(key)
        if attempts <= self.config.max_retries:
            LOG.warning("S3 %s for %s (attempt %d/%d): %s",
                        status or "error", image_id, attempts,
                        self.config.max_retries, message)
            return Reply.retry()
        self.counters.reset(key)
        LOG.error("S3 upload failed permanently for %s: %s", image_id,
                  message)
        return Reply.failure(status or 500, message)


def make_client(config) -> object:
    """Build the S3 client from config: real SigV4 client when
    credentials are configured, local fake store otherwise (dev mode)."""
    from .. import config as cfg

    access = config.get_str(cfg.S3_ACCESS_KEY)
    secret = config.get_str(cfg.S3_SECRET_KEY)
    if access and secret and "YOUR_" not in access.upper():
        return HttpS3Client(access, secret,
                            config.get_str(cfg.S3_REGION) or "us-east-1",
                            config.get_str(cfg.S3_ENDPOINT))
    root = os.path.join(
        os.environ.get("BUCKETEER_TMPDIR") or "/tmp", "bucketeer-fake-s3")
    os.makedirs(root, exist_ok=True)
    LOG.info("no S3 credentials; using fake local store at %s", root)
    return FakeS3Client(root)
