"""Row-sharded multi-level 2-D DWT with halo exchange over ICI.

This is the spatial/context-parallel analog for this workload (SURVEY.md
§5 "long-context"): where the reference routes over-sized images *whole*
to a dedicated second service instance
(reference: verticles/LargeImageVerticle.java:72-97,
handlers/LoadCsvHandler.java:270-281), the TPU design decomposes — one
huge tile's rows are sharded across the ``tile`` mesh axis and the
vertical lifting passes exchange 4-row halos with row-neighbor shards via
``lax.ppermute`` (ring pattern, ICI traffic only; the horizontal pass is
fully local).

Correctness argument: every lifting step reads ±1 row of the other
parity, and valid data shrinks by one row per step from each halo edge;
4 halo rows cover the 4-step 9/7 schedule (2-step 5/3 a fortiori), so
after cropping the halos every local row equals the unsharded transform.
Global symmetric boundary extension is reproduced at the outer shards by
reflecting their own edge rows. Each shard keeps an even number of rows
at every level, so the even/odd polyphase split — and therefore the
subband row ordering — is shard-local with no resharding between levels.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, PartitionSpec as P

from ..analysis.contracts import contract
from ..codec.dwt import (ALPHA, BETA, DELTA, GAMMA, K_HI, K_LO,
                         _fwd53_last, _fwd97_last)
from .compat import SM_NO_CHECK, shard_map
from .mesh import TILE_AXIS

HALO = 4  # covers the 4-step 9/7 lifting support


def _halo_pad(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Pad local rows (..., Hs, W) with HALO rows from row-neighbor
    shards; outer shards reflect their own boundary (symmetric
    extension)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    up_perm = [(i, i + 1) for i in range(n - 1)]      # recv from idx-1
    down_perm = [(i + 1, i) for i in range(n - 1)]    # recv from idx+1
    up = jax.lax.ppermute(x[..., -HALO:, :], axis_name, up_perm)
    down = jax.lax.ppermute(x[..., :HALO, :], axis_name, down_perm)
    top_reflect = jnp.flip(x[..., 1:HALO + 1, :], axis=-2)
    bot_reflect = jnp.flip(x[..., -HALO - 1:-1, :], axis=-2)
    up = jnp.where(idx == 0, top_reflect, up)
    down = jnp.where(idx == n - 1, bot_reflect, down)
    return jnp.concatenate([up, x, down], axis=-2)


def _vlift_fwd(xp: jnp.ndarray, reversible: bool) -> jnp.ndarray:
    """Forward vertical lifting over a halo-padded block. Row parity of
    the padded local index equals global parity (shard heights and HALO
    are even)."""
    rows = np.arange(xp.shape[-2])
    even = jnp.asarray(rows % 2 == 0)[:, None]
    odd = jnp.asarray(rows % 2 == 1)[:, None]

    def nbr(y):
        return jnp.roll(y, 1, axis=-2) + jnp.roll(y, -1, axis=-2)

    if reversible:
        xp = jnp.where(odd, xp - (nbr(xp) >> 1), xp)
        xp = jnp.where(even, xp + ((nbr(xp) + 2) >> 2), xp)
    else:
        xp = xp.astype(jnp.float32)
        xp = jnp.where(odd, xp + ALPHA * nbr(xp), xp)
        xp = jnp.where(even, xp + BETA * nbr(xp), xp)
        xp = jnp.where(odd, xp + GAMMA * nbr(xp), xp)
        xp = jnp.where(even, xp + DELTA * nbr(xp), xp)
    return xp


def _local_dwt(levels: int, reversible: bool, axis_name: str,
               x: jnp.ndarray):
    """shard_map body: multi-level DWT of this shard's rows."""
    fwd = _fwd53_last if reversible else _fwd97_last
    ll = x if reversible else x.astype(jnp.float32)
    bands = []
    for _ in range(levels):
        hs = ll.shape[-2]
        if hs % 2 or hs < HALO + 1:
            raise ValueError(
                f"shard rows {hs} must be even and > {HALO} at every "
                f"level; pick tile_parallel/levels so H/(shards*2^levels) "
                f"stays >= {HALO + 1}")
        xp = _vlift_fwd(_halo_pad(ll, axis_name), reversible)
        core = xp[..., HALO:-HALO, :]
        v_lo, v_hi = core[..., 0::2, :], core[..., 1::2, :]
        if not reversible:
            v_lo, v_hi = K_LO * v_lo, K_HI * v_hi
        ll, hl = fwd(v_lo)
        lh, hh = fwd(v_hi)
        bands.append({"HL": hl, "LH": lh, "HH": hh})
    return ll, bands


def can_row_shard(h: int, levels: int, n_shards: int) -> bool:
    """True when ``h`` rows split over ``n_shards`` satisfy the sharded
    DWT's invariants at every level: each shard keeps an even row count
    (polyphase split stays shard-local) and more rows than the halo."""
    if n_shards < 2 or h % n_shards:
        return False
    per = h // n_shards
    return per % (1 << levels) == 0 and (per >> levels) >= 3


def sharded_dwt_program(levels: int, reversible: bool, mesh: Mesh,
                        ndim: int = 2):
    """(shard_map-wrapped fn, row PartitionSpec) for the multi-level
    DWT at ``ndim`` input rank — the construction
    :func:`sharded_dwt2d_forward` runs, shared with the graftmesh
    registry (analysis/graftmesh.py), which lowers it under the forced
    8-device host mesh and audits its halo-exchange collectives."""
    row = tuple(None for _ in range(ndim - 2)) + (TILE_AXIS, None)
    spec = P(*row)
    fn = shard_map(partial(_local_dwt, levels, reversible, TILE_AXIS),
                   mesh=mesh, in_specs=(spec,), out_specs=spec,
                   **SM_NO_CHECK)
    return fn, spec


@contract(shapes={"x": [("H", "W"), ("C", "H", "W")]},
          dtypes={"x": "number"})
def sharded_dwt2d_forward(x: jnp.ndarray, levels: int, reversible: bool,
                          mesh: Mesh):
    """Multi-level forward DWT of one giant tile, rows sharded over the
    ``tile`` mesh axis.

    x: (H, W) or (C, H, W) with H divisible by (tile-axis size × 2^levels).
    Returns (ll, bands) row-sharded identically to
    :func:`bucketeer_tpu.codec.dwt.dwt2d_forward`'s layout.
    """
    fn, _ = sharded_dwt_program(levels, reversible, mesh, x.ndim)
    return fn(x)


@contract(shapes={"tile": [("H", "W"), ("H", "W", "C")]},
          dtypes={"tile": "number"})
def sharded_transform_tile(plan, tile: np.ndarray, mesh: Mesh) -> np.ndarray:
    """The single-giant-tile encode transform, rows sharded over the
    ``tile`` mesh axis: level shift + RCT/ICT (elementwise, runs sharded
    for free) + :func:`sharded_dwt2d_forward` + quantization. Produces
    exactly what :func:`bucketeer_tpu.codec.pipeline.run_tiles` returns
    for a batch of one — a (C, H, W) int32 Mallat plane on host — so the
    encoder's host Tier-1 path consumes it unchanged.

    This is the large-image decompose route (SURVEY.md §5): where the
    reference ships oversized scans whole to a second service instance
    (verticles/LargeImageVerticle.java:72-97), the mesh splits one
    tile's rows across devices and exchanges DWT halos over ICI.
    Caller must check :func:`can_row_shard` first.
    """
    from ..codec.pipeline import _step_map
    from ..codec.quant import quantize_fp
    from ..codec.transforms import (ict_forward, level_shift_forward,
                                    rct_forward)

    if not can_row_shard(plan.tile_h, plan.levels,
                         mesh.shape[TILE_AXIS]):
        raise ValueError(
            f"{plan.tile_h} rows cannot shard over "
            f"{mesh.shape[TILE_AXIS]} devices at {plan.levels} levels; "
            "check can_row_shard() before routing")
    x = jnp.asarray(tile)
    if x.ndim == 2:
        x = x[..., None]
    x = level_shift_forward(x.astype(jnp.int32), plan.bitdepth)
    if plan.used_mct:
        ycc = rct_forward(x) if plan.lossless else ict_forward(
            x.astype(jnp.float32))
    else:
        ycc = x if plan.lossless else x.astype(jnp.float32)
    planes = jnp.moveaxis(ycc, -1, 0)            # (C, H, W)
    ll, bands = sharded_dwt2d_forward(planes, plan.levels,
                                      plan.lossless, mesh)
    # Assemble the Mallat layout on host (the coefficient planes come
    # back for host block slicing anyway on this path).
    out = np.asarray(jax.device_get(ll))
    for band in reversed([{k: np.asarray(jax.device_get(v))
                           for k, v in b.items()} for b in bands]):
        top = np.concatenate([out, band["HL"]], axis=-1)
        bot = np.concatenate([band["LH"], band["HH"]], axis=-1)
        out = np.concatenate([top, bot], axis=-2)
    if plan.lossless:
        return out.astype(np.int32)
    q = quantize_fp(jnp.asarray(out), jnp.asarray(_step_map(plan)))
    return np.asarray(jax.device_get(q))
