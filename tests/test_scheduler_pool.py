"""Device-pool data plane (engine/scheduler.py, ISSUE 17): the
multi-device scheduler must spread launches across pool workers with
exact per-device attribution, stay byte-identical to the serialized
single-device path for every job kind (encode / decode / tensor), size
itself from config/env, keep admission control intact with N workers,
and map pipeline stages onto disjoint device subsets via the
bi-criteria splitter. Runs on the conftest-forced 8-device CPU mesh."""
import threading
import time

import numpy as np
import pytest

from bucketeer_tpu.codec import encoder
from bucketeer_tpu.codec.decode.decoder import decode
from bucketeer_tpu.codec.encoder import EncodeParams
from bucketeer_tpu.engine.scheduler import (DeadlineExceeded,
                                            EncodeScheduler, QueueFull)
from bucketeer_tpu.server.metrics import Metrics
from bucketeer_tpu.tensor import decode_tensor, encode_tensor

JOIN_S = 10


def _images(n, size, seed):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            for _ in range(n)]


def _run_concurrent(fns):
    """Run the thunks on a shared barrier; return (results, errors)."""
    outs = [None] * len(fns)
    errs = [None] * len(fns)
    barrier = threading.Barrier(len(fns))

    def client(i):
        barrier.wait()
        try:
            outs[i] = fns[i]()
        except BaseException as exc:          # surfaced to the test
            errs[i] = exc

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(fns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "pool client hung"
    return outs, errs


def _per_device(counters, family):
    return {k: v for k, v in counters.items()
            if k.startswith(f"{family}.device_launches.d")}


# --- launch distribution and attribution ------------------------------

def test_concurrent_launches_spread_over_distinct_devices():
    """Two overlapping incompatible launches land on two distinct pool
    workers (the gate makes the overlap deterministic: the first launch
    cannot finish until the second has started), and the per-device
    counters attribute each to its real worker."""
    ev = [threading.Event(), threading.Event()]
    seen = []
    lock = threading.Lock()

    def gated_launch(plan, tiles, mode="rows"):
        with lock:
            i = len(seen)
            seen.append(plan)
        ev[i].set()
        assert ev[1 - i].wait(timeout=JOIN_S), "peer launch never ran"
        return ("pending", plan)

    sched = EncodeScheduler(queue_depth=16, max_concurrent=4,
                            pool_size=2, window_s=0, devices=4)
    sched.launch_fn = gated_launch
    sink = Metrics()
    sched.set_metrics_sink(sink)
    try:
        outs, errs = _run_concurrent([
            lambda: sched.dispatch_frontend(
                ("p1",), np.zeros((1, 2, 2, 3), np.uint8)),
            lambda: sched.dispatch_frontend(
                ("p2",), np.zeros((1, 2, 2, 3), np.uint8))])
        assert errs == [None, None]
        assert sorted(o[1][0] for o in outs) == ["p1", "p2"]
        counters = sink.report()["counters"]
        per_dev = _per_device(counters, "encode")
        assert counters["encode.device_launches"] == 2
        assert len(per_dev) >= 2, per_dev       # >= 2 distinct devices
        assert sum(per_dev.values()) == 2
        rep = sched.pool_report()
        assert rep["devices"] == 4
        assert rep["device_queue_depth"] == 0
    finally:
        sched.close()


# --- byte-identity matrix on the 8-device mesh ------------------------

@pytest.fixture
def sched():
    # An explicit 2-device pool: conftest defaults the suite to one
    # device (each engaged device pays its own frontend recompile on
    # the CPU probe), so multi-device byte-identity opts in with the
    # smallest real pool.
    s = EncodeScheduler(queue_depth=16, max_concurrent=4, pool_size=2,
                        window_s=0.2, devices=2)
    yield s
    s.close()


def test_pool_encode_bytes_identical(sched):
    imgs = _images(4, 64, seed=21)
    params = EncodeParams(lossless=True, levels=3)
    serial = [encoder.encode_jp2(im, 8, params) for im in imgs]
    outs, errs = _run_concurrent(
        [lambda im=im: sched.encode_jp2(im, 8, params) for im in imgs])
    assert errs == [None] * 4
    assert outs == serial


def test_pool_decode_bytes_identical(sched):
    imgs = _images(3, 64, seed=22)
    params = EncodeParams(lossless=True, levels=2)
    blobs = [encoder.encode_jp2(im, 8, params) for im in imgs]
    serial = [decode(b) for b in blobs]
    outs, errs = _run_concurrent(
        [lambda b=b: sched.read(decode, b) for b in blobs])
    assert errs == [None] * 3
    for got, want in zip(outs, serial):
        assert np.array_equal(got, want)


@pytest.mark.slow
def test_pool_tensor_bytes_identical():
    # Slow-marked: a two-device pool compiles the device MQ chunk
    # program once per assigned device (~1 min each on the CPU probe);
    # the CI multichip job runs this file unfiltered. Small-magnitude
    # int8 keeps the sequential scans affordable (same trick as
    # test_tensor_codec).
    sched = EncodeScheduler(queue_depth=16, max_concurrent=4,
                            pool_size=2, window_s=0.2, devices=2)
    rng = np.random.default_rng(23)
    arrs = [rng.integers(-3, 4, size=(600,), dtype=np.int8)
            for _ in range(3)]
    try:
        serial = [encode_tensor(x, device="device") for x in arrs]
        outs, errs = _run_concurrent(
            [lambda x=x: sched.submit_tensor(encode_tensor, x,
                                             device="device")
             for x in arrs])
        assert errs == [None] * 3
        assert outs == serial
        for blob, x in zip(outs, arrs):
            assert np.array_equal(decode_tensor(blob), x)
    finally:
        sched.close()


@pytest.mark.slow
def test_pool_rate_targeted_cxd_bytes_identical(sched):
    """The fused-path corner of the matrix: rate-targeted encodes with
    the device CX/D scan, concurrent over the pool, byte-identical to
    the serialized baseline (compiles the device scan: slow-marked;
    the serving-stress CI job runs it)."""
    imgs = _images(3, 96, seed=24)
    params = EncodeParams(lossless=False, levels=3, base_delta=2.0,
                          rate=1.5, device_cxd=True)
    serial = [encoder.encode_jp2(im, 8, params) for im in imgs]
    outs, errs = _run_concurrent(
        [lambda im=im: sched.encode_jp2(im, 8, params) for im in imgs])
    assert errs == [None] * 3
    assert outs == serial


@pytest.mark.slow
def test_pipeline_auto_bytes_identical():
    """pipeline=auto with the fused device MQ path: front-end and
    Tier-1 stages run on disjoint device subsets, output byte-identical
    to the in-process single-device encoder."""
    sched = EncodeScheduler(queue_depth=16, max_concurrent=4,
                            pool_size=2, window_s=0.2, pipeline="auto")
    imgs = _images(3, 64, seed=25)
    params = EncodeParams(lossless=True, levels=2, device_cxd=True,
                          device_mq=True)
    try:
        serial = [encoder.encode_jp2(im, 8, params) for im in imgs]
        outs, errs = _run_concurrent(
            [lambda im=im: sched.encode_jp2(im, 8, params)
             for im in imgs])
        assert errs == [None] * 3
        assert outs == serial
        assert sched.stats()["pipeline_split"] is not None
    finally:
        sched.close()


# --- pipeline-stage mapping ------------------------------------------

def test_dispatch_t1_stages_onto_tier1_subset():
    """With pipeline=auto over a simulated 4-device pool, staged Tier-1
    closures run on pool workers from the Tier-1 subset only (worker
    index >= split), with per-device attribution."""
    sched = EncodeScheduler(queue_depth=16, max_concurrent=4,
                            pool_size=2, window_s=0, devices=4,
                            pipeline="auto", pipeline_split=2)
    sched.launch_fn = lambda plan, tiles, mode="rows": "pending"
    sink = Metrics()
    sched.set_metrics_sink(sink)
    try:
        outs, errs = _run_concurrent(
            [lambda i=i: sched.dispatch_t1(lambda p: ("ran", p), i)
             for i in range(4)])
        assert errs == [None] * 4
        assert sorted(outs) == [("ran", i) for i in range(4)]
        assert sched.stats()["pipeline_split"] == 2
        counters = sink.report()["counters"]
        per_dev = _per_device(counters, "t1")
        assert counters["t1.device_launches"] == 4
        assert sum(per_dev.values()) == 4
        # Disjoint subsets: Tier-1 work never lands on a front-end
        # worker [0, split).
        assert all(int(k.rsplit(".d", 1)[1]) >= 2 for k in per_dev), \
            per_dev
    finally:
        sched.close()


def test_dispatch_t1_pipeline_off_runs_inline():
    sched = EncodeScheduler(queue_depth=4, max_concurrent=2,
                            pool_size=1, window_s=0, devices=4)
    sched.launch_fn = lambda plan, tiles, mode="rows": "pending"
    sink = Metrics()
    sched.set_metrics_sink(sink)
    try:
        assert sched.dispatch_t1(lambda p: p + 1, 41) == 42
        counters = sink.report().get("counters", {})
        assert "t1.device_launches" not in counters
        assert sched.stats()["pipeline_split"] is None
    finally:
        sched.close()


def test_plan_split_override_model_and_fallback(monkeypatch):
    from bucketeer_tpu.obs import cost as obs_cost

    sched = EncodeScheduler(pipeline="auto", pipeline_split=3)
    try:
        assert sched._plan_split(8) == 3          # config override wins
        sched.pipeline_split = 0
        # Bi-criteria mapper on modeled costs: heavy Tier-1 stage pulls
        # the split toward more Tier-1 workers.
        monkeypatch.setattr(obs_cost, "modeled_stage_costs",
                            lambda: (3.0, 1.0))
        assert sched._plan_split(4) == 3
        monkeypatch.setattr(obs_cost, "modeled_stage_costs",
                            lambda: (1.0, 1.0))
        assert sched._plan_split(4) == 2
        # No model: even split.
        monkeypatch.setattr(obs_cost, "modeled_stage_costs",
                            lambda: None)
        assert sched._plan_split(8) == 4
    finally:
        sched.close()


def test_modeled_stage_costs_from_manifest():
    """The repo manifest + CPU machine model yield both stage costs
    (the mapper's inputs) as positive seconds."""
    from bucketeer_tpu.obs import cost as obs_cost

    costs = obs_cost.modeled_stage_costs()
    if costs is None:
        pytest.skip("no audit manifest/machine model available")
    ca, cb = costs
    assert ca > 0 and cb > 0


# --- pool sizing and config ------------------------------------------

def test_devices_env_and_ctor_sizing(monkeypatch):
    monkeypatch.setenv("BUCKETEER_SCHED_DEVICES", "3")
    sched = EncodeScheduler()
    sched.launch_fn = lambda plan, tiles, mode="rows": "pending"
    try:
        assert sched.devices == 3
        sched.dispatch_frontend(("p",), np.zeros((1, 2, 2, 3), np.uint8))
        assert sched.pool_report()["devices"] == 3
    finally:
        sched.close()
    explicit = EncodeScheduler(devices=2)
    try:
        assert explicit.devices == 2      # ctor beats env
    finally:
        explicit.close()


def test_devices_cap_clamps_to_available():
    sched = EncodeScheduler(devices=64)
    try:
        with sched._dq_cv:
            sched._ensure_devices_locked()
            assert len(sched._devices) == 8   # the forced host mesh
    finally:
        sched.close()


def test_invalid_pipeline_rejected():
    with pytest.raises(ValueError):
        EncodeScheduler(pipeline="sideways")
    sched = EncodeScheduler()
    try:
        with pytest.raises(ValueError):
            sched.configure(pipeline="sideways")
        sched.configure(pipeline="auto", devices=2, pipeline_split=1)
        assert (sched.pipeline, sched.devices,
                sched.pipeline_split) == ("auto", 2, 1)
    finally:
        sched.close()


# --- admission control with N workers ---------------------------------

def test_queue_full_and_deadline_with_pool_workers():
    """Admission stays bounded however many pool workers exist: with
    both slots held, a queued deadline expires typed
    (DeadlineExceeded) and the full queue rejects typed (QueueFull)."""
    sched = EncodeScheduler(queue_depth=3, max_concurrent=2,
                            pool_size=2, window_s=0, devices=4)
    sched.launch_fn = lambda plan, tiles, mode="rows": "pending"
    release = threading.Event()
    holding = [threading.Event(), threading.Event()]

    def hold(i):
        def body():
            holding[i].set()
            release.wait(timeout=JOIN_S)
        sched.submit(body)

    threads = [threading.Thread(target=hold, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    try:
        for h in holding:
            assert h.wait(timeout=JOIN_S)
        # Both slots busy, one admission slot free: a queued request's
        # deadline expires typed while it waits for a slot.
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            sched.submit(lambda: None, deadline_s=0.05)
        assert time.monotonic() - t0 < JOIN_S
        # Fill the last admission slot with a patient queued request,
        # then the next arrival bounces typed with a retry hint.
        queued = threading.Thread(target=lambda: sched.submit(lambda: None))
        queued.start()
        threads.append(queued)
        while sched.stats()["waiting"] < 1:
            time.sleep(0.005)
        with pytest.raises(QueueFull) as exc_info:
            sched.submit(lambda: None)
        assert exc_info.value.retry_after > 0
    finally:
        release.set()
        for t in threads:
            t.join(timeout=JOIN_S)
            assert not t.is_alive()
        sched.close()


# --- tensor merge ------------------------------------------------------

def test_tensor_merge_stub_occupancy_and_slicing():
    """Deterministic fast twin of the byte-identity test below: while
    the lone worker is held inside a gated launch, two same-key tensor
    chunks queue behind it and merge into ONE launch
    (tensor.batch_occupancy == 2), each waiter getting its own
    (result, offset, n_blocks) slice of the merged result."""
    sched = EncodeScheduler(queue_depth=16, max_concurrent=4,
                            pool_size=2, window_s=0, devices=1)
    gate = threading.Event()
    started = threading.Event()
    launches: list = []

    def stub_launch(plan, rows, mode="rows"):
        if mode == "rows":                        # the holder job
            started.set()
            assert gate.wait(timeout=JOIN_S), "gate never released"
            return "pending"
        launches.append(np.asarray(rows).shape[0])
        return ("merged", len(rows))

    sched.launch_fn = stub_launch
    sink = Metrics()
    sched.set_metrics_sink(sink)
    outs = [None, None]
    threads = []
    try:
        holder = threading.Thread(
            target=lambda: sched.dispatch_frontend(
                ("hold",), np.zeros((1, 2, 2, 3), np.uint8)))
        holder.start()
        threads.append(holder)
        assert started.wait(timeout=JOIN_S)
        rows = np.zeros((2, 8), np.float32)
        floors = np.zeros(2, np.int32)
        for i in range(2):
            t = threading.Thread(
                target=lambda i=i: outs.__setitem__(
                    i, sched.dispatch_tensor_chunk(rows, floors)))
            t.start()
            threads.append(t)
        while sched.stats()["device_queue_depth"] < 2:
            time.sleep(0.005)
        gate.set()
        for t in threads:
            t.join(timeout=JOIN_S)
            assert not t.is_alive(), "merge client hung"
        # One merged launch of both jobs' rows; disjoint block slices
        # of the one shared result.
        assert launches == [4]
        assert sorted(o[1] for o in outs) == [0, 2]
        assert all(o[0] == ("merged", 4) and o[2] == 2 for o in outs)
        rep = sink.report()
        assert rep["values"]["tensor.batch_occupancy"]["max"] == 2
        counters = rep["counters"]
        assert counters["tensor.device_launches"] == 1
        assert counters["tensor.device_launches.d0"] == 1
    finally:
        gate.set()
        sched.close()


@pytest.mark.slow
def test_tensor_merge_byte_identity_and_occupancy():
    """Two concurrent same-dtype tensor jobs on a one-worker pool merge
    into shared device launches (tensor.batch_occupancy > 1) and stay
    byte-identical to serial encodes — the merged launch's per-job
    block slices never leak across jobs. Slow-marked: the merged
    2-job chunk shape compiles its own device MQ program (~1 min on
    the CPU probe); the CI multichip job runs this file unfiltered."""
    sched = EncodeScheduler(queue_depth=16, max_concurrent=4,
                            pool_size=2, window_s=0.2, devices=1)
    sink = Metrics()
    sched.set_metrics_sink(sink)
    rng = np.random.default_rng(26)
    arrs = [rng.integers(-3, 4, size=(600,), dtype=np.int8),
            rng.integers(-3, 4, size=(600,), dtype=np.int8)]
    try:
        serial = [encode_tensor(x, device="device") for x in arrs]
        outs, errs = _run_concurrent(
            [lambda x=x: sched.submit_tensor(encode_tensor, x,
                                             device="device")
             for x in arrs])
        assert errs == [None, None]
        assert outs == serial
        rep = sink.report()
        occ = rep["values"]["tensor.batch_occupancy"]
        assert occ["max"] > 1, occ
        counters = rep["counters"]
        assert counters["tensor.device_launches.d0"] == \
            counters["tensor.device_launches"]
    finally:
        sched.close()


# --- graftrace regression ---------------------------------------------

@pytest.mark.parametrize("seed", [0, 1])
def test_graftrace_device_pool_storm_pinned_schedules(seed):
    """Pinned-schedule sweep of the device_pool_storm scenario (fatal
    worker replacement, cross-worker priority order, close-drain over
    a 4-device pool). Deterministic per seed."""
    from bucketeer_tpu.analysis.graftrace import explore

    findings, summary = explore.run_race(
        "bucketeer_tpu", scenario_names=["device_pool_storm"],
        schedules=24, seed=seed, budget_s=240)
    assert findings == [], "\n".join(f.render() for f in findings)
    assert summary["deadlocks"] == 0
    assert summary["invariant_failures"] == 0
    assert summary["races"] == 0
