"""Scalar quantization and step-size signaling (JPEG 2000 Part 1, Annex E).

Replaces the quantization stage of the Kakadu binary (reference:
converters/KakaduConverter.java:38-43 — kdu derives step sizes internally
from the 9/7 filter gains; lossless uses ``Creversible=yes`` i.e. no
quantization). Deadzone scalar quantizer, vectorized as jnp so it fuses
with the DWT output on device.

Conventions:
- Irreversible (9/7): per-subband step ``delta_b = base_delta / g_b`` where
  ``g_b`` is the L2 synthesis gain of the subband (dwt.synthesis_gains).
  Steps are signaled "scalar expounded" as (exponent, mantissa) pairs with
  ``delta_b = 2^(R_b - eps_b) * (1 + mu_b / 2^11)``, R_b = component bit
  depth + log2 subband nominal gain (LL 0, HL/LH 1, HH 2).
- Reversible (5/3): no quantization; exponents-only signaling with
  ``eps_b = R_b``.
- Number of coded magnitude bit-planes: ``M_b = guard_bits + eps_b - 1``.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

GUARD_BITS = 2

# Fractional magnitude bits kept alongside the quantizer index for PCRD
# distortion estimation (the index alone only locates a coefficient to
# within one step; the fraction pins the true |c|/delta so R-D slopes
# rank correctly when many blocks have near-identical statistics).
FRAC_BITS = 7

# log2 of the nominal dynamic-range gain per subband type (T.800 E.1.1).
_LOG2_GAIN = {"LL": 0, "HL": 1, "LH": 1, "HH": 2}


@dataclass(frozen=True)
class SubbandQuant:
    """Signaling info for one subband."""
    exponent: int   # eps_b (5 bits)
    mantissa: int   # mu_b (11 bits); 0 for reversible
    delta: float    # actual step used by the encoder
    n_bitplanes: int  # M_b


def quantize_fp(coeffs: jnp.ndarray, delta: float) -> jnp.ndarray:
    """Deadzone scalar quantizer keeping FRAC_BITS fractional magnitude
    bits: signed fixed-point ``sign * floor(|c|/delta * 2^FRAC_BITS)``.
    The coded index is the fixed-point value >> FRAC_BITS (identical to
    a plain ``floor(|c|/delta)``); the low bits feed Tier-1's distortion
    estimates. Magnitudes are clamped below 2^31 so int32 never wraps —
    an index that large (> 2^24) trips the encoder's ``Mb`` assertion
    loudly instead of corrupting the codestream silently."""
    scale = float(1 << FRAC_BITS)
    lim = float(2 ** 31 - (1 << FRAC_BITS) - 1)
    q = jnp.floor(jnp.minimum(jnp.abs(coeffs) / delta * scale,
                              lim)).astype(jnp.int32)
    return jnp.where(coeffs < 0, -q, q)


def step_for_subband(base_delta: float, gain: float) -> float:
    return base_delta / gain


def signal_irreversible(delta: float, bitdepth: int, band: str,
                        guard_bits: int = GUARD_BITS) -> SubbandQuant:
    """Encode a step size as (exponent, mantissa) and return the *exact*
    step implied by the signaling (the encoder must quantize with the
    signaled value so encoder and decoder agree)."""
    rb = bitdepth + _LOG2_GAIN[band]
    # delta = 2^(rb - eps) * (1 + mu/2048); find eps so mantissa in [0,1).
    import math
    e = rb - math.floor(math.log2(delta))
    # log2(delta) = rb - e + log2(1+mu/2048) with 0 <= log2(1+mu/2048) < 1
    frac = delta / (2.0 ** (rb - e))
    while frac >= 2.0:
        e -= 1
        frac /= 2.0
    while frac < 1.0:
        e += 1
        frac *= 2.0
    eps = max(0, min(31, e))
    mu = int(round((frac - 1.0) * 2048.0))
    mu = max(0, min(2047, mu))
    exact = (2.0 ** (rb - eps)) * (1.0 + mu / 2048.0)
    return SubbandQuant(eps, mu, exact, guard_bits + eps - 1)


def signal_reversible(bitdepth: int, band: str,
                      guard_bits: int = GUARD_BITS,
                      extra_bits: int = 0) -> SubbandQuant:
    """Reversible path: no quantization, exponents-only (style 0).

    ``extra_bits`` accounts for dynamic-range growth the nominal R_b does
    not cover (e.g. the RCT chroma components carry one extra bit).
    """
    eps = bitdepth + _LOG2_GAIN[band] + extra_bits
    eps = max(0, min(31, eps))
    return SubbandQuant(eps, 0, 1.0, guard_bits + eps - 1)
