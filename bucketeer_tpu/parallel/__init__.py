"""Multi-chip parallelism: device mesh, data-parallel tile batching, and
row-sharded DWT with halo exchange (SURVEY.md §2.3, §5)."""
from .batch import run_tiles_sharded
from .mesh import (DATA_AXIS, TILE_AXIS, batch_sharding, make_mesh,
                   replicated, row_sharding)
from .sharded_dwt import sharded_dwt2d_forward

__all__ = [
    "DATA_AXIS", "TILE_AXIS", "batch_sharding", "make_mesh", "replicated",
    "row_sharding", "run_tiles_sharded", "sharded_dwt2d_forward",
]
