"""The in-process TPU converter — the component the reference outsources
to the Kakadu binary (reference: converters/KakaduConverter.java:55-77).

Emits the reference's full Kakadu recipe (reference:
KakaduConverter.java:38-44): ``Clevels=6 Clayers=6
Cprecincts={256,256},{256,256},{128,128} Stiles={512,512} Corder=RPCL
ORGgen_plt=yes ORGtparts=R Cblk={64,64} Cuse_sop=yes Cuse_eph=yes``;
lossless = reversible 5/3 + RCT (``Creversible=yes -rate -``), lossy =
irreversible 9/7 + ICT with PCRD-opt truncation to 3 bpp (``-rate 3``).
"""
from __future__ import annotations

import logging
import os

from .. import obs
from ..codec import tiff
from ..codec.encoder import EncodeParams, encode_jp2
from .base import Conversion, ConverterError, output_path

LOG = logging.getLogger(__name__)

LOSSY_RATE = 3.0    # reference: -rate 3 (KakaduConverter.java:43)

# Images at or above this pixel count route through the device mesh
# whenever more than one device is visible: a single giant tile is
# row-sharded (parallel.sharded_dwt), a tiled image's batches are
# data-sharded (parallel.batch.run_tiles_sharded). The default is sized
# so ordinary scans stay on the single-device overlapped pipeline and
# only archival monsters (BASELINE config 4's 400 MPix maps) pay the
# mesh dispatch overhead. Override: BUCKETEER_MESH_MIN_PIXELS env or
# the bucketeer.mesh.min.pixels config key (engine/batch.py).
DEFAULT_MESH_MIN_PIXELS = 64_000_000


def _env_mesh_min_pixels() -> int:
    return int(os.environ.get("BUCKETEER_MESH_MIN_PIXELS",
                              str(DEFAULT_MESH_MIN_PIXELS)))


_cache_state: dict = {"enabled": False, "dir": None}


def maybe_enable_compile_cache(path: str | None = None) -> dict:
    """Enable JAX's persistent compilation cache so repeated bench and
    server runs skip XLA recompiles (the encoder's jitted programs are
    keyed by tile shape and plane capacity — a warm cache turns a
    multi-second boot into a disk read).

    ``path``: cache directory; None reads BUCKETEER_COMPILE_CACHE (the
    bucketeer.tpu.compile.cache config key is wired through the
    converter). Empty/"0" leaves caching off. Returns
    {"enabled", "dir", "entries"} — ``entries`` is the number of cached
    programs currently on disk, which bench.py diffs across a run to
    report hits (no new entries) vs misses (new compiles persisted).
    """
    path = path if path is not None else os.environ.get(
        "BUCKETEER_COMPILE_CACHE", "")
    if not path or path == "0":
        return dict(_cache_state, entries=0)
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything: the default thresholds skip fast compiles,
        # but the encoder's many small per-shape programs are exactly
        # the boot cost we want gone.
        for knob, val in (("jax_enable_compilation_cache", True),
                          ("jax_persistent_cache_min_entry_size_bytes",
                           -1),
                          ("jax_persistent_cache_min_compile_time_secs",
                           0.0)):
            try:
                jax.config.update(knob, val)
            except AttributeError:      # older jax: knob absent
                pass
        # The cache latches "initialized, disabled" on its first use; if
        # any compile happened before the dir was configured (backend
        # probing, an earlier encode), reset so the new dir takes.
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except (ImportError, AttributeError):
            pass
        _cache_state.update(enabled=True, dir=path)
    except (OSError, AttributeError) as exc:
        LOG.warning("compile cache unavailable at %s: %s", path, exc)
    return dict(_cache_state, entries=compile_cache_entries())


def compile_cache_entries() -> int:
    """Number of persisted XLA programs in the active cache (0 if off).
    Each program is a ``*-cache`` file (the ``*-atime`` twins are
    eviction bookkeeping, not entries)."""
    if not _cache_state["enabled"]:
        return 0
    try:
        return sum(1 for e in os.scandir(_cache_state["dir"])
                   if e.is_file() and e.name.endswith("-cache"))
    except OSError:
        return 0


class TpuConverter:
    """JPEG 2000 encoding on the local TPU/accelerator via the JAX codec."""

    name = "TPU"

    def __init__(self, lossy_rate: float = LOSSY_RATE,
                 jpx: bool = True,
                 mesh_min_pixels: int | None = None,
                 device_cxd: bool | None = None,
                 device_mq: bool | None = None,
                 compile_cache: str | None = None,
                 scheduler=None) -> None:
        self.lossy_rate = lossy_rate
        self.jpx = jpx
        self.mesh_min_pixels = (_env_mesh_min_pixels()
                                if mesh_min_pixels is None
                                else mesh_min_pixels)
        # None defers to the BUCKETEER_DEVICE_CXD env flag per encode
        # (encoder._device_cxd); the engine wires the
        # bucketeer.tpu.device.cxd config key through here.
        self.device_cxd = device_cxd
        # Full Tier-1 on device (CX/D + MQ coder); None defers to the
        # BUCKETEER_DEVICE_MQ env flag per encode (encoder._device_mq);
        # the engine wires bucketeer.tpu.device.mq through here.
        self.device_mq = device_mq
        # Encodes go through the cross-request scheduler (admission
        # control + continuous device batching + shared host Tier-1).
        # None = the process-wide instance, resolved lazily per convert
        # (engine/scheduler.py imports converters back — a boot-time
        # import here would cycle).
        self.scheduler = scheduler
        maybe_enable_compile_cache(compile_cache)

    def _choose_mesh(self, h: int, w: int, params: EncodeParams):
        """Mesh routing for over-threshold images: a ('data', 'tile')
        mesh over all visible devices — all-spatial when the image is a
        single row-shardable tile, all-data otherwise. None keeps the
        single-device overlapped pipeline."""
        if self.mesh_min_pixels <= 0 or h * w < self.mesh_min_pixels:
            return None
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharded_dwt import can_row_shard

        devices = jax.devices()
        if len(devices) < 2:
            return None
        if params.tile_size is None:
            # A single tile can only parallelize spatially. If its rows
            # don't shard, a data mesh would pad the batch of one up to
            # n_devices full-size zero tiles (parallel/batch.py) — all
            # host memory and dispatch overhead, zero speedup — so stay
            # on the single-device pipeline instead.
            if can_row_shard(h, params.levels, len(devices)):
                return make_mesh(devices, tile_parallel=len(devices))
            return None
        return make_mesh(devices, tile_parallel=1)

    def convert(self, image_id: str, source_path: str,
                conversion: Conversion = Conversion.LOSSLESS,
                priority: int | None = None,
                deadline_s: float | None = None) -> str:
        """Convert one source image to a JP2/JPX derivative.

        ``priority``: scheduler queue class — engine/scheduler.py
        PRIORITY_SINGLE (default, interactive requests) or
        PRIORITY_BATCH (CSV items; the batch worker passes it so
        interactive traffic jumps the queue). ``deadline_s`` bounds the
        request end to end; expiry raises through as a typed scheduler
        error. Raises ``QueueFull`` (503 + Retry-After upstream) when
        the scheduler's bounded queue is at depth.
        """
        from ..engine import scheduler as sched_mod

        if not os.path.exists(source_path):
            raise ConverterError(f"source not found: {source_path}")
        try:
            img, bitdepth = tiff.read_image(source_path)
        except Exception as exc:
            raise ConverterError(
                f"cannot read {source_path}: {exc}") from exc

        h, w = img.shape[:2]
        params = EncodeParams.kakadu_recipe(
            lossless=conversion == Conversion.LOSSLESS,
            rate=self.lossy_rate)
        params.device_cxd = self.device_cxd
        params.device_mq = self.device_mq
        # Tiny images can't sustain 6 levels; clamp like encoders do.
        while params.levels > 1 and (min(h, w) >> params.levels) < 4:
            params.levels -= 1
        if max(h, w) <= params.tile_size:
            params.tile_size = None         # single tile, like kdu untiled
        # The base step is calibrated for 8-bit signals; scale it with
        # the signal range so deeper scans quantize proportionally.
        params.base_delta *= (1 << (bitdepth - 8))
        mesh = self._choose_mesh(h, w, params)
        if mesh is not None:
            LOG.info("routing %s (%dx%d) through the device mesh %s",
                     image_id, w, h, dict(mesh.shape))
        sched = self.scheduler or sched_mod.get_scheduler()
        try:
            with obs.span("convert.encode", image_id=image_id,
                          pixels=h * w):
                data = sched.encode_jp2(
                    img, bitdepth, params, jpx=self.jpx, mesh=mesh,
                    priority=(sched_mod.PRIORITY_SINGLE
                              if priority is None else priority),
                    deadline_s=deadline_s)
        except (sched_mod.QueueFull, sched_mod.DeadlineExceeded):
            # Admission/deadline outcomes are protocol, not converter
            # failures: the HTTP layer maps them to 503 + Retry-After.
            raise
        except Exception as exc:
            raise ConverterError(
                f"encode failed for {image_id}: {exc}") from exc

        dest = output_path(image_id, ".jpx" if self.jpx else ".jp2")
        # Unique temp name: concurrent converts of the same id must not
        # interleave writes before the atomic replace.
        tmp = f"{dest}.{os.getpid()}.{id(data):x}.part"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, dest)
        return dest
