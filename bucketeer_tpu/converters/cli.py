"""CLI converters wrapping external JPEG 2000 encoders when installed.

Port of the reference's Kakadu/OpenJPEG converters (reference:
converters/KakaduConverter.java:36-77, OpenJPEGConverter.java:12-25 — the
latter is an unfinished stub there; here it works). Used as a
correctness oracle in tests and a no-TPU fallback, inverting the
reference's arrangement where the CLI was the primary path.
"""
from __future__ import annotations

import os
import shutil
import subprocess

from .base import Conversion, ConverterError, output_path


class CliConverter:
    """Base for subprocess-driven converters (reference:
    AbstractConverter.java:29-39 — run, wait, stderr -> exception)."""

    name = "CLI"
    executable = ""

    def _run(self, command: list[str]) -> None:
        proc = subprocess.run(command, capture_output=True, text=True)
        if proc.returncode != 0:
            raise ConverterError(
                f"{self.executable} failed ({proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}")

    @classmethod
    def find_executable(cls) -> str | None:
        """Probe PATH (and KAKADU_HOME for kdu) the way the factory probes
        ``kdu_compress -v`` (reference: ConverterFactory.java:86-103)."""
        path = shutil.which(cls.executable)
        if path:
            return path
        home = os.environ.get("KAKADU_HOME")
        if home:
            candidate = os.path.join(home, cls.executable)
            if os.path.exists(candidate):
                return candidate
        return None

    @classmethod
    def is_available(cls) -> bool:
        return cls.find_executable() is not None


class KakaduConverter(CliConverter):
    """``kdu_compress`` with the reference's exact recipe (reference:
    KakaduConverter.java:38-44)."""

    name = "Kakadu"
    executable = "kdu_compress"

    BASE_OPTIONS = [
        "Clevels=6", "Clayers=6",
        "Cprecincts={256,256},{256,256},{128,128}",
        "Stiles={512,512}", "Corder=RPCL", "ORGgen_plt=yes", "ORGtparts=R",
        "Cblk={64,64}", "Cuse_sop=yes", "Cuse_eph=yes",
        "-flush_period", "1024",
    ]

    def convert(self, image_id: str, source_path: str,
                conversion: Conversion = Conversion.LOSSLESS) -> str:
        exe = self.find_executable()
        if exe is None:
            raise ConverterError("kdu_compress not found")
        dest = output_path(image_id, ".jpx")
        cmd = [exe, "-i", source_path, "-o", dest] + self.BASE_OPTIONS
        if conversion == Conversion.LOSSLESS:
            cmd += ["Creversible=yes", "-rate", "-"]
        else:
            cmd += ["-rate", "3"]
        self._run(cmd)
        return dest


class OpenJPEGConverter(CliConverter):
    """``opj_compress`` — complete here, unlike the reference's stub
    (reference: OpenJPEGConverter.java:22-25 returns null)."""

    name = "OpenJPEG"
    executable = "opj_compress"

    def convert(self, image_id: str, source_path: str,
                conversion: Conversion = Conversion.LOSSLESS) -> str:
        exe = self.find_executable()
        if exe is None:
            raise ConverterError("opj_compress not found")
        dest = output_path(image_id, ".jp2")
        cmd = [exe, "-i", source_path, "-o", dest, "-n", "7",
               "-b", "64,64", "-t", "512,512"]
        if conversion == Conversion.LOSSY:
            cmd += ["-r", "8"]   # ~3bpp on 24bpp input
        self._run(cmd)
        return dest
