"""The bench harness's dispatch-time backend fallback (BENCH_r05: the
``axon UNAVAILABLE`` error raised at *first dispatch*, after init-time
probing had already passed, leaving rc=1 with zero numbers).

The re-exec itself replaces the process, so what's unit-testable is the
detector and the guard; the end-to-end path is covered by the bench
smoke CI jobs running on CPU-only hosts.
"""
import importlib.util
import os
import sys


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_detector_matches_real_failure_modes():
    bench = _load_bench()
    real = RuntimeError(
        "Unable to initialize backend 'axon': UNAVAILABLE: TPU backend "
        "setup/compile error (Unavailable). (set JAX_PLATFORMS='' to "
        "automatically choose an available backend)")
    assert bench._backend_unavailable(real)
    # The config wrapper re-raises through other layers; the detector
    # must follow the cause chain.
    try:
        try:
            raise real
        except RuntimeError as exc:
            raise ValueError("encode failed") from exc
    except ValueError as wrapped:
        assert bench._backend_unavailable(wrapped)


def test_detector_ignores_ordinary_errors():
    bench = _load_bench()
    assert not bench._backend_unavailable(ValueError("bad shape"))
    assert not bench._backend_unavailable(RuntimeError("oom"))
    assert not bench._backend_unavailable(KeyError("x"))


def test_reexec_guard_env_is_plumbed_into_report():
    """The JSON line must carry platform_fallback when the re-exec env
    marker is set (the re-exec'd process is the one that prints)."""
    bench = _load_bench()
    assert bench._REEXEC_ENV == "BUCKETEER_BENCH_CPU_REEXEC"
    src = open(os.path.join(os.path.dirname(__file__), "..",
                            "bench.py")).read()
    assert "platform_fallback" in src
    assert src.count("_reexec_on_cpu()") >= 1
