"""Reserved for hand-written Pallas TPU kernels.

Planned role: fuse the Tier-1 front-end's bit-plane packing and
significance statistics (codec/frontend.py) into a single custom kernel
once the plain-jnp formulation stops scaling — the packing step's
``(N, 64, 8, 8) -> (N, 512)`` byte assembly is the likeliest candidate
for a Pallas rewrite because XLA materializes an intermediate the kernel
could keep in registers.

Nothing here is implemented yet. The front-end runs entirely as jitted
jnp today; an earlier docstring claimed otherwise and was reverted
(commit b4c697b), which is why the empty-package lint rule
(``graftlint: empty-package``) now requires this stub to say so
explicitly. When adding the first kernel, read the TPU guide under
/opt/skills/guides/ first and keep the jnp path as the fallback for
CPU-backend tests.
"""
