"""The in-process read path: JP2/JPX derivatives back to pixels.

The counterpart of :class:`TpuConverter` for the serving direction the
reference stack exists to feed (TIFF -> JP2 -> S3 for IIIF viewers):
IIIF tile/thumbnail requests are resolution-level reads, so the reader
exposes the decoder's native partial decode — ``reduce=r`` touches only
the low-frequency subbands (Tier-1 work for the skipped resolutions is
never done), ``layers=l`` truncates at a quality layer.

Repeated reads of the same derivative (viewers re-request thumbnails
constantly) are served from a small bounded LRU keyed by
``(path, mtime, size, reduce, layers)`` — the file-identity part of the
key means a re-converted derivative is never served stale. Budget:
``BUCKETEER_DECODE_CACHE_MB`` (default 64, 0 disables); hits/misses/
evictions surface as ``decode.cache_hits`` / ``decode.cache_misses`` /
``decode.cache_evictions`` counters when a metrics sink is attached.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

from ..codec.decode import DecodeError, decode
from ..codec.decode import probe as _probe
from .base import ConverterError, output_path

DEFAULT_CACHE_MB = 64


def derivative_path(image_id: str) -> str | None:
    """Locate the stored derivative for an image id (the file
    :class:`TpuConverter.convert` wrote): .jpx first (the default
    output), then .jp2. None if neither exists."""
    for ext in (".jpx", ".jp2"):
        path = output_path(image_id, ext)
        if os.path.exists(path):
            return path
    return None


class _DecodeCache:
    """Bounded LRU of decoded arrays, sized in bytes. Entries are
    returned write-locked (``setflags(write=False)``) so a caller
    mutating a cached array fails loudly instead of corrupting every
    later hit."""

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self.evictions = 0

    def get(self, key):
        with self._lock:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
            return arr

    def put(self, key, arr: np.ndarray) -> int:
        """Insert and evict LRU entries past the budget. Returns how
        many entries *this* call evicted (computed under the lock, so
        concurrent misses don't count each other's evictions)."""
        if arr.nbytes > self.max_bytes:
            return 0                    # bigger than the whole budget
        arr.setflags(write=False)
        evicted_here = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = arr
            self._bytes += arr.nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self.evictions += 1
                evicted_here += 1
        return evicted_here

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes


class TpuReader:
    """JPEG 2000 decoding on the local TPU/accelerator via the JAX
    codec — the inverse of :class:`TpuConverter`.

    ``cache_mb``: decoded-image LRU budget; negative resolves the
    BUCKETEER_DECODE_CACHE_MB env (default 64), 0 disables. ``metrics``:
    optional server.metrics.Metrics-like sink for the cache counters.
    """

    name = "TPU"

    def __init__(self, cache_mb: int = -1, metrics=None) -> None:
        if cache_mb < 0:
            try:
                cache_mb = int(os.environ.get("BUCKETEER_DECODE_CACHE_MB",
                                              str(DEFAULT_CACHE_MB)))
            except ValueError:
                cache_mb = DEFAULT_CACHE_MB
        self.cache = (_DecodeCache(cache_mb << 20) if cache_mb > 0
                      else None)
        self.metrics = metrics

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(name)

    def read(self, source_path: str, reduce: int = 0,
             layers: int | None = None) -> np.ndarray:
        """Decode a JP2/JPX file (or raw codestream) from disk.
        Missing files raise ConverterError; malformed content raises
        the decoder's typed DecodeError. Cache hits return a read-only
        array — copy before mutating."""
        try:
            st = os.stat(source_path)
        except OSError:
            raise ConverterError(
                f"derivative not found: {source_path}") from None
        key = (source_path, st.st_mtime_ns, st.st_size, reduce, layers)
        if self.cache is not None:
            img = self.cache.get(key)
            if img is not None:
                self._count("decode.cache_hits")
                return img
            self._count("decode.cache_misses")
        with open(source_path, "rb") as fh:
            data = fh.read()
        img = decode(data, reduce=reduce, layers=layers)
        if self.cache is not None:
            evicted = self.cache.put(key, img)
            if evicted and self.metrics is not None:
                self.metrics.count("decode.cache_evictions", evicted)
        return img

    def probe(self, source_path: str) -> dict:
        """Main-header metadata (dims, bit depth, levels, layers)
        without decoding any tile data — what the server needs to pick
        response encodings and validate partial-decode parameters."""
        if not os.path.exists(source_path):
            raise ConverterError(f"derivative not found: {source_path}")
        with open(source_path, "rb") as fh:
            return _probe(fh.read())

    def read_id(self, image_id: str, reduce: int = 0,
                layers: int | None = None) -> np.ndarray:
        """Decode the stored derivative for ``image_id``."""
        path = derivative_path(image_id)
        if path is None:
            raise ConverterError(
                f"no derivative for image id: {image_id}")
        return self.read(path, reduce=reduce, layers=layers)


__all__ = ["TpuReader", "derivative_path", "DecodeError"]
