"""graftlint engine + rules: seeded-defect fixtures, suppression,
baseline, ABI cross-check, CLI exit codes.

Each seeded-defect test plants exactly one violation in a scratch
package and asserts the analyzer reports exactly one finding of the
expected rule — the acceptance bar for the analyzer's signal/noise.
"""
import json
import textwrap

import pytest

from bucketeer_tpu.analysis import abi, lint
from bucketeer_tpu.analysis.__main__ import main as cli_main


def _make_pkg(tmp_path, files: dict):
    """Write a scratch package and return its root directory."""
    root = tmp_path / "pkg"
    for relpath, body in files.items():
        path = root / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body), encoding="utf-8")
        init = path.parent / "__init__.py"
        if path.name != "__init__.py" and not init.exists():
            init.write_text('"""fixture"""\n', encoding="utf-8")
    if not (root / "__init__.py").exists():
        (root / "__init__.py").write_text('"""fixture"""\n',
                                          encoding="utf-8")
    return root


def _rules(findings):
    return [f.rule for f in findings]


# --- seeded defects: exactly one finding each -------------------------

def test_seeded_tracer_host_sync(tmp_path):
    root = _make_pkg(tmp_path, {"codec/bad.py": """\
        import jax
        import jax.numpy as jnp


        def _body(x):
            y = jnp.abs(x)
            return y.item()

        _fn = jax.jit(_body)
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["host-sync"]
    assert findings[0].line == 7


def test_seeded_abi_mismatch(tmp_path):
    native = tmp_path / "native"
    native.mkdir()
    (native / "__init__.py").write_text(textwrap.dedent("""\
        import ctypes
        _ABI_VERSION = 4


        def load(lib):
            lib.t1_abi_version.restype = ctypes.c_int32
            lib.t1_encode_packed.restype = ctypes.c_void_p
        """), encoding="utf-8")
    (native / "t1.cpp").write_text(textwrap.dedent("""\
        #include <cstdint>
        extern "C" {
        int32_t t1_abi_version() { return 3; }
        void t1_encode_packed(int n) {}
        }
        """), encoding="utf-8")
    findings = abi.check_native(native)
    assert _rules(findings) == ["abi-version-mismatch"]
    assert "4" in findings[0].message and "3" in findings[0].message


def test_seeded_abi_arity_mismatch(tmp_path):
    """argtypes declaring a different argument count than the C++
    definition takes — including the `[x] + [y] * k` binding idiom —
    must be exactly one abi-arity-mismatch finding."""
    native = tmp_path / "native"
    native.mkdir()
    (native / "__init__.py").write_text(textwrap.dedent("""\
        import ctypes
        _ABI_VERSION = 3


        def load(lib):
            lib.t1_abi_version.restype = ctypes.c_int32
            lib.t1_encode_cxd.argtypes = [ctypes.c_int] + \\
                [ctypes.c_void_p] * 2
            lib.t1_free.argtypes = [ctypes.c_void_p]
        """), encoding="utf-8")
    (native / "t1.cpp").write_text(textwrap.dedent("""\
        #include <cstdint>
        extern "C" {
        int32_t t1_abi_version() { return 3; }
        void t1_encode_cxd(int n, const uint8_t* payload,
                           const int64_t* offsets, int threads) {}
        void t1_free(void* r) {}
        }
        """), encoding="utf-8")
    findings = abi.check_native(native)
    assert _rules(findings) == ["abi-arity-mismatch"]
    assert "3 argument(s)" in findings[0].message
    assert "takes 4" in findings[0].message
    assert "t1_encode_cxd" in findings[0].message


def test_seeded_swallowed_exception(tmp_path):
    root = _make_pkg(tmp_path, {"engine/bad.py": """\
        def f(g):
            try:
                return g()
            except Exception:
                pass
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["swallowed-exception"]


# --- blocking-call-in-async -------------------------------------------

def test_seeded_blocking_sleep_in_async(tmp_path):
    root = _make_pkg(tmp_path, {"server/bad.py": """\
        import time


        async def handler(request):
            time.sleep(1)
            return request
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["blocking-call-in-async"]
    assert findings[0].line == 5


def test_seeded_blocking_convert_in_async(tmp_path):
    root = _make_pkg(tmp_path, {"engine/bad.py": """\
        async def handle(self, message):
            return self.converter.convert("id", "/p.tif")
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["blocking-call-in-async"]


def test_seeded_blocking_reader_read_in_async(tmp_path):
    """`self.reader.read(...)` is receiver-matched (a bare `read` leaf
    would false-positive on awaited multipart/file reads)."""
    root = _make_pkg(tmp_path, {"server/bad.py": """\
        async def get_image(self, request):
            img = self.reader.read("/p.jpx", 0, None)
            data = await request.content.read()
            return img, data
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["blocking-call-in-async"]
    assert findings[0].line == 2


def test_to_thread_bridged_call_is_clean(tmp_path):
    """The sanctioned pattern passes the blocking callable as a value
    to asyncio.to_thread — no call node, no finding. asyncio.sleep is
    not time.sleep. A nested sync def runs on the executor, not the
    loop."""
    root = _make_pkg(tmp_path, {"engine/good.py": """\
        import asyncio


        async def handle(self, message):
            out = await asyncio.to_thread(
                self.converter.convert, "id", "/p.tif")
            await asyncio.sleep(0.1)

            def local_retry():
                return self.converter.convert("id", "/p.tif")

            return out, await asyncio.to_thread(local_retry)
        """})
    assert lint.run_lint(root) == []


def test_blocking_async_inline_suppression(tmp_path):
    root = _make_pkg(tmp_path, {"server/meh.py": """\
        import time


        async def handler(request):
            time.sleep(0)  # graftlint: disable=blocking-call-in-async
            return request
        """})
    assert lint.run_lint(root) == []


# --- the other device-region rules ------------------------------------

def test_tracer_branch_and_float64(tmp_path):
    root = _make_pkg(tmp_path, {"codec/bad.py": """\
        import jax
        import jax.numpy as jnp


        def _body(x):
            if x.sum() > 0:
                x = x * 2
            return x.astype(jnp.float64)

        _fn = jax.jit(_body)
        """})
    findings = lint.run_lint(root)
    assert sorted(_rules(findings)) == ["float64-leak", "tracer-branch"]


def test_partial_static_args_not_tainted(tmp_path):
    """Config objects bound via partial at the jit root may drive Python
    branches — only the traced operands are tainted."""
    root = _make_pkg(tmp_path, {"codec/ok.py": """\
        import jax
        import jax.numpy as jnp
        from functools import partial


        def _body(plan, x):
            if plan.lossless:                  # static: fine
                x = x + 1
            if x.shape[0] == 1:                # shape: static, fine
                x = x * 2
            return jnp.abs(x)

        _fn = jax.jit(partial(_body, object()))
        """})
    assert lint.run_lint(root) == []


def test_d2h_outside_gather(tmp_path):
    root = _make_pkg(tmp_path, {"codec/xfer.py": """\
        import jax


        def helper(arr):
            return jax.device_get(arr)


        def fetch_payload(arr):
            return jax.device_get(arr)         # sanctioned
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["d2h-outside-gather"]
    assert "helper" in findings[0].message


def test_broad_handler_that_logs_is_clean(tmp_path):
    root = _make_pkg(tmp_path, {"engine/ok.py": """\
        import logging

        LOG = logging.getLogger(__name__)


        def f(g):
            try:
                return g()
            except Exception:
                LOG.exception("g failed")
            try:
                return g()
            except Exception as exc:
                return ("error", str(exc))
        """})
    assert lint.run_lint(root) == []


def test_empty_package_rule(tmp_path):
    root = _make_pkg(tmp_path, {"sub/__init__.py": ""})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["empty-package"]
    # A docstring satisfies the rule.
    (root / "sub" / "__init__.py").write_text('"""planned."""\n',
                                              encoding="utf-8")
    assert lint.run_lint(root) == []


# --- suppression + baseline -------------------------------------------

def test_inline_suppression(tmp_path):
    root = _make_pkg(tmp_path, {"engine/sup.py": """\
        def f(g):
            try:
                return g()
            except Exception:   # graftlint: disable=swallowed-exception
                pass
        """})
    assert lint.run_lint(root) == []


def test_file_level_suppression(tmp_path):
    root = _make_pkg(tmp_path, {"engine/sup.py": """\
        # graftlint: disable-file=swallowed-exception
        def f(g):
            try:
                return g()
            except Exception:
                pass
        """})
    assert lint.run_lint(root) == []


def test_stale_inline_suppression_is_a_warning(tmp_path):
    """A disable comment that suppresses nothing is itself reported
    (warning severity: fails --strict, tolerated otherwise)."""
    root = _make_pkg(tmp_path, {"engine/ok.py": """\
        def f(g):
            return g()   # graftlint: disable=swallowed-exception
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["stale-suppression"]
    assert findings[0].severity == "warning"
    assert findings[0].line == 2
    assert "swallowed-exception" in findings[0].message


def test_stale_file_suppression_is_a_warning(tmp_path):
    root = _make_pkg(tmp_path, {"engine/ok.py": """\
        # graftlint: disable-file=host-sync
        def f(g):
            return g()
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["stale-suppression"]
    assert "disable-file=host-sync" in findings[0].message


def test_live_suppression_is_not_stale(tmp_path):
    root = _make_pkg(tmp_path, {"engine/sup.py": """\
        def f(g):
            try:
                return g()
            except Exception:   # graftlint: disable=swallowed-exception
                pass
        """})
    assert lint.run_lint(root) == []


def test_partially_stale_suppression_flags_only_dead_rules(tmp_path):
    """disable=a,b where only a fires: b is the stale half."""
    root = _make_pkg(tmp_path, {"engine/sup.py": """\
        def f(g):
            try:
                return g()
            except Exception:   # graftlint: disable=swallowed-exception,host-sync
                pass
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["stale-suppression"]
    assert "host-sync" in findings[0].message


def test_cli_strict_fails_on_stale_suppression_and_baseline(tmp_path,
                                                            capsys):
    root = _make_pkg(tmp_path, {"engine/ok.py": """\
        def f(g):
            return g()   # graftlint: disable=swallowed-exception
        """})
    assert cli_main([str(root)]) == 0                 # warning only
    assert cli_main([str(root), "--strict"]) == 1
    capsys.readouterr()

    # A baseline entry that matches nothing is likewise a warning...
    baseline = tmp_path / "b.json"
    baseline.write_text(json.dumps({"findings": [
        {"fingerprint": "deadbeefdeadbeef", "rule": "host-sync",
         "path": "x.py", "line": 1}]}), encoding="utf-8")
    clean = _make_pkg(tmp_path / "c", {"engine/ok.py": "X = 1\n"})
    assert cli_main([str(clean), "--baseline", str(baseline)]) == 0
    assert cli_main([str(clean), "--strict",
                     "--baseline", str(baseline)]) == 1
    out = capsys.readouterr().out
    assert "stale-baseline-entry" in out
    assert "deadbeefdeadbeef" in out


def test_prune_baseline_rewrites_only_stale_entries(tmp_path, capsys):
    root = _make_pkg(tmp_path, {"engine/bad.py": """\
        def f(g):
            try:
                return g()
            except Exception:
                pass
        """})
    baseline = tmp_path / "b.json"
    assert cli_main([str(root), "--write-baseline",
                     "--baseline", str(baseline)]) == 0
    # Seed one dead fingerprint beside the live one.
    data = json.loads(baseline.read_text(encoding="utf-8"))
    data["findings"].append({"fingerprint": "feedfacefeedface",
                             "rule": "host-sync", "path": "x.py",
                             "line": 1})
    baseline.write_text(json.dumps(data), encoding="utf-8")
    capsys.readouterr()
    assert cli_main([str(root), "--strict", "--prune-baseline",
                     "--baseline", str(baseline)]) == 0
    out = capsys.readouterr().out
    assert "pruned 1 stale entry" in out
    kept = json.loads(baseline.read_text(encoding="utf-8"))["findings"]
    assert len(kept) == 1
    assert kept[0]["fingerprint"] != "feedfacefeedface"
    # The pruned baseline still suppresses the live finding.
    assert cli_main([str(root), "--strict",
                     "--baseline", str(baseline)]) == 0


def test_baseline_filters_known_findings(tmp_path):
    root = _make_pkg(tmp_path, {"engine/bad.py": """\
        def f(g):
            try:
                return g()
            except Exception:
                pass
        """})
    findings = lint.run_lint(root)
    assert len(findings) == 1
    baseline_path = tmp_path / "baseline.json"
    lint.write_baseline(baseline_path, findings)
    baseline = lint.load_baseline(baseline_path)
    assert lint.run_lint(root, baseline=baseline) == []
    # The fingerprint keys on content, not line number: shifting the
    # function down the file keeps the suppression.
    path = root / "engine" / "bad.py"
    path.write_text("X = 1\n\n\n" + path.read_text(encoding="utf-8"),
                    encoding="utf-8")
    assert lint.run_lint(root, baseline=baseline) == []


# --- ABI cross-checker corners ----------------------------------------

def test_abi_missing_export(tmp_path):
    native = tmp_path / "native"
    native.mkdir()
    (native / "__init__.py").write_text(
        "import ctypes\n_ABI_VERSION = 3\n\n\n"
        "def load(lib):\n"
        "    lib.t1_abi_version.restype = ctypes.c_int32\n"
        "    lib.t1_gone.restype = ctypes.c_void_p\n",
        encoding="utf-8")
    (native / "t1.cpp").write_text(
        '#include <cstdint>\nextern "C" {\n'
        "int32_t t1_abi_version() { return 3; }\n}\n", encoding="utf-8")
    findings = abi.check_native(native)
    assert _rules(findings) == ["abi-missing-export"]
    assert "t1_gone" in findings[0].message


def test_abi_unbound_export_is_warning(tmp_path):
    native = tmp_path / "native"
    native.mkdir()
    (native / "__init__.py").write_text(
        "import ctypes\n_ABI_VERSION = 3\n\n\n"
        "def load(lib):\n"
        "    lib.t1_abi_version.restype = ctypes.c_int32\n",
        encoding="utf-8")
    (native / "t1.cpp").write_text(
        '#include <cstdint>\nextern "C" {\n'
        "int32_t t1_abi_version() { return 3; }\n"
        "void t1_extra(int n) {}\n}\n", encoding="utf-8")
    findings = abi.check_native(native)
    assert _rules(findings) == ["abi-unbound-export"]
    assert findings[0].severity == "warning"


def test_abi_real_native_package_is_in_sync():
    from pathlib import Path
    native = Path(__file__).resolve().parent.parent / "bucketeer_tpu" \
        / "native"
    assert [f for f in abi.check_native(native)
            if f.severity == "error"] == []


# --- the runtime ABI guard (native/__init__.py) ------------------------

class _FakeSymbol:
    def __init__(self, version):
        self._version = version
        self.restype = None

    def __call__(self):
        return self._version


class _FakeLib:
    def __init__(self, version):
        self.t1_abi_version = _FakeSymbol(version)


def test_native_abi_guard_raises_typed_error():
    from bucketeer_tpu import native

    native._check_abi(_FakeLib(native._ABI_VERSION))   # in sync: ok
    with pytest.raises(native.NativeABIError) as exc:
        native._check_abi(_FakeLib(native._ABI_VERSION + 1))
    assert exc.value.expected == native._ABI_VERSION
    assert exc.value.found == native._ABI_VERSION + 1
    assert "BUCKETEER_NO_NATIVE" in str(exc.value)     # remediation hint

    with pytest.raises(native.NativeABIError) as exc:
        native._check_abi(object())                    # symbol missing
    assert exc.value.found == -1


# --- CLI ---------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    root = _make_pkg(tmp_path, {"engine/bad.py": """\
        def f(g):
            try:
                return g()
            except Exception:
                pass
        """})
    assert cli_main([str(root), "--strict"]) == 1
    out = capsys.readouterr().out
    assert "swallowed-exception" in out

    # --write-baseline makes the gate start green...
    assert cli_main([str(root), "--write-baseline",
                     "--baseline", str(tmp_path / "b.json")]) == 0
    assert cli_main([str(root), "--strict",
                     "--baseline", str(tmp_path / "b.json")]) == 0
    capsys.readouterr()

    # ...and --json stays machine-readable.
    assert cli_main([str(root), "--json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert data[0]["rule"] == "swallowed-exception"
    assert cli_main(["/nonexistent-dir"]) == 2


def test_cli_warnings_fail_only_in_strict(tmp_path):
    native_pkg = _make_pkg(tmp_path, {"native/__init__.py": """\
        import ctypes
        _ABI_VERSION = 3


        def load(lib):
            lib.t1_abi_version.restype = ctypes.c_int32
        """})
    (native_pkg / "native" / "t1.cpp").write_text(
        '#include <cstdint>\nextern "C" {\n'
        "int32_t t1_abi_version() { return 3; }\n"
        "void t1_extra(int n) {}\n}\n", encoding="utf-8")
    assert cli_main([str(native_pkg)]) == 0          # warning only
    assert cli_main([str(native_pkg), "--strict"]) == 1


# --- missing-donation -------------------------------------------------

def test_seeded_missing_donation(tmp_path):
    root = _make_pkg(tmp_path, {"codec/frontend.py": """\
        import jax


        def _body(batch):
            return batch * 2

        _fn = jax.jit(_body)
        """})
    findings = lint.run_lint(root)
    assert _rules(findings) == ["missing-donation"]
    assert "donate_argnums" in findings[0].message


def test_donation_spec_is_clean(tmp_path):
    root = _make_pkg(tmp_path, {"codec/decode/device.py": """\
        import jax


        def _body(batch):
            return batch * 2

        _fn = jax.jit(_body, donate_argnums=(0,))
        _gn = jax.jit(_body, donate_argnames=("batch",))
        """})
    assert lint.run_lint(root) == []


def test_donation_whitelist_and_scope(tmp_path):
    root = _make_pkg(tmp_path, {
        # `gather` is whitelisted by name: its rows buffer is re-read
        # across chunked dispatches.
        "codec/frontend.py": """\
            import jax


            def gather(rows, src):
                return rows[src]

            _fn = jax.jit(gather)
            """,
        # Out of scope: only the hot device modules are gated.
        "codec/other.py": """\
            import jax


            def _body(x):
                return x + 1

            _fn = jax.jit(_body)
            """})
    assert _rules(lint.run_lint(root)) == []


def test_repo_frontend_and_decode_device_donate():
    """The real modules must stay clean under the rule — buffer
    donation on the jitted front-end and decode inverse is the fix the
    rule exists to keep in place."""
    from pathlib import Path

    import bucketeer_tpu

    root = Path(bucketeer_tpu.__file__).parent
    from bucketeer_tpu.analysis import rules_donation
    project = lint.load_project(root)
    assert rules_donation.run(project) == []
