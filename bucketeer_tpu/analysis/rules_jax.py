"""Device-region rules: host-sync, tracer-branch, float64-leak, d2h.

The encoder's hot path is a handful of jit-compiled programs; a host
sync or a Python branch on a tracer inside one of them either crashes at
trace time (branch) or silently serializes the pipeline (sync). These
rules find the *device region* — every function reachable from a
``jax.jit``/``shard_map`` root — and run a lightweight taint walk over
it: function parameters that receive traced arrays are tainted, taint
propagates through arithmetic/indexing/jnp calls, and is laundered by
static attributes (``.shape``, ``.dtype``, ...). Violations are:

- ``host-sync``: ``np.*``, ``float()``/``int()``/``bool()``, ``.item()``,
  ``.tolist()``, ``.block_until_ready()`` applied to a tainted value
  inside the device region.
- ``tracer-branch``: ``if``/``while``/``assert`` (or a conditional
  expression) whose test is tainted — Python control flow cannot see a
  tracer's value; use ``jnp.where``/``lax.cond``.
- ``float64-leak``: any ``float64`` dtype reference inside the device
  region (TPUs emulate f64 at a heavy cost; JAX silently downcasts
  unless x64 is enabled, so either way the intent is wrong).
- ``d2h-outside-gather``: ``jax.device_get`` in the codec/parallel
  layers outside the sanctioned host-transfer functions — the design
  allows exactly one compacted gather (frontend.fetch_payload) plus the
  batch-entry wrappers; any other copy reintroduces the 4-byte/sample
  transfer bottleneck the front-end exists to remove.

Static arguments bound via ``functools.partial(fn, a, b, ...)`` at the
jit root (and ``static_argnums``) are untainted, so plan/config objects
do not false-positive Python branches on static configuration.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .findings import ERROR, Finding

HOST_SYNC = "host-sync"
TRACER_BRANCH = "tracer-branch"
FLOAT64_LEAK = "float64-leak"
D2H = "d2h-outside-gather"

# Attribute reads that yield static (trace-time) values: using them does
# not propagate taint.
LAUNDER_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize",
                 "weak_type", "sharding", "aval", "device"}
# Builtins whose result is static even on a traced argument.
LAUNDER_BUILTINS = {"isinstance", "len", "type", "hasattr", "callable",
                    "id", "repr", "str", "format", "getattr"}
# Builtins that force a concrete value out of a tracer.
SYNC_BUILTINS = {"float", "int", "bool", "complex"}
SYNC_METHODS = {"item", "tolist", "block_until_ready",
                "copy_to_host_async"}

# Functions allowed to call jax.device_get in the codec/parallel layers:
# the sanctioned compaction gather (frontend.gather_rows, shared by the
# packed-bitmap fetch_payload and the CX/D symbol fetch), the host
# batch-entry wrappers, the async-dispatch stats resolver
# (PendingFrontend.resolve_stats and its once-per-launch cache
# _host_stats, which several requests share after a merged
# cross-request launch — a few KB of per-block stats), the
# CX/D stream assembly (cxd.run_cxd — pass tables + row-granular symbol
# payload), the device-MQ byte-segment fetch (cxd.run_device_mq — pass
# cursors + truncation snapshots + row-granular finished byte segments,
# the only d2h traffic of the full-device Tier-1 chain), the mesh
# single-tile transform exit, and the decode subsystem's device->host
# boundary (decode.device.run_inverse — the reconstructed sample batch
# is the decoder's product; there is nothing smaller to ship). The
# tensor subsystem adds two: tensor.codec.fetch_block_meta (the pack
# stage's 4-bytes-per-block magnitude maxima — the blocks themselves
# stay in HBM for the CX/D scan) and CoefficientSet.to_host (the
# explicit materialization escape of the otherwise device-resident
# coefficient product).
D2H_SANCTIONED = {"fetch_payload", "gather_rows", "run_frontend",
                  "run_tiles", "run_tiles_sharded", "resolve_stats",
                  "_host_stats", "run_cxd", "run_device_mq",
                  "sharded_transform_tile",
                  "run_inverse", "run_region_inverse",
                  "fetch_block_meta", "to_host"}
D2H_SCOPES = ("codec", "parallel", "tensor")


@dataclass
class _DeviceFn:
    mod: object
    node: ast.FunctionDef
    tainted: set = field(default_factory=set)     # tainted param names


def _param_names(node: ast.FunctionDef) -> list:
    args = node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    return names


def _attr_root(node: ast.expr):
    """Name at the base of an attribute chain, plus the chain attrs."""
    attrs = []
    while isinstance(node, ast.Attribute):
        attrs.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, list(reversed(attrs))
    return None, list(reversed(attrs))


def _is_jnp_call(mod, func: ast.expr) -> bool:
    root, chain = _attr_root(func)
    if root is None:
        return False
    if root in mod.jnp_aliases:
        return True
    if root in mod.jax_aliases and chain[:1] != ["device_get"]:
        return True
    return False


def _is_float64(mod, node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and node.value in ("float64", "f8",
                                                        "double"):
        return True
    root, chain = _attr_root(node)
    return (root in (mod.jnp_aliases | mod.np_aliases | mod.jax_aliases)
            and chain[-1:] == ["float64"])


class _FnAnalysis:
    """One pass over a device function: propagate taint, collect call
    edges (for device-region growth) and optionally emit findings."""

    def __init__(self, mod, node, tainted_params, emit: bool):
        self.mod = mod
        self.node = node
        self.env = set(tainted_params)
        self.emit = emit
        self.findings: list = []
        # (callee name, [positional arg taints], {kwarg: taint})
        self.edges: list = []
        self.escapes: set = set()     # function names referenced as values

    # -- reporting ----------------------------------------------------
    def _finding(self, rule, node, message):
        if self.emit:
            self.findings.append(Finding(
                rule, self.mod.relpath, node.lineno, message, ERROR,
                self.mod.source_line(node.lineno)))

    # -- expression taint ---------------------------------------------
    def taint(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.env
        if isinstance(node, ast.Attribute):
            if node.attr in LAUNDER_ATTRS:
                return False
            return self.taint(node.value)
        # NOTE: subexpressions are always evaluated eagerly (no `or`
        # short-circuit) — taint() also records call edges and findings,
        # so every subtree must be visited.
        if isinstance(node, ast.Subscript):
            parts = [self.taint(node.value), self.taint(node.slice)]
            return any(parts)
        if isinstance(node, ast.Slice):
            parts = [self.taint(x) for x in
                     (node.lower, node.upper, node.step)]
            return any(parts)
        if isinstance(node, ast.BinOp):
            parts = [self.taint(node.left), self.taint(node.right)]
            return any(parts)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.BoolOp):
            parts = [self.taint(v) for v in node.values]
            return any(parts)
        if isinstance(node, ast.Compare):
            parts = [self.taint(node.left)]
            parts += [self.taint(c) for c in node.comparators]
            return any(parts)
        if isinstance(node, ast.IfExp):
            if self.taint(node.test):
                self._finding(TRACER_BRANCH, node,
                              "conditional expression on a traced value; "
                              "use jnp.where / lax.select")
            parts = [self.taint(node.body), self.taint(node.orelse)]
            return any(parts)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            parts = [self.taint(e) for e in node.elts]
            return any(parts)
        if isinstance(node, ast.Dict):
            parts = [self.taint(v) for v in node.values if v is not None]
            return any(parts)
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            extra = set()
            for comp in node.generators:
                if self.taint(comp.iter):
                    for n in ast.walk(comp.target):
                        if isinstance(n, ast.Name):
                            extra.add(n.id)
            self.env |= extra
            return self.taint(node.elt) or bool(extra)
        if isinstance(node, ast.DictComp):
            return self.taint(node.value)
        if isinstance(node, ast.Call):
            return self.call(node)
        if isinstance(node, ast.JoinedStr):
            return False
        if isinstance(node, ast.Lambda):
            return False
        # Unknown node kind: conservative — treat as untainted rather
        # than cascade false positives.
        return False

    # -- calls --------------------------------------------------------
    def call(self, node: ast.Call) -> bool:
        arg_taints = [self.taint(a) for a in node.args]
        kw_taints = {kw.arg: self.taint(kw.value)
                     for kw in node.keywords if kw.arg is not None}
        any_tainted = any(arg_taints) or any(kw_taints.values())
        func = node.func

        # float64 leakage spelled as a string (attribute spellings like
        # jnp.float64 are caught by the attribute walk in run()).
        for kw in node.keywords:
            if kw.arg == "dtype" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value in ("float64", "f8", "double"):
                self._finding(FLOAT64_LEAK, node,
                              "float64 dtype inside the device region")
        if (isinstance(func, ast.Attribute) and func.attr == "astype"
                and node.args and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in ("float64", "f8", "double")):
            self._finding(FLOAT64_LEAK, node,
                          "astype('float64') inside the device region")

        if isinstance(func, ast.Name):
            name = func.id
            if name in LAUNDER_BUILTINS:
                return False
            if name in SYNC_BUILTINS and any_tainted:
                self._finding(
                    HOST_SYNC, node,
                    f"{name}() on a traced value forces a host sync "
                    "inside a jit-compiled function")
                return False
            if name in self.mod.partial_aliases and node.args:
                inner, _ = _attr_root(node.args[0])
                if inner:
                    self.edges.append((inner, arg_taints[1:], kw_taints))
                return any_tainted
            self.edges.append((name, arg_taints, kw_taints))
            return True if self._is_project_fn(name) else any_tainted

        if isinstance(func, ast.Attribute):
            root, chain = _attr_root(func)
            # numpy call on a traced value: implicit device_get
            if root in self.mod.np_aliases:
                if any_tainted:
                    self._finding(
                        HOST_SYNC, node,
                        f"np.{'.'.join(chain)} on a traced value pulls "
                        "it to the host inside a jit-compiled function; "
                        "use the jnp equivalent")
                return False
            if root in self.mod.jax_aliases and chain and \
                    chain[-1] == "device_get":
                self._finding(
                    HOST_SYNC, node,
                    "jax.device_get inside a jit-compiled function")
                return False
            if _is_jnp_call(self.mod, func):
                return True
            # method call: visit the receiver exactly once
            obj_tainted = self.taint(func.value)
            if func.attr in SYNC_METHODS and obj_tainted:
                self._finding(
                    HOST_SYNC, node,
                    f".{func.attr}() on a traced value forces a host "
                    "sync inside a jit-compiled function")
                return False
            return obj_tainted or any_tainted
        return any_tainted

    def _is_project_fn(self, name: str) -> bool:
        return name in self.project_funcs if hasattr(
            self, "project_funcs") else False

    # -- statements ---------------------------------------------------
    def _bind(self, target, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.env.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)

    def run(self) -> None:
        # Two passes so taint assigned late in a loop body reaches
        # earlier uses; findings are emitted only on the final pass.
        emit = self.emit
        self.emit = False
        for stmt in self.node.body:
            self.stmt(stmt)
        self.emit = emit
        self.findings = []
        self.edges = []
        for stmt in self.node.body:
            self.stmt(stmt)

    def stmt(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return            # nested defs analyzed via their own edges
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = node.value
            tainted = self.taint(value) if value is not None else False
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if isinstance(node, ast.AugAssign):
                tainted = tainted or self.taint(node.target)
            for t in targets:
                self._bind(t, tainted)
                # Track function-name escapes: `fwd = _fwd53_last` makes
                # _fwd53_last part of the device region.
            if isinstance(value, ast.Name):
                self.escapes.add(value.id)
            elif isinstance(value, ast.IfExp):
                for side in (value.body, value.orelse):
                    if isinstance(side, ast.Name):
                        self.escapes.add(side.id)
            return
        if isinstance(node, (ast.If, ast.While)):
            if self.taint(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                self._finding(
                    TRACER_BRANCH, node,
                    f"`{kind}` on a traced value (Python control flow "
                    "cannot see tracer values; use jnp.where/lax.cond)")
            for s in node.body + node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.Assert):
            if self.taint(node.test):
                self._finding(TRACER_BRANCH, node,
                              "assert on a traced value")
            return
        if isinstance(node, ast.For):
            self._bind(node.target, self.taint(node.iter))
            for s in node.body + node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.With):
            for item in node.items:
                self.taint(item.context_expr)
            for s in node.body:
                self.stmt(s)
            return
        if isinstance(node, ast.Try):
            for s in (node.body + node.orelse + node.finalbody
                      + [h for hh in node.handlers for h in hh.body]):
                self.stmt(s)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                self.taint(node.value)
            return
        if isinstance(node, ast.Expr):
            self.taint(node.value)
            return
        if isinstance(node, (ast.Raise, ast.Pass, ast.Break,
                             ast.Continue, ast.Global, ast.Nonlocal,
                             ast.Import, ast.ImportFrom, ast.Delete)):
            return
        # Fallback: walk child expressions for their side effects.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.taint(child)


def _local_binding(scope, name: str):
    """The last value expression bound to ``name`` inside ``scope`` (a
    FunctionDef body), plus the tuple index when the binding is an
    unpacking assignment (``fn, donate = ...``). (None, None) when the
    name is not locally bound."""
    found = (None, None)
    if scope is None:
        return found
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == name:
                found = (node.value, None)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for i, e in enumerate(t.elts):
                    if isinstance(e, ast.Name) and e.id == name:
                        found = (node.value, i)
    return found


def _seam_return(project, mod, call: ast.Call, index: int):
    """Resolve ``x, y = some_program(...)`` through the callee: find
    the seam function's ``return fn, donate`` and hand back
    (element expression, its module, its scope). The ``*_program``
    seams each jitted codec module exports (the construction both the
    production jit and the device audit consume) bind their traceable
    callable this way."""
    callee, _ = _attr_root(call.func)
    leaf = callee
    if isinstance(call.func, ast.Attribute):
        leaf = call.func.attr
    cmod, cnode = _resolve(project, mod, leaf)
    if cnode is None:
        return None, None, None
    for node in ast.walk(cnode):
        if isinstance(node, ast.Return) and \
                isinstance(node.value, (ast.Tuple, ast.List)) and \
                index < len(node.value.elts):
            return node.value.elts[index], cmod, cnode
    return None, None, None


def _unwrap_jit_target(mod, node, project=None, scope=None, depth=0):
    """Resolve a jit/shard_map first argument to (func name, n_static).

    Handles ``fn``, ``partial(fn, a, b)`` (leading args static), the
    retrace wrapper ``instrument("stage", fn_or_partial)``, and — when
    ``project``/``scope`` are given — local bindings through the
    ``*_program`` seams: ``fn, donate = frontend_program(...)`` then
    ``jax.jit(fn, ...)`` resolves through the seam's return statement
    to the underlying traced body.
    """
    if depth > 6:
        return None, 0
    if isinstance(node, ast.Name):
        if project is not None:
            value, idx = _local_binding(scope, node.id)
            if value is not None:
                if idx is not None:
                    if isinstance(value, (ast.Tuple, ast.List)) and \
                            idx < len(value.elts):
                        return _unwrap_jit_target(
                            mod, value.elts[idx], project, scope,
                            depth + 1)
                    if isinstance(value, ast.Call):
                        elt, emod, escope = _seam_return(
                            project, mod, value, idx)
                        if elt is not None:
                            return _unwrap_jit_target(
                                emod, elt, project, escope, depth + 1)
                    return node.id, 0
                return _unwrap_jit_target(mod, value, project, scope,
                                          depth + 1)
        return node.id, 0
    if isinstance(node, ast.Call):
        root, chain = _attr_root(node.func)
        leaf = chain[-1] if chain else root
        if leaf == "instrument" and node.args:
            return _unwrap_jit_target(mod, node.args[-1], project,
                                      scope, depth + 1)
        if root in mod.partial_aliases or leaf == "partial":
            if node.args and isinstance(node.args[0], ast.Name):
                return node.args[0].id, len(node.args) - 1
    return None, 0


def enclosing_functions(mod) -> dict:
    """id(node) -> the innermost FunctionDef containing it."""
    out: dict = {}

    def visit(fnode, current):
        for child in ast.iter_child_nodes(fnode):
            inner = (child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef))
                else current)
            if current is not None:
                out[id(child)] = current
            visit(child, inner)

    visit(mod.tree, None)
    return out


def _find_jit_roots(mod, project=None):
    """[(target function name, set of static param positions)]."""
    roots = []
    scopes = enclosing_functions(mod) if project is not None else {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        root, chain = _attr_root(node.func)
        leaf = chain[-1] if chain else root
        is_jit = ((root in mod.jax_aliases and leaf in ("jit", "pmap"))
                  or root in mod.jit_names
                  or root in mod.shardmap_names
                  or leaf == "shard_map" and root in mod.shardmap_names)
        if not is_jit or not node.args:
            continue
        name, n_static = _unwrap_jit_target(mod, node.args[0], project,
                                            scopes.get(id(node)))
        if name is None:
            continue
        static = set(range(n_static))
        for kw in node.keywords:
            if kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, int):
                        static.add(n.value)
        roots.append((name, static))
    return roots


def _resolve(project, mod, name):
    """Find the FunctionDef for a called name: same module first."""
    candidates = project.funcs_by_name.get(name, [])
    for cmod, cnode in candidates:
        if cmod is mod:
            return cmod, cnode
    if len(candidates) == 1:
        return candidates[0]
    return None, None


def _device_region(project):
    """Fixpoint: map id(FunctionDef) -> _DeviceFn with tainted params."""
    region: dict = {}
    worklist: list = []

    def add(mod, node, tainted) -> None:
        key = id(node)
        fn = region.get(key)
        if fn is None:
            fn = region[key] = _DeviceFn(mod, node)
            fn.tainted |= set(tainted)
            worklist.append(fn)
            return
        new = set(tainted) - fn.tainted
        if new:
            fn.tainted |= new
            if fn not in worklist:
                worklist.append(fn)

    for mod in project.modules:
        for name, static in _find_jit_roots(mod, project):
            rmod, rnode = _resolve(project, mod, name)
            if rnode is None:
                continue
            params = _param_names(rnode)
            tainted = {p for i, p in enumerate(params) if i not in static}
            add(rmod, rnode, tainted)

    while worklist:
        fn = worklist.pop()
        analysis = _FnAnalysis(fn.mod, fn.node, fn.tainted, emit=False)
        analysis.project_funcs = set(project.funcs_by_name)
        analysis.run()
        for name, arg_taints, kw_taints in analysis.edges:
            cmod, cnode = _resolve(project, fn.mod, name)
            if cnode is None or id(cnode) == id(fn.node):
                continue
            params = _param_names(cnode)
            tainted = {params[i] for i, t in enumerate(arg_taints)
                       if t and i < len(params)}
            tainted |= {k for k, t in kw_taints.items()
                        if t and k in params}
            add(cmod, cnode, tainted)
        for name in analysis.escapes:
            cmod, cnode = _resolve(project, fn.mod, name)
            if cnode is not None and id(cnode) != id(fn.node):
                # A function referenced as a value from device code is
                # device code; all params conservatively tainted.
                add(cmod, cnode, set(_param_names(cnode)))
    return region


def _d2h_rule(project) -> list:
    findings = []
    for mod in project.modules:
        parts = mod.relpath.split("/")
        if not any(p in parts for p in D2H_SCOPES):
            continue
        for fnode in ast.walk(mod.tree):
            if not isinstance(fnode, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            if fnode.name in D2H_SANCTIONED:
                continue
            for node in ast.walk(fnode):
                if not isinstance(node, ast.Call):
                    continue
                root, chain = _attr_root(node.func)
                if root in mod.jax_aliases and chain[-1:] == \
                        ["device_get"]:
                    findings.append(Finding(
                        D2H, mod.relpath, node.lineno,
                        f"jax.device_get in {fnode.name}(): "
                        "device-to-host copies in the codec/parallel "
                        "layers are restricted to the sanctioned "
                        f"transfer functions {sorted(D2H_SANCTIONED)}",
                        ERROR, mod.source_line(node.lineno)))
    return findings


def run(project) -> list:
    findings: list = []
    region = _device_region(project)
    seen = set()
    for fn in region.values():
        key = id(fn.node)
        if key in seen:
            continue
        seen.add(key)
        analysis = _FnAnalysis(fn.mod, fn.node, fn.tainted, emit=True)
        analysis.project_funcs = set(project.funcs_by_name)
        analysis.run()
        findings += analysis.findings
        # float64 attribute references (jnp.float64 / np.float64) in
        # device code, in any position (astype arg, dtype=, bare).
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Attribute) and node.attr == \
                    "float64" and _is_float64(fn.mod, node):
                findings.append(Finding(
                    FLOAT64_LEAK, fn.mod.relpath, node.lineno,
                    "float64 reference inside the device region",
                    ERROR, fn.mod.source_line(node.lineno)))
    findings += _d2h_rule(project)
    return findings
