"""Hand-written Pallas TPU kernels.

First (and so far only) kernel: the EBCOT CX/D stripe scan
(:mod:`.cxd_scan`) — the device half of the Tier-1 split that ships
context-modeling symbol streams, not work, to the host MQ coder
(codec/cxd.py, ``BUCKETEER_DEVICE_CXD``). It keeps a code-block's
significance state and symbol buffer resident in VMEM for the whole
plane walk instead of letting XLA spill the batched scan state through
HBM.

Selection: codec/cxd.py picks the Pallas kernel on the TPU backend and
the plain-jnp ``lax.scan`` formulation elsewhere (CPU dev mode, tests);
``BUCKETEER_CXD_PALLAS=1/0`` forces either way. Both implementations
share one step function, and interpret-mode parity tests
(tests/test_cxd.py) pin them to each other and to the codec/t1.py
reference coder.

The earlier plan recorded here — fusing the bit-plane packing of the
packed-bitmap path into a kernel — is superseded: the CX/D split removes
that packing from the hot path entirely. When adding kernels, read the
TPU guide under /opt/skills/guides/ first and keep a jnp fallback for
the CPU backend.
"""
