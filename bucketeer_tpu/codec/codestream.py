"""JPEG 2000 codestream assembly: markers and segments (T.800 Annex A).

Produces the raw .j2k codestream (SOC..EOC) that jp2.py wraps in JP2/JPX
boxes — the byte-level contract that lets any conforming decoder
(OpenJPEG, Kakadu, browsers) read what the TPU encoded. Mirrors the
structural options of the reference's Kakadu recipe
(reference: converters/KakaduConverter.java:38-44).
"""
from __future__ import annotations

import struct

SOC = 0xFF4F
SIZ = 0xFF51
COD = 0xFF52
COC = 0xFF53
QCD = 0xFF5C
QCC = 0xFF5D
COM = 0xFF64
SOT = 0xFF90
SOD = 0xFF93
EOC = 0xFFD9
PLT = 0xFF58

PROG_LRCP = 0
PROG_RLCP = 1
PROG_RPCL = 2
PROG_PCRL = 3
PROG_CPRL = 4


def _seg(marker: int, payload: bytes) -> bytes:
    return struct.pack(">HH", marker, len(payload) + 2) + payload


def siz(width: int, height: int, n_comps: int, bitdepth: int,
        tile_w: int, tile_h: int, signed: bool = False) -> bytes:
    ssiz = (bitdepth - 1) | (0x80 if signed else 0)
    payload = struct.pack(">HIIIIIIIIH", 0, width, height, 0, 0,
                          tile_w, tile_h, 0, 0, n_comps)
    payload += bytes([ssiz, 1, 1]) * n_comps
    return _seg(SIZ, payload)


def cod(progression: int, n_layers: int, use_mct: bool, levels: int,
        cblk_w_exp: int = 6, cblk_h_exp: int = 6, reversible: bool = False,
        precinct_exps=None, use_sop: bool = False, use_eph: bool = False) -> bytes:
    scod = ((1 if precinct_exps else 0)
            | (2 if use_sop else 0)
            | (4 if use_eph else 0))
    payload = bytes([scod]) + struct.pack(">BHB", progression, n_layers,
                                          1 if use_mct else 0)
    payload += bytes([levels, cblk_w_exp - 2, cblk_h_exp - 2, 0,
                      1 if reversible else 0])
    if precinct_exps:
        # One byte per resolution 0..levels: PPx | PPy<<4
        payload += bytes([(px & 0xF) | ((py & 0xF) << 4)
                          for px, py in precinct_exps])
    return _seg(COD, payload)


def qcd(style: int, guard_bits: int, subband_values: list) -> bytes:
    """style 0: no quantization, values = exponents (one byte eps<<3).
    style 2: scalar expounded, values = (eps, mu) pairs (two bytes)."""
    sqcd = style | (guard_bits << 5)
    payload = bytes([sqcd])
    if style == 0:
        payload += bytes([(eps & 0x1F) << 3 for eps in subband_values])
    else:
        for eps, mu in subband_values:
            payload += struct.pack(">H", ((eps & 0x1F) << 11) | (mu & 0x7FF))
    return _seg(QCD, payload)


def com(text: str) -> bytes:
    return _seg(COM, struct.pack(">H", 1) + text.encode("latin-1"))


def sot(tile_idx: int, tile_part_len: int, tpsot: int = 0, tnsot: int = 1) -> bytes:
    return _seg(SOT, struct.pack(">HIBB", tile_idx, tile_part_len, tpsot, tnsot))


def plt(packet_lengths: list, zplt: int = 0) -> bytes:
    """Packet-length marker (A.7.3), 7-bit big-endian varints."""
    payload = bytes([zplt])
    out = bytearray(payload)
    for ln in packet_lengths:
        enc = []
        enc.append(ln & 0x7F)
        ln >>= 7
        while ln:
            enc.append(0x80 | (ln & 0x7F))
            ln >>= 7
        out += bytes(reversed(enc))
    return _seg(PLT, bytes(out))


def assemble(main_segments: list, tiles: list) -> bytes:
    """tiles: list of (tile_idx, [aux_segments], packet_bytes) — one
    tile-part per tile."""
    return assemble_parts(main_segments, [
        (tile_idx, 0, 1, aux, packets)
        for tile_idx, aux, packets in tiles])


def assemble_parts(main_segments: list, tileparts: list) -> bytes:
    """Multi-tile-part assembly (reference recipe ``ORGtparts=R`` splits
    each tile at resolution boundaries, KakaduConverter.java:40).

    tileparts: list of (tile_idx, tpsot, tnsot, [aux_segments],
    packet_bytes) in codestream order.
    """
    out = bytearray(struct.pack(">H", SOC))
    for seg in main_segments:
        out += seg
    for tile_idx, tpsot, tnsot, aux, packets in tileparts:
        aux_len = sum(len(a) for a in aux)
        psot = 12 + aux_len + 2 + len(packets)
        out += sot(tile_idx, psot, tpsot, tnsot)
        for a in aux:
            out += a
        out += struct.pack(">H", SOD)
        out += packets
    out += struct.pack(">H", EOC)
    return bytes(out)
