"""Top-level JPEG 2000 encoder: the TPU-native replacement for the
``kdu_compress`` invocation at the core of the reference service
(reference: converters/KakaduConverter.java:55-77,
converters/AbstractConverter.java:29-39).

Pipeline (SURVEY.md §7 minimum slice):
  host image array -> [device] level shift + RCT/ICT + tiled multi-level
  DWT + quantization (one jitted XLA program per tile shape,
  bucketeer_tpu.codec.pipeline; tiles batched per shape group so an
  image is at most four device calls) -> [host] EBCOT Tier-1 per
  code-block -> Tier-2 packets -> codestream -> JP2/JPX boxes.

This module is the orchestration; it works standalone on CPU (the same
jitted program runs on the host backend) so the service runs in a no-TPU
dev mode, mirroring how the reference degrades to OpenJPEG when Kakadu is
absent (reference: converters/ConverterFactory.java:37-47).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import codestream as cs
from . import jp2 as jp2box
from . import t1, t1_batch, t2
from .pipeline import TilePlan, extract_bands, make_plan, run_tiles
from .quant import GUARD_BITS, SubbandQuant

CBLK_EXP = 6  # 64x64 code-blocks (reference recipe Cblk={64,64})


@dataclass
class EncodeParams:
    lossless: bool = True
    levels: int = 5
    tile_size: int | None = None       # None = single tile (whole image)
    base_delta: float = 0.5            # irreversible base step (image domain)
    n_layers: int = 1
    progression: int = cs.PROG_LRCP
    comment: str = "bucketeer-tpu jp2 encoder"


@dataclass
class _Band:
    name: str           # LL / HL / LH / HH
    mags: np.ndarray    # uint magnitudes (quantizer indices)
    signs: np.ndarray
    q: SubbandQuant
    blocks: list = field(default_factory=list)        # t1.CodedBlock, raster
    grid: tuple = (0, 0)                              # (nblocks_h, nblocks_w)


def _collect_blocks(band: _Band, specs: list, dests: list) -> None:
    """Append this band's code-block inputs to the global batch."""
    h, w = band.mags.shape
    if h == 0 or w == 0:
        band.grid = (0, 0)
        return
    nbh = (h + (1 << CBLK_EXP) - 1) >> CBLK_EXP
    nbw = (w + (1 << CBLK_EXP) - 1) >> CBLK_EXP
    band.grid = (nbh, nbw)
    for by in range(nbh):
        for bx in range(nbw):
            y0, x0 = by << CBLK_EXP, bx << CBLK_EXP
            specs.append((band.mags[y0:y0 + 64, x0:x0 + 64],
                          band.signs[y0:y0 + 64, x0:x0 + 64], band.name))
            dests.append(band)


def _tile_bands(planes: np.ndarray, plan: TilePlan, specs: list,
                dests: list):
    """(C, h, w) coefficient planes -> [component][resolution] band lists,
    queueing code-block inputs into the global Tier-1 batch."""
    comp_res = []
    for c in range(planes.shape[0]):
        resolutions = []
        for res in extract_bands(planes[c], plan):
            bands = []
            for slot, mags, signs in res:
                band = _Band(slot.name, mags, signs, slot.quant)
                _collect_blocks(band, specs, dests)
                bands.append(band)
            resolutions.append(bands)
        comp_res.append(resolutions)
    return comp_res


def _tile_packets(comp_resolutions: list, n_layers: int,
                  progression: int) -> bytes:
    """Build the packet stream for one tile. comp_resolutions:
    [component][resolution] -> list[_Band]."""
    n_comps = len(comp_resolutions)
    n_res = len(comp_resolutions[0])

    # Build Tier-2 precinct state (default precincts: one per band).
    precincts = {}  # (comp, res) -> list[t2.Precinct]
    for c in range(n_comps):
        for r in range(n_res):
            plist = []
            for band in comp_resolutions[c][r]:
                nbh, nbw = band.grid
                prec = t2.Precinct(nbw, nbh)
                for i, blk in enumerate(band.blocks):
                    pb = t2.PrecinctBlock(
                        missing_bitplanes=band.q.n_bitplanes - blk.n_bitplanes)
                    if blk.n_bitplanes > 0:
                        pb.layers = _layer_split(blk, n_layers)
                    prec.blocks[i] = pb
                plist.append(prec)
            precincts[(c, r)] = plist

    out = bytearray()
    if progression == cs.PROG_LRCP:
        order = ((l, r, c) for l in range(n_layers)
                 for r in range(n_res) for c in range(n_comps))
    elif progression == cs.PROG_RLCP:
        order = ((l, r, c) for r in range(n_res)
                 for l in range(n_layers) for c in range(n_comps))
    else:
        # RPCL/PCRL/CPRL need per-precinct position iteration; until the
        # precinct machinery lands, refuse rather than emit a codestream
        # whose packet order contradicts its COD marker.
        raise NotImplementedError(
            f"progression {progression} not yet supported (LRCP/RLCP only)")
    for l, r, c in order:
        out += t2.encode_packet(precincts[(c, r)], l, n_layers)
    return bytes(out)


def _layer_split(blk: t1.CodedBlock, n_layers: int) -> dict:
    """Assign coding passes to quality layers. Single-layer: everything in
    layer 0. (PCRD-opt multi-layer allocation plugs in here.)"""
    if not blk.passes:
        return {}
    return {0: t2.BlockLayer(len(blk.passes), blk.data)}


def encode_array(img: np.ndarray, bitdepth: int = 8,
                 params: EncodeParams | None = None) -> bytes:
    """Encode a (H, W) or (H, W, 3) array into a raw JPEG 2000 codestream."""
    params = params or EncodeParams()
    h, w = img.shape[:2]
    n_comps = 1 if img.ndim == 2 else img.shape[2]
    assert n_comps in (1, 3), "components must be 1 or 3"
    tile = params.tile_size or max(h, w)
    levels = params.levels

    if img.ndim == 2:
        img = img[..., None]

    # Group tiles by shape: interior tiles batch into one device call;
    # ragged right/bottom tiles form up to three more groups.
    n_tiles_x = (w + tile - 1) // tile
    n_tiles_y = (h + tile - 1) // tile
    groups: dict = {}
    for ty in range(n_tiles_y):
        for tx in range(n_tiles_x):
            y0, x0 = ty * tile, tx * tile
            th, tw = min(tile, h - y0), min(tile, w - x0)
            groups.setdefault((th, tw), []).append(
                (ty * n_tiles_x + tx, y0, x0))

    # Phase 1: device transforms (batched per shape group) and code-block
    # collection across the whole image.
    specs: list = []
    dests: list = []
    tile_records = []
    qcd_values = None
    for (th, tw), members in groups.items():
        plan = make_plan(th, tw, n_comps, levels, params.lossless, bitdepth,
                         params.base_delta)
        batch = np.stack([img[y0:y0 + th, x0:x0 + tw]
                          for _, y0, x0 in members])
        planes = run_tiles(plan, batch)              # (B, C, th, tw)
        if qcd_values is None:
            qcd_values = _qcd_values(plan)
        for (tidx, _, _), tile_planes in zip(members, planes):
            comp_res = _tile_bands(tile_planes, plan, specs, dests)
            tile_records.append((tidx, comp_res))

    # Phase 2: one Tier-1 batch over every code-block in the image (native
    # thread pool when available).
    for band, blk in zip(dests, t1_batch.encode_blocks(specs)):
        assert blk.n_bitplanes <= band.q.n_bitplanes, (
            f"block bitplanes {blk.n_bitplanes} exceed Mb "
            f"{band.q.n_bitplanes} in {band.name}")
        band.blocks.append(blk)
    # Coefficients are fully entropy-coded now; drop them so a huge image
    # doesn't hold every tile's magnitude/sign planes through Tier-2.
    specs.clear()
    dests.clear()
    for _, comp_res in tile_records:
        for resolutions in comp_res:
            for bands in resolutions:
                for band in bands:
                    band.mags = band.signs = None

    # Phase 3: Tier-2 packets per tile.
    tiles = []
    for tidx, comp_res in tile_records:
        packets = _tile_packets(comp_res, params.n_layers,
                                params.progression)
        tiles.append((tidx, [], packets))
    tiles.sort(key=lambda item: item[0])

    used_mct = n_comps == 3
    segs = [
        cs.siz(w, h, n_comps, bitdepth, tile, tile),
        cs.cod(params.progression, params.n_layers,
               use_mct=used_mct, levels=levels,
               cblk_w_exp=CBLK_EXP, cblk_h_exp=CBLK_EXP,
               reversible=params.lossless),
        cs.qcd(0 if params.lossless else 2, GUARD_BITS, qcd_values),
    ]
    if params.comment:
        segs.append(cs.com(params.comment))
    return cs.assemble(segs, tiles)


def _qcd_values(plan: TilePlan) -> list:
    vals = []
    for slot in plan.slots:
        if plan.lossless:
            vals.append(slot.quant.exponent)
        else:
            vals.append((slot.quant.exponent, slot.quant.mantissa))
    return vals


def encode_jp2(img: np.ndarray, bitdepth: int = 8,
               params: EncodeParams | None = None, jpx: bool = False) -> bytes:
    """Encode to a boxed .jp2 / .jpx file image."""
    code = encode_array(img, bitdepth, params)
    h, w = img.shape[:2]
    n_comps = 1 if img.ndim == 2 else img.shape[2]
    return jp2box.wrap(code, w, h, n_comps, bitdepth, jpx=jpx)
