"""Fused device encode pipeline: the single XLA computation that replaces
the sample-transform half of ``kdu_compress`` (reference:
converters/KakaduConverter.java:38-44 — level shift, RCT/ICT, multi-level
DWT and quantization all happen inside the Kakadu binary; here they are
one jitted TPU program).

Design (TPU-first, SURVEY.md §7):
- A *plan* (:class:`TilePlan`) is built once per (tile shape, levels,
  lossless, bitdepth, components) combination on the host: subband
  geometry, signaled quantizer steps, and a per-pixel step map for the
  Mallat coefficient layout.
- The jitted transform maps a batch of same-shape tiles
  ``(B, h, w, C) -> (B, C, h, w) int32`` in one program: level shift +
  RCT/ICT + L-level lifting DWT + dead-zone quantization against the
  static step map. Everything is elementwise/concat on static shapes, so
  XLA fuses it into a few vectorized kernels and the batch dimension
  feeds the VPU lanes.
- Batch parallelism is plain leading-dim batching (no explicit vmap
  needed — the lifting kernels are written on the last two axes), which
  composes with ``shard_map`` over a device mesh (bucketeer_tpu.parallel).
- The host slices code-block inputs back out of the Mallat layout with
  :func:`extract_bands`; Tier-1 entropy coding consumes those.

Ragged images: JPEG 2000 edge tiles are genuinely smaller (SIZ defines
the tile grid), so the encoder groups tiles by shape and runs one device
batch per shape group — at most four shapes per image (interior, right
column, bottom row, corner), so recompiles stay bounded (SURVEY.md §7
hard part #4).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import graftcost, retrace
from ..analysis.contracts import contract
from .dwt import dwt2d_forward, synthesis_gains
from .quant import (FRAC_BITS, SubbandQuant, quantize_fp,
                    signal_irreversible, signal_reversible,
                    step_for_subband)
from .transforms import ict_forward, level_shift_forward, rct_forward


@dataclass(frozen=True)
class BandSlot:
    """One subband's rectangle inside the Mallat-layout coefficient plane.

    ``resolution`` 0 is the coarsest (LL); resolution r>0 holds the
    HL/LH/HH bands of decomposition level ``levels - r + 1`` — matching
    the packet resolution ordering of the codestream.
    """
    name: str            # LL / HL / LH / HH
    resolution: int
    y0: int
    x0: int
    h: int
    w: int
    quant: SubbandQuant


@dataclass(frozen=True)
class TilePlan:
    """Static encode plan for one tile shape."""
    tile_h: int
    tile_w: int
    n_comps: int
    levels: int
    lossless: bool
    bitdepth: int
    base_delta: float
    slots: tuple          # tuple[BandSlot], resolution-major, LL first
    used_mct: bool

    @property
    def shape(self):
        return (self.tile_h, self.tile_w)


def _band_geometry(h: int, w: int, levels: int):
    """Mallat-layout rectangles: [(name, level, y0, x0, bh, bw)] with level
    1 = finest. LL of the coarsest level is at the origin."""
    out = []
    ch, cw = h, w
    for lvl in range(1, levels + 1):
        nh, nw = (ch + 1) // 2, (cw + 1) // 2
        out.append(("HL", lvl, 0, nw, nh, cw - nw))
        out.append(("LH", lvl, nh, 0, ch - nh, nw))
        out.append(("HH", lvl, nh, nw, ch - nh, cw - nw))
        ch, cw = nh, nw
    out.append(("LL", levels, 0, 0, ch, cw))
    return out


@lru_cache(maxsize=256)
def make_plan(tile_h: int, tile_w: int, n_comps: int, levels: int,
              lossless: bool, bitdepth: int,
              base_delta: float = 0.5,
              use_mct: bool | None = None) -> TilePlan:
    """Build the static plan: geometry + signaled quantizer per subband.

    ``use_mct`` — apply the multi-component transform (RCT/ICT) to a
    3-component tile; None = yes whenever there are 3 components. The
    encoder passes an explicit value from its per-image adaptive choice
    (encoder._mct_helps)."""
    used_mct = n_comps == 3 if use_mct is None else (use_mct
                                                    and n_comps == 3)
    rct_extra = 1 if (used_mct and lossless) else 0
    ll_gain, gains = synthesis_gains(levels, lossless)

    slots = []
    geo = _band_geometry(tile_h, tile_w, levels)
    for name, lvl, y0, x0, bh, bw in geo:
        if name == "LL":
            res, gain = 0, ll_gain
        else:
            res = levels - lvl + 1
            gain = gains[lvl - 1][name]
        if lossless:
            q = signal_reversible(bitdepth, name, extra_bits=rct_extra)
        else:
            q = signal_irreversible(step_for_subband(base_delta, gain),
                                    bitdepth, name)
        slots.append(BandSlot(name, res, y0, x0, bh, bw, q))
    slots.sort(key=lambda s: (s.resolution, {"LL": 0, "HL": 1, "LH": 2,
                                             "HH": 3}[s.name]))
    return TilePlan(tile_h, tile_w, n_comps, levels, lossless, bitdepth,
                    base_delta, tuple(slots), used_mct)


def _step_map(plan: TilePlan) -> np.ndarray:
    """(h, w) float32 quantizer-step image over the Mallat layout."""
    m = np.ones((plan.tile_h, plan.tile_w), dtype=np.float32)
    for s in plan.slots:
        m[s.y0:s.y0 + s.h, s.x0:s.x0 + s.w] = s.quant.delta
    return m


def _mallat(ll: jnp.ndarray, bands: list) -> jnp.ndarray:
    """Assemble (..., H, W) Mallat layout from pyramid outputs by
    concatenation, coarsest-first (static shapes; XLA fuses the copies)."""
    for band in reversed(bands):
        top = jnp.concatenate([ll, band["HL"]], axis=-1)
        bot = jnp.concatenate([band["LH"], band["HH"]], axis=-1)
        ll = jnp.concatenate([top, bot], axis=-2)
    return ll


def _transform_batch(plan: TilePlan, step_map: jnp.ndarray,
                     batch: jnp.ndarray) -> jnp.ndarray:
    """(B, h, w, C) samples -> (B, C, h, w) int32 quantizer indices."""
    x = batch.astype(jnp.int32)
    x = level_shift_forward(x, plan.bitdepth)
    if plan.used_mct:
        ycc = rct_forward(x) if plan.lossless else ict_forward(
            x.astype(jnp.float32))
    else:
        ycc = x[..., None] if x.ndim == 3 else x
        if not plan.lossless:
            ycc = ycc.astype(jnp.float32)
    planes = jnp.moveaxis(ycc, -1, 1)            # (B, C, h, w)
    ll, bands = dwt2d_forward(planes, plan.levels, reversible=plan.lossless)
    coeffs = _mallat(ll, bands)
    if plan.lossless:
        return coeffs.astype(jnp.int32)
    return quantize_fp(coeffs, step_map)


def transform_program(plan: TilePlan):
    """(traceable fn, device donate_argnums) for the standalone sample
    transform — the construction :func:`compiled_transform` jits,
    shared with the device audit (analysis/deviceaudit.py). Donation of
    the sample batch is unusable here: the (B, h, w, C) input aval
    never matches the (B, C, h, w) coefficient output (axis order), so
    XLA would silently drop the alias — verified by the audit's forced
    lowering."""
    step_map = jnp.asarray(_step_map(plan)) if not plan.lossless else None
    return retrace.instrument(
        "transform", partial(_transform_batch, plan, step_map)), ()


@lru_cache(maxsize=256)
def compiled_transform(plan: TilePlan):
    """The jitted device computation for one plan. XLA still specializes
    on the batch size; callers bound retraces by padding B to a bucket
    size (:func:`run_tiles`)."""
    fn, donate = transform_program(plan)
    return jax.jit(fn, donate_argnums=donate_argnums_if_supported(*donate))


def donate_argnums_if_supported(*argnums) -> tuple:
    """Buffer-donation spec for ``jax.jit``: the requested argnums on
    backends that implement donation, ``()`` on CPU where donation is a
    no-op that warns per compile. The jitted entry points' large array
    operands are all freshly staged host arrays (``jnp.asarray`` of a
    numpy batch) that no caller reads after the launch, so aliasing them
    into the outputs halves the HBM high-water mark of a launch."""
    return argnums if jax.default_backend() != "cpu" else ()


def _bucket(b: int) -> int:
    """Round a batch size up to the next power of two so a long-running
    service compiles O(log max-batch) programs per tile shape, not one
    per distinct tile count."""
    n = 1
    while n < b:
        n <<= 1
    return n


@contract(shapes={"tiles": [("B", "h", "w"), ("B", "h", "w", "C")]},
          dtypes={"tiles": "number"})
def run_tiles(plan: TilePlan, tiles: np.ndarray) -> np.ndarray:
    """Encode-transform a (B, h, w[, C]) batch of tiles; returns
    (B, C, h, w) int32 on host."""
    if tiles.ndim == 3:
        tiles = tiles[..., None]
    b = tiles.shape[0]
    pad = _bucket(b) - b
    graftcost.record_bucket("transform.batch", b, b + pad)
    if pad:
        tiles = np.concatenate(
            [tiles, np.zeros((pad,) + tiles.shape[1:], tiles.dtype)])
    fn = compiled_transform(plan)
    out = fn(jnp.asarray(tiles))
    return np.asarray(jax.device_get(out))[:b]


def extract_bands(plane: np.ndarray, plan: TilePlan):
    """Slice one component's (h, w) int32 Mallat plane into
    resolution-major band arrays.

    Returns [resolution][band] of (slot, mags uint32, signs bool,
    fracs uint8|None). Lossy planes are fixed point with FRAC_BITS
    fractional magnitude bits (quantize_fp): the coded index is
    ``fp >> FRAC_BITS`` and the low bits drive Tier-1's distortion
    estimates. Lossless coefficients are exact integers (fracs=None).
    """
    n_res = plan.levels + 1
    resolutions = [[] for _ in range(n_res)]
    for s in plan.slots:
        idx = plane[s.y0:s.y0 + s.h, s.x0:s.x0 + s.w].astype(np.int64)
        mag = np.abs(idx)
        if plan.lossless:
            mags, fracs = mag.astype(np.uint32), None
        else:
            mags = (mag >> FRAC_BITS).astype(np.uint32)
            fracs = (mag & ((1 << FRAC_BITS) - 1)).astype(np.uint8)
        resolutions[s.resolution].append((s, mags, idx < 0, fracs))
    return resolutions
