"""Benchmark: lossy JP2 encode throughput (BASELINE.json config 1).

Encodes a synthetic photographic 4096x4096 RGB image to a lossy JP2
(9/7 DWT, 5 levels) end-to-end — device transform + Tier-1 entropy
coding + Tier-2/boxing — and reports MPixels/s against the 500 MPix/s
north star (BASELINE.json). Prints exactly one JSON line.

Env knobs: BENCH_SIZE (default 4096), BENCH_REPEATS (default 3).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_MPIX_S = 500.0


def synthetic_photo(size: int, seed: int = 7) -> np.ndarray:
    """Photograph-like content: smooth gradients + texture + edges, so the
    entropy coder sees realistic significance statistics."""
    rng = np.random.default_rng(seed)
    y, x = np.mgrid[0:size, 0:size]
    base = (128 + 96 * np.sin(2 * np.pi * x / size * 3)
            * np.cos(2 * np.pi * y / size * 2))
    texture = rng.normal(0, 12, size=(size, size))
    edges = ((x // 256 + y // 256) % 2) * 20
    img = np.stack([
        np.clip(base + texture + edges, 0, 255),
        np.clip(base * 0.8 + texture + 30, 0, 255),
        np.clip(base * 0.6 + texture + edges + 60, 0, 255),
    ], axis=-1)
    return img.astype(np.uint8)


def main() -> None:
    from bucketeer_tpu.codec import encoder
    from bucketeer_tpu.codec.encoder import EncodeParams

    size = int(os.environ.get("BENCH_SIZE", "4096"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    img = synthetic_photo(size)
    params = EncodeParams(lossless=False, levels=5, tile_size=1024,
                          base_delta=2.0)

    # Warmup: trigger XLA compilation so the steady-state rate is measured.
    encoder.encode_jp2(img[:1024, :1024], 8, params)

    times = []
    n_bytes = 0
    for _ in range(repeats):
        t0 = time.perf_counter()
        data = encoder.encode_jp2(img, 8, params)
        times.append(time.perf_counter() - t0)
        n_bytes = len(data)

    mpix = size * size / 1e6
    best = min(times)
    value = mpix / best
    print(json.dumps({
        "metric": "lossy_jp2_encode_throughput",
        "value": round(value, 3),
        "unit": "MPix/s",
        "vs_baseline": round(value / BASELINE_MPIX_S, 4),
        "detail": {
            "image": f"{size}x{size}x3 uint8",
            "seconds": round(best, 3),
            "output_bytes": n_bytes,
            "bpp": round(8.0 * n_bytes / (size * size), 3),
            "repeats": repeats,
        },
    }))


if __name__ == "__main__":
    main()
