"""graftgremlin: deterministic fault injection for the ingest path.

The graftrace seam (``analysis/graftrace/seam.py``) made thread
*interleavings* controllable; this module does the same for *failures*.
The batch path (``s3.py``, ``bus.py``, ``store.py``, ``batch.py``,
``workers.py``, ``scheduler.py``) marks its failure-prone moments with
:func:`point` — a no-op module-global load plus a ``None`` check in
production. A test (or the chaos CLI) installs a :class:`FaultPlan`
that decides, deterministically, which hits of which site raise what:
S3 5xx/timeout bursts, converter crashes, lock timeouts, journal I/O
errors, and process kills (:class:`ProcessKilled`, or a hard
``os._exit`` for real kill-and-restart smokes).

Every decision a plan makes is appended to ``plan.trace``, so two runs
of the same seeded scenario produce identical traces — replayable
bit-for-bit like graftrace schedules. Named seeded scenarios live in
:data:`SCENARIOS`.

Injection sites (grep for ``faults.point``):

========================  ====================================================
``s3.put``                before the S3 client call (5xx / timeout bursts)
``bus.request``           before enqueueing a bus request
``store.lock``            before acquiring the job lock (lock timeouts)
``journal.write``         before a WAL append (journal-unavailable, kills)
``batch.convert``         before the batch converter runs an item
``batch.status``          between derivative upload and status write — the
                          at-least-once window (kills land here)
``sched.submit``          encode-scheduler admission (forced QueueFull)
========================  ====================================================
"""
from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

_PLAN = None   # the installed FaultPlan; None in production


def install(plan) -> None:
    """Install (or, with None, remove) the active fault plan. Only
    tests and the chaos CLI call this."""
    global _PLAN
    _PLAN = plan


def active() -> bool:
    return _PLAN is not None


def current():
    return _PLAN


def point(site: str, **ctx) -> None:
    """A named injection point. No-op until a plan is installed; under
    a plan, the plan may raise (fault) or ``os._exit`` (hard kill)."""
    plan = _PLAN
    if plan is not None:
        plan.fire(site, ctx)


class ProcessKilled(BaseException):
    """Simulated process death at an injection point. Deliberately a
    ``BaseException``: the engine's ``except Exception`` failure
    handling must not swallow it — only the test harness's restart
    driver catches it, exactly like a real SIGKILL skips ``finally``
    blocks in spirit (we do run them; what matters is that no status
    is written past the kill point)."""


@dataclass
class FaultRule:
    site: str
    exc: Callable[[], BaseException] | None = None
    times: int = 1            # how many hits fault (after the skips)
    after: int = 0            # skip this many matching hits first
    p: float | None = None    # None => always; else seeded coin flip
    when: Callable[[dict], bool] | None = None
    kill: bool = False        # raise ProcessKilled
    hard_exit: int | None = None   # os._exit(code) — real kill
    hits: int = 0             # matching-hit counter (incl. skipped)
    fired: int = 0


class FaultPlan:
    """Deterministic scripted/seeded fault plan.

    ``at(site, exc=..., times=, after=, p=, when=, kill=, hard_exit=)``
    registers a rule; :meth:`fire` is called by :func:`point`. With
    ``p`` set, each eligible hit flips the plan's seeded RNG — same
    seed, same faults, bit-for-bit.
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self.trace: list[tuple] = []   # (seq, site, decision, detail)
        # point() fires from the event loop *and* worker threads (WAL
        # appends hop through asyncio.to_thread): hit counting and the
        # trace must not race.
        self._lock = threading.Lock()

    def at(self, site: str, exc=None, *, times: int = 1, after: int = 0,
           p: float | None = None, when=None, kill: bool = False,
           hard_exit: int | None = None) -> "FaultPlan":
        if exc is None and not kill and hard_exit is None:
            raise ValueError("rule needs exc=, kill=True or hard_exit=")
        with self._lock:
            self.rules.append(FaultRule(site, exc, times, after, p,
                                        when, kill, hard_exit))
        return self

    def _record(self, site: str, decision: str, detail: str) -> None:
        self.trace.append((len(self.trace), site, decision, detail))

    def fire(self, site: str, ctx: dict) -> None:
        with self._lock:
            self._fire_locked(site, ctx)

    def _fire_locked(self, site: str, ctx: dict) -> None:
        ruled = False
        for rule in self.rules:
            if rule.site != site:
                continue
            ruled = True
            if rule.when is not None and not rule.when(ctx):
                continue
            rule.hits += 1
            if rule.hits <= rule.after or rule.fired >= rule.times:
                continue
            if rule.p is not None:
                # Seeded coin flip; the draw itself is part of the
                # deterministic trace (same seed => same schedule).
                roll = self.rng.random()
                if roll >= rule.p:
                    self._record(site, "pass", f"roll={roll:.6f}")
                    continue
                detail = f"roll={roll:.6f}"
            else:
                detail = f"hit={rule.hits}"
            rule.fired += 1
            if rule.hard_exit is not None:
                self._record(site, "hard_exit", detail)
                self.flush_trace()
                os._exit(rule.hard_exit)
            if rule.kill:
                self._record(site, "kill", detail)
                raise ProcessKilled(f"{site} ({detail})")
            exc = rule.exc() if callable(rule.exc) else rule.exc
            self._record(site, f"raise:{type(exc).__name__}", detail)
            raise exc
        # Only *ruled* sites are traced: no-op hits at unruled sites
        # interleave freely across the event loop and WAL worker
        # threads, and recording them would break the bit-for-bit
        # trace comparison the replay workflow promises. Every site a
        # rule targets is hit from one deterministic task order.
        if ruled:
            self._record(site, "ok", "")

    # -- trace persistence (chaos CLI artifact) -------------------------

    trace_path: str | None = None

    def flush_trace(self) -> None:
        """Write the decision trace to ``trace_path`` (if set) — called
        before a hard exit and by the chaos CLI at the end of a run, so
        CI can upload the fault schedule as an artifact."""
        if not self.trace_path:
            return
        import json
        try:
            with open(self.trace_path, "w", encoding="utf-8") as fh:
                json.dump({"seed": self.seed, "trace": self.trace}, fh,
                          indent=0)
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            pass                      # tracing must never mask the run


# -- named seeded scenarios ---------------------------------------------
#
# Each factory returns a fresh plan for a seed; running the same
# (name, seed) twice yields identical ``plan.trace`` lists and, because
# every downstream retry delay draws from seeded RNGs, an identical
# ingest outcome. Exceptions are imported lazily to keep this module
# import-free of the engine (the engine imports *us*).

def _s3_outage(seed: int) -> FaultPlan:
    """Permanent S3 5xx outage: every put fails until the budget is
    spent — dead letters + open breaker, never a spin."""
    from .s3 import S3Error
    return FaultPlan(seed).at(
        "s3.put", lambda: S3Error(503, "injected outage"), times=10**9)


def _s3_burst(seed: int) -> FaultPlan:
    """Seeded 5xx burst: each put fails with p=0.5 for the first 40
    eligible hits, then the weather clears — the job must still finish."""
    from .s3 import S3Error
    return FaultPlan(seed).at(
        "s3.put", lambda: S3Error(500, "injected burst"), times=40,
        p=0.5)


def _s3_timeout(seed: int) -> FaultPlan:
    """S3 timeouts (treated as retryable 5xx-class) for the first 3
    puts."""
    return FaultPlan(seed).at(
        "s3.put", lambda: TimeoutError("injected S3 timeout"), times=3)


def _converter_crash(seed: int) -> FaultPlan:
    """The converter dies on its first two items (then recovers) — the
    items must resolve FAILED or be retried, never stranded."""
    from ..converters import ConverterError
    return FaultPlan(seed).at(
        "batch.convert", lambda: ConverterError("injected crash"),
        times=2)


def _lock_storm(seed: int) -> FaultPlan:
    """Transient job-lock timeouts on the first two status writes — the
    status-update retry loop must absorb them."""
    from .store import LockTimeout
    return FaultPlan(seed).at(
        "store.lock", lambda: LockTimeout("injected lock timeout"),
        times=2)


def _kill_mid_job(seed: int) -> FaultPlan:
    """Simulated process death in the at-least-once window (after the
    derivative upload, before the status write) of the second item."""
    return FaultPlan(seed).at("batch.status", after=1, kill=True)


SCENARIOS: dict[str, Callable[[int], FaultPlan]] = {
    "s3_outage": _s3_outage,
    "s3_burst": _s3_burst,
    "s3_timeout": _s3_timeout,
    "converter_crash": _converter_crash,
    "lock_storm": _lock_storm,
    "kill_mid_job": _kill_mid_job,
}


def make_plan(name: str, seed: int = 0) -> FaultPlan:
    try:
        factory = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown fault scenario {name!r}; "
            f"have: {', '.join(sorted(SCENARIOS))}")
    return factory(seed)
