"""Batch assembly: fan-out, merged dequant, sharded placement.

One admitted ``kind="batchread"`` request produces one
:class:`BatchResult`: per-item coefficient decodes fan out across a
thread pool (each rides the scheduler's device queue as a
``_DequantJob``, where compatible launches from sibling items merge
into one combined device program), and the surviving items assemble
into ONE per-subband batched tensor placed with
``NamedSharding(mesh, P("batch"))`` (SNIPPETS.md [2]) — bit-exact
against stacking per-image :func:`decode_to_coefficients` calls,
because the dequant program is elementwise per band.

Failure ladder (the production contract):

- unknown ids / mixed geometry / reduce beyond the coded levels /
  dtype mismatch — the *request* is wrong: typed
  :class:`InvalidParam`, detected by cheap main-header probes before
  any Tier-1 work runs;
- a corrupt item mid-decode — per-item typed failure in the batch
  manifest (``ok: false`` + error type), never all-or-nothing; only a
  batch with zero survivors raises :class:`DecodeError`;
- deadline expiry / scheduler shutdown — batch-fatal: the fan-out is
  drained (no pool worker stranded, no queued per-item job leaked —
  graftrace scenario ``batch_fanout_vs_read`` pins this) and the
  typed error propagates to the admission layer.
"""
from __future__ import annotations

import functools
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from .. import obs
from ..codec.decode import parser
from ..codec.decode.errors import DecodeError, InvalidParam
from ..engine.scheduler import DeadlineExceeded, SchedulerClosed
from ..tensor import coeffs as tcoeffs
from .recipe import BatchRecipe

BATCH_AXIS = "batch"

# Fan-out width: item decode threads per batch. Tier-1 is host work,
# so past the device-pool size extra threads only deepen the dequant
# merge window's fill — small by default.
_FANOUT = int(os.environ.get("BUCKETEER_BATCH_FANOUT", "8"))

_SINK = None

# One persistent fan-out pool for every batch: thread startup costs
# ~10ms of GIL-contended wall each on this class of host, which a
# per-request executor pays N times per batch — straight off the
# margin over decode-then-stack.
_POOL = None
_POOL_LOCK = threading.Lock()


def _fanout_pool() -> ThreadPoolExecutor:
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=max(1, _FANOUT),
                thread_name_prefix="batchread")
        return _POOL


def set_metrics_sink(sink) -> None:
    """Install the Metrics sink batch assembly records into (item
    failure counts, assembly seconds) — same pattern as
    tensor.codec.set_metrics_sink."""
    global _SINK
    _SINK = sink


@dataclass
class BatchResult:
    """One assembled batch: ``bands`` maps each subband key to a
    ``(N, C, H_b, W_b)`` device array whose leading axis is the batch,
    placed per ``layout`` (``sharded`` = ``P("batch")`` over the batch
    mesh, ``replicated`` = every device holds the full batch).
    ``ids`` are the surviving items in batch order — row ``i`` of every
    band belongs to ``ids[i]``; ``manifest`` records every *recipe*
    item, failed ones with their typed error."""
    ids: tuple
    bands: dict                  # (res, name) -> (N, C, Hb, Wb)
    deltas: dict                 # (res, name) -> quantizer step
    manifest: list               # [{"id", "ok", ["error", "message"]}]
    meta: dict = field(default_factory=dict)
    layout: str = "replicated"

    @property
    def n_items(self) -> int:
        return len(self.ids)

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.bands.values())

    def to_host(self) -> dict:
        """Materialize every batched band on host — the batch plane's
        one sanctioned device->host seam (rules_jax.D2H_SANCTIONED);
        training consumers keep the sharded device arrays instead."""
        import jax

        return {key: np.asarray(jax.device_get(arr))
                for key, arr in self.bands.items()}


def batch_mesh_program(reversible: bool, deltas: tuple):
    """(traceable fn, donate_argnums) for the *batched* dequant as the
    merged device launch runs it — audit seam (analysis/deviceaudit.py
    ``batch.assemble.dequant`` entries, and graftmesh's sharded
    lowering under the forced 8-device mesh: elementwise per band, so
    the expected collective set is empty). Identical program to
    :func:`tensor.coeffs.dequant_program`; the batch axis rides the
    shape polymorphism."""
    return tcoeffs.dequant_program(reversible, deltas)


def _error_entry(image_id: str, exc: BaseException) -> dict:
    return {"id": image_id, "ok": False,
            "error": type(exc).__name__, "message": str(exc)}


def _probe_items(recipe: BatchRecipe, blobs: dict):
    """Cheap main-header pass over every item before any Tier-1 work:
    request-shaped problems (mixed geometry, reduce beyond levels,
    dtype mismatch) become one typed InvalidParam; per-item corrupt
    headers become upfront manifest failures. Returns (ok ids,
    manifest entries for the failures, reference geometry)."""
    geom = {}
    failed = []
    for image_id in recipe.ids:
        try:
            geom[image_id] = parser.probe(blobs[image_id])
        except DecodeError as exc:
            failed.append(_error_entry(image_id, exc))
    ok_ids = [i for i in recipe.ids if i in geom]
    if not ok_ids:
        raise DecodeError(
            "every item in the batch failed the header probe")

    sigs = {i: (g["width"], g["height"], g["n_comps"], g["levels"],
                g["reversible"]) for i, g in geom.items()}
    ref_id = ok_ids[0]
    ref = sigs[ref_id]
    mixed = sorted(i for i in ok_ids if sigs[i] != ref)
    if mixed:
        raise InvalidParam(
            f"mixed geometry: {', '.join(mixed)} differ from "
            f"{ref_id} (batch items must share width/height/"
            f"components/levels/reversibility)")
    if recipe.reduce > ref[3]:
        raise InvalidParam(
            f"reduce={recipe.reduce} beyond the {ref[3]} coded "
            f"decomposition levels")
    want = {"int32": True, "float32": False}.get(recipe.dtype)
    if want is not None and ref[4] != want:
        have = "int32" if ref[4] else "float32"
        raise InvalidParam(
            f"dtype={recipe.dtype} but the codestreams are "
            f"{'reversible' if ref[4] else 'irreversible'} ({have})")
    if recipe.region is not None:
        x, y, w, h = recipe.region
        if x >= ref[0] or y >= ref[1]:
            raise InvalidParam(
                f"region origin ({x}, {y}) outside the "
                f"{ref[0]}x{ref[1]} image")
    return ok_ids, failed, geom[ref_id]


def _placement(n: int, layout: str):
    """The batch mesh + sharding for an ``n``-item batch: a 1-D
    ``("batch",)`` mesh over every visible device, ``P("batch")`` when
    the batch divides it (SNIPPETS.md [2] rule), replicated otherwise.
    ``layout="sharded"`` fails closed instead of falling back."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, (BATCH_AXIS,))
    if layout == "replicated":
        return mesh, NamedSharding(mesh, P()), "replicated"
    divides = n % len(devices) == 0
    if layout == "sharded" and not divides:
        raise InvalidParam(
            f"layout=sharded but the {n}-item batch does not divide "
            f"the {len(devices)}-device mesh")
    if divides:
        return mesh, NamedSharding(mesh, P(BATCH_AXIS)), "sharded"
    return mesh, NamedSharding(mesh, P()), "replicated"


@functools.lru_cache(maxsize=1)
def _stack_fn():
    """One fused stack program for every band at once: each band's
    per-item arrays concatenate along the new batch axis in a single
    dispatch, instead of one jnp.stack per band. It runs where the
    inputs live (the dequant pool device); mesh placement is the
    device_put that follows — jit with ``out_shardings`` would reject
    the pool-committed inputs on a multi-device mesh."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda parts: [jnp.stack(p) for p in parts])


@functools.lru_cache(maxsize=1)
def _gather_fn():
    """The fast assembly path when one merged dequant launch covered
    the whole batch: every item's bands are :class:`BandSlice` views
    of one shared batched output, so assembly is a single fused
    row-gather (batch order, surviving rows only) — no per-image slice
    or re-stack dispatches at all."""
    import jax

    return jax.jit(lambda parts, idx: [p[idx] for p in parts])


def assemble_batch(recipe: BatchRecipe, *, data_for=None) -> BatchResult:
    """Assemble one batch under the CALLER's admission: run this
    through ``scheduler.submit_batchread`` so the deadline hook and the
    merged-dequant launch hook are installed (``coeff_services``) —
    standalone calls still work, with inline dequant and no deadline.

    ``data_for(image_id)`` returns the item's JP2/JPX bytes or None
    for unknown ids (the server binds the derivative store; tests and
    bench bind dicts)."""
    import time as _time

    if data_for is None:
        from ..converters import derivative_path

        def data_for(image_id):
            path = derivative_path(image_id)
            if path is None or not os.path.exists(path):
                return None
            with open(path, "rb") as fh:
                return fh.read()

    t0 = _time.perf_counter()
    blobs, unknown = {}, []
    for image_id in recipe.ids:
        data = data_for(image_id)
        if data is None:
            unknown.append(image_id)
        else:
            blobs[image_id] = data
    if unknown:
        raise InvalidParam(f"unknown image ids: {', '.join(unknown)}")

    ok_ids, upfront_failed, _ = _probe_items(recipe, blobs)

    # The admitted request thread owns the scheduler hooks
    # (thread-locals): capture them here, re-install in every item
    # worker with the fan-out width bound so the device worker's merge
    # window knows how many compatible dequant launches to wait for.
    check, launch = tcoeffs.current_services()
    n = len(ok_ids)
    # Only min(n, fan-out width) items decode concurrently, so that is
    # the most compatible dequant launches the merge window can ever
    # see at once — advertising n would burn the window waiting for
    # stragglers that cannot arrive.
    expected = min(n, max(1, _FANOUT))
    bound_launch = None
    if launch is not None:
        def bound_launch(reversible, deltas, arrays):
            return launch(reversible, deltas, arrays,
                          _expected=expected)
    parent_ctx = obs.current_context()
    request_id = obs.current_request_id()

    def decode_item(idx: int):
        image_id = ok_ids[idx]
        with obs.request_context(request_id), \
                obs.use_context(parent_ctx), \
                obs.span("batchread.item", image_id=image_id,
                         index=idx), \
                tcoeffs.coeff_services(check=check,
                                       launch=bound_launch):
            return tcoeffs.decode_to_coefficients(
                blobs[image_id], region=recipe.region,
                reduce=recipe.reduce, layers=recipe.layers)

    sets: list = [None] * n
    failures: dict = {}
    fatal: BaseException | None = None
    futs = {_fanout_pool().submit(decode_item, i): i
            for i in range(n)}
    # The result loop waits on EVERY item, fatal or not: a batch-fatal
    # error never leaves a pool worker holding a queued dequant job
    # the caller no longer waits for.
    for fut in futs:
        i = futs[fut]
        try:
            sets[i] = fut.result()
        except (DeadlineExceeded, SchedulerClosed) as exc:
            fatal = fatal or exc
        except DecodeError as exc:
            failures[i] = _error_entry(ok_ids[i], exc)
            if _SINK is not None:
                _SINK.count("batchread.item_failures")
    if fatal is not None:
        raise fatal

    manifest = list(upfront_failed)
    kept_ids, kept_sets = [], []
    for i, image_id in enumerate(ok_ids):
        if i in failures:
            manifest.append(failures[i])
        else:
            manifest.append({"id": image_id, "ok": True})
            kept_ids.append(image_id)
            kept_sets.append(sets[i])
    # Manifest rows in recipe order, like the batch axis.
    order = {image_id: k for k, image_id in enumerate(recipe.ids)}
    manifest.sort(key=lambda e: order[e["id"]])
    if not kept_sets:
        raise DecodeError("every item in the batch failed to decode")

    ref = kept_sets[0]
    mesh, sharding, layout = _placement(len(kept_sets), recipe.layout)
    with obs.span("batchread.assemble", items=len(kept_sets),
                  layout=layout, bands=len(ref.bands)):
        keys = list(ref.bands)
        cols = [[cs.bands[key] for cs in kept_sets] for key in keys]
        shared = all(
            isinstance(v, tcoeffs.BandSlice)
            and v.parent is col[0].parent
            for col in cols for v in col)
        if shared:
            # Every item rode ONE merged dequant launch: gather its
            # rows out of the shared batched output in batch order.
            idx = np.asarray([v.index for v in cols[0]],
                             dtype=np.int32)
            stacked = _gather_fn()(
                [col[0].parent for col in cols], idx)
        else:
            # Items landed in different launches (window split,
            # partial failure mid-wave): stack per item. One fused
            # device program — device-to-device, no host round-trip.
            stacked = _stack_fn()(
                [[v.materialize()
                  if isinstance(v, tcoeffs.BandSlice) else v
                  for v in col] for col in cols])
        # Mesh placement last: the stack/gather ran on the dequant
        # pool device, device_put reshards onto the batch mesh (a
        # no-op when the mesh IS that device).
        import jax

        bands = dict(zip(keys, jax.device_put(stacked, sharding)))

    meta = {"width": ref.width, "height": ref.height,
            "n_comps": ref.n_comps, "bitdepth": ref.bitdepth,
            "levels": ref.levels, "reduce": ref.reduce,
            "reversible": ref.reversible, "used_mct": ref.used_mct,
            "region": recipe.region, "layers": recipe.layers,
            "n_devices": len(mesh.devices.flat)}
    if _SINK is not None:
        _SINK.count("batchread.batches")
        _SINK.count("batchread.items", len(kept_sets))
        _SINK.record("batchread.assemble",
                     _time.perf_counter() - t0)
    return BatchResult(ids=tuple(kept_ids), bands=bands,
                       deltas=dict(ref.deltas), manifest=manifest,
                       meta=meta, layout=layout)
