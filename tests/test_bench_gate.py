"""bench_gate.py: the CI throughput regression gate's decision logic
and JSON-line extraction."""
import json
import sys

sys.path.insert(0, ".")          # bench_gate lives at the repo root
import bench_gate  # noqa: E402


def _rep(value, platform="cpu", **kw):
    out = {"value": value, "unit": "MPix/s", "platform": platform,
           "device_run_valid": True}
    out.update(kw)
    return out


def test_within_tolerance_passes():
    ok, msg = bench_gate.check(_rep(0.97), _rep(1.0), 5.0)
    assert ok and "-" not in msg.split("(")[0]


def test_loss_beyond_tolerance_fails():
    ok, msg = bench_gate.check(_rep(0.90), _rep(1.0), 5.0)
    assert not ok
    assert "10.0% loss" in msg


def test_faster_always_passes():
    ok, _ = bench_gate.check(_rep(2.0), _rep(1.0), 5.0)
    assert ok


def test_platform_mismatch_skips():
    ok, msg = bench_gate.check(_rep(0.01, platform="cpu"),
                               _rep(100.0, platform="tpu"), 5.0)
    assert ok and "mismatch" in msg


def test_machine_mismatch_relaxes_threshold():
    ref = _rep(1.0, machine={"arch": "x86_64", "cpu_count": 64})
    # 20% loss: beyond the strict 5% limit but within the relaxed
    # cross-machine one — passes with the mismatch note.
    ok, msg = bench_gate.check(
        _rep(0.8, machine={"arch": "x86_64", "cpu_count": 2}), ref, 5.0)
    assert ok and "machine mismatch" in msg
    # 50% loss: a halved pipeline fails even across machine classes.
    cur = _rep(0.5, machine={"arch": "x86_64", "cpu_count": 2})
    ok, msg = bench_gate.check(cur, ref, 5.0)
    assert not ok and "limit 40%" in msg
    # --force applies the strict threshold despite the mismatch.
    ok, msg = bench_gate.check(cur, ref, 5.0, force=True)
    assert not ok and "limit 5%" in msg


def test_workload_smoke_mismatch_skips():
    ok, msg = bench_gate.check(_rep(0.5, smoke=True),
                               _rep(1.0, smoke=False), 5.0)
    assert ok and "workload mismatch" in msg


def test_same_machine_gates():
    m = {"arch": "x86_64", "cpu_count": 4}
    ok, _ = bench_gate.check(_rep(0.5, machine=m), _rep(1.0, machine=m),
                             5.0)
    assert not ok


def test_invalid_device_run_never_gates_device_reference():
    cur = _rep(1.0, platform="tpu", device_run_valid=False,
               platform_fallback=True)
    ok, msg = bench_gate.check(cur, _rep(100.0, platform="tpu"), 5.0)
    assert ok and "invalid device run" in msg


def test_missing_headline_value_fails():
    ok, _ = bench_gate.check(_rep(0.0), _rep(1.0), 5.0)
    assert not ok


def test_empty_reference_skips():
    ok, msg = bench_gate.check(_rep(1.0), _rep(0.0), 5.0)
    assert ok and "skipped" in msg


def test_load_report_takes_last_json_line(tmp_path):
    p = tmp_path / "run.json"
    p.write_text("# log noise\n" + json.dumps({"value": 1}) + "\n"
                 + json.dumps({"value": 2, "platform": "cpu"}) + "\n")
    assert bench_gate.load_report(str(p))["value"] == 2


def test_main_exit_codes(tmp_path):
    cur = tmp_path / "cur.json"
    ref = tmp_path / "ref.json"
    ref.write_text(json.dumps(_rep(1.0)) + "\n")
    cur.write_text(json.dumps(_rep(0.98)) + "\n")
    assert bench_gate.main([str(cur), str(ref)]) == 0
    cur.write_text(json.dumps(_rep(0.5)) + "\n")
    assert bench_gate.main([str(cur), str(ref)]) == 1
    assert bench_gate.main([str(cur), str(ref),
                            "--max-loss-pct=60"]) == 0
    assert bench_gate.main([str(cur)]) == 2
