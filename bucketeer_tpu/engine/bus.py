"""Asyncio message bus: the replacement for the Vert.x event bus that
connects the reference's worker verticles (reference:
verticles/AbstractBucketeerVerticle.java:63-96).

Semantics kept from the reference:
- consumers are registered under a string address (there: the verticle
  class name);
- request/reply with three reply ops — ``success``, ``retry`` (the
  backpressure signal), and ``failure(code, message)``
  (reference: Op.java:34-42);
- senders that receive ``retry`` requeue after a delay.

TPU-first differences: consumers are async coroutines multiplexed on
the event loop with bounded per-address queues — worker concurrency
comes from ``instances`` (parallel consumer tasks), the analog of
verticle instances x worker-pool threads (reference:
MainVerticle.java:212-242) — and the reference's *infinite fixed-delay*
requeue loop (reference: AbstractBucketeerVerticle.java:76-96) is
replaced by the unified :class:`~.retry.RetryPolicy`: bounded attempts
with exponential backoff + full jitter, per-address circuit breakers
(``self.breakers``), and a dead-letter record for messages that exhaust
their budget instead of spinning forever.
"""
from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from .. import constants as c
from .. import op
from . import faults
from .retry import (BreakerRegistry, DeadLetterLog, RetryPolicy,
                    count_metric)

LOG = logging.getLogger(__name__)

Handler = Callable[[dict], Awaitable["Reply"]]


@dataclass
class Reply:
    """A consumer's reply: op + optional body/failure details."""

    op: str = op.SUCCESS
    body: dict = field(default_factory=dict)
    code: int = 0
    message: str = ""

    @property
    def is_success(self) -> bool:
        return self.op == op.SUCCESS

    @property
    def is_retry(self) -> bool:
        return self.op == op.RETRY

    @classmethod
    def success(cls, body: dict | None = None) -> "Reply":
        return cls(op.SUCCESS, body or {})

    @classmethod
    def retry(cls) -> "Reply":
        return cls(op.RETRY)

    @classmethod
    def failure(cls, code: int, message: str) -> "Reply":
        return cls(op.FAILURE, {}, code, message)


class BusError(RuntimeError):
    def __init__(self, code: int, message: str) -> None:
        self.code = code
        super().__init__(message)


class BusClosed(BusError):
    """The bus was closed: pending request futures are cancelled with
    this (mirroring the scheduler's typed ``SchedulerClosed``), and
    ``send``/``request`` on a closed bus raise it immediately instead
    of parking the sender forever."""

    def __init__(self, address: str = "") -> None:
        where = f" (to {address})" if address else ""
        super().__init__(503, f"message bus is closed{where}")


@dataclass
class _Consumer:
    handler: Handler
    queue: asyncio.Queue
    tasks: list = field(default_factory=list)


class MessageBus:
    """In-process async request/reply bus."""

    def __init__(self, retry_delay: float = 1.0,
                 retry_policy: RetryPolicy | None = None,
                 seed: int = 0) -> None:
        self._consumers: dict[str, _Consumer] = {}
        self.retry_delay = retry_delay
        # Default policy: backoff starts at the configured requeue
        # delay; jitter draws from a per-bus seeded RNG so fault
        # scenarios replay their retry schedules bit-for-bit.
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=32, base_delay=retry_delay,
            max_delay=max(retry_delay, min(30.0, retry_delay * 30)))
        self._rng = random.Random(seed)
        self.breakers = BreakerRegistry()
        self.dead_letters = DeadLetterLog()
        self._pending: set[asyncio.Future] = set()
        self._closed = False

    def consumer(self, address: str, handler: Handler,
                 instances: int = 1, queue_size: int = 0) -> None:
        """Register ``instances`` parallel consumer tasks on ``address``
        (reference analog: verticle instances, MainVerticle.java:229-242)."""
        if address in self._consumers:
            raise ValueError(f"consumer already registered: {address}")
        con = _Consumer(handler, asyncio.Queue(maxsize=queue_size))
        for i in range(max(1, instances)):
            con.tasks.append(
                asyncio.create_task(self._consume(address, con),
                                    name=f"bus-{address}-{i}"))
        self._consumers[address] = con

    def addresses(self) -> list[str]:
        return sorted(self._consumers)

    async def _consume(self, address: str, con: _Consumer) -> None:
        while True:
            message, future = await con.queue.get()
            try:
                reply = await con.handler(message)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # handler bug -> failure reply
                LOG.exception("handler error on %s", address)
                reply = Reply.failure(500, f"{type(exc).__name__}: {exc}")
            if future is not None and not future.done():
                future.set_result(reply)
            con.queue.task_done()

    def _track(self, future: asyncio.Future) -> None:
        self._pending.add(future)
        future.add_done_callback(self._pending.discard)

    async def request(self, address: str, message: dict,
                      timeout: float | None = None) -> Reply:
        """Send and await one reply (may be ``retry``; see
        :meth:`request_with_retry` for the requeue loop)."""
        if self._closed:
            raise BusClosed(address)
        faults.point("bus.request", address=address)
        con = self._consumers.get(address)
        if con is None:
            raise BusError(404, f"no consumer at {address}")
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._track(future)
        await con.queue.put((message, future))
        if timeout:
            return await asyncio.wait_for(future, timeout)
        return await future

    async def request_with_retry(self, address: str, message: dict,
                                 retry_delay: float | None = None,
                                 policy: RetryPolicy | None = None
                                 ) -> Reply:
        """Send, and on a ``retry`` reply back off and resend — bounded
        by the unified :class:`RetryPolicy` (the reference retried
        forever at a fixed delay; AbstractBucketeerVerticle.java:76-96).

        When the address has a circuit breaker (``self.breakers``) and
        it is open, attempts fast-fail locally (no enqueue) and wait for
        the half-open window instead — still drawing from the same
        bounded budget. Exhausting the budget dead-letters the message
        and returns a 503 ``failure`` reply. Raises :class:`BusClosed`
        if the bus closes at any point of the loop.
        """
        policy = policy or self.retry_policy
        if retry_delay is not None:
            policy = policy.with_base(retry_delay)
        attempt = 0
        last = "retry requested by consumer"
        while True:
            if self._closed:
                raise BusClosed(address)
            breaker = self.breakers.lookup(address)
            if breaker is not None and breaker.is_open:
                # Fast-fail: nothing is enqueued toward a dead target;
                # wait out (part of) the open window instead.
                wait = min(breaker.time_until_ready(), policy.max_delay)
                last = f"circuit open (retry in {wait:.1f}s)"
            else:
                reply = await self.request(address, message)
                if not reply.is_retry:
                    return reply
                wait = policy.delay(attempt, self._rng)
            attempt += 1
            count_metric("retry.attempts")
            if policy.exhausted(attempt):
                self.dead_letters.record(
                    address, attempt, last,
                    image_id=message.get(c.IMAGE_ID),
                    job_name=message.get(c.JOB_NAME))
                LOG.error("dead-letter on %s after %d attempts: %s",
                          address, attempt, last)
                return Reply.failure(
                    503, f"{address}: retry budget exhausted after "
                         f"{attempt} attempts ({last})")
            LOG.debug("retry %d from %s; backing off %.3fs", attempt,
                      address, wait)
            await asyncio.sleep(wait)

    async def send(self, address: str, message: dict) -> None:
        """Fire-and-forget (reference: eventBus.send)."""
        if self._closed:
            raise BusClosed(address)
        con = self._consumers.get(address)
        if con is None:
            raise BusError(404, f"no consumer at {address}")
        await con.queue.put((message, None))

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for con in self._consumers.values():
            for task in con.tasks:
                task.cancel()
        for con in self._consumers.values():
            for task in con.tasks:
                try:
                    await task
                except asyncio.CancelledError:
                    pass          # the cancellation we just requested
                except Exception:
                    LOG.exception("consumer task died during bus close")
        # Senders parked on an unresolved request get a typed
        # cancellation, never an eternal await (the pre-PR-11 hang).
        for future in list(self._pending):
            if not future.done():
                future.set_exception(BusClosed())
                # Mark retrieved so a sender that already gave up (e.g.
                # timed out) doesn't trigger the GC never-retrieved
                # warning; awaiting senders still see the exception.
                future.exception()
        self._pending.clear()
        self._consumers.clear()
