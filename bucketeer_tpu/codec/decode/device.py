"""Device-side decode back half: dequantization, multi-level inverse DWT
and inverse RCT/ICT as one jitted XLA program per reconstructed tile
shape — the inference-path mirror of ``pipeline._transform_batch``.

The host Tier-1 decoder hands over signed half-magnitude integers
(``t1_dec``: ``|hval| = 2*(m + 0.5) * 2^p``) assembled into the Mallat
layout of the *reduced* tile (partial decode drops the finest
resolutions before anything reaches the device). Dequantization is then
uniform over the layout:

- reversible (5/3): exact coefficient = ``sign * (|hval| >> 1)`` — the
  midpoint half-bit floors away, so full lossless decodes are bit-exact
  and truncated ones match OpenJPEG's integer reconstruction;
- irreversible (9/7): coefficient = ``hval * (delta_b / 2)`` against a
  static per-pixel half-step map, the decode twin of the encoder's
  ``_step_map``.

Like the encode pipeline, everything is static-shaped elementwise/concat
work XLA fuses into a few kernels; batches of same-shape tiles share one
program, padded to power-of-two bucket sizes to bound retraces.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis import retrace
from ...analysis.contracts import contract
from ..dwt import dwt2d_inverse
from ..pipeline import _band_geometry, _bucket
from ..transforms import ict_inverse, level_shift_inverse, rct_inverse


@dataclass(frozen=True)
class InversePlan:
    """Static decode plan for one reconstructed tile shape. ``slots``
    carries (name, level, y0, x0, h, w, delta) rectangles of the reduced
    Mallat layout — deltas are the *signaled* steps from QCD, so the
    decoder dequantizes with exactly what the encoder quantized with."""
    tile_h: int              # reduced tile height (after ``reduce``)
    tile_w: int
    n_comps: int
    levels: int              # levels remaining after ``reduce``
    reversible: bool
    bitdepth: int
    used_mct: bool
    slots: tuple             # ((name, level, y0, x0, h, w, delta), ...)


def make_inverse_plan(rh: int, rw: int, n_comps: int, levels: int,
                      reversible: bool, bitdepth: int, used_mct: bool,
                      delta_of) -> InversePlan:
    """``delta_of(level, name) -> float`` maps a reduced-layout band to
    its signaled quantizer step (level as in ``_band_geometry``: 1 =
    finest of the reduced tile; the LL entry uses its own level)."""
    slots = tuple(
        (name, lvl, y0, x0, bh, bw, float(delta_of(lvl, name)))
        for name, lvl, y0, x0, bh, bw in _band_geometry(rh, rw, levels))
    return InversePlan(rh, rw, n_comps, levels, reversible, bitdepth,
                       used_mct, slots)


def _half_step_map(plan: InversePlan) -> np.ndarray:
    """(h, w) float32 map of delta_b / 2 over the reduced Mallat layout
    (hvals are in doubled units, so the half step lands on delta)."""
    m = np.ones((plan.tile_h, plan.tile_w), dtype=np.float32)
    for _, _, y0, x0, bh, bw, delta in plan.slots:
        m[y0:y0 + bh, x0:x0 + bw] = delta * 0.5
    return m


def _inverse_body(plan: InversePlan, half_map, hv: jnp.ndarray):
    """(B, C, h, w) int32 half-magnitudes -> (B, h, w, C) int32 samples."""
    if plan.reversible:
        mag = jnp.abs(hv) >> 1
        vals = jnp.where(hv < 0, -mag, mag)
    else:
        vals = hv.astype(jnp.float32) * half_map

    bands = [dict() for _ in range(plan.levels)]
    ll = None
    for name, lvl, y0, x0, bh, bw, _ in plan.slots:
        rect = vals[..., y0:y0 + bh, x0:x0 + bw]
        if name == "LL":
            ll = rect
        else:
            bands[lvl - 1][name] = rect
    img = dwt2d_inverse(ll, bands, plan.reversible)

    x = jnp.moveaxis(img, 1, -1)                  # (B, h, w, C)
    if plan.used_mct:
        x = rct_inverse(x) if plan.reversible else ict_inverse(x)
    x = level_shift_inverse(x, plan.bitdepth)
    if not plan.reversible:
        x = jnp.round(x)
    x = jnp.clip(x, 0, (1 << plan.bitdepth) - 1)
    return x.astype(jnp.int32)


@lru_cache(maxsize=256)
def _compiled_inverse(plan: InversePlan):
    half_map = (None if plan.reversible
                else jnp.asarray(_half_step_map(plan)))
    return jax.jit(retrace.instrument(
        "inverse", partial(_inverse_body, plan, half_map)))


@contract(shapes={"hvals": ("B", "C", "h", "w")},
          dtypes={"hvals": "integer"})
def run_inverse(plan: InversePlan, hvals: np.ndarray) -> np.ndarray:
    """Run the jitted inverse for a (B, C, h, w) int32 batch of decoded
    tile coefficient planes; returns (B, h, w, C) int32 samples on host.
    The batch is padded to a power-of-two bucket so a long-running read
    service compiles O(log max-batch) programs per tile shape."""
    b = hvals.shape[0]
    pad = _bucket(b) - b
    if pad:
        hvals = np.concatenate(
            [hvals, np.zeros((pad,) + hvals.shape[1:], hvals.dtype)])
    fn = _compiled_inverse(plan)
    out = fn(jnp.asarray(hvals))
    return np.asarray(jax.device_get(out))[:b]
