"""Pallas TPU kernel for the EBCOT CX/D stripe scan (codec/cxd.py).

The first hand-written kernel in this package. One code-block per grid
cell: the block's (64, 64) int32 coefficients land in VMEM, the kernel
runs the same stripe-column step function the jnp path scans with
(``cxd._make_step`` — shared verbatim, so the two implementations cannot
drift), carrying the significance state, symbol buffer and pass
counters through a ``lax.fori_loop`` over the P*3*1024 plane/pass/column
steps, and writes the per-block symbol stream + pass tables back out.

Why Pallas at all: the jnp formulation materializes the scan as an XLA
while-loop over (N, ...) batched state with one dynamic-slice/scatter
bundle per stripe column — fine on CPU, but on TPU the batched gathers
round-trip through HBM layouts the compiler picks. Here the whole
working set (state ~17 KB, symbol buffer ~100 KB, coefficients 16 KB)
is pinned in VMEM for the kernel's lifetime and only the finished
streams leave the core.

Compiled-TPU status: the kernel is a product path, not a parity
artifact. The grid's block axis is declared ``parallel``
(:func:`_tpu_params`) so Mosaic may fan code-blocks out across
TensorCores — every grid cell reads and writes disjoint slices — and
the batch axis is pow-2 bucketed upstream (frontend/scheduler batch
buckets flow through ``run_cxd``/``run_device_mq`` unchanged) so a
long-running service compiles O(log max-batch) kernel variants, not one
per chunk size. Selection is ``BUCKETEER_CXD_PALLAS`` (default: auto —
TPU backend only) behind the Mosaic capability probe (support.py):
backends that cannot compile Pallas programs downgrade to the jnp scan
with a logged reason + metrics counter instead of dying at first
dispatch (the BENCH_r02/r05 axon failure mode). Semantics stay locked
to the jnp path by interpret-mode parity tests (tests/test_cxd.py) on
every CI run, and the device audit (analysis/deviceaudit.py, CI
``audit`` job) lowers the interpret-mode program on CPU every PR — via
``cxd.cxd_program(..., pallas=True, interpret=True)`` — so structural
drift in the kernel's emitted ops (and any host callback or f64
creeping in) fails a PR even without TPU hardware in the loop; the
measured-throughput side (symbols/s, bytes/s) is the bench's
``tier1_split`` report.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:                                    # CPU-only jaxlibs lack the TPU ext
    from jax.experimental.pallas import tpu as pltpu
except ImportError:                     # pragma: no cover
    pltpu = None

from .. import cxd

CBLK = cxd.CBLK


def _tpu_params(interpret: bool) -> dict:
    """Mosaic compiler params for the Tier-1 kernels: the single grid
    axis iterates independent code-blocks (disjoint input/output
    slices), so it is declared ``parallel`` — the compiler may split it
    across TensorCores instead of running the blocks as one sequential
    grid walk. Interpret mode (and jaxlibs without the TPU extension)
    takes no params; jax renames the params class across versions, so
    resolve it defensively."""
    if interpret or pltpu is None:
        return {}
    for name in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, name, None)
        if cls is not None:
            try:
                return {"compiler_params":
                        cls(dimension_semantics=("parallel",))}
            except TypeError:           # pragma: no cover - version skew
                continue
    return {}                           # pragma: no cover - version skew


def _kernel(P: int, frac_bits: int, n_steps: int,
            coeff_ref, meta_ref, zc_ref, scc_ref, scx_ref,
            buf_ref, counts_ref, dh_ref, dl_ref, cur_ref):
    coeffs = coeff_ref[0]
    nbp, floor = meta_ref[0, 0], meta_ref[0, 1]
    cls, h, w = meta_ref[0, 2], meta_ref[0, 3], meta_ref[0, 4]
    idx = (jnp.abs(coeffs) >> frac_bits).astype(jnp.int32)
    idx = (idx >> floor) << floor       # packed-path floor truncation
    neg = (coeffs < 0).astype(jnp.int32)
    step = cxd._make_step(P, idx, neg, nbp, floor, cls, h, w,
                          tables=(zc_ref[:], scc_ref[:], scx_ref[:]))

    def body(t, carry):
        # Decode the flat step index into (plane, pass, stripe, column)
        # — same order as cxd.scan_xs, planes descending.
        plane = P - 1 - t // (3 * cxd.COLS_PER_PLANE)
        rem = t % (3 * cxd.COLS_PER_PLANE)
        pt = rem // cxd.COLS_PER_PLANE
        s = rem % cxd.COLS_PER_PLANE
        xt = jnp.stack([plane, pt, (s // CBLK) * 4, s % CBLK])
        return step(carry, xt)[0]

    _, _, _, cur, buf, counts, dh, dl = lax.fori_loop(
        0, n_steps, body, cxd.init_state(P))
    buf_ref[0] = buf
    counts_ref[0] = counts
    dh_ref[0] = dh
    dl_ref[0] = dl
    cur_ref[0, 0] = cur


def cxd_pallas(P: int, frac_bits: int, blocks, nbps, floors, cls, hs, ws,
               interpret: bool = False):
    """Drop-in replacement for the vmapped jnp scan: (N, 64, 64) int32
    blocks -> (buf (N, max_syms) uint8, counts (N, P, 3) int32,
    dh/dl (N, P, 3) float32, cursors (N,) int32)."""
    n = blocks.shape[0]
    msym = cxd.max_syms(P)
    n_steps = P * 3 * cxd.COLS_PER_PLANE
    meta = jnp.stack([nbps, floors, cls, hs, ws], axis=1).astype(jnp.int32)
    sc_c, sc_x = cxd._sc_tables()
    zc = jnp.asarray(cxd._zc_stack())
    vmem = dict(memory_space=pltpu.VMEM) if pltpu is not None else {}
    smem = dict(memory_space=pltpu.SMEM) if pltpu is not None else {}
    buf, counts, dh, dl, cur = pl.pallas_call(
        partial(_kernel, P, frac_bits, n_steps),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, CBLK, CBLK), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, 5), lambda b: (b, 0), **smem),
            pl.BlockSpec(zc.shape, lambda b: (0, 0, 0, 0), **vmem),
            pl.BlockSpec(sc_c.shape, lambda b: (0, 0), **vmem),
            pl.BlockSpec(sc_x.shape, lambda b: (0, 0), **vmem),
        ],
        out_specs=(
            pl.BlockSpec((1, msym), lambda b: (b, 0), **vmem),
            pl.BlockSpec((1, P, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, P, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, P, 3), lambda b: (b, 0, 0), **vmem),
            pl.BlockSpec((1, 1), lambda b: (b, 0), **smem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, msym), jnp.uint8),
            jax.ShapeDtypeStruct((n, P, 3), jnp.int32),
            jax.ShapeDtypeStruct((n, P, 3), jnp.float32),
            jax.ShapeDtypeStruct((n, P, 3), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
        ),
        interpret=interpret,
        **_tpu_params(interpret),
    )(blocks.astype(jnp.int32), meta, zc, jnp.asarray(sc_c),
      jnp.asarray(sc_x))
    return buf, counts, dh, dl, cur[:, 0]
