"""Log-record correlation: stamp ``request_id`` on every log line.

A ``logging.setLogRecordFactory`` wrapper (not a handler filter, which
would only cover handlers it's attached to) adds ``record.request_id``
from the graftscope trace context — ``"-"`` outside any request. Any
formatter can then carry ``%(request_id)s``; the server's boot config
does, so every log line a request emits (handler, scheduler thread via
:func:`..trace.bind`, bus consumer via ``request_context``) is
greppable by the same id the span tree and the ``X-Request-Id``
response header carry.
"""
from __future__ import annotations

import logging

from . import trace

_PREV = None


def install() -> None:
    """Install the stamping record factory (idempotent)."""
    global _PREV
    if _PREV is not None:
        return
    prev = logging.getLogRecordFactory()

    def factory(*args, **kwargs):
        record = prev(*args, **kwargs)
        record.request_id = trace.current_request_id() or "-"
        return record

    _PREV = prev
    logging.setLogRecordFactory(factory)


def uninstall() -> None:
    global _PREV
    if _PREV is not None:
        logging.setLogRecordFactory(_PREV)
        _PREV = None


def installed() -> bool:
    return _PREV is not None
