"""Tier-2 coding: tag trees, packet headers, packet assembly (T.800 Annex B).

Builds the packet stream that wraps Tier-1 code-block segments — the
precinct/progression/layer machinery configured by the reference's Kakadu
recipe (reference: converters/KakaduConverter.java:38-40: ``Corder=RPCL
Cprecincts={256,256},{256,256},{128,128} Cuse_sop=yes Cuse_eph=yes``).
Host-side by design: byte twiddling, not FLOPs (SURVEY.md §7 layer 1,
codec/t2).
"""
from __future__ import annotations

from dataclasses import dataclass, field

SOP = 0xFF91
EPH = 0xFF92


class BitWriter:
    """MSB-first bit packer with JPEG 2000 bit-stuffing: a byte of 0xFF is
    followed by a 7-bit byte (MSB forced 0) — B.10.1."""

    def __init__(self) -> None:
        self.bytes = bytearray()
        self._acc = 0
        self._nbits = 0

    def _cap(self) -> int:
        # 7 bits available if previous byte was 0xFF
        return 7 if (self.bytes and self.bytes[-1] == 0xFF) else 8

    def put_bit(self, b: int) -> None:
        self._acc = (self._acc << 1) | (b & 1)
        self._nbits += 1
        if self._nbits == self._cap():
            self.bytes.append(self._acc)
            self._acc = 0
            self._nbits = 0

    def put_bits(self, value: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.put_bit((value >> i) & 1)

    def flush(self) -> bytes:
        if self._nbits:
            self._acc <<= (self._cap() - self._nbits)
            self.bytes.append(self._acc)
            self._acc = 0
            self._nbits = 0
        if self.bytes and self.bytes[-1] == 0xFF:
            self.bytes.append(0x00)
        return bytes(self.bytes)


class BitReader:
    """MSB-first bit unpacker mirroring :class:`BitWriter`: after a 0xFF
    byte the next byte carries only 7 bits (B.10.1 bit-stuffing). Reads
    from a buffer at an absolute position; overruns raise the caller's
    ``overrun`` exception type so the decoder surfaces a typed error
    instead of IndexError."""

    def __init__(self, data: bytes, pos: int = 0,
                 end: int | None = None, overrun=ValueError) -> None:
        self.data = data
        self.pos = pos
        self.end = len(data) if end is None else end
        self._overrun = overrun
        self._acc = 0
        self._nbits = 0
        self._last = 0          # previously consumed byte (stuffing state)

    def bit(self) -> int:
        if self._nbits == 0:
            if self.pos >= self.end:
                raise self._overrun("bit stream truncated")
            byte = self.data[self.pos]
            self.pos += 1
            cap = 7 if self._last == 0xFF else 8
            if cap == 7 and byte & 0x80:
                raise self._overrun("invalid bit-stuffing after 0xFF")
            self._acc = byte
            self._nbits = cap
            self._last = byte
        self._nbits -= 1
        return (self._acc >> self._nbits) & 1

    def bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.bit()
        return v

    def align(self) -> None:
        """Byte-align after a packet header (inverse of BitWriter.flush:
        discard padding bits; a final 0xFF is followed by a stuffed
        byte that belongs to the header)."""
        self._acc = 0
        self._nbits = 0
        if self._last == 0xFF:
            if self.pos >= self.end:
                raise self._overrun("bit stream truncated at stuffing")
            self.pos += 1
        self._last = 0


class TagTree:
    """2-D tag tree (B.10.2): quad-tree of running minima, coded
    incrementally against rising thresholds across layers."""

    def __init__(self, w: int, h: int) -> None:
        self.w, self.h = w, h
        self.levels = []
        lw, lh = w, h
        while True:
            self.levels.append((lw, lh))
            if lw <= 1 and lh <= 1:  # also terminates for empty (0-size) grids
                break
            lw, lh = (lw + 1) // 2, (lh + 1) // 2
        self.value = [[0] * (lw_ * lh_) for lw_, lh_ in self.levels]
        self.low = [[0] * (lw_ * lh_) for lw_, lh_ in self.levels]
        self.known = [[False] * (lw_ * lh_) for lw_, lh_ in self.levels]

    def set_values(self, vals) -> None:
        """vals: row-major leaf values (len w*h). Internal = min of children."""
        assert len(vals) == self.w * self.h
        self.value[0] = list(vals)
        for lev in range(1, len(self.levels)):
            pw, ph = self.levels[lev - 1]
            lw, lh = self.levels[lev]
            up = self.value[lev - 1]
            cur = [0] * (lw * lh)
            for y in range(lh):
                for x in range(lw):
                    children = []
                    for dy in (0, 1):
                        for dx in (0, 1):
                            cy, cx = 2 * y + dy, 2 * x + dx
                            if cy < ph and cx < pw:
                                children.append(up[cy * pw + cx])
                    cur[y * lw + x] = min(children)
            self.value[lev] = cur

    def encode(self, bw: BitWriter, x: int, y: int, threshold: int) -> None:
        """Emit bits so the decoder learns whether leaf(x,y) < threshold."""
        # Path from root (last level) down to leaf (level 0).
        path = []
        for lev in range(len(self.levels)):
            lw, _ = self.levels[lev]
            path.append((lev, (y >> lev) * lw + (x >> lev)))
        low = 0
        for lev, idx in reversed(path):
            if low > self.low[lev][idx]:
                self.low[lev][idx] = low
            else:
                low = self.low[lev][idx]
            while low < threshold:
                if low >= self.value[lev][idx]:
                    if not self.known[lev][idx]:
                        bw.put_bit(1)
                        self.known[lev][idx] = True
                    break
                bw.put_bit(0)
                low += 1
            self.low[lev][idx] = low


    def decode(self, br: BitReader, x: int, y: int, threshold: int,
               cap: int = 1 << 20):
        """Decoder mirror of :meth:`encode`: consume bits until the
        decoder knows whether leaf(x, y) < threshold. Returns the leaf
        value if it is known and < threshold, else None (leaf >=
        threshold at this point in the stream). ``cap`` bounds the value
        a corrupt stream can grow to (each 0-bit costs one iteration)."""
        path = []
        for lev in range(len(self.levels)):
            lw, _ = self.levels[lev]
            path.append((lev, (y >> lev) * lw + (x >> lev)))
        low = 0
        for lev, idx in reversed(path):
            if low > self.low[lev][idx]:
                self.low[lev][idx] = low
            else:
                low = self.low[lev][idx]
            while low < threshold:
                if self.known[lev][idx]:
                    break
                if low >= cap:
                    raise br._overrun("tag-tree value overflow")
                if br.bit():
                    self.value[lev][idx] = low
                    self.known[lev][idx] = True
                else:
                    low += 1
            self.low[lev][idx] = low
        lev, idx = path[0]
        if self.known[lev][idx] and self.value[lev][idx] < threshold:
            return self.value[lev][idx]
        return None


def put_npasses(bw: BitWriter, n: int) -> None:
    """Number-of-coding-passes code (Table B.4)."""
    if n == 1:
        bw.put_bit(0)
    elif n == 2:
        bw.put_bits(0b10, 2)
    elif n <= 5:
        bw.put_bits(0b11, 2)
        bw.put_bits(n - 3, 2)
    elif n <= 36:
        bw.put_bits(0b1111, 4)
        bw.put_bits(n - 6, 5)
    else:
        bw.put_bits(0b111111111, 9)
        bw.put_bits(n - 37, 7)


def get_npasses(br: BitReader) -> int:
    """Inverse of :func:`put_npasses` (Table B.4)."""
    if not br.bit():
        return 1
    if not br.bit():
        return 2
    v = br.bits(2)
    if v < 3:
        return 3 + v
    w = br.bits(5)
    if w < 31:
        return 6 + w
    return 37 + br.bits(7)


@dataclass
class BlockLayer:
    """One code-block's contribution to one layer."""
    npasses: int
    data: bytes


@dataclass
class PrecinctBlock:
    """Tier-2 state for one code-block within a precinct."""
    missing_bitplanes: int
    layers: dict = field(default_factory=dict)  # layer -> BlockLayer
    included_in: int = -1   # first layer included (filled during encode)
    lblock: int = 3


@dataclass
class Precinct:
    """One precinct of one band: grid of code-blocks."""
    nblocks_w: int
    nblocks_h: int
    blocks: list = field(default_factory=list)  # row-major PrecinctBlock|None

    def __post_init__(self):
        if not self.blocks:
            self.blocks = [None] * (self.nblocks_w * self.nblocks_h)
        self.incl_tree = None
        self.zbp_tree = None

    def _init_trees(self, n_layers: int) -> None:
        self.incl_tree = TagTree(self.nblocks_w, self.nblocks_h)
        self.zbp_tree = TagTree(self.nblocks_w, self.nblocks_h)
        incl_vals, zbp_vals = [], []
        for blk in self.blocks:
            if blk is None or not blk.layers:
                incl_vals.append(n_layers)   # never included
                zbp_vals.append(0)
            else:
                incl_vals.append(min(blk.layers))
                zbp_vals.append(blk.missing_bitplanes)
        self.incl_tree.set_values(incl_vals)
        self.zbp_tree.set_values(zbp_vals)


def encode_packet(precincts, layer: int, n_layers: int,
                  sop_index: int | None = None,
                  use_eph: bool = False) -> bytes:
    """Encode one packet: the given layer for a list of band-precincts
    (the bands of one resolution at one precinct position), header +
    body. ``sop_index`` non-None prepends an SOP marker segment with that
    sequence number (reference recipe ``Cuse_sop=yes``); ``use_eph``
    appends the EPH marker after the packet header (``Cuse_eph=yes``) —
    KakaduConverter.java:40."""
    bw = BitWriter()
    body = bytearray()
    any_data = any(
        blk is not None and layer in blk.layers
        for prec in precincts for blk in prec.blocks
    )
    bw.put_bit(1 if any_data else 0)
    if any_data:
        for prec in precincts:
            if prec.incl_tree is None:
                prec._init_trees(n_layers)
            for i, blk in enumerate(prec.blocks):
                if blk is None:
                    continue
                x, y = i % prec.nblocks_w, i // prec.nblocks_w
                contrib = layer in blk.layers
                if blk.included_in < 0:
                    prec.incl_tree.encode(bw, x, y, layer + 1)
                    if contrib:
                        blk.included_in = layer
                        # Zero-bitplane count, coded to full precision
                        # (threshold = infinity emits zeros up to the value
                        # plus the terminating one).
                        prec.zbp_tree.encode(bw, x, y, 1 << 30)
                else:
                    bw.put_bit(1 if contrib else 0)
                if not contrib:
                    continue
                bl = blk.layers[layer]
                put_npasses(bw, bl.npasses)
                # Length signaling (B.10.7), single codeword segment.
                nbits_len = blk.lblock + _floor_log2(bl.npasses)
                length = len(bl.data)
                while length >= (1 << nbits_len):
                    bw.put_bit(1)
                    blk.lblock += 1
                    nbits_len += 1
                bw.put_bit(0)
                bw.put_bits(length, nbits_len)
                body += bl.data
    header = bw.flush()
    out = bytearray()
    if sop_index is not None:
        out += SOP.to_bytes(2, "big") + (4).to_bytes(2, "big")
        out += (sop_index & 0xFFFF).to_bytes(2, "big")
    out += header
    if use_eph:
        out += EPH.to_bytes(2, "big")
    out += body
    return bytes(out)


def _floor_log2(n: int) -> int:
    return n.bit_length() - 1
