"""unguarded-field-write: lock-discipline inference over threaded classes.

The concurrency surface (the cross-request scheduler, the tiered read
caches, the metrics registry) is hand-locked: each class owns one or
more ``threading.Lock``/``RLock``/``Condition`` fields and every
mutation of its shared state is supposed to happen inside a ``with
self._lock:`` block. A single missed ``with`` — one more code path
appending to the merged-batch queue, one cache insert on a new branch —
corrupts shared state *silently* under load, which in this codebase
means corrupted customer bytes, not a crash.

The rule infers the discipline instead of being told it:

1. **Lock fields** are attributes assigned a ``threading.Lock()`` /
   ``RLock()`` / ``Condition()`` (either ``self.X = threading.Lock()``
   in a method or the dataclass idiom
   ``X: Lock = field(default_factory=threading.Lock)``).
2. A statement is in a **locked context** when it sits inside ``with
   self.<lockfield>:`` (any of the class's locks), or in a method whose
   name ends in ``_locked`` — the codebase convention for "caller holds
   the lock" (``_grant_next_locked``, ``_report_locked``). ``__init__``
   / ``__post_init__`` are construction: their accesses are exempt in
   both directions (no thread has the object yet).
3. A field is **guarded** when at least one non-construction access to
   it happens in a locked context.
4. Every *write* to a guarded field outside any locked context is a
   finding. Writes are attribute assignment/augmented-assignment/del,
   stores through a subscript (``self.f[k] = v``), and calls of known
   mutating container methods (``self.f.append(...)``, ``.pop()``,
   ``.update()``, ...). Unlocked *reads* are deliberately tolerated:
   the serving path has documented lock-free fast reads (cache-hit
   paths, stat snapshots) whose worst case is staleness, not
   corruption — flagging them would bury the real signal.

Out of scope (documented, not detected): manual ``.acquire()`` /
``.release()`` pairing, locks inherited from a base class (a subclass
with no locally visible lock field simply infers nothing), and
module-global state — ``analysis/retrace.py``'s trace counter is
guarded by its own lock directly rather than relying on this rule.
"""
from __future__ import annotations

import ast

from .findings import ERROR, Finding

UNGUARDED_WRITE = "unguarded-field-write"

# threading factories plus the graftrace seam's traced drop-ins
# (analysis/graftrace/seam.py) — the serving core creates its locks
# through the seam, and the inference must see through it.
LOCK_FACTORIES = {"Lock", "RLock", "Condition",
                  "make_lock", "make_rlock", "make_condition"}
CONSTRUCTORS = {"__init__", "__post_init__", "__new__"}
# Container methods that mutate their receiver in place.
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "add", "discard", "remove", "pop", "popleft", "popitem",
            "clear", "update", "setdefault", "move_to_end", "sort",
            "reverse", "rotate", "setflags"}


def _leaf_name(node):
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lock_factory(node) -> bool:
    return (isinstance(node, ast.Call)
            and _leaf_name(node.func) in LOCK_FACTORIES)


def _factory_ref(node) -> bool:
    """True for a default_factory value that builds a lock: a bare
    factory reference (``threading.Lock``) or the zero-arg-lambda idiom
    the seam needs for named locks
    (``lambda: seam.make_lock("Metrics._lock")``)."""
    if _leaf_name(node) in LOCK_FACTORIES:
        return True
    return isinstance(node, ast.Lambda) and _is_lock_factory(node.body)


def _self_attr(node, self_name: str):
    """The attribute name when ``node`` is ``<self>.<attr>``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id == self_name:
        return node.attr
    return None


def _lock_fields(cls: ast.ClassDef) -> set:
    locks = set()
    for stmt in cls.body:
        # dataclass field: X: Lock = field(default_factory=Lock)
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and \
                isinstance(stmt.value, ast.Call):
            if _is_lock_factory(stmt.value):
                locks.add(stmt.target.id)
            for kw in stmt.value.keywords:
                if kw.arg == "default_factory" and _factory_ref(kw.value):
                    locks.add(stmt.target.id)
        if isinstance(stmt, ast.Assign) and _is_lock_factory(stmt.value):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    locks.add(t.id)
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        self_name = _method_self(meth)
        if self_name is None:
            continue
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign) and \
                    _is_lock_factory(node.value):
                for t in node.targets:
                    attr = _self_attr(t, self_name)
                    if attr:
                        locks.add(attr)
    return locks


def _method_self(meth) -> str | None:
    args = meth.args.posonlyargs + meth.args.args
    if not args:
        return None
    for dec in meth.decorator_list:
        if _leaf_name(dec) == "staticmethod":
            return None
    return args[0].arg


class _Access:
    __slots__ = ("locked", "write", "line", "method", "lock")

    def __init__(self, locked, write, line, method, lock):
        self.locked = locked
        self.write = write
        self.line = line
        self.method = method
        self.lock = lock


class _MethodWalk:
    """Collect self-field accesses in one method with a locked flag."""

    def __init__(self, self_name: str, locks: set, method: str,
                 accesses: dict):
        self.self_name = self_name
        self.locks = locks
        self.method = method
        self.accesses = accesses
        self.base_locked = method.endswith("_locked")

    def _add(self, attr, locked, write, line, lock=None):
        if attr in self.locks:
            return
        self.accesses.setdefault(attr, []).append(
            _Access(locked or self.base_locked, write, line,
                    self.method, lock))

    def _write_target(self, target, locked, lock):
        """Record stores: self.f = ..., self.f[k] = ..., tuple targets."""
        attr = self._self_attr(target)
        if attr:
            self._add(attr, locked, True, target.lineno, lock)
            return
        if isinstance(target, ast.Subscript):
            attr = self._self_attr(target.value)
            if attr:
                self._add(attr, locked, True, target.lineno, lock)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._write_target(e, locked, lock)
        if isinstance(target, ast.Starred):
            self._write_target(target.value, locked, lock)

    def _self_attr(self, node):
        return _self_attr(node, self.self_name)

    def _reads(self, node, locked, lock):
        """Record remaining accesses in an expression tree: mutator
        method calls as writes, plain mentions as reads."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute):
                    attr = self._self_attr(f.value)
                    if attr and f.attr in MUTATORS:
                        self._add(attr, locked, True, sub.lineno, lock)
            attr = self._self_attr(sub)
            if attr:
                self._add(attr, locked, False, sub.lineno, lock)

    def stmt(self, node, locked, lock):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def runs later, wherever it is called — its body
            # cannot assume the lock is still held, not even inside a
            # *_locked method (base_locked covers the method body, not
            # closures escaping it).
            saved = self.base_locked
            self.base_locked = False
            for s in node.body:
                self.stmt(s, False, None)
            self.base_locked = saved
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner, inner_lock = locked, lock
            for item in node.items:
                ctx = item.context_expr
                attr = self._self_attr(ctx)
                if attr in self.locks:
                    inner, inner_lock = True, attr
                else:
                    self._reads(ctx, locked, lock)
            for s in node.body:
                self.stmt(s, inner, inner_lock)
            return
        if isinstance(node, ast.Assign):
            self._reads(node.value, locked, lock)
            for t in node.targets:
                self._write_target(t, locked, lock)
            return
        if isinstance(node, ast.AugAssign):
            self._reads(node.value, locked, lock)
            self._write_target(node.target, locked, lock)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._reads(node.value, locked, lock)
            self._write_target(node.target, locked, lock)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._write_target(t, locked, lock)
            return
        body_fields = ("body", "orelse", "finalbody")
        if isinstance(node, (ast.If, ast.While)):
            self._reads(node.test, locked, lock)
        elif isinstance(node, ast.For):
            self._reads(node.iter, locked, lock)
            self._write_target(node.target, locked, lock)
        elif isinstance(node, ast.Try):
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s, locked, lock)
        elif isinstance(node, (ast.Return, ast.Expr)):
            if node.value is not None:
                self._reads(node.value, locked, lock)
            return
        elif isinstance(node, (ast.Raise, ast.Assert)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self._reads(sub, locked, lock)
            return
        elif isinstance(node, ast.stmt):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self._reads(sub, locked, lock)
        for f in body_fields:
            for s in getattr(node, f, ()):
                self.stmt(s, locked, lock)


def class_accesses(cls: ast.ClassDef):
    """(lock fields, {attr: [access records]}) for one class. Shared
    between the unguarded-write check below and graftrace's
    static/dynamic cross-check (analysis/graftrace/explore.py), so the
    two analyses reason from the same inference."""
    locks = _lock_fields(cls)
    accesses: dict = {}
    if not locks:
        return locks, accesses
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if meth.name in CONSTRUCTORS:
            continue
        self_name = _method_self(meth)
        if self_name is None:
            continue
        walk = _MethodWalk(self_name, locks, meth.name, accesses)
        for stmt in meth.body:
            walk.stmt(stmt, False, None)
    return locks, accesses


def _check_class(mod, cls: ast.ClassDef) -> list:
    locks, accesses = class_accesses(cls)
    if not locks:
        return []

    findings = []
    for attr, accs in sorted(accesses.items()):
        guards = sorted({a.lock for a in accs if a.locked and a.lock})
        guarded_in = sorted({a.method for a in accs if a.locked})
        if not guarded_in:
            continue                      # never lock-associated
        lock_desc = (f"self.{guards[0]}" if len(guards) == 1
                     else f"{[f'self.{g}' for g in guards]}")
        for a in accs:
            if a.write and not a.locked:
                findings.append(Finding(
                    UNGUARDED_WRITE, mod.relpath, a.line,
                    f"{cls.name}.{attr} is lock-guarded (held in "
                    f"{', '.join(guarded_in)} via {lock_desc}) but "
                    f"written here in {a.method}() with no lock held — "
                    "a racing thread sees the mutation mid-flight. "
                    "Wrap the access in the guarding lock, or rename "
                    "the method with the _locked suffix if every "
                    "caller already holds it",
                    ERROR, mod.source_line(a.line)))
    return findings


def run(project) -> list:
    findings: list = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings += _check_class(mod, node)
    return findings
