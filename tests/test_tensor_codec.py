"""The general bit-plane tensor codec (ISSUE 13): lossless roundtrip
across dtypes via the sign-magnitude limb mapping, byte identity of the
device-MQ chain vs the host paths, progressive truncation at plane
boundaries, the typed-container contract, and the scheduler's tensor
job kind.
"""
import threading

import numpy as np
import pytest

from bucketeer_tpu.codec.decode import DecodeError
from bucketeer_tpu.tensor import (decode_tensor, encode_tensor,
                                  tensor_stats, truncate_tensor)
from bucketeer_tpu.tensor import container, planes


def _bits(arr: np.ndarray) -> np.ndarray:
    """Bit-pattern view for exactness checks (NaN != NaN, -0.0 == 0.0
    under ==, so value comparison is not enough for floats)."""
    return arr.view((np.uint8, arr.dtype.itemsize))


def _assert_bitexact(a: np.ndarray, b: np.ndarray):
    assert a.dtype == b.dtype and a.shape == b.shape, (a.dtype, b.dtype,
                                                      a.shape, b.shape)
    np.testing.assert_array_equal(_bits(a), _bits(b))


# --- lossless roundtrip, host backend (fast; byte-identical to the
# device chain by the identity test below + the PR 3/9 parity suites) ---

@pytest.mark.parametrize("dtype,shape", [
    ("int8", (300,)),
    ("int8", (64, 65)),              # straddles one block boundary
    ("int16", (4096,)),              # exactly one block
    ("int32", (100, 3)),             # two limbs
    ("uint8", (17,)),
    ("uint16", (257,)),
    ("uint32", (64,)),
    ("float16", (129,)),
    ("float32", (1000,)),            # two limbs
    ("float64", (48,)),              # four limbs
])
def test_roundtrip_lossless(rng, dtype, shape):
    dt = np.dtype(dtype)
    n = int(np.prod(shape))
    if dt.kind in "iu":
        info = np.iinfo(dt)
        x = rng.integers(info.min, int(info.max) + 1, size=shape,
                         dtype=dt)
    else:
        x = (rng.standard_normal(n) * 10).astype(dt).reshape(shape)
    blob = encode_tensor(x, device="host")
    _assert_bitexact(decode_tensor(blob), x)


def test_roundtrip_bfloat16(rng):
    import ml_dtypes

    x = (rng.standard_normal(300).astype(np.float32)
         .astype(ml_dtypes.bfloat16))
    blob = encode_tensor(x, device="host")
    _assert_bitexact(decode_tensor(blob), x)


def test_roundtrip_special_values():
    x = np.array([np.nan, -np.nan, np.inf, -np.inf, 0.0, -0.0,
                  np.float32(1e-45), -np.float32(1e-45),  # denormals
                  np.finfo(np.float32).max, np.finfo(np.float32).min],
                 dtype=np.float32)
    blob = encode_tensor(x, device="host")
    _assert_bitexact(decode_tensor(blob), x)


def test_negative_zero_escape_list():
    x = np.array([0.0, -0.0, 1.5, -0.0], dtype=np.float32)
    enc = container.parse(encode_tensor(x, device="host"))
    # The two -0.0 positions are the only sign-magnitude collisions;
    # the container records them explicitly.
    np.testing.assert_array_equal(enc.neg_zeros, [1, 3])
    _assert_bitexact(decode_tensor(container.dump(enc)), x)


def test_int_extremes_roundtrip():
    x = np.array([-128, 127, 0, -1], dtype=np.int8)
    _assert_bitexact(decode_tensor(encode_tensor(x, device="host")), x)
    y = np.array([np.iinfo(np.int32).min, np.iinfo(np.int32).max, -1, 0],
                 dtype=np.int32)
    _assert_bitexact(decode_tensor(encode_tensor(y, device="host")), y)


def test_empty_and_zero_tensors():
    x = np.zeros((0, 5), dtype=np.float32)
    _assert_bitexact(decode_tensor(encode_tensor(x, device="host")), x)
    z = np.zeros((5000,), dtype=np.int16)
    blob = encode_tensor(z, device="host")
    _assert_bitexact(decode_tensor(blob), z)
    # An all-zero tensor codes two empty blocks: near-header-only blob.
    assert len(blob) < 100


def test_unsupported_dtype_rejected():
    with pytest.raises(TypeError):
        encode_tensor(np.zeros(4, dtype=np.complex64))
    with pytest.raises(TypeError):
        encode_tensor(np.array(["a"], dtype=object))


# --- device-MQ chain: byte identity with the host paths ------------------

def test_device_host_replay_byte_identity(rng):
    """The acceptance contract: the full-device chain (pack -> CX/D
    scan -> MQ scan), the device-CX/D + host-MQ replay path, and the
    pure-host reference coder emit byte-identical containers. Small
    magnitudes keep the sequential device scans affordable on the CPU
    backend (plane count bounds the scan's trip count)."""
    x = rng.integers(-3, 4, size=(5000,), dtype=np.int8)
    host = encode_tensor(x, device="host")
    device = encode_tensor(x, device="device")
    replay = encode_tensor(x, device="replay")
    assert host == device == replay
    _assert_bitexact(decode_tensor(device), x)


@pytest.mark.slow
def test_device_float32_roundtrip_byte_identity(rng):
    """float32 (two 16-plane limbs) through the device MQ path:
    lossless roundtrip with the stream byte-identical to the host
    replay path. Slow: the per-symbol device scans pay ~100k sequential
    steps on CPU; the tensor-parity CI job runs it."""
    x = rng.standard_normal(4096).astype(np.float32)
    device = encode_tensor(x, device="device")
    replay = encode_tensor(x, device="replay")
    assert device == replay
    _assert_bitexact(decode_tensor(device), x)


@pytest.mark.slow
def test_device_bf16_int8_roundtrip(rng):
    import ml_dtypes

    xb = (rng.standard_normal(4096).astype(np.float32)
          .astype(ml_dtypes.bfloat16))
    _assert_bitexact(decode_tensor(encode_tensor(xb, device="device")),
                     xb)
    xi = rng.integers(-128, 128, size=(4096,), dtype=np.int8)
    assert encode_tensor(xi, device="device") == \
        encode_tensor(xi, device="host")


# --- progressive truncation ----------------------------------------------

def test_truncation_monotone_and_lossless_cap(rng):
    x = rng.standard_normal(5000).astype(np.float32)
    blob = encode_tensor(x, device="host")
    total = 2 * planes.LIMB_BITS
    sizes, errs = [], []
    for k in (6, 12, 20, total):
        cut = truncate_tensor(blob, planes=k)
        y = decode_tensor(cut)
        sizes.append(len(cut))
        errs.append(float(np.mean(np.abs(y - x))))
    assert sizes == sorted(sizes)
    assert errs == sorted(errs, reverse=True)
    # The full-plane cut is the identity.
    _assert_bitexact(decode_tensor(truncate_tensor(blob, planes=total)),
                     x)
    assert truncate_tensor(blob, planes=total) == blob


def test_rate_truncation_fits_budget(rng):
    x = rng.standard_normal(5000).astype(np.float32)
    blob = encode_tensor(x, device="host")
    budget = len(blob) // 3
    cut = truncate_tensor(blob, rate=budget)
    assert len(cut) <= budget
    decode_tensor(cut)                       # still decodes
    # encode_tensor(rate=) is encode + truncate.
    assert encode_tensor(x, device="host", rate=budget) == cut
    # The rate search sizes candidates arithmetically; the formula
    # must agree with the serializer byte for byte at every cut.
    from bucketeer_tpu.tensor.codec import (_apply_cut, _container_size,
                                            _limb_bases)
    enc = container.parse(blob)
    bases = _limb_bases(enc.spec.n_limbs, enc.blocks_per_limb)
    for c in (0, 5, 17, 32):
        assert _container_size(enc, c, bases) == \
            len(container.dump(_apply_cut(enc, c))), c


def test_encode_time_planes_match_truncation_decode(rng):
    """encode_tensor(planes=k) floors at encode time (different bytes:
    the stream flushes at the floor instead of being sliced mid-run),
    but must reconstruct exactly like truncating a lossless encode at
    the same plane boundary."""
    x = rng.standard_normal(3000).astype(np.float32)
    full = encode_tensor(x, device="host")
    for k in (8, 16, 24):
        floored = encode_tensor(x, device="host", planes=k)
        sliced = truncate_tensor(full, planes=k)
        assert len(floored) <= len(sliced)
        _assert_bitexact(decode_tensor(floored), decode_tensor(sliced))
    # decode-side planes= is the same cut applied on the fly.
    _assert_bitexact(decode_tensor(full, planes=16),
                     decode_tensor(truncate_tensor(full, planes=16)))


def test_truncate_arg_validation(rng):
    blob = encode_tensor(np.zeros(4, np.int8), device="host")
    with pytest.raises(ValueError):
        truncate_tensor(blob)
    with pytest.raises(ValueError):
        truncate_tensor(blob, planes=2, rate=100)
    with pytest.raises(ValueError):
        truncate_tensor(blob, planes=-1)
    with pytest.raises(ValueError):
        decode_tensor(blob, planes=-1)


# --- the container trust boundary ----------------------------------------

def test_container_garbage_typed():
    for junk in (b"", b"\x00" * 3, b"nope", b"\xff" * 64,
                 b"BTT1" + b"\x00" * 2):
        with pytest.raises(DecodeError):
            decode_tensor(junk)
    with pytest.raises(TypeError):
        decode_tensor(123)


def test_container_truncation_and_bitflips_typed(rng):
    x = rng.integers(-50, 50, size=(600,), dtype=np.int8)
    blob = encode_tensor(x, device="host")
    for cut in sorted(set(rng.integers(0, len(blob), 40).tolist())):
        try:
            out = decode_tensor(blob[:cut])
            assert isinstance(out, np.ndarray)
        except DecodeError:
            pass
    for _ in range(60):
        pos = int(rng.integers(0, len(blob)))
        mutated = bytearray(blob)
        mutated[pos] ^= 1 << int(rng.integers(0, 8))
        try:
            out = decode_tensor(bytes(mutated))
            assert isinstance(out, np.ndarray)
        except DecodeError:
            pass


def test_tensor_stats(rng):
    x = rng.integers(-7, 8, size=(100, 10), dtype=np.int8)
    blob = encode_tensor(x, device="host")
    stats = tensor_stats(blob)
    assert stats["dtype"] == "int8" and stats["shape"] == [100, 10]
    assert stats["raw_bytes"] == 1000
    assert stats["coded_bytes"] == len(blob)
    assert stats["ratio"] == round(1000 / len(blob), 4)


def test_metrics_segments(rng):
    from bucketeer_tpu import tensor as tensor_mod
    from bucketeer_tpu.server.metrics import Metrics

    sink = Metrics()
    tensor_mod.set_metrics_sink(sink)
    try:
        x = rng.integers(-7, 8, size=(5000,), dtype=np.int8)
        blob = encode_tensor(x, device="host")
        decode_tensor(blob)
    finally:
        tensor_mod.set_metrics_sink(None)
    rep = sink.report()
    assert "tensor.encode" in rep["stages"]
    assert "tensor.decode" in rep["stages"]
    counters = rep["counters"]
    assert counters["tensor.encode_blocks"] == 2
    assert counters["tensor.raw_bytes"] == 5000
    assert counters["tensor.coded_bytes"] == len(blob)


# --- the scheduler's tensor job kind -------------------------------------

def test_submit_tensor_runs_and_reads_outrank(rng):
    """submit_tensor executes the job in an admitted slot; with the
    only slot held, a queued read is granted before a queued tensor
    job regardless of arrival order (the graftrace scenario explores
    the schedules; this pins the real-thread behavior)."""
    from bucketeer_tpu.engine.scheduler import (PRIORITY_TENSOR,
                                                EncodeScheduler)

    sched = EncodeScheduler(queue_depth=8, max_concurrent=1,
                            pool_size=1, window_s=0)
    try:
        x = rng.integers(-3, 4, size=(100,), dtype=np.int8)
        blob = sched.submit_tensor(encode_tensor, x, device="host")
        _assert_bitexact(decode_tensor(blob), x)

        release = threading.Event()
        started = threading.Event()
        order = []

        def hold():
            started.set()
            release.wait(5)

        tb = threading.Thread(target=lambda: sched.submit(hold))
        tb.start()
        assert started.wait(5)
        t_tensor = sched._admit(PRIORITY_TENSOR, None, "tensor")
        t_read = sched._admit(-1, None, "decode")

        def waiter(t, tag):
            sched._await_slot(t)
            order.append(tag)
            sched._finish(t)

        wt = threading.Thread(target=waiter, args=(t_tensor, "tensor"))
        wr = threading.Thread(target=waiter, args=(t_read, "read"))
        wt.start()
        wr.start()
        release.set()
        for t in (tb, wt, wr):
            t.join(5)
        assert order[0] == "read", order
    finally:
        sched.close()


def test_queued_tensor_job_cancelled_typed_at_close():
    from bucketeer_tpu.engine.scheduler import (EncodeScheduler,
                                                SchedulerClosed)

    sched = EncodeScheduler(queue_depth=8, max_concurrent=1,
                            pool_size=1, window_s=0)
    release = threading.Event()
    started = threading.Event()
    outcome = {}

    def hold():
        started.set()
        release.wait(5)

    tb = threading.Thread(target=lambda: sched.submit(hold))
    tb.start()
    assert started.wait(5)

    def queued():
        try:
            sched.submit_tensor(lambda: None)
            outcome["r"] = "ran"
        except SchedulerClosed:
            outcome["r"] = "closed"

    tq = threading.Thread(target=queued)
    tq.start()
    while sched.stats()["waiting"] < 1 and tq.is_alive():
        pass
    release.set()
    sched.close()
    tq.join(5)
    tb.join(5)
    assert outcome.get("r") in ("ran", "closed")
    assert sched.stats()["admitted"] == 0


def test_tensor_deadline_polled_between_chunks(rng):
    """The tensor_services deadline hook fires mid-encode, between
    chunks, not only while queued."""
    from bucketeer_tpu.engine.scheduler import (DeadlineExceeded,
                                                EncodeScheduler)

    sched = EncodeScheduler(queue_depth=8, max_concurrent=1,
                            pool_size=1, window_s=0)
    try:
        x = rng.integers(-3, 4, size=(20 * 4096,), dtype=np.int8)
        with pytest.raises(DeadlineExceeded):
            # deadline expires immediately; the first inter-chunk poll
            # must surface it (host backend: ~20 cheap chunks).
            sched.submit_tensor(encode_tensor, x, device="host",
                                chunk_blocks=1, deadline_s=1e-9)
    finally:
        sched.close()
