"""Durable job store (engine/journal.py + JobStore journal mode):
WAL replay, snapshot compaction, truncated/corrupt tails, idempotent
resolution, replay of already-finalized jobs, and the
dispatched-but-unresolved re-queue window."""
import asyncio
import json
import os

import pytest

from bucketeer_tpu import job_factory
from bucketeer_tpu.engine import faults
from bucketeer_tpu.engine.journal import (JOURNAL, JobJournal,
                                          JournalUnavailable)
from bucketeer_tpu.engine.store import JobStore
from bucketeer_tpu.models import WorkflowState
from bucketeer_tpu.utils import path_prefix as pp


def run(coro):
    return asyncio.run(coro)


def _mk_job(tmp_path, n=3, name="j1"):
    for i in range(n):
        (tmp_path / f"img{i}.tif").write_bytes(b"II*\x00")
    csv_text = "Item ARK,File Name\n" + "\n".join(
        f"ark:/1/{i},img{i}.tif" for i in range(n)) + "\n"
    return job_factory.create_job(
        name, csv_text, prefix=pp.GenericFilePathPrefix(str(tmp_path)))


def _journal_lines(jdir):
    path = os.path.join(jdir, JOURNAL)
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


class TestReplay:
    def test_crash_replay_restores_jobs_and_dispatch_state(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        job = _mk_job(tmp_path)
        store.put(job)
        store.mark_dispatched("j1", "ark:/1/0")
        store.mark_dispatched("j1", "ark:/1/1")
        store.resolve_item("j1", "ark:/1/0", True, "http://iiif/0")
        store.close()

        # "Crash": a fresh process loads the same directory.
        store2 = JobStore(journal_dir=jdir)
        assert store2.recovery["records"] == 4
        j2 = store2.get("j1")
        assert j2.remaining() == 2
        item = j2.find_item("ark:/1/0")
        assert item.workflow_state is WorkflowState.SUCCEEDED
        assert item.access_url == "http://iiif/0"
        # The dispatched-but-unresolved item is exactly the re-queue set.
        assert store2.dispatched("j1") == {"ark:/1/1"}

    def test_resolution_is_idempotent_no_double_count(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=2))
        fin, applied = store.resolve_item("j1", "ark:/1/0", True, "u")
        assert (fin, applied) == (False, True)
        # Replay (a crashed worker's re-run, a double PATCH): no state
        # flip, no second count toward finalization.
        fin, applied = store.resolve_item("j1", "ark:/1/0", False)
        assert (fin, applied) == (False, False)
        state = store.get("j1").find_item("ark:/1/0").workflow_state
        assert state is WorkflowState.SUCCEEDED
        fin, applied = store.resolve_item("j1", "ark:/1/1", False)
        assert (fin, applied) == (True, True)
        # A replayed final update reports finished but NOT applied —
        # the caller must not re-trigger finalization.
        fin, applied = store.resolve_item("j1", "ark:/1/1", False)
        assert (fin, applied) == (True, False)
        store.close()
        # The no-op replays never reached the journal (the idempotence
        # check runs before the WAL append), so replay is exact.
        assert len(_journal_lines(jdir)) == 3   # put + 2 resolves
        store2 = JobStore(journal_dir=jdir)
        assert store2.get("j1").remaining() == 0
        assert store2.recovery == {"snapshot": True, "records": 3,
                                   "ignored": 0, "truncated": False}

    def test_replay_of_already_finalized_job_is_ignored(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=1))
        store.resolve_item("j1", "ark:/1/0", True)
        store.remove("j1")
        # Hand-append a stale record landing after the remove (the
        # crash-during-finalize window).
        journal = JobJournal(jdir)
        journal.append({"op": "resolve", "job": "j1", "id": "ark:/1/0",
                        "state": "FAILED", "url": None})
        journal.append({"op": "dispatch", "job": "j1", "id": "x"})
        journal.close()
        store.close()
        store2 = JobStore(journal_dir=jdir)
        assert "j1" not in store2
        assert store2.recovery["ignored"] >= 2

    def test_truncated_tail_dropped(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=2))
        store.resolve_item("j1", "ark:/1/0", True)
        store.close()
        # Crash mid-write: a partial record with no trailing newline.
        with open(os.path.join(jdir, JOURNAL), "a") as fh:
            fh.write('{"op":"resolve","job":"j1","id":"ark:/1/1","sta')
        store2 = JobStore(journal_dir=jdir)
        assert store2.recovery["truncated"]
        j2 = store2.get("j1")
        assert j2.find_item("ark:/1/0").workflow_state is \
            WorkflowState.SUCCEEDED
        assert j2.remaining() == 1           # the torn record is gone

    def test_valid_json_broken_content_is_skipped_not_fatal(
            self, tmp_path):
        """A record that parses but can't replay (unknown state name,
        missing fields — e.g. written by a different version) must
        degrade to 'ignored', never crash recovery and block boot."""
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=2))
        store.close()
        with open(os.path.join(jdir, JOURNAL), "a") as fh:
            fh.write('{"op":"resolve","job":"j1","id":"ark:/1/0",'
                     '"state":"NOT_A_STATE"}\n')
            fh.write('{"op":"resolve","job":"j1","id":"ark:/1/1",'
                     '"state":"SUCCEEDED","url":null}\n')
        store2 = JobStore(journal_dir=jdir)
        assert store2.recovery["ignored"] >= 1
        j2 = store2.get("j1")
        assert j2.find_item("ark:/1/0").workflow_state is \
            WorkflowState.EMPTY
        assert j2.find_item("ark:/1/1").workflow_state is \
            WorkflowState.SUCCEEDED

    def test_journal_compacts_after_append_threshold(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.COMPACT_EVERY = 6
        for k in range(3):
            job = _mk_job(tmp_path, n=1, name=f"j{k}")
            store.put(job)
            store.mark_dispatched(job.name, "ark:/1/0")
            store.resolve_item(job.name, "ark:/1/0", True)
            store.remove(job.name)
        # 12 appends with a threshold of 6: at least one mid-life
        # compaction ran, so the journal is shorter than history.
        assert len(_journal_lines(jdir)) < 12
        store.close()
        store2 = JobStore(journal_dir=jdir)
        assert len(store2) == 0              # state survived compaction

    def test_corrupt_middle_line_stops_replay_at_prefix(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=2))
        store.close()
        with open(os.path.join(jdir, JOURNAL), "a") as fh:
            fh.write("NOT JSON AT ALL\n")
            fh.write('{"op":"resolve","job":"j1","id":"ark:/1/0",'
                     '"state":"SUCCEEDED","url":null}\n')
        store2 = JobStore(journal_dir=jdir)
        # Replay stops at the first bad line; the good-looking record
        # *after* garbage is not trusted.
        assert store2.recovery["truncated"]
        assert store2.get("j1").remaining() == 2

    def test_kill_between_upload_and_status_requeues_item(self, tmp_path):
        """The at-least-once window: dispatch journaled, upload done,
        no resolve — the replayed item must still be EMPTY (so it
        re-dispatches) and counted exactly once overall."""
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=2))
        store.mark_dispatched("j1", "ark:/1/0")
        # (upload happened here; process died before the status write)
        store.close()
        store2 = JobStore(journal_dir=jdir)
        j2 = store2.get("j1")
        assert j2.find_item("ark:/1/0").workflow_state is \
            WorkflowState.EMPTY
        assert "ark:/1/0" in store2.dispatched("j1")
        # The re-run resolves it once; a duplicate resolve (the
        # pre-kill worker's status write arriving late) is a no-op.
        assert store2.resolve_item("j1", "ark:/1/0", True) == \
            (False, True)
        assert store2.resolve_item("j1", "ark:/1/0", True) == \
            (False, False)


class TestSnapshot:
    def test_recovery_compacts(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=2))
        store.resolve_item("j1", "ark:/1/0", True)
        store.close()
        assert len(_journal_lines(jdir)) == 2
        store2 = JobStore(journal_dir=jdir)
        # Startup wrote a fresh snapshot and truncated the journal:
        # the next crash replays state-sized work, not history-sized.
        assert _journal_lines(jdir) == []
        snap = json.load(open(os.path.join(jdir, "snapshot.json")))
        assert len(snap["jobs"]) == 1
        store2.close()
        store3 = JobStore(journal_dir=jdir)
        assert store3.get("j1").remaining() == 1

    def test_unreadable_snapshot_falls_back_to_journal(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        store.put(_mk_job(tmp_path, n=1))
        store.close()
        with open(os.path.join(jdir, "snapshot.json"), "w") as fh:
            fh.write("{broken")
        store2 = JobStore(journal_dir=jdir)
        assert "j1" in store2                # journal still has the put


class TestJournalUnavailable:
    def test_append_failure_raises_typed(self, tmp_path):
        jdir = str(tmp_path / "journal")
        store = JobStore(journal_dir=jdir)
        plan = faults.FaultPlan().at(
            "journal.write", lambda: OSError("disk gone"), times=1)
        faults.install(plan)
        try:
            with pytest.raises(JournalUnavailable):
                store.put(_mk_job(tmp_path, n=1))
        finally:
            faults.install(None)
        # WAL discipline: the failed put did NOT land in memory.
        assert "j1" not in store
        # The journal recovers once the fault clears.
        store.put(_mk_job(tmp_path, n=1))
        assert "j1" in store

    def test_in_memory_store_never_journals(self, tmp_path):
        store = JobStore()
        assert not store.durable
        store.put(_mk_job(tmp_path, n=1))
        store.resolve_item("j1", "ark:/1/0", True)
        store.close()                        # no-op, no files
        assert not (tmp_path / "journal").exists()
