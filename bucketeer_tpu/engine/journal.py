"""Durable job state: write-ahead journal + snapshot for the JobStore.

The reference keeps jobs in Vert.x shared data — a process kill loses
every in-flight batch (reference: SURVEY.md §1; Constants.java:145).
Here the :class:`JobStore` can attach a :class:`JobJournal`
(``BUCKETEER_JOB_JOURNAL_DIR`` / ``bucketeer.job.journal.dir``): every
mutation is appended to ``journal.jsonl`` (JSON line, flush + fsync)
*before* it lands in memory, and recovery loads ``snapshot.json`` +
replays the journal, so a killed process re-loads queued jobs on
startup and re-queues items stuck dispatched-but-unresolved.

Record ops (one JSON object per line):

- ``{"op": "put", "job": {...}}``          — job accepted (full state)
- ``{"op": "dispatch", "job": n, "id": i}`` — item handed to a worker
- ``{"op": "resolve", "job": n, "id": i, "state": "SUCCEEDED"|"FAILED",
  "url": ...}``                             — item terminal state
- ``{"op": "remove", "job": n}``            — job finalized/deleted

Replay is idempotent and tolerant: a truncated/corrupt tail (crash
mid-write) stops replay at the last good line; ops for a job that was
already removed (a replayed status update racing finalization) are
ignored; a ``resolve`` for an already-terminal item is a no-op — so a
replayed update can never double-count toward finalization. After
recovery the store writes a fresh snapshot and truncates the journal,
bounding replay cost.
"""
from __future__ import annotations

import json
import logging
import os
import threading

from .. import obs
from ..models import Job, WorkflowState
from . import faults
from .retry import count_metric as _count

LOG = logging.getLogger(__name__)


class JournalUnavailable(RuntimeError):
    """The journal directory cannot be written. Propagates to HTTP 503
    + Retry-After (server/app.py) the same way QueueFull does: durable
    acceptance is part of the contract, so a job that cannot be
    journaled is not accepted."""

    retry_after = 5.0


SNAPSHOT = "snapshot.json"
JOURNAL = "journal.jsonl"


class JobJournal:
    """Append-only WAL + snapshot in one directory."""

    def __init__(self, dirpath: str, fsync: bool = True) -> None:
        self.dirpath = dirpath
        self.fsync = fsync
        try:
            os.makedirs(dirpath, exist_ok=True)
        except OSError as exc:
            raise JournalUnavailable(
                f"cannot create journal dir {dirpath}: {exc}")
        self.journal_path = os.path.join(dirpath, JOURNAL)
        self.snapshot_path = os.path.join(dirpath, SNAPSHOT)
        self._fh = None
        # File ops may run off the event loop (asyncio.to_thread keeps
        # the fsync latency off the loop); serialize writers/compaction.
        self._lock = threading.Lock()

    # -- writing ---------------------------------------------------------

    def _handle_locked(self):
        if self._fh is None:
            self._fh = open(self.journal_path, "a", encoding="utf-8")
        return self._fh

    def append(self, record: dict) -> None:
        """Durably append one record (WAL discipline: callers append
        *before* mutating memory, so a crash never acknowledges state
        the disk doesn't have)."""
        try:
            faults.point("journal.write", op=record.get("op", ""))
            with obs.span("journal.write", op=record.get("op", "")), \
                    self._lock:
                fh = self._handle_locked()
                fh.write(json.dumps(record, separators=(",", ":"))
                         + "\n")
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
        except OSError as exc:
            # Re-open next time; the fd may be the broken part.
            self._close_handle()
            _count("journal.write_errors")
            raise JournalUnavailable(f"journal append failed: {exc}")
        _count("journal.records")

    def _close_handle_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _close_handle(self) -> None:
        with self._lock:
            self._close_handle_locked()

    def close(self) -> None:
        self._close_handle()

    # -- recovery --------------------------------------------------------

    def load(self) -> tuple[dict, dict, dict]:
        """Replay snapshot + journal.

        Returns ``(jobs, dispatched, stats)`` where ``jobs`` maps name
        -> :class:`Job`, ``dispatched`` maps name -> set of image-ids
        handed out but not resolved, and ``stats`` describes the replay
        (records applied, ignored, truncated tail).
        """
        jobs: dict[str, Job] = {}
        dispatched: dict[str, set] = {}
        stats = {"snapshot": False, "records": 0, "ignored": 0,
                 "truncated": False}

        if os.path.exists(self.snapshot_path):
            try:
                with open(self.snapshot_path, "r", encoding="utf-8") as fh:
                    snap = json.load(fh)
                for jdata in snap.get("jobs", []):
                    job = Job.from_json(jdata)
                    jobs[job.name] = job
                for name, ids in snap.get("dispatched", {}).items():
                    if name in jobs:
                        dispatched[name] = set(ids)
                stats["snapshot"] = True
            except (OSError, ValueError, KeyError) as exc:
                LOG.error("job snapshot unreadable (%s); replaying "
                          "journal only", exc)

        if os.path.exists(self.journal_path):
            with open(self.journal_path, "r", encoding="utf-8") as fh:
                for line in fh:
                    if not line.endswith("\n"):
                        # Crash mid-write: a partial last line is the
                        # expected corruption shape; drop it.
                        stats["truncated"] = True
                        break
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        stats["truncated"] = True
                        break
                    try:
                        applied = self._apply(rec, jobs, dispatched)
                    except Exception as exc:
                        # Valid JSON, broken content (a record from a
                        # newer/older version, a torn write that still
                        # parses): recovery must degrade, never refuse
                        # to boot over one record.
                        LOG.error("unreplayable journal record "
                                  "skipped (%s): %.120s", exc, line)
                        stats["ignored"] += 1
                        continue
                    if applied:
                        stats["records"] += 1
                    else:
                        stats["ignored"] += 1
        if stats["truncated"]:
            _count("journal.truncated_tails")
        return jobs, dispatched, stats

    @staticmethod
    def _apply(rec: dict, jobs: dict, dispatched: dict) -> bool:
        """Apply one replayed record; False when it was a no-op (job
        gone, item already terminal — the idempotence guarantees)."""
        op = rec.get("op")
        if op == "put":
            try:
                job = Job.from_json(rec["job"])
            except (KeyError, ValueError, TypeError):
                return False
            jobs[job.name] = job
            dispatched[job.name] = set()
            return True
        name = rec.get("job")
        if name not in jobs:
            return False               # replay past finalization
        if op == "dispatch":
            dispatched.setdefault(name, set()).add(rec.get("id"))
            return True
        if op == "resolve":
            item = jobs[name].find_item(rec.get("id"))
            if item is None or \
                    item.workflow_state != WorkflowState.EMPTY:
                return False           # idempotent: no double-count
            item.set_state(WorkflowState[rec["state"]])
            if rec.get("url"):
                item.access_url = rec["url"]
            dispatched.get(name, set()).discard(rec.get("id"))
            return True
        if op == "remove":
            jobs.pop(name, None)
            dispatched.pop(name, None)
            return True
        return False

    def compact(self, jobs: dict, dispatched: dict) -> None:
        """Write a fresh snapshot (tmp + fsync + rename) and truncate
        the journal — recovery cost stays proportional to live state,
        not history."""
        tmp = self.snapshot_path + ".tmp"
        try:
            with self._lock:
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({
                        "jobs": [j.to_json() for j in jobs.values()],
                        "dispatched": {n: sorted(ids) for n, ids
                                       in dispatched.items() if ids},
                    }, fh, separators=(",", ":"))
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, self.snapshot_path)
                self._close_handle_locked()
                with open(self.journal_path, "w",
                          encoding="utf-8") as fh:
                    fh.flush()
                    os.fsync(fh.fileno())
        except OSError as exc:
            raise JournalUnavailable(f"snapshot failed: {exc}")
        _count("journal.snapshots")
