"""Sign-magnitude plane mapping: arbitrary int/float tensors to the
16-bit signed limbs the EBCOT machinery codes.

The bit-plane coder consumes signed integer code-blocks (magnitude
planes + a sign coded once per sample). Every supported dtype maps to
that shape bijectively:

- signed ints: payload = |v|, sign = v < 0 (two's complement widens to
  int64 first, so int8's -128 maps cleanly to magnitude 128);
- unsigned ints: payload = v, sign always clear;
- floats: the IEEE bit pattern splits at the sign bit — payload = the
  exponent+mantissa field, sign = the sign bit. NaNs and infinities are
  ordinary payloads and round-trip bit-exact.

Payloads wider than 16 bits are split into 16-bit **limbs**, most
significant limb first, and every limb carries the element's sign
(``limb = sign ? -limb_mag : limb_mag``), so the sign survives whichever
limb happens to be the first nonzero one. The split is what keeps the
per-block plane count <= 16: the CX/D scan's sequential trip count and
the host decoder's pass walk both scale linearly with the plane count,
and a 31-plane float32 payload would additionally overflow the
decoder's ``(2m+1)`` half-magnitude representation — 16-bit limbs stay
comfortably inside int32 everywhere.

The one collision of sign-magnitude coding: a sample whose payload is 0
never becomes significant, so its sign is never coded. For integers
that case *is* zero; for floats it is IEEE negative zero (and only
that), so the container records the flat positions of negative zeros as
an explicit escape list (:func:`negative_zero_positions`) and the
decoder re-applies the sign bit after reconstruction.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1


@dataclass(frozen=True)
class DtypeSpec:
    """One supported dtype's place in the plane mapping."""
    code: int                # container dtype code (stable on disk)
    name: str                # numpy dtype name ("bfloat16" via ml_dtypes)
    itemsize: int
    payload_bits: int        # magnitude bits per element
    kind: str                # "int" | "uint" | "float"

    @property
    def n_limbs(self) -> int:
        return -(-self.payload_bits // LIMB_BITS)


_SPECS = [
    DtypeSpec(0, "int8", 1, 8, "int"),
    DtypeSpec(1, "int16", 2, 16, "int"),
    DtypeSpec(2, "int32", 4, 32, "int"),
    DtypeSpec(3, "uint8", 1, 8, "uint"),
    DtypeSpec(4, "uint16", 2, 16, "uint"),
    DtypeSpec(5, "uint32", 4, 32, "uint"),
    DtypeSpec(6, "float32", 4, 31, "float"),
    DtypeSpec(7, "bfloat16", 2, 15, "float"),
    DtypeSpec(8, "float16", 2, 15, "float"),
    DtypeSpec(9, "float64", 8, 63, "float"),
]
_BY_CODE = {s.code: s for s in _SPECS}
_BY_NAME = {s.name: s for s in _SPECS}


def _np_dtype(spec: DtypeSpec) -> np.dtype:
    if spec.name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(spec.name)


def spec_for(dtype) -> DtypeSpec:
    """The DtypeSpec for a numpy dtype; raises TypeError for dtypes the
    mapping does not cover (objects, complex, ...)."""
    name = np.dtype(dtype).name
    spec = _BY_NAME.get(name)
    if spec is None:
        raise TypeError(
            f"unsupported tensor dtype {name!r}; supported: "
            f"{sorted(_BY_NAME)}")
    return spec


def spec_by_code(code: int) -> DtypeSpec:
    spec = _BY_CODE.get(code)
    if spec is None:
        raise ValueError(f"unknown container dtype code {code}")
    return spec


def _payload_and_sign(arr: np.ndarray, spec: DtypeSpec):
    """Flat (n,) uint64 payload magnitudes + bool sign bits."""
    flat = arr.ravel()
    if spec.kind == "float":
        bits = flat.view(f"u{spec.itemsize}").astype(np.uint64)
        sign = (bits >> (8 * spec.itemsize - 1)).astype(bool)
        payload = bits & ((np.uint64(1) << np.uint64(spec.payload_bits))
                          - np.uint64(1))
    elif spec.kind == "int":
        wide = flat.astype(np.int64)
        sign = wide < 0
        payload = np.abs(wide).astype(np.uint64)
    else:
        sign = np.zeros(flat.shape, dtype=bool)
        payload = flat.astype(np.uint64)
    return payload, sign


def negative_zero_positions(arr: np.ndarray, spec: DtypeSpec) -> np.ndarray:
    """Flat positions whose payload is 0 but sign is set — IEEE -0.0
    for floats, empty for every integer dtype."""
    if spec.kind != "float":
        return np.zeros(0, dtype=np.int64)
    payload, sign = _payload_and_sign(arr, spec)
    return np.nonzero(sign & (payload == 0))[0].astype(np.int64)


def to_limbs(arr: np.ndarray) -> np.ndarray:
    """Map a tensor to its (K, n) int32 signed limb planes, most
    significant limb first. ``limbs[k]`` holds
    ``sign * ((payload >> shift_k) & 0xFFFF)``."""
    spec = spec_for(arr.dtype)
    payload, sign = _payload_and_sign(arr, spec)
    k = spec.n_limbs
    out = np.empty((k, payload.size), dtype=np.int32)
    for j in range(k):
        shift = np.uint64((k - 1 - j) * LIMB_BITS)
        mag = ((payload >> shift) & np.uint64(LIMB_MASK)).astype(np.int32)
        out[j] = np.where(sign, -mag, mag)
    return out


def from_limbs(limbs: np.ndarray, spec: DtypeSpec, shape: tuple,
               neg_zeros: np.ndarray | None = None) -> np.ndarray:
    """Inverse of :func:`to_limbs`: (K, n) signed limb planes back to a
    tensor of ``shape``. The element sign is the sign of the most
    significant nonzero limb (on a lossless decode all nonzero limbs
    agree; on a truncated decode the deepest surviving limb decides).
    ``neg_zeros``: flat positions to re-sign (float dtypes only)."""
    k, n = limbs.shape
    if k != spec.n_limbs:
        raise ValueError(
            f"{k} limb planes for a {spec.n_limbs}-limb dtype "
            f"({spec.name})")
    payload = np.zeros(n, dtype=np.uint64)
    sign = np.zeros(n, dtype=bool)
    decided = np.zeros(n, dtype=bool)
    for j in range(k):
        limb = limbs[j].astype(np.int64)
        mag = np.abs(limb).astype(np.uint64) & np.uint64(LIMB_MASK)
        payload |= mag << np.uint64((k - 1 - j) * LIMB_BITS)
        nz = limb != 0
        sign = np.where(~decided & nz, limb < 0, sign)
        decided |= nz
    if spec.kind == "float":
        bits = payload
        neg = sign.copy()
        if neg_zeros is not None and neg_zeros.size:
            neg[neg_zeros] = True
        bits = bits | (neg.astype(np.uint64)
                       << np.uint64(8 * spec.itemsize - 1))
        out = bits.astype(f"u{spec.itemsize}").view(_np_dtype(spec))
    elif spec.kind == "int":
        wide = np.where(sign, -payload.astype(np.int64),
                        payload.astype(np.int64))
        out = wide.astype(_np_dtype(spec))
    else:
        out = payload.astype(_np_dtype(spec))
    return out.reshape(shape)
